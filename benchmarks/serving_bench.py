"""Serving engine benchmark: arrival rate × slot count × prefill-chunk sweep.

Each arm runs the continuous-batching engine (uccl_tpu/serving) under a
synthetic Poisson arrival stream of mixed-length prompts and emits ONE JSON
line with goodput, TTFT/TPOT/queue-wait percentiles, and the decode-stall
surface chunked prefill exists to shrink — ``tpot_p95_ms`` and
``max_step_ms`` per arm, so the stall reduction is a recorded number, not a
claim (docs/SERVING.md). Compile warmup happens before the clock starts, so
the percentiles measure serving, not XLA.

    python benchmarks/serving_bench.py --devices 2 --rates 4,16 --slots 2,4
    python benchmarks/serving_bench.py --stack moe --devices 4 --slots 4
    python benchmarks/serving_bench.py --prompt-len 64 --rates 16 \
        --slots 4 --prefill-chunks off,8,32      # the stall-bound sweep
"""

from __future__ import annotations

import argparse
import json

from _bootstrap import init_devices


def run_arm(args, jax, stack, rate, n_slots, prefill_chunk=None):
    step_tokens = (args.step_tokens or None) if prefill_chunk else None
    if step_tokens is not None and step_tokens < prefill_chunk:
        return None  # this arm's budget can't admit even one chunk
    import numpy as np

    from uccl_tpu.serving import DenseBackend, MoEBackend, ServingEngine
    from uccl_tpu.serving.loadgen import drive, synth_workload, warm_engine

    max_seq = args.prompt_len + args.new_tokens
    if stack == "dense":
        from uccl_tpu.models.dense import DenseConfig, init_params

        cfg = DenseConfig(
            vocab=args.vocab, dim=args.dim, n_layers=args.layers,
            n_heads=4, n_kv_heads=2, head_dim=args.dim // 4, ffn=args.ffn,
        )
        params = init_params(jax.random.PRNGKey(args.seed), cfg)
        backend = DenseBackend(params, cfg, n_slots=n_slots, max_seq=max_seq)
        world, vocab = 1, cfg.vocab
    else:
        from uccl_tpu.models.moe_inference import (
            MoEServeConfig, MoEServer, init_params,
        )
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        world = len(jax.devices())
        if n_slots % world:
            return None  # this arm's pool doesn't tile the mesh
        cfg = MoEServeConfig(
            vocab=args.vocab, dim=args.dim, n_layers=args.layers,
            n_heads=4, n_kv_heads=2, head_dim=args.dim // 4,
            moe_ffn=args.ffn,
        )
        srv = MoEServer(cfg, make_mesh(MeshConfig(dp=world), jax.devices()))
        params = init_params(jax.random.PRNGKey(args.seed), cfg)
        backend = MoEBackend(
            srv, srv.shard_params(params), batch_local=n_slots // world,
            max_seq=max_seq,
        )
        vocab = cfg.vocab

    engine = ServingEngine(
        backend, prefill_chunk=prefill_chunk, step_tokens=step_tokens,
    )
    rng = np.random.default_rng(args.seed)
    prompts, lens, arrivals = synth_workload(
        rng, args.requests, args.prompt_len, vocab, rate
    )
    warm_engine(engine, lens, max_seq, args.new_tokens)
    _, wall = drive(engine, prompts, arrivals, args.new_tokens)

    from uccl_tpu import obs

    snap = engine.snapshot()
    return {
        "bench": "serving", "schema_version": obs.SCHEMA_VERSION,
        "stack": stack, "world": world,
        "arrival_rate": rate, "slots": n_slots,
        "prefill_chunk": prefill_chunk, "step_tokens": step_tokens,
        "requests": args.requests, "new_tokens": args.new_tokens,
        "prompt_len": args.prompt_len, "wall_s": round(wall, 3),
        "completed": snap["completed"], "rejected": snap["rejected"],
        "goodput_tok_s": snap.get("goodput_tok_s"),
        "ttft_ms": snap["ttft_ms"], "queue_wait_ms": snap["queue_wait_ms"],
        "tpot_ms": snap["tpot_ms"],
        "tpot_p95_ms": snap["tpot_ms"].get("p95"),
        "decode_step_ms": snap["decode_step_ms"],
        "step_ms": snap["step_ms"],
        "max_step_ms": snap.get("max_step_ms"),
        "prefill_chunks": snap["prefill_chunks"],
        "slot_high_water": engine.pool.high_water,
        # the obs registry's counter/gauge state rides along (fallback
        # events, rejections, slot gauges — docs/OBSERVABILITY.md) so a
        # bench line is self-contained for later analysis; counters are
        # cumulative across the process's arms
        "obs": obs.REGISTRY.snapshot()["metrics"],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="virtual CPU device count (0 = ambient)")
    ap.add_argument("--stack", default="dense", choices=["dense", "moe"])
    ap.add_argument("--rates", default="4,16",
                    help="comma-separated Poisson arrival rates (req/s)")
    ap.add_argument("--slots", default="2,4",
                    help="comma-separated slot pool sizes")
    ap.add_argument("--prefill-chunks", default="off,8,32",
                    help="comma-separated chunked-prefill arms: 'off' = "
                         "whole-prompt (PR 3 path), an integer = chunk "
                         "size C (one C-token chunk per admitted request "
                         "per step — bounds decode stalls)")
    ap.add_argument("--step-tokens", type=int, default=0,
                    help="per-step token budget for chunked arms "
                         "(0 = unbudgeted)")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--ffn", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    from uccl_tpu import obs

    obs.add_cli_args(ap)
    args = ap.parse_args()
    obs.setup_from_args(args)
    obs.dump_at_exit(args)  # every return path + crashes dump the surfaces

    jax = init_devices(args.devices)
    chunks = [None if c.strip() in ("off", "0", "none") else int(c)
              for c in args.prefill_chunks.split(",")]
    for rate in [float(r) for r in args.rates.split(",")]:
        for n_slots in [int(s) for s in args.slots.split(",")]:
            for chunk in chunks:
                arm = run_arm(args, jax, args.stack, rate, n_slots, chunk)
                if arm is None:
                    print(json.dumps({
                        "bench": "serving", "stack": args.stack,
                        "arrival_rate": rate, "slots": n_slots,
                        "prefill_chunk": chunk,
                        "skipped": "slots must divide by the MoE world, or "
                                   "--step-tokens < the arm's chunk",
                    }), flush=True)
                    continue
                print(json.dumps(arm), flush=True)


if __name__ == "__main__":
    main()
