"""Serving engine benchmark: arrival rate × slot count × prefill-chunk sweep,
plus the prefix-cache hit-rate sweep and the disaggregated-pair arm.

Each arm runs the continuous-batching engine (uccl_tpu/serving) under a
synthetic Poisson arrival stream of mixed-length prompts and emits ONE JSON
line with goodput, TTFT/TPOT/queue-wait percentiles, and the decode-stall
surface chunked prefill exists to shrink — ``tpot_p95_ms`` and
``max_step_ms`` per arm, so the stall reduction is a recorded number, not a
claim (docs/SERVING.md). Compile warmup happens before the clock starts, so
the percentiles measure serving, not XLA.

``--prefix-hit-rates`` enables the prefix-reuse cache on chunked arms and
drives a shared-system-prompt workload: with probability p a prompt starts
with a fixed ``--shared-prefix-len`` token prefix. Per-arm cache
hits/misses/evictions/tokens-reused and prefill-tokens-computed are
COUNTER DELTAS around the measured window (warmup excluded), so the
"prefix hits cut prefill compute" claim is counter-derived, not inferred.
``--disagg`` additionally runs each arm through the in-process
disaggregated pair (prefill engine → chunk-streamed KV over loopback p2p →
decode engine), reporting the decode side's TTFT split into
queue/prefill/transfer (docs/SERVING.md).
``--spec-k 0,2,4`` sweeps speculative decoding (0 = vanilla): each arm
reports ``acceptance_rate`` off ``spec_tokens_total`` counter deltas and
``decode_tok_s`` off the committed-token count (never an assumed 1
token/step); pair it with ``--workload repeat`` for the template-heavy
prompt family whose looping continuations the prompt-lookup drafter
predicts (``--workload random`` bounds the novel-text end).
``--replicas 1,2`` switches to the scale-out sweep
(``bench=serving_router`` lines): N engines behind the least-loaded
router under sustained Poisson overload (``--overload`` multiplies the
offered rate), optionally class-mixed (``--priority-mix`` interactive
fraction, short interactive turns via ``--interactive-new-tokens`` over
long batch jobs) with chunk-boundary preemption on/off (``--preempt``).
Each arm reports per-class TTFT/TPOT SLO attainment against
``--slo-ttft-ms``/``--slo-tpot-ms`` and labels itself off REAL counter
deltas — per-replica routed counts, spillovers, router rejections,
preemptions/resumes (docs/SERVING.md).

    python benchmarks/serving_bench.py --devices 2 --rates 4,16 --slots 2,4
    python benchmarks/serving_bench.py --stack moe --devices 4 --slots 4
    python benchmarks/serving_bench.py --prompt-len 64 --rates 16 \
        --slots 4 --prefill-chunks off,8,32      # the stall-bound sweep
    python benchmarks/serving_bench.py --prompt-len 64 --rates 16 --slots 4 \
        --prefill-chunks 8 --prefix-hit-rates 0,0.75 --shared-prefix-len 48
    python benchmarks/serving_bench.py --disagg --prompt-len 64 --rates 16 \
        --slots 4 --prefill-chunks 8 --prefix-hit-rates 0,0.75
    python benchmarks/serving_bench.py --stack dense --workload repeat \
        --rates 24 --slots 4 --prefill-chunks off --spec-k 0,2,4 \
        --prompt-len 24 --new-tokens 32     # the speculative-decode sweep
    python benchmarks/serving_bench.py --stack dense --rates 12 --slots 4 \
        --prefill-chunks 8 --replicas 1,2 --overload 1,2,4 \
        --priority-mix 0.25 --preempt on,off --interactive-new-tokens 8 \
        --prompt-len 32 --new-tokens 96     # the scale-out/SLO sweep
    python benchmarks/serving_bench.py --stack dense --rates 24 --slots 4 \
        --prefill-chunks off --tenants 100 --overload-tenant \
        --adapter-rank 2 --requests 200     # the tenant-isolation sweep

``--tenants N --overload-tenant`` runs the multi-tenant isolation sweep
(``bench=serving_tenants`` lines): N synthetic tenants round-robin on one
engine, three arms — fair/no-overload, fair/overload, nofair/overload —
where the overloading tenant (t0) floods with as many extra requests as
every other tenant combined. Each line reports victim-vs-overloader SLO
attainment plus per-tenant traffic and (with ``--adapter-rank``) adapter
cache hit/miss/eviction numbers, all from real counter deltas. The
isolation claim: victim attainment with fairness on stays >= 0.9x its
no-overload value while the fairness-off arm visibly collapses
(docs/SERVING.md, scripts/check_obs.py --tenants).
"""

from __future__ import annotations

import argparse
import json

from _bootstrap import init_devices

# the counter families whose per-arm deltas label the output lines
_ARM_COUNTERS = (
    ("prefix_cache_hits_total", {}),
    ("prefix_cache_misses_total", {}),
    ("prefix_cache_evictions_total", {}),
    ("prefix_cache_tokens_reused_total", {}),
    ("serving_prefill_tokens_total", {"kind": "computed"}),
    ("serving_prefill_tokens_total", {"kind": "skipped"}),
    ("kv_stream_chunks_total", {"role": "tx"}),
    ("p2p_bytes_total", {"verb": "write"}),
    ("spec_tokens_total", {"outcome": "accepted"}),
    ("spec_tokens_total", {"outcome": "rejected"}),
    ("spec_tokens_total", {"outcome": "bonus"}),
    ("serving_preempted_total", {}),
    ("serving_resumed_total", {}),
    ("serving_router_spillover_total", {}),
    ("serving_router_rejected_total", {"reason": "saturated"}),
    ("serving_admission_rejected_total", {}),
    ("obs_trace_contexts_total", {}),
)


def _counter_state():
    from uccl_tpu import obs

    return [obs.counter(name).get(**labels) for name, labels in _ARM_COUNTERS]


def _hist_state(name):
    """Cumulative bucket state of one latency histogram (serving/metrics
    observes them alongside the sample lists) — diffed around the
    measured window like the counters above."""
    from uccl_tpu import obs

    return obs.histogram(name).state()


def _hist_delta_ms(name, before):
    """Histogram-DERIVED p50/p95 (ms) of the window since ``before`` —
    stamped next to the sample-derived percentiles so the two derivations
    cross-check in every recorded arm line (they must agree within one
    bucket width; obs/aggregate.py federates only the histogram form
    across processes, so the cross-check is what certifies it)."""
    from uccl_tpu import obs

    fam = obs.histogram(name)
    zero = ((0,) * (len(fam.uppers) + 1), 0.0)
    out = {}
    for key, (counts, _) in fam.state().items():
        prev = before.get(key, zero)[0]
        delta = [a - b for a, b in zip(counts, prev)]
        for q in (50, 95):
            v = obs.histogram_quantile(fam.uppers, delta, q)
            if v is not None:
                out[f"p{q}"] = round(v * 1e3, 3)
    return out


def _counter_deltas(before):
    out = {}
    for (name, labels), b, a in zip(_ARM_COUNTERS, before, _counter_state()):
        key = name.replace("_total", "")
        if labels:
            key += "_" + "_".join(labels.values())
        out[key] = a - b
    return out


# the tiered-KV counter families, delta'd per arm across every tier label
_KV_TIER_COUNTERS = ("kv_tier_hits_total", "kv_tier_promotions_total",
                     "kv_tier_demotions_total", "kv_tier_drops_total")
_KV_TIERS = ("t0", "t1", "t2")


def _kv_tier_state():
    from uccl_tpu import obs

    return {(name, t): obs.counter(name).get(tier=t)
            for name in _KV_TIER_COUNTERS for t in _KV_TIERS}


def _kv_tier_deltas(before):
    """Per-tier traffic of the measured window: ``{hits: {t0: n, ...},
    promotions: {...}, demotions: {...}, drops: {...}}`` — the audited
    tier-traffic block every kv-tier arm line carries."""
    after = _kv_tier_state()
    out = {}
    for name in _KV_TIER_COUNTERS:
        short = name[len("kv_tier_"):-len("_total")]
        out[short] = {t: after[(name, t)] - before[(name, t)]
                      for t in _KV_TIERS}
    return out


def _make_backend(args, jax, stack, n_slots, max_seq):
    """One serving backend, or None when the arm's pool doesn't tile the
    MoE mesh — shared by the single-engine and disagg arms. Returns
    (backend, world, vocab)."""
    if stack == "dense":
        from uccl_tpu.models.dense import DenseConfig, init_params
        from uccl_tpu.serving import DenseBackend

        cfg = DenseConfig(
            vocab=args.vocab, dim=args.dim, n_layers=args.layers,
            n_heads=4, n_kv_heads=2, head_dim=args.dim // 4, ffn=args.ffn,
        )
        params = init_params(jax.random.PRNGKey(args.seed), cfg)
        backend = DenseBackend(params, cfg, n_slots=n_slots, max_seq=max_seq)
        return backend, 1, cfg.vocab
    from uccl_tpu.models.moe_inference import (
        MoEServeConfig, MoEServer, init_params,
    )
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh
    from uccl_tpu.serving import MoEBackend

    world = len(jax.devices())
    if n_slots % world:
        return None, world, 0  # this arm's pool doesn't tile the mesh
    cfg = MoEServeConfig(
        vocab=args.vocab, dim=args.dim, n_layers=args.layers,
        n_heads=4, n_kv_heads=2, head_dim=args.dim // 4,
        moe_ffn=args.ffn,
    )
    srv = MoEServer(cfg, make_mesh(MeshConfig(dp=world), jax.devices()))
    params = init_params(jax.random.PRNGKey(args.seed), cfg)
    backend = MoEBackend(
        srv, srv.shard_params(params), batch_local=n_slots // world,
        max_seq=max_seq,
    )
    return backend, world, cfg.vocab


def _make_backends(args, jax, stack, n_slots, max_seq, n):
    """N replica backends (or None when the pool doesn't tile the MoE
    mesh) — the sharing rule (dense: one compiled-fn cache; MoE: one
    server) lives in serving.replicate_backend, the same path serve.py
    builds its replica set through."""
    from uccl_tpu.serving import replicate_backend

    first, world, vocab = _make_backend(args, jax, stack, n_slots, max_seq)
    if first is None:
        return None, world, vocab
    return replicate_backend(first, n), world, vocab


def _slo_attainment(reqs, slo_ttft_ms, slo_tpot_ms):
    """Per-class SLO attainment over the arm's completed requests: the
    fraction whose measured TTFT / TPOT met the target — the headline the
    overload sweep plots (docs/SERVING.md)."""
    from uccl_tpu.serving import RequestState

    out = {}
    for r in reqs:
        if r.state is not RequestState.FINISHED:
            continue
        c = out.setdefault(r.priority, {"n": 0, "ttft_ok": 0,
                                        "tpot_ok": 0, "tpot_n": 0})
        c["n"] += 1
        if r.ttft is not None and r.ttft * 1e3 <= slo_ttft_ms:
            c["ttft_ok"] += 1
        if r.tpot is not None:
            c["tpot_n"] += 1
            if r.tpot * 1e3 <= slo_tpot_ms:
                c["tpot_ok"] += 1
    return {
        cls: {
            "completed": c["n"],
            "ttft_attainment": round(c["ttft_ok"] / c["n"], 4)
            if c["n"] else None,
            "tpot_attainment": round(c["tpot_ok"] / c["tpot_n"], 4)
            if c["tpot_n"] else None,
        }
        for cls, c in sorted(out.items())
    }


def _workload(args, vocab, rate, hit_rate):
    import numpy as np

    from uccl_tpu.serving.loadgen import (
        synth_repeat_workload, synth_shared_workload, synth_workload,
    )

    rng = np.random.default_rng(args.seed)
    if hit_rate is not None:
        shared = args.shared_prefix_len or max(1, args.prompt_len // 2)
        return synth_shared_workload(rng, args.requests, args.prompt_len,
                                     vocab, rate, hit_rate, shared)
    if args.workload == "repeat":
        return synth_repeat_workload(rng, args.requests, args.prompt_len,
                                     vocab, rate, args.motif_max)
    return synth_workload(rng, args.requests, args.prompt_len, vocab, rate)


def _arm_header(args, stack, world, rate, n_slots, prefill_chunk,
                step_tokens, hit_rate, spec_k=None):
    from uccl_tpu import obs

    head = {
        "bench": "serving", "schema_version": obs.SCHEMA_VERSION,
        "stack": stack, "world": world,
        "arrival_rate": rate, "slots": n_slots,
        "prefill_chunk": prefill_chunk, "step_tokens": step_tokens,
        "requests": args.requests, "new_tokens": args.new_tokens,
        "prompt_len": args.prompt_len,
    }
    head["workload"] = "shared" if hit_rate is not None else args.workload
    if args.spec_k:
        head["spec_k"] = spec_k or 0
    if hit_rate is not None:
        head["prefix_hit_rate"] = hit_rate
        head["shared_prefix_len"] = (args.shared_prefix_len
                                     or max(1, args.prompt_len // 2))
    return head


def _spec_fields(snap, deltas):
    """Counter-derived speculative-decoding numbers: acceptance off the
    spec_tokens_total deltas (the auditable claim), decode throughput off
    the COMMITTED token count over decode-call time (metrics.py) — never
    an assumed 1 token per call."""
    acc = deltas["spec_tokens_accepted"]
    rej = deltas["spec_tokens_rejected"]
    out = {
        "decode_tokens": snap["decode_tokens"],
        "decode_tok_s": snap.get("decode_tok_s"),
        "spec_accepted": acc, "spec_rejected": rej,
        "spec_bonus": deltas["spec_tokens_bonus"],
    }
    if acc + rej > 0:
        out["acceptance_rate"] = round(acc / (acc + rej), 4)
    if "accepted_len" in snap:
        out["accepted_len"] = snap["accepted_len"]
    return out


def _cache_fields(deltas):
    """Counter-derived per-arm cache/stream numbers (docs/SERVING.md)."""
    hits, misses = deltas["prefix_cache_hits"], deltas["prefix_cache_misses"]
    out = {
        "cache_hits": hits, "cache_misses": misses,
        "cache_evictions": deltas["prefix_cache_evictions"],
        "tokens_reused": deltas["prefix_cache_tokens_reused"],
        "prefill_tokens_computed": deltas["serving_prefill_tokens_computed"],
    }
    if hits + misses > 0:
        out["observed_hit_rate"] = round(hits / (hits + misses), 4)
    return out


def _hit_arm_viable(args, prefill_chunk, hit_rate) -> bool:
    """A hit-rate arm needs chunk-granular matches to be POSSIBLE: a
    shared prefix shorter than one chunk can never match (random tails),
    so the arm would report its requested hit rate with zero hits."""
    if hit_rate is None:
        return True
    if not prefill_chunk:
        return False  # the prefix cache is chunk-granular by construction
    shared = args.shared_prefix_len or max(1, args.prompt_len // 2)
    # upper bound: synth_shared_workload needs room for a >=1-token tail
    return prefill_chunk <= shared < args.prompt_len


def run_arm(args, jax, stack, rate, n_slots, prefill_chunk=None,
            hit_rate=None, spec_k=None):
    step_tokens = (args.step_tokens or None) if prefill_chunk else None
    if step_tokens is not None and step_tokens < prefill_chunk:
        return None  # this arm's budget can't admit even one chunk
    if not _hit_arm_viable(args, prefill_chunk, hit_rate):
        return None

    from uccl_tpu.serving import PrefixCache, ServingEngine
    from uccl_tpu.serving.loadgen import drive, warm_engine

    max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
    backend, world, vocab = _make_backend(args, jax, stack, n_slots, max_seq)
    if backend is None:
        return None
    engine = ServingEngine(
        backend, prefill_chunk=prefill_chunk, step_tokens=step_tokens,
        prefix_cache=(PrefixCache(prefill_chunk)
                      if hit_rate is not None else None),
        spec_k=spec_k,
    )
    prompts, lens, arrivals = _workload(args, vocab, rate, hit_rate)
    warm_engine(engine, lens, max_seq, args.new_tokens)
    before = _counter_state()
    ttft_hist_before = _hist_state("serving_ttft_seconds")
    _, wall = drive(engine, prompts, arrivals, args.new_tokens)
    deltas = _counter_deltas(before)

    from uccl_tpu import obs

    snap = engine.snapshot()
    arm = _arm_header(args, stack, world, rate, n_slots, prefill_chunk,
                      step_tokens, hit_rate, spec_k)
    arm.update({
        "wall_s": round(wall, 3),
        "completed": snap["completed"], "rejected": snap["rejected"],
        # one trace context per request timeline (obs/context.py) — the
        # arm's requests are individually traceable across processes
        "trace_ids": deltas["obs_trace_contexts"],
        "goodput_tok_s": snap.get("goodput_tok_s"),
        "ttft_ms": snap["ttft_ms"], "queue_wait_ms": snap["queue_wait_ms"],
        # histogram-derived TTFT percentiles beside the sample-derived
        # ones: the merge-safe path and the exact path cross-check in
        # every recorded line (docs/OBSERVABILITY.md)
        "ttft_hist_ms": _hist_delta_ms("serving_ttft_seconds",
                                       ttft_hist_before),
        "tpot_ms": snap["tpot_ms"],
        "tpot_p95_ms": snap["tpot_ms"].get("p95"),
        "decode_step_ms": snap["decode_step_ms"],
        "step_ms": snap["step_ms"],
        "max_step_ms": snap.get("max_step_ms"),
        "prefill_chunks": snap["prefill_chunks"],
        "slot_high_water": engine.pool.high_water,
    })
    if args.spec_k:
        arm.update(_spec_fields(snap, deltas))
    if hit_rate is not None:
        arm.update(_cache_fields(deltas))
    # the obs registry's counter/gauge state rides along (fallback
    # events, rejections, slot gauges — docs/OBSERVABILITY.md) so a
    # bench line is self-contained for later analysis; counters are
    # cumulative across the process's arms
    arm["obs"] = obs.REGISTRY.snapshot()["metrics"]
    return arm


def _parse_tier_cfg(cfg: str):
    """One --kv-tiers arm label -> (enable tiers, wire_dtype, enable T2).
    ``t0`` = prefix cache only (the baseline the sweep beats), ``t1`` =
    + lossless host pool, ``t1-fp8``/``t1-int8`` = host pool quantized at
    rest, ``t1-t2`` = lossless host pool + loopback remote peer."""
    cfg = cfg.strip()
    if cfg == "t0":
        return False, None, False
    if cfg == "t1":
        return True, None, False
    if cfg in ("t1-fp8", "t1-int8"):
        return True, cfg.split("-")[1], False
    if cfg == "t1-t2":
        return True, None, True
    raise SystemExit(f"unknown --kv-tiers config {cfg!r} (want "
                     "t0|t1|t1-fp8|t1-int8|t1-t2)")


def run_kv_tier_arm(args, jax, stack, rate, n_slots, prefill_chunk,
                    tier_cfg, working_set):
    """One tiered-KV-cache arm: the multi-prefix working-set workload
    (``working_set`` × ``n_slots`` distinct shared prefixes, round-robin —
    every prefix's donor is evicted before its next use) against one tier
    config. The line carries counter-delta tier traffic (hits/promotions/
    demotions/drops per tier), computed-vs-skipped prefill tokens, TTFT,
    and — with --check-oracle — every finished request verified against
    the one-shot oracle (hard-exact on lossless-at-rest configs; quantized
    configs record the match fraction instead, their documented
    bounded-divergence contract)."""
    if not prefill_chunk:
        return None  # the prefix cache is chunk-granular by construction
    if stack != "dense":
        return None  # the sweep's oracle runs the dense stack (MoE
        # lossless exactness is pinned in tests/test_kv_tiers.py)
    shared = args.shared_prefix_len or max(1, args.prompt_len // 2)
    if not (prefill_chunk <= shared < args.prompt_len):
        return None  # no chunk-aligned hit would ever be possible

    import numpy as np

    from uccl_tpu import obs
    from uccl_tpu.models.dense import DenseConfig, init_params
    from uccl_tpu.serving import (
        DenseBackend, PrefixCache, ServingEngine, TieredKVCache,
    )
    from uccl_tpu.serving.loadgen import (
        drive, synth_multi_prefix_workload, warm_engine,
    )

    enable, wire_dtype, enable_t2 = _parse_tier_cfg(tier_cfg)
    max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
    cfg = DenseConfig(
        vocab=args.vocab, dim=args.dim, n_layers=args.layers,
        n_heads=4, n_kv_heads=2, head_dim=args.dim // 4, ffn=args.ffn,
    )
    params = init_params(jax.random.PRNGKey(args.seed), cfg)
    backend = DenseBackend(params, cfg, n_slots=n_slots, max_seq=max_seq)

    # T1 capacity in ENTRY units: --host-tier-entries × the raw f32 bytes
    # of one full-depth entry. The same byte budget holds ~3.6× the
    # entries quantized at rest — that capacity, not speed, is the
    # quantized mode's measured win.
    ent_tokens = (args.prompt_len // prefill_chunk) * prefill_chunk
    ent_bytes = 2 * cfg.n_layers * ent_tokens * cfg.n_kv_heads \
        * cfg.head_dim * 4
    n_prefixes = working_set * n_slots

    server = remote = chan_pair = None
    tiers = None
    if enable:
        remote = None
        if enable_t2:
            import threading

            from uccl_tpu.p2p import Channel, Endpoint
            from uccl_tpu.serving import KvTierServer, RemoteKVTier

            sep, cep = Endpoint(), Endpoint()
            res = {}
            t = threading.Thread(
                target=lambda: res.setdefault("c", Channel.accept(sep)))
            t.start()
            c = Channel.connect(cep, "127.0.0.1", sep.port, n_paths=2)
            t.join(timeout=20)
            chan_pair = (sep, cep, res["c"], c)
            # the remote peer advertises room for the WHOLE working set:
            # T1 spills land there instead of dropping
            server = KvTierServer(capacity_bytes=ent_bytes * n_prefixes
                                  + (1 << 16))
            server.serve_forever(res["c"], timeout_ms=10000)
            remote = RemoteKVTier(c, max_entry_bytes=ent_bytes + (1 << 12))
        tiers = TieredKVCache(
            host_bytes=args.host_tier_entries * ent_bytes + 16,
            wire_dtype=wire_dtype, remote=remote,
        )
    engine = ServingEngine(
        backend, prefill_chunk=prefill_chunk,
        step_tokens=(args.step_tokens or None),
        prefix_cache=PrefixCache(prefill_chunk), kv_tiers=tiers,
    )
    rng = np.random.default_rng(args.seed)
    prompts, lens, arrivals = synth_multi_prefix_workload(
        rng, args.requests, args.prompt_len, cfg.vocab, rate,
        n_prefixes, shared,
    )
    warm_engine(engine, lens, max_seq, args.new_tokens)
    if tiers is not None:
        # codec compile warmup: the first real demote at each entry shape
        # would otherwise compile the quantize/dequantize programs INSIDE
        # the measured window (entry token counts vary with the random
        # tail — one warm round trip per reachable chunk depth)
        from uccl_tpu.serving.kv_tiers import decode_entry, encode_entry

        for tok in sorted({(s // prefill_chunk) * prefill_chunk
                           for s in range(shared + 1,
                                          args.prompt_len + 1)}):
            dummy = np.zeros((cfg.n_layers, tok, cfg.n_kv_heads,
                              cfg.head_dim), np.float32)
            decode_entry(*encode_entry(dummy, dummy, tiers.wire_dtype,
                                       tiers.block))
        # ...and the DEVICE side of each cycle: demotion jit-compiles
        # export_rows per donor depth and promotion compiles import_rows
        # at the matched length. One real demote per reachable depth plus
        # one promoting hit keeps those compiles out of the window too.
        from uccl_tpu.serving.loadgen import _clear_warmup_trace

        wrng = np.random.default_rng(args.seed + 10_007)
        base = wrng.integers(0, cfg.vocab, args.prompt_len).astype(np.int32)
        for tok in sorted({(s // prefill_chunk) * prefill_chunk
                           for s in range(shared + 1,
                                          args.prompt_len + 1)}):
            engine.submit(base[:tok], max_new_tokens=1)
            engine.drain()
            engine.prefix_cache.evict_lru(engine.pool,
                                          demote=tiers.demote)
        sc = (shared // prefill_chunk) * prefill_chunk
        engine.submit(np.concatenate([base[:sc], base[-1:]]),
                      max_new_tokens=1)
        engine.drain()
        engine.prefix_cache.clear(engine.pool)
        engine.reset_metrics()
        _clear_warmup_trace()
    before = _counter_state()
    kv_before = _kv_tier_state()
    ttft_hist_before = _hist_state("serving_ttft_seconds")
    reqs, wall = drive(engine, prompts, arrivals, args.new_tokens)
    deltas = _counter_deltas(before)
    snap = engine.snapshot()

    exact_rest = tiers is None or tiers.exact
    oracle_checked = oracle_matched = 0
    if args.check_oracle:
        import jax.numpy as jnp

        from uccl_tpu.models.inference import generate

        for r in reqs:
            toks = generate(params, jnp.asarray(r.prompt)[None], cfg,
                            max_new_tokens=r.max_new_tokens,
                            max_seq=max_seq)
            want = np.asarray(toks)[0, :r.n_generated].tolist()
            oracle_checked += 1
            if r.out_tokens == want:
                oracle_matched += 1
            elif exact_rest:
                raise SystemExit(
                    f"ORACLE MISMATCH on lossless tier config "
                    f"{tier_cfg}: rid={r.rid} got {r.out_tokens} "
                    f"want {want}"
                )
    if chan_pair is not None:
        remote.close()
        for ep in (chan_pair[0], chan_pair[1]):
            ep.close()

    arm = _arm_header(args, stack, 1, rate, n_slots, prefill_chunk,
                      args.step_tokens or None, None)
    arm.update({
        "bench": "serving_kv_tiers",
        "workload": "multi_prefix",
        "tier_config": tier_cfg,
        "working_set": working_set,
        "n_prefixes": n_prefixes,
        "shared_prefix_len": shared,
        "host_tier_entries": args.host_tier_entries if enable else 0,
        "entry_bytes_raw": ent_bytes,
        "wire_dtype": wire_dtype,
        "exact_rest": exact_rest,
        "wall_s": round(wall, 3),
        "completed": snap["completed"],
        "goodput_tok_s": snap.get("goodput_tok_s"),
        "ttft_ms": snap["ttft_ms"],
        "ttft_hist_ms": _hist_delta_ms("serving_ttft_seconds",
                                       ttft_hist_before),
        "tpot_ms": snap["tpot_ms"],
        "kv_tier": _kv_tier_deltas(kv_before),
        "prefill_tokens_skipped": deltas["serving_prefill_tokens_skipped"],
        "slot_high_water": engine.pool.high_water,
    })
    arm.update(_cache_fields(deltas))
    if enable:
        arm["t1_resident_bytes"] = tiers.t1.used_bytes
        arm["t1_resident_entries"] = len(tiers.t1)
        if server is not None:
            arm["t2_resident_entries"] = len(server)
            arm["t2_resident_bytes"] = server.used_bytes
    if args.check_oracle:
        arm["oracle_checked"] = oracle_checked
        arm["oracle_exact"] = oracle_matched == oracle_checked
        if not exact_rest and oracle_checked:
            arm["oracle_match_rate"] = round(
                oracle_matched / oracle_checked, 4)
    arm["obs"] = obs.REGISTRY.snapshot()["metrics"]
    return arm


def _tenant_counter_state():
    """Per-tenant label state of the tenancy counter families — diffed
    around the measured window so an arm's per-tenant traffic is real
    counter deltas, not mirrored loadgen math."""
    from uccl_tpu import obs

    out = {}
    for name in ("serving_tenant_requests_total",
                 "serving_tenant_tokens_total"):
        for labels, v in obs.counter(name).samples():
            out[(name, labels.get("tenant", ""))] = v
    return out


_ADAPTER_COUNTERS = ("adapter_cache_hits_total", "adapter_cache_misses_total",
                     "adapter_cache_evictions_total")


def _tenant_slo_split(reqs, slo_ttft_ms, slo_tpot_ms, overloader):
    """Aggregate TTFT/TPOT SLO attainment over the VICTIM tenants (every
    tenant except ``overloader``) and over the overloader itself — the
    isolation headline: fairness on must hold the victim number near its
    no-overload value while the overloader absorbs the queueing."""
    from uccl_tpu.serving import RequestState

    def agg(rs):
        n = ttft_ok = tpot_ok = tpot_n = 0
        for r in rs:
            n += 1
            if r.ttft is not None and r.ttft * 1e3 <= slo_ttft_ms:
                ttft_ok += 1
            if r.tpot is not None:
                tpot_n += 1
                if r.tpot * 1e3 <= slo_tpot_ms:
                    tpot_ok += 1
        return {
            "completed": n,
            "ttft_attainment": round(ttft_ok / n, 4) if n else None,
            "tpot_attainment": round(tpot_ok / tpot_n, 4)
            if tpot_n else None,
        }

    fin = [r for r in reqs if r.state is RequestState.FINISHED]
    return (agg([r for r in fin if r.tenant != overloader]),
            agg([r for r in fin if r.tenant == overloader]))


def _tenant_workload(args, vocab, rate, n_tenants, overload):
    """Round-robin multi-tenant stream, optionally with tenant t0
    flooding: the overloader offers as many EXTRA requests as the entire
    rest of the fleet combined, front-loaded as a 10x-rate Poisson burst
    (the head-of-line-blocking shape admission fairness exists for) and
    merged by arrival time. The base stream's draws come first at a fixed
    seed, so the no-overload arm and both overload arms face identical
    victim traffic — the paired-arm rule every sweep here follows."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    lo = max(1, args.prompt_len // 2)
    n = args.requests
    lens = rng.integers(lo, args.prompt_len + 1, n)
    prompts = [rng.integers(0, vocab, l).astype(np.int32) for l in lens]
    if rate > 0:
        arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
    else:
        arrivals = np.zeros(n)
    tenants = [f"t{i % n_tenants}" for i in range(n)]
    if overload:
        f_lens = rng.integers(lo, args.prompt_len + 1, n)
        prompts += [rng.integers(0, vocab, l).astype(np.int32)
                    for l in f_lens]
        if rate > 0:
            f_arr = np.cumsum(rng.exponential(1.0 / (10.0 * rate), n))
        else:
            f_arr = np.zeros(n)
        arrivals = np.concatenate([arrivals, f_arr])
        tenants += ["t0"] * n
        order = np.argsort(arrivals, kind="stable")
        prompts = [prompts[i] for i in order]
        tenants = [tenants[i] for i in order]
        arrivals = arrivals[order]
    return prompts, tenants, arrivals


def run_tenant_arm(args, jax, stack, rate, n_slots, prefill_chunk,
                   fair, overload):
    """One multi-tenant isolation arm: ``--tenants`` synthetic tenants
    round-robin on one engine, with tenant-fair admission (DRR +
    per-tenant accounting) on or off and tenant t0 optionally flooding.
    With ``--adapter-rank`` every tenant carries its own LoRA adapter
    staged through a bounded AdapterStore, so the arm's adapter cache
    hit/miss/eviction deltas are live restaging traffic, not synthetic.
    The line's victim/overloader SLO attainment comes from per-request
    TTFT/TPOT against --slo-ttft-ms/--slo-tpot-ms; per-tenant traffic is
    serving_tenant_* counter deltas."""
    step_tokens = (args.step_tokens or None) if prefill_chunk else None
    if step_tokens is not None and step_tokens < prefill_chunk:
        return None

    import numpy as np

    from uccl_tpu import obs
    from uccl_tpu.serving import AdapterStore, ServingEngine, make_lora
    from uccl_tpu.serving.loadgen import (
        _clear_warmup_trace, drive, warm_engine,
    )

    max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
    backend, world, vocab = _make_backend(args, jax, stack, n_slots, max_seq)
    if backend is None:
        return None
    store = None
    if args.adapter_rank:
        if stack != "dense":
            return None  # adapter dims below are the dense head layout
        head_dim = args.dim // 4
        store = AdapterStore(args.layers, args.dim, 4 * head_dim,
                             2 * head_dim, max_rank=args.adapter_rank,
                             capacity=max(4, n_slots))
        for j in range(args.tenants):
            store.publish(f"t{j}", make_lora(
                jax.random.PRNGKey(args.seed * 7919 + j + 1),
                args.layers, args.dim, 4 * head_dim, 2 * head_dim,
                args.adapter_rank,
            ))
    engine = ServingEngine(
        backend, prefill_chunk=prefill_chunk, step_tokens=step_tokens,
        adapters=store, tenant_fair=fair or None,
    )
    prompts, tenants, arrivals = _tenant_workload(args, vocab, rate,
                                                  args.tenants, overload)
    lens = np.array([p.size for p in prompts])
    warm_engine(engine, lens, max_seq, args.new_tokens)
    if store is not None:
        # the fused-adapter programs (prefill/chunked-prefill/decode with
        # the adapter tables as jit args) compile on the first ADAPTED
        # call — warm them outside the window like every other sweep
        wrng = np.random.default_rng(args.seed + 10_007)
        engine.submit(
            wrng.integers(0, vocab, args.prompt_len).astype(np.int32),
            max_new_tokens=2, tenant="t0", adapter="t0",
        )
        engine.drain()
        engine.reset_metrics()
        _clear_warmup_trace()
    adapters = tenants if store is not None else None
    before = _counter_state()
    tenant_before = _tenant_counter_state()
    adapter_before = [obs.counter(n).get() for n in _ADAPTER_COUNTERS]
    ttft_hist_before = _hist_state("serving_ttft_seconds")
    reqs, wall = drive(engine, prompts, arrivals, args.new_tokens,
                       tenants=tenants, adapters=adapters)
    deltas = _counter_deltas(before)
    tenant_after = _tenant_counter_state()
    snap = engine.snapshot()

    def tdelta(name, tenant):
        return (tenant_after.get((name, tenant), 0.0)
                - tenant_before.get((name, tenant), 0.0))

    served = sorted({t for (n, t) in tenant_after
                     if n == "serving_tenant_requests_total"
                     and tdelta(n, t) > 0})
    victim, overloader = _tenant_slo_split(reqs, args.slo_ttft_ms,
                                           args.slo_tpot_ms, "t0")
    arm = _arm_header(args, stack, world, rate, n_slots, prefill_chunk,
                      step_tokens, None)
    arm.update({
        "bench": "serving_tenants",
        "workload": "tenant_rr",
        "tenants": args.tenants,
        "fair": fair,
        "overload": overload,
        "adapter_rank": args.adapter_rank,
        "wall_s": round(wall, 3),
        "completed": snap["completed"], "rejected": snap["rejected"],
        "trace_ids": deltas["obs_trace_contexts"],
        "goodput_tok_s": snap.get("goodput_tok_s"),
        "ttft_ms": snap["ttft_ms"], "queue_wait_ms": snap["queue_wait_ms"],
        "ttft_hist_ms": _hist_delta_ms("serving_ttft_seconds",
                                       ttft_hist_before),
        "tpot_ms": snap["tpot_ms"],
        "slot_high_water": engine.pool.high_water,
        "slo_ttft_ms": args.slo_ttft_ms,
        "slo_tpot_ms": args.slo_tpot_ms,
        # the isolation headline and its label: counter-delta per-tenant
        # traffic, victim vs overloader attainment
        "tenant_series": len(served),
        "overloader_requests": tdelta("serving_tenant_requests_total",
                                      "t0"),
        "overloader_tokens": tdelta("serving_tenant_tokens_total", "t0"),
        "victim_requests": sum(
            tdelta("serving_tenant_requests_total", t)
            for t in served if t != "t0"),
        "victim_slo": victim,
        "overloader_slo": overloader,
    })
    if store is not None:
        hits, misses, evictions = (
            obs.counter(n).get() - b
            for n, b in zip(_ADAPTER_COUNTERS, adapter_before))
        arm.update({
            "adapter_hits": hits, "adapter_misses": misses,
            "adapter_evictions": evictions,
            "adapter_resident": store.n_resident,
        })
    arm["obs"] = obs.REGISTRY.snapshot()["metrics"]
    return arm


def run_router_arm(args, jax, stack, rate, n_slots, prefill_chunk,
                   n_replicas, mix, preempt_on, overload):
    """One replica-router arm under sustained Poisson (over)load:
    ``n_replicas`` engines behind the least-loaded router, offered
    ``rate × overload`` req/s, optionally class-mixed (``mix`` =
    interactive fraction) with chunk-boundary preemption on or off. The
    line reports per-class TTFT/TPOT SLO attainment (measured against
    --slo-ttft-ms/--slo-tpot-ms) and labels itself off REAL counter
    deltas: per-replica routed counts, spillovers, router rejections,
    preemptions/resumes — never mirrored scheduler math."""
    priority = mix is not None
    preempt = bool(priority and preempt_on and prefill_chunk)
    if preempt_on and not preempt:
        return None  # preemption-on arm without classes/chunks: no-op
    step_tokens = (args.step_tokens or None) if prefill_chunk else None
    if step_tokens is not None and step_tokens < (prefill_chunk or 0):
        return None

    import numpy as np

    from uccl_tpu import obs
    from uccl_tpu.serving import Router, ServingEngine
    from uccl_tpu.serving.loadgen import (
        assign_classes, drive, warm_replicas,
    )

    max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
    backends, world, vocab = _make_backends(args, jax, stack, n_slots,
                                            max_seq, n_replicas)
    if backends is None:
        return None
    engines = [ServingEngine(
        b, prefill_chunk=prefill_chunk, step_tokens=step_tokens,
        max_queue=args.max_queue or None,
        priority_classes=priority, preempt=preempt,
    ) for b in backends]
    router = Router(engines)
    eff_rate = rate * overload
    prompts, lens, arrivals = _workload(args, vocab, eff_rate, None)
    rng_cls = np.random.default_rng(args.seed + 1)  # classes after arrivals
    priorities = (assign_classes(rng_cls, args.requests, mix,
                                 pattern=args.class_pattern)
                  if priority else None)
    warm_replicas(router, lens, max_seq, args.new_tokens)
    # short interactive turns over long batch jobs (the Llumnix-shape
    # workload preemption exists for): per-class token budgets when the
    # arm is classed and --interactive-new-tokens is set
    new_tokens = args.new_tokens
    if priority and args.interactive_new_tokens:
        new_tokens = [args.interactive_new_tokens
                      if c == "interactive" else args.new_tokens
                      for c in priorities]
    routed_c = obs.counter("serving_router_requests_total")
    routed0 = [routed_c.get(replica=str(i)) for i in range(n_replicas)]
    before = _counter_state()
    ttft_hist_before = _hist_state("serving_ttft_seconds")
    reqs, wall = drive(router, prompts, arrivals, new_tokens,
                       priorities=priorities)
    deltas = _counter_deltas(before)
    snap = router.snapshot()
    router.close()

    arm = _arm_header(args, stack, world, rate, n_slots, prefill_chunk,
                      step_tokens, None)
    arm.update({
        "bench": "serving_router",
        "replicas": n_replicas,
        "overload": overload,
        "offered_rate": eff_rate,
        "priority_mix": mix,
        "preempt": preempt,
        "wall_s": round(wall, 3),
        "completed": snap["completed"], "rejected": snap["rejected"],
        "expired": snap["expired"],
        "trace_ids": deltas["obs_trace_contexts"],
        "goodput_tok_s": snap.get("goodput_tok_s"),
        "ttft_ms": snap["ttft_ms"], "queue_wait_ms": snap["queue_wait_ms"],
        "ttft_hist_ms": _hist_delta_ms("serving_ttft_seconds",
                                       ttft_hist_before),
        "tpot_ms": snap["tpot_ms"],
        "tpot_p95_ms": snap["tpot_ms"].get("p95"),
        "max_step_ms": snap.get("max_step_ms"),
        # the routing decisions this arm is labeled from — counter deltas
        "routed": [routed_c.get(replica=str(i)) - routed0[i]
                   for i in range(n_replicas)],
        "spillovers": deltas["serving_router_spillover"],
        "router_rejected": deltas["serving_router_rejected_saturated"],
        "engine_rejected": deltas["serving_admission_rejected"],
        "preemptions": deltas["serving_preempted"],
        "resumes": deltas["serving_resumed"],
        "slo_ttft_ms": args.slo_ttft_ms,
        "slo_tpot_ms": args.slo_tpot_ms,
        "slo": _slo_attainment(reqs, args.slo_ttft_ms, args.slo_tpot_ms),
    })
    if "per_class" in snap:
        arm["per_class"] = snap["per_class"]
    if "per_tenant" in snap:
        arm["per_tenant"] = snap["per_tenant"]
    arm["obs"] = obs.REGISTRY.snapshot()["metrics"]
    return arm


def run_disagg_arm(args, jax, stack, rate, n_slots, prefill_chunk,
                   hit_rate=None, spec_k=None):
    """One disaggregated arm: prefill engine → chunk-streamed KV over
    loopback p2p → decode engine (speculating when ``spec_k`` — adopted
    requests decode through the same verify window), measured at the
    decode side (where the user-visible TTFT and its
    queue/prefill/transfer split live)."""
    if not prefill_chunk:
        return None  # streaming granularity IS the prefill chunk
    step_tokens = args.step_tokens or None
    if step_tokens is not None and step_tokens < prefill_chunk:
        return None  # this arm's budget can't admit even one chunk
    if not _hit_arm_viable(args, prefill_chunk, hit_rate):
        return None
    from uccl_tpu.serving import PrefixCache, ServingEngine
    from uccl_tpu.serving.disagg import (
        drive_pair, make_local_pair, warm_pair,
    )

    max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
    pb, world, vocab = _make_backend(args, jax, stack, n_slots, max_seq)
    db, _, _ = _make_backend(args, jax, stack, n_slots, max_seq)
    if pb is None or db is None:
        return None
    pe = ServingEngine(
        pb, prefill_chunk=prefill_chunk, step_tokens=step_tokens,
        prefix_cache=(PrefixCache(prefill_chunk)
                      if hit_rate is not None else None),
    )
    de = ServingEngine(db, spec_k=spec_k)
    pw, dw = make_local_pair(pe, de)
    try:
        warm_pair(pw, dw, args.prompt_len, args.new_tokens)
        prompts, _, arrivals = _workload(args, vocab, rate, hit_rate)
        before = _counter_state()
        ttft_hist_before = _hist_state("serving_disagg_ttft_seconds")
        finished, wall = drive_pair(pw, dw, prompts, arrivals,
                                    args.new_tokens)
        deltas = _counter_deltas(before)
        pw.close()
        psnap, dsnap = pe.snapshot(), de.snapshot()
    finally:
        # each arm owns two endpoints + two registered full-pool mirrors;
        # a sweep must not accumulate them until process exit
        pw.ep.close()
        dw.ep.close()

    from uccl_tpu import obs

    arm = _arm_header(args, stack, world, rate, n_slots, prefill_chunk,
                      step_tokens, hit_rate, spec_k)
    arm.update({
        "bench": "serving_disagg",
        "wall_s": round(wall, 3),
        "completed": dsnap["completed"],
        "adopted": dsnap.get("adopted", 0),
        "trace_ids": deltas["obs_trace_contexts"],
        "goodput_tok_s": dsnap.get("goodput_tok_s"),
        # the end-to-end TTFT and its split, from the stream's wall-clock
        # marks (docs/SERVING.md): queue+prefill on the prefill fleet,
        # transfer = prefill-done -> adopt on the decode fleet
        "ttft_ms": dsnap.get("disagg_ttft_ms", {}),
        "ttft_hist_ms": _hist_delta_ms("serving_disagg_ttft_seconds",
                                       ttft_hist_before),
        "ttft_p95_ms": dsnap.get("disagg_ttft_ms", {}).get("p95"),
        "ttft_queue_ms": dsnap.get("disagg_queue_ms", {}),
        "ttft_prefill_ms": dsnap.get("disagg_prefill_ms", {}),
        "ttft_transfer_ms": dsnap.get("disagg_transfer_ms", {}),
        "tpot_ms": dsnap["tpot_ms"],
        "tpot_p95_ms": dsnap["tpot_ms"].get("p95"),
        "decode_step_ms": dsnap["decode_step_ms"],
        "prefill_ms": psnap["prefill_ms"],
        "prefill_chunks": psnap["prefill_chunks"],
        "kv_slabs_streamed": deltas["kv_stream_chunks_tx"],
        "kv_bytes_streamed": deltas["p2p_bytes_write"],
    })
    if args.spec_k:
        arm.update(_spec_fields(dsnap, deltas))
    if hit_rate is not None:  # cache absent ≠ cache enabled-but-cold
        arm.update(_cache_fields(deltas))
    arm["obs"] = obs.REGISTRY.snapshot()["metrics"]
    return arm


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="virtual CPU device count (0 = ambient)")
    ap.add_argument("--stack", default="dense", choices=["dense", "moe"])
    ap.add_argument("--rates", default="4,16",
                    help="comma-separated Poisson arrival rates (req/s)")
    ap.add_argument("--slots", default="2,4",
                    help="comma-separated slot pool sizes")
    ap.add_argument("--prefill-chunks", default="off,8,32",
                    help="comma-separated chunked-prefill arms: 'off' = "
                         "whole-prompt (PR 3 path), an integer = chunk "
                         "size C (one C-token chunk per admitted request "
                         "per step — bounds decode stalls)")
    ap.add_argument("--step-tokens", type=int, default=0,
                    help="per-step token budget for chunked arms "
                         "(0 = unbudgeted)")
    ap.add_argument("--prefix-hit-rates", default="",
                    help="comma-separated shared-system-prompt rates (e.g. "
                         "'0,0.75'): enables the prefix-reuse cache on "
                         "chunked arms and labels each arm with its "
                         "counter-derived hits/tokens-reused/prefill-"
                         "tokens-computed")
    ap.add_argument("--shared-prefix-len", type=int, default=0,
                    help="shared system-prompt length for the hit-rate "
                         "sweep (0 = prompt_len/2)")
    ap.add_argument("--kv-tiers", default="",
                    help="comma-separated tiered-KV-cache arms (e.g. "
                         "'t0,t1,t1-fp8,t1-t2'): each runs the multi-"
                         "prefix working-set workload against one tier "
                         "config — t0 = prefix cache only, t1 = + bounded "
                         "lossless host pool, t1-fp8/t1-int8 = host pool "
                         "quantized at rest, t1-t2 = + a loopback remote "
                         "peer over the SACK channel. Lines are "
                         "bench=serving_kv_tiers with counter-delta tier "
                         "traffic; dense chunked arms only")
    ap.add_argument("--working-sets", default="10",
                    help="comma-separated working-set multipliers for "
                         "--kv-tiers arms: each arm uses N x slots "
                         "distinct shared prefixes round-robin (the "
                         "10-100x device-capacity axis)")
    ap.add_argument("--host-tier-entries", type=int, default=8,
                    help="T1 host-pool capacity in raw-f32 full-depth "
                         "entry units (the same bytes hold ~3.6x the "
                         "entries under fp8/int8 at rest)")
    ap.add_argument("--check-oracle", action="store_true",
                    help="kv-tier arms: verify every finished request "
                         "against the one-shot oracle — hard-exact on "
                         "lossless-at-rest configs, match-rate recorded "
                         "on quantized ones")
    ap.add_argument("--spec-k", default="",
                    help="comma-separated speculative-decoding arms (e.g. "
                         "'0,2,4'; 0 = vanilla): each decoding slot "
                         "drafts K tokens via the prompt-lookup NGram "
                         "drafter and one batched [slots, K+1] verify "
                         "commits the accepted prefix + 1 target token. "
                         "Arms report acceptance_rate + decode_tok_s off "
                         "spec_tokens_total counter deltas")
    ap.add_argument("--workload", default="random",
                    choices=["random", "repeat"],
                    help="prompt family for non-prefix arms: 'random' = "
                         "mixed-length uniform tokens (novel text — the "
                         "near-zero-acceptance bound for spec arms), "
                         "'repeat' = tiled 1..motif-max-token motifs "
                         "(template-heavy traffic whose continuations "
                         "loop — the regime prompt-lookup drafting "
                         "targets)")
    ap.add_argument("--motif-max", type=int, default=2,
                    help="repeat workload: max motif length being tiled")
    ap.add_argument("--disagg", action="store_true",
                    help="run each arm through the disaggregated "
                         "prefill->decode pair (chunk-streamed KV over "
                         "loopback p2p) instead of one engine, reporting "
                         "the TTFT queue/prefill/transfer split")
    ap.add_argument("--replicas", default="",
                    help="comma-separated replica counts (e.g. '1,2'): "
                         "each arm runs N engines behind the least-loaded "
                         "router and reports per-class SLO attainment + "
                         "counter-derived routing/preemption labels "
                         "(bench=serving_router lines). Composes with "
                         "--overload and --priority-mix; not with "
                         "--disagg/--prefix-hit-rates/--spec-k sweeps")
    ap.add_argument("--overload", default="1",
                    help="comma-separated offered-load multipliers on each "
                         "--rates value for router arms (e.g. '1,2,4' — "
                         "sustained Poisson overload is where preemption "
                         "and rejection earn their keep)")
    ap.add_argument("--priority-mix", default="",
                    help="comma-separated interactive fractions for "
                         "router arms (e.g. '0.3,0.5'; 'off' = no "
                         "classes): requests split interactive/batch and "
                         "the line carries per-class TTFT/TPOT SLO "
                         "attainment")
    ap.add_argument("--preempt", default="on",
                    help="comma-separated preemption arms for classed "
                         "router sweeps: 'on', 'off', or 'on,off' for "
                         "the paired comparison at equal load")
    ap.add_argument("--class-pattern", default="bernoulli",
                    choices=["bernoulli", "batch-first"],
                    help="how classes map onto arrival order (bernoulli "
                         "= interleaved mixed traffic; batch-first = the "
                         "deterministic preemption fixture)")
    ap.add_argument("--interactive-new-tokens", type=int, default=0,
                    help="router arms: token budget for INTERACTIVE "
                         "requests (0 = same as --new-tokens). Short "
                         "interactive turns over long batch jobs is the "
                         "workload shape chunk-boundary preemption "
                         "exists for")
    ap.add_argument("--tenants", type=int, default=0,
                    help="multi-tenant isolation sweep: N synthetic "
                         "tenants round-robin on one engine "
                         "(bench=serving_tenants lines). Runs the "
                         "fair/no-overload baseline arm; add "
                         "--overload-tenant for the paired overload "
                         "arms. Does not compose with the other sweeps")
    ap.add_argument("--overload-tenant", action="store_true",
                    help="tenant sweep: add the overload arms — tenant "
                         "t0 floods with as many extra requests as the "
                         "whole rest of the fleet combined, once with "
                         "tenant-fair admission on and once off (the "
                         "isolation-vs-collapse paired comparison)")
    ap.add_argument("--adapter-rank", type=int, default=0,
                    help="tenant sweep: stage a rank-R LoRA adapter per "
                         "tenant through a bounded AdapterStore (dense "
                         "stack), so arm lines carry live adapter cache "
                         "hit/miss/eviction counter deltas (0 = no "
                         "adapters)")
    ap.add_argument("--slo-ttft-ms", type=float, default=250.0,
                    help="TTFT target for per-class attainment")
    ap.add_argument("--slo-tpot-ms", type=float, default=50.0,
                    help="TPOT target for per-class attainment")
    ap.add_argument("--max-queue", type=int, default=0,
                    help="router arms: bounded per-replica queue depth "
                         "(0 = unbounded) — the backpressure the router's "
                         "spillover/rejection counters need to fire")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--max-seq", type=int, default=0,
                    help="KV slot capacity (0 = prompt+new): size the pool "
                         "for the longest SUPPORTED sequence, not this "
                         "workload's — per-step cost scales with pool "
                         "size, so capacity belongs to the arm label")
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--ffn", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    from uccl_tpu import obs

    obs.add_cli_args(ap)
    args = ap.parse_args()
    obs.setup_from_args(args)
    obs.dump_at_exit(args)  # every return path + crashes dump the surfaces

    if args.max_seq and args.max_seq < args.prompt_len + args.new_tokens:
        raise SystemExit(
            f"--max-seq {args.max_seq} < --prompt-len {args.prompt_len} + "
            f"--new-tokens {args.new_tokens}: every arm's slots would "
            "overflow"
        )
    jax = init_devices(args.devices)
    chunks = [None if c.strip() in ("off", "0", "none") else int(c)
              for c in args.prefill_chunks.split(",")]
    hit_rates = ([float(h) for h in args.prefix_hit_rates.split(",")]
                 if args.prefix_hit_rates else [None])
    spec_ks = ([None if int(k) == 0 else int(k)
                for k in args.spec_k.split(",")]
               if args.spec_k else [None])

    if args.kv_tiers:
        # the tiered-KV sweep: tier config x working set arms, each a
        # serving_kv_tiers JSON line with audited per-tier traffic
        if args.disagg or args.replicas or args.prefix_hit_rates \
                or args.spec_k:
            raise SystemExit(
                "--kv-tiers composes with --working-sets/--host-tier-"
                "entries, not the --disagg/--replicas/--prefix-hit-rates/"
                "--spec-k sweeps"
            )
        for rate in [float(r) for r in args.rates.split(",")]:
            for n_slots in [int(s) for s in args.slots.split(",")]:
                for chunk in chunks:
                    for ws in [int(w) for w in
                               args.working_sets.split(",")]:
                        for tc in args.kv_tiers.split(","):
                            arm = run_kv_tier_arm(args, jax, args.stack,
                                                  rate, n_slots, chunk,
                                                  tc.strip(), ws)
                            if arm is None:
                                print(json.dumps({
                                    "bench": "serving_kv_tiers",
                                    "tier_config": tc.strip(),
                                    "working_set": ws, "slots": n_slots,
                                    "prefill_chunk": chunk,
                                    "skipped": "kv-tier arms need the "
                                               "dense stack, a prefill "
                                               "chunk, and a chunk-"
                                               "reachable shared prefix",
                                }), flush=True)
                                continue
                            print(json.dumps(arm), flush=True)
        return

    if args.tenants:
        # the multi-tenant isolation sweep: baseline + (with
        # --overload-tenant) the fair-on/fair-off overload pair, each a
        # serving_tenants JSON line whose victim/overloader SLO split and
        # per-tenant traffic come from real counter deltas
        if args.disagg or args.replicas or args.prefix_hit_rates \
                or args.spec_k or args.kv_tiers:
            raise SystemExit(
                "--tenants composes with --overload-tenant/"
                "--adapter-rank, not the --disagg/--replicas/"
                "--prefix-hit-rates/--spec-k/--kv-tiers sweeps"
            )
        arms = [(True, False)]
        if args.overload_tenant:
            arms += [(True, True), (False, True)]
        for rate in [float(r) for r in args.rates.split(",")]:
            for n_slots in [int(s) for s in args.slots.split(",")]:
                for chunk in chunks:
                    for fair, over in arms:
                        arm = run_tenant_arm(args, jax, args.stack, rate,
                                             n_slots, chunk, fair, over)
                        if arm is None:
                            print(json.dumps({
                                "bench": "serving_tenants",
                                "tenants": args.tenants, "fair": fair,
                                "overload": over, "slots": n_slots,
                                "prefill_chunk": chunk,
                                "skipped": "slots must divide the MoE "
                                           "world, --step-tokens < the "
                                           "arm's chunk, or --adapter-"
                                           "rank off the dense stack",
                            }), flush=True)
                            continue
                        print(json.dumps(arm), flush=True)
        return

    if args.replicas:
        # the scale-out sweep: replicas x overload x priority-mix x
        # preempt arms, each a serving_router JSON line labeled off real
        # routing/preemption counter deltas
        if args.disagg or args.prefix_hit_rates or args.spec_k:
            raise SystemExit(
                "--replicas composes with --overload/--priority-mix, not "
                "the --disagg/--prefix-hit-rates/--spec-k sweeps"
            )
        mixes = ([None if m.strip() == "off" else float(m)
                  for m in args.priority_mix.split(",")]
                 if args.priority_mix else [None])
        preempts = [p.strip() == "on" for p in args.preempt.split(",")]
        for rate in [float(r) for r in args.rates.split(",")]:
            for n_slots in [int(s) for s in args.slots.split(",")]:
                for chunk in chunks:
                    for n_rep in [int(x)
                                  for x in args.replicas.split(",")]:
                        for overload in [float(x) for x
                                         in args.overload.split(",")]:
                            for mix in mixes:
                                for pre in (preempts if mix is not None
                                            else [False]):
                                    arm = run_router_arm(
                                        args, jax, args.stack, rate,
                                        n_slots, chunk, n_rep, mix, pre,
                                        overload,
                                    )
                                    if arm is not None:
                                        print(json.dumps(arm), flush=True)
        return

    for rate in [float(r) for r in args.rates.split(",")]:
        for n_slots in [int(s) for s in args.slots.split(",")]:
            for chunk in chunks:
                for hit_rate in hit_rates:
                    for spec_k in spec_ks:
                        if args.disagg:
                            arm = run_disagg_arm(args, jax, args.stack,
                                                 rate, n_slots, chunk,
                                                 hit_rate, spec_k)
                        else:
                            arm = run_arm(args, jax, args.stack, rate,
                                          n_slots, chunk, hit_rate,
                                          spec_k)
                        if arm is None:
                            print(json.dumps({
                                "bench": ("serving_disagg" if args.disagg
                                          else "serving"),
                                "stack": args.stack,
                                "arrival_rate": rate, "slots": n_slots,
                                "prefill_chunk": chunk,
                                "prefix_hit_rate": hit_rate,
                                "spec_k": spec_k,
                                "skipped": "slots must divide by the MoE "
                                           "world, --step-tokens < the "
                                           "arm's chunk, a chunkless "
                                           "prefix/disagg arm, or a "
                                           "shared prefix shorter than "
                                           "the chunk (no hit possible)",
                            }), flush=True)
                            continue
                        print(json.dumps(arm), flush=True)


if __name__ == "__main__":
    main()
