"""Shared benchmark bootstrap: repo path + optional virtual-CPU device forcing."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def init_devices(n_virtual: int):
    """Import jax, forcing n_virtual CPU devices when n_virtual > 0 (guarding
    against double-appending the XLA flag on repeated calls)."""
    if n_virtual:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_virtual}"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax
    return jax
