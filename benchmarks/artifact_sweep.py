"""OSDI-artifact-style parameter sweeps over the p2p transfer engine.

The reference's artifact (collective/utran_osdi26ae.md:28-36, 135-250) fixes
its figures with three knob sweeps plus a loss-recovery study: message sizes
1 KB -> 1 GB, ``UCCL_CHUNK_SIZE_KB`` in {8..256}, ``UCCL_NUM_ENGINES`` in
{1,2,4,8}, and injected loss rates. This runner reproduces the same recipe
shapes against this framework's knobs on TCP loopback (2 local ranks):

  A. message-size sweep            (p2p_bench, 1 KB -> 64 MB, 1 & 4 paths)
  B. chunk-size sweep              (chunk_bytes 8 KB -> 1 MB at 16 MB msgs)
  C. engine-count sweep            (n_engines 1/2/4/8 at 16 MB msgs)
  D. loss-recovery study           (set_drop_rate 0..10%, goodput + chunk
                                    retransmissions via Channel retry)

Each row prints as one JSON line; --markdown appends a table to
docs/ARTIFACT_SWEEP.md. Loopback on this sandbox measures the engine's
scheduling/framing costs, not NIC bandwidth — the transferable signals are
the SHAPES (chunk-size knee, engine scaling, graceful loss degradation),
the same thing the reference's figures argue.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from uccl_tpu.p2p import Channel, Endpoint  # noqa: E402


def _pair(n_engines=2, n_paths=4, chunk_bytes=None):
    """(server_ep, client_ep, server_chan, client_chan) on loopback.
    Endpoints are closed on ANY setup failure — engine threads must not
    outlive a failed sweep point."""
    server = Endpoint(n_engines=n_engines)
    client = Endpoint(n_engines=n_engines)
    try:
        acc = {}

        def srv():
            acc["chan"] = Channel.accept(server, chunk_bytes=chunk_bytes)

        t = threading.Thread(target=srv)
        t.start()
        chan = Channel.connect(
            client, "127.0.0.1", server.port, n_paths=n_paths,
            chunk_bytes=chunk_bytes,
        )
        t.join(timeout=20)
        if "chan" not in acc:
            raise RuntimeError("accept side did not complete")
        return server, client, acc["chan"], chan
    except BaseException:
        client.close()
        server.close()
        raise


def _timed_writes(server, chan, size, iters, timeout_ms=60000):
    """Mean seconds per write of `size` bytes into an advertised window,
    plus the retransmitted-chunk count attributable to the timed writes
    (warmup excluded). Window reuse across identical messages is safe
    without a fence here: every write carries the same bytes."""
    dst = np.zeros(size, np.uint8)
    fifo = server.advertise(server.reg(dst))
    src = np.random.default_rng(0).integers(0, 255, size).astype(np.uint8)
    chan.write(src, fifo, timeout_ms=timeout_ms)  # warmup
    base = chan.retransmitted_chunks
    t0 = time.perf_counter()
    for _ in range(iters):
        chan.write(src, fifo, timeout_ms=timeout_ms)
    dt = (time.perf_counter() - t0) / iters
    return dt, chan.retransmitted_chunks - base


def sweep_msg_size(emit, iters):
    from benchmarks.p2p_bench import run as p2p_run

    for row in p2p_run(
        sizes=(1 << 10, 16 << 10, 256 << 10, 4 << 20, 64 << 20),
        iters=iters, paths=(1, 4), quiet=True,
    ):
        emit({"fig": "A_msg_size", **row})


def sweep_chunk_size(emit, iters, size=16 << 20):
    for ck in (8, 32, 64, 128, 256, 1024):
        server, client, _, chan = _pair(chunk_bytes=ck << 10)
        with server, client:
            dt, _ = _timed_writes(server, chan, size, iters)
            emit({
                "fig": "B_chunk_size", "chunk_kb": ck, "size": size,
                "GB/s": round(size / dt / 1e9, 3),
                "lat_ms": round(dt * 1e3, 2),
            })


def sweep_engines(emit, iters, size=16 << 20):
    for ne in (1, 2, 4, 8):
        server, client, _, chan = _pair(n_engines=ne, n_paths=max(ne, 1))
        with server, client:
            dt, _ = _timed_writes(server, chan, size, iters)
            emit({
                "fig": "C_engines", "n_engines": ne, "size": size,
                "GB/s": round(size / dt / 1e9, 3),
                "lat_ms": round(dt * 1e3, 2),
            })


def sweep_loss(emit, iters, size=4 << 20):
    """Goodput + recovery work vs injected frame-loss rate. Retry budget is
    raised so high loss converges by retransmission rather than failing
    (reference recipe: loss rates for the recovery study)."""
    for drop in (0.0, 0.01, 0.05, 0.10):
        server, client, _, chan = _pair(chunk_bytes=256 << 10)
        chan.retries = 16
        with server, client:
            client.set_drop_rate(drop)
            try:
                dt, retrans = _timed_writes(
                    server, chan, size, iters, timeout_ms=400
                )
            finally:
                client.set_drop_rate(0.0)
            emit({
                "fig": "D_loss", "drop": drop, "size": size,
                "goodput_GB/s": round(size / dt / 1e9, 3),
                "lat_ms": round(dt * 1e3, 2),
                "retransmitted_chunks": retrans,
            })


def sweep_loss_udp(emit, iters, size=4 << 20):
    """Fig E: the same loss study over the UDP wire, where recovery is the
    ENGINE's SACK/selective-repeat (RTO at millisecond scale) instead of the
    channel's progress-timeout chunk retransmission (400 ms detection). This
    is the configuration where loss handling is load-bearing: packets are
    genuinely dropped before the socket and only retransmission delivers
    the bytes."""
    import os

    os.environ["UCCL_TPU_WIRE"] = "udp"
    try:
        for drop in (0.0, 0.01, 0.05, 0.10, 0.20):
            server = Endpoint(n_engines=1)
            client = Endpoint(n_engines=1)
            with server, client:
                cid = client.connect("127.0.0.1", server.port)
                server.accept(timeout_ms=5000)
                dst = np.zeros(size, np.uint8)
                fifo = server.advertise(server.reg(dst))
                src = np.random.default_rng(0).integers(
                    0, 255, size
                ).astype(np.uint8)
                # warmup (no loss)
                client.wait(
                    client.write_async(cid, src, fifo), timeout_ms=60000
                )
                base = client.conn_stats(cid)["pkts_rtx"]
                client.set_drop_rate(drop)
                try:
                    t0 = time.perf_counter()
                    for _ in range(iters):
                        ok = client.wait(
                            client.write_async(cid, src, fifo),
                            timeout_ms=120000,
                        )
                        if not ok:
                            raise RuntimeError(f"write lost at drop={drop}")
                    dt = (time.perf_counter() - t0) / iters
                finally:
                    client.set_drop_rate(0.0)
                retx = client.conn_stats(cid)["pkts_rtx"] - base
                if not np.array_equal(dst, src):
                    raise RuntimeError(f"corruption at drop={drop}")
                emit({
                    "fig": "E_loss_udp", "drop": drop, "size": size,
                    "goodput_GB/s": round(size / dt / 1e9, 3),
                    "lat_ms": round(dt * 1e3, 2),
                    "retransmitted_pkts": int(retx),
                })
    finally:
        del os.environ["UCCL_TPU_WIRE"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--figs", default="A,B,C,D,E",
                    help="comma list from A,B,C,D,E (E = UDP-wire loss study)")
    ap.add_argument("--markdown", action="store_true",
                    help="append results table to docs/ARTIFACT_SWEEP.md")
    args = ap.parse_args()

    rows = []

    def emit(row):
        rows.append(row)
        print(json.dumps(row), flush=True)

    figs = {f.strip().upper() for f in args.figs.split(",")}
    if "A" in figs:
        sweep_msg_size(emit, args.iters)
    if "B" in figs:
        sweep_chunk_size(emit, args.iters)
    if "C" in figs:
        sweep_engines(emit, args.iters)
    if "D" in figs:
        sweep_loss(emit, args.iters)
    if "E" in figs:
        sweep_loss_udp(emit, args.iters)

    if args.markdown and rows:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs", "ARTIFACT_SWEEP.md")
        with open(path, "a") as f:
            f.write(f"\n## Sweep run ({time.strftime('%Y-%m-%d %H:%M')}, "
                    f"iters={args.iters})\n\n")
            keys = sorted({k for r in rows for k in r})
            f.write("| " + " | ".join(keys) + " |\n")
            f.write("|" + "---|" * len(keys) + "\n")
            for r in rows:
                f.write("| " + " | ".join(str(r.get(k, "")) for k in keys)
                        + " |\n")
        print(f"[artifact_sweep] appended {len(rows)} rows to {path}",
              file=sys.stderr)
    return rows


if __name__ == "__main__":
    main()
