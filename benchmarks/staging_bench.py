"""HBM↔host↔wire staging pipeline benchmark (send_jax/recv_jax).

Measures end-to-end tensor hand-off latency over TCP loopback: monolithic
(stage the WHOLE tensor to host, then send — the round-2 serial path) vs
pipelined (chunked D2H overlapped with wire TX and chunked H2D on receive,
SURVEY §7 hard-part 3; the reference hides staging with GPUDirect/bounce-pool
pipelining, p2p/engine.cc staged paths). Prints one JSON line per size.

On a real TPU the D2H/H2D legs are genuine DMAs and the overlap is larger;
on CPU-jax the staging legs are memcpys, so the measured win here is the
wire/copy overlap only (a lower bound).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from uccl_tpu.p2p import Endpoint  # noqa: E402


def _xfer(server, client, conn_s, conn_c, x, shape, dtype, chunk_bytes):
    box = {}

    def rx():
        y = server.recv_jax(conn_s, shape, dtype, timeout_ms=120000)
        np.asarray(y).reshape(-1)[:1]  # host read: the tensor is really there
        box["y"] = y

    t = threading.Thread(target=rx)
    t.start()
    t0 = time.perf_counter()
    client.send_jax(conn_c, x, chunk_bytes=chunk_bytes)
    t.join()
    return time.perf_counter() - t0


def run(sizes=(16 << 20, 64 << 20, 256 << 20), iters=5, chunk=8 << 20):
    import jax.numpy as jnp

    results = []
    with Endpoint(n_engines=2) as server, Endpoint(n_engines=2) as client:
        conn_c = client.connect("127.0.0.1", server.port)
        conn_s = server.accept()
        for size in sizes:
            elems = size // 4
            x = jnp.arange(elems, dtype=jnp.float32)
            shape, dtype = (elems,), np.float32
            for mode, cb in (("serial", 1 << 62), ("pipelined", chunk)):
                _xfer(server, client, conn_s, conn_c, x, shape, dtype, cb)
                ts = [
                    _xfer(server, client, conn_s, conn_c, x, shape, dtype, cb)
                    for _ in range(iters)
                ]
                best = min(ts)
                results.append(
                    {
                        "size": size,
                        "mode": mode,
                        "ms": round(best * 1e3, 2),
                        "GB/s": round(size / best / 1e9, 3),
                    }
                )
                print(json.dumps(results[-1]))
            s = next(r for r in results if r["size"] == size and r["mode"] == "serial")
            p = next(r for r in results if r["size"] == size and r["mode"] == "pipelined")
            print(json.dumps({"size": size, "pipelined_vs_serial": round(p["ms"] / s["ms"], 3)}))
    return results


if __name__ == "__main__":
    # This measures host wire/staging overlap — force CPU the way
    # tests/conftest.py does (the env var alone does not stop a
    # pre-registered TPU PJRT plugin from initializing, and a wedged
    # tunnel then blocks backend init indefinitely).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    run()
