"""EP dispatch+combine benchmark — the test_low_latency.py analog.

Reports per-member dispatch latency, combine latency, and bandwidth for both
the normal (sorted, capacity-padded) path and the packed low-latency path
(reference metric definition: ep/bench/test_low_latency.py:438-464 — per-rank
dispatch/combine GB/s and avg/min/max µs).

Usage:
  python benchmarks/ep_bench.py [--devices N] [--tokens T] [--hidden H]
  python benchmarks/ep_bench.py --ll            # low-latency packed path
  python benchmarks/ep_bench.py --table         # E ∈ {8, 32} latency table
  python benchmarks/ep_bench.py --wire pallas   # device-initiated remote-DMA
                                                # all-to-all (ep/pallas_a2a)
  python benchmarks/ep_bench.py --wire pallas --chunks 2,4
      # chunk-pipelined MoE layer sweep: per-chunk double-buffered
      # dispatch/GEMM/combine vs the strictly phased step, with the
      # overlap-efficiency metric (fraction of wire time hidden under the
      # expert GEMMs, from the slope estimator legs — docs/EP_BENCH.md)
"""

from __future__ import annotations

import argparse
import time

from _bootstrap import init_devices


def _time_fn(fn, args, iters):
    """Per-op latency via the shared SLOPE estimator
    (uccl_tpu.utils.timing.slope_timeit): chained fori_loop, differenced
    over two run lengths so the fixed tunnel cost (dispatch + host-read
    RTT, tens of ms) cancels exactly — a per-call loop over µs-scale EP
    ops measures only its own dispatch floor (the round-4 on-chip table
    recorded tens of ms for ops this measures in tens of µs). Imported
    lazily: uccl_tpu pulls in jax, which must not initialize before
    init_devices has set XLA_FLAGS."""
    from uccl_tpu.utils.timing import slope_timeit

    return slope_timeit(fn, args, iters)


def _time_fn_percall(fn, args, iters):
    """One dispatch per iteration, host-read sync (jax_block). Carries the
    full per-call tunnel overhead — use ONLY where the op itself cannot be
    traced into a fori_loop (the cross-pod forward does host socket I/O),
    and time BOTH sides of any reported ratio with this same discipline so
    the fixed cost cancels in the quotient."""
    out = fn(*args)  # compile + warmup
    jax_block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax_block(out)
    return (time.perf_counter() - t0) / iters


def jax_block(tree):
    import jax
    import numpy as np

    leaves = [x for x in jax.tree.leaves(tree) if hasattr(x, "block_until_ready")]
    for x in leaves:  # host-read EVERY leaf: tunnel's block_until_ready lies
        np.asarray(x).reshape(-1)[:1]


def _ep_bytes_snapshot():
    from uccl_tpu.obs import counters as obsc

    fam = obsc.counter("ep_bytes_total")
    return {tuple(sorted(lb.items())): v for lb, v in fam.samples()}


def _ep_bytes_delta(before):
    return sum(
        int(v - before.get(k, 0))
        for k, v in _ep_bytes_snapshot().items()
        if v > before.get(k, 0)
    )


def bench_config(jax, *, tokens, hidden, experts, topk, iters, mode, fp8,
                 wire="auto", wire_dtype=None, return_recv=False):
    """Time dispatch and combine separately for one config. Returns a dict.

    Per-verb wire bytes come off the REAL ``ep_bytes_total`` counter delta
    around one call (quantized payload + scale sidecar when ``wire_dtype``
    applies — the counter's arithmetic, never re-derived here), and
    ``wire_gbps`` is the effective per-member wire bandwidth those bytes
    imply at the measured latencies."""
    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.ep import Buffer
    from uccl_tpu.parallel.mesh import AXIS, MeshConfig, make_mesh

    n = len(jax.devices())
    if wire == "pallas":
        # the legacy discharge interpreter can only address single-named-axis
        # meshes; a 1-axis dp mesh keeps the pallas arm runnable everywhere
        # (Buffer would otherwise downgrade the wire silently)
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        axis = "dp"
    else:
        mesh = make_mesh(MeshConfig(dp=n))
        axis = AXIS.EP
    experts = max(experts, n)
    experts -= experts % n
    buf = Buffer(mesh, axis, num_experts=experts, num_selected=topk,
                 wire=wire, wire_dtype=wire_dtype)

    rng = np.random.default_rng(0)
    x = buf.device_put(
        rng.standard_normal((n, tokens, hidden)).astype(np.float32)
    )
    idx = buf.device_put(
        rng.integers(0, experts, (n, tokens, topk)).astype(np.int32)
    )
    wts = buf.device_put(
        np.full((n, tokens, topk), 1.0 / topk, np.float32)
    )

    # wire_dtype rides the Buffer default; without it the legacy --fp8 flag
    # maps onto an explicit per-call wire_fp8 (preserving the old bench's
    # explicit-off for the LL path, whose Buffer default is fp8-on)
    fp8_kw = {} if wire_dtype is not None else {"wire_fp8": fp8}
    if mode == "ll":
        recv, counts, handle = buf.low_latency_dispatch(
            x, idx, None, wts, **fp8_kw
        )
        before = _ep_bytes_snapshot()
        buf.low_latency_dispatch(x, idx, None, wts, **fp8_kw)
        bytes_dispatch = _ep_bytes_delta(before)
        before = _ep_bytes_snapshot()
        buf.low_latency_combine(recv, handle)
        bytes_combine = _ep_bytes_delta(before)
        dt_dispatch = _time_fn(
            lambda a, b, c: buf.low_latency_dispatch(a, b, None, c,
                                                     **fp8_kw),
            (x, idx, wts), iters,
        )
        dt_combine = _time_fn(
            lambda y: buf.low_latency_combine(y, handle), (recv,), iters
        )
        wire_rows = tokens * topk  # actual rows moved (ragged wire)
    else:
        recv, handle = buf.dispatch(x, idx, wts, **fp8_kw)
        before = _ep_bytes_snapshot()
        buf.dispatch(x, idx, wts, **fp8_kw)
        bytes_dispatch = _ep_bytes_delta(before)
        before = _ep_bytes_snapshot()
        buf.combine(recv, handle, **fp8_kw)
        bytes_combine = _ep_bytes_delta(before)
        dt_dispatch = _time_fn(
            lambda a, b, c: buf.dispatch(a, b, c, **fp8_kw)[0],
            (x, idx, wts), iters,
        )
        dt_combine = _time_fn(
            lambda y: buf.combine(y, handle, **fp8_kw), (recv,), iters
        )
        wire_rows = experts // n * buf.capacity(tokens) * n  # padded slots

    bytes_per_row = hidden * (1 if (fp8 or wire_dtype) else 4)
    out = {
        "mode": mode,
        "wire": wire,
        "wire_dtype": wire_dtype or ("fp8" if fp8 else "none"),
        "experts": experts,
        "tokens": tokens,
        "hidden": hidden,
        "topk": topk,
        "dispatch_us": dt_dispatch * 1e6,
        "combine_us": dt_combine * 1e6,
        "gbps": wire_rows * bytes_per_row / (dt_dispatch + dt_combine) / 1e9,
        "wire_bytes_dispatch": bytes_dispatch,
        "wire_bytes_combine": bytes_combine,
        "wire_gbps": (bytes_dispatch + bytes_combine)
        / (dt_dispatch + dt_combine) / 1e9,
    }
    if return_recv:
        out["_recv"] = np.asarray(recv)
    return out


def bench_quant_sweep(jax, *, tokens, hidden, experts, topk, iters, mode,
                      wire, wire_dtypes):
    """Quantized-wire EP arms: one JSON line with a full-precision anchor
    arm plus one arm per ``wire_dtype``. Per-arm wire bytes and effective
    bandwidth come off the REAL ``ep_bytes_total{...,wire_dtype}`` counter
    deltas (bench_config — quantized payload + scale sidecar, never
    mirrored arithmetic); error is max-abs/rel of the dispatch recv buffer
    vs the full-precision arm (same routing seed, so the wire is the only
    difference — docs/QUANT_WIRE.md)."""
    import json

    import numpy as np

    from uccl_tpu import obs

    arms = []
    ref = None
    ref_bytes = None
    for wd in [None] + list(wire_dtypes):
        r = bench_config(
            jax, tokens=tokens, hidden=hidden, experts=experts, topk=topk,
            iters=iters, mode=mode, fp8=False, wire=wire, wire_dtype=wd,
            return_recv=True,
        )
        recv = r.pop("_recv")
        wire_bytes = r["wire_bytes_dispatch"] + r["wire_bytes_combine"]
        if wd is None:
            ref, ref_bytes = recv, wire_bytes
            err_abs = err_rel = 0.0
        else:
            err_abs = float(np.abs(recv - ref).max())
            err_rel = float(err_abs / (np.abs(ref).max() + 1e-12))
        arms.append({
            "wire_dtype": wd or "none",
            "dispatch_us": round(r["dispatch_us"], 1),
            "combine_us": round(r["combine_us"], 1),
            "wire_bytes_dispatch": r["wire_bytes_dispatch"],
            "wire_bytes_combine": r["wire_bytes_combine"],
            "wire_gbps": round(r["wire_gbps"], 3),
            "wire_byte_reduction": round(ref_bytes / wire_bytes, 2)
            if wire_bytes else None,
            "max_abs_err": err_abs,
            "max_rel_err": err_rel,
        })
    line = {
        "bench": "ep_quant_sweep", "schema_version": obs.SCHEMA_VERSION,
        "mode": mode, "wire": wire, "tokens": tokens, "hidden": hidden,
        "experts": r["experts"], "topk": topk,
        "substrate": jax.default_backend(),
        "arms": arms,
    }
    print(json.dumps(line))
    return line


def bench_skew_sweep(jax, *, tokens, hidden, experts, topk, iters, alphas,
                     modes, fp8=False, n_chunks=1):
    """Contention-aware scheduled a2a sweep: Zipf(alpha) routing skew x
    ``a2a_sched`` mode (docs/EP_BENCH.md "scheduled all-to-all").

    Per alpha, one routing draw (uccl_tpu.ep.a2a_sched.zipf_topk) fixes the
    traffic matrix for every mode arm, so the wire ORDER is the only
    difference. Every arm label comes off REAL counters, never the CLI
    knob mirrored back: the algo that actually drove the exchange from the
    ``collective_plan_total{verb="ep_a2a"}`` delta, the round count from
    ``ep_a2a_rounds_total``, wire bytes from ``ep_bytes_total``, and any
    budget downgrade from ``ep_wire_fallback_total``. The off-arm recv
    buffer is the exactness anchor: scheduled arms must match it
    bit-for-bit (the schedule is a pure reordering of the same write-once
    DMAs). ``fp8``/``n_chunks`` compose the sweep with the quantized wire
    and chunk pipelining — on the CPU-fit interpret budget that
    composition is what makes the model's sched/streams crossover
    physically reachable (the per-chunk gate, not the monolithic one).
    Each sweep also records the cost model's round-time for BOTH wire
    orders at the measured skew (``model``): on interpret substrates the
    wall-clock columns measure the rendezvous emulation, so the audited
    model delta is the honest "what a real wire would save" number."""
    import json

    import numpy as np
    from jax.sharding import Mesh

    from uccl_tpu import obs
    from uccl_tpu.collective import dma
    from uccl_tpu.collective import plan as _plan
    from uccl_tpu.ep import Buffer, a2a_sched
    from uccl_tpu.obs import counters as obsc

    n = len(jax.devices())
    # single-named-axis mesh: the legacy discharge interpreter's pallas
    # addressing constraint, same as the --wire pallas arm above
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    experts = max(experts, n)
    experts -= experts % n
    rng = np.random.default_rng(0)
    x_np = rng.standard_normal((n, tokens, hidden)).astype(np.float32)
    wts_np = np.full((n, tokens, topk), 1.0 / topk, np.float32)

    def _snap(name, **match):
        return {tuple(sorted(lb.items())): v
                for lb, v in obsc.counter(name).samples()
                if all(lb.get(k) == v2 for k, v2 in match.items())}

    def _delta(name, before, **match):
        return {k: int(v - before.get(k, 0))
                for k, v in _snap(name, **match).items()
                if v - before.get(k, 0) > 0}

    wire_dtype = "fp8" if fp8 else None
    sweeps = []
    for alpha in alphas:
        idx_np = a2a_sched.zipf_topk(rng, n, tokens, topk, experts, alpha)
        arms = []
        ref_recv = None
        traffic = None
        for mode in modes:
            r0 = _snap("ep_a2a_rounds_total")
            p0 = _snap("collective_plan_total", verb="ep_a2a")
            b0 = _ep_bytes_snapshot()
            f0 = _snap("ep_wire_fallback_total")
            buf = Buffer(mesh, "dp", num_experts=experts,
                         num_selected=topk, wire="pallas",
                         n_chunks=n_chunks, wire_dtype=wire_dtype,
                         a2a_sched=mode)
            if traffic is None:
                traffic = a2a_sched.traffic_from_topk(
                    idx_np, experts, buf.capacity(tokens), n
                )
            if mode != "off":
                # rebuild with the measured matrix (static per Buffer)
                buf = Buffer(mesh, "dp", num_experts=experts,
                             num_selected=topk, wire="pallas",
                             n_chunks=n_chunks, wire_dtype=wire_dtype,
                             a2a_sched=mode, a2a_traffic=traffic)
            x = buf.device_put(x_np)
            idx = buf.device_put(idx_np)
            wts = buf.device_put(wts_np)
            recv, handle = buf.dispatch(x, idx, wts)
            buf.combine(recv, handle)
            rounds = _delta("ep_a2a_rounds_total", r0)
            plans = _delta("collective_plan_total", p0, verb="ep_a2a")
            wire_bytes = _ep_bytes_delta(b0)
            fallbacks = {f"{dict(k)['what']}:{dict(k)['reason']}": v
                         for k, v in
                         _delta("ep_wire_fallback_total", f0).items()}
            algos = sorted({dict(k)["algo"] for k in plans}) or (
                ["ep_streams"] if mode == "off" else [])
            recv_np = np.asarray(recv)
            if mode == "off":
                ref_recv = recv_np
            dt_dispatch = _time_fn(
                lambda a, b, c: buf.dispatch(a, b, c)[0],
                (x, idx, wts), iters,
            )
            dt_combine = _time_fn(
                lambda y: buf.combine(y, handle), (recv,), iters
            )
            arms.append({
                "a2a_sched": mode,
                "algo": "+".join(algos),
                "sched_active": bool(handle.a2a_sched),
                "rounds": {dict(k)["algo"]: v for k, v in rounds.items()},
                "dispatch_us": round(dt_dispatch * 1e6, 1),
                "combine_us": round(dt_combine * 1e6, 1),
                "wire_bytes": wire_bytes,
                "wire_fallbacks": fallbacks,
                "bit_identical_to_off": bool(
                    ref_recv is not None
                    and np.array_equal(recv_np, ref_recv)
                ),
            })
        # the cost model's round-time for BOTH wire orders at the measured
        # skew and the REAL round count (plan_ep_a2a's own arithmetic, one
        # quiet plan call cross-checks the reconstruction) — on interpret
        # substrates this is the honest perf column; the wall clocks above
        # time the rendezvous emulation, not a wire
        skew_v = a2a_sched.skew(traffic)
        rounds_n = len(a2a_sched.wire_schedule(traffic, n)[0])
        cap = buf.capacity(tokens)
        shape = (n, experts // n, cap, hidden)
        cep = buf._sched_chunk_charge(n_chunks, cap,
                                      (experts // n) * hidden)
        planner = _plan.get_planner()
        mdl = planner.model
        mean_bytes = (n - 1) / n * planner.wire_bytes(
            shape, np.float32, wire_dtype)
        streams_us = (mdl.alpha_us * (n - 1)
                      + mdl.beta_us_per_byte * max(1.0, skew_v) * mean_bytes
                      + mdl.gamma_us)
        sched_us = (mdl.alpha_us * rounds_n
                    + mdl.beta_us_per_byte * mean_bytes
                    + mdl.gamma_us * rounds_n)
        p = planner.plan_ep_a2a(
            shape, np.float32, n, skew=skew_v, n_rounds=rounds_n,
            wire_dtype=wire_dtype,
            n_chunks=n_chunks if cep is not None else 1,
            chunk_elems_per_peer=cep, emit=False,
        )
        assert abs(p.predicted_us
                   - (sched_us if p.algo == "ep_sched" else streams_us)) \
            < 1e-6, "bench model reconstruction drifted from plan_ep_a2a"
        sweeps.append({
            "alpha": alpha,
            "skew": round(skew_v, 3),
            "traffic_rows": [int(v) for v in
                             np.asarray(traffic).sum(axis=1)],
            "model": {
                "n_rounds": rounds_n,
                "streams_us": round(streams_us, 2),
                "sched_us": round(sched_us, 2),
                "round_time_reduction_pct": round(
                    100.0 * (streams_us - sched_us) / streams_us, 1),
                "planner_algo": p.algo,
            },
            "arms": arms,
        })
    line = {
        "bench": "ep_sched_sweep", "schema_version": obs.SCHEMA_VERSION,
        "tokens": tokens, "hidden": hidden, "experts": experts,
        "topk": topk, "world": n, "fp8": bool(fp8), "n_chunks": n_chunks,
        "interpret_budget_bytes": dma.budget_limit(
            dma.resolve_interpret(None)),
        "substrate": jax.default_backend(),
        "sweeps": sweeps,
    }
    print(json.dumps(line))
    return line


def bench_chunk_sweep(jax, *, tokens, hidden, ffn, experts, topk, iters,
                      chunks, fp8):
    """Chunk-pipelined MoE layer sweep on the pallas wire.

    Three slope-estimated legs per shape — wire-only (route + dispatch +
    combine, no GEMM), compute-only (the three expert einsums on a resident
    recv buffer), and the full layer step at each chunk depth — yield the
    overlap-efficiency metric:

        overlap_efficiency(N) = (t_wire + t_gemm - t_layer(N)) / t_wire

    i.e. the fraction of the wire leg hidden under compute (1.0 = the wire
    is free; <= 0 = no overlap, or chunk overhead ate the gain). All legs
    ride the same estimator so fixed dispatch cost cancels. Also reports
    whether the pallas kernel actually carried each arm or the budget gate
    took the fallback chain (chunked → unchunked pallas → lax; PERF.md
    honesty: on the virtual CPU mesh these are contract/overhead numbers —
    overlap gains are claimed on-chip only)."""
    import json

    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import jax.numpy as jnp

    from uccl_tpu.collective import dma
    from uccl_tpu.ep import ops as ep_ops
    from uccl_tpu.utils.jaxcompat import shard_map

    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    experts = max(experts, n)
    experts -= experts % n
    e_local = experts // n
    cap = max(1, int(1.25 * tokens * topk / experts))
    rng = np.random.default_rng(0)

    def put(a, spec=P("dp")):
        return jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec))

    x = put(rng.standard_normal((n, tokens, hidden)).astype(np.float32))
    logits = put(rng.standard_normal((n, tokens, experts)).astype(np.float32))
    scale = 1.0 / np.sqrt(hidden)
    wg = put((rng.standard_normal((experts, hidden, ffn)) * scale).astype(
        np.float32))
    wu = put((rng.standard_normal((experts, hidden, ffn)) * scale).astype(
        np.float32))
    wd = put((rng.standard_normal((experts, ffn, hidden)) * scale).astype(
        np.float32))

    def shmap(f, n_in, out_specs=P("dp")):
        return jax.jit(shard_map(
            f, mesh, tuple(P("dp") for _ in range(n_in)), out_specs,
            check_vma=False,
        ))

    def layer_fn(n_chunks):
        def f(xv, lv, g, u, d):
            out, _, _ = ep_ops.moe_ffn(
                xv[0], lv[0], g, u, d, "dp", num_selected=topk,
                capacity_factor=1.25, impl="sort", wire="pallas",
                wire_fp8=fp8, n_chunks=n_chunks,
            )
            return out[None]

        return shmap(f, 5)

    def wire_f(xv, lv):
        rs = ep_ops.route_topk_sorted(lv[0], topk, cap)
        recv = ep_ops.dispatch_sorted(
            xv[0], rs.token_for_slot, experts, cap, "dp", wire="pallas",
            wire_fp8=fp8,
        )
        out = ep_ops.combine_sorted(
            recv, rs.slot, rs.weights, "dp", wire="pallas", wire_fp8=fp8
        )
        return out[None]

    def gemm_f(recv, g, u, d):
        xe = recv[0]
        act = jax.nn.silu(jnp.einsum("ebh,ehf->ebf", xe, g)) * jnp.einsum(
            "ebh,ehf->ebf", xe, u
        )
        return jnp.einsum("ebf,efh->ebh", act, d)[None]

    wire_fn = shmap(wire_f, 2)
    gemm_fn = shmap(gemm_f, 4)
    recv = put(rng.standard_normal(
        (n, e_local, n * cap, hidden)).astype(np.float32))

    # per-arm wire labels come off the REAL fallback counter
    # (obs ep_wire_fallback_total, incremented at trace time by the gates
    # themselves — uccl_tpu/collective/dma.py record_fallback) instead of
    # the old hand-mirrored budget arithmetic: snapshot before each arm's
    # compile, diff after. An "ep_all_to_all:*" event is the terminal
    # lax fallback (the unchunked kernel did not carry the exchange);
    # "ep_moe_chunked:*"/"ep_all_to_all_chunked:*" events mean only the
    # chunk pipeline degraded to the unchunked pallas wire.
    # ... and the RESOLVED chunk depth comes off the planner's decision
    # series (collective_plan_total{algo="ep_a2a", chunks}) the resolver
    # emits — never the requested CLI knob mirrored back.
    def _plan_snapshot():
        from uccl_tpu.obs import counters as obsc

        return {tuple(sorted(lb.items())): v
                for lb, v in obsc.counter("collective_plan_total").samples()
                if lb.get("algo") == "ep_a2a"}

    def _plan_chunks_delta(before):
        for k, v in _plan_snapshot().items():
            if v - before.get(k, 0) > 0:
                return int(dict(k)["chunks"])
        return None

    def _fb_snapshot():
        return {tuple(sorted(lb.items())): v
                for lb, v in dma.WIRE_FALLBACK.samples()}

    def _fb_delta(before):
        out = {}
        for k, v in _fb_snapshot().items():
            d = int(v - before.get(k, 0))
            if d > 0:
                lb = dict(k)
                out[f"{lb['what']}:{lb['reason']}"] = d
        return out

    t_wire = _time_fn(wire_fn, (x, logits), iters)
    t_gemm = _time_fn(gemm_fn, (recv, wg, wu, wd), iters)
    fb0 = _fb_snapshot()
    pl0 = _plan_snapshot()
    t1 = _time_fn(layer_fn(1), (x, logits, wg, wu, wd), iters)
    fb1 = _fb_delta(fb0)
    rc1 = _plan_chunks_delta(pl0)

    arms = []
    for nc in chunks:
        if nc == 1:
            t_n, fb, rc = t1, fb1, rc1
        else:
            before = _fb_snapshot()
            plb = _plan_snapshot()
            t_n = _time_fn(layer_fn(nc), (x, logits, wg, wu, wd), iters)
            fb = _fb_delta(before)
            rc = _plan_chunks_delta(plb)
        arms.append({
            "chunks": nc,
            "resolved_chunks": rc,
            "layer_us": round(t_n * 1e6, 1),
            "vs_unchunked": round(t_n / max(t1, 1e-12), 3),
            "overlap_efficiency": round(
                (t_wire + t_gemm - t_n) / max(t_wire, 1e-12), 3
            ),
            "pallas_wire_active": not any(
                k.startswith("ep_all_to_all:") for k in fb
            ),
            "wire_fallbacks": fb,
        })
    from uccl_tpu import obs

    line = {
        "bench": "ep_chunk_sweep", "schema_version": obs.SCHEMA_VERSION,
        "tokens": tokens, "hidden": hidden, "ffn": ffn,
        "experts": experts, "topk": topk, "fp8": fp8, "capacity": cap,
        "wire_us": round(t_wire * 1e6, 1),
        "gemm_us": round(t_gemm * 1e6, 1),
        "unchunked_layer_us": round(t1 * 1e6, 1),
        "arms": arms,
        "substrate": jax.default_backend(),
    }
    print(json.dumps(line))
    return line


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0)
    ap.add_argument("--tokens", type=int, default=512)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--experts", type=int, default=32)
    ap.add_argument("--topk", type=int, default=4)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--fp8", action="store_true")
    ap.add_argument(
        "--ll", action="store_true",
        help="packed low-latency path (ragged wire on TPU/GPU, grouped "
             "recv buffers + counts; the DeepEP LL contract)",
    )
    ap.add_argument(
        "--wire", default="auto",
        choices=["auto", "ragged", "dense", "pallas"],
        help="EP transport: 'pallas' = device-initiated remote-DMA "
             "all-to-all (uccl_tpu.ep.pallas_a2a, Buffer wire='pallas'); "
             "'auto' keeps the XLA-collective resolution",
    )
    ap.add_argument(
        "--table", action="store_true",
        help="print the per-rank latency table at E ∈ {8, 32} for both the "
             "normal and low-latency paths (the BASELINE.md north-star "
             "metric shape)",
    )
    ap.add_argument(
        "--compare-dense", action="store_true",
        help="also time the dense [T,E,C] mask-einsum oracle path and print "
             "the sorted-path speedup",
    )
    ap.add_argument(
        "--cross-pod", action="store_true",
        help="2-pod cross-pod MoE forward over DCN loopback: per-pod "
             "dispatch+compute+combine µs and a compute-only baseline "
             "(reference: proxy-served inter-node EP, ep/src/proxy.cpp:701)",
    )
    ap.add_argument(
        "--wire-dtype", default="",
        help="comma list of block-quantized wire arms to sweep beside a "
             "full-precision anchor (e.g. 'fp8,int8'): one JSON line with "
             "counter-derived wire bytes, effective bandwidth, wire-byte "
             "reduction, and max-abs/rel error per arm (docs/QUANT_WIRE.md)",
    )
    ap.add_argument(
        "--skew", default="",
        help="comma list of Zipf alphas (e.g. '0,0.8,1.2'): the "
             "contention-aware scheduled-a2a sweep — per alpha one routing "
             "draw, per --a2a-sched mode one counter-audited arm "
             "(docs/EP_BENCH.md). Size --tokens/--hidden to the interpret "
             "budget on CPU (e.g. --tokens 16 --hidden 64 --devices 4)",
    )
    ap.add_argument(
        "--a2a-sched", default="off,on,auto",
        help="comma list of Buffer a2a_sched modes for the --skew sweep "
             "(subset of off/on/auto; 'off' anchors the exactness check)",
    )
    ap.add_argument("--ffn", type=int, default=256,
                    help="expert FFN width for --cross-pod and the --chunks "
                         "sweep")
    ap.add_argument("--chunks", default="1",
                    help="chunk-pipeline depth(s). A single value sets the "
                         "cross-pod slot-space pipelining depth; with "
                         "--wire pallas a comma list (e.g. '2,4') runs the "
                         "chunk-pipelined MoE layer sweep and reports the "
                         "overlap-efficiency metric (docs/EP_BENCH.md)")
    from uccl_tpu import obs

    obs.add_cli_args(ap)
    args = ap.parse_args()
    # every CLI dumps the obs surfaces the same way (--trace-out /
    # --metrics-out, docs/OBSERVABILITY.md); the exit-time net covers
    # every return path of the mode dispatch below, crashes included
    obs.setup_from_args(args)
    obs.dump_at_exit(args)
    try:
        chunk_list = [int(c) for c in str(args.chunks).split(",") if c != ""]
    except ValueError:
        ap.error(f"--chunks wants an int or comma list of ints, got "
                 f"{args.chunks!r}")
    if not chunk_list:
        chunk_list = [1]
    if args.cross_pod and len(chunk_list) != 1:
        ap.error("--cross-pod takes a single --chunks depth (the sweep is "
                 "the pallas-wire mode)")
    if chunk_list != [1] and not args.cross_pod and not args.skew:
        # the chunk sweep is its own mode: validate the combination up
        # front instead of silently ignoring half the flags
        if args.wire != "pallas":
            ap.error("--chunks sweeps the chunk-pipelined pallas wire; add "
                     "--wire pallas")
        if any(c < 1 for c in chunk_list):
            ap.error("--chunks sweep arms are explicit depths >= 1 "
                     "(0 = auto is a layer knob, not a sweep arm)")
        if args.ll:
            ap.error("--chunks sweeps the sorted chunk-pipelined layer; "
                     "the LL path chunks only its wire (no per-chunk GEMM) "
                     "and has no sweep mode — drop --ll")
        if args.table:
            ap.error("--table and the --chunks sweep are separate modes; "
                     "pick one")

    wire_dtypes = [w for w in args.wire_dtype.split(",") if w]
    for w in wire_dtypes:
        if w not in ("fp8", "int8"):
            ap.error(f"unknown --wire-dtype arm {w!r} (want fp8/int8)")
    if wire_dtypes and (args.cross_pod or args.table
                        or chunk_list != [1]):
        ap.error("--wire-dtype is its own sweep mode; drop "
                 "--cross-pod/--table/--chunks")

    if args.skew:
        try:
            alphas = [float(a) for a in args.skew.split(",") if a != ""]
        except ValueError:
            ap.error(f"--skew wants a comma list of floats, got "
                     f"{args.skew!r}")
        sched_modes = [m for m in args.a2a_sched.split(",") if m]
        for m in sched_modes:
            if m not in ("off", "on", "auto"):
                ap.error(f"unknown --a2a-sched mode {m!r} (want off/on/auto)")
        if "off" not in sched_modes:
            sched_modes = ["off"] + sched_modes  # the exactness anchor
        if args.cross_pod or args.table or args.ll or wire_dtypes:
            ap.error("--skew is its own sweep mode; drop "
                     "--cross-pod/--table/--ll/--wire-dtype (--fp8 and a "
                     "single --chunks depth DO compose with it)")
        if len(chunk_list) != 1 or chunk_list[0] < 1:
            ap.error("--skew takes a single --chunks depth >= 1 (the "
                     "sweep axis is alpha x mode, not chunk depth)")
    else:
        alphas = sched_modes = None

    jax = init_devices(args.devices)
    n = len(jax.devices())

    if alphas is not None:
        bench_skew_sweep(
            jax, tokens=args.tokens, hidden=args.hidden,
            experts=args.experts, topk=args.topk, iters=args.iters,
            alphas=alphas, modes=sched_modes, fp8=args.fp8,
            n_chunks=chunk_list[0],
        )
        return

    if wire_dtypes:
        bench_quant_sweep(
            jax, tokens=args.tokens, hidden=args.hidden,
            experts=args.experts, topk=args.topk, iters=args.iters,
            mode="ll" if args.ll else "normal", wire=args.wire,
            wire_dtypes=wire_dtypes,
        )
        return

    if args.cross_pod:
        out = bench_cross_pod(
            args.tokens, args.hidden, args.ffn, args.experts, args.topk,
            args.iters, n_chunks=chunk_list[0],
        )
        for p, (fwd_us, comp_us) in sorted(out.items()):
            print(
                f"cross-pod pod {p}: forward {fwd_us:.0f} us "
                f"(compute-only {comp_us:.0f} us, comm+host share "
                f"{max(0.0, 1 - comp_us / max(fwd_us, 1e-9)) * 100:.0f}%) "
                f"tokens={args.tokens} hidden={args.hidden} "
                f"E={args.experts} k={args.topk}"
            )
        return

    if args.table:
        print(f"EP latency table ({n} members, tokens={args.tokens}, "
              f"hidden={args.hidden}, topk={args.topk})")
        print(f"{'mode':>8} {'E':>4} {'fp8':>5} {'dispatch us':>12} "
              f"{'combine us':>11} {'GB/s':>8}")
        for experts in (8, 32):
            for mode in ("normal", "ll"):
                for fp8 in (False, True):
                    r = bench_config(
                        jax, tokens=args.tokens, hidden=args.hidden,
                        experts=experts, topk=args.topk, iters=args.iters,
                        mode=mode, fp8=fp8,
                    )
                    print(
                        f"{mode:>8} {r['experts']:>4} {str(fp8):>5} "
                        f"{r['dispatch_us']:>12.1f} {r['combine_us']:>11.1f} "
                        f"{r['gbps']:>8.3f}"
                    )
        return

    if chunk_list != [1]:
        if 1 not in chunk_list:
            chunk_list = [1] + chunk_list  # always anchor on the phased arm
        bench_chunk_sweep(
            jax, tokens=args.tokens, hidden=args.hidden, ffn=args.ffn,
            experts=args.experts, topk=args.topk, iters=args.iters,
            chunks=sorted(set(chunk_list)), fp8=args.fp8,
        )
        return

    mode = "ll" if args.ll else "normal"
    r = bench_config(
        jax, tokens=args.tokens, hidden=args.hidden, experts=args.experts,
        topk=args.topk, iters=args.iters, mode=mode, fp8=args.fp8,
        wire=args.wire,
    )
    print(
        f"EP{n} {mode}: tokens={r['tokens']} hidden={r['hidden']} "
        f"experts={r['experts']} topk={r['topk']} fp8={args.fp8} "
        f"wire={r['wire']}"
    )
    print(
        f"  dispatch {r['dispatch_us']:.1f} us | combine "
        f"{r['combine_us']:.1f} us | {r['gbps']:.3f} GB/s per member"
    )

    if args.compare_dense:
        import numpy as np
        from jax.sharding import PartitionSpec as P

        import jax.numpy as jnp

        from uccl_tpu.ep import Buffer
        from uccl_tpu.ep import ops as ep_ops
        from uccl_tpu.parallel.mesh import AXIS, MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(dp=n))
        experts = max(args.experts, n)
        experts -= experts % n
        buf = Buffer(mesh, AXIS.EP, num_experts=experts,
                     num_selected=args.topk)
        cap = buf.capacity(args.tokens)
        rng = np.random.default_rng(0)
        x = buf.device_put(
            rng.standard_normal((n, args.tokens, args.hidden)).astype(
                np.float32
            )
        )
        idx = buf.device_put(
            rng.integers(0, experts, (n, args.tokens, args.topk)).astype(
                np.int32
            )
        )
        wts = buf.device_put(
            np.full((n, args.tokens, args.topk), 1.0 / args.topk, np.float32)
        )

        def dense_f(xv, iv, wv):
            xv, iv, wv = xv[0], iv[0], wv[0]
            mask, weights, _ = ep_ops.masks_from_topk(iv, wv, experts, cap)
            xe = ep_ops.dispatch(xv, mask, "dp")
            return ep_ops.combine(xe, weights, "dp")[None]

        from uccl_tpu.utils.jaxcompat import shard_map

        dense_fn = jax.jit(
            shard_map(
                dense_f, mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
                out_specs=P("dp"), check_vma=False,
            )
        )
        iters = max(1, args.iters // 5)
        dt_dense = _time_fn(dense_fn, (x, idx, wts), iters)
        total = (r["dispatch_us"] + r["combine_us"]) / 1e6
        print(
            f"  dense-mask oracle: {dt_dense * 1e6:.0f} us "
            f"({mode} path speedup {dt_dense / total:.1f}x)"
        )




def bench_cross_pod(tokens, hidden, ffn, experts, topk, iters, n_chunks=1):
    """Cross-pod MoE forward latency over the DCN loopback (reference:
    proxy-served inter-node EP, ep/src/proxy.cpp:701): 2 pods, experts
    split across them, per-pod µs for the full dispatch+compute+combine
    forward plus a local-compute-only baseline to expose the comm share."""
    import threading

    import numpy as np

    from uccl_tpu.collective.hierarchical import DcnGroup
    from uccl_tpu.ep.cross_pod import CrossPodMoE
    from uccl_tpu.p2p.store import StoreClient, StoreServer
    from uccl_tpu.parallel.distributed import Session
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    import jax
    import jax.numpy as jnp

    P_pods = 2
    epp = experts // P_pods
    rng = np.random.default_rng(0)
    wg = (rng.standard_normal((experts, hidden, ffn)) * 0.2).astype(
        np.float32
    )
    wd = (rng.standard_normal((experts, ffn, hidden)) * 0.2).astype(
        np.float32
    )
    x = rng.standard_normal((P_pods, tokens, hidden)).astype(np.float32)
    logits = rng.standard_normal((P_pods, tokens, experts)).astype(np.float32)
    gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    ti = np.argsort(-gates, axis=-1)[..., :topk].astype(np.int32)
    tv = np.take_along_axis(gates, ti, -1)
    tv = (tv / tv.sum(-1, keepdims=True)).astype(np.float32)

    def expert_fn(buf, w):
        hmid = jnp.maximum(jnp.einsum("ech,ehf->ecf", buf, w["wg"]), 0.0)
        return jnp.einsum("ecf,efh->ech", hmid, w["wd"])

    server = StoreServer()
    out = {}
    errors = []

    def pod_main(p):
        try:
            client = StoreClient("127.0.0.1", server.port)
            sess = Session(rank=p, world=P_pods, store=client)
            dcn = DcnGroup(sess, n_paths=2, tag="epbench")
            mesh = make_mesh(MeshConfig(dp=1), jax.devices()[:1])
            # cf = P guarantees no drops (per-pod demand is <= T after the
            # per-(token,pod) dedup: cf*T*K/P >= T) at 1/E-th the buffer an
            # experts-scaled factor would allocate
            moe = CrossPodMoE(
                dcn, mesh, num_global_experts=experts, num_selected=topk,
                capacity_factor=float(P_pods), n_chunks=n_chunks,
            )
            w_local = {
                "fn": expert_fn,
                "wg": jnp.asarray(wg[p * epp:(p + 1) * epp]),
                "wd": jnp.asarray(wd[p * epp:(p + 1) * epp]),
            }
            fwd = lambda: moe.forward(
                x[p], ti[p], tv[p], w_local, save_for_backward=False
            )
            fwd()  # warmup + compile
            dcn.barrier()
            t0 = time.perf_counter()
            for _ in range(iters):
                fwd()
            dcn.barrier()
            fwd_us = (time.perf_counter() - t0) / iters * 1e6
            # local-only baseline: the same expert compute, no wire —
            # keyed at the chunk shape so it reuses forward's cached jit
            cap = moe._pod_capacity(tokens)
            cs = cap // moe.n_chunks
            fn = moe._local_compute(((P_pods * cs, hidden), topk), expert_fn)
            xs = jnp.zeros((P_pods * cs, hidden), jnp.float32)
            idx = jnp.zeros((P_pods * cs, topk), jnp.int32)
            wts = jnp.ones((P_pods * cs, topk), jnp.float32)
            warrs = {k: v for k, v in w_local.items() if k != "fn"}
            # Stagger the compute-only baselines (pod p measures in turn
            # while the others wait at barriers): on the 1-core sandbox a
            # concurrent baseline would include the peer's compute and
            # overstate the denominator; real pods compute on their own
            # chips, so the uncontended number is the honest one. One
            # baseline run covers one chunk; the full forward runs
            # n_chunks of them.
            comp_us = 0.0
            for turn in range(P_pods):
                dcn.barrier()
                if turn == p:
                    # per-call on BOTH sides of the fwd/compute ratio:
                    # fwd does host socket I/O and cannot use the slope
                    # harness, so the baseline must carry the same fixed
                    # per-dispatch cost for the ratio to cancel it
                    comp_us = (
                        _time_fn_percall(fn, (xs, idx, wts, warrs), iters)
                        * 1e6 * moe.n_chunks
                    )
            dcn.barrier()
            out[p] = (fwd_us, comp_us)
            dcn.close()
            client.close()
        except Exception as e:  # pragma: no cover
            import traceback

            errors.append((p, e, traceback.format_exc()))

    ts = [threading.Thread(target=pod_main, args=(p,), daemon=True)
          for p in range(P_pods)]
    [t.start() for t in ts]
    [t.join(timeout=600) for t in ts]
    hung = [i for i, t in enumerate(ts) if t.is_alive()]
    server.close()
    if errors:
        raise RuntimeError(errors[0][2])
    if hung:
        raise RuntimeError(f"pod threads hung past join timeout: {hung}")
    return out


if __name__ == "__main__":
    main()
