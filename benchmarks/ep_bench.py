"""EP dispatch+combine benchmark — the test_low_latency.py analog.

Prints per-member dispatch+combine latency and bandwidth for the DeepEP-shaped
Buffer (reference metric definition: ep/bench/test_low_latency.py:438-464 —
per-rank dispatch/combine GB/s and µs).

Usage: python benchmarks/ep_bench.py [--devices N] [--tokens T] [--hidden H]
"""

from __future__ import annotations

import argparse
import time

from _bootstrap import init_devices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0)
    ap.add_argument("--tokens", type=int, default=512)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--experts", type=int, default=32)
    ap.add_argument("--topk", type=int, default=4)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--fp8", action="store_true")
    ap.add_argument(
        "--compare-dense", action="store_true",
        help="also time the dense [T,E,C] mask-einsum oracle path (the pre-"
             "round-2 formulation) and print the sorted-path speedup",
    )
    args = ap.parse_args()

    jax = init_devices(args.devices)

    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.ep import Buffer
    from uccl_tpu.parallel.mesh import AXIS, MeshConfig, make_mesh

    n = len(jax.devices())
    mesh = make_mesh(MeshConfig(dp=n))
    experts = max(args.experts, n)
    experts -= experts % n
    buf = Buffer(mesh, AXIS.EP, num_experts=experts, num_selected=args.topk)

    rng = np.random.default_rng(0)
    x = buf.device_put(
        rng.standard_normal((n, args.tokens, args.hidden)).astype(np.float32)
    )
    idx = buf.device_put(
        rng.integers(0, experts, (n, args.tokens, args.topk)).astype(np.int32)
    )
    wts = buf.device_put(
        np.full((n, args.tokens, args.topk), 1.0 / args.topk, np.float32)
    )

    def roundtrip():
        recv, handle = (
            buf.low_latency_dispatch(x, idx, wts)
            if args.fp8
            else buf.dispatch(x, idx, wts)
        )
        out = (
            buf.low_latency_combine(recv, handle)
            if args.fp8
            else buf.combine(recv, handle)
        )
        return out

    out = roundtrip()  # compile + warmup
    np.asarray(out)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = roundtrip()
    np.asarray(out)
    dt = (time.perf_counter() - t0) / args.iters

    if args.compare_dense:
        from jax.sharding import PartitionSpec as P

        from uccl_tpu.ep import ops as ep_ops

        cap = buf.capacity(args.tokens)

        # Fair comparison: same precomputed idx/wts as the sorted timing
        # (no routing math on either side)
        def dense_f(xv, iv, wv):
            xv, iv, wv = xv[0], iv[0], wv[0]
            mask, weights, _ = ep_ops.masks_from_topk(iv, wv, experts, cap)
            xe = ep_ops.dispatch(xv, mask, "dp")
            return ep_ops.combine(xe, weights, "dp")[None]

        import jax as _jax

        dense_fn = _jax.jit(
            _jax.shard_map(
                dense_f, mesh=mesh, in_specs=(P("dp"), P("dp"), P("dp")),
                out_specs=P("dp"), check_vma=False,
            )
        )
        np.asarray(dense_fn(x, idx, wts))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(max(1, args.iters // 5)):
            o = dense_fn(x, idx, wts)
        np.asarray(o)
        dt_dense = (time.perf_counter() - t0) / max(1, args.iters // 5)
        print(
            f"  dense-mask oracle: {dt_dense * 1e6:.0f} us "
            f"(sorted path speedup {dt_dense / dt:.1f}x)"
        )

    per_member_bytes = args.tokens * args.hidden * 4 * args.topk  # moved payload
    print(
        f"EP{n} dispatch+combine: tokens={args.tokens} hidden={args.hidden} "
        f"experts={experts} topk={args.topk} fp8={args.fp8}"
    )
    print(
        f"  avg {dt * 1e6:.1f} us | {per_member_bytes / dt / 1e9:.3f} GB/s per member"
    )


if __name__ == "__main__":
    main()
