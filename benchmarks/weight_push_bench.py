"""Time-to-consistent-fleet sweep for the versioned weight-push service.

The fleet question (ISSUE 14): N replicas need the same published weight
version — how long until EVERY peer holds a verified, bit-exact copy?
Two push shapes per (N, wire) point, in-process peers (each subscriber
owns its own Endpoint; the native engine threads move the bytes):

* ``naive``  — N point-to-point copies out of the root, one per peer
  (the root's egress serialized: the spin-up shape this service
  replaces). Time-to-consistent-fleet grows ~linearly in N.
* ``relay``  — ONE pipelined chain root → s1 → ... → sN: every node
  fetches from its upstream and forwards each verified slab group
  downstream while later groups are still in flight
  (``weight_push.fetch(forward_to=...)``). The root ships each chunk
  once — counter-audited as ``weight_push_bytes_total{role="tx",
  src="publisher"}`` staying ONE snapshot — and fleet time approaches
  one snapshot time plus (N-1) group times: sublinear in N.

Every arm verifies every peer's tree bit-exact against the published
version (CRC-gated on the wire, then an explicit array_equal here) and
is labeled from REAL counter deltas, never assumed arithmetic. One JSON
line per arm; ``--json-out`` records them (docs/weight_push_r01.json),
``--metrics-out`` dumps the Prometheus snapshot for
``scripts/check_obs.py --weights``.
"""

from __future__ import annotations

import argparse
import json
import threading
import time

import numpy as np

from _bootstrap import init_devices  # noqa: F401  (repo path side effect)

from uccl_tpu import obs
from uccl_tpu.p2p import Channel, Endpoint, WeightPublisher
from uccl_tpu.p2p import weight_push as wp


def chan_pair(server_ep, client_ep, n_paths=2):
    """(server-side, client-side) channel between two in-process
    endpoints."""
    res = {}
    t = threading.Thread(
        target=lambda: res.setdefault("c", Channel.accept(server_ep)))
    t.start()
    c = Channel.connect(client_ep, "127.0.0.1", server_ep.port,
                        n_paths=n_paths)
    t.join(timeout=20)
    if "c" not in res:
        raise TimeoutError("channel accept timed out")
    return res["c"], c


def _push_snapshot():
    fam = obs.counter("weight_push_bytes_total")
    return {tuple(sorted(lb.items())): v for lb, v in fam.samples()}


def _delta(before, **labels):
    want = set(labels.items())
    out = 0.0
    for k, v in _push_snapshot().items():
        if want <= set(k):
            out += v - before.get(k, 0)
    return out


def run_arm(n: int, mode: str, wire, tree, canon, group_kb: int,
            timeout_ms: int, nic_bps: int = 0) -> dict:
    pub = WeightPublisher(group_bytes=group_kb << 10)
    version = pub.publish("fleet", tree, wire=wire)
    snap = pub.get("fleet", version)
    eps = [Endpoint(n_engines=2) for _ in range(n + 1)]  # [root, s1..sN]
    if nic_bps:
        # model per-NIC egress (the resource the relay actually relieves):
        # every endpoint's tx rides its own token-bucket pacer, so the
        # naive root serializes N copies through ONE pacer while the
        # relay's hops ride N distinct ones concurrently — the loopback
        # stand-in for a NIC-bound fleet (in-process un-paced endpoints
        # share one host's memory bandwidth, which hides the difference)
        for ep in eps:
            ep.set_rate_limit(nic_bps)
    peers_before = obs.counter("weight_push_peers_total").get(name="fleet")
    bytes_before = _push_snapshot()
    snaps = [None] * n
    errs = []
    try:
        if mode == "relay":
            # chain root -> s1 -> ... -> sN; node i forwards to i+1
            ups, downs = [], []
            for i in range(n):
                up_srv, up_cli = chan_pair(eps[i], eps[i + 1])
                ups.append(up_cli)
                downs.append(up_srv)  # node i's downstream-serving side
            # downs[i] is served BY node i-1 (or the root for i=0): node
            # i fetches on ups[i] and forwards on downs[i+1]
            def node(i):
                try:
                    fwd = [downs[i + 1]] if i + 1 < n else []
                    snaps[i] = wp.fetch(ups[i], "fleet", forward_to=fwd,
                                        timeout_ms=timeout_ms)
                except BaseException as e:
                    errs.append(e)

            ts = [threading.Thread(target=node, args=(i,))
                  for i in range(n)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            pub.serve(downs[0], timeout_ms=timeout_ms)
            for t in ts:
                t.join(timeout=timeout_ms / 1e3)
            t_fleet = time.perf_counter() - t0
        else:  # naive: N sequential point-to-point copies out of the root
            pairs = [chan_pair(eps[0], eps[i + 1]) for i in range(n)]
            t0 = time.perf_counter()
            for i, (srv, cli) in enumerate(pairs):

                def one(i=i, cli=cli):
                    try:
                        snaps[i] = wp.fetch(cli, "fleet",
                                            timeout_ms=timeout_ms)
                    except BaseException as e:
                        errs.append(e)

                t = threading.Thread(target=one)
                t.start()
                pub.serve(srv, timeout_ms=timeout_ms)
                t.join(timeout=timeout_ms / 1e3)
            t_fleet = time.perf_counter() - t0
        if errs:
            raise errs[0]
        bitexact = all(
            s is not None and all(
                np.array_equal(s.flat()[k], canon[k]) for k in canon)
            for s in snaps
        )
        root_tx = _delta(bytes_before, role="tx", src="publisher")
        fleet_tx = _delta(bytes_before, role="tx")
        peers = obs.counter("weight_push_peers_total").get(
            name="fleet") - peers_before
        return {
            "bench": "weight_push",
            "schema_version": obs.SCHEMA_VERSION,
            "n_peers": n, "mode": mode, "wire_dtype": wire or "none",
            "snapshot_bytes": snap.total_bytes,
            "groups": len(snap.manifest["groups"]),
            "nic_mbps": nic_bps / 1e6 if nic_bps else None,
            "t_fleet_s": round(t_fleet, 4),
            "fleet_mb_s": round(
                n * snap.total_bytes / t_fleet / 1e6, 2),
            "root_tx_bytes": int(root_tx),
            "fleet_tx_bytes": int(fleet_tx),
            "peers_consistent": int(peers),
            "bitexact": bool(bitexact),
        }
    finally:
        for ep in eps:
            ep.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fleet", default="2,4,8",
                    help="comma list of peer counts N to sweep")
    ap.add_argument("--mb", type=float, default=8.0,
                    help="approximate snapshot megabytes")
    ap.add_argument("--wire", default="none",
                    help="comma list of wire codecs: none,fp8,lossless")
    ap.add_argument("--modes", default="relay,naive")
    ap.add_argument("--group-kb", type=int, default=512,
                    help="slab-group (pipeline tick) size in KiB")
    ap.add_argument("--nic-mbps", type=float, default=100.0,
                    help="per-endpoint egress pacing in MB/s (0 = off): "
                    "the NIC-bound fleet model — without it the "
                    "in-process peers share one host's memory bandwidth "
                    "and both modes converge on it")
    ap.add_argument("--timeout-ms", type=int, default=120000)
    ap.add_argument("--smoke", action="store_true",
                    help="CI arm: N=3, ~2 MB, relay+naive, exits nonzero "
                    "unless every peer lands bit-exact and the relay's "
                    "root egress stayed one snapshot")
    ap.add_argument("--json-out", default="")
    obs.add_cli_args(ap)
    args = ap.parse_args()
    obs.setup_from_args(args)

    if args.smoke:
        fleets, modes, wires, mb = [3], ["relay", "naive"], [None], 2.0
    else:
        fleets = [int(v) for v in args.fleet.split(",") if v]
        modes = [m for m in args.modes.split(",") if m]
        wires = [None if w in ("none", "") else w
                 for w in args.wire.split(",")]
        mb = args.mb

    # a dense-model-shaped tree: a few big matrices + small vectors
    rng = np.random.default_rng(0)
    dim = max(64, int((mb * 1e6 / 6 / 4) ** 0.5))
    tree = {}
    for i in range(6):
        tree[f"layer{i}.w"] = rng.standard_normal(
            (dim, dim)).astype(np.float32)
        tree[f"layer{i}.b"] = rng.standard_normal(dim).astype(np.float32)

    lines = []
    failed = 0
    for wire in wires:
        canon_pub = WeightPublisher()
        canon_pub.publish("fleet", tree, wire=wire)
        canon = canon_pub.get("fleet").flat()
        for n in fleets:
            for mode in modes:
                rec = run_arm(n, mode, wire, tree, canon, args.group_kb,
                              args.timeout_ms,
                              nic_bps=int(args.nic_mbps * 1e6))
                print(json.dumps(rec), flush=True)
                lines.append(rec)
                if not rec["bitexact"] or rec["peers_consistent"] != n:
                    failed = 1
    if args.smoke:
        relay = next(r for r in lines if r["mode"] == "relay")
        if relay["root_tx_bytes"] != relay["snapshot_bytes"]:
            print("weight_push_bench: SMOKE FAILED — relay root egress "
                  f"{relay['root_tx_bytes']} != one snapshot "
                  f"{relay['snapshot_bytes']}", flush=True)
            failed = 1
    if args.json_out:
        with open(args.json_out, "w") as f:
            for rec in lines:
                f.write(json.dumps(rec) + "\n")
    obs.dump_from_args(args)
    if failed:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
