"""P2P transfer-engine bandwidth over TCP loopback (2 local ranks).

The analog of the reference's p2p/benchmarks (and the driver config "p2p
send/recv over TCP loopback"). Prints one JSON line per message size.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from uccl_tpu.p2p import Endpoint  # noqa: E402


def run(sizes=(4 << 10, 64 << 10, 1 << 20, 16 << 20, 64 << 20), iters=20,
        paths=(1, 4), quiet=False):
    import threading

    from uccl_tpu.p2p import Channel

    results = []
    for n_paths in paths:
        with Endpoint(n_engines=max(2, n_paths)) as server, Endpoint(
            n_engines=max(2, n_paths)
        ) as client:
            acc = {}
            t = threading.Thread(
                target=lambda: acc.setdefault("c", Channel.accept(server))
            )
            t.start()
            chan = Channel.connect(client, "127.0.0.1", server.port, n_paths=n_paths)
            t.join()
            for size in sizes:
                dst = np.zeros(size, np.uint8)
                fifo = server.advertise(server.reg(dst))
                src = np.random.default_rng(0).integers(0, 255, size).astype(np.uint8)
                chan.write(src, fifo)  # warmup
                t0 = time.perf_counter()
                for _ in range(iters):
                    chan.write(src, fifo)
                dt = (time.perf_counter() - t0) / iters
                gbps = size / dt / 1e9
                results.append(
                    {
                        "size": size,
                        "paths": n_paths,
                        "GB/s": round(gbps, 3),
                        "lat_us": round(dt * 1e6, 1),
                    }
                )
                if not quiet:
                    print(json.dumps(results[-1]))
    return results


if __name__ == "__main__":
    run()
