"""Chaos harness: kill replicas mid-run, drop control notifs, prove recovery.

The fault-tolerance acceptance bench (docs/SERVING.md): every resilience
claim the serving fleet makes is asserted here against REAL failures, with
the evidence landing on real counters —

* **router arm** — N dense replicas behind a health-enabled ``Router``
  under a Poisson stream. One replica is ``kill()``ed mid-run at a chosen
  point (``--kill-at prefill`` waits until the victim holds BOTH a
  mid-prefill and a mid-decode request; ``decode`` waits for decode-only
  work), the failure detector walks it HEALTHY→SUSPECT→DEAD, and router
  recovery resubmits/restarts its requests on the survivor. Asserted:
  every completed request **bit-exact** vs the one-shot ``generate``
  oracle, each accepted trace_id completes at most once (exactly-once),
  the extended conservation invariant ``submitted == completed + active +
  queued + rejected + expired + lost`` across the fleet, ``leaked() == 0``
  on all survivors, ``serving_recovered_total`` deltas equal to the
  evacuated request count, and a **bounded goodput dip** vs an unfaulted
  twin run of the same workload (reported, gated by
  ``--min-goodput-frac``).

* **disagg arm** — an in-process prefill/decode pair over the windowed
  SACK channel transport with BOTH fault planes injected: the native
  data-plane injector (``Endpoint.set_drop_rate`` — KV slab frames,
  recovered by PR 13 selective repeat) and the control-plane injector
  (``disagg.set_ctrl_drop`` — BEGIN/GRANT/FINAL/ack notifs; the native
  injector deliberately never faults notifs, so control loss is injected
  at the send site with a seeded RNG). The retried, rid-idempotent
  control plane must converge: every request completes **bit-exact**
  under loss, retries counted on ``disagg_ctrl_retries_total``. Then the
  **post-GRANT kill**: a request's prefill worker dies after GRANT and
  before FINAL — the decode side's lease expires, the reserved slot is
  reclaimed (``disagg_leases_expired_total``), and the decode pool ends
  with ``leaked() == 0``.

``--smoke`` runs both arms at CI sizes (1 killed replica out of 2, 5%
control drop) and the combined fleet conservation snapshot is dumped via
``--metrics-out`` for ``scripts/check_obs.py --chaos`` to audit. Each arm
also emits one JSON line (``--json-out``) labeled off counter deltas.

With ``--flight-dir`` the run doubles as the **flight-recorder
acceptance arm** (``scripts/check_obs.py --flight``): the SACK and
control-plane storm thresholds are armed, and every injected fault class
must land EXACTLY ONE attributable post-mortem bundle — the router kill
and the post-GRANT kill each a ``peer_dead``, the control-notif drops a
``ctrl_storm``, the data-plane drops a ``retx_storm``, and a deliberately
tight SLO objective evaluated over the faulted window a ``slo_burn``.
A clean phase then re-runs an unfaulted drive with the SAME thresholds
armed into a fresh recorder (``<flight-dir>/clean``) and must produce
zero bundles and zero burn alerts.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from _bootstrap import init_devices


def _counters(*specs):
    """Cumulative counter reads: labels=None sums the whole family."""
    from uccl_tpu import obs

    return [obs.counter(name).total() if labels is None
            else obs.counter(name).get(**labels)
            for name, labels in specs]


_ROUTER_COUNTERS = (
    ("serving_recovered_total", {"outcome": "resubmitted"}),
    ("serving_recovered_total", {"outcome": "restarted"}),
    ("serving_recovered_total", {"outcome": "lost"}),
    ("fleet_heartbeats_total", None),
)
_DISAGG_COUNTERS = (
    ("disagg_ctrl_retries_total", {"msg": "begin"}),
    ("disagg_ctrl_retries_total", {"msg": "grant"}),
    ("disagg_ctrl_retries_total", {"msg": "final"}),
    ("disagg_ctrl_dropped_total", None),
)


def _make_dense(args, jax, n_slots, max_seq, n):
    from uccl_tpu.models.dense import DenseConfig, init_params
    from uccl_tpu.serving.engine import DenseBackend, replicate_backend

    cfg = DenseConfig(
        vocab=args.vocab, dim=args.dim, n_layers=args.layers,
        n_heads=4, n_kv_heads=2, head_dim=args.dim // 4,
        ffn=args.dim * 2,
    )
    params = init_params(jax.random.PRNGKey(args.seed), cfg)
    proto = DenseBackend(params, cfg, n_slots=n_slots, max_seq=max_seq)
    return replicate_backend(proto, n), params, cfg


def _oracle_fn(params, cfg, max_seq):
    import jax.numpy as jnp

    from uccl_tpu.models.inference import generate

    def oracle(req):
        toks = generate(params, jnp.asarray(req.prompt)[None], cfg,
                        max_new_tokens=req.max_new_tokens,
                        max_seq=max_seq)
        return np.asarray(toks)[0, :req.n_generated]

    return oracle


def _check_oracle(reqs, oracle) -> int:
    """Every FINISHED request's tokens vs the unfaulted one-shot oracle;
    returns the number checked, raises on the first mismatch."""
    checked = 0
    for r in reqs:
        want = oracle(r)
        got = np.asarray(r.out_tokens, np.int32)
        if got.shape != want.shape or not np.array_equal(got, want):
            raise SystemExit(
                f"ORACLE MISMATCH rid={r.rid} trace={r.trace_id}: "
                f"got {got.tolist()} want {want.tolist()}"
            )
        checked += 1
    return checked


def _drive_with_kill(router, engines, victim, prompts, arrivals,
                     new_tokens, kill_at, timeout_s=300.0):
    """The faulted drive loop: submit per arrivals, step the router, and
    kill the victim engine once the trigger condition holds (victim has
    mid-prefill + mid-decode work for ``prefill``, decode-only for
    ``decode``; ``off`` never kills — the baseline twin). Returns
    (accepted, finished, wall_s, t_killed)."""
    from uccl_tpu.serving.request import now

    accepted, finished = [], []
    killed_t = None
    i, n = 0, len(prompts)
    t0 = now()
    deadline = time.monotonic() + timeout_s
    while i < n or router.has_work():
        t = now() - t0
        while i < n and arrivals[i] <= t:
            r = router.submit(prompts[i], max_new_tokens=new_tokens)
            if r is not None:
                accepted.append(r)
            i += 1
        if router.has_work():
            finished.extend(router.step())
        if kill_at != "off" and killed_t is None:
            eng = engines[victim]
            decoding = (len(eng._by_slot) - len(eng._prefilling)) > 0
            trigger = ((kill_at == "prefill" and eng._prefilling)
                       or (kill_at == "decode" and decoding)
                       # stream fully offered and never triggered: kill
                       # while the victim still holds ANY work so the
                       # arm always tests recovery
                       or (i >= n and eng.has_work()))
            if trigger and not eng.dead:
                eng.kill()
                killed_t = now() - t0
        if time.monotonic() > deadline:
            raise SystemExit(
                f"chaos drive stalled: {len(finished)}/{len(accepted)} "
                f"finished, recoveries={router.recoveries}"
            )
    return accepted, finished, now() - t0, killed_t


def run_router_arm(args, jax, kill_at):
    from uccl_tpu import obs
    from uccl_tpu.serving import Router, ServingEngine
    from uccl_tpu.serving.loadgen import synth_workload, warm_replicas

    max_seq = args.prompt_len + args.new_tokens
    rng = np.random.default_rng(args.seed)
    prompts, lens, arrivals = synth_workload(
        rng, args.requests, args.prompt_len, args.vocab, args.rate
    )

    def build():
        backends, params, cfg = _make_dense(
            args, jax, args.slots, max_seq, args.replicas
        )
        engines = [ServingEngine(b, prefill_chunk=args.prefill_chunk,
                                 max_queue=args.max_queue)
                   for b in backends]
        router = Router(engines)
        router.enable_health(suspect_after_s=args.suspect_s,
                             dead_after_s=args.dead_s)
        warm_replicas(router, lens, max_seq, args.new_tokens)
        return router, engines, params, cfg

    # unfaulted twin first: same workload, same replica count — the
    # goodput baseline the dip is measured against
    router0, engines0, params, cfg = build()
    acc0, fin0, wall0, _ = _drive_with_kill(
        router0, engines0, 0, prompts, arrivals, args.new_tokens, "off"
    )
    snap0 = router0.snapshot()
    base_goodput = snap0.get("goodput_tok_s", 0.0)
    router0.close()

    c0 = _counters(*_ROUTER_COUNTERS)
    router, engines, params, cfg = build()
    victim = 0
    acc, fin, wall, killed_t = _drive_with_kill(
        router, engines, victim, prompts, arrivals, args.new_tokens,
        kill_at,
    )
    snap = router.snapshot()
    deltas = dict(zip(("resubmitted", "restarted", "lost", "heartbeats"),
                      (a - b for a, b in
                       zip(_counters(*_ROUTER_COUNTERS), c0))))

    # -- the chaos assertions (each a named SystemExit on violation) ----
    oracle = _oracle_fn(params, cfg, max_seq)
    checked = _check_oracle(fin, oracle)
    lost_traces = {r["trace_id"] for r in router.recoveries
                   if r["outcome"] == "lost"}
    done_traces = [r.trace_id for r in fin]
    if len(done_traces) != len(set(done_traces)):
        raise SystemExit("EXACTLY-ONCE VIOLATED: a trace_id completed "
                         "more than once across the fleet")
    want_traces = {r.trace_id for r in acc}
    if set(done_traces) | lost_traces != want_traces:
        raise SystemExit(
            f"CONSERVATION VIOLATED: accepted {len(want_traces)} traces, "
            f"completed {len(set(done_traces))} + lost "
            f"{len(lost_traces)} do not cover them"
        )
    if snap["submitted"] != (snap["completed"] + snap["active"]
                             + snap["queued"] + snap["rejected"]
                             + snap["expired"] + snap["lost"]):
        raise SystemExit(f"INVARIANT VIOLATED: {snap}")
    if router.leaked() != 0:
        raise SystemExit(f"LEAKED SLOTS: {router.leaked()}")
    n_rec = deltas["resubmitted"] + deltas["restarted"] + deltas["lost"]
    if len(router.recoveries) != n_rec:
        raise SystemExit(
            f"recovery log ({len(router.recoveries)}) != counter delta "
            f"({n_rec}) — recoveries are not counter-audited"
        )
    if kill_at != "off" and n_rec < 1:
        raise SystemExit("kill arm recovered nothing — the chaos never "
                         "bit")
    goodput = snap.get("goodput_tok_s", 0.0)
    frac = (goodput / base_goodput) if base_goodput else 1.0
    # bounded dip: the faulted run may pay (a) the configured detection
    # window (suspect grace + dead threshold — dead work sits still
    # until the detector fires) plus (b) re-running recovered work on
    # the surviving capacity (≤ dip-wall-factor × the unfaulted wall)
    # plus scheduling slack. Anything beyond that budget is an
    # UNEXPLAINED stall — a wedged retry loop, not a bounded dip.
    budget = (wall0 * args.dip_wall_factor + args.dead_s
              + args.dip_slack_s)
    if wall > budget:
        raise SystemExit(
            f"GOODPUT DIP UNBOUNDED: faulted wall {wall:.3f}s exceeds "
            f"the explained budget {budget:.3f}s (= unfaulted "
            f"{wall0:.3f}s x {args.dip_wall_factor} + detection "
            f"{args.dead_s}s + slack {args.dip_slack_s}s); goodput "
            f"{goodput:.1f} vs {base_goodput:.1f} tok/s"
        )
    arm = {
        "bench": "chaos_router", "kill_at": kill_at,
        "replicas": args.replicas, "requests": args.requests,
        "accepted": len(acc), "completed": len(fin),
        "oracle_checked": checked, "oracle_exact": True,
        "killed_at_s": round(killed_t, 3) if killed_t else None,
        "recovered": deltas, "lost": snap["lost"],
        "leaked": router.leaked(),
        "goodput_tok_s": goodput, "goodput_unfaulted_tok_s": base_goodput,
        "goodput_frac": round(frac, 3),
        "wall_s": round(wall, 3), "wall_unfaulted_s": round(wall0, 3),
        "conservation_ok": True,
    }
    metrics = [m for m in ([e.metrics for e in router.engines])]
    router.close()
    obs.gauge("serving_leaked_slots",
              "live-occupied slots left after a chaos arm drained "
              "(must be 0)").set(0 if router.leaked() == 0 else
                                 router.leaked(), component="router")
    print(json.dumps(arm), flush=True)
    return arm, metrics


def run_disagg_arm(args, jax):
    from uccl_tpu import obs
    from uccl_tpu.serving import FailureDetector, ServingEngine
    from uccl_tpu.serving import health as health_mod
    from uccl_tpu.serving.disagg import (
        make_local_pair, set_ctrl_drop, warm_pair,
    )
    from uccl_tpu.serving.loadgen import synth_workload

    max_seq = args.prompt_len + args.new_tokens
    backends, params, cfg = _make_dense(args, jax, args.slots, max_seq, 2)
    pe = ServingEngine(backends[0], prefill_chunk=args.prefill_chunk)
    de = ServingEngine(backends[1])
    detector = FailureDetector(suspect_after_s=args.suspect_s,
                               dead_after_s=args.dead_s)
    pw, dw = make_local_pair(
        pe, de, transport="channel",
        grant_lease_s=args.lease_s, detector=detector,
        heartbeat_s=args.suspect_s / 4, ctrl_retry_s=args.ctrl_retry_s,
    )
    try:
        warm_pair(pw, dw, args.prompt_len, args.new_tokens)
        rng = np.random.default_rng(args.seed + 1)
        prompts, lens, arrivals = synth_workload(
            rng, args.requests, args.prompt_len, args.vocab, args.rate
        )
        c0 = _counters(*_DISAGG_COUNTERS)
        # both fault planes on: native data-plane drop (KV slab frames,
        # recovered by the SACK window) + control-notif drop (recovered
        # by the idempotent retry plane)
        pw.ep.set_drop_rate(args.data_drop)
        set_ctrl_drop(args.ctrl_drop, seed=args.seed)
        finished = []
        i, accepted = 0, 0
        t_start = time.monotonic()
        deadline = t_start + 600.0
        while i < len(prompts) or not pw.idle() \
                or len(finished) < accepted:
            t = time.monotonic() - t_start
            while i < len(prompts) and arrivals[i] <= t:
                if pw.submit(prompts[i],
                             max_new_tokens=args.new_tokens) is not None:
                    accepted += 1
                i += 1
            pw.step()
            finished.extend(dw.step())
            if time.monotonic() > deadline:
                raise SystemExit(
                    f"disagg chaos stalled: {len(finished)}/{accepted}, "
                    f"outstanding={pw.outstanding()}"
                )
        set_ctrl_drop(0.0)
        pw.ep.set_drop_rate(0.0)
        oracle = _oracle_fn(params, cfg, max_seq)
        checked = _check_oracle(finished, oracle)

        # -- post-GRANT kill: lease reclaims the reserved decode slot --
        # (reason may be "timeout" or "peer_dead": the detector's missed
        # heartbeats can win the race against the lease clock — either
        # way the slot comes back, so the audit sums the family)
        lease0 = obs.counter("disagg_leases_expired_total").total()
        doomed = pw.submit(np.asarray(prompts[0], np.int32),
                           max_new_tokens=args.new_tokens)
        grant_deadline = time.monotonic() + 30.0
        while not dw._granted:
            pw.pump()  # BEGIN out, GRANT back — the engine never steps
            dw.poll()
            if time.monotonic() > grant_deadline:
                raise SystemExit("post-GRANT arm never saw the GRANT")
        # the prefill process "dies": its engine is killed, its stranded
        # requests counted lost, and it never pumps again — no FINAL
        # will ever arrive for the granted stream
        pe.kill()
        health_mod.abandon_engine(pe)
        reclaim_deadline = time.monotonic() + 30.0
        while dw._granted:
            dw.poll()
            time.sleep(0.005)
            if time.monotonic() > reclaim_deadline:
                raise SystemExit(
                    f"LEASE NEVER EXPIRED: granted={sorted(dw._granted)}"
                )
        expired = obs.counter("disagg_leases_expired_total").total() \
            - lease0
        if expired < 1:
            raise SystemExit("post-GRANT kill reclaimed no lease")
        if dw.engine.pool.leaked() != 0:
            raise SystemExit(
                f"DECODE LEAKED {dw.engine.pool.leaked()} slot(s) after "
                f"lease reclaim"
            )
        if dw.engine.pool.n_free != dw.engine.pool.n_slots:
            raise SystemExit("reclaimed slot did not return to the pool")
        from uccl_tpu.obs import flight as flight_mod
        n_dead = 0
        if flight_mod.enabled():
            # flight acceptance: the lease clock may have won the
            # reclaim race above, but the peer_dead POST-MORTEM needs
            # the detector transition — keep ticking until every conn
            # the silent prefill side fed is DEAD (both directions go
            # silent together: pe is killed and pw never pumps again),
            # so the bundle count per arm is deterministic
            dead_deadline = time.monotonic() + 30.0
            while any(detector.state(p) != "dead"
                      for p in detector.peers()):
                dw.poll()
                time.sleep(0.005)
                if time.monotonic() > dead_deadline:
                    raise SystemExit(
                        "flight arm: detector never declared the killed "
                        "prefill peer DEAD"
                    )
            n_dead = len(detector.peers())
        deltas = dict(zip(
            ("retry_begin", "retry_grant", "retry_final", "ctrl_dropped"),
            (a - b for a, b in zip(_counters(*_DISAGG_COUNTERS), c0)),
        ))
        obs.gauge("serving_leaked_slots").set(
            dw.engine.pool.leaked(), component="decode")
        obs.gauge("serving_leaked_slots").set(
            pe.pool.leaked(), component="prefill")
        arm = {
            "bench": "chaos_disagg", "requests": args.requests,
            "ctrl_drop": args.ctrl_drop, "data_drop": args.data_drop,
            "completed": len(finished), "oracle_checked": checked,
            "oracle_exact": True, "leases_expired": int(expired),
            "decode_leaked": dw.engine.pool.leaked(),
            "conservation_ok": True, "recovered": deltas,
            "flight_peer_dead": n_dead,
        }
        print(json.dumps(arm), flush=True)
        _ = doomed
        return arm, [pe.metrics, de.metrics]
    finally:
        set_ctrl_drop(0.0)
        try:
            dw.close()
        except Exception:
            pass
        pw.ep.close()
        dw.ep.close()


def run_clean_phase(args, jax) -> int:
    """The zero-dump half of the flight acceptance claim: an unfaulted
    drive with the SAME storm thresholds armed (into a fresh recorder the
    caller just enabled) plus a lenient burn monitor over it. Returns the
    number of burn alerts fired (must be 0; the caller asserts the
    recorder stayed empty)."""
    from uccl_tpu.obs import slo as slo_mod
    from uccl_tpu.serving import ServingEngine

    clock = [0.0]
    mon = slo_mod.BurnRateMonitor(
        slo_mod.serving_objectives(ttft_s=120.0, tpot_s=120.0,
                                   queue_wait_s=120.0, step_s=120.0,
                                   target=0.99),
        windows=((60.0, 1.0),), clock=lambda: clock[0])
    mon.sample()
    backends, params, cfg = _make_dense(
        args, jax, args.slots, args.prompt_len + args.new_tokens, 1
    )
    eng = ServingEngine(backends[0], prefill_chunk=args.prefill_chunk)
    rng = np.random.default_rng(args.seed + 7)
    for _ in range(3):
        prompt = rng.integers(0, args.vocab,
                              args.prompt_len).astype(np.int32)
        eng.submit(prompt, max_new_tokens=args.new_tokens)
        eng.drain()
    eng.close()
    clock[0] = 61.0
    return len(mon.evaluate())


def run_flight_checks(args, jax, arms, slo_mon, slo_clock) -> dict:
    """After the faulted arms: fire the tight-SLO burn, assert every
    injected fault class landed EXACTLY ONE attributable bundle, then run
    the clean phase (same thresholds armed, fresh recorder) and assert
    zero dumps + zero burn alerts. Returns the ``chaos_flight`` JSON arm
    ``scripts/check_obs.py --flight`` re-audits against the bundles and
    the exported counters."""
    import os
    from collections import Counter

    from uccl_tpu import obs
    from uccl_tpu.obs import flight as flight_mod

    # space past the recorder's min_interval_s: the disagg arm's last
    # bundle just landed, and the slo_burn dump must not be rate-limited
    time.sleep(0.3)
    slo_clock[0] = 61.0
    burn_alerts = slo_mon.evaluate()
    if not burn_alerts:
        raise SystemExit("flight arm: the tight SLO objective fired no "
                         "burn alert over the faulted window")

    # expectations derived from the faults that actually bit — each is
    # asserted to have bitten, so the arm can never pass vacuously
    expected = Counter()
    for arm in arms:
        if arm.get("bench") == "chaos_router" and arm.get("killed_at_s"):
            expected["peer_dead"] += 1
        expected["peer_dead"] += arm.get("flight_peer_dead", 0)
    if obs.counter("disagg_ctrl_retries_total").total() < 1:
        raise SystemExit("flight arm: control-plane chaos never bit "
                         "(no ctrl retries)")
    if obs.counter("p2p_channel_retx_total").total() < 1:
        raise SystemExit("flight arm: data-plane chaos never bit "
                         "(no retransmits)")
    expected["ctrl_storm"] = 1
    expected["retx_storm"] = 1
    expected["slo_burn"] = 1

    rec = flight_mod.get_recorder()
    names = sorted(os.path.basename(p) for p in rec.bundles)
    kinds = Counter(n.split("_", 2)[2][:-len(".json")] for n in names)
    if dict(kinds) != dict(expected):
        raise SystemExit(
            f"FLIGHT ATTRIBUTION MISMATCH: bundles {dict(kinds)} vs "
            f"expected {dict(expected)} ({names})"
        )

    clean_dir = os.path.join(args.flight_dir, "clean")
    clean_rec = flight_mod.enable(clean_dir)
    clean_alerts = run_clean_phase(args, jax)
    if clean_rec.bundles or clean_alerts:
        raise SystemExit(
            f"CLEAN RUN NOT CLEAN: {len(clean_rec.bundles)} bundle(s), "
            f"{clean_alerts} burn alert(s) with no fault injected"
        )
    arm = {
        "bench": "chaos_flight", "flight_dir": args.flight_dir,
        "expected": dict(expected), "bundles": names,
        "burn_alerts": len(burn_alerts),
        "clean_dir": clean_dir, "clean_bundles": 0,
        "clean_burn_alerts": 0,
    }
    print(json.dumps(arm), flush=True)
    return arm


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--arm", default="router,disagg",
                    help="comma list: router,disagg")
    ap.add_argument("--smoke", action="store_true",
                    help="CI sizes: 2 replicas, 1 killed, 5%% ctrl drop")
    ap.add_argument("--devices", type=int, default=2)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--rate", type=float, default=50.0)
    ap.add_argument("--prompt-len", type=int, default=12)
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--prefill-chunk", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=None)
    ap.add_argument("--kill-at", default="prefill",
                    help="router-arm kill trigger: prefill|decode|off")
    ap.add_argument("--suspect-s", type=float, default=0.08,
                    help="detector suspect window (seconds)")
    ap.add_argument("--dead-s", type=float, default=0.25,
                    help="detector dead window (seconds)")
    ap.add_argument("--lease-s", type=float, default=1.0,
                    help="decode-side GRANT lease (seconds)")
    ap.add_argument("--ctrl-retry-s", type=float, default=0.1)
    ap.add_argument("--ctrl-drop", type=float, default=0.05,
                    help="control-notif drop rate (Python injector)")
    ap.add_argument("--data-drop", type=float, default=0.05,
                    help="native data-plane frame drop rate")
    ap.add_argument("--dip-wall-factor", type=float, default=3.0,
                    help="bounded-dip gate: recovered work may cost up "
                    "to this many unfaulted walls of recompute on the "
                    "surviving capacity")
    ap.add_argument("--dip-slack-s", type=float, default=1.0,
                    help="bounded-dip gate: fixed scheduling slack on "
                    "top of the detection window")
    ap.add_argument("--json-out", default="")
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    from uccl_tpu import obs

    obs.add_cli_args(ap)
    args = ap.parse_args()
    obs.setup_from_args(args)
    flight_on = bool(getattr(args, "flight_dir", ""))
    slo_mon, slo_clock = None, [0.0]
    if flight_on:
        from uccl_tpu.obs import slo as slo_mod
        from uccl_tpu.p2p import sack as sack_mod
        from uccl_tpu.serving import disagg as disagg_mod

        # one retransmit / one control retry proves the trigger path
        # end-to-end at smoke sizes (a deployment arms its real loss
        # budget); the seeded drop injectors make the bite deterministic
        # and run_flight_checks asserts each fault class actually bit
        sack_mod.arm_flight(storm_after=1)
        disagg_mod.arm_ctrl_flight(storm_after=1)
        # a deliberately unmeetable objective sampled BEFORE the faulted
        # arms: the diff window over their TTFTs must burn
        slo_mon = slo_mod.BurnRateMonitor(
            [slo_mod.Objective(name="ttft_tight",
                               metric="serving_ttft_seconds",
                               threshold_s=1e-6, target=0.99)],
            windows=((60.0, 1.0),), clock=lambda: slo_clock[0])
        slo_mon.sample()
    if args.smoke:
        args.replicas, args.requests = 2, 10
        args.ctrl_drop = 0.05
        # burst arrivals: the whole stream is queued when the kill
        # fires, so recovery always has both in-slot work to restart
        # AND queued work to resubmit (deterministic chaos bite)
        args.rate = 0.0
    jax = init_devices(args.devices)

    arms, fleet_metrics = [], []
    for arm_name in [a.strip() for a in args.arm.split(",") if a.strip()]:
        if arm_name == "router":
            arm, ms = run_router_arm(args, jax, args.kill_at)
        elif arm_name == "disagg":
            arm, ms = run_disagg_arm(args, jax)
        else:
            raise SystemExit(f"unknown arm {arm_name!r}")
        arms.append(arm)
        fleet_metrics.extend(ms)

    if flight_on:
        arms.append(run_flight_checks(args, jax, arms, slo_mon,
                                      slo_clock))

    # the FLEET conservation snapshot: every engine the chaos touched
    # (survivors, victims, both disagg roles) merged — check_obs --chaos
    # re-asserts the invariant straight off these exported lines
    from uccl_tpu.serving.metrics import ServingMetrics

    merged = ServingMetrics.merged(fleet_metrics)
    snap = merged.snapshot()
    if snap["submitted"] != (snap["completed"] + snap["active"]
                             + snap["queued"] + snap["rejected"]
                             + snap["expired"] + snap["lost"]):
        raise SystemExit(f"FLEET INVARIANT VIOLATED: {snap}")
    written = obs.dump_from_args(
        args, extra_lines=ServingMetrics.prometheus_lines(snap)
    )
    for w in written:
        print(f"chaos_bench: wrote {w}", flush=True)
    if args.json_out:
        with open(args.json_out, "w") as f:
            for arm in arms:
                f.write(json.dumps(arm) + "\n")
        print(f"chaos_bench: wrote {args.json_out}", flush=True)
    print(f"chaos_bench: ALL OK ({len(arms)} arm(s))", flush=True)


if __name__ == "__main__":
    main()
