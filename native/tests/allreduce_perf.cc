// allreduce_perf: nccl-tests-shaped acceptance benchmark over the net plugin.
//
// The reference's system-level acceptance test is thirdparty/nccl-tests'
// all_reduce_perf driven against its NCCL net plugin (SURVEY §4.5,
// collective/rdma/run_nccl_test.sh). This is the TPU-framework analog: a
// standalone C++ harness that dlopens libuccl_tpu_net.so, speaks ONLY the
// ucclt_net_v1 vtable (listen/connect/accept/reg_mr/isend/irecv/test), and
// runs a ring allreduce across N forked ranks on this host — proving the
// plugin ABI is complete enough to build a collective runtime on, exactly
// what NCCL proves about the reference's plugin.
//
// Output mirrors nccl-tests: one row per size with time, algorithm bandwidth
// and bus bandwidth (busbw = algbw * 2*(n-1)/n), plus a #wrong correctness
// column (rank-patterned input, exact float sum verified).
//
// Usage: allreduce_perf [-n ranks] [-b minbytes] [-e maxbytes] [-f factor]
//                       [-i iters] [-w warmup] [-p plugin.so] [-c 0|1]

#include <dlfcn.h>
#include <getopt.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <array>
#include <string>
#include <vector>

#include "uccl_tpu/net_plugin.h"

namespace {

double now_us() {
  timeval tv;
  gettimeofday(&tv, nullptr);
  return tv.tv_sec * 1e6 + tv.tv_usec;
}

bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t k = write(fd, p, n);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool read_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t k = read(fd, p, n);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

struct SizeReport {
  double time_us;
  uint64_t wrong;
};

// Deterministic rank-patterned input (the nccl-tests discipline: seeded
// data, exact expected reduction).
float pattern(int rank, size_t i) {
  return static_cast<float>((i * 37 + static_cast<size_t>(rank) * 101) % 97) *
         0.25f;
}

struct Ring {
  const ucclt_net_v1_t* net = nullptr;
  void* send_comm = nullptr;  // to next rank
  void* recv_comm = nullptr;  // from prev rank
  void* send_mr = nullptr;
  void* recv_mr = nullptr;
  // Barrier token buffers, registered in their own right (the harness must
  // honor the reg_mr contract it exists to validate — a conforming plugin
  // may DMA from exactly the registered range).
  uint8_t tok_out = 0;
  uint8_t tok_in = 0;
  void* tok_out_mr = nullptr;
  void* tok_in_mr = nullptr;
  int nranks = 0;

  // Blocking send+recv pair (the harness is single-threaded per rank; the
  // plugin's isend is buffer-reusable-on-done so polling both to completion
  // cannot deadlock over the framed-TCP engine).
  int rank = -1;

  // Bidirectional step with per-direction sizes (ring segments may differ
  // in length when count % n != 0; a zero-length direction is skipped on
  // both sides, which agree on lengths by construction).
  bool exchange2(const void* sbuf, size_t sbytes, void* rbuf, size_t rbytes,
                 uint64_t tag) {
    if (getenv("ARP_TRACE")) {
      fprintf(stderr, "[r%d pid%d] xchg tag=%llu s=%zu r=%zu\n", rank,
              getpid(), (unsigned long long)tag, sbytes, rbytes);
    }
    void* sreq = nullptr;
    void* rreq = nullptr;
    if (rbytes &&
        net->irecv(recv_comm, rbuf, rbytes, tag, recv_mr, &rreq) !=
            UCCLT_NET_OK) {
      fprintf(stderr, "rank %d: irecv(tag=%llu) failed\n", rank,
              (unsigned long long)tag);
      return false;
    }
    if (sbytes &&
        net->isend(send_comm, sbuf, sbytes, tag, send_mr, &sreq) !=
            UCCLT_NET_OK) {
      fprintf(stderr, "rank %d: isend(tag=%llu) failed\n", rank,
              (unsigned long long)tag);
      return false;
    }
    int sdone = sbytes ? 0 : 1, rdone = rbytes ? 0 : 1;
    size_t got = 0;
    while (!sdone || !rdone) {
      if (!sdone && net->test(sreq, &sdone, &got) != UCCLT_NET_OK) {
        fprintf(stderr, "rank %d: send test(tag=%llu) failed\n", rank,
                (unsigned long long)tag);
        return false;
      }
      if (!rdone && net->test(rreq, &rdone, &got) != UCCLT_NET_OK) {
        fprintf(stderr, "rank %d: recv test(tag=%llu, %zuB) failed\n", rank,
                (unsigned long long)tag, rbytes);
        return false;
      }
    }
    return true;
  }

  bool exchange(const void* sbuf, void* rbuf, size_t bytes, uint64_t tag) {
    return exchange2(sbuf, bytes, rbuf, bytes, tag);
  }

  // Dissemination barrier on the ring: after k neighbor exchanges a rank
  // has (transitively) heard from every rank within distance k, so n-1
  // rounds make a true barrier. Consumes n-1 tags starting at `tag`.
  bool barrier(uint64_t tag) {
    for (int round = 0; round < nranks - 1; ++round) {
      tok_out = 1;
      void* sreq = nullptr;
      void* rreq = nullptr;
      if (net->irecv(recv_comm, &tok_in, 1, tag + round, tok_in_mr, &rreq) !=
          UCCLT_NET_OK)
        return false;
      if (net->isend(send_comm, &tok_out, 1, tag + round, tok_out_mr,
                     &sreq) != UCCLT_NET_OK)
        return false;
      int sdone = 0, rdone = 0;
      size_t got = 0;
      while (!sdone || !rdone) {
        if (!sdone && net->test(sreq, &sdone, &got) != UCCLT_NET_OK)
          return false;
        if (!rdone && net->test(rreq, &rdone, &got) != UCCLT_NET_OK)
          return false;
      }
    }
    return true;
  }
};

// Ring allreduce (sum, f32), in place: reduce-scatter then allgather, the
// canonical 2*(n-1)/n bus-bandwidth schedule nccl-tests rates plugins by.
bool ring_allreduce(Ring& r, float* data, size_t count, int rank, int n,
                    float* scratch, uint64_t tag_base) {
  if (n == 1) return true;
  size_t seg = (count + static_cast<size_t>(n) - 1) / n;
  auto seg_ptr = [&](int s) { return data + static_cast<size_t>(s) * seg; };
  auto seg_len = [&](int s) {
    size_t lo = static_cast<size_t>(s) * seg;
    if (lo >= count) return static_cast<size_t>(0);
    size_t hi = lo + seg;
    return (hi > count ? count : hi) - lo;
  };
  uint64_t tag = tag_base;
  for (int step = 0; step < n - 1; ++step, ++tag) {
    int ssend = ((rank - step) % n + n) % n;
    int srecv = ((rank - step - 1) % n + n) % n;
    size_t len = seg_len(srecv);
    if (!r.exchange2(seg_ptr(ssend), seg_len(ssend) * sizeof(float), scratch,
                     len * sizeof(float), tag))
      return false;
    float* dst = seg_ptr(srecv);
    for (size_t i = 0; i < len; ++i) dst[i] += scratch[i];
  }
  for (int step = 0; step < n - 1; ++step, ++tag) {
    int ssend = ((rank + 1 - step) % n + n) % n;
    int srecv = ((rank - step) % n + n) % n;
    size_t len = seg_len(srecv);
    if (!r.exchange2(seg_ptr(ssend), seg_len(ssend) * sizeof(float), scratch,
                     len * sizeof(float), tag))
      return false;
    memcpy(seg_ptr(srecv), scratch, len * sizeof(float));
  }
  return true;
}

int run_rank(int rank, int n, int oob_fd, const char* plugin_path,
             size_t min_bytes, size_t max_bytes, int factor, int iters,
             int warmup, int check) {
  void* so = dlopen(plugin_path, RTLD_NOW | RTLD_LOCAL);
  if (!so) {
    fprintf(stderr, "rank %d: dlopen %s: %s\n", rank, plugin_path, dlerror());
    return 2;
  }
  auto* net = static_cast<const ucclt_net_v1_t*>(dlsym(so, "ucclt_net_v1"));
  if (!net) {
    fprintf(stderr, "rank %d: no ucclt_net_v1 symbol\n", rank);
    return 2;
  }
  if (net->init() != UCCLT_NET_OK) return 2;

  // Multi-NIC: ranks round-robin across the plugin's logical devices
  // (reference: NCCL schedules channels across the devices nccl_plugin.cc
  // enumerates). With UCCL_TPU_NIC_LIST set this exercises listens bound to
  // distinct NICs and cross-device dials in one ring.
  int ndev = 1;
  if (net->devices(&ndev) != UCCLT_NET_OK || ndev < 1) return 2;
  int dev = rank % ndev;

  // Rendezvous: ship my listen handle to the parent, get back the handle of
  // the rank I connect to (next in ring). This is the out-of-band channel
  // the plugin contract assumes (NCCL ships handles via its bootstrap).
  char handle[UCCLT_NET_HANDLE_BYTES];
  void* listen_comm = nullptr;
  if (net->listen(dev, handle, &listen_comm) != UCCLT_NET_OK) return 2;
  if (!write_all(oob_fd, handle, sizeof(handle))) return 2;
  char next_handle[UCCLT_NET_HANDLE_BYTES];
  if (!read_all(oob_fd, next_handle, sizeof(next_handle))) return 2;

  Ring ring;
  ring.net = net;
  ring.rank = rank;
  ring.nranks = n;
  if (net->connect(dev, next_handle, &ring.send_comm) != UCCLT_NET_OK)
    return 2;
  if (net->accept(listen_comm, &ring.recv_comm) != UCCLT_NET_OK) return 2;
  if (net->reg_mr(ring.send_comm, &ring.tok_out, 1, 0, &ring.tok_out_mr) !=
      UCCLT_NET_OK)
    return 2;
  if (net->reg_mr(ring.recv_comm, &ring.tok_in, 1, 0, &ring.tok_in_mr) !=
      UCCLT_NET_OK)
    return 2;

  size_t max_count = max_bytes / sizeof(float);
  size_t seg = (max_count + static_cast<size_t>(n) - 1) / n;
  std::vector<float> data(max_count ? max_count : 1);
  std::vector<float> scratch((seg ? seg : 1) + 1);
  if (net->reg_mr(ring.send_comm, data.data(), data.size() * sizeof(float), 0,
                  &ring.send_mr) != UCCLT_NET_OK)
    return 2;
  if (net->reg_mr(ring.recv_comm, scratch.data(),
                  scratch.size() * sizeof(float), 0,
                  &ring.recv_mr) != UCCLT_NET_OK)
    return 2;

  uint64_t tag = 1000;
  for (size_t bytes = min_bytes; bytes <= max_bytes;
       bytes *= static_cast<size_t>(factor)) {
    size_t count = bytes / sizeof(float);
    if (!count) continue;
    SizeReport rep{0.0, 0};
    for (int it = 0; it < warmup + iters; ++it) {
      for (size_t i = 0; i < count; ++i) data[i] = pattern(rank, i);
      if (!ring.barrier(tag)) return 2;
      tag += static_cast<uint64_t>(n);  // barrier consumed n-1 tags
      double t0 = now_us();
      if (!ring_allreduce(ring, data.data(), count, rank, n, scratch.data(),
                          tag))
        return 2;
      double dt = now_us() - t0;
      tag += 2 * static_cast<uint64_t>(n);
      if (it >= warmup) rep.time_us += dt / iters;
      if (check && it == warmup + iters - 1) {
        for (size_t i = 0; i < count; ++i) {
          float want = 0.f;
          for (int rr = 0; rr < n; ++rr) want += pattern(rr, i);
          if (data[i] != want) ++rep.wrong;
        }
      }
    }
    if (!write_all(oob_fd, &rep, sizeof(rep))) return 2;
  }

  net->dereg_mr(ring.send_comm, ring.send_mr);
  net->dereg_mr(ring.recv_comm, ring.recv_mr);
  net->dereg_mr(ring.send_comm, ring.tok_out_mr);
  net->dereg_mr(ring.recv_comm, ring.tok_in_mr);
  net->close_send(ring.send_comm);
  net->close_recv(ring.recv_comm);
  net->close_listen(listen_comm);
  net->finalize();
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  int n = 2, iters = 5, warmup = 2, factor = 2, check = 1;
  size_t min_bytes = 1024, max_bytes = 1 << 22;
  std::string plugin = "build/libuccl_tpu_net.so";
  int opt;
  while ((opt = getopt(argc, argv, "n:b:e:f:i:w:p:c:")) != -1) {
    switch (opt) {
      case 'n': n = atoi(optarg); break;
      case 'b': min_bytes = strtoull(optarg, nullptr, 0); break;
      case 'e': max_bytes = strtoull(optarg, nullptr, 0); break;
      case 'f': factor = atoi(optarg); break;
      case 'i': iters = atoi(optarg); break;
      case 'w': warmup = atoi(optarg); break;
      case 'p': plugin = optarg; break;
      case 'c': check = atoi(optarg); break;
      default:
        fprintf(stderr, "bad flag\n");
        return 2;
    }
  }
  if (n < 2 || factor < 2 || min_bytes < sizeof(float) ||
      max_bytes < min_bytes) {
    fprintf(stderr, "need -n>=2, -f>=2, 4 <= -b <= -e\n");
    return 2;
  }

  std::vector<int> fds(n);
  std::vector<pid_t> pids(n);
  for (int r = 0; r < n; ++r) {
    int sv[2];
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
      perror("socketpair");
      return 2;
    }
    pid_t pid = fork();
    if (pid == 0) {
      close(sv[0]);
      for (int k = 0; k < r; ++k) close(fds[k]);
      int rc = run_rank(r, n, sv[1], plugin.c_str(), min_bytes, max_bytes,
                        factor, iters, warmup, check);
      _exit(rc);
    }
    close(sv[1]);
    fds[r] = sv[0];
    pids[r] = pid;
  }

  // Handle exchange: collect every rank's listen handle, hand rank r the
  // handle of rank (r+1)%n.
  std::vector<std::array<char, UCCLT_NET_HANDLE_BYTES>> handles(n);
  bool ok = true;
  for (int r = 0; r < n; ++r)
    ok = ok && read_all(fds[r], handles[r].data(), UCCLT_NET_HANDLE_BYTES);
  for (int r = 0; r < n && ok; ++r)
    ok = ok && write_all(fds[r], handles[(r + 1) % n].data(),
                         UCCLT_NET_HANDLE_BYTES);
  if (!ok) {
    fprintf(stderr, "handle exchange failed\n");
    return 2;
  }

  printf("# allreduce_perf over ucclt_net_v1 (%s), %d ranks, ring, f32 sum\n",
         plugin.c_str(), n);
  printf("# %10s %10s %12s %12s %12s %8s\n", "size_B", "count", "time_us",
         "algbw_GBps", "busbw_GBps", "wrong");
  uint64_t total_wrong = 0;
  for (size_t bytes = min_bytes; bytes <= max_bytes;
       bytes *= static_cast<size_t>(factor)) {
    size_t count = bytes / sizeof(float);
    if (!count) continue;
    double worst = 0.0;
    uint64_t wrong = 0;
    for (int r = 0; r < n; ++r) {
      SizeReport rep;
      if (!read_all(fds[r], &rep, sizeof(rep))) {
        fprintf(stderr, "rank %d died mid-benchmark\n", r);
        return 2;
      }
      if (rep.time_us > worst) worst = rep.time_us;
      wrong += rep.wrong;
    }
    double algbw = worst > 0 ? bytes / (worst * 1e-6) / 1e9 : 0.0;
    double busbw = algbw * 2.0 * (n - 1) / n;
    printf("  %10zu %10zu %12.1f %12.3f %12.3f %8llu\n", bytes, count, worst,
           algbw, busbw, static_cast<unsigned long long>(wrong));
    total_wrong += wrong;
  }

  int bad = 0;
  for (int r = 0; r < n; ++r) {
    int st = 0;
    waitpid(pids[r], &st, 0);
    if (!WIFEXITED(st) || WEXITSTATUS(st) != 0) bad = 1;
    close(fds[r]);
  }
  if (total_wrong) {
    printf("# FAILED: %llu wrong elements\n",
           static_cast<unsigned long long>(total_wrong));
    return 1;
  }
  if (bad) return 2;
  printf("# OK\n");
  return 0;
}
