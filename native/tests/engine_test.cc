// Threaded engine tests, built to run under TSAN and ASan+UBSan.
//
// The substrate headers have sanitizer coverage (substrate_test.cc); this
// drives the ENGINE's concurrent surface — io/tx thread pairs, the xfer
// tracking map, recv and notif queues, reap, drop injection — through a
// loopback Endpoint pair from multiple application threads, the same
// shapes the Python suite exercises but visible to the race detectors.
// (Reference ships no sanitizer coverage at all — SURVEY.md §5.)

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <random>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "uccl_tpu/engine.h"

using uccl_tpu::Endpoint;
using uccl_tpu::FifoItem;
using uccl_tpu::XferState;

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__,        \
                   __LINE__, #cond);                                    \
      std::abort();                                                     \
    }                                                                   \
  } while (0)

struct Pair {
  Endpoint server{0, 2};
  Endpoint client{0, 2};
  uint64_t conn_s = 0, conn_c = 0;
  Pair() {
    CHECK(server.ok() && client.ok());
    int64_t cc = -1;
    std::thread dial([&] {
      cc = client.connect("127.0.0.1", server.listen_port());
    });
    int64_t cs = server.accept(10000);
    dial.join();
    CHECK(cs >= 0 && cc >= 0);
    conn_s = static_cast<uint64_t>(cs);
    conn_c = static_cast<uint64_t>(cc);
  }
};

// One-sided writes from N application threads into N distinct windows,
// each thread doing write_async + wait; verifies every byte.
static void test_concurrent_writes() {
  Pair p;
  constexpr int kThreads = 4, kIters = 16, kLen = 8192;
  std::vector<std::vector<uint8_t>> dst(kThreads,
                                        std::vector<uint8_t>(kLen));
  std::vector<FifoItem> fifos(kThreads);
  std::vector<uint64_t> mrs(kThreads);
  for (int t = 0; t < kThreads; ++t) {
    mrs[t] = p.server.reg(dst[t].data(), kLen);
    CHECK(p.server.advertise(mrs[t], 0, kLen, &fifos[t]));
  }
  std::vector<std::thread> ths;
  for (int t = 0; t < kThreads; ++t) {
    ths.emplace_back([&, t] {
      std::vector<uint8_t> src(kLen);
      for (int i = 0; i < kIters; ++i) {
        std::mt19937 gen(t * 1000 + i);
        for (auto& b : src) b = static_cast<uint8_t>(gen());
        uint64_t xid =
            p.client.write_async(p.conn_c, src.data(), kLen, fifos[t]);
        CHECK(p.client.wait(xid, 10000));
      }
      // last iteration's bytes must be in the window
      std::mt19937 gen(t * 1000 + kIters - 1);
      for (int j = 0; j < kLen; ++j)
        CHECK(dst[t][j] == static_cast<uint8_t>(gen()));
    });
  }
  for (auto& th : ths) th.join();
  std::printf("engine concurrent_writes ok\n");
}

// Two-sided send/recv + notifs from concurrent senders; recv ordering is
// per-conn FIFO, notifs drain across conns with source tagging.
static void test_send_recv_notifs() {
  Pair p;
  constexpr int kMsgs = 64;
  std::thread sender([&] {
    for (int i = 0; i < kMsgs; ++i) {
      char buf[32];
      int n = std::snprintf(buf, sizeof buf, "msg-%03d", i);
      CHECK(p.client.send(p.conn_c, buf, static_cast<size_t>(n)));
    }
  });
  std::thread notifier([&] {
    for (int i = 0; i < kMsgs; ++i) {
      char buf[32];
      int n = std::snprintf(buf, sizeof buf, "ntf-%03d", i);
      CHECK(p.client.send_notif(p.conn_c, buf, static_cast<size_t>(n)));
    }
  });
  // drain both queues concurrently with the senders (bounded: a dropped
  // message must fail the test, not hang make test/tsan in CI)
  std::set<std::string> notifs;
  int got_msgs = 0;
  auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(60);
  while (got_msgs < kMsgs || notifs.size() < static_cast<size_t>(kMsgs)) {
    CHECK(std::chrono::steady_clock::now() < deadline);
    char buf[64];
    if (got_msgs < kMsgs) {
      int64_t n = p.server.recv(p.conn_s, buf, sizeof buf, 10);
      if (n > 0) {
        char want[32];
        std::snprintf(want, sizeof want, "msg-%03d", got_msgs);
        CHECK(n == (int64_t)std::strlen(want) &&
              0 == std::memcmp(buf, want, n));
        ++got_msgs;
      }
    }
    uint64_t conn = 0;
    int64_t n = p.server.get_notif(&conn, buf, sizeof buf);
    if (n > 0) {
      CHECK(conn == p.conn_s);
      notifs.emplace(buf, buf + n);
    }
  }
  sender.join();
  notifier.join();
  CHECK(notifs.size() == static_cast<size_t>(kMsgs));  // all distinct
  std::printf("engine send_recv_notifs ok\n");
}

// Drop injection: a dropped frame's xfer stays pending; reap erases it;
// concurrent reaps/polls while traffic flows must be race-free.
static void test_drop_reap() {
  Pair p;
  constexpr int kLen = 1024;
  std::vector<uint8_t> dst(kLen), src(kLen, 0x5A);
  uint64_t mr = p.server.reg(dst.data(), kLen);
  FifoItem fifo{};
  CHECK(p.server.advertise(mr, 0, kLen, &fifo));

  p.client.set_drop_rate(1.0);
  std::vector<uint64_t> lost;
  for (int i = 0; i < 8; ++i)
    lost.push_back(p.client.write_async(p.conn_c, src.data(), kLen, fifo));
  for (uint64_t x : lost) CHECK(!p.client.wait(x, 50));
  p.client.set_drop_rate(0.0);

  // reap the abandoned ids from one thread while another pushes new
  // (deliverable) traffic through the same conn
  std::thread reaper([&] {
    for (uint64_t x : lost) p.client.reap(x);
  });
  std::thread writer([&] {
    for (int i = 0; i < 16; ++i) {
      uint64_t xid = p.client.write_async(p.conn_c, src.data(), kLen, fifo);
      CHECK(p.client.wait(xid, 10000));
    }
  });
  reaper.join();
  writer.join();
  const char* wire = std::getenv("UCCL_TPU_WIRE");
  bool udp = wire != nullptr && std::strcmp(wire, "udp") == 0;
  for (uint64_t x : lost) {
    if (udp) {
      // UDP wire: drop_rate loses PACKETS, and once it resets the
      // reliability layer retransmits — the "lost" frames are recovered,
      // so a reaped id may legitimately resolve kDone (late completion)
      // or kError (reap consumed it first). Either is terminal; the test
      // here is that the reap/retransmit race never corrupts tracking.
      CHECK(p.client.poll(x) != XferState::kPending);
    } else {
      CHECK(p.client.poll(x) == XferState::kError);
    }
  }
  for (int j = 0; j < kLen; ++j) CHECK(dst[j] == 0x5A);
  std::printf("engine drop_reap ok\n");
}

// Read path under concurrency: N threads read the same advertised window.
static void test_concurrent_reads() {
  Pair p;
  constexpr int kThreads = 4, kLen = 4096;
  std::vector<uint8_t> src(kLen);
  for (int j = 0; j < kLen; ++j) src[j] = static_cast<uint8_t>(j * 7);
  uint64_t mr = p.server.reg(src.data(), kLen);
  FifoItem fifo{};
  CHECK(p.server.advertise(mr, 0, kLen, &fifo));
  std::vector<std::thread> ths;
  for (int t = 0; t < kThreads; ++t) {
    ths.emplace_back([&] {
      std::vector<uint8_t> dst(kLen);
      for (int i = 0; i < 8; ++i) {
        std::memset(dst.data(), 0, kLen);
        CHECK(p.client.read(p.conn_c, dst.data(), kLen, fifo));
        CHECK(0 == std::memcmp(dst.data(), src.data(), kLen));
      }
    });
  }
  for (auto& th : ths) th.join();
  std::printf("engine concurrent_reads ok\n");
}

// Teardown with traffic GENUINELY in flight must not race engine threads:
// async writes are issued and never waited for, so ~Endpoint runs while
// frames sit in rings/tx queues and completions are still arriving. The
// source/destination buffers outlive the endpoints (declared before the
// deletes), honoring the keepalive contract even through teardown.
static void test_teardown_under_load() {
  for (int round = 0; round < 4; ++round) {
    std::vector<uint8_t> dst(1 << 16);
    std::vector<uint8_t> src(dst.size(), 0x33);
    Pair* p = new Pair();
    uint64_t mr = p->server.reg(dst.data(), dst.size());
    FifoItem fifo{};
    CHECK(p->server.advertise(mr, 0, dst.size(), &fifo));
    for (int i = 0; i < 32; ++i) {
      p->client.write_async(p->conn_c, src.data(), src.size(), fifo);
    }
    delete p;  // destructor drains/joins with transfers outstanding
  }
  std::printf("engine teardown_under_load ok\n");
}

int main() {
  // UCCLT_TEST_REPS loops the whole list in-process: rare-interleaving
  // hunts (the ASan soak that caught a use-after-free only under a
  // loaded box) get far more schedule rolls per second than re-execing.
  int reps = 1;
  if (const char* r = std::getenv("UCCLT_TEST_REPS")) reps = std::atoi(r);
  for (int rep = 0; rep < reps; ++rep) {
    test_concurrent_writes();
    test_send_recv_notifs();
    test_drop_reap();
    test_concurrent_reads();
    test_teardown_under_load();
    if (reps > 1 && (rep + 1) % 25 == 0)
      std::printf("rep %d/%d\n", rep + 1, reps), std::fflush(stdout);
  }
  std::printf("ALL ENGINE TESTS PASSED\n");
  return 0;
}
