// Substrate unit tests: SPSC/MPSC rings, lrpc channel, shared pool.
//
// The analog of the reference's pure-CPU unit mains (util_lrpc_test.cc,
// util_test.cc — SURVEY.md §4.1). Build plain, or under -fsanitize=thread /
// address via `make tsan` / `make asan` — the sanitizer coverage the
// reference lacks (SURVEY.md §5).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include "uccl_tpu/cb.h"
#include "uccl_tpu/list.h"
#include "uccl_tpu/lrpc.h"
#include "uccl_tpu/pool.h"
#include "uccl_tpu/ring.h"
#include "uccl_tpu/timing_wheel.h"

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,       \
                   #cond);                                               \
      std::exit(1);                                                      \
    }                                                                    \
  } while (0)

using namespace uccl_tpu;

static void test_spsc_threaded() {
  SpscRing<uint64_t> ring(256);
  constexpr uint64_t kN = 200000;
  std::thread producer([&] {
    for (uint64_t i = 0; i < kN; ++i) {
      while (!ring.push(i)) std::this_thread::yield();
    }
  });
  uint64_t expect = 0;
  while (expect < kN) {
    uint64_t v;
    if (ring.pop(&v)) {
      CHECK(v == expect);  // FIFO, no loss, no duplication
      ++expect;
    }
  }
  producer.join();
  uint64_t v;
  CHECK(!ring.pop(&v));
  std::puts("spsc_threaded ok");
}

static void test_mpsc_threaded() {
  MpscRing<uint64_t> ring(512);
  constexpr int kProducers = 4;
  constexpr uint64_t kPer = 50000;
  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&, p] {
      for (uint64_t i = 0; i < kPer; ++i) {
        // encode (producer, seq) so the consumer can check per-producer FIFO
        uint64_t v = (static_cast<uint64_t>(p) << 32) | i;
        while (!ring.push(v)) std::this_thread::yield();
      }
    });
  }
  uint64_t next_seq[kProducers] = {0, 0, 0, 0};
  uint64_t got = 0;
  while (got < kProducers * kPer) {
    uint64_t v;
    if (ring.pop(&v)) {
      int p = static_cast<int>(v >> 32);
      uint64_t seq = v & 0xffffffffull;
      CHECK(p < kProducers);
      CHECK(seq == next_seq[p]);  // per-producer order preserved
      ++next_seq[p];
      ++got;
    }
  }
  for (auto& t : producers) t.join();
  std::puts("mpsc_threaded ok");
}

static void test_lrpc_threaded() {
  LrpcChannel chan(64);
  constexpr uint64_t kN = 100000;
  std::thread producer([&] {
    for (uint64_t i = 0; i < kN; ++i) {
      while (!chan.send(&i, sizeof(i))) std::this_thread::yield();
    }
  });
  for (uint64_t expect = 0; expect < kN;) {
    uint64_t v = 0;
    if (chan.recv(&v, sizeof(v))) {
      CHECK(v == expect);
      ++expect;
    }
  }
  producer.join();
  uint64_t v;
  CHECK(!chan.recv(&v, sizeof(v)));
  std::puts("lrpc_threaded ok");
}

static void test_lrpc_full_and_payload() {
  LrpcChannel chan(4);
  char big[kLrpcPayload + 1] = {0};
  CHECK(!chan.send(big, sizeof(big)));  // oversize rejected
  for (int i = 0; i < 4; ++i) CHECK(chan.send(&i, sizeof(i)));
  int x = 9;
  CHECK(!chan.send(&x, sizeof(x)));  // full
  int v = -1;
  CHECK(chan.recv(&v, sizeof(v)) && v == 0);
  CHECK(chan.send(&x, sizeof(x)));  // slot freed
  std::puts("lrpc_full ok");
}

struct PoolObj {
  uint64_t stamp = 0;
  std::vector<uint8_t> buf;
};

static void test_pool_threaded() {
  SharedPool<PoolObj> pool(16);
  constexpr int kThreads = 4;
  std::atomic<uint64_t> alive{0};
  std::vector<std::thread> threads;
  for (int i = 0; i < kThreads; ++i) {
    threads.emplace_back([&] {
      std::vector<PoolObj*> held;
      for (int it = 0; it < 20000; ++it) {
        PoolObj* o = pool.get();
        o->stamp = alive.fetch_add(1);
        o->buf.resize(64);
        held.push_back(o);
        if (held.size() > 8) {
          pool.put(held.back());
          held.pop_back();
          pool.put(held.front());
          held.erase(held.begin());
        }
      }
      for (PoolObj* o : held) pool.put(o);
    });
  }
  for (auto& t : threads) t.join();
  // churn again from this thread: recycled objects come back usable
  for (int i = 0; i < 1000; ++i) {
    PoolObj* o = pool.get();
    CHECK(o != nullptr);
    pool.put(o);
  }
  std::puts("pool_threaded ok");
}

static void test_circular_buffer() {
  CircularBuffer<int> cb(6);  // rounds to 8
  CHECK(cb.capacity() == 8);
  CHECK(cb.empty() && !cb.full());
  for (int i = 0; i < 8; ++i) CHECK(cb.push(i));
  CHECK(cb.full());
  CHECK(!cb.push(99));  // full rejected
  CHECK(cb.front() == 0);
  CHECK(cb.at(3) == 3);
  int v = -1;
  for (int i = 0; i < 5; ++i) {
    CHECK(cb.pop(&v) && v == i);  // FIFO
  }
  // wrap: indices cross the mask boundary and stay FIFO
  for (int i = 100; i < 105; ++i) CHECK(cb.push(i));
  CHECK(cb.size() == 8);
  for (int want : {5, 6, 7, 100, 101, 102, 103, 104}) {
    CHECK(cb.pop(&v) && v == want);
  }
  CHECK(cb.empty() && !cb.pop(&v));
  std::puts("circular_buffer ok");
}

static void test_timing_wheel() {
  TimingWheel<int> w(/*granularity_us=*/10, /*horizon_slots=*/16);
  std::vector<int> fired;
  // nothing scheduled: advance is a no-op
  CHECK(w.advance(1000, &fired) == 0);

  w.schedule(1000, 1);
  w.schedule(1050, 2);
  w.schedule(1049, 3);  // rounds up into the same slot as 2 (tick 105)
  w.schedule(990, 4);   // already past the cursor: next advance
  CHECK(w.size() == 4);

  CHECK(w.advance(1000, &fired) == 2);  // 1 and 4 due
  CHECK(fired.size() == 2 && fired[0] == 1 && fired[1] == 4);

  fired.clear();
  CHECK(w.advance(1044, &fired) == 0);  // never-early: 1049/1050 not due
  CHECK(w.advance(1050, &fired) == 2);
  CHECK(fired[0] == 2 && fired[1] == 3);  // same slot: schedule order

  // beyond-horizon item parks and fires on its lap, not a whole lap early
  fired.clear();
  uint64_t far = 1050 + 10 * 16 * 3;  // 3 laps out, slot-aligned
  w.schedule(far, 7);
  CHECK(w.advance(far - 200, &fired) == 0);  // mid-lap sweep skips it
  CHECK(w.advance(far, &fired) == 1 && fired[0] == 7);
  CHECK(w.empty());

  // far-first-then-near: the near deadline must not be dragged to the far
  // item's slot (cursor tracks advance time, not the first schedule)
  fired.clear();
  w.schedule(far + 100000, 8);  // 100ms out
  w.schedule(far + 20, 9);      // 20us out
  CHECK(w.advance(far + 20, &fired) == 1 && fired[0] == 9);

  // long idle gap then a burst: one advance catches everything due, and
  // the cursor jump keeps later advances cheap
  fired.clear();
  uint64_t late = far + 100000;
  CHECK(w.advance(late, &fired) == 1 && fired[0] == 8);
  w.schedule(late + 5, 10);
  CHECK(w.advance(late + 10 * 16 * 50, &fired) == 1);  // 50-lap gap
  CHECK(fired[1] == 10 && w.empty());
  std::puts("timing_wheel ok");
}

static void test_wheel_recorder() {
  // the action trail (reference wheel_record_t): every pop logs (due,
  // fired); lateness is fired - due, never negative; ring overwrites oldest
  TimingWheel<int> w(/*granularity_us=*/10, /*horizon_slots=*/16);
  WheelRecorder rec(/*capacity=*/4);
  w.set_recorder(&rec);
  std::vector<int> fired;
  // same-lap, non-aliasing slots so pop (= record) order is due order
  w.schedule(100, 1);
  w.schedule(140, 2);
  w.advance(250, &fired);  // both fire late (at 250)
  CHECK(rec.count() == 2);
  auto snap = rec.snapshot();
  CHECK(snap[0].due_us == 100 && snap[0].fired_us == 250);
  CHECK(snap[1].due_us == 140 && snap[1].lateness_us() == 110);
  CHECK(rec.max_lateness_us() == 150);
  // past-due schedule: lateness measured against the CALLER's deadline,
  // not the clamped slot tick
  fired.clear();
  w.schedule(40, 5);  // cursor is already past tick 4
  w.advance(260, &fired);
  CHECK(fired.size() == 1 && fired[0] == 5);
  CHECK(rec.max_lateness_us() == 220);  // 260 - 40, not ~0
  // overflow: capacity 4 keeps the newest 4, oldest-first order
  for (int i = 0; i < 6; ++i) w.schedule(300 + 10 * i, 10 + i);
  fired.clear();
  w.advance(1000, &fired);
  CHECK(fired.size() == 6);
  CHECK(rec.count() == 4);
  snap = rec.snapshot();
  CHECK(snap.front().due_us == 320 && snap.back().due_us == 350);
  std::puts("wheel_recorder ok");
}

struct Flow {
  int id = 0;
  ListHead link;
};

static void test_intrusive_list() {
  ListHead active;
  ListHead idle;
  Flow flows[4];
  for (int i = 0; i < 4; ++i) flows[i].id = i;
  CHECK(active.empty());
  CHECK(active.front() == nullptr && active.back() == nullptr);
  active.push_back(&flows[0].link);
  active.push_back(&flows[1].link);
  active.push_front(&flows[2].link);  // order: 2, 0, 1
  CHECK(UCCL_LIST_ENTRY(active.front(), Flow, link)->id == 2);
  CHECK(UCCL_LIST_ENTRY(active.back(), Flow, link)->id == 1);
  flows[0].link.unlink();  // O(1) removal from the middle
  CHECK(UCCL_LIST_ENTRY(flows[2].link.next, Flow, link)->id == 1);
  CHECK(!flows[0].link.linked());
  flows[0].link.unlink();  // unlink twice is safe
  // re-homing a linked node detaches it from its old list first
  idle.push_back(&flows[1].link);
  CHECK(UCCL_LIST_ENTRY(idle.front(), Flow, link)->id == 1);
  CHECK(UCCL_LIST_ENTRY(active.front(), Flow, link)->id == 2);
  CHECK(active.front() == active.back());  // only flow 2 remains
  flows[2].link.unlink();
  flows[1].link.unlink();
  CHECK(active.empty() && idle.empty());
  std::puts("intrusive_list ok");
}

int main() {
  test_spsc_threaded();
  test_mpsc_threaded();
  test_lrpc_threaded();
  test_lrpc_full_and_payload();
  test_pool_threaded();
  test_circular_buffer();
  test_timing_wheel();
  test_wheel_recorder();
  test_intrusive_list();
  std::puts("ALL SUBSTRATE TESTS PASSED");
  return 0;
}
