// Substrate unit tests: SPSC/MPSC rings, lrpc channel, shared pool.
//
// The analog of the reference's pure-CPU unit mains (util_lrpc_test.cc,
// util_test.cc — SURVEY.md §4.1). Build plain, or under -fsanitize=thread /
// address via `make tsan` / `make asan` — the sanitizer coverage the
// reference lacks (SURVEY.md §5).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include "uccl_tpu/lrpc.h"
#include "uccl_tpu/pool.h"
#include "uccl_tpu/ring.h"

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,       \
                   #cond);                                               \
      std::exit(1);                                                      \
    }                                                                    \
  } while (0)

using namespace uccl_tpu;

static void test_spsc_threaded() {
  SpscRing<uint64_t> ring(256);
  constexpr uint64_t kN = 200000;
  std::thread producer([&] {
    for (uint64_t i = 0; i < kN; ++i) {
      while (!ring.push(i)) std::this_thread::yield();
    }
  });
  uint64_t expect = 0;
  while (expect < kN) {
    uint64_t v;
    if (ring.pop(&v)) {
      CHECK(v == expect);  // FIFO, no loss, no duplication
      ++expect;
    }
  }
  producer.join();
  uint64_t v;
  CHECK(!ring.pop(&v));
  std::puts("spsc_threaded ok");
}

static void test_mpsc_threaded() {
  MpscRing<uint64_t> ring(512);
  constexpr int kProducers = 4;
  constexpr uint64_t kPer = 50000;
  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&, p] {
      for (uint64_t i = 0; i < kPer; ++i) {
        // encode (producer, seq) so the consumer can check per-producer FIFO
        uint64_t v = (static_cast<uint64_t>(p) << 32) | i;
        while (!ring.push(v)) std::this_thread::yield();
      }
    });
  }
  uint64_t next_seq[kProducers] = {0, 0, 0, 0};
  uint64_t got = 0;
  while (got < kProducers * kPer) {
    uint64_t v;
    if (ring.pop(&v)) {
      int p = static_cast<int>(v >> 32);
      uint64_t seq = v & 0xffffffffull;
      CHECK(p < kProducers);
      CHECK(seq == next_seq[p]);  // per-producer order preserved
      ++next_seq[p];
      ++got;
    }
  }
  for (auto& t : producers) t.join();
  std::puts("mpsc_threaded ok");
}

static void test_lrpc_threaded() {
  LrpcChannel chan(64);
  constexpr uint64_t kN = 100000;
  std::thread producer([&] {
    for (uint64_t i = 0; i < kN; ++i) {
      while (!chan.send(&i, sizeof(i))) std::this_thread::yield();
    }
  });
  for (uint64_t expect = 0; expect < kN;) {
    uint64_t v = 0;
    if (chan.recv(&v, sizeof(v))) {
      CHECK(v == expect);
      ++expect;
    }
  }
  producer.join();
  uint64_t v;
  CHECK(!chan.recv(&v, sizeof(v)));
  std::puts("lrpc_threaded ok");
}

static void test_lrpc_full_and_payload() {
  LrpcChannel chan(4);
  char big[kLrpcPayload + 1] = {0};
  CHECK(!chan.send(big, sizeof(big)));  // oversize rejected
  for (int i = 0; i < 4; ++i) CHECK(chan.send(&i, sizeof(i)));
  int x = 9;
  CHECK(!chan.send(&x, sizeof(x)));  // full
  int v = -1;
  CHECK(chan.recv(&v, sizeof(v)) && v == 0);
  CHECK(chan.send(&x, sizeof(x)));  // slot freed
  std::puts("lrpc_full ok");
}

struct PoolObj {
  uint64_t stamp = 0;
  std::vector<uint8_t> buf;
};

static void test_pool_threaded() {
  SharedPool<PoolObj> pool(16);
  constexpr int kThreads = 4;
  std::atomic<uint64_t> alive{0};
  std::vector<std::thread> threads;
  for (int i = 0; i < kThreads; ++i) {
    threads.emplace_back([&] {
      std::vector<PoolObj*> held;
      for (int it = 0; it < 20000; ++it) {
        PoolObj* o = pool.get();
        o->stamp = alive.fetch_add(1);
        o->buf.resize(64);
        held.push_back(o);
        if (held.size() > 8) {
          pool.put(held.back());
          held.pop_back();
          pool.put(held.front());
          held.erase(held.begin());
        }
      }
      for (PoolObj* o : held) pool.put(o);
    });
  }
  for (auto& t : threads) t.join();
  // churn again from this thread: recycled objects come back usable
  for (int i = 0; i < 1000; ++i) {
    PoolObj* o = pool.get();
    CHECK(o != nullptr);
    pool.put(o);
  }
  std::puts("pool_threaded ok");
}

int main() {
  test_spsc_threaded();
  test_mpsc_threaded();
  test_lrpc_threaded();
  test_lrpc_full_and_payload();
  test_pool_threaded();
  std::puts("ALL SUBSTRATE TESTS PASSED");
  return 0;
}
