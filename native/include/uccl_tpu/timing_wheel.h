// Hashed timing wheel for paced / deadline-scheduled work.
//
// TPU-native equivalent of the reference's Carousel pacing wheel
// (collective/rdma/timing_wheel.h: slotted wheel that holds per-chunk
// transmit times so the engine loop injects traffic at the CC-prescribed
// rate) and of its RTO bookkeeping. The DCN engine's aggregate egress cap
// uses a token bucket (engine.cc pace()); this wheel is the finer-grained
// facility for per-item schedules — CC probe timers, retransmit deadlines,
// heal backoff — owned by one thread, no locks.
//
// Design: H slots of G microseconds each; an item due at time T lands in
// slot (T / G) % H. advance(now) sweeps slots from the last sweep position
// through `now`, popping items whose due time has truly arrived (items
// further than one horizon out stay parked in their slot and are skipped
// until their lap comes — the classic hashed-wheel re-lap rule).

#pragma once

#include <cstdint>
#include <vector>

namespace uccl_tpu {

// Action recorder (reference wheel_record_t, collective/rdma/
// timing_wheel.h:31): a bounded ring of (due, fired) pairs capturing how
// late each item actually fired — the pacing-forensics trail. Overwrites
// oldest when full; owned by the wheel's thread, no locks.
struct WheelRecord {
  uint64_t due_us;
  uint64_t fired_us;
  uint64_t lateness_us() const {
    return fired_us > due_us ? fired_us - due_us : 0;
  }
};

class WheelRecorder {
 public:
  explicit WheelRecorder(size_t capacity = 4096)
      : ring_(capacity ? capacity : 1), head_(0), count_(0) {}

  void record(uint64_t due_us, uint64_t fired_us) {
    ring_[head_] = WheelRecord{due_us, fired_us};
    head_ = (head_ + 1) % ring_.size();
    if (count_ < ring_.size()) ++count_;
  }

  // Oldest-first copy of the retained records.
  std::vector<WheelRecord> snapshot() const {
    std::vector<WheelRecord> out;
    out.reserve(count_);
    size_t start = (head_ + ring_.size() - count_) % ring_.size();
    for (size_t i = 0; i < count_; ++i) {
      out.push_back(ring_[(start + i) % ring_.size()]);
    }
    return out;
  }

  // Max lateness across retained records (the pacing health number).
  // In-place scan: a stats path may poll this every tick.
  uint64_t max_lateness_us() const {
    uint64_t m = 0;
    for (size_t i = 0; i < count_; ++i) {
      uint64_t l = ring_[i].lateness_us();
      if (l > m) m = l;
    }
    return m;
  }

  size_t count() const { return count_; }

 private:
  std::vector<WheelRecord> ring_;
  size_t head_;
  size_t count_;
};

template <typename T>
class TimingWheel {
 public:
  // granularity_us: slot width; horizon_slots: wheel size (one lap covers
  // granularity_us * horizon_slots microseconds).
  explicit TimingWheel(uint64_t granularity_us = 64,
                       size_t horizon_slots = 1024)
      : gran_(granularity_us ? granularity_us : 1),
        slots_(horizon_slots ? horizon_slots : 1),
        cursor_(0),
        size_(0) {}

  // Schedule `item` to fire at absolute time `due_us`. Items due in the
  // past (relative to the last advance) fire on the next advance(). Ticks
  // round UP: an item never fires before its due time, at most one slot
  // (granularity_us) late — the right discipline for pacing (early
  // injection defeats the rate cap).
  void schedule(uint64_t due_us, T item) {
    uint64_t tick = (due_us + gran_ - 1) / gran_;
    if (tick < cursor_) tick = cursor_;  // past-due: next sweep's slot
    // Entry keeps the ORIGINAL due time: the recorder must measure lateness
    // against what the caller asked for, not the clamped/rounded slot tick
    // (a past-due item is exactly the late event the trail exists to show).
    slots_[tick % slots_.size()].push_back(
        Entry{tick, due_us, std::move(item)});
    ++size_;
  }

  // Pop every item due at or before `now_us` into `out` (appended in slot
  // order; within a slot, schedule order). Returns the number popped.
  // Cost is bounded by one lap per call regardless of how long the wheel
  // sat idle: the pop test compares against `now`, so a single full lap
  // releases everything due and the cursor can jump straight to now.
  size_t advance(uint64_t now_us, std::vector<T>* out) {
    uint64_t now_tick = now_us / gran_;
    if (now_tick < cursor_) return 0;
    if (size_ == 0) {  // idle fast path: nothing to sweep, just catch up
      cursor_ = now_tick;
      return 0;
    }
    size_t popped = 0;
    uint64_t end = now_tick;
    if (end - cursor_ >= slots_.size()) {
      end = cursor_ + slots_.size() - 1;  // one full lap visits every slot
    }
    for (uint64_t t = cursor_; t <= end; ++t) {
      auto& slot = slots_[t % slots_.size()];
      size_t keep = 0;
      for (size_t i = 0; i < slot.size(); ++i) {
        if (slot[i].tick <= now_tick) {
          if (rec_ != nullptr) rec_->record(slot[i].due_us, now_us);
          out->push_back(std::move(slot[i].item));
          ++popped;
          --size_;
        } else {
          if (keep != i) slot[keep] = std::move(slot[i]);
          ++keep;  // parked for a later lap, order preserved
        }
      }
      slot.resize(keep);
    }
    cursor_ = now_tick;
    return popped;
  }

  size_t size() const { return size_; }
  bool empty() const { return size_ == 0; }

  // Attach an action recorder (nullptr detaches): every pop logs
  // (due, fired). Same-thread discipline as the wheel itself.
  void set_recorder(WheelRecorder* rec) { rec_ = rec; }

 private:
  struct Entry {
    uint64_t tick;
    uint64_t due_us;  // caller's original deadline (recorder ground truth)
    T item;
  };
  uint64_t gran_;
  std::vector<std::vector<Entry>> slots_;
  uint64_t cursor_;  // tick of the last advance (next sweep starts here)
  size_t size_;
  WheelRecorder* rec_ = nullptr;
};

}  // namespace uccl_tpu
