// Intrusive doubly-linked list (kernel-style).
//
// TPU-native equivalent of the reference's include/util/list.h (the
// list_head idiom its EQDS active/idle pacer queues are built on): a node
// embeds the link, so membership costs no allocation and unlink is O(1)
// from the node itself. Single-owner (no locks), like cb.h.

#pragma once

#include <cstddef>

namespace uccl_tpu {

struct ListHead {
  ListHead* prev;
  ListHead* next;

  ListHead() { reset(); }
  // A linked node's neighbors point AT it — copying or moving one would
  // leave them pointing at the original while the copy claims membership.
  ListHead(const ListHead&) = delete;
  ListHead& operator=(const ListHead&) = delete;

  void reset() { prev = next = this; }
  bool empty() const { return next == this; }
  bool linked() const { return next != this; }

  // Insert `n` at the tail (before this sentinel). A node already on a
  // list is detached first — re-homing must never cross-link two lists.
  void push_back(ListHead* n) {
    n->unlink();
    n->prev = prev;
    n->next = this;
    prev->next = n;
    prev = n;
  }

  // Insert `n` at the head (after this sentinel).
  void push_front(ListHead* n) {
    n->unlink();
    n->prev = this;
    n->next = next;
    next->prev = n;
    next = n;
  }

  // Unlink this node from whatever list holds it; safe on unlinked nodes.
  void unlink() {
    prev->next = next;
    next->prev = prev;
    reset();
  }

  // nullptr when empty — callers can't accidentally rebase the sentinel
  // into a garbage object pointer via UCCL_LIST_ENTRY.
  ListHead* front() const {
    return next == this ? nullptr : next;
  }
  ListHead* back() const {
    return prev == this ? nullptr : prev;
  }
};

// Recover the owning object from an embedded ListHead — the container_of
// idiom, via offsetof (fully defined for standard-layout owners, which
// every flow/queue bookkeeping struct here is; conditionally-supported and
// accepted by GCC/Clang beyond that).
#define UCCL_LIST_ENTRY(node, T, member) \
  (reinterpret_cast<T*>(reinterpret_cast<char*>(node) - offsetof(T, member)))

}  // namespace uccl_tpu
