// Lock-free single-producer single-consumer ring.
//
// TPU-native equivalent of the reference's universal inter-thread channel
// (include/util/jring.h, FreeBSD/DPDK lineage; used as `Channel` in
// collective/rdma/transport.h:50 and the p2p task rings, p2p/engine.h:441).
// Fixed power-of-two capacity, cache-line separated head/tail, acquire/release
// ordering only — no fences on the fast path.

#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <vector>

namespace uccl_tpu {

template <typename T>
class SpscRing {
 public:
  explicit SpscRing(size_t capacity_pow2) : mask_(capacity_pow2 - 1) {
    // capacity must be a power of two
    if ((capacity_pow2 & mask_) != 0 || capacity_pow2 == 0) {
      capacity_pow2 = 1024;
      mask_ = capacity_pow2 - 1;
    }
    slots_.resize(capacity_pow2);
  }

  bool push(T v) {
    const uint64_t head = head_.load(std::memory_order_relaxed);
    const uint64_t tail = tail_.load(std::memory_order_acquire);
    if (head - tail > mask_) return false;  // full
    slots_[head & mask_] = std::move(v);
    head_.store(head + 1, std::memory_order_release);
    return true;
  }

  bool pop(T* out) {
    const uint64_t tail = tail_.load(std::memory_order_relaxed);
    const uint64_t head = head_.load(std::memory_order_acquire);
    if (tail == head) return false;  // empty
    *out = std::move(slots_[tail & mask_]);
    tail_.store(tail + 1, std::memory_order_release);
    return true;
  }

  size_t size() const {
    return head_.load(std::memory_order_acquire) -
           tail_.load(std::memory_order_acquire);
  }

 private:
  alignas(64) std::atomic<uint64_t> head_{0};
  alignas(64) std::atomic<uint64_t> tail_{0};
  size_t mask_;
  std::vector<T> slots_;
};

}  // namespace uccl_tpu
