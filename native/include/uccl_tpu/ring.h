// Lock-free single-producer single-consumer ring.
//
// TPU-native equivalent of the reference's universal inter-thread channel
// (include/util/jring.h, FreeBSD/DPDK lineage; used as `Channel` in
// collective/rdma/transport.h:50 and the p2p task rings, p2p/engine.h:441).
// Fixed power-of-two capacity, cache-line separated head/tail, acquire/release
// ordering only — no fences on the fast path.

#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <vector>

namespace uccl_tpu {

template <typename T>
class SpscRing {
 public:
  explicit SpscRing(size_t capacity_pow2) : mask_(capacity_pow2 - 1) {
    // capacity must be a power of two
    if ((capacity_pow2 & mask_) != 0 || capacity_pow2 == 0) {
      capacity_pow2 = 1024;
      mask_ = capacity_pow2 - 1;
    }
    slots_.resize(capacity_pow2);
  }

  bool push(T v) {
    const uint64_t head = head_.load(std::memory_order_relaxed);
    const uint64_t tail = tail_.load(std::memory_order_acquire);
    if (head - tail > mask_) return false;  // full
    slots_[head & mask_] = std::move(v);
    head_.store(head + 1, std::memory_order_release);
    return true;
  }

  bool pop(T* out) {
    const uint64_t tail = tail_.load(std::memory_order_relaxed);
    const uint64_t head = head_.load(std::memory_order_acquire);
    if (tail == head) return false;  // empty
    *out = std::move(slots_[tail & mask_]);
    tail_.store(tail + 1, std::memory_order_release);
    return true;
  }

  size_t size() const {
    return head_.load(std::memory_order_acquire) -
           tail_.load(std::memory_order_acquire);
  }

 private:
  alignas(64) std::atomic<uint64_t> head_{0};
  alignas(64) std::atomic<uint64_t> tail_{0};
  size_t mask_;
  std::vector<T> slots_;
};



// Bounded multi-producer ring (Vyukov MPMC queue, used single-consumer):
// producers CAS-claim a slot and publish via its per-slot sequence stamp —
// the reference uses jring's MPSC mode the same way for task submission
// from many app threads into one engine (include/util/jring.h).
template <typename T>
class MpscRing {
  struct Cell {
    std::atomic<uint64_t> seq;
    T data;
  };

 public:
  explicit MpscRing(size_t capacity_pow2) {
    if (capacity_pow2 == 0 || (capacity_pow2 & (capacity_pow2 - 1)) != 0) {
      capacity_pow2 = 1024;
    }
    cells_ = std::vector<Cell>(capacity_pow2);
    mask_ = capacity_pow2 - 1;
    for (size_t i = 0; i < capacity_pow2; ++i) {
      cells_[i].seq.store(i, std::memory_order_relaxed);
    }
  }

  bool push(T v) {
    uint64_t pos = head_.load(std::memory_order_relaxed);
    Cell* cell;
    while (true) {
      cell = &cells_[pos & mask_];
      uint64_t seq = cell->seq.load(std::memory_order_acquire);
      intptr_t dif = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos);
      if (dif == 0) {
        if (head_.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed)) {
          break;
        }
      } else if (dif < 0) {
        return false;  // full
      } else {
        pos = head_.load(std::memory_order_relaxed);
      }
    }
    cell->data = std::move(v);
    cell->seq.store(pos + 1, std::memory_order_release);
    return true;
  }

  bool pop(T* out) {
    uint64_t pos = tail_.load(std::memory_order_relaxed);
    Cell* cell = &cells_[pos & mask_];
    uint64_t seq = cell->seq.load(std::memory_order_acquire);
    intptr_t dif =
        static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos + 1);
    if (dif != 0) return false;  // empty (or producer mid-publish)
    *out = std::move(cell->data);
    cell->seq.store(pos + mask_ + 1, std::memory_order_release);
    tail_.store(pos + 1, std::memory_order_relaxed);
    return true;
  }

  // Approximate depth for observability (racy by nature; never used for
  // correctness decisions).
  size_t size() const {
    uint64_t h = head_.load(std::memory_order_acquire);
    uint64_t t = tail_.load(std::memory_order_acquire);
    return h > t ? static_cast<size_t>(h - t) : 0;
  }

 private:
  std::vector<Cell> cells_;
  uint64_t mask_ = 0;
  alignas(64) std::atomic<uint64_t> head_{0};
  alignas(64) std::atomic<uint64_t> tail_{0};
};

}  // namespace uccl_tpu
