// Object pool: global freelist + per-thread caches.
//
// TPU-native equivalent of the reference's shared_pool (include/util/
// shared_pool.h:15 — global pool with per-CPU caches feeding the hot
// engine loops). Objects recycle through a per-thread magazine; refills and
// flushes hit the mutex-guarded global list in batches, so the steady-state
// alloc/free path takes no lock. Thread caches reference the pool's core
// through a weak_ptr, so pools and threads may die in either order.

#pragma once

#include <cstddef>
#include <memory>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace uccl_tpu {

template <typename T>
class SharedPool {
  struct Core {
    std::mutex mtx;
    std::vector<T*> global;
    size_t magazine;
    ~Core() {
      for (T* p : global) delete p;
    }
  };

  struct Cache {
    std::weak_ptr<Core> core;
    std::vector<T*> items;
    ~Cache() {  // thread exit: hand items back (or free if pool is gone)
      if (auto c = core.lock()) {
        std::lock_guard<std::mutex> lk(c->mtx);
        for (T* p : items) c->global.push_back(p);
      } else {
        for (T* p : items) delete p;
      }
    }
  };

 public:
  explicit SharedPool(size_t magazine = 32)
      : core_(std::make_shared<Core>()) {
    core_->magazine = magazine ? magazine : 32;
  }

  SharedPool(const SharedPool&) = delete;
  SharedPool& operator=(const SharedPool&) = delete;

  T* get() {
    Cache& cache = tls();
    if (cache.items.empty()) refill(cache.items);
    if (cache.items.empty()) return new T();  // pool dry: allocate fresh
    T* p = cache.items.back();
    cache.items.pop_back();
    return p;
  }

  void put(T* p) {
    Cache& cache = tls();
    cache.items.push_back(p);
    if (cache.items.size() >= 2 * core_->magazine) flush(cache.items);
  }

  size_t global_size() {
    std::lock_guard<std::mutex> lk(core_->mtx);
    return core_->global.size();
  }

 private:
  Cache& tls() {
    // Memoize the last-used pool per thread: the steady-state get()/put()
    // path (one pool instance, by far the common case) skips the map.
    thread_local const void* last_key = nullptr;
    thread_local Cache* last_cache = nullptr;
    if (last_key == core_.get() && last_cache != nullptr) return *last_cache;
    thread_local std::unordered_map<const void*, Cache> caches;
    Cache& c = caches[core_.get()];
    if (c.core.expired()) c.core = core_;
    last_key = core_.get();
    last_cache = &c;
    return c;
  }

  void refill(std::vector<T*>& items) {
    std::lock_guard<std::mutex> lk(core_->mtx);
    size_t take = core_->magazine < core_->global.size()
                      ? core_->magazine
                      : core_->global.size();
    for (size_t i = 0; i < take; ++i) {
      items.push_back(core_->global.back());
      core_->global.pop_back();
    }
  }

  void flush(std::vector<T*>& items) {
    std::lock_guard<std::mutex> lk(core_->mtx);
    while (items.size() > core_->magazine) {
      core_->global.push_back(items.back());
      items.pop_back();
    }
  }

  std::shared_ptr<Core> core_;
};

}  // namespace uccl_tpu
