// Loadable network-plugin ABI: an NCCL-net-shaped C vtable over the engine.
//
// The reference ships its transport as a loadable NCCL net plugin
// (collective/rdma/nccl_plugin.cc: pluginInit/Listen/Connect/Accept/RegMr/
// Isend/Irecv/Test/Close exported as the `ncclNetPlugin_v8` vtable symbol,
// selected via NCCL_NET_PLUGIN=libnccl-net-uccl.so). TPU hosts run no NCCL,
// so binary compatibility with NCCL is meaningless here — what carries over
// is the *shape*: a dlopen-able .so exporting one versioned struct of C
// function pointers, opaque listen handles shipped out-of-band by the caller,
// comm/mr/request objects owned by the plugin, and nonblocking test()
// completion. Anything that can drive an NCCL-style net plugin (a collective
// runtime, a test harness, a future interop shim) can drive this over the
// DCN engine.
//
// Semantics:
//  * isend copies the payload into the engine tx queue — a request is
//    complete when the user buffer is reusable (NCCL's contract), and the
//    framed-TCP engine below guarantees in-order delivery or connection
//    death.
//  * irecv posts (buffer, size, tag); test() drains engine messages and
//    tag-matches, failing the request if the arrived message exceeds the
//    posted size.
//  * listen handles carry {ip, port, listen_id}; connect() sends a hello
//    naming the listen_id so concurrent listens (one per NCCL channel, in
//    the reference's world) accept their own peers even when connections
//    land interleaved.

#pragma once

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define UCCLT_NET_HANDLE_BYTES 128
#define UCCLT_NET_OK 0
#define UCCLT_NET_ERR (-1)

typedef struct {
  char name[32];       // device name
  int speed_mbps;      // advertised link speed
  int port;            // listen port of the underlying endpoint
  int max_comms;       // soft cap on simultaneous comms
  int max_recvs;       // irecv batch width (1 in v1)
  int reg_is_global;   // mr handles valid across comms on this device
  // Fields below were added after the first ucclt_net_v1 export and are
  // therefore APPENDED: a consumer compiled against the original v1 layout
  // still reads every field above at its old offset. Any future layout
  // change that cannot append must bump the exported vtable symbol.
  char addr[64];       // the NIC address this device binds (dial target)
} ucclt_net_props_t;

typedef struct {
  const char* name;  // "uccl_tpu_dcn"

  int (*init)(void);
  int (*devices)(int* ndev);
  int (*get_properties)(int dev, ucclt_net_props_t* props);

  // handle: caller-provided UCCLT_NET_HANDLE_BYTES buffer, filled by listen
  // and shipped out-of-band (verbatim bytes) to the connecting side.
  int (*listen)(int dev, void* handle, void** listen_comm);
  int (*connect)(int dev, const void* handle, void** send_comm);
  int (*accept)(void* listen_comm, void** recv_comm);

  int (*reg_mr)(void* comm, void* data, size_t size, int type,
                void** mhandle);
  int (*dereg_mr)(void* comm, void* mhandle);

  int (*isend)(void* send_comm, const void* data, size_t size, uint64_t tag,
               void* mhandle, void** request);
  int (*irecv)(void* recv_comm, void* data, size_t size, uint64_t tag,
               void* mhandle, void** request);
  // done=1 when terminal; *size = delivered bytes (recv) or queued bytes
  // (send). Returns UCCLT_NET_ERR for a failed request. A done/failed
  // request is freed by this call.
  int (*test)(void* request, int* done, size_t* size);
  // No GPUDirect analog on the DCN path: completion already implies host
  // visibility, so iflush returns a pre-completed request (kept for shape
  // parity with the reference vtable).
  int (*iflush)(void* recv_comm, void* data, size_t size, void* mhandle,
                void** request);

  int (*close_send)(void* send_comm);
  int (*close_recv)(void* recv_comm);
  int (*close_listen)(void* listen_comm);
  int (*finalize)(void);
} ucclt_net_v1_t;

// The exported vtable (dlsym "ucclt_net_v1").
extern const ucclt_net_v1_t ucclt_net_v1;

#ifdef __cplusplus
}  // extern "C"
#endif
