// Cache-line RPC channel: fixed 64-byte messages, seq-stamped slots.
//
// TPU-native equivalent of the reference's lrpc channels (include/util/
// lrpc.h:18, Barrelfish-style): one cache line per message; the producer
// stamps a monotonically increasing sequence into the line's header word,
// the consumer spins on the stamp of ITS next slot — the data-ready check
// touches only the message line itself (no head/tail ping-pong), which is
// the property that makes lrpc the right primitive for ultra-hot control
// paths (doorbells, completions). The consumer additionally publishes a
// consumed counter the producer reads only when a slot might still be in
// use, i.e. once per lap.

#pragma once

#include <atomic>
#include <cstdint>
#include <cstring>
#include <vector>

namespace uccl_tpu {

// One cache line: 8-byte sequence stamp + 56 bytes of payload.
struct alignas(64) LrpcMsg {
  std::atomic<uint64_t> seq{0};  // 0 = never written; else 1-based msg index
  uint8_t data[56];
};
static_assert(sizeof(LrpcMsg) == 64, "LrpcMsg must be one cache line");

constexpr size_t kLrpcPayload = sizeof(LrpcMsg::data);

// SPSC channel over a ring of stamped cache lines.
class LrpcChannel {
 public:
  explicit LrpcChannel(size_t capacity_pow2 = 128) {
    if (capacity_pow2 == 0 || (capacity_pow2 & (capacity_pow2 - 1)) != 0) {
      capacity_pow2 = 128;
    }
    slots_ = std::vector<LrpcMsg>(capacity_pow2);
    mask_ = capacity_pow2 - 1;
  }

  // Producer. False when the ring is full (consumer a full lap behind).
  bool send(const void* payload, size_t len) {
    if (len > kLrpcPayload) return false;
    const uint64_t idx = next_send_;
    const size_t cap = mask_ + 1;
    if (idx >= cap &&
        consumed_.load(std::memory_order_acquire) < idx - cap + 1) {
      return false;  // slot (idx % cap) still holds an unconsumed message
    }
    LrpcMsg& m = slots_[idx & mask_];
    std::memcpy(m.data, payload, len);
    if (len < kLrpcPayload) {
      std::memset(m.data + len, 0, kLrpcPayload - len);
    }
    m.seq.store(idx + 1, std::memory_order_release);  // publish
    next_send_ = idx + 1;
    return true;
  }

  // Consumer. False when no new message. The ready check reads only the
  // target cache line.
  bool recv(void* out, size_t len) {
    const uint64_t idx = next_recv_;
    LrpcMsg& m = slots_[idx & mask_];
    if (m.seq.load(std::memory_order_acquire) != idx + 1) return false;
    std::memcpy(out, m.data, len > kLrpcPayload ? kLrpcPayload : len);
    consumed_.store(idx + 1, std::memory_order_release);
    next_recv_ = idx + 1;
    return true;
  }

  size_t capacity() const { return mask_ + 1; }

 private:
  std::vector<LrpcMsg> slots_;
  uint64_t mask_ = 0;
  uint64_t next_send_ = 0;                // producer-local
  uint64_t next_recv_ = 0;                // consumer-local
  std::atomic<uint64_t> consumed_{0};     // consumer progress (per-lap read)
};

}  // namespace uccl_tpu
