// P2P transfer engine: endpoint, memory registry, proxy threads.
//
// TPU-native redesign of the reference's p2p/engine.{h,cc} Endpoint
// (engine.h:243-499: conn/MR registries, TCP OOB exchange, task rings + proxy
// threads, one-sided read/write + async + vectorized, advertise() FifoItem
// handshake). On TPU there is no user-programmable NIC RDMA under the
// collectives, but the DCN (host network) side carries over: this engine owns
// the wire with a framed TCP protocol, background send-proxy + IO threads, and
// one-sided semantics against *advertised* registered buffers. TPU HBM arrays
// reach it through host staging in the Python layer (dlpack/numpy), the analog
// of the reference's GPU staging.

#pragma once

#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "uccl_tpu/pool.h"
#include "uccl_tpu/ring.h"

namespace uccl_tpu {

// 64-byte advertised-buffer descriptor, the moral equivalent of the
// reference's FifoItem (p2p/util/common.h:75: addr/size/rkey/rid). Each
// advertise() mints a *window* with its own id + token, so a peer holding one
// FifoItem can only touch the advertised byte range, never the rest of the
// registration.
struct FifoItem {
  uint64_t rid;        // window id (stands in for addr+rkey)
  uint64_t size;       // advertised byte length
  uint64_t token;      // random token guarding the window
  uint64_t offset;     // reserved (window-relative transfers start at 0)
  uint8_t pad[32];
};
static_assert(sizeof(FifoItem) == 64, "FifoItem must stay 64 bytes");

// Lock-free power-of-two latency histogram — the role of the reference's
// include/util/latency.h percentile tracker wired into the transport hot
// loops (collective/rdma/transport.cc:1797 stats thread). record() costs one
// CLZ + two relaxed increments; percentiles are derived off the hot path
// (bucket b spans [2^b, 2^(b+1)) ns, so a percentile is exact to 2x).
struct LatHist {
  std::atomic<uint64_t> buckets[64] = {};
  std::atomic<uint64_t> count{0};
  void record(uint64_t ns) {
    int b = 63 - __builtin_clzll(ns | 1);
    buckets[b].fetch_add(1, std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
  }
  // Upper edge (ns) of the bucket holding the p-th percentile; 0 when empty.
  uint64_t percentile_ns(double p) const {
    uint64_t n = count.load(std::memory_order_relaxed);
    if (n == 0) return 0;
    double target = n * p / 100.0;
    uint64_t acc = 0;
    for (int b = 0; b < 64; ++b) {
      acc += buckets[b].load(std::memory_order_relaxed);
      if (static_cast<double>(acc) >= target) return 2ull << b;
    }
    return ~0ull;
  }
};

enum class Op : uint16_t {
  kWrite = 1,      // payload lands in advertised region
  kWriteAck = 2,   // completion notification back to the writer
  kRead = 3,       // request remote advertised region
  kReadResp = 4,   // payload answer
  kSend = 5,       // two-sided send (matches a recv() on the peer)
  kNotif = 6,      // out-of-band notification (NIXL notify pattern: a small
                   // tagged message the target drains non-blocking across
                   // ALL conns — reference p2p/uccl_engine.h:20-26,218-226)
  kHello = 7,      // UDP wire handshake: h.offset carries the sender's UDP
                   // data port; always rides TCP (the only frame that does
                   // in UDP wire mode)
};

struct FrameHeader {
  uint32_t magic;
  uint16_t op;
  uint16_t flags;
  uint64_t xfer_id;    // echo for acks / responses
  uint64_t rid;        // target registration
  uint64_t token;
  uint64_t offset;
  uint64_t len;        // payload bytes following this header
};

enum class XferState : int { kPending = 0, kDone = 1, kError = -1 };

class Endpoint {
 public:
  // port==0 picks an ephemeral port (see listen_port()). n_engines is the
  // number of io+tx thread pairs; connections are distributed across engines
  // round-robin (the analog of the reference's UCCL_NUM_ENGINES,
  // collective/rdma/transport_config.h:38 — per-NIC engine threads).
  // listen_ip optionally pins the listener to one interface (multi-tenant
  // hosts); empty/null binds INADDR_ANY.
  //
  // THREAT MODEL (matches the reference's RDMA fabric assumptions): this
  // engine is built for a trusted cluster network. Advertised windows are
  // guarded by per-window 64-bit random tokens — protection against buggy
  // peers and stale descriptors, not against an adversary with TCP reach
  // who can observe traffic. Do not expose the listen port beyond the
  // cluster fabric; on shared hosts, bind to the fabric interface.
  explicit Endpoint(uint16_t port, int n_engines = 2,
                    const char* listen_ip = nullptr);
  ~Endpoint();

  // false if the listen socket could not be bound (port in use, or an
  // unparseable listen_ip).
  bool ok() const { return listen_fd_ >= 0; }
  uint16_t listen_port() const { return listen_port_; }

  // --- connections (reference: Endpoint::connect/accept, engine.h:286-297)
  // local_ip optionally binds the outgoing conn's source address to one
  // interface — the multi-NIC data-path selection knob (reference: per-GPU
  // NIC selection, p2p/rdma/rdma_endpoint.h; here per-path source binding).
  int64_t connect(const std::string& ip, uint16_t port,
                  const char* local_ip = nullptr);  // >=0 conn id
  int64_t accept(int timeout_ms);                   // >=0 conn id
  // Peer address of an established conn ("ip:port" into out); false if the
  // conn is unknown. Lets multipath layers verify per-path NIC placement.
  bool peer_addr(uint64_t conn_id, char* out, size_t cap);
  bool remove_conn(uint64_t conn_id);  // reference: remove_remote_endpoint
  // Wait until every frame send() already queued on the conn has been
  // handed to the kernel socket (tx queue empty), so a subsequent
  // remove_conn/close cannot drop frames whose sends completed ("done"
  // means copied to the tx queue, not transmitted — the graceful-close gap
  // a raw remove_conn leaves). Covers send()-queued frames only: a
  // write_async/read_async task still waiting in the engine ring has not
  // reached the tx queue yet and is not waited for — wait() on its xfer id
  // first. False on conn death or timeout.
  bool flush_conn(uint64_t conn_id, int timeout_ms = 5000);
  // true while the conn is registered and not marked dead — lets pollers
  // distinguish "nothing queued yet" from "peer is gone" (recv() returns -1
  // for both).
  bool conn_alive(uint64_t conn_id);

  // --- memory registry (reference: reg/regv/dereg, engine.h:300-305)
  uint64_t reg(void* ptr, size_t len);
  bool dereg(uint64_t mr_id);

  // --- advertise (reference: advertise[v], engine.h:347-352)
  bool advertise(uint64_t mr_id, size_t offset, size_t len, FifoItem* out);

  // --- one-sided ops (reference: read/write[v][_async], engine.h:308-344)
  // Contract (as in the reference's registered-MR model): `src` must stay
  // valid until the transfer reaches a terminal state — including after a
  // wait() timeout, since the frame may still be queued behind a slow peer.
  // The Python layer enforces this by holding the source array in its
  // in-flight table until poll/wait observes completion or the conn dies.
  uint64_t write_async(uint64_t conn_id, const void* src, size_t len,
                       const FifoItem& item);
  uint64_t read_async(uint64_t conn_id, void* dst, size_t len,
                      const FifoItem& item);
  bool write(uint64_t conn_id, const void* src, size_t len,
             const FifoItem& item);
  bool read(uint64_t conn_id, void* dst, size_t len, const FifoItem& item);
  // Vectorized (reference: writev/readv over descriptor lists,
  // p2p/engine.h:311-344, engine_api.cc:448 XferDescList): n transfers
  // enqueued as ONE batch — one ring pass, one proxy wake — with per-element
  // completion ids written to xids_out[n].
  void writev_async(uint64_t conn_id, const void* const* srcs,
                    const size_t* lens, const FifoItem* items, size_t n,
                    uint64_t* xids_out);
  void readv_async(uint64_t conn_id, void* const* dsts, const size_t* lens,
                   const FifoItem* items, size_t n, uint64_t* xids_out);

  // --- two-sided (reference: send/recv_async family)
  bool send(uint64_t conn_id, const void* buf, size_t len);
  // >=0: bytes copied out. -1: timeout. <=-2: buffer too small, message left
  // queued; required size is -(ret + 2).
  int64_t recv(uint64_t conn_id, void* buf, size_t cap, int timeout_ms);

  // --- out-of-band notifications (reference: NIXL notify,
  // p2p/uccl_engine.h uccl_engine_send_notif/get_notifs). Unlike send/recv
  // these do not pair with a per-conn recv(): the target drains one global
  // queue non-blocking, each message tagged with the source conn id.
  bool send_notif(uint64_t conn_id, const void* buf, size_t len);
  // Pop the oldest pending notification. Returns -1 if none, the message
  // size on success (conn_out receives the source conn), or -(size)-2 if
  // cap is too small (message stays queued).
  int64_t get_notif(uint64_t* conn_out, void* buf, size_t cap);

  // --- completion (reference: poll_async, engine.h:394)
  // Completions are one-shot: the first poll()/wait() observing a terminal
  // state reclaims the entry (bounds memory on long-lived endpoints);
  // subsequent queries for that id return kError.
  XferState poll(uint64_t xfer_id);
  bool wait(uint64_t xfer_id, int timeout_ms);
  // Abandon a transfer the caller will never poll/wait again (e.g. a
  // timed-out chunk being retransmitted): erases the tracking entry in any
  // state so lost-frame xfers — which never complete — cannot accumulate.
  // A late completion of a still-in-flight abandoned id re-inserts a
  // terminal entry (pre-existing behavior, bounded by real completions).
  void reap(uint64_t xfer_id);

  // --- fault injection (reference kTestLoss knobs, transport_config.h:222)
  // TCP mode scopes injection to the one-sided DATA plane (kWrite/kRead/
  // kReadResp/kWriteAck): loss/reorder model a lossy data fabric under a
  // reliable control plane, so two-sided send/notif rendezvous (and the
  // kHello handshake) survive any injected rate. UDP wire mode injects at
  // the packet level instead (engine.cc udp_send_seg_locked).
  void set_drop_rate(double p) { drop_rate_ = p; }
  // Reorder injection: with probability p a data frame is held back in a
  // per-conn stash and released AFTER the next enqueued frame (or after a
  // 2 ms flush deadline), so same-conn frames swap on the wire — chunk
  // writes and their acks land/complete out of order.
  void set_reorder_rate(double p) { reorder_rate_ = p; }
  // Delay jitter: each data frame gets a uniform [0, max_us] not-before
  // stamp; the tx thread holds the conn's queue until the head frame is
  // due (head-of-line, like a genuinely slow path).
  void set_delay_jitter_us(int64_t max_us) { jitter_us_ = max_us; }
  // Per-conn overrides (<0 inherits the endpoint-global knobs): lets a
  // multipath channel make SOME paths lossy/slow while the control path
  // stays clean — the per-path-quality steering testbed.
  bool set_conn_fault(uint64_t conn_id, double drop, double reorder,
                      int64_t jitter_us);

  // --- pacing (reference: Carousel timing wheel, collective/rdma/
  // timing_wheel.h — paces chunk injection; here a token bucket on the tx
  // proxies). bytes_per_sec == 0 disables pacing.
  void set_rate_limit(uint64_t bytes_per_sec) { rate_bps_ = bytes_per_sec; }
  uint64_t rate_limit() const { return rate_bps_.load(); }

  // --- per-conn CC control plane (UDP wire mode; reference: the CC
  // algorithms actuate chunk injection rates per flow,
  // collective/rdma/transport.h:449-533 EventOn* hooks). rate==0 falls back
  // to the endpoint-global token bucket.
  struct ConnStats {
    double rtt_us = 0.0;       // EWMA of ack-sampled RTT
    uint64_t pkts_tx = 0;      // first transmissions
    uint64_t pkts_rtx = 0;     // retransmissions (RTO + SACK-triggered)
    uint64_t pkts_rx = 0;      // data packets received
    uint64_t acks_rx = 0;      // ack packets processed
    uint64_t bytes_unacked = 0;
    uint64_t rate_bps = 0;     // current per-conn pacing rate (0 = global)
    bool udp_active = false;
  };
  bool conn_stats(uint64_t conn_id, ConnStats* out);
  bool set_conn_rate(uint64_t conn_id, uint64_t bytes_per_sec);

  // --- stats
  uint64_t bytes_tx() const { return bytes_tx_.load(); }
  uint64_t bytes_rx() const { return bytes_rx_.load(); }
  // JSON snapshot of per-engine hot-loop stats (frame counts, service
  // latency percentiles, queue depths). Returns bytes written (excl. NUL).
  size_t stats_json(char* out, size_t cap);

 private:
  // One queued outbound frame with send progress. Frames per conn go out in
  // order; progress lets a partially-sent frame resume after EAGAIN.
  struct TxItem {
    FrameHeader h{};
    const void* src = nullptr;   // unowned payload (caller keeps alive)
    std::vector<uint8_t> owned;  // or task-owned payload
    // Payload bytes actually following the header on the wire. NOT always
    // h.len: a kRead frame carries the *requested* length in h.len but no
    // payload bytes at all.
    size_t wire_len = 0;
    uint64_t fail_xfer = 0;      // xfer to fail if the conn dies mid-send
    size_t off = 0;              // bytes of (header+payload) already sent
    bool credited = false;       // stats counted (exactly once per frame)
    uint64_t t_enq_ns = 0;       // enqueue time: tx service-latency sample
    uint64_t t_not_before_ns = 0;  // delay-jitter injection: hold until due
    const uint8_t* payload() const {
      return owned.empty() ? static_cast<const uint8_t*>(src) : owned.data();
    }
    size_t total() const { return sizeof(FrameHeader) + wire_len; }
  };

  // Frame-parser state for ONE ordered byte stream (io thread only): a peer
  // stalling mid-frame just leaves the state parked; the loop never blocks
  // on one conn. TCP conns have one stream; UDP wire mode has a second
  // (the reliability layer delivers an in-order byte stream, and this same
  // parser consumes it — frame semantics are wire-independent).
  struct RxParse {
    enum class Stage : uint8_t { kHdr, kBody };
    Stage stage = Stage::kHdr;
    size_t got = 0;                // bytes of current stage received
    FrameHeader hdr{};
    uint8_t* dst = nullptr;        // zero-copy window target (kWrite)
    uint64_t t0_ns = 0;            // first header byte: rx latency sample
    std::shared_ptr<std::atomic<int>> pin;  // held while dst in flight
    std::vector<uint8_t> buf;      // owned body (non-window ops / sink)
    bool ok = false;               // window resolved for current kWrite
  };

  // UDP wire state (one per conn in UDP wire mode): selective-repeat
  // reliability over an unreliable datagram socket — the layer where the
  // repo's SACK tracking and CC pacing actually deliver the bytes
  // (reference: pcb.h snd_una/snd_nxt/rcv_nxt + kSackBitmapSize=128 SACK
  // bitmaps, collective/rdma/pcb.h:20).
  struct UdpState {
    int ufd = -1;
    std::atomic<bool> active{false};  // hello exchanged, epoll-registered

    // --- sender side (mtx guards everything below it; taken by the tx
    // thread (serialize/packetize/retransmit) and the io thread (acks))
    std::mutex mtx;
    std::vector<uint8_t> ring;     // tx byte ring (power of two)
    uint64_t stream_end = 0;       // bytes serialized into the ring (abs)
    uint64_t sent_end = 0;         // bytes packetized at least once (abs)
    uint64_t una_stream = 0;       // ring tail: bytes cumulatively acked
    struct Seg {                   // one packet in flight
      uint64_t seq = 0;            // packet sequence number
      uint64_t off = 0;            // absolute stream offset
      uint32_t len = 0;
      uint64_t t_tx_ns = 0;        // last (re)transmission time
      uint32_t rtx = 0;            // retransmission count
      bool sacked = false;         // covered by a SACK bit
    };
    std::deque<Seg> inflight;      // seq-ascending
    uint64_t next_seq = 0;
    double srtt_us = 0.0;          // RTT EWMA (7/8)
    // pacing token bucket (per-conn CC actuation point)
    double tokens = 0.0;
    uint64_t t_refill_ns = 0;

    // --- receiver side (io thread only)
    uint64_t rcv_nxt_seq = 0;      // next expected packet seq
    std::map<uint64_t, std::vector<uint8_t>> ooo;  // out-of-order packets

    // --- stats (atomics: read by conn_stats from app threads)
    std::atomic<uint64_t> pkts_tx{0}, pkts_rtx{0}, pkts_rx{0}, acks_rx{0};
    std::atomic<uint64_t> rtt_ewma_us{0};

    ~UdpState() {
      if (ufd >= 0) ::close(ufd);
    }
  };

  struct Conn {
    int fd = -1;
    uint64_t id = 0;
    int engine = 0;  // which engine serves this conn
    // TSAN wire-order fence slot (engine.cc g_wire_order): hash of the
    // NORMALIZED 4-tuple, computed ONCE at registration while the socket
    // is healthy — both ends hash to the same slot, and a later peer abort
    // (getpeername ENOTCONN) can no longer desynchronize the two sides.
    uint32_t wire_slot = 0;

    RxParse rx_tcp;                // TCP stream parser (io thread only)
    RxParse rx_udp;                // UDP-delivered stream parser (io thread)
    std::unique_ptr<UdpState> udp; // present only in UDP wire mode
    std::atomic<uint64_t> rate_bps{0};  // per-conn pacing (0 = global)

    // --- fault-injection overrides (<0 = inherit the endpoint-global
    // knobs). Atomics: set from app threads, read on every enqueue.
    std::atomic<double> fault_drop{-1.0};
    std::atomic<double> fault_reorder{-1.0};
    std::atomic<int64_t> fault_jitter_us{-1};

    // --- tx queue (tx thread drains; any thread appends)
    std::mutex txq_mtx;
    std::deque<TxItem> txq;
    // Reorder-injection stash (txq_mtx guards): frames held back so a later
    // enqueue overtakes them; flushed into txq on the next enqueue or by
    // service_tx after stash_deadline_ns (the tx loop ticks every 1 ms).
    std::deque<TxItem> reorder_stash;
    uint64_t stash_deadline_ns = 0;
    std::atomic<size_t> txq_bytes{0};  // queued wire bytes (backpressure)
    // Set on any fatal condition; ONLY the tx thread then clears the queue
    // and fails its transfers (single-owner teardown — no cross-thread races
    // on queue entries a send may be touching).
    std::atomic<bool> dead{false};

    ~Conn() {
      // Safety net: if the conn dies while a zero-copy receive is parked
      // mid-frame, release the registration pin so dereg() can't hang.
      if (rx_tcp.pin) rx_tcp.pin->fetch_sub(1, std::memory_order_acq_rel);
      if (rx_udp.pin) rx_udp.pin->fetch_sub(1, std::memory_order_acq_rel);
      if (fd >= 0) ::close(fd);
    }
  };
  struct Reg {
    void* ptr = nullptr;
    size_t len = 0;
    // In-flight zero-copy receives targeting this registration. dereg()
    // blocks until it drains so the application can safely free the buffer
    // once dereg returns (the io thread streams payloads into ptr without
    // holding regs_mtx_).
    std::shared_ptr<std::atomic<int>> pins = std::make_shared<std::atomic<int>>(0);
  };
  // An advertised byte range with its own id/token (see FifoItem).
  struct Window {
    uint64_t mr_id = 0;
    size_t offset = 0;
    size_t len = 0;
    uint64_t token = 0;
  };
  struct PendingRead {
    void* dst = nullptr;
    size_t len = 0;
  };
  struct Task {
    uint64_t conn_id = 0;
    Op op = Op::kWrite;
    uint64_t xfer_id = 0;
    const void* src = nullptr;
    size_t len = 0;
    FifoItem item{};
    std::vector<uint8_t> owned;  // payload owned by the task (read responses)
    uint16_t flags = 0;

    void reset() {  // recycle through the task pool without reallocating
      conn_id = 0;
      op = Op::kWrite;
      xfer_id = 0;
      src = nullptr;
      len = 0;
      item = FifoItem{};
      owned.clear();
      // A task freed with a large payload still attached (e.g. a dropped
      // read response) must not pin that memory in the pool forever.
      if (owned.capacity() > (64u << 10)) {
        owned.shrink_to_fit();
      }
      flags = 0;
    }
  };

  // One engine = one epoll/io thread + one tx thread + its task ring. The
  // per-engine split is what lets multiple DCN "paths" (connections) move
  // bytes concurrently — the TPU-framework analog of UCCL's per-NIC engine
  // threads and multipath spraying.
  struct EngineCtx {
    int epoll_fd = -1;
    int wake_fd = -1;
    // multi-producer: any app thread + the io thread submit without a lock
    MpscRing<Task*> ring{4096};
    std::condition_variable cv;
    std::mutex cv_mtx;
    std::thread io_thread;
    std::thread tx_thread;
    // conns served by this engine. Holds strong refs so a conn removed from
    // the public map still gets one final tx pass (fail_txq) before the tx
    // thread prunes it — queued transfers fail fast instead of timing out.
    std::mutex conns_mtx;
    std::vector<std::shared_ptr<Conn>> conns;
    // hot-loop observability (reference transport.cc:1797 stats thread)
    LatHist tx_lat;                       // enqueue → last byte sent
    LatHist rx_lat;                       // first header byte → dispatched
    std::atomic<uint64_t> tx_frames{0};
    std::atomic<uint64_t> rx_frames{0};
  };

  void io_loop(int engine);  // epoll frame dispatch (recv proxy analog)
  void tx_loop(int engine);  // drains that engine's ring (send proxy analog)
  // rx state machine step: drain available bytes without blocking.
  // kDrained = socket empty (hit EAGAIN); kBudget = fairness budget spent
  // with bytes possibly still buffered; kDead = conn died.
  enum class RxResult { kDead, kDrained, kBudget };
  RxResult drain_rx(Conn* c);
  void finish_rx_frame(Conn* c, RxParse& rx);
  // Resolve a just-completed frame header on `rx` (window lookup for
  // kWrite); false = protocol violation, kill the conn. Shared by the TCP
  // socket parser and the UDP stream parser.
  bool on_rx_header(Conn* c, RxParse& rx);

  // --- UDP wire mode (selective repeat + SACK over datagrams) -----------
  // io thread: drain datagrams (data + acks) from the conn's UDP socket.
  RxResult drain_udp(Conn* c);
  // io thread: feed in-order stream bytes through the rx_udp frame parser.
  bool consume_udp_bytes(Conn* c, const uint8_t* p, size_t n);
  // tx thread: serialize queued frames into the ring, packetize within
  // cwnd/pacing, retransmit due segments. false = conn must die.
  bool service_udp_tx(Conn* c);
  void udp_send_ack(Conn* c, uint64_t echo_ts_us);
  // send one segment (first tx or retx); mtx must be held by the caller.
  void udp_send_seg_locked(Conn* c, UdpState& u, UdpState::Seg& s);
  void udp_activate(Conn* c, uint16_t peer_port);  // io thread (kHello)
  void send_hello(const std::shared_ptr<Conn>& c);
  bool wait_udp_active(uint64_t conn_id, int timeout_ms);
  bool udp_mode_ = false;
  // append a frame to the conn's tx queue (applies drop injection) and wake
  // the serving engine's tx thread.
  void enqueue_frame(const std::shared_ptr<Conn>& c, const FrameHeader& h,
                     const void* src, std::vector<uint8_t> owned,
                     uint64_t fail_xfer);
  // nonblocking send of queued frames; returns false when the conn died,
  // sets *blocked when EAGAIN left data queued. tx thread only.
  bool service_tx(Conn* c, bool* blocked);
  bool wait_txq_below(Conn* c, size_t threshold, int timeout_ms);
  // tx thread only: fail + drop every queued frame of a dead conn.
  void fail_txq(Conn* c);
  void conn_error(uint64_t conn_id);
  void handle_frame(Conn* c, const FrameHeader& h,
                    std::vector<uint8_t>& payload);
  std::shared_ptr<Conn> get_conn(uint64_t id);
  void register_conn(const std::shared_ptr<Conn>& c);
  uint64_t new_xfer();
  void complete(uint64_t xfer_id, XferState st);
  void* resolve_window_locked(
      uint64_t wid, uint64_t token, uint64_t offset, uint64_t len,
      std::shared_ptr<std::atomic<int>>* pin_out = nullptr);
  void enqueue_task(Task* t);
  // push a whole batch under one ring lock + one proxy wake
  void enqueue_tasks(Task* const* ts, size_t n);

  int listen_fd_ = -1;
  uint16_t listen_port_ = 0;
  std::atomic<bool> stop_{false};
  std::vector<std::unique_ptr<EngineCtx>> engines_;

  // Periodic stats thread (reference: per-engine stats cadence in
  // transport.cc:1797). Always counts ticks; prints only when
  // UCCL_TPU_ENGINE_STATS=1 (quiet by default). Cadence from
  // UCCL_TPU_ENGINE_STATS_MS (default 2000).
  void stats_loop();
  std::thread stats_thread_;
  std::atomic<uint64_t> stats_ticks_{0};

  std::mutex conns_mtx_;
  // shared_ptr: in-flight senders keep a Conn alive across remove_conn();
  // the fd closes when the last holder drops (Conn::~Conn).
  std::unordered_map<uint64_t, std::shared_ptr<Conn>> conns_;
  std::atomic<uint64_t> next_conn_{1};
  SpscRing<uint64_t> accept_queue_{256};
  std::mutex accept_mtx_;  // accept() may be called from multiple threads

  std::mutex regs_mtx_;
  std::unordered_map<uint64_t, Reg> regs_;
  std::unordered_map<uint64_t, Window> windows_;
  std::atomic<uint64_t> next_reg_{1};
  std::atomic<uint64_t> next_window_{1};

  std::mutex xfers_mtx_;
  std::condition_variable xfers_cv_;
  std::unordered_map<uint64_t, XferState> xfers_;
  std::unordered_map<uint64_t, PendingRead> pending_reads_;
  std::atomic<uint64_t> next_xfer_{1};

  // two-sided receive queues per conn
  std::mutex recvq_mtx_;
  std::condition_variable recvq_cv_;
  std::map<uint64_t, std::deque<std::vector<uint8_t>>> recvq_;
  std::mutex notifq_mtx_;
  std::deque<std::pair<uint64_t, std::vector<uint8_t>>> notifq_;

  std::atomic<uint64_t> bytes_tx_{0};
  std::atomic<uint64_t> bytes_rx_{0};
  std::atomic<double> drop_rate_{0.0};
  std::atomic<double> reorder_rate_{0.0};
  std::atomic<int64_t> jitter_us_{0};
  std::atomic<uint64_t> rate_bps_{0};
  // task recycling (reference: shared_pool feeding the engine hot loops,
  // include/util/shared_pool.h:15) — tasks come from per-thread magazines
  // instead of new/delete per op
  SharedPool<Task> task_pool_;
  // reset at PUT time: a task freed with a large payload attached (e.g. a
  // dropped read response) must shed that memory before it parks in a
  // magazine, not at some future realloc. Pool-fresh tasks are default-
  // constructed, so get() needs no reset.
  Task* alloc_task() { return task_pool_.get(); }
  void free_task(Task* t) {
    t->reset();
    task_pool_.put(t);
  }

  std::mutex pace_mtx_;  // one shared leaky bucket across engines
  std::chrono::steady_clock::time_point pace_next_{};
  void pace(EngineCtx& eng, uint64_t bytes);  // token-bucket wait in tx_loop
};

}  // namespace uccl_tpu
