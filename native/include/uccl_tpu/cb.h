// Fixed-capacity circular buffer (single-owner bookkeeping).
//
// TPU-native equivalent of the reference's include/util/cb.h: a plain
// ring of slots for tracking in-flight work (chunks awaiting acks, recent
// samples) inside one thread — no atomics, unlike ring.h's inter-thread
// SPSC/MPSC queues. Capacity is rounded up to a power of two so indexing
// is a mask.

#pragma once

#include <cstddef>
#include <utility>
#include <vector>

namespace uccl_tpu {

template <typename T>
class CircularBuffer {
 public:
  explicit CircularBuffer(size_t capacity) {
    size_t cap = 1;
    while (cap < capacity) cap <<= 1;
    buf_.resize(cap);
    mask_ = cap - 1;
  }

  size_t capacity() const { return buf_.size(); }
  size_t size() const { return head_ - tail_; }
  bool empty() const { return head_ == tail_; }
  bool full() const { return size() == buf_.size(); }

  // false when full (caller decides: drop, grow elsewhere, or pop first).
  bool push(T v) {
    if (full()) return false;
    buf_[head_++ & mask_] = std::move(v);
    return true;
  }

  // false when empty.
  bool pop(T* out) {
    if (empty()) return false;
    *out = std::move(buf_[tail_++ & mask_]);
    return true;
  }

  // Oldest element (undefined when empty — check first).
  T& front() { return buf_[tail_ & mask_]; }
  // i-th oldest, 0 <= i < size().
  T& at(size_t i) { return buf_[(tail_ + i) & mask_]; }

 private:
  std::vector<T> buf_;
  size_t mask_ = 0;
  size_t head_ = 0;
  size_t tail_ = 0;
};

}  // namespace uccl_tpu
