// NCCL-net-shaped loadable plugin over the DCN engine. See net_plugin.h.

#include "uccl_tpu/net_plugin.h"

#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "uccl_tpu/engine.h"

namespace {

using uccl_tpu::Endpoint;

constexpr uint32_t kHandleMagic = 0x75636e74;  // "ucnt"

struct Handle {
  uint32_t magic;
  uint32_t listen_id;
  uint16_t port;
  char ip[64];
};
static_assert(sizeof(Handle) <= UCCLT_NET_HANDLE_BYTES, "handle too big");

struct ListenComm {
  uint32_t listen_id;
  int dev = 0;
};

// One tagged message as delivered by the engine (tag prefix stripped).
struct TaggedMsg {
  uint64_t tag;
  std::vector<uint8_t> data;
};

struct Comm {
  uint64_t conn_id = 0;
  int dev = 0;  // which plugin device (endpoint) carries this comm
  bool sender = false;
  // recv side: engine messages drained but not yet matched to an irecv
  std::deque<TaggedMsg> unmatched;
};

struct Request {
  enum class Kind { kSend, kRecv, kFlush } kind = Kind::kSend;
  Comm* comm = nullptr;
  void* data = nullptr;
  size_t posted = 0;
  uint64_t tag = 0;
  // terminal state reached at creation (send/flush) or by test() (recv)
  int done = 0;
  int failed = 0;
  size_t size = 0;
};

// The global mutex guards plugin bookkeeping (listen registry, accept
// routing, comm unmatched queues) and is NEVER held across an engine wait
// (accept/recv with a timeout) — those run on a shared_ptr copy of the
// endpoint, which also makes finalize() safe against in-flight calls (the
// last holder destroys the engine).
struct Plugin {
  std::mutex mtx;
  // One logical plugin device per NIC in UCCL_TPU_NIC_LIST (reference:
  // nccl_plugin.cc enumerates one device per RDMA NIC and NCCL schedules
  // across them); unset → one device on UCCL_TPU_HOST_IP/INADDR_ANY. Each
  // device is its own Endpoint whose listener (and, when the list is
  // explicit, outgoing source address) binds to that NIC.
  std::vector<std::string> nic_ips;  // empty string = unbound (default dev)
  bool nic_list_explicit = false;
  std::vector<std::shared_ptr<Endpoint>> eps;
  uint32_t next_listen = 1;
  // listen_id → device it listens on; membership here IS listen liveness
  std::map<uint32_t, int> listen_dev;
  // conns that said hello for a live listen_id nobody accepted yet
  std::map<uint32_t, std::deque<uint64_t>> pending_accepts;
  std::vector<uint8_t> staging;  // drain buffer (under mtx)

  void resolve_nics_locked() {
    if (!nic_ips.empty()) return;
    if (const char* lst = std::getenv("UCCL_TPU_NIC_LIST")) {
      std::string s(lst);
      size_t pos = 0;
      while (pos <= s.size()) {
        size_t comma = s.find(',', pos);
        if (comma == std::string::npos) comma = s.size();
        std::string ip = s.substr(pos, comma - pos);
        if (!ip.empty()) nic_ips.push_back(ip);
        pos = comma + 1;
      }
      nic_list_explicit = !nic_ips.empty();
    }
    if (nic_ips.empty()) {
      const char* ip = std::getenv("UCCL_TPU_HOST_IP");
      nic_ips.push_back(ip != nullptr ? ip : "");
    }
    eps.resize(nic_ips.size());
  }

  int ndev() {
    std::lock_guard<std::mutex> lk(mtx);
    resolve_nics_locked();
    return static_cast<int>(nic_ips.size());
  }

  std::shared_ptr<Endpoint> endpoint_locked(int dev) {
    resolve_nics_locked();
    if (dev < 0 || dev >= static_cast<int>(nic_ips.size())) return nullptr;
    if (!eps[dev]) {
      int n_engines = 2;
      if (const char* e = std::getenv("UCCL_TPU_NET_ENGINES")) {
        n_engines = std::max(1, atoi(e));
      }
      const char* ip = nic_ips[dev].empty() ? nullptr : nic_ips[dev].c_str();
      auto cand = std::make_shared<Endpoint>(0, n_engines, ip);
      if (cand->ok()) eps[dev] = std::move(cand);
    }
    return eps[dev];
  }
  std::shared_ptr<Endpoint> endpoint(int dev) {
    std::lock_guard<std::mutex> lk(mtx);
    return endpoint_locked(dev);
  }
};

Plugin& plugin() {
  static Plugin p;
  return p;
}

bool net_debug() {
  // Cached once: getenv scans environ linearly and drain_comm runs per
  // received message under the plugin mutex.
  static const bool dbg = std::getenv("UCCL_TPU_NET_DEBUG") != nullptr;
  return dbg;
}

// The address a peer should dial for device `dev` (already resolved).
std::string dev_ip_locked(Plugin& p, int dev) {
  if (dev >= 0 && dev < static_cast<int>(p.nic_ips.size()) &&
      !p.nic_ips[dev].empty()) {
    return p.nic_ips[dev];
  }
  const char* ip = std::getenv("UCCL_TPU_HOST_IP");
  return (ip && ip[0]) ? ip : "127.0.0.1";
}

int pi_init(void) {
  return plugin().endpoint(0) ? UCCLT_NET_OK : UCCLT_NET_ERR;
}

int pi_devices(int* ndev) {
  // One logical plugin device per NIC (reference: nccl_plugin.cc reports one
  // device per RDMA NIC and NCCL schedules rings/channels across them).
  // UCCL_TPU_NIC_LIST unset → 1; engine fan-out within a device still comes
  // from its own n_engines io/tx pairs.
  *ndev = plugin().ndev();
  return UCCLT_NET_OK;
}

int pi_get_properties(int dev, ucclt_net_props_t* props) {
  if (!props) return UCCLT_NET_ERR;
  Plugin& p = plugin();
  std::lock_guard<std::mutex> lk(p.mtx);
  auto ep = p.endpoint_locked(dev);
  if (!ep) return UCCLT_NET_ERR;
  std::memset(props, 0, sizeof(*props));
  if (p.nic_list_explicit) {
    std::snprintf(props->name, sizeof(props->name), "uccl_tpu_dcn%d", dev);
  } else {
    std::snprintf(props->name, sizeof(props->name), "uccl_tpu_dcn");
  }
  std::snprintf(props->addr, sizeof(props->addr), "%s",
                dev_ip_locked(p, dev).c_str());
  props->speed_mbps = 100000;  // nominal DCN host link
  props->port = ep->listen_port();
  props->max_comms = 65536;
  props->max_recvs = 1;
  props->reg_is_global = 1;
  return UCCLT_NET_OK;
}

int pi_listen(int dev, void* handle, void** listen_comm) {
  if (!handle || !listen_comm) return UCCLT_NET_ERR;
  Plugin& p = plugin();
  std::lock_guard<std::mutex> lk(p.mtx);
  auto ep = p.endpoint_locked(dev);
  if (!ep) return UCCLT_NET_ERR;
  auto* lc = new ListenComm{p.next_listen++, dev};
  p.listen_dev[lc->listen_id] = dev;
  Handle h{};
  h.magic = kHandleMagic;
  h.listen_id = lc->listen_id;
  h.port = ep->listen_port();
  std::snprintf(h.ip, sizeof(h.ip), "%s", dev_ip_locked(p, dev).c_str());
  std::memset(handle, 0, UCCLT_NET_HANDLE_BYTES);
  std::memcpy(handle, &h, sizeof(h));
  *listen_comm = lc;
  return UCCLT_NET_OK;
}

int pi_connect(int dev, const void* handle, void** send_comm) {
  if (!handle || !send_comm) return UCCLT_NET_ERR;
  Handle h{};
  std::memcpy(&h, handle, sizeof(h));
  if (h.magic != kHandleMagic) return UCCLT_NET_ERR;
  Plugin& p = plugin();
  std::string src;
  std::shared_ptr<Endpoint> ep;
  {
    std::lock_guard<std::mutex> lk(p.mtx);
    ep = p.endpoint_locked(dev);  // null for out-of-range dev
    // bind the outgoing source address to this device's NIC only when the
    // operator gave an explicit list (a default/implicit device must not
    // pin loopback as the source of a cross-host dial)
    if (ep && p.nic_list_explicit) src = p.nic_ips[dev];
  }
  if (!ep) return UCCLT_NET_ERR;
  int64_t conn = ep->connect(h.ip, h.port, src.empty() ? nullptr : src.c_str());
  if (conn < 0) return UCCLT_NET_ERR;
  // hello: route this conn to the right accept() queue on the peer
  uint32_t listen_id = h.listen_id;
  if (!ep->send(static_cast<uint64_t>(conn), &listen_id, sizeof(listen_id))) {
    ep->remove_conn(static_cast<uint64_t>(conn));
    return UCCLT_NET_ERR;
  }
  auto* c = new Comm;
  c->conn_id = static_cast<uint64_t>(conn);
  c->dev = dev;
  c->sender = true;
  *send_comm = c;
  return UCCLT_NET_OK;
}

int pi_accept(void* listen_comm, void** recv_comm) {
  if (!listen_comm || !recv_comm) return UCCLT_NET_ERR;
  auto* lc = static_cast<ListenComm*>(listen_comm);
  Plugin& p = plugin();
  auto ep = p.endpoint(lc->dev);
  if (!ep) return UCCLT_NET_ERR;
  for (int spin = 0; spin < 100; ++spin) {
    {
      std::lock_guard<std::mutex> lk(p.mtx);
      if (!p.listen_dev.count(lc->listen_id)) return UCCLT_NET_ERR;
      auto& q = p.pending_accepts[lc->listen_id];
      if (!q.empty()) {
        auto* c = new Comm;
        c->conn_id = q.front();
        c->dev = lc->dev;
        q.pop_front();
        *recv_comm = c;
        return UCCLT_NET_OK;
      }
    }
    // Engine waits run unlocked so concurrent test()/close on other comms
    // never stall behind a pending accept.
    int64_t conn = ep->accept(100);
    if (conn < 0) continue;
    uint32_t listen_id = 0;
    int64_t n = ep->recv(static_cast<uint64_t>(conn), &listen_id,
                         sizeof(listen_id), 2000);
    std::lock_guard<std::mutex> lk(p.mtx);
    auto ld = p.listen_dev.find(listen_id);
    if (n != sizeof(listen_id) || ld == p.listen_dev.end() ||
        ld->second != lc->dev) {
      // malformed hello, a closed/unknown listen, or a hello for a listen
      // on a DIFFERENT device (its conn lives on this device's endpoint —
      // parking it would hand that listen a conn its endpoint can't serve)
      ep->remove_conn(static_cast<uint64_t>(conn));
      continue;
    }
    p.pending_accepts[listen_id].push_back(static_cast<uint64_t>(conn));
  }
  return UCCLT_NET_ERR;  // nothing arrived for this listen
}

int pi_reg_mr(void* comm, void* data, size_t size, int type, void** mhandle) {
  // The engine's kSend path copies through its own framing; registration is
  // a handle-shaped no-op kept for vtable parity (type mirrors NCCL's
  // host/device flag — only host memory exists on the DCN side).
  (void)comm;
  (void)data;
  (void)size;
  (void)type;
  if (!mhandle) return UCCLT_NET_ERR;
  *mhandle = nullptr;
  return UCCLT_NET_OK;
}

int pi_dereg_mr(void* comm, void* mhandle) {
  (void)comm;
  (void)mhandle;
  return UCCLT_NET_OK;
}

int pi_isend(void* send_comm, const void* data, size_t size, uint64_t tag,
             void* mhandle, void** request) {
  (void)mhandle;
  if (!send_comm || !request || (!data && size)) return UCCLT_NET_ERR;
  auto* c = static_cast<Comm*>(send_comm);
  auto ep = plugin().endpoint(c->dev);
  if (!ep) return UCCLT_NET_ERR;
  // wire format: [tag u64][payload]
  std::vector<uint8_t> framed(sizeof(tag) + size);
  std::memcpy(framed.data(), &tag, sizeof(tag));
  if (size) std::memcpy(framed.data() + sizeof(tag), data, size);
  auto* r = new Request;
  r->kind = Request::Kind::kSend;
  r->comm = c;
  r->posted = size;
  r->size = size;
  if (ep->send(c->conn_id, framed.data(), framed.size())) {
    r->done = 1;  // payload copied into the engine tx queue: buffer reusable
  } else {
    r->done = 1;
    r->failed = 1;
  }
  *request = r;
  return UCCLT_NET_OK;
}

int pi_irecv(void* recv_comm, void* data, size_t size, uint64_t tag,
             void* mhandle, void** request) {
  (void)mhandle;
  if (!recv_comm || !request || (!data && size)) return UCCLT_NET_ERR;
  auto* r = new Request;
  r->kind = Request::Kind::kRecv;
  r->comm = static_cast<Comm*>(recv_comm);
  r->data = data;
  r->posted = size;
  r->tag = tag;
  *request = r;
  return UCCLT_NET_OK;
}

// Drain every queued engine message for this comm into its unmatched list.
// Caller holds the plugin mutex (recv with timeout 0 never blocks).
void drain_comm(Plugin& p, Endpoint* ep, Comm* c) {
  for (;;) {
    if (p.staging.size() < (1u << 16)) p.staging.resize(1u << 16);
    int64_t n = ep->recv(c->conn_id, p.staging.data(), p.staging.size(), 0);
    if (n == -1) return;  // nothing queued
    if (n <= -2) {        // message larger than staging: grow and retry
      p.staging.resize(static_cast<size_t>(-(n + 2)));
      continue;
    }
    if (static_cast<size_t>(n) < sizeof(uint64_t)) continue;  // malformed
    TaggedMsg m;
    std::memcpy(&m.tag, p.staging.data(), sizeof(uint64_t));
    m.data.assign(p.staging.begin() + sizeof(uint64_t),
                  p.staging.begin() + static_cast<size_t>(n));
    if (net_debug()) {
      fprintf(stderr, "[net %d] drained conn=%llu tag=%llu size=%zu\n",
              getpid(), (unsigned long long)c->conn_id,
              (unsigned long long)m.tag, m.data.size());
    }
    c->unmatched.push_back(std::move(m));
  }
}

int pi_test(void* request, int* done, size_t* size) {
  if (!request || !done) return UCCLT_NET_ERR;
  auto* r = static_cast<Request*>(request);
  if (!r->done && r->kind == Request::Kind::kRecv) {
    Plugin& p = plugin();
    std::lock_guard<std::mutex> lk(p.mtx);
    auto ep = p.endpoint_locked(r->comm->dev);
    if (!ep) {
      r->done = 1;
      r->failed = 1;  // engine torn down under a posted recv
    } else {
      // Liveness snapshot BEFORE draining: messages delivered before the
      // conn died are still drained and matched; only when the conn was
      // already dead and nothing matches can nothing ever arrive.
      bool alive = ep->conn_alive(r->comm->conn_id);
      drain_comm(p, ep.get(), r->comm);
      auto& q = r->comm->unmatched;
      for (auto it = q.begin(); it != q.end(); ++it) {
        if (it->tag != r->tag) continue;
        if (it->data.size() > r->posted) {
          r->failed = 1;  // peer sent more than posted (NCCL contract breach)
          if (net_debug()) {
            fprintf(stderr, "[net] recv tag=%llu oversize: got %zu posted %zu\n",
                    (unsigned long long)r->tag, it->data.size(), r->posted);
          }
        } else {
          std::memcpy(r->data, it->data.data(), it->data.size());
          r->size = it->data.size();
        }
        r->done = 1;
        q.erase(it);
        break;
      }
      if (!r->done && !alive) {
        r->done = 1;
        r->failed = 1;  // peer gone, nothing queued: surface the error
        if (net_debug()) {
          fprintf(stderr, "[net] recv tag=%llu: conn %llu dead, %zu unmatched\n",
                  (unsigned long long)r->tag,
                  (unsigned long long)r->comm->conn_id, q.size());
        }
      }
    }
  }
  *done = r->done;
  if (size) *size = r->size;
  int rc = r->failed ? UCCLT_NET_ERR : UCCLT_NET_OK;
  if (r->done) delete r;
  return rc;
}

int pi_iflush(void* recv_comm, void* data, size_t size, void* mhandle,
              void** request) {
  (void)recv_comm;
  (void)data;
  (void)size;
  (void)mhandle;
  if (!request) return UCCLT_NET_ERR;
  // No GPUDirect analog on the DCN path: completion already implies host
  // visibility, so flush is a pre-completed request.
  auto* r = new Request;
  r->kind = Request::Kind::kFlush;
  r->done = 1;
  *request = r;
  return UCCLT_NET_OK;
}

int close_comm(void* comm) {
  if (!comm) return UCCLT_NET_ERR;
  auto* c = static_cast<Comm*>(comm);
  auto ep = plugin().endpoint(c->dev);
  if (ep) {
    // isend "done" means copied to the engine tx queue; NCCL's contract is
    // that completed sends are delivered, so drain the queue into the
    // kernel before tearing the conn down (the kernel finishes delivery
    // after an orderly close).
    if (c->sender) ep->flush_conn(c->conn_id, 2000);
    ep->remove_conn(c->conn_id);
  }
  delete c;
  return UCCLT_NET_OK;
}

int pi_close_send(void* c) { return close_comm(c); }
int pi_close_recv(void* c) { return close_comm(c); }

int pi_close_listen(void* listen_comm) {
  if (!listen_comm) return UCCLT_NET_ERR;
  auto* lc = static_cast<ListenComm*>(listen_comm);
  Plugin& p = plugin();
  std::lock_guard<std::mutex> lk(p.mtx);
  p.listen_dev.erase(lc->listen_id);
  auto it = p.pending_accepts.find(lc->listen_id);
  if (it != p.pending_accepts.end()) {
    // conns queued for this listen will never be accepted: release them
    if (auto ep = p.endpoint_locked(lc->dev)) {
      for (uint64_t conn : it->second) ep->remove_conn(conn);
    }
    p.pending_accepts.erase(it);
  }
  delete lc;
  return UCCLT_NET_OK;
}

int pi_finalize(void) {
  Plugin& p = plugin();
  std::lock_guard<std::mutex> lk(p.mtx);
  // in-flight calls hold shared_ptr copies; the last one destroys. Clearing
  // nic_ips lets a re-init re-read UCCL_TPU_NIC_LIST.
  p.eps.clear();
  p.nic_ips.clear();
  p.nic_list_explicit = false;
  p.listen_dev.clear();
  p.pending_accepts.clear();
  return UCCLT_NET_OK;
}

}  // namespace

extern "C" const ucclt_net_v1_t ucclt_net_v1 = {
    "uccl_tpu_dcn", pi_init,       pi_devices,    pi_get_properties,
    pi_listen,      pi_connect,    pi_accept,     pi_reg_mr,
    pi_dereg_mr,    pi_isend,      pi_irecv,      pi_test,
    pi_iflush,      pi_close_send, pi_close_recv, pi_close_listen,
    pi_finalize,
};
