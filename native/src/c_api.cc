// Plain-C API over the engine for ctypes bindings.
//
// The analog of the reference's p2p/uccl_engine.{h,cc} C API (uccl_engine.h:35:
// engine create/connect/reg/xfer/notify for the NIXL plugin); the Python
// package binds these with ctypes (pybind11/nanobind are not available in this
// environment — see uccl_tpu/p2p/endpoint.py).

#include <cstring>
#include <vector>

#include "uccl_tpu/engine.h"

using uccl_tpu::Endpoint;
using uccl_tpu::FifoItem;
using uccl_tpu::XferState;

extern "C" {

// listen_ip pins the listener to one interface (nullptr/"" = INADDR_ANY).
void* ucclt_create_bound(const char* listen_ip, uint16_t port, int n_engines) {
  auto* ep = new Endpoint(port, n_engines, listen_ip);
  if (!ep->ok()) {  // port in use, or unparseable listen ip
    delete ep;
    return nullptr;
  }
  return ep;
}

void* ucclt_create(uint16_t port, int n_engines) {
  return ucclt_create_bound(nullptr, port, n_engines);
}

void ucclt_destroy(void* ep) { delete static_cast<Endpoint*>(ep); }

uint16_t ucclt_listen_port(void* ep) {
  return static_cast<Endpoint*>(ep)->listen_port();
}

int64_t ucclt_connect(void* ep, const char* ip, uint16_t port) {
  return static_cast<Endpoint*>(ep)->connect(ip, port);
}

// Bind the outgoing conn's source address to local_ip (multi-NIC data-path
// selection); local_ip nullptr/"" behaves like ucclt_connect.
int64_t ucclt_connect_from(void* ep, const char* ip, uint16_t port,
                           const char* local_ip) {
  return static_cast<Endpoint*>(ep)->connect(ip, port, local_ip);
}

// Writes "ip:port" of the conn's peer into out (cap bytes); -1 if unknown.
int ucclt_peer_addr(void* ep, uint64_t conn_id, char* out, size_t cap) {
  return static_cast<Endpoint*>(ep)->peer_addr(conn_id, out, cap) ? 0 : -1;
}

int64_t ucclt_accept(void* ep, int timeout_ms) {
  return static_cast<Endpoint*>(ep)->accept(timeout_ms);
}

int ucclt_remove_conn(void* ep, uint64_t conn_id) {
  return static_cast<Endpoint*>(ep)->remove_conn(conn_id) ? 0 : -1;
}

// 1 = registered and not dead, 0 otherwise
int ucclt_conn_alive(void* ep, uint64_t conn_id) {
  return static_cast<Endpoint*>(ep)->conn_alive(conn_id) ? 1 : 0;
}

uint64_t ucclt_reg(void* ep, void* ptr, size_t len) {
  return static_cast<Endpoint*>(ep)->reg(ptr, len);
}

int ucclt_dereg(void* ep, uint64_t mr) {
  return static_cast<Endpoint*>(ep)->dereg(mr) ? 0 : -1;
}

// out must point at 64 writable bytes (the serialized FifoItem).
int ucclt_advertise(void* ep, uint64_t mr, size_t offset, size_t len,
                    uint8_t* out) {
  FifoItem item;
  if (!static_cast<Endpoint*>(ep)->advertise(mr, offset, len, &item)) return -1;
  std::memcpy(out, &item, sizeof(item));
  return 0;
}

static FifoItem parse_item(const uint8_t* buf) {
  FifoItem item;
  std::memcpy(&item, buf, sizeof(item));
  return item;
}

int ucclt_write(void* ep, uint64_t conn, const void* src, size_t len,
                const uint8_t* fifo) {
  return static_cast<Endpoint*>(ep)->write(conn, src, len, parse_item(fifo))
             ? 0
             : -1;
}

int ucclt_read(void* ep, uint64_t conn, void* dst, size_t len,
               const uint8_t* fifo) {
  return static_cast<Endpoint*>(ep)->read(conn, dst, len, parse_item(fifo))
             ? 0
             : -1;
}

uint64_t ucclt_write_async(void* ep, uint64_t conn, const void* src, size_t len,
                           const uint8_t* fifo) {
  return static_cast<Endpoint*>(ep)->write_async(conn, src, len,
                                                 parse_item(fifo));
}

uint64_t ucclt_read_async(void* ep, uint64_t conn, void* dst, size_t len,
                          const uint8_t* fifo) {
  return static_cast<Endpoint*>(ep)->read_async(conn, dst, len,
                                                parse_item(fifo));
}

// Vectorized transfers over descriptor arrays (reference: XferDescList,
// engine_api.cc:448). fifos is n packed 64-byte FifoItems; xids_out gets n
// per-element completion ids. One engine wake per batch.
void ucclt_writev_async(void* ep, uint64_t conn, const void* const* srcs,
                        const size_t* lens, const uint8_t* fifos, size_t n,
                        uint64_t* xids_out) {
  std::vector<FifoItem> items(n);
  for (size_t i = 0; i < n; ++i) items[i] = parse_item(fifos + i * 64);
  static_cast<Endpoint*>(ep)->writev_async(conn, srcs, lens, items.data(), n,
                                           xids_out);
}

void ucclt_readv_async(void* ep, uint64_t conn, void* const* dsts,
                       const size_t* lens, const uint8_t* fifos, size_t n,
                       uint64_t* xids_out) {
  std::vector<FifoItem> items(n);
  for (size_t i = 0; i < n; ++i) items[i] = parse_item(fifos + i * 64);
  static_cast<Endpoint*>(ep)->readv_async(conn, dsts, lens, items.data(), n,
                                          xids_out);
}

// 0 = pending, 1 = done, -1 = error
int ucclt_poll(void* ep, uint64_t xfer) {
  switch (static_cast<Endpoint*>(ep)->poll(xfer)) {
    case XferState::kPending:
      return 0;
    case XferState::kDone:
      return 1;
    default:
      return -1;
  }
}

int ucclt_wait(void* ep, uint64_t xfer, int timeout_ms) {
  return static_cast<Endpoint*>(ep)->wait(xfer, timeout_ms) ? 0 : -1;
}

void ucclt_reap(void* ep, uint64_t xfer) {
  static_cast<Endpoint*>(ep)->reap(xfer);
}

// NIXL notify pattern (reference p2p/uccl_engine.h:218-226)
int ucclt_send_notif(void* ep, uint64_t conn, const void* buf, size_t len) {
  return static_cast<Endpoint*>(ep)->send_notif(conn, buf, len) ? 0 : -1;
}

int64_t ucclt_get_notif(void* ep, uint64_t* conn_out, void* buf, size_t cap) {
  return static_cast<Endpoint*>(ep)->get_notif(conn_out, buf, cap);
}

int ucclt_send(void* ep, uint64_t conn, const void* buf, size_t len) {
  return static_cast<Endpoint*>(ep)->send(conn, buf, len) ? 0 : -1;
}

int64_t ucclt_recv(void* ep, uint64_t conn, void* buf, size_t cap,
                   int timeout_ms) {
  return static_cast<Endpoint*>(ep)->recv(conn, buf, cap, timeout_ms);
}

void ucclt_set_drop_rate(void* ep, double p) {
  static_cast<Endpoint*>(ep)->set_drop_rate(p);
}

void ucclt_set_reorder_rate(void* ep, double p) {
  static_cast<Endpoint*>(ep)->set_reorder_rate(p);
}

void ucclt_set_delay_jitter_us(void* ep, int64_t max_us) {
  static_cast<Endpoint*>(ep)->set_delay_jitter_us(max_us);
}

int ucclt_set_conn_fault(void* ep, uint64_t conn, double drop, double reorder,
                         int64_t jitter_us) {
  return static_cast<Endpoint*>(ep)->set_conn_fault(conn, drop, reorder,
                                                    jitter_us)
             ? 0
             : -1;
}

void ucclt_set_rate_limit(void* ep, uint64_t bytes_per_sec) {
  static_cast<Endpoint*>(ep)->set_rate_limit(bytes_per_sec);
}

uint64_t ucclt_bytes_tx(void* ep) {
  return static_cast<Endpoint*>(ep)->bytes_tx();
}

uint64_t ucclt_bytes_rx(void* ep) {
  return static_cast<Endpoint*>(ep)->bytes_rx();
}

// Per-engine hot-loop stats snapshot as JSON (reference analog: the periodic
// transport stats, collective/rdma/transport.cc:1797). Returns bytes written.
int64_t ucclt_stats_json(void* ep, char* out, size_t cap) {
  return static_cast<int64_t>(
      static_cast<Endpoint*>(ep)->stats_json(out, cap));
}

// Per-conn transport stats for the CC control plane (UDP wire mode): the
// Python Timely/Swift controllers read RTT/loss from here and actuate
// ucclt_set_conn_rate — the role of the reference's per-flow EventOnRxACK
// CC updates (collective/rdma/transport.h:449-533). POD mirror of
// Endpoint::ConnStats; append-only layout.
typedef struct {
  double rtt_us;
  uint64_t pkts_tx;
  uint64_t pkts_rtx;
  uint64_t pkts_rx;
  uint64_t acks_rx;
  uint64_t bytes_unacked;
  uint64_t rate_bps;
  int32_t udp_active;
  int32_t pad;
} ucclt_conn_stats_t;

int ucclt_conn_stats(void* ep, uint64_t conn_id, ucclt_conn_stats_t* out) {
  Endpoint::ConnStats s;
  if (!static_cast<Endpoint*>(ep)->conn_stats(conn_id, &s)) return -1;
  out->rtt_us = s.rtt_us;
  out->pkts_tx = s.pkts_tx;
  out->pkts_rtx = s.pkts_rtx;
  out->pkts_rx = s.pkts_rx;
  out->acks_rx = s.acks_rx;
  out->bytes_unacked = s.bytes_unacked;
  out->rate_bps = s.rate_bps;
  out->udp_active = s.udp_active ? 1 : 0;
  out->pad = 0;
  return 0;
}

// Block until every queued frame on the conn reached the kernel socket —
// and, on the UDP wire, until every serialized byte was ACKED (see
// Endpoint::flush_conn). 0 = drained; -1 = timeout or dead conn.
int ucclt_flush_conn(void* ep, uint64_t conn_id, int timeout_ms) {
  return static_cast<Endpoint*>(ep)->flush_conn(conn_id, timeout_ms) ? 0 : -1;
}

int ucclt_set_conn_rate(void* ep, uint64_t conn_id, uint64_t bytes_per_sec) {
  return static_cast<Endpoint*>(ep)->set_conn_rate(conn_id, bytes_per_sec)
             ? 0
             : -1;
}

}  // extern "C"
