// Implementation of the P2P transfer engine (see include/uccl_tpu/engine.h).
//
// Threading model mirrors the reference's p2p engine: application threads
// enqueue tasks onto a lock-free ring; a dedicated tx proxy thread owns the
// wire sends (reference send_proxy_thread_func, p2p/engine.cc:2248); one io
// thread owns epoll dispatch of inbound frames (recv proxy, engine.cc:2286).

#include "uccl_tpu/engine.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <random>

namespace uccl_tpu {

namespace {
// Detect ThreadSanitizer under both gcc (__SANITIZE_THREAD__) and clang
// (__has_feature). The wire-order fence and the syscall-read suppression
// below exist purely for the race detector; production builds compile to
// the exact pre-fence code.
#if defined(__SANITIZE_THREAD__)
#define UCCLT_TSAN 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define UCCLT_TSAN 1
#endif
#endif
#ifndef UCCLT_TSAN
#define UCCLT_TSAN 0
#endif

#if UCCLT_TSAN
// Wire-order fence: a kernel TCP socket orders a sender's ::send before the
// peer's matching read, but TSAN cannot see through the socket — under
// single-process loopback a completed transfer's buffer reuse would be
// flagged as a race on the payload pointer. A release RMW BEFORE each
// ::send (bytes cannot reach the peer until the syscall copies them, which
// is after the release) and an acquire load per fully-received frame make
// the real ordering visible to the detector. The one access this cannot
// cover is the syscall's own read of the payload (it follows the release
// by construction), so that read is explicitly ignored — its safety is the
// keepalive contract (source buffers outlive the transfer until a terminal
// state) plus kernel ordering, the exact invariant the Python/channel
// layers enforce.
//
// SCOPING: one global atomic would add happens-before edges between ALL
// threads touching ANY connection, masking unrelated real races from the
// detector. Instead the fence is an array slot keyed by the connection's
// NORMALIZED 4-tuple hash — both ends of one socket compute the same slot
// (addresses sorted), so edges form (essentially) only along the real
// kernel-ordered channel; hash collisions can only ADD edges, never remove
// detection of the fenced pair.
std::atomic<uint64_t> g_wire_order[256];
extern "C" void AnnotateIgnoreReadsBegin(const char* f, int l);
extern "C" void AnnotateIgnoreReadsEnd(const char* f, int l);
#define UCCLT_WIRE_RELEASE(slot) \
  g_wire_order[slot].fetch_add(1, std::memory_order_release)
#define UCCLT_WIRE_ACQUIRE(slot) \
  ((void)g_wire_order[slot].load(std::memory_order_acquire))
#define UCCLT_TSAN_IGNORE_READS_BEGIN() \
  AnnotateIgnoreReadsBegin(__FILE__, __LINE__)
#define UCCLT_TSAN_IGNORE_READS_END() AnnotateIgnoreReadsEnd(__FILE__, __LINE__)
#else
#define UCCLT_WIRE_RELEASE(slot) ((void)0)
#define UCCLT_WIRE_ACQUIRE(slot) ((void)0)
#define UCCLT_TSAN_IGNORE_READS_BEGIN() ((void)0)
#define UCCLT_TSAN_IGNORE_READS_END() ((void)0)
#endif

constexpr uint32_t kMagic = 0x7C71u;

// --- UDP wire mode ---------------------------------------------------------
// Packet header for the unreliable-datagram data path. Reliability is
// packet-seq selective repeat with 128-bit SACK bitmaps (the reference's PCB
// shape: snd_una/snd_nxt/rcv_nxt + kSackBitmapSize=128,
// collective/rdma/pcb.h:20). Data packets carry consecutive bytes of the
// conn's frame stream; the receiver releases them IN SEQ ORDER into the same
// frame parser the TCP path uses, so frame semantics are wire-independent.
constexpr uint32_t kUdpMagic = 0x7C72u;
struct UdpPktHdr {
  uint32_t magic;
  uint8_t kind;  // 0 = data, 1 = ack
  uint8_t pad[3];
  uint64_t seq;     // data: packet seq | ack: cumulative (next expected seq)
  uint64_t ts_us;   // data: tx timestamp | ack: echo of the trigger packet
  uint64_t sack0;   // ack: bit i => packet (cum+1+i) received (i in 0..63)
  uint64_t sack1;   // ack: bits 64..127
  uint32_t len;     // data payload bytes
  uint32_t zero;
};
static_assert(sizeof(UdpPktHdr) == 48, "UdpPktHdr layout");

int env_int(const char* name, int dflt) {
  const char* v = std::getenv(name);
  return (v != nullptr && v[0] != '\0') ? std::atoi(v) : dflt;
}

// Tunables (reference transport_config.h idiom: env-overridable knobs).
size_t udp_pkt_bytes() {
  static const size_t v = static_cast<size_t>(
      std::max(512, env_int("UCCL_TPU_UDP_PKT_BYTES", 8192)));
  return v;
}
size_t udp_ring_bytes() {
  static const size_t v = [] {
    size_t want = static_cast<size_t>(
        std::max(1 << 16, env_int("UCCL_TPU_UDP_RING_BYTES", 4 << 20)));
    size_t p = 1;
    while (p < want) p <<= 1;
    return p;
  }();
  return v;
}
size_t udp_cwnd_pkts() {
  static const size_t v = static_cast<size_t>(
      std::max(4, env_int("UCCL_TPU_UDP_CWND", 256)));
  return v;
}
uint64_t udp_rto_min_us() {
  static const uint64_t v = static_cast<uint64_t>(
      std::max(200, env_int("UCCL_TPU_UDP_RTO_US", 2000)));
  return v;
}
// consecutive retransmissions of one segment before the conn is declared
// dead (reference kRTOAbortThreshold=50, transport_config.h:202)
constexpr uint32_t kUdpRtxAbort = 50;
constexpr size_t kUdpMaxOoo = 4096;  // out-of-order packets held per conn
// Upper bound on a single frame payload — rejects absurd lengths from a buggy
// or malicious peer before any allocation happens.
constexpr uint64_t kMaxFrameLen = 1ull << 30;
// Per-conn tx queue watermark: above this, two-sided send() blocks (caller
// backpressure, like the old blocking send path) and read responses to a
// non-draining requester are dropped (it times out; it wasn't reading).
constexpr size_t kTxqHighWater = 64ull << 20;
// Max bytes drained from ONE conn per epoll event: a fast sender pumping a
// large frame refills the kernel buffer faster than EAGAIN can fire, and an
// unbudgeted drain would serve that conn forever while the listener and
// every other conn on the engine starve. Level-triggered epoll re-reports
// the fd immediately, so the io loop round-robins at this granularity.
constexpr size_t kRxBudgetPerEvent = 4ull << 20;

void set_nonblocking(int fd) {
  int flags = ::fcntl(fd, F_GETFL, 0);
  if (flags >= 0) ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

uint64_t random_token() {
  static thread_local std::mt19937_64 gen{std::random_device{}()};
  return gen();
}

// Fence slot for a connected fd: hash of the normalized 4-tuple so both
// ends of one socket agree (see g_wire_order). On syscall failure falls
// back to slot 0 — a collision can only ADD detector edges. Computed once
// per connection at registration (the 4-tuple is immutable afterwards).
[[maybe_unused]] uint32_t wire_slot_for_fd(int fd) {
  sockaddr_in a{}, b{};
  socklen_t al = sizeof(a), bl = sizeof(b);
  if (::getsockname(fd, reinterpret_cast<sockaddr*>(&a), &al) != 0 ||
      ::getpeername(fd, reinterpret_cast<sockaddr*>(&b), &bl) != 0) {
    return 0;
  }
  uint64_t x = (static_cast<uint64_t>(a.sin_addr.s_addr) << 16) ^ a.sin_port;
  uint64_t y = (static_cast<uint64_t>(b.sin_addr.s_addr) << 16) ^ b.sin_port;
  uint64_t lo = x < y ? x : y, hi = x < y ? y : x;
  uint64_t h = lo * 0x9E3779B97F4A7C15ull ^ hi;
  return static_cast<uint32_t>((h >> 13) & 255);
}

// Fault-injection coin flip (one definition: frame-level and packet-level
// injection must never diverge silently).
bool should_drop(double p) {
  if (p <= 0.0) return false;
  static thread_local std::mt19937_64 gen{std::random_device{}()};
  std::uniform_real_distribution<double> d(0.0, 1.0);
  return d(gen) < p;
}

// Delay-jitter injection sample: uniform [0, max_us] in nanoseconds.
uint64_t jitter_ns(int64_t max_us) {
  static thread_local std::mt19937_64 gen{std::random_device{}()};
  std::uniform_int_distribution<uint64_t> d(
      0, static_cast<uint64_t>(max_us) * 1000ull);
  return d(gen);
}

uint64_t now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}
}  // namespace

Endpoint::Endpoint(uint16_t port, int n_engines, const char* listen_ip) {
  // Wire selection (both ends must agree; see kHello): "udp" runs the
  // selective-repeat datagram path where the repo's SACK/CC machinery is
  // load-bearing; default stays framed TCP.
  const char* wire = std::getenv("UCCL_TPU_WIRE");
  udp_mode_ = wire != nullptr && std::strcmp(wire, "udp") == 0;
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  bool ip_ok = true;
  if (listen_ip != nullptr && listen_ip[0] != '\0') {
    ip_ok = ::inet_pton(AF_INET, listen_ip, &addr.sin_addr) == 1;
  }
  addr.sin_port = htons(port);
  // Every failure mode falls through to engine creation: a !ok() endpoint
  // must still be safe to call into (engines_ non-empty).
  if (!ip_ok ||
      ::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(listen_fd_, 128) != 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
  } else {
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    listen_port_ = ntohs(addr.sin_port);
  }

  if (n_engines < 1) n_engines = 1;
  for (int e = 0; e < n_engines; ++e) {
    auto ctx = std::make_unique<EngineCtx>();
    ctx->epoll_fd = ::epoll_create1(0);
    ctx->wake_fd = ::eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = 0;  // 0 => wake fd
    ::epoll_ctl(ctx->epoll_fd, EPOLL_CTL_ADD, ctx->wake_fd, &ev);
    if (e == 0 && listen_fd_ >= 0) {
      ev.data.u64 = 1;  // 1 => listener (engine 0 owns accepts)
      ::epoll_ctl(ctx->epoll_fd, EPOLL_CTL_ADD, listen_fd_, &ev);
    }
    engines_.push_back(std::move(ctx));
  }
  for (int e = 0; e < n_engines; ++e) {
    engines_[e]->io_thread = std::thread([this, e] { io_loop(e); });
    engines_[e]->tx_thread = std::thread([this, e] { tx_loop(e); });
  }
  stats_thread_ = std::thread([this] { stats_loop(); });
}

Endpoint::~Endpoint() {
  // Flush: sends are queued asynchronously, so frames an application handed
  // over just before close (e.g. a collective's final DONE control message)
  // may still sit in conn tx queues. Let the tx threads drain them as long
  // as progress is being made; a peer that stopped draining only costs the
  // short no-progress cutoff.
  auto queued = [this]() -> size_t {
    size_t total = 0;
    std::lock_guard<std::mutex> lk(conns_mtx_);
    for (auto& kv : conns_) {
      total += kv.second->txq_bytes.load(std::memory_order_relaxed);
      if (kv.second->udp) {  // UDP: serialized-but-unacked counts as queued
        std::lock_guard<std::mutex> ulk(kv.second->udp->mtx);
        total += kv.second->udp->stream_end - kv.second->udp->una_stream;
      }
    }
    return total;
  };
  size_t last = queued();
  auto last_progress = std::chrono::steady_clock::now();
  while (last > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
    size_t now_q = queued();
    auto now = std::chrono::steady_clock::now();
    if (now_q < last) {
      last = now_q;
      last_progress = now;
    } else if (now - last_progress > std::chrono::milliseconds(250)) {
      break;  // peer stopped draining; don't hold shutdown hostage
    }
  }
  stop_.store(true);
  uint64_t one = 1;
  for (auto& eng : engines_) {
    ::write(eng->wake_fd, &one, sizeof(one));
    eng->cv.notify_all();
  }
  for (auto& eng : engines_) {
    if (eng->io_thread.joinable()) eng->io_thread.join();
    if (eng->tx_thread.joinable()) eng->tx_thread.join();
  }
  if (stats_thread_.joinable()) stats_thread_.join();
  {
    std::lock_guard<std::mutex> lk(conns_mtx_);
    conns_.clear();  // Conn destructors close the fds
  }
  if (listen_fd_ >= 0) ::close(listen_fd_);
  for (auto& eng : engines_) {
    if (eng->epoll_fd >= 0) ::close(eng->epoll_fd);
    if (eng->wake_fd >= 0) ::close(eng->wake_fd);
    Task* t = nullptr;
    while (eng->ring.pop(&t)) free_task(t);
  }
}

int64_t Endpoint::connect(const std::string& ip, uint16_t port,
                          const char* local_ip) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (::inet_pton(AF_INET, ip.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (local_ip && local_ip[0]) {
    // Multi-NIC data-path selection (reference: per-GPU NIC selection and
    // data channels spread across NICs, p2p/rdma/rdma_endpoint.h:117):
    // bind the outgoing conn's source address to the chosen interface.
    sockaddr_in src{};
    src.sin_family = AF_INET;
    src.sin_port = 0;
    if (::inet_pton(AF_INET, local_ip, &src.sin_addr) != 1 ||
        ::bind(fd, reinterpret_cast<sockaddr*>(&src), sizeof(src)) != 0) {
      ::close(fd);
      return -1;
    }
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto c = std::make_shared<Conn>();
  c->fd = fd;
  c->id = next_conn_.fetch_add(1);
  uint64_t id = c->id;
  register_conn(c);
  if (udp_mode_) {
    send_hello(c);
    // The conn is usable only once the datagram path is live on BOTH
    // ends — every post-handshake frame then rides one ordered UDP
    // stream, so TCP/UDP frames can never interleave out of order.
    if (!wait_udp_active(id, env_int("UCCL_TPU_UDP_HELLO_MS", 5000))) {
      remove_conn(id);
      return -1;
    }
  }
  return static_cast<int64_t>(id);
}

// Enqueue the UDP handshake frame (always rides TCP): h.offset carries our
// data port so the peer can aim its datagrams.
void Endpoint::send_hello(const std::shared_ptr<Conn>& c) {
  uint16_t uport = 0;
  if (c->udp && c->udp->ufd >= 0) {
    sockaddr_in a{};
    socklen_t al = sizeof(a);
    if (::getsockname(c->udp->ufd, reinterpret_cast<sockaddr*>(&a), &al) == 0)
      uport = ntohs(a.sin_port);
  }
  FrameHeader h{};
  h.magic = kMagic;
  h.op = static_cast<uint16_t>(Op::kHello);
  h.offset = uport;
  h.len = 0;
  enqueue_frame(c, h, nullptr, {}, 0);
}

bool Endpoint::wait_udp_active(uint64_t conn_id, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    auto c = get_conn(conn_id);
    if (!c || c->dead.load(std::memory_order_relaxed)) return false;
    if (c->udp && c->udp->active.load(std::memory_order_acquire)) return true;
    if (stop_.load() || std::chrono::steady_clock::now() > deadline) {
      return false;
    }
    std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
}

void Endpoint::register_conn(const std::shared_ptr<Conn>& c) {
  c->engine = static_cast<int>(c->id % engines_.size());
#if UCCLT_TSAN
  // populated only for the race detector's wire-order fence; production
  // builds skip the two syscalls and never read the field
  c->wire_slot = wire_slot_for_fd(c->fd);
#endif
  set_nonblocking(c->fd);  // rx state machine + queued tx never block
  if (udp_mode_) {
    auto u = std::make_unique<UdpState>();
    u->ufd = ::socket(AF_INET, SOCK_DGRAM, 0);
    if (u->ufd >= 0) {
      // Bind to the SAME local address family/interface as the TCP conn so
      // multi-NIC path striping keeps working; ephemeral port.
      sockaddr_in self{};
      socklen_t sl = sizeof(self);
      ::getsockname(c->fd, reinterpret_cast<sockaddr*>(&self), &sl);
      self.sin_port = 0;
      ::bind(u->ufd, reinterpret_cast<sockaddr*>(&self), sizeof(self));
      set_nonblocking(u->ufd);
      int buf = 4 << 20;
      ::setsockopt(u->ufd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
      ::setsockopt(u->ufd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
      u->ring.resize(udp_ring_bytes());
      u->t_refill_ns = now_ns();
    }
    c->udp = std::move(u);
  }
  {
    std::lock_guard<std::mutex> lk(conns_mtx_);
    conns_[c->id] = c;
  }
  EngineCtx& eng = *engines_[c->engine];
  {
    std::lock_guard<std::mutex> lk(eng.conns_mtx);
    eng.conns.push_back(c);
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = (c->id << 2) | 2;  // tag 2 => conn
  ::epoll_ctl(eng.epoll_fd, EPOLL_CTL_ADD, c->fd, &ev);
}

int64_t Endpoint::accept(int timeout_ms) {
  std::lock_guard<std::mutex> alk(accept_mtx_);  // queue pop is single-consumer
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  uint64_t id = 0;
  while (!accept_queue_.pop(&id)) {
    if (stop_.load() || std::chrono::steady_clock::now() > deadline) return -1;
    std::this_thread::sleep_for(std::chrono::microseconds(100));
  }
  if (udp_mode_) {
    auto remain = std::chrono::duration_cast<std::chrono::milliseconds>(
        deadline - std::chrono::steady_clock::now());
    // the caller's budget covers the WHOLE accept, handshake included — a
    // short-timeout accept() poll loop must not be held 1s past its ask
    int ms = std::max<int>(1, static_cast<int>(remain.count()));
    if (!wait_udp_active(id, ms)) {
      remove_conn(id);
      return -1;
    }
  }
  return static_cast<int64_t>(id);
}

bool Endpoint::peer_addr(uint64_t conn_id, char* out, size_t cap) {
  auto c = get_conn(conn_id);
  if (!c || cap == 0) return false;
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (::getpeername(c->fd, reinterpret_cast<sockaddr*>(&addr), &len) != 0) {
    return false;
  }
  char ip[INET_ADDRSTRLEN] = {0};
  if (!::inet_ntop(AF_INET, &addr.sin_addr, ip, sizeof(ip))) return false;
  std::snprintf(out, cap, "%s:%u", ip, ntohs(addr.sin_port));
  return true;
}

bool Endpoint::conn_alive(uint64_t conn_id) {
  std::lock_guard<std::mutex> lk(conns_mtx_);
  auto it = conns_.find(conn_id);
  return it != conns_.end() && !it->second->dead.load(std::memory_order_relaxed);
}

bool Endpoint::remove_conn(uint64_t conn_id) {
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(conns_mtx_);
    auto it = conns_.find(conn_id);
    if (it == conns_.end()) return false;
    c = it->second;
    conns_.erase(it);
  }
  // The tx thread (sole queue owner) fails queued transfers on its next
  // pass — the engine's strong conn list keeps the object alive until then.
  c->dead.store(true, std::memory_order_relaxed);
  ::epoll_ctl(engines_[c->engine]->epoll_fd, EPOLL_CTL_DEL, c->fd, nullptr);
  if (c->udp && c->udp->ufd >= 0) {
    ::epoll_ctl(engines_[c->engine]->epoll_fd, EPOLL_CTL_DEL, c->udp->ufd,
                nullptr);
  }
  // Unblock any thread mid-send/recv on this fd; the fd itself closes when
  // the last shared_ptr holder drops (Conn::~Conn), never under a user.
  ::shutdown(c->fd, SHUT_RDWR);
  return true;
}

bool Endpoint::flush_conn(uint64_t conn_id, int timeout_ms) {
  auto c = get_conn(conn_id);
  if (!c) return false;
  if (!wait_txq_below(c.get(), 0, timeout_ms)) return false;
  if (c->udp && c->udp->active.load(std::memory_order_acquire)) {
    // UDP "handed to the kernel" is not enough — flush means every
    // serialized byte was ACKED (the reliability layer's definition of
    // delivered; until then retransmission may still need the ring).
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    while (true) {
      {
        std::lock_guard<std::mutex> lk(c->udp->mtx);
        if (c->udp->una_stream == c->udp->stream_end) break;
      }
      if (c->dead.load() || stop_.load() ||
          std::chrono::steady_clock::now() > deadline) {
        return false;
      }
      std::this_thread::sleep_for(std::chrono::microseconds(200));
    }
  }
  return !c->dead.load();
}

uint64_t Endpoint::reg(void* ptr, size_t len) {
  Reg r{ptr, len};
  uint64_t id = next_reg_.fetch_add(1);
  std::lock_guard<std::mutex> lk(regs_mtx_);
  regs_[id] = r;
  return id;
}

bool Endpoint::dereg(uint64_t mr_id) {
  std::shared_ptr<std::atomic<int>> pins;
  {
    std::lock_guard<std::mutex> lk(regs_mtx_);
    for (auto it = windows_.begin(); it != windows_.end();) {
      if (it->second.mr_id == mr_id) {
        it = windows_.erase(it);
      } else {
        ++it;
      }
    }
    auto rit = regs_.find(mr_id);
    if (rit == regs_.end()) return false;
    pins = rit->second.pins;
    regs_.erase(rit);
  }
  // Drain in-flight zero-copy receives before the caller may free the buffer.
  while (pins->load(std::memory_order_acquire) > 0) {
    std::this_thread::sleep_for(std::chrono::microseconds(50));
  }
  return true;
}

bool Endpoint::advertise(uint64_t mr_id, size_t offset, size_t len,
                         FifoItem* out) {
  std::lock_guard<std::mutex> lk(regs_mtx_);
  auto it = regs_.find(mr_id);
  if (it == regs_.end() || offset > it->second.len ||
      len > it->second.len - offset) {
    return false;
  }
  uint64_t wid = next_window_.fetch_add(1);
  windows_[wid] = Window{mr_id, offset, len, random_token()};
  std::memset(out, 0, sizeof(*out));
  out->rid = wid;
  out->size = len;
  out->token = windows_[wid].token;
  out->offset = 0;
  return true;
}

// Resolve a (window id, token, offset, len) quadruple from the wire into a
// host pointer, enforcing the advertised byte range with overflow-safe math.
// Returns nullptr if anything is off. Caller must hold regs_mtx_.
void* Endpoint::resolve_window_locked(
    uint64_t wid, uint64_t token, uint64_t offset, uint64_t len,
    std::shared_ptr<std::atomic<int>>* pin_out) {
  auto wit = windows_.find(wid);
  if (wit == windows_.end() || wit->second.token != token) return nullptr;
  const Window& w = wit->second;
  if (offset > w.len || len > w.len - offset) return nullptr;
  auto rit = regs_.find(w.mr_id);
  if (rit == regs_.end()) return nullptr;
  if (pin_out != nullptr) {
    // Caller will touch the memory after dropping regs_mtx_: pin so dereg()
    // blocks until the access completes.
    rit->second.pins->fetch_add(1, std::memory_order_acq_rel);
    *pin_out = rit->second.pins;
  }
  return static_cast<uint8_t*>(rit->second.ptr) + w.offset + offset;
}

std::shared_ptr<Endpoint::Conn> Endpoint::get_conn(uint64_t id) {
  std::lock_guard<std::mutex> lk(conns_mtx_);
  auto it = conns_.find(id);
  return it == conns_.end() ? nullptr : it->second;
}

uint64_t Endpoint::new_xfer() {
  uint64_t id = next_xfer_.fetch_add(1);
  std::lock_guard<std::mutex> lk(xfers_mtx_);
  xfers_[id] = XferState::kPending;
  return id;
}

void Endpoint::complete(uint64_t xfer_id, XferState st) {
  {
    std::lock_guard<std::mutex> lk(xfers_mtx_);
    xfers_[xfer_id] = st;
    if (st == XferState::kError) pending_reads_.erase(xfer_id);
  }
  xfers_cv_.notify_all();
}

void Endpoint::enqueue_task(Task* t) {
  enqueue_tasks(&t, 1);
}

void Endpoint::enqueue_tasks(Task* const* ts, size_t n) {
  if (n == 0) return;
  // Route to the engine serving this conn so its tx thread owns the sends
  // (all tasks of one batch target the same conn).
  auto c = get_conn(ts[0]->conn_id);
  EngineCtx& eng = *engines_[c ? c->engine : 0];
  for (size_t i = 0; i < n; ++i) {  // MPSC ring: lock-free from any thread
    while (!eng.ring.push(ts[i])) std::this_thread::yield();
  }
  eng.cv.notify_one();  // one wake for the whole batch
}

uint64_t Endpoint::write_async(uint64_t conn_id, const void* src, size_t len,
                               const FifoItem& item) {
  uint64_t xid = new_xfer();
  if (len > item.size) {  // reject over-window writes before they hit the wire
    complete(xid, XferState::kError);
    return xid;
  }
  Task* t = alloc_task();
  t->conn_id = conn_id;
  t->op = Op::kWrite;
  t->xfer_id = xid;
  t->src = src;
  t->len = len;
  t->item = item;
  enqueue_task(t);
  return xid;
}

uint64_t Endpoint::read_async(uint64_t conn_id, void* dst, size_t len,
                              const FifoItem& item) {
  uint64_t xid = new_xfer();
  if (len > item.size) {
    complete(xid, XferState::kError);
    return xid;
  }
  {
    std::lock_guard<std::mutex> lk(xfers_mtx_);
    pending_reads_[xid] = PendingRead{dst, len};
  }
  Task* t = alloc_task();
  t->conn_id = conn_id;
  t->op = Op::kRead;
  t->xfer_id = xid;
  t->len = len;
  t->item = item;
  enqueue_task(t);
  return xid;
}

void Endpoint::writev_async(uint64_t conn_id, const void* const* srcs,
                            const size_t* lens, const FifoItem* items,
                            size_t n, uint64_t* xids_out) {
  std::vector<Task*> batch;
  batch.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    uint64_t xid = new_xfer();
    xids_out[i] = xid;
    if (lens[i] > items[i].size) {  // reject before it hits the wire
      complete(xid, XferState::kError);
      continue;
    }
    Task* t = alloc_task();
    t->conn_id = conn_id;
    t->op = Op::kWrite;
    t->xfer_id = xid;
    t->src = srcs[i];
    t->len = lens[i];
    t->item = items[i];
    batch.push_back(t);
  }
  enqueue_tasks(batch.data(), batch.size());
}

void Endpoint::readv_async(uint64_t conn_id, void* const* dsts,
                           const size_t* lens, const FifoItem* items,
                           size_t n, uint64_t* xids_out) {
  std::vector<Task*> batch;
  batch.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    uint64_t xid = new_xfer();
    xids_out[i] = xid;
    if (lens[i] > items[i].size) {
      complete(xid, XferState::kError);
      continue;
    }
    {
      std::lock_guard<std::mutex> lk(xfers_mtx_);
      pending_reads_[xid] = PendingRead{dsts[i], lens[i]};
    }
    Task* t = alloc_task();
    t->conn_id = conn_id;
    t->op = Op::kRead;
    t->xfer_id = xid;
    t->len = lens[i];
    t->item = items[i];
    batch.push_back(t);
  }
  enqueue_tasks(batch.data(), batch.size());
}

bool Endpoint::write(uint64_t conn_id, const void* src, size_t len,
                     const FifoItem& item) {
  return wait(write_async(conn_id, src, len, item), 30000);
}

bool Endpoint::read(uint64_t conn_id, void* dst, size_t len,
                    const FifoItem& item) {
  return wait(read_async(conn_id, dst, len, item), 30000);
}

// Poll until the conn's queued tx bytes drop to `threshold` or below;
// false on conn death, endpoint stop, or timeout. Serves both send()'s
// high-water backpressure and flush_conn()'s drain-to-empty.
bool Endpoint::wait_txq_below(Conn* c, size_t threshold, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (c->txq_bytes.load(std::memory_order_relaxed) > threshold) {
    if (c->dead.load() || stop_.load() ||
        std::chrono::steady_clock::now() > deadline) {
      return false;
    }
    std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
  return true;
}

bool Endpoint::send(uint64_t conn_id, const void* buf, size_t len) {
  auto c = get_conn(conn_id);
  if (!c) return false;
  // Backpressure: a peer that stops reading fills its queue to the
  // watermark, then senders block here (the old blocking-send behavior)
  // instead of growing the owned-copy queue without bound.
  if (!wait_txq_below(c.get(), kTxqHighWater, 5000)) return false;
  if (c->dead.load()) return false;
  FrameHeader h{};
  h.magic = kMagic;
  h.op = static_cast<uint16_t>(Op::kSend);
  h.len = len;
  // Copy: the frame outlives this call on the conn's tx queue (delivery
  // failure surfaces as conn death, like any reliable-stream send).
  std::vector<uint8_t> owned(static_cast<const uint8_t*>(buf),
                             static_cast<const uint8_t*>(buf) + len);
  enqueue_frame(c, h, nullptr, std::move(owned), 0);
  return true;
}

int64_t Endpoint::recv(uint64_t conn_id, void* buf, size_t cap,
                       int timeout_ms) {
  std::unique_lock<std::mutex> lk(recvq_mtx_);
  bool ok = recvq_cv_.wait_for(
      lk, std::chrono::milliseconds(timeout_ms),
      [&] { return !recvq_[conn_id].empty() || stop_.load(); });
  if (!ok || recvq_[conn_id].empty()) return -1;
  auto& front = recvq_[conn_id].front();
  if (front.size() > cap) {
    // Leave the message queued; tell the caller the size it needs.
    return -static_cast<int64_t>(front.size()) - 2;
  }
  auto msg = std::move(front);
  recvq_[conn_id].pop_front();
  lk.unlock();
  std::memcpy(buf, msg.data(), msg.size());
  return static_cast<int64_t>(msg.size());
}

bool Endpoint::send_notif(uint64_t conn_id, const void* buf, size_t len) {
  auto c = get_conn(conn_id);
  if (!c) return false;
  if (!wait_txq_below(c.get(), kTxqHighWater, 5000)) return false;
  if (c->dead.load()) return false;
  FrameHeader h{};
  h.magic = kMagic;
  h.op = static_cast<uint16_t>(Op::kNotif);
  h.len = len;
  std::vector<uint8_t> owned(static_cast<const uint8_t*>(buf),
                             static_cast<const uint8_t*>(buf) + len);
  enqueue_frame(c, h, nullptr, std::move(owned), 0);
  return true;
}

int64_t Endpoint::get_notif(uint64_t* conn_out, void* buf, size_t cap) {
  std::lock_guard<std::mutex> lk(notifq_mtx_);
  if (notifq_.empty()) return -1;
  auto& front = notifq_.front();
  if (front.second.size() > cap)
    return -static_cast<int64_t>(front.second.size()) - 2;
  *conn_out = front.first;
  std::memcpy(buf, front.second.data(), front.second.size());
  int64_t n = static_cast<int64_t>(front.second.size());
  notifq_.pop_front();
  return n;
}

void Endpoint::reap(uint64_t xfer_id) {
  std::lock_guard<std::mutex> lk(xfers_mtx_);
  xfers_.erase(xfer_id);
}

XferState Endpoint::poll(uint64_t xfer_id) {
  std::lock_guard<std::mutex> lk(xfers_mtx_);
  auto it = xfers_.find(xfer_id);
  if (it == xfers_.end()) return XferState::kError;
  XferState st = it->second;
  if (st != XferState::kPending) xfers_.erase(it);  // one-shot reclaim
  return st;
}

bool Endpoint::wait(uint64_t xfer_id, int timeout_ms) {
  std::unique_lock<std::mutex> lk(xfers_mtx_);
  bool ok = xfers_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
    auto it = xfers_.find(xfer_id);
    return it == xfers_.end() || it->second != XferState::kPending;
  });
  if (!ok) return false;
  auto it = xfers_.find(xfer_id);
  if (it == xfers_.end()) return false;  // already consumed elsewhere
  XferState st = it->second;
  xfers_.erase(it);  // one-shot reclaim
  return st == XferState::kDone;
}

void Endpoint::enqueue_frame(const std::shared_ptr<Conn>& c,
                             const FrameHeader& h, const void* src,
                             std::vector<uint8_t> owned, uint64_t fail_xfer) {
  // Fault injection: silently drop the frame (reference kTestLoss,
  // transport_config.h:222) — the transfer then times out at the caller.
  // In UDP wire mode injection moves down to the PACKET level (real loss on
  // an unreliable wire, recovered by the reliability layer, not timeouts).
  // TCP-mode injection (drop, reorder, jitter) is scoped to the one-sided
  // DATA plane (kWrite/kRead/kReadResp/kWriteAck): it models a lossy data
  // fabric under a reliable control plane, so send/notif rendezvous and
  // the kHello handshake survive any injected rate. Per-conn overrides
  // (fault_*, <0 = inherit) let a multipath layer fault individual paths.
  Op op = static_cast<Op>(h.op);
  bool data_op = op == Op::kWrite || op == Op::kRead ||
                 op == Op::kReadResp || op == Op::kWriteAck;
  if (!udp_mode_ && data_op) {
    double dr = c->fault_drop.load(std::memory_order_relaxed);
    if (dr < 0.0) dr = drop_rate_.load();
    if (should_drop(dr)) return;
  }
  TxItem it;
  it.h = h;
  it.src = src;
  it.owned = std::move(owned);
  it.wire_len = !it.owned.empty() ? it.owned.size()
              : (src != nullptr ? static_cast<size_t>(h.len) : 0);
  it.fail_xfer = fail_xfer;
  it.t_enq_ns = now_ns();
  double rr = -1.0;
  if (!udp_mode_ && data_op) {
    int64_t jit = c->fault_jitter_us.load(std::memory_order_relaxed);
    if (jit < 0) jit = jitter_us_.load();
    if (jit > 0) it.t_not_before_ns = now_ns() + jitter_ns(jit);
    rr = c->fault_reorder.load(std::memory_order_relaxed);
    if (rr < 0.0) rr = reorder_rate_.load();
  }
  size_t total = it.total();
  {
    std::lock_guard<std::mutex> lk(c->txq_mtx);
    if (rr > 0.0 && should_drop(rr)) {
      // Reorder injection: hold this frame back so the NEXT enqueued
      // frame overtakes it on the wire. push_back-only queue mutation —
      // service_tx holds a reference to txq.front() outside the lock, and
      // deque end-insertion preserves element references. If nothing
      // follows, service_tx force-flushes after the deadline.
      c->reorder_stash.push_back(std::move(it));
      c->stash_deadline_ns = now_ns() + 2000000;  // 2 ms max holdback
    } else {
      c->txq.push_back(std::move(it));
      while (!c->reorder_stash.empty()) {
        c->txq.push_back(std::move(c->reorder_stash.front()));
        c->reorder_stash.pop_front();
      }
    }
  }
  c->txq_bytes.fetch_add(total, std::memory_order_relaxed);
  engines_[c->engine]->cv.notify_one();
}

bool Endpoint::set_conn_fault(uint64_t conn_id, double drop, double reorder,
                              int64_t jitter_us) {
  auto c = get_conn(conn_id);
  if (!c) return false;
  c->fault_drop.store(drop, std::memory_order_relaxed);
  c->fault_reorder.store(reorder, std::memory_order_relaxed);
  c->fault_jitter_us.store(jitter_us, std::memory_order_relaxed);
  return true;
}

// --- UDP wire mode: selective repeat + SACK over datagrams -----------------

namespace {
// ring helpers: absolute stream offsets, power-of-two capacity
inline void ring_copy_in(std::vector<uint8_t>& ring, uint64_t at,
                         const uint8_t* src, size_t n) {
  size_t mask = ring.size() - 1;
  size_t pos = static_cast<size_t>(at) & mask;
  size_t first = std::min(n, ring.size() - pos);
  std::memcpy(ring.data() + pos, src, first);
  if (n > first) std::memcpy(ring.data(), src + first, n - first);
}
}  // namespace

// Send one segment (first transmission or retransmission) as a single
// datagram, scattering straight from the ring (no copy). u.mtx held.
// Packet-level drop injection lives here: in UDP mode a "dropped" frame is
// a lost packet the reliability layer must recover, not a caller timeout.
void Endpoint::udp_send_seg_locked(Conn* c, UdpState& u, UdpState::Seg& s) {
  (void)c;  // kept for symmetry with the other per-conn send paths
  if (should_drop(drop_rate_.load())) return;  // lost; RTO/SACK recovers
  UdpPktHdr h{};
  h.magic = kUdpMagic;
  h.kind = 0;
  h.seq = s.seq;
  h.ts_us = now_ns() / 1000;
  h.len = s.len;
  size_t mask = u.ring.size() - 1;
  size_t pos = static_cast<size_t>(s.off) & mask;
  size_t first = std::min<size_t>(s.len, u.ring.size() - pos);
  iovec iov[3];
  iov[0] = {&h, sizeof(h)};
  iov[1] = {u.ring.data() + pos, first};
  int niov = 2;
  if (first < s.len) {
    iov[2] = {u.ring.data(), s.len - first};
    niov = 3;
  }
  msghdr m{};
  m.msg_iov = iov;
  m.msg_iovlen = niov;
  // EAGAIN/any error == packet lost; the reliability layer recovers.
  ::sendmsg(u.ufd, &m, MSG_DONTWAIT | MSG_NOSIGNAL);
}

// Cumulative + SACK-bitmap acknowledgement (io thread). Receiver-side state
// only; robust to ack loss because every later ack supersedes.
void Endpoint::udp_send_ack(Conn* c, uint64_t echo_ts_us) {
  UdpState& u = *c->udp;
  UdpPktHdr a{};
  a.magic = kUdpMagic;
  a.kind = 1;
  a.seq = u.rcv_nxt_seq;
  a.ts_us = echo_ts_us;
  for (auto& kv : u.ooo) {
    uint64_t rel = kv.first - u.rcv_nxt_seq;
    if (rel >= 1 && rel <= 64) {
      a.sack0 |= 1ull << (rel - 1);
    } else if (rel >= 65 && rel <= 128) {
      a.sack1 |= 1ull << (rel - 65);
    } else if (rel > 128) {
      break;  // ordered map: nothing later fits the bitmap
    }
  }
  ::send(u.ufd, &a, sizeof(a), MSG_DONTWAIT | MSG_NOSIGNAL);
}

// io thread: drain datagrams — data packets feed the in-order stream parser
// (out-of-order ones wait in a bounded map), ack packets drive the sender's
// selective repeat (cumulative advance, SACK marks, RTT samples, dup-ack
// fast retransmit).
Endpoint::RxResult Endpoint::drain_udp(Conn* c) {
  UdpState& u = *c->udp;
  // Sized for the UDP maximum, NOT the local UCCL_TPU_UDP_PKT_BYTES knob:
  // a peer configured with a bigger packet size must not have its datagrams
  // truncated (and then silently discarded) by our recv buffer.
  static thread_local std::vector<uint8_t> buf;
  buf.resize(64 << 10);
  for (int budget = 0; budget < 1024; ++budget) {
    ssize_t n = ::recv(u.ufd, buf.data(), buf.size(), 0);
    if (n < 0) {
      if (errno == EINTR) continue;
      // ECONNREFUSED etc. from ICMP on a connected UDP socket are
      // transient (peer socket not up yet); liveness is the TCP fd's job.
      return RxResult::kDrained;
    }
    if (static_cast<size_t>(n) < sizeof(UdpPktHdr)) continue;
    auto* h = reinterpret_cast<UdpPktHdr*>(buf.data());
    if (h->magic != kUdpMagic) continue;
    if (h->kind == 1) {  // --- ack
      u.acks_rx.fetch_add(1, std::memory_order_relaxed);
      uint64_t now_us_ = now_ns() / 1000;
      std::lock_guard<std::mutex> lk(u.mtx);
      if (h->ts_us != 0 && now_us_ >= h->ts_us) {
        double rtt = static_cast<double>(now_us_ - h->ts_us);
        u.srtt_us = u.srtt_us == 0.0 ? rtt : 0.875 * u.srtt_us + 0.125 * rtt;
        u.rtt_ewma_us.store(static_cast<uint64_t>(u.srtt_us),
                            std::memory_order_relaxed);
      }
      uint64_t cum = h->seq;
      while (!u.inflight.empty() && u.inflight.front().seq < cum) {
        u.una_stream += u.inflight.front().len;
        u.inflight.pop_front();
      }
      uint64_t max_sacked = 0;
      for (auto& s : u.inflight) {
        uint64_t rel = s.seq - cum;
        if (rel >= 1 && rel <= 128) {
          bool bit = rel <= 64 ? ((h->sack0 >> (rel - 1)) & 1)
                               : ((h->sack1 >> (rel - 65)) & 1);
          if (bit) {
            s.sacked = true;
            max_sacked = s.seq;
          }
        }
      }
      if (max_sacked != 0) {
        // Dup-ack-equivalent fast retransmit: 3+ later packets arrived, the
        // gap is very likely loss, not reordering. The one-RTT age guard
        // keeps a burst of acks from retransmitting the same gap again.
        uint64_t now = now_ns();
        uint64_t guard_ns =
            static_cast<uint64_t>(std::max(u.srtt_us, 100.0)) * 1000;
        for (auto& s : u.inflight) {
          if (s.sacked || s.seq + 3 > max_sacked) continue;
          if (now - s.t_tx_ns < guard_ns) continue;
          if (++s.rtx > kUdpRtxAbort) return RxResult::kDead;
          s.t_tx_ns = now;
          udp_send_seg_locked(c, u, s);
          u.pkts_rtx.fetch_add(1, std::memory_order_relaxed);
        }
      }
      continue;
    }
    // --- data
    if (h->len != static_cast<uint32_t>(n) - sizeof(UdpPktHdr)) continue;
    u.pkts_rx.fetch_add(1, std::memory_order_relaxed);
    const uint8_t* payload = buf.data() + sizeof(UdpPktHdr);
    if (h->seq == u.rcv_nxt_seq) {
      if (!consume_udp_bytes(c, payload, h->len)) return RxResult::kDead;
      u.rcv_nxt_seq++;
      while (!u.ooo.empty() && u.ooo.begin()->first == u.rcv_nxt_seq) {
        auto& v = u.ooo.begin()->second;
        if (!consume_udp_bytes(c, v.data(), v.size())) return RxResult::kDead;
        u.ooo.erase(u.ooo.begin());
        u.rcv_nxt_seq++;
      }
    } else if (h->seq > u.rcv_nxt_seq && u.ooo.size() < kUdpMaxOoo &&
               h->seq - u.rcv_nxt_seq <= 4 * udp_cwnd_pkts()) {
      u.ooo.emplace(h->seq,
                    std::vector<uint8_t>(payload, payload + h->len));
    }  // else: duplicate (or absurdly far ahead) — the ack below refreshes
    udp_send_ack(c, h->ts_us);
  }
  return RxResult::kBudget;  // level-triggered epoll re-reports the rest
}

// tx thread: the UDP-mode send service. (1) serialize queued frames into
// the byte ring (frames "sent" == serialized; delivery is the reliability
// layer's job, end-to-end completion still comes from peer acks/responses),
// (2) packetize new bytes within cwnd and the pacing budget, (3) RTO-scan.
bool Endpoint::service_udp_tx(Conn* c) {
  UdpState& u = *c->udp;
  while (true) {
    TxItem* it = nullptr;
    {
      std::lock_guard<std::mutex> lk(c->txq_mtx);
      if (c->txq.empty()) break;
      it = &c->txq.front();
    }
    if (static_cast<Op>(it->h.op) == Op::kHello) {
      // pre-activation frame: finish it on TCP (the peer's handshake waits
      // on these 48 bytes)
      while (it->off < it->total()) {
        const uint8_t* base =
            reinterpret_cast<const uint8_t*>(&it->h) + it->off;
        ssize_t s = ::send(c->fd, base, it->total() - it->off, MSG_NOSIGNAL);
        if (s < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) return true;  // retry
          return false;
        }
        it->off += static_cast<size_t>(s);
      }
    } else {
      if (!it->credited) {
        bytes_tx_.fetch_add(it->total());
        it->credited = true;
      }
      size_t total = it->total();
      bool done = false;
      {
        std::lock_guard<std::mutex> lk(u.mtx);
        uint64_t used = u.stream_end - u.una_stream;
        uint64_t free_space = u.ring.size() - used;
        while (it->off < total && free_space > 0) {
          const uint8_t* base;
          size_t n;
          if (it->off < sizeof(FrameHeader)) {
            base = reinterpret_cast<const uint8_t*>(&it->h) + it->off;
            n = sizeof(FrameHeader) - it->off;
          } else {
            size_t poff = it->off - sizeof(FrameHeader);
            base = it->payload() + poff;
            n = it->wire_len - poff;
          }
          size_t take = std::min<uint64_t>(n, free_space);
          ring_copy_in(u.ring, u.stream_end, base, take);
          u.stream_end += take;
          it->off += take;
          free_space -= take;
        }
        done = it->off >= total;
      }
      if (!done) break;  // ring full until acks free space
    }
    size_t total = it->total();
    uint64_t t_enq = it->t_enq_ns;
    {
      std::lock_guard<std::mutex> lk(c->txq_mtx);
      c->txq.pop_front();
    }
    c->txq_bytes.fetch_sub(total, std::memory_order_relaxed);
    auto& eng = *engines_[c->engine];
    eng.tx_lat.record(now_ns() - t_enq);
    eng.tx_frames.fetch_add(1, std::memory_order_relaxed);
  }

  // packetize + retransmit
  uint64_t now = now_ns();
  std::lock_guard<std::mutex> lk(u.mtx);
  uint64_t rate = c->rate_bps.load(std::memory_order_relaxed);
  if (rate == 0) rate = rate_bps_.load(std::memory_order_relaxed);
  if (rate != 0) {
    double add = static_cast<double>(now - u.t_refill_ns) * rate / 1e9;
    double cap = static_cast<double>(
        std::max<size_t>(udp_pkt_bytes() * 8, 256 << 10));
    u.tokens = std::min(u.tokens + add, cap);
  }
  u.t_refill_ns = now;
  while (u.sent_end < u.stream_end && u.inflight.size() < udp_cwnd_pkts()) {
    uint32_t len = static_cast<uint32_t>(
        std::min<uint64_t>(udp_pkt_bytes(), u.stream_end - u.sent_end));
    if (rate != 0) {
      if (u.tokens < len) break;  // pacing: CC's actuation point
      u.tokens -= len;
    }
    UdpState::Seg s;
    s.seq = u.next_seq++;
    s.off = u.sent_end;
    s.len = len;
    s.t_tx_ns = now;
    udp_send_seg_locked(c, u, s);
    u.sent_end += len;
    u.inflight.push_back(s);
    u.pkts_tx.fetch_add(1, std::memory_order_relaxed);
  }
  uint64_t srtt_ns =
      static_cast<uint64_t>(std::max(u.srtt_us, 50.0)) * 1000;
  for (auto& s : u.inflight) {
    if (s.sacked) continue;
    uint64_t rto_ns = std::max<uint64_t>(4 * srtt_ns,
                                         udp_rto_min_us() * 1000)
                      << std::min<uint32_t>(s.rtx, 5);
    if (now - s.t_tx_ns > rto_ns) {
      if (++s.rtx > kUdpRtxAbort) return false;  // peer unreachable
      s.t_tx_ns = now;
      udp_send_seg_locked(c, u, s);
      u.pkts_rtx.fetch_add(1, std::memory_order_relaxed);
    }
  }
  return true;
}

bool Endpoint::conn_stats(uint64_t conn_id, ConnStats* out) {
  auto c = get_conn(conn_id);
  if (!c || out == nullptr) return false;
  *out = ConnStats{};
  out->rate_bps = c->rate_bps.load(std::memory_order_relaxed);
  if (c->udp) {
    auto& u = *c->udp;
    out->udp_active = u.active.load(std::memory_order_relaxed);
    out->rtt_us = static_cast<double>(
        u.rtt_ewma_us.load(std::memory_order_relaxed));
    out->pkts_tx = u.pkts_tx.load(std::memory_order_relaxed);
    out->pkts_rtx = u.pkts_rtx.load(std::memory_order_relaxed);
    out->pkts_rx = u.pkts_rx.load(std::memory_order_relaxed);
    out->acks_rx = u.acks_rx.load(std::memory_order_relaxed);
    std::lock_guard<std::mutex> lk(u.mtx);
    out->bytes_unacked = u.stream_end - u.una_stream;
  }
  return true;
}

bool Endpoint::set_conn_rate(uint64_t conn_id, uint64_t bytes_per_sec) {
  auto c = get_conn(conn_id);
  if (!c) return false;
  c->rate_bps.store(bytes_per_sec, std::memory_order_relaxed);
  return true;
}

bool Endpoint::service_tx(Conn* c, bool* blocked) {
  if (c->udp && c->udp->active.load(std::memory_order_acquire)) {
    return service_udp_tx(c);  // *blocked stays false: the 1ms tx cadence
                               // doubles as the RTO/pacing clock
  }
  while (true) {
    TxItem* it = nullptr;
    {
      std::lock_guard<std::mutex> lk(c->txq_mtx);
      if (c->txq.empty()) {
        // Reorder-injection stash nothing overtook: force-flush once the
        // holdback deadline passes (this loop ticks every ~1 ms).
        if (c->reorder_stash.empty() || now_ns() < c->stash_deadline_ns)
          return true;
        while (!c->reorder_stash.empty()) {
          c->txq.push_back(std::move(c->reorder_stash.front()));
          c->reorder_stash.pop_front();
        }
      }
      // Safe to use outside the lock: this thread is the sole consumer, and
      // deque push_back never invalidates references to existing elements.
      it = &c->txq.front();
    }
    // Delay-jitter injection: the head frame is not due yet — park the
    // whole queue (head-of-line, like a genuinely slow path) and let the
    // tx loop's 1 ms tick retry.
    if (it->t_not_before_ns != 0 && now_ns() < it->t_not_before_ns)
      return true;
    // Stats credit up front: a peer can receive (and ack) the final bytes
    // while this thread is between its last send syscall and any post-hoc
    // accounting, which would let a completed blocking write observe a
    // stale counter. Counting at transmit-start makes "transfer complete
    // implies counted" a real ordering guarantee (at the price of counting
    // a frame a dying conn never finished — acceptable for stats).
    if (!it->credited) {
      bytes_tx_.fetch_add(it->total());
      it->credited = true;  // EAGAIN re-entries must not credit again
    }
    // Send syscalls run without txq_mtx so app threads can keep enqueueing.
    while (it->off < it->total()) {
      const uint8_t* base;
      size_t n;
      if (it->off < sizeof(FrameHeader)) {
        base = reinterpret_cast<const uint8_t*>(&it->h) + it->off;
        n = sizeof(FrameHeader) - it->off;
      } else {
        size_t poff = it->off - sizeof(FrameHeader);
        base = it->payload() + poff;
        n = it->wire_len - poff;
      }
      // Release precedes the syscall: every prior write to the payload is
      // published before any byte can reach the peer (see g_wire_order).
      UCCLT_WIRE_RELEASE(c->wire_slot);
      UCCLT_TSAN_IGNORE_READS_BEGIN();
      ssize_t s = ::send(c->fd, base, n, MSG_NOSIGNAL);
      UCCLT_TSAN_IGNORE_READS_END();
      if (s < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          *blocked = true;  // kernel buffer full; resume on POLLOUT
          return true;
        }
        return false;
      }
      it->off += static_cast<size_t>(s);
    }
    size_t total = it->total();
    uint64_t t_enq = it->t_enq_ns;
    {
      std::lock_guard<std::mutex> lk(c->txq_mtx);
      c->txq.pop_front();
    }
    c->txq_bytes.fetch_sub(total, std::memory_order_relaxed);
    auto& eng = *engines_[c->engine];
    eng.tx_lat.record(now_ns() - t_enq);
    eng.tx_frames.fetch_add(1, std::memory_order_relaxed);
  }
}

void Endpoint::fail_txq(Conn* c) {
  std::deque<TxItem> q;
  {
    std::lock_guard<std::mutex> lk(c->txq_mtx);
    q.swap(c->txq);
    while (!c->reorder_stash.empty()) {  // stashed frames die with the conn
      q.push_back(std::move(c->reorder_stash.front()));
      c->reorder_stash.pop_front();
    }
  }
  size_t bytes = 0;
  for (auto& it : q) {
    bytes += it.total();
    if (it.fail_xfer != 0) complete(it.fail_xfer, XferState::kError);
  }
  c->txq_bytes.fetch_sub(bytes, std::memory_order_relaxed);
}

// Token-bucket pacing: before a payload send, wait until enough tokens have
// accrued. ONE bucket shared by all engines — the cap is the endpoint's
// aggregate egress regardless of how traffic spreads across paths (reference
// analog: the Carousel timing wheel pacing chunk injection,
// collective/rdma/timing_wheel.h).
void Endpoint::pace(EngineCtx& /*eng*/, uint64_t bytes) {
  uint64_t bps = rate_bps_.load(std::memory_order_relaxed);
  if (bps == 0 || bytes == 0) return;
  const double rate = static_cast<double>(bps);
  constexpr double kBurstS = 0.01;  // at most 10ms of credit after idle
  double wait_s = 0.0;
  {
    // Virtual-time leaky bucket: pace_next_ is when the next byte may go.
    // Exact long-run rate (each send advances it by bytes/rate), bounded
    // burst (it can lag `now` by at most kBurstS).
    std::lock_guard<std::mutex> lk(pace_mtx_);
    auto now = std::chrono::steady_clock::now();
    auto floor = now - std::chrono::duration_cast<
                           std::chrono::steady_clock::duration>(
                           std::chrono::duration<double>(kBurstS));
    if (pace_next_ < floor) pace_next_ = floor;
    pace_next_ += std::chrono::duration_cast<std::chrono::steady_clock::duration>(
        std::chrono::duration<double>(bytes / rate));
    // Wait until this frame's own virtual finish time: a single frame larger
    // than the burst window is paced too, not just its successors.
    wait_s = std::chrono::duration<double>(pace_next_ - now).count();
  }
  // Interruptible sleep: never outlive shutdown by more than one slice.
  while (wait_s > 0.0 && !stop_.load(std::memory_order_relaxed)) {
    double slice = std::min(wait_s, 0.01);
    std::this_thread::sleep_for(std::chrono::duration<double>(slice));
    wait_s -= slice;
  }
}

void Endpoint::tx_loop(int engine) {
  EngineCtx& eng = *engines_[engine];
  while (!stop_.load()) {
    // Phase 1: admit tasks from the ring into per-conn tx queues. Pacing
    // throttles admission (one shared token bucket = aggregate egress cap).
    Task* t = nullptr;
    while (eng.ring.pop(&t)) {
      auto c = get_conn(t->conn_id);
      if (!c || c->dead.load(std::memory_order_relaxed)) {
        // Only locally-initiated ops carry OUR xfer ids; a kReadResp's
        // xfer_id belongs to the remote requester's counter and must never
        // be completed against the local table.
        if (t->xfer_id != 0 && (t->op == Op::kWrite || t->op == Op::kRead)) {
          complete(t->xfer_id, XferState::kError);
        }
        free_task(t);
        continue;
      }
      FrameHeader h{};
      h.magic = kMagic;
      h.op = static_cast<uint16_t>(t->op);
      h.xfer_id = t->xfer_id;
      h.rid = t->item.rid;
      h.token = t->item.token;
      h.offset = t->item.offset;
      h.flags = t->flags;
      if (t->op == Op::kWrite) {
        h.len = t->len;
        pace(eng, t->len);
        enqueue_frame(c, h, t->src, {}, t->xfer_id);
        // completion arrives as kWriteAck
      } else if (t->op == Op::kRead) {
        // kRead frames carry the *requested* length in len, no payload.
        h.len = t->len;
        enqueue_frame(c, h, nullptr, {}, t->xfer_id);
      } else if (t->op == Op::kReadResp) {
        if (c->txq_bytes.load(std::memory_order_relaxed) > kTxqHighWater) {
          // The requester isn't draining its own responses; dropping lets
          // it time out without growing the owned-copy queue unboundedly.
          free_task(t);
          continue;
        }
        h.rid = 0;
        h.token = 0;
        h.offset = 0;
        h.len = t->owned.size();
        pace(eng, h.len);
        enqueue_frame(c, h, nullptr, std::move(t->owned), 0);
      } else if (t->op == Op::kWriteAck) {
        h.rid = 0;
        h.token = 0;
        h.offset = 0;
        h.len = 0;
        enqueue_frame(c, h, nullptr, {}, 0);
      }
      free_task(t);
    }

    // Phase 2: round-robin nonblocking service of every conn with queued
    // frames. One backpressured peer parks with POLLOUT interest; the rest
    // keep moving — no cross-conn head-of-line blocking (the discipline of
    // the reference engine run-loop, transport.cc:443-470).
    std::vector<std::shared_ptr<Conn>> cs;
    {
      std::lock_guard<std::mutex> lk(eng.conns_mtx);
      cs = eng.conns;
    }
    std::vector<pollfd> blocked_fds;
    std::vector<uint64_t> pruned;
    for (auto& c : cs) {
      if (c->dead.load(std::memory_order_relaxed)) {
        fail_txq(c.get());  // tx owns queue cleanup (sole consumer)
        pruned.push_back(c->id);
        continue;
      }
      bool blocked = false;
      if (!service_tx(c.get(), &blocked)) {
        // Socket died mid-send: fail queued transfers and shut the fd down;
        // the io thread observes the error event and finishes teardown.
        c->dead.store(true, std::memory_order_relaxed);
        fail_txq(c.get());
        ::shutdown(c->fd, SHUT_RDWR);
      } else if (blocked) {
        blocked_fds.push_back(pollfd{c->fd, POLLOUT, 0});
      }
    }
    if (!pruned.empty()) {
      std::lock_guard<std::mutex> lk(eng.conns_mtx);
      eng.conns.erase(
          std::remove_if(eng.conns.begin(), eng.conns.end(),
                         [&](const std::shared_ptr<Conn>& c) {
                           return std::find(pruned.begin(), pruned.end(),
                                            c->id) != pruned.end();
                         }),
          eng.conns.end());
    }

    // Phase 3: wait for room on blocked sockets or for new work.
    if (!blocked_fds.empty()) {
      ::poll(blocked_fds.data(), blocked_fds.size(), 1);
    } else {
      std::unique_lock<std::mutex> lk(eng.cv_mtx);
      eng.cv.wait_for(lk, std::chrono::milliseconds(1));
    }
  }
}

void Endpoint::handle_frame(Conn* c, const FrameHeader& h,
                            std::vector<uint8_t>& payload) {
  switch (static_cast<Op>(h.op)) {
    // Op::kWrite is fully handled by io_loop's zero-copy fast path.
    case Op::kWriteAck:
      complete(h.xfer_id, h.flags == 0 ? XferState::kDone : XferState::kError);
      break;
    case Op::kRead: {
      // Copy the window contents into a task-owned buffer and hand the
      // (possibly large, blocking) send to the tx proxy thread.
      Task* t = alloc_task();
      t->conn_id = c->id;
      t->op = Op::kReadResp;
      t->xfer_id = h.xfer_id;
      {
        std::lock_guard<std::mutex> lk(regs_mtx_);
        void* src = resolve_window_locked(h.rid, h.token, h.offset, h.len);
        if (src != nullptr) {
          t->owned.assign(static_cast<uint8_t*>(src),
                          static_cast<uint8_t*>(src) + h.len);
        } else {
          t->flags = 1;
        }
      }
      enqueue_task(t);
      break;
    }
    case Op::kReadResp: {
      PendingRead pr{};
      {
        std::lock_guard<std::mutex> lk(xfers_mtx_);
        auto it = pending_reads_.find(h.xfer_id);
        if (it != pending_reads_.end()) {
          pr = it->second;
          pending_reads_.erase(it);
        }
      }
      if (h.flags == 0 && pr.dst != nullptr && h.len <= pr.len) {
        std::memcpy(pr.dst, payload.data(), h.len);
        complete(h.xfer_id, XferState::kDone);
      } else {
        complete(h.xfer_id, XferState::kError);
      }
      break;
    }
    case Op::kSend: {
      {
        std::lock_guard<std::mutex> lk(recvq_mtx_);
        recvq_[c->id].push_back(std::move(payload));
      }
      recvq_cv_.notify_all();
      break;
    }
    case Op::kNotif: {
      std::lock_guard<std::mutex> lk(notifq_mtx_);
      notifq_.emplace_back(c->id, std::move(payload));
      break;
    }
    case Op::kHello:
      udp_activate(c, static_cast<uint16_t>(h.offset));
      break;
    default:
      break;
  }
}

// kHello arrived (io thread): aim our datagram socket at the peer's UDP
// port and go live. Packets the peer fired before our epoll registration
// sat in the bound socket's buffer and are drained on the first event.
void Endpoint::udp_activate(Conn* c, uint16_t peer_port) {
  if (!c->udp || c->udp->ufd < 0 || peer_port == 0) return;
  if (c->udp->active.load(std::memory_order_relaxed)) return;
  sockaddr_in peer{};
  socklen_t pl = sizeof(peer);
  if (::getpeername(c->fd, reinterpret_cast<sockaddr*>(&peer), &pl) != 0) {
    return;
  }
  peer.sin_port = htons(peer_port);
  if (::connect(c->udp->ufd, reinterpret_cast<sockaddr*>(&peer),
                sizeof(peer)) != 0) {
    return;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = (c->id << 2) | 3;  // tag 3 => conn's UDP data socket
  ::epoll_ctl(engines_[c->engine]->epoll_fd, EPOLL_CTL_ADD, c->udp->ufd, &ev);
  c->udp->active.store(true, std::memory_order_release);
  engines_[c->engine]->cv.notify_one();  // tx may switch to the UDP path
}

// Finish one fully-received frame (io thread only): dispatch by op, release
// the window pin, reset the state machine for the next header.
void Endpoint::finish_rx_frame(Conn* c, RxParse& rx) {
  // Acquire side of the wire-order fence (see g_wire_order): the sender's
  // pre-send writes happen-before everything after this frame's dispatch.
  // (The UDP path does not need it — its completion chain passes through
  // in-process mutexes the detector can see — but the acquire is free.)
  UCCLT_WIRE_ACQUIRE(c->wire_slot);
  const FrameHeader& h = rx.hdr;
  size_t body = (static_cast<Op>(h.op) == Op::kRead) ? 0 : h.len;
  bytes_rx_.fetch_add(sizeof(h) + body);
  auto& eng = *engines_[c->engine];
  eng.rx_lat.record(now_ns() - rx.t0_ns);
  eng.rx_frames.fetch_add(1, std::memory_order_relaxed);
  if (static_cast<Op>(h.op) == Op::kWrite) {
    if (rx.pin) {
      rx.pin->fetch_sub(1, std::memory_order_acq_rel);
      rx.pin.reset();
    }
    Task* ack = alloc_task();
    ack->conn_id = c->id;
    ack->op = Op::kWriteAck;
    ack->xfer_id = h.xfer_id;
    ack->flags = rx.ok ? 0 : 1;
    enqueue_task(ack);
  } else {
    handle_frame(c, h, rx.buf);
  }
  rx.stage = RxParse::Stage::kHdr;
  rx.got = 0;
  rx.dst = nullptr;
  rx.ok = false;
  rx.buf.clear();
}

// A frame header just completed on `rx`: validate and resolve the write
// window (shared by the TCP socket parser and the UDP stream parser).
// false = protocol violation; the caller kills the conn.
bool Endpoint::on_rx_header(Conn* c, RxParse& rx) {
  (void)c;
  const FrameHeader& h = rx.hdr;
  if (h.magic != kMagic || h.len > kMaxFrameLen) return false;
  size_t body = (static_cast<Op>(h.op) == Op::kRead) ? 0 : h.len;
  if (static_cast<Op>(h.op) == Op::kWrite) {
    // Fast path: land write payloads straight into the resolved window —
    // one copy total (the DCN analog of the reference's zero-copy RDMA
    // receive into registered memory). Pin so dereg() waits for us
    // (zero-length writes resolve too — their ack must report success —
    // but take no pin, since no bytes will land).
    void* dst = nullptr;
    std::shared_ptr<std::atomic<int>> pin;
    {
      std::lock_guard<std::mutex> lk(regs_mtx_);
      dst = resolve_window_locked(h.rid, h.token, h.offset, h.len,
                                  body > 0 ? &pin : nullptr);
    }
    if (dst != nullptr) {
      rx.dst = static_cast<uint8_t*>(dst);
      rx.pin = std::move(pin);
      rx.ok = true;
    } else {
      rx.dst = nullptr;
      rx.ok = false;
    }
  }
  return true;
}

// Drain available bytes through the per-conn state machine without ever
// blocking: a peer that stalls mid-frame parks the state until more bytes
// arrive, and every other connection on the engine keeps flowing (the fix
// for the reference-style blocking recv dispatch; ADVICE.md round 1).
Endpoint::RxResult Endpoint::drain_rx(Conn* c) {
  RxParse& rx = c->rx_tcp;
  size_t consumed = 0;
  while (consumed < kRxBudgetPerEvent) {
    if (rx.stage == RxParse::Stage::kHdr) {
      uint8_t* p = reinterpret_cast<uint8_t*>(&rx.hdr);
      while (rx.got < sizeof(FrameHeader)) {
        ssize_t n = ::recv(c->fd, p + rx.got, sizeof(FrameHeader) - rx.got, 0);
        if (n == 0) return RxResult::kDead;
        if (n < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) return RxResult::kDrained;
          return RxResult::kDead;
        }
        if (rx.got == 0) rx.t0_ns = now_ns();  // frame service starts
        rx.got += static_cast<size_t>(n);
        consumed += static_cast<size_t>(n);
      }
      if (!on_rx_header(c, rx)) return RxResult::kDead;
      size_t body =
          (static_cast<Op>(rx.hdr.op) == Op::kRead) ? 0 : rx.hdr.len;
      if (body == 0) {
        finish_rx_frame(c, rx);
        continue;
      }
      if (rx.dst == nullptr) {
        try {
          rx.buf.resize(body);  // owned body (or sink for bad windows)
        } catch (const std::exception&) {
          return RxResult::kDead;
        }
      }
      rx.stage = RxParse::Stage::kBody;
      rx.got = 0;
    }
    // Body stage.
    size_t body = static_cast<size_t>(rx.hdr.len);
    uint8_t* dst = rx.dst != nullptr ? rx.dst : rx.buf.data();
    while (rx.got < body) {
      // Header bytes above may have nudged consumed past the budget;
      // saturating arithmetic, never wrap.
      size_t remaining = consumed < kRxBudgetPerEvent
                             ? kRxBudgetPerEvent - consumed
                             : 0;
      if (remaining == 0) return RxResult::kBudget;
      ssize_t n = ::recv(c->fd, dst + rx.got,
                         std::min(body - rx.got, remaining), 0);
      if (n == 0) return RxResult::kDead;
      if (n < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) return RxResult::kDrained;
        return RxResult::kDead;
      }
      rx.got += static_cast<size_t>(n);
      consumed += static_cast<size_t>(n);
    }
    finish_rx_frame(c, rx);
  }
  return RxResult::kBudget;  // epoll re-reports any bytes still waiting
}

// Feed in-order UDP-delivered stream bytes through the rx_udp frame parser
// (io thread only). Memory-fed twin of drain_rx's socket loop; false = kill.
bool Endpoint::consume_udp_bytes(Conn* c, const uint8_t* p, size_t n) {
  RxParse& rx = c->rx_udp;
  while (n > 0) {
    if (rx.stage == RxParse::Stage::kHdr) {
      if (rx.got == 0) rx.t0_ns = now_ns();
      size_t want = sizeof(FrameHeader) - rx.got;
      size_t take = std::min(want, n);
      std::memcpy(reinterpret_cast<uint8_t*>(&rx.hdr) + rx.got, p, take);
      rx.got += take;
      p += take;
      n -= take;
      if (rx.got < sizeof(FrameHeader)) return true;
      if (!on_rx_header(c, rx)) return false;
      size_t body =
          (static_cast<Op>(rx.hdr.op) == Op::kRead) ? 0 : rx.hdr.len;
      if (body == 0) {
        finish_rx_frame(c, rx);
        continue;
      }
      if (rx.dst == nullptr) {
        try {
          rx.buf.resize(body);
        } catch (const std::exception&) {
          return false;
        }
      }
      rx.stage = RxParse::Stage::kBody;
      rx.got = 0;
      continue;
    }
    size_t body = static_cast<size_t>(rx.hdr.len);
    uint8_t* dst = rx.dst != nullptr ? rx.dst : rx.buf.data();
    size_t take = std::min(body - rx.got, n);
    std::memcpy(dst + rx.got, p, take);
    rx.got += take;
    p += take;
    n -= take;
    if (rx.got == body) finish_rx_frame(c, rx);
  }
  return true;
}

void Endpoint::conn_error(uint64_t conn_id) {
  auto c = get_conn(conn_id);
  if (c) {
    // io thread owns rx state; we run on the io thread
    for (RxParse* rx : {&c->rx_tcp, &c->rx_udp}) {
      if (rx->pin) {
        rx->pin->fetch_sub(1, std::memory_order_acq_rel);
        rx->pin.reset();
      }
    }
    // The tx thread (sole queue consumer) fails + clears the queue on its
    // next pass; touching it here would race a send in progress.
    c->dead.store(true, std::memory_order_relaxed);
  }
  remove_conn(conn_id);
}

void Endpoint::io_loop(int engine) {
  EngineCtx& eng = *engines_[engine];
  constexpr int kMaxEvents = 64;
  epoll_event events[kMaxEvents];
  while (!stop_.load()) {
    int n = ::epoll_wait(eng.epoll_fd, events, kMaxEvents, 100);
    for (int i = 0; i < n; ++i) {
      uint64_t tag = events[i].data.u64;
      if (tag == 0) {  // wake fd
        uint64_t v;
        ::read(eng.wake_fd, &v, sizeof(v));
        continue;
      }
      if (tag == 1) {  // listener (engine 0 only)
        int fd = ::accept(listen_fd_, nullptr, nullptr);
        if (fd < 0) continue;
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        auto c = std::make_shared<Conn>();
        c->fd = fd;
        c->id = next_conn_.fetch_add(1);
        uint64_t id = c->id;
        register_conn(c);
        if (udp_mode_) send_hello(c);  // acceptor's half of the handshake
        if (!accept_queue_.push(id)) {
          // accept backlog overflow: reject the connection rather than leak
          // an id the application can never accept()
          remove_conn(id);
        }
        continue;
      }
      // connection event. Drain BEFORE acting on ERR/HUP: a peer that sent
      // its last frames and closed leaves EPOLLIN|EPOLLHUP with buffered
      // bytes that must still be delivered (drain_rx reports kDead at EOF).
      // A budget-limited drain must NOT act on HUP either — bytes may still
      // be buffered; the level-triggered event re-fires and we resume.
      uint64_t conn_id = tag >> 2;
      auto conn = get_conn(conn_id);
      if (!conn) continue;
      if ((tag & 3) == 3) {  // the conn's UDP data socket
        if (drain_udp(conn.get()) == RxResult::kDead) conn_error(conn_id);
        continue;
      }
      RxResult res = drain_rx(conn.get());
      bool dead = res == RxResult::kDead ||
                  (res == RxResult::kDrained &&
                   (events[i].events & (EPOLLERR | EPOLLHUP)) != 0);
      if (dead) conn_error(conn_id);
    }
  }
}

// JSON snapshot of the hot-loop stats: per-engine frame counts, service
// latency percentiles (µs), queued tx bytes, task-ring depth. The analog of
// the reference's periodic transport stats (transport.cc:1797 +
// include/util/latency.h), readable on demand through the C API.
size_t Endpoint::stats_json(char* out, size_t cap) {
  size_t off = 0;
  auto put = [&](const char* fmt, auto... args) {
    if (off < cap) {
      int w = std::snprintf(out + off, cap - off, fmt, args...);
      if (w > 0) off += static_cast<size_t>(w) < cap - off
                            ? static_cast<size_t>(w)
                            : cap - off - 1;
    }
  };
  size_t notifs_pending = 0;
  {
    std::lock_guard<std::mutex> lk(notifq_mtx_);
    notifs_pending = notifq_.size();
  }
  put("{\"bytes_tx\":%llu,\"bytes_rx\":%llu,\"stats_ticks\":%llu,"
      "\"notifs_pending\":%llu,\"engines\":[",
      static_cast<unsigned long long>(bytes_tx_.load()),
      static_cast<unsigned long long>(bytes_rx_.load()),
      static_cast<unsigned long long>(stats_ticks_.load()),
      static_cast<unsigned long long>(notifs_pending));
  for (size_t e = 0; e < engines_.size(); ++e) {
    auto& eng = *engines_[e];
    size_t txq_bytes = 0;
    {
      std::lock_guard<std::mutex> lk(eng.conns_mtx);
      for (auto& c : eng.conns)
        txq_bytes += c->txq_bytes.load(std::memory_order_relaxed);
    }
    put("%s{\"tx_frames\":%llu,\"rx_frames\":%llu,"
        "\"tx_p50_us\":%.1f,\"tx_p99_us\":%.1f,"
        "\"rx_p50_us\":%.1f,\"rx_p99_us\":%.1f,"
        "\"txq_bytes\":%llu,\"ring_depth\":%llu}",
        e == 0 ? "" : ",",
        static_cast<unsigned long long>(eng.tx_frames.load()),
        static_cast<unsigned long long>(eng.rx_frames.load()),
        eng.tx_lat.percentile_ns(50) / 1e3,
        eng.tx_lat.percentile_ns(99) / 1e3,
        eng.rx_lat.percentile_ns(50) / 1e3,
        eng.rx_lat.percentile_ns(99) / 1e3,
        static_cast<unsigned long long>(txq_bytes),
        static_cast<unsigned long long>(eng.ring.size()));
  }
  put("]}");
  return off;
}

void Endpoint::stats_loop() {
  const char* v = std::getenv("UCCL_TPU_ENGINE_STATS");
  bool verbose = v != nullptr && v[0] == '1';
  const char* pm = std::getenv("UCCL_TPU_ENGINE_STATS_MS");
  int period_ms = pm != nullptr ? std::atoi(pm) : 2000;
  if (period_ms <= 0) period_ms = 2000;
  while (!stop_.load()) {
    // sleep in short steps so shutdown never waits out the cadence
    for (int slept = 0; slept < period_ms && !stop_.load(); slept += 20) {
      std::this_thread::sleep_for(std::chrono::milliseconds(20));
    }
    if (stop_.load()) break;
    stats_ticks_.fetch_add(1, std::memory_order_relaxed);
    if (verbose) {
      char buf[4096];
      stats_json(buf, sizeof(buf));
      std::fprintf(stderr, "[uccl_tpu:engine-stats] %s\n", buf);
    }
  }
}

}  // namespace uccl_tpu
