// Implementation of the P2P transfer engine (see include/uccl_tpu/engine.h).
//
// Threading model mirrors the reference's p2p engine: application threads
// enqueue tasks onto a lock-free ring; a dedicated tx proxy thread owns the
// wire sends (reference send_proxy_thread_func, p2p/engine.cc:2248); one io
// thread owns epoll dispatch of inbound frames (recv proxy, engine.cc:2286).

#include "uccl_tpu/engine.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <random>

namespace uccl_tpu {

namespace {
// Detect ThreadSanitizer under both gcc (__SANITIZE_THREAD__) and clang
// (__has_feature). The wire-order fence and the syscall-read suppression
// below exist purely for the race detector; production builds compile to
// the exact pre-fence code.
#if defined(__SANITIZE_THREAD__)
#define UCCLT_TSAN 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define UCCLT_TSAN 1
#endif
#endif
#ifndef UCCLT_TSAN
#define UCCLT_TSAN 0
#endif

#if UCCLT_TSAN
// Wire-order fence: a kernel TCP socket orders a sender's ::send before the
// peer's matching read, but TSAN cannot see through the socket — under
// single-process loopback a completed transfer's buffer reuse would be
// flagged as a race on the payload pointer. A release RMW BEFORE each
// ::send (bytes cannot reach the peer until the syscall copies them, which
// is after the release) and an acquire load per fully-received frame make
// the real ordering visible to the detector. The one access this cannot
// cover is the syscall's own read of the payload (it follows the release
// by construction), so that read is explicitly ignored — its safety is the
// keepalive contract (source buffers outlive the transfer until a terminal
// state) plus kernel ordering, the exact invariant the Python/channel
// layers enforce.
//
// SCOPING: one global atomic would add happens-before edges between ALL
// threads touching ANY connection, masking unrelated real races from the
// detector. Instead the fence is an array slot keyed by the connection's
// NORMALIZED 4-tuple hash — both ends of one socket compute the same slot
// (addresses sorted), so edges form (essentially) only along the real
// kernel-ordered channel; hash collisions can only ADD edges, never remove
// detection of the fenced pair.
std::atomic<uint64_t> g_wire_order[256];
extern "C" void AnnotateIgnoreReadsBegin(const char* f, int l);
extern "C" void AnnotateIgnoreReadsEnd(const char* f, int l);
#define UCCLT_WIRE_RELEASE(slot) \
  g_wire_order[slot].fetch_add(1, std::memory_order_release)
#define UCCLT_WIRE_ACQUIRE(slot) \
  ((void)g_wire_order[slot].load(std::memory_order_acquire))
#define UCCLT_TSAN_IGNORE_READS_BEGIN() \
  AnnotateIgnoreReadsBegin(__FILE__, __LINE__)
#define UCCLT_TSAN_IGNORE_READS_END() AnnotateIgnoreReadsEnd(__FILE__, __LINE__)
#else
#define UCCLT_WIRE_RELEASE(slot) ((void)0)
#define UCCLT_WIRE_ACQUIRE(slot) ((void)0)
#define UCCLT_TSAN_IGNORE_READS_BEGIN() ((void)0)
#define UCCLT_TSAN_IGNORE_READS_END() ((void)0)
#endif

constexpr uint32_t kMagic = 0x7C71u;
// Upper bound on a single frame payload — rejects absurd lengths from a buggy
// or malicious peer before any allocation happens.
constexpr uint64_t kMaxFrameLen = 1ull << 30;
// Per-conn tx queue watermark: above this, two-sided send() blocks (caller
// backpressure, like the old blocking send path) and read responses to a
// non-draining requester are dropped (it times out; it wasn't reading).
constexpr size_t kTxqHighWater = 64ull << 20;
// Max bytes drained from ONE conn per epoll event: a fast sender pumping a
// large frame refills the kernel buffer faster than EAGAIN can fire, and an
// unbudgeted drain would serve that conn forever while the listener and
// every other conn on the engine starve. Level-triggered epoll re-reports
// the fd immediately, so the io loop round-robins at this granularity.
constexpr size_t kRxBudgetPerEvent = 4ull << 20;

void set_nonblocking(int fd) {
  int flags = ::fcntl(fd, F_GETFL, 0);
  if (flags >= 0) ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

uint64_t random_token() {
  static thread_local std::mt19937_64 gen{std::random_device{}()};
  return gen();
}

// Fence slot for a connected fd: hash of the normalized 4-tuple so both
// ends of one socket agree (see g_wire_order). On syscall failure falls
// back to slot 0 — a collision can only ADD detector edges. Computed once
// per connection at registration (the 4-tuple is immutable afterwards).
[[maybe_unused]] uint32_t wire_slot_for_fd(int fd) {
  sockaddr_in a{}, b{};
  socklen_t al = sizeof(a), bl = sizeof(b);
  if (::getsockname(fd, reinterpret_cast<sockaddr*>(&a), &al) != 0 ||
      ::getpeername(fd, reinterpret_cast<sockaddr*>(&b), &bl) != 0) {
    return 0;
  }
  uint64_t x = (static_cast<uint64_t>(a.sin_addr.s_addr) << 16) ^ a.sin_port;
  uint64_t y = (static_cast<uint64_t>(b.sin_addr.s_addr) << 16) ^ b.sin_port;
  uint64_t lo = x < y ? x : y, hi = x < y ? y : x;
  uint64_t h = lo * 0x9E3779B97F4A7C15ull ^ hi;
  return static_cast<uint32_t>((h >> 13) & 255);
}

uint64_t now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}
}  // namespace

Endpoint::Endpoint(uint16_t port, int n_engines, const char* listen_ip) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  bool ip_ok = true;
  if (listen_ip != nullptr && listen_ip[0] != '\0') {
    ip_ok = ::inet_pton(AF_INET, listen_ip, &addr.sin_addr) == 1;
  }
  addr.sin_port = htons(port);
  // Every failure mode falls through to engine creation: a !ok() endpoint
  // must still be safe to call into (engines_ non-empty).
  if (!ip_ok ||
      ::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(listen_fd_, 128) != 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
  } else {
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    listen_port_ = ntohs(addr.sin_port);
  }

  if (n_engines < 1) n_engines = 1;
  for (int e = 0; e < n_engines; ++e) {
    auto ctx = std::make_unique<EngineCtx>();
    ctx->epoll_fd = ::epoll_create1(0);
    ctx->wake_fd = ::eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = 0;  // 0 => wake fd
    ::epoll_ctl(ctx->epoll_fd, EPOLL_CTL_ADD, ctx->wake_fd, &ev);
    if (e == 0 && listen_fd_ >= 0) {
      ev.data.u64 = 1;  // 1 => listener (engine 0 owns accepts)
      ::epoll_ctl(ctx->epoll_fd, EPOLL_CTL_ADD, listen_fd_, &ev);
    }
    engines_.push_back(std::move(ctx));
  }
  for (int e = 0; e < n_engines; ++e) {
    engines_[e]->io_thread = std::thread([this, e] { io_loop(e); });
    engines_[e]->tx_thread = std::thread([this, e] { tx_loop(e); });
  }
  stats_thread_ = std::thread([this] { stats_loop(); });
}

Endpoint::~Endpoint() {
  // Flush: sends are queued asynchronously, so frames an application handed
  // over just before close (e.g. a collective's final DONE control message)
  // may still sit in conn tx queues. Let the tx threads drain them as long
  // as progress is being made; a peer that stopped draining only costs the
  // short no-progress cutoff.
  auto queued = [this]() -> size_t {
    size_t total = 0;
    std::lock_guard<std::mutex> lk(conns_mtx_);
    for (auto& kv : conns_) {
      total += kv.second->txq_bytes.load(std::memory_order_relaxed);
    }
    return total;
  };
  size_t last = queued();
  auto last_progress = std::chrono::steady_clock::now();
  while (last > 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
    size_t now_q = queued();
    auto now = std::chrono::steady_clock::now();
    if (now_q < last) {
      last = now_q;
      last_progress = now;
    } else if (now - last_progress > std::chrono::milliseconds(250)) {
      break;  // peer stopped draining; don't hold shutdown hostage
    }
  }
  stop_.store(true);
  uint64_t one = 1;
  for (auto& eng : engines_) {
    ::write(eng->wake_fd, &one, sizeof(one));
    eng->cv.notify_all();
  }
  for (auto& eng : engines_) {
    if (eng->io_thread.joinable()) eng->io_thread.join();
    if (eng->tx_thread.joinable()) eng->tx_thread.join();
  }
  if (stats_thread_.joinable()) stats_thread_.join();
  {
    std::lock_guard<std::mutex> lk(conns_mtx_);
    conns_.clear();  // Conn destructors close the fds
  }
  if (listen_fd_ >= 0) ::close(listen_fd_);
  for (auto& eng : engines_) {
    if (eng->epoll_fd >= 0) ::close(eng->epoll_fd);
    if (eng->wake_fd >= 0) ::close(eng->wake_fd);
    Task* t = nullptr;
    while (eng->ring.pop(&t)) free_task(t);
  }
}

int64_t Endpoint::connect(const std::string& ip, uint16_t port,
                          const char* local_ip) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (::inet_pton(AF_INET, ip.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (local_ip && local_ip[0]) {
    // Multi-NIC data-path selection (reference: per-GPU NIC selection and
    // data channels spread across NICs, p2p/rdma/rdma_endpoint.h:117):
    // bind the outgoing conn's source address to the chosen interface.
    sockaddr_in src{};
    src.sin_family = AF_INET;
    src.sin_port = 0;
    if (::inet_pton(AF_INET, local_ip, &src.sin_addr) != 1 ||
        ::bind(fd, reinterpret_cast<sockaddr*>(&src), sizeof(src)) != 0) {
      ::close(fd);
      return -1;
    }
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto c = std::make_shared<Conn>();
  c->fd = fd;
  c->id = next_conn_.fetch_add(1);
  uint64_t id = c->id;
  register_conn(c);
  return static_cast<int64_t>(id);
}

void Endpoint::register_conn(const std::shared_ptr<Conn>& c) {
  c->engine = static_cast<int>(c->id % engines_.size());
#if UCCLT_TSAN
  // populated only for the race detector's wire-order fence; production
  // builds skip the two syscalls and never read the field
  c->wire_slot = wire_slot_for_fd(c->fd);
#endif
  set_nonblocking(c->fd);  // rx state machine + queued tx never block
  {
    std::lock_guard<std::mutex> lk(conns_mtx_);
    conns_[c->id] = c;
  }
  EngineCtx& eng = *engines_[c->engine];
  {
    std::lock_guard<std::mutex> lk(eng.conns_mtx);
    eng.conns.push_back(c);
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = (c->id << 2) | 2;  // tag 2 => conn
  ::epoll_ctl(eng.epoll_fd, EPOLL_CTL_ADD, c->fd, &ev);
}

int64_t Endpoint::accept(int timeout_ms) {
  std::lock_guard<std::mutex> alk(accept_mtx_);  // queue pop is single-consumer
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  uint64_t id = 0;
  while (!accept_queue_.pop(&id)) {
    if (stop_.load() || std::chrono::steady_clock::now() > deadline) return -1;
    std::this_thread::sleep_for(std::chrono::microseconds(100));
  }
  return static_cast<int64_t>(id);
}

bool Endpoint::peer_addr(uint64_t conn_id, char* out, size_t cap) {
  auto c = get_conn(conn_id);
  if (!c || cap == 0) return false;
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (::getpeername(c->fd, reinterpret_cast<sockaddr*>(&addr), &len) != 0) {
    return false;
  }
  char ip[INET_ADDRSTRLEN] = {0};
  if (!::inet_ntop(AF_INET, &addr.sin_addr, ip, sizeof(ip))) return false;
  std::snprintf(out, cap, "%s:%u", ip, ntohs(addr.sin_port));
  return true;
}

bool Endpoint::conn_alive(uint64_t conn_id) {
  std::lock_guard<std::mutex> lk(conns_mtx_);
  auto it = conns_.find(conn_id);
  return it != conns_.end() && !it->second->dead.load(std::memory_order_relaxed);
}

bool Endpoint::remove_conn(uint64_t conn_id) {
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(conns_mtx_);
    auto it = conns_.find(conn_id);
    if (it == conns_.end()) return false;
    c = it->second;
    conns_.erase(it);
  }
  // The tx thread (sole queue owner) fails queued transfers on its next
  // pass — the engine's strong conn list keeps the object alive until then.
  c->dead.store(true, std::memory_order_relaxed);
  ::epoll_ctl(engines_[c->engine]->epoll_fd, EPOLL_CTL_DEL, c->fd, nullptr);
  // Unblock any thread mid-send/recv on this fd; the fd itself closes when
  // the last shared_ptr holder drops (Conn::~Conn), never under a user.
  ::shutdown(c->fd, SHUT_RDWR);
  return true;
}

bool Endpoint::flush_conn(uint64_t conn_id, int timeout_ms) {
  auto c = get_conn(conn_id);
  if (!c) return false;
  if (!wait_txq_below(c.get(), 0, timeout_ms)) return false;
  return !c->dead.load();
}

uint64_t Endpoint::reg(void* ptr, size_t len) {
  Reg r{ptr, len};
  uint64_t id = next_reg_.fetch_add(1);
  std::lock_guard<std::mutex> lk(regs_mtx_);
  regs_[id] = r;
  return id;
}

bool Endpoint::dereg(uint64_t mr_id) {
  std::shared_ptr<std::atomic<int>> pins;
  {
    std::lock_guard<std::mutex> lk(regs_mtx_);
    for (auto it = windows_.begin(); it != windows_.end();) {
      if (it->second.mr_id == mr_id) {
        it = windows_.erase(it);
      } else {
        ++it;
      }
    }
    auto rit = regs_.find(mr_id);
    if (rit == regs_.end()) return false;
    pins = rit->second.pins;
    regs_.erase(rit);
  }
  // Drain in-flight zero-copy receives before the caller may free the buffer.
  while (pins->load(std::memory_order_acquire) > 0) {
    std::this_thread::sleep_for(std::chrono::microseconds(50));
  }
  return true;
}

bool Endpoint::advertise(uint64_t mr_id, size_t offset, size_t len,
                         FifoItem* out) {
  std::lock_guard<std::mutex> lk(regs_mtx_);
  auto it = regs_.find(mr_id);
  if (it == regs_.end() || offset > it->second.len ||
      len > it->second.len - offset) {
    return false;
  }
  uint64_t wid = next_window_.fetch_add(1);
  windows_[wid] = Window{mr_id, offset, len, random_token()};
  std::memset(out, 0, sizeof(*out));
  out->rid = wid;
  out->size = len;
  out->token = windows_[wid].token;
  out->offset = 0;
  return true;
}

// Resolve a (window id, token, offset, len) quadruple from the wire into a
// host pointer, enforcing the advertised byte range with overflow-safe math.
// Returns nullptr if anything is off. Caller must hold regs_mtx_.
void* Endpoint::resolve_window_locked(
    uint64_t wid, uint64_t token, uint64_t offset, uint64_t len,
    std::shared_ptr<std::atomic<int>>* pin_out) {
  auto wit = windows_.find(wid);
  if (wit == windows_.end() || wit->second.token != token) return nullptr;
  const Window& w = wit->second;
  if (offset > w.len || len > w.len - offset) return nullptr;
  auto rit = regs_.find(w.mr_id);
  if (rit == regs_.end()) return nullptr;
  if (pin_out != nullptr) {
    // Caller will touch the memory after dropping regs_mtx_: pin so dereg()
    // blocks until the access completes.
    rit->second.pins->fetch_add(1, std::memory_order_acq_rel);
    *pin_out = rit->second.pins;
  }
  return static_cast<uint8_t*>(rit->second.ptr) + w.offset + offset;
}

std::shared_ptr<Endpoint::Conn> Endpoint::get_conn(uint64_t id) {
  std::lock_guard<std::mutex> lk(conns_mtx_);
  auto it = conns_.find(id);
  return it == conns_.end() ? nullptr : it->second;
}

uint64_t Endpoint::new_xfer() {
  uint64_t id = next_xfer_.fetch_add(1);
  std::lock_guard<std::mutex> lk(xfers_mtx_);
  xfers_[id] = XferState::kPending;
  return id;
}

void Endpoint::complete(uint64_t xfer_id, XferState st) {
  {
    std::lock_guard<std::mutex> lk(xfers_mtx_);
    xfers_[xfer_id] = st;
    if (st == XferState::kError) pending_reads_.erase(xfer_id);
  }
  xfers_cv_.notify_all();
}

void Endpoint::enqueue_task(Task* t) {
  enqueue_tasks(&t, 1);
}

void Endpoint::enqueue_tasks(Task* const* ts, size_t n) {
  if (n == 0) return;
  // Route to the engine serving this conn so its tx thread owns the sends
  // (all tasks of one batch target the same conn).
  auto c = get_conn(ts[0]->conn_id);
  EngineCtx& eng = *engines_[c ? c->engine : 0];
  for (size_t i = 0; i < n; ++i) {  // MPSC ring: lock-free from any thread
    while (!eng.ring.push(ts[i])) std::this_thread::yield();
  }
  eng.cv.notify_one();  // one wake for the whole batch
}

uint64_t Endpoint::write_async(uint64_t conn_id, const void* src, size_t len,
                               const FifoItem& item) {
  uint64_t xid = new_xfer();
  if (len > item.size) {  // reject over-window writes before they hit the wire
    complete(xid, XferState::kError);
    return xid;
  }
  Task* t = alloc_task();
  t->conn_id = conn_id;
  t->op = Op::kWrite;
  t->xfer_id = xid;
  t->src = src;
  t->len = len;
  t->item = item;
  enqueue_task(t);
  return xid;
}

uint64_t Endpoint::read_async(uint64_t conn_id, void* dst, size_t len,
                              const FifoItem& item) {
  uint64_t xid = new_xfer();
  if (len > item.size) {
    complete(xid, XferState::kError);
    return xid;
  }
  {
    std::lock_guard<std::mutex> lk(xfers_mtx_);
    pending_reads_[xid] = PendingRead{dst, len};
  }
  Task* t = alloc_task();
  t->conn_id = conn_id;
  t->op = Op::kRead;
  t->xfer_id = xid;
  t->len = len;
  t->item = item;
  enqueue_task(t);
  return xid;
}

void Endpoint::writev_async(uint64_t conn_id, const void* const* srcs,
                            const size_t* lens, const FifoItem* items,
                            size_t n, uint64_t* xids_out) {
  std::vector<Task*> batch;
  batch.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    uint64_t xid = new_xfer();
    xids_out[i] = xid;
    if (lens[i] > items[i].size) {  // reject before it hits the wire
      complete(xid, XferState::kError);
      continue;
    }
    Task* t = alloc_task();
    t->conn_id = conn_id;
    t->op = Op::kWrite;
    t->xfer_id = xid;
    t->src = srcs[i];
    t->len = lens[i];
    t->item = items[i];
    batch.push_back(t);
  }
  enqueue_tasks(batch.data(), batch.size());
}

void Endpoint::readv_async(uint64_t conn_id, void* const* dsts,
                           const size_t* lens, const FifoItem* items,
                           size_t n, uint64_t* xids_out) {
  std::vector<Task*> batch;
  batch.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    uint64_t xid = new_xfer();
    xids_out[i] = xid;
    if (lens[i] > items[i].size) {
      complete(xid, XferState::kError);
      continue;
    }
    {
      std::lock_guard<std::mutex> lk(xfers_mtx_);
      pending_reads_[xid] = PendingRead{dsts[i], lens[i]};
    }
    Task* t = alloc_task();
    t->conn_id = conn_id;
    t->op = Op::kRead;
    t->xfer_id = xid;
    t->len = lens[i];
    t->item = items[i];
    batch.push_back(t);
  }
  enqueue_tasks(batch.data(), batch.size());
}

bool Endpoint::write(uint64_t conn_id, const void* src, size_t len,
                     const FifoItem& item) {
  return wait(write_async(conn_id, src, len, item), 30000);
}

bool Endpoint::read(uint64_t conn_id, void* dst, size_t len,
                    const FifoItem& item) {
  return wait(read_async(conn_id, dst, len, item), 30000);
}

// Poll until the conn's queued tx bytes drop to `threshold` or below;
// false on conn death, endpoint stop, or timeout. Serves both send()'s
// high-water backpressure and flush_conn()'s drain-to-empty.
bool Endpoint::wait_txq_below(Conn* c, size_t threshold, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (c->txq_bytes.load(std::memory_order_relaxed) > threshold) {
    if (c->dead.load() || stop_.load() ||
        std::chrono::steady_clock::now() > deadline) {
      return false;
    }
    std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
  return true;
}

bool Endpoint::send(uint64_t conn_id, const void* buf, size_t len) {
  auto c = get_conn(conn_id);
  if (!c) return false;
  // Backpressure: a peer that stops reading fills its queue to the
  // watermark, then senders block here (the old blocking-send behavior)
  // instead of growing the owned-copy queue without bound.
  if (!wait_txq_below(c.get(), kTxqHighWater, 5000)) return false;
  if (c->dead.load()) return false;
  FrameHeader h{};
  h.magic = kMagic;
  h.op = static_cast<uint16_t>(Op::kSend);
  h.len = len;
  // Copy: the frame outlives this call on the conn's tx queue (delivery
  // failure surfaces as conn death, like any reliable-stream send).
  std::vector<uint8_t> owned(static_cast<const uint8_t*>(buf),
                             static_cast<const uint8_t*>(buf) + len);
  enqueue_frame(c, h, nullptr, std::move(owned), 0);
  return true;
}

int64_t Endpoint::recv(uint64_t conn_id, void* buf, size_t cap,
                       int timeout_ms) {
  std::unique_lock<std::mutex> lk(recvq_mtx_);
  bool ok = recvq_cv_.wait_for(
      lk, std::chrono::milliseconds(timeout_ms),
      [&] { return !recvq_[conn_id].empty() || stop_.load(); });
  if (!ok || recvq_[conn_id].empty()) return -1;
  auto& front = recvq_[conn_id].front();
  if (front.size() > cap) {
    // Leave the message queued; tell the caller the size it needs.
    return -static_cast<int64_t>(front.size()) - 2;
  }
  auto msg = std::move(front);
  recvq_[conn_id].pop_front();
  lk.unlock();
  std::memcpy(buf, msg.data(), msg.size());
  return static_cast<int64_t>(msg.size());
}

bool Endpoint::send_notif(uint64_t conn_id, const void* buf, size_t len) {
  auto c = get_conn(conn_id);
  if (!c) return false;
  if (!wait_txq_below(c.get(), kTxqHighWater, 5000)) return false;
  if (c->dead.load()) return false;
  FrameHeader h{};
  h.magic = kMagic;
  h.op = static_cast<uint16_t>(Op::kNotif);
  h.len = len;
  std::vector<uint8_t> owned(static_cast<const uint8_t*>(buf),
                             static_cast<const uint8_t*>(buf) + len);
  enqueue_frame(c, h, nullptr, std::move(owned), 0);
  return true;
}

int64_t Endpoint::get_notif(uint64_t* conn_out, void* buf, size_t cap) {
  std::lock_guard<std::mutex> lk(notifq_mtx_);
  if (notifq_.empty()) return -1;
  auto& front = notifq_.front();
  if (front.second.size() > cap)
    return -static_cast<int64_t>(front.second.size()) - 2;
  *conn_out = front.first;
  std::memcpy(buf, front.second.data(), front.second.size());
  int64_t n = static_cast<int64_t>(front.second.size());
  notifq_.pop_front();
  return n;
}

void Endpoint::reap(uint64_t xfer_id) {
  std::lock_guard<std::mutex> lk(xfers_mtx_);
  xfers_.erase(xfer_id);
}

XferState Endpoint::poll(uint64_t xfer_id) {
  std::lock_guard<std::mutex> lk(xfers_mtx_);
  auto it = xfers_.find(xfer_id);
  if (it == xfers_.end()) return XferState::kError;
  XferState st = it->second;
  if (st != XferState::kPending) xfers_.erase(it);  // one-shot reclaim
  return st;
}

bool Endpoint::wait(uint64_t xfer_id, int timeout_ms) {
  std::unique_lock<std::mutex> lk(xfers_mtx_);
  bool ok = xfers_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
    auto it = xfers_.find(xfer_id);
    return it == xfers_.end() || it->second != XferState::kPending;
  });
  if (!ok) return false;
  auto it = xfers_.find(xfer_id);
  if (it == xfers_.end()) return false;  // already consumed elsewhere
  XferState st = it->second;
  xfers_.erase(it);  // one-shot reclaim
  return st == XferState::kDone;
}

void Endpoint::enqueue_frame(const std::shared_ptr<Conn>& c,
                             const FrameHeader& h, const void* src,
                             std::vector<uint8_t> owned, uint64_t fail_xfer) {
  // Fault injection: silently drop the frame (reference kTestLoss,
  // transport_config.h:222) — the transfer then times out at the caller.
  double p = drop_rate_.load();
  if (p > 0.0) {
    static thread_local std::mt19937_64 gen{std::random_device{}()};
    std::uniform_real_distribution<double> d(0.0, 1.0);
    if (d(gen) < p) return;
  }
  TxItem it;
  it.h = h;
  it.src = src;
  it.owned = std::move(owned);
  it.wire_len = !it.owned.empty() ? it.owned.size()
              : (src != nullptr ? static_cast<size_t>(h.len) : 0);
  it.fail_xfer = fail_xfer;
  it.t_enq_ns = now_ns();
  size_t total = it.total();
  {
    std::lock_guard<std::mutex> lk(c->txq_mtx);
    c->txq.push_back(std::move(it));
  }
  c->txq_bytes.fetch_add(total, std::memory_order_relaxed);
  engines_[c->engine]->cv.notify_one();
}

bool Endpoint::service_tx(Conn* c, bool* blocked) {
  while (true) {
    TxItem* it = nullptr;
    {
      std::lock_guard<std::mutex> lk(c->txq_mtx);
      if (c->txq.empty()) return true;
      // Safe to use outside the lock: this thread is the sole consumer, and
      // deque push_back never invalidates references to existing elements.
      it = &c->txq.front();
    }
    // Stats credit up front: a peer can receive (and ack) the final bytes
    // while this thread is between its last send syscall and any post-hoc
    // accounting, which would let a completed blocking write observe a
    // stale counter. Counting at transmit-start makes "transfer complete
    // implies counted" a real ordering guarantee (at the price of counting
    // a frame a dying conn never finished — acceptable for stats).
    if (!it->credited) {
      bytes_tx_.fetch_add(it->total());
      it->credited = true;  // EAGAIN re-entries must not credit again
    }
    // Send syscalls run without txq_mtx so app threads can keep enqueueing.
    while (it->off < it->total()) {
      const uint8_t* base;
      size_t n;
      if (it->off < sizeof(FrameHeader)) {
        base = reinterpret_cast<const uint8_t*>(&it->h) + it->off;
        n = sizeof(FrameHeader) - it->off;
      } else {
        size_t poff = it->off - sizeof(FrameHeader);
        base = it->payload() + poff;
        n = it->wire_len - poff;
      }
      // Release precedes the syscall: every prior write to the payload is
      // published before any byte can reach the peer (see g_wire_order).
      UCCLT_WIRE_RELEASE(c->wire_slot);
      UCCLT_TSAN_IGNORE_READS_BEGIN();
      ssize_t s = ::send(c->fd, base, n, MSG_NOSIGNAL);
      UCCLT_TSAN_IGNORE_READS_END();
      if (s < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          *blocked = true;  // kernel buffer full; resume on POLLOUT
          return true;
        }
        return false;
      }
      it->off += static_cast<size_t>(s);
    }
    size_t total = it->total();
    uint64_t t_enq = it->t_enq_ns;
    {
      std::lock_guard<std::mutex> lk(c->txq_mtx);
      c->txq.pop_front();
    }
    c->txq_bytes.fetch_sub(total, std::memory_order_relaxed);
    auto& eng = *engines_[c->engine];
    eng.tx_lat.record(now_ns() - t_enq);
    eng.tx_frames.fetch_add(1, std::memory_order_relaxed);
  }
}

void Endpoint::fail_txq(Conn* c) {
  std::deque<TxItem> q;
  {
    std::lock_guard<std::mutex> lk(c->txq_mtx);
    q.swap(c->txq);
  }
  size_t bytes = 0;
  for (auto& it : q) {
    bytes += it.total();
    if (it.fail_xfer != 0) complete(it.fail_xfer, XferState::kError);
  }
  c->txq_bytes.fetch_sub(bytes, std::memory_order_relaxed);
}

// Token-bucket pacing: before a payload send, wait until enough tokens have
// accrued. ONE bucket shared by all engines — the cap is the endpoint's
// aggregate egress regardless of how traffic spreads across paths (reference
// analog: the Carousel timing wheel pacing chunk injection,
// collective/rdma/timing_wheel.h).
void Endpoint::pace(EngineCtx& /*eng*/, uint64_t bytes) {
  uint64_t bps = rate_bps_.load(std::memory_order_relaxed);
  if (bps == 0 || bytes == 0) return;
  const double rate = static_cast<double>(bps);
  constexpr double kBurstS = 0.01;  // at most 10ms of credit after idle
  double wait_s = 0.0;
  {
    // Virtual-time leaky bucket: pace_next_ is when the next byte may go.
    // Exact long-run rate (each send advances it by bytes/rate), bounded
    // burst (it can lag `now` by at most kBurstS).
    std::lock_guard<std::mutex> lk(pace_mtx_);
    auto now = std::chrono::steady_clock::now();
    auto floor = now - std::chrono::duration_cast<
                           std::chrono::steady_clock::duration>(
                           std::chrono::duration<double>(kBurstS));
    if (pace_next_ < floor) pace_next_ = floor;
    pace_next_ += std::chrono::duration_cast<std::chrono::steady_clock::duration>(
        std::chrono::duration<double>(bytes / rate));
    // Wait until this frame's own virtual finish time: a single frame larger
    // than the burst window is paced too, not just its successors.
    wait_s = std::chrono::duration<double>(pace_next_ - now).count();
  }
  // Interruptible sleep: never outlive shutdown by more than one slice.
  while (wait_s > 0.0 && !stop_.load(std::memory_order_relaxed)) {
    double slice = std::min(wait_s, 0.01);
    std::this_thread::sleep_for(std::chrono::duration<double>(slice));
    wait_s -= slice;
  }
}

void Endpoint::tx_loop(int engine) {
  EngineCtx& eng = *engines_[engine];
  while (!stop_.load()) {
    // Phase 1: admit tasks from the ring into per-conn tx queues. Pacing
    // throttles admission (one shared token bucket = aggregate egress cap).
    Task* t = nullptr;
    while (eng.ring.pop(&t)) {
      auto c = get_conn(t->conn_id);
      if (!c || c->dead.load(std::memory_order_relaxed)) {
        // Only locally-initiated ops carry OUR xfer ids; a kReadResp's
        // xfer_id belongs to the remote requester's counter and must never
        // be completed against the local table.
        if (t->xfer_id != 0 && (t->op == Op::kWrite || t->op == Op::kRead)) {
          complete(t->xfer_id, XferState::kError);
        }
        free_task(t);
        continue;
      }
      FrameHeader h{};
      h.magic = kMagic;
      h.op = static_cast<uint16_t>(t->op);
      h.xfer_id = t->xfer_id;
      h.rid = t->item.rid;
      h.token = t->item.token;
      h.offset = t->item.offset;
      h.flags = t->flags;
      if (t->op == Op::kWrite) {
        h.len = t->len;
        pace(eng, t->len);
        enqueue_frame(c, h, t->src, {}, t->xfer_id);
        // completion arrives as kWriteAck
      } else if (t->op == Op::kRead) {
        // kRead frames carry the *requested* length in len, no payload.
        h.len = t->len;
        enqueue_frame(c, h, nullptr, {}, t->xfer_id);
      } else if (t->op == Op::kReadResp) {
        if (c->txq_bytes.load(std::memory_order_relaxed) > kTxqHighWater) {
          // The requester isn't draining its own responses; dropping lets
          // it time out without growing the owned-copy queue unboundedly.
          free_task(t);
          continue;
        }
        h.rid = 0;
        h.token = 0;
        h.offset = 0;
        h.len = t->owned.size();
        pace(eng, h.len);
        enqueue_frame(c, h, nullptr, std::move(t->owned), 0);
      } else if (t->op == Op::kWriteAck) {
        h.rid = 0;
        h.token = 0;
        h.offset = 0;
        h.len = 0;
        enqueue_frame(c, h, nullptr, {}, 0);
      }
      free_task(t);
    }

    // Phase 2: round-robin nonblocking service of every conn with queued
    // frames. One backpressured peer parks with POLLOUT interest; the rest
    // keep moving — no cross-conn head-of-line blocking (the discipline of
    // the reference engine run-loop, transport.cc:443-470).
    std::vector<std::shared_ptr<Conn>> cs;
    {
      std::lock_guard<std::mutex> lk(eng.conns_mtx);
      cs = eng.conns;
    }
    std::vector<pollfd> blocked_fds;
    std::vector<uint64_t> pruned;
    for (auto& c : cs) {
      if (c->dead.load(std::memory_order_relaxed)) {
        fail_txq(c.get());  // tx owns queue cleanup (sole consumer)
        pruned.push_back(c->id);
        continue;
      }
      bool blocked = false;
      if (!service_tx(c.get(), &blocked)) {
        // Socket died mid-send: fail queued transfers and shut the fd down;
        // the io thread observes the error event and finishes teardown.
        c->dead.store(true, std::memory_order_relaxed);
        fail_txq(c.get());
        ::shutdown(c->fd, SHUT_RDWR);
      } else if (blocked) {
        blocked_fds.push_back(pollfd{c->fd, POLLOUT, 0});
      }
    }
    if (!pruned.empty()) {
      std::lock_guard<std::mutex> lk(eng.conns_mtx);
      eng.conns.erase(
          std::remove_if(eng.conns.begin(), eng.conns.end(),
                         [&](const std::shared_ptr<Conn>& c) {
                           return std::find(pruned.begin(), pruned.end(),
                                            c->id) != pruned.end();
                         }),
          eng.conns.end());
    }

    // Phase 3: wait for room on blocked sockets or for new work.
    if (!blocked_fds.empty()) {
      ::poll(blocked_fds.data(), blocked_fds.size(), 1);
    } else {
      std::unique_lock<std::mutex> lk(eng.cv_mtx);
      eng.cv.wait_for(lk, std::chrono::milliseconds(1));
    }
  }
}

void Endpoint::handle_frame(Conn* c, const FrameHeader& h,
                            std::vector<uint8_t>& payload) {
  switch (static_cast<Op>(h.op)) {
    // Op::kWrite is fully handled by io_loop's zero-copy fast path.
    case Op::kWriteAck:
      complete(h.xfer_id, h.flags == 0 ? XferState::kDone : XferState::kError);
      break;
    case Op::kRead: {
      // Copy the window contents into a task-owned buffer and hand the
      // (possibly large, blocking) send to the tx proxy thread.
      Task* t = alloc_task();
      t->conn_id = c->id;
      t->op = Op::kReadResp;
      t->xfer_id = h.xfer_id;
      {
        std::lock_guard<std::mutex> lk(regs_mtx_);
        void* src = resolve_window_locked(h.rid, h.token, h.offset, h.len);
        if (src != nullptr) {
          t->owned.assign(static_cast<uint8_t*>(src),
                          static_cast<uint8_t*>(src) + h.len);
        } else {
          t->flags = 1;
        }
      }
      enqueue_task(t);
      break;
    }
    case Op::kReadResp: {
      PendingRead pr{};
      {
        std::lock_guard<std::mutex> lk(xfers_mtx_);
        auto it = pending_reads_.find(h.xfer_id);
        if (it != pending_reads_.end()) {
          pr = it->second;
          pending_reads_.erase(it);
        }
      }
      if (h.flags == 0 && pr.dst != nullptr && h.len <= pr.len) {
        std::memcpy(pr.dst, payload.data(), h.len);
        complete(h.xfer_id, XferState::kDone);
      } else {
        complete(h.xfer_id, XferState::kError);
      }
      break;
    }
    case Op::kSend: {
      {
        std::lock_guard<std::mutex> lk(recvq_mtx_);
        recvq_[c->id].push_back(std::move(payload));
      }
      recvq_cv_.notify_all();
      break;
    }
    case Op::kNotif: {
      std::lock_guard<std::mutex> lk(notifq_mtx_);
      notifq_.emplace_back(c->id, std::move(payload));
      break;
    }
    default:
      break;
  }
}

// Finish one fully-received frame (io thread only): dispatch by op, release
// the window pin, reset the state machine for the next header.
void Endpoint::finish_rx_frame(Conn* c) {
  // Acquire side of the wire-order fence (see g_wire_order): the sender's
  // pre-send writes happen-before everything after this frame's dispatch.
  UCCLT_WIRE_ACQUIRE(c->wire_slot);
  const FrameHeader& h = c->rx_hdr;
  size_t body = (static_cast<Op>(h.op) == Op::kRead) ? 0 : h.len;
  bytes_rx_.fetch_add(sizeof(h) + body);
  auto& eng = *engines_[c->engine];
  eng.rx_lat.record(now_ns() - c->rx_t0_ns);
  eng.rx_frames.fetch_add(1, std::memory_order_relaxed);
  if (static_cast<Op>(h.op) == Op::kWrite) {
    if (c->rx_pin) {
      c->rx_pin->fetch_sub(1, std::memory_order_acq_rel);
      c->rx_pin.reset();
    }
    Task* ack = alloc_task();
    ack->conn_id = c->id;
    ack->op = Op::kWriteAck;
    ack->xfer_id = h.xfer_id;
    ack->flags = c->rx_ok ? 0 : 1;
    enqueue_task(ack);
  } else {
    handle_frame(c, h, c->rx_buf);
  }
  c->rx_stage = Conn::RxStage::kHdr;
  c->rx_got = 0;
  c->rx_dst = nullptr;
  c->rx_ok = false;
  c->rx_buf.clear();
}

// Drain available bytes through the per-conn state machine without ever
// blocking: a peer that stalls mid-frame parks the state until more bytes
// arrive, and every other connection on the engine keeps flowing (the fix
// for the reference-style blocking recv dispatch; ADVICE.md round 1).
Endpoint::RxResult Endpoint::drain_rx(Conn* c) {
  size_t consumed = 0;
  while (consumed < kRxBudgetPerEvent) {
    if (c->rx_stage == Conn::RxStage::kHdr) {
      uint8_t* p = reinterpret_cast<uint8_t*>(&c->rx_hdr);
      while (c->rx_got < sizeof(FrameHeader)) {
        ssize_t n = ::recv(c->fd, p + c->rx_got,
                           sizeof(FrameHeader) - c->rx_got, 0);
        if (n == 0) return RxResult::kDead;
        if (n < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) return RxResult::kDrained;
          return RxResult::kDead;
        }
        if (c->rx_got == 0) c->rx_t0_ns = now_ns();  // frame service starts
        c->rx_got += static_cast<size_t>(n);
        consumed += static_cast<size_t>(n);
      }
      const FrameHeader& h = c->rx_hdr;
      if (h.magic != kMagic || h.len > kMaxFrameLen) return RxResult::kDead;
      size_t body = (static_cast<Op>(h.op) == Op::kRead) ? 0 : h.len;
      if (static_cast<Op>(h.op) == Op::kWrite) {
        // Fast path: land write payloads straight into the resolved window —
        // one copy total (the DCN analog of the reference's zero-copy RDMA
        // receive into registered memory). Pin so dereg() waits for us
        // (zero-length writes resolve too — their ack must report success —
        // but take no pin, since no bytes will land).
        void* dst = nullptr;
        std::shared_ptr<std::atomic<int>> pin;
        {
          std::lock_guard<std::mutex> lk(regs_mtx_);
          dst = resolve_window_locked(h.rid, h.token, h.offset, h.len,
                                      body > 0 ? &pin : nullptr);
        }
        if (dst != nullptr) {
          c->rx_dst = static_cast<uint8_t*>(dst);
          c->rx_pin = std::move(pin);
          c->rx_ok = true;
        } else {
          c->rx_dst = nullptr;
          c->rx_ok = false;
        }
      }
      if (body == 0) {
        finish_rx_frame(c);
        continue;
      }
      if (c->rx_dst == nullptr) {
        try {
          c->rx_buf.resize(body);  // owned body (or sink for bad windows)
        } catch (const std::exception&) {
          return RxResult::kDead;
        }
      }
      c->rx_stage = Conn::RxStage::kBody;
      c->rx_got = 0;
    }
    // Body stage.
    size_t body = static_cast<size_t>(c->rx_hdr.len);
    uint8_t* dst = c->rx_dst != nullptr ? c->rx_dst : c->rx_buf.data();
    while (c->rx_got < body) {
      // Header bytes above may have nudged consumed past the budget;
      // saturating arithmetic, never wrap.
      size_t remaining = consumed < kRxBudgetPerEvent
                             ? kRxBudgetPerEvent - consumed
                             : 0;
      if (remaining == 0) return RxResult::kBudget;
      ssize_t n = ::recv(c->fd, dst + c->rx_got,
                         std::min(body - c->rx_got, remaining), 0);
      if (n == 0) return RxResult::kDead;
      if (n < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) return RxResult::kDrained;
        return RxResult::kDead;
      }
      c->rx_got += static_cast<size_t>(n);
      consumed += static_cast<size_t>(n);
    }
    finish_rx_frame(c);
  }
  return RxResult::kBudget;  // epoll re-reports any bytes still waiting
}

void Endpoint::conn_error(uint64_t conn_id) {
  auto c = get_conn(conn_id);
  if (c) {
    if (c->rx_pin) {  // io thread owns rx state; we run on the io thread
      c->rx_pin->fetch_sub(1, std::memory_order_acq_rel);
      c->rx_pin.reset();
    }
    // The tx thread (sole queue consumer) fails + clears the queue on its
    // next pass; touching it here would race a send in progress.
    c->dead.store(true, std::memory_order_relaxed);
  }
  remove_conn(conn_id);
}

void Endpoint::io_loop(int engine) {
  EngineCtx& eng = *engines_[engine];
  constexpr int kMaxEvents = 64;
  epoll_event events[kMaxEvents];
  while (!stop_.load()) {
    int n = ::epoll_wait(eng.epoll_fd, events, kMaxEvents, 100);
    for (int i = 0; i < n; ++i) {
      uint64_t tag = events[i].data.u64;
      if (tag == 0) {  // wake fd
        uint64_t v;
        ::read(eng.wake_fd, &v, sizeof(v));
        continue;
      }
      if (tag == 1) {  // listener (engine 0 only)
        int fd = ::accept(listen_fd_, nullptr, nullptr);
        if (fd < 0) continue;
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        auto c = std::make_shared<Conn>();
        c->fd = fd;
        c->id = next_conn_.fetch_add(1);
        uint64_t id = c->id;
        register_conn(c);
        if (!accept_queue_.push(id)) {
          // accept backlog overflow: reject the connection rather than leak
          // an id the application can never accept()
          remove_conn(id);
        }
        continue;
      }
      // connection event. Drain BEFORE acting on ERR/HUP: a peer that sent
      // its last frames and closed leaves EPOLLIN|EPOLLHUP with buffered
      // bytes that must still be delivered (drain_rx reports kDead at EOF).
      // A budget-limited drain must NOT act on HUP either — bytes may still
      // be buffered; the level-triggered event re-fires and we resume.
      uint64_t conn_id = tag >> 2;
      auto conn = get_conn(conn_id);
      if (!conn) continue;
      RxResult res = drain_rx(conn.get());
      bool dead = res == RxResult::kDead ||
                  (res == RxResult::kDrained &&
                   (events[i].events & (EPOLLERR | EPOLLHUP)) != 0);
      if (dead) conn_error(conn_id);
    }
  }
}

// JSON snapshot of the hot-loop stats: per-engine frame counts, service
// latency percentiles (µs), queued tx bytes, task-ring depth. The analog of
// the reference's periodic transport stats (transport.cc:1797 +
// include/util/latency.h), readable on demand through the C API.
size_t Endpoint::stats_json(char* out, size_t cap) {
  size_t off = 0;
  auto put = [&](const char* fmt, auto... args) {
    if (off < cap) {
      int w = std::snprintf(out + off, cap - off, fmt, args...);
      if (w > 0) off += static_cast<size_t>(w) < cap - off
                            ? static_cast<size_t>(w)
                            : cap - off - 1;
    }
  };
  size_t notifs_pending = 0;
  {
    std::lock_guard<std::mutex> lk(notifq_mtx_);
    notifs_pending = notifq_.size();
  }
  put("{\"bytes_tx\":%llu,\"bytes_rx\":%llu,\"stats_ticks\":%llu,"
      "\"notifs_pending\":%llu,\"engines\":[",
      static_cast<unsigned long long>(bytes_tx_.load()),
      static_cast<unsigned long long>(bytes_rx_.load()),
      static_cast<unsigned long long>(stats_ticks_.load()),
      static_cast<unsigned long long>(notifs_pending));
  for (size_t e = 0; e < engines_.size(); ++e) {
    auto& eng = *engines_[e];
    size_t txq_bytes = 0;
    {
      std::lock_guard<std::mutex> lk(eng.conns_mtx);
      for (auto& c : eng.conns)
        txq_bytes += c->txq_bytes.load(std::memory_order_relaxed);
    }
    put("%s{\"tx_frames\":%llu,\"rx_frames\":%llu,"
        "\"tx_p50_us\":%.1f,\"tx_p99_us\":%.1f,"
        "\"rx_p50_us\":%.1f,\"rx_p99_us\":%.1f,"
        "\"txq_bytes\":%llu,\"ring_depth\":%llu}",
        e == 0 ? "" : ",",
        static_cast<unsigned long long>(eng.tx_frames.load()),
        static_cast<unsigned long long>(eng.rx_frames.load()),
        eng.tx_lat.percentile_ns(50) / 1e3,
        eng.tx_lat.percentile_ns(99) / 1e3,
        eng.rx_lat.percentile_ns(50) / 1e3,
        eng.rx_lat.percentile_ns(99) / 1e3,
        static_cast<unsigned long long>(txq_bytes),
        static_cast<unsigned long long>(eng.ring.size()));
  }
  put("]}");
  return off;
}

void Endpoint::stats_loop() {
  const char* v = std::getenv("UCCL_TPU_ENGINE_STATS");
  bool verbose = v != nullptr && v[0] == '1';
  const char* pm = std::getenv("UCCL_TPU_ENGINE_STATS_MS");
  int period_ms = pm != nullptr ? std::atoi(pm) : 2000;
  if (period_ms <= 0) period_ms = 2000;
  while (!stop_.load()) {
    // sleep in short steps so shutdown never waits out the cadence
    for (int slept = 0; slept < period_ms && !stop_.load(); slept += 20) {
      std::this_thread::sleep_for(std::chrono::milliseconds(20));
    }
    if (stop_.load()) break;
    stats_ticks_.fetch_add(1, std::memory_order_relaxed);
    if (verbose) {
      char buf[4096];
      stats_json(buf, sizeof(buf));
      std::fprintf(stderr, "[uccl_tpu:engine-stats] %s\n", buf);
    }
  }
}

}  // namespace uccl_tpu
