// Implementation of the P2P transfer engine (see include/uccl_tpu/engine.h).
//
// Threading model mirrors the reference's p2p engine: application threads
// enqueue tasks onto a lock-free ring; a dedicated tx proxy thread owns the
// wire sends (reference send_proxy_thread_func, p2p/engine.cc:2248); one io
// thread owns epoll dispatch of inbound frames (recv proxy, engine.cc:2286).

#include "uccl_tpu/engine.h"

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <random>

namespace uccl_tpu {

namespace {
constexpr uint32_t kMagic = 0x7C71u;
// Upper bound on a single frame payload — rejects absurd lengths from a buggy
// or malicious peer before any allocation happens.
constexpr uint64_t kMaxFrameLen = 1ull << 30;

bool recv_all(int fd, void* buf, size_t len) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  size_t got = 0;
  while (got < len) {
    ssize_t n = ::recv(fd, p + got, len - got, MSG_WAITALL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      return false;
    }
    got += static_cast<size_t>(n);
  }
  return true;
}

bool send_all(int fd, const void* buf, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  size_t sent = 0;
  while (sent < len) {
    ssize_t n = ::send(fd, p + sent, len - sent, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    sent += static_cast<size_t>(n);
  }
  return true;
}

uint64_t random_token() {
  static thread_local std::mt19937_64 gen{std::random_device{}()};
  return gen();
}
}  // namespace

Endpoint::Endpoint(uint16_t port, int n_engines) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(listen_fd_, 128) != 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
  } else {
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    listen_port_ = ntohs(addr.sin_port);
  }

  if (n_engines < 1) n_engines = 1;
  for (int e = 0; e < n_engines; ++e) {
    auto ctx = std::make_unique<EngineCtx>();
    ctx->epoll_fd = ::epoll_create1(0);
    ctx->wake_fd = ::eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = 0;  // 0 => wake fd
    ::epoll_ctl(ctx->epoll_fd, EPOLL_CTL_ADD, ctx->wake_fd, &ev);
    if (e == 0 && listen_fd_ >= 0) {
      ev.data.u64 = 1;  // 1 => listener (engine 0 owns accepts)
      ::epoll_ctl(ctx->epoll_fd, EPOLL_CTL_ADD, listen_fd_, &ev);
    }
    engines_.push_back(std::move(ctx));
  }
  for (int e = 0; e < n_engines; ++e) {
    engines_[e]->io_thread = std::thread([this, e] { io_loop(e); });
    engines_[e]->tx_thread = std::thread([this, e] { tx_loop(e); });
  }
}

Endpoint::~Endpoint() {
  stop_.store(true);
  uint64_t one = 1;
  for (auto& eng : engines_) {
    ::write(eng->wake_fd, &one, sizeof(one));
    eng->cv.notify_all();
  }
  for (auto& eng : engines_) {
    if (eng->io_thread.joinable()) eng->io_thread.join();
    if (eng->tx_thread.joinable()) eng->tx_thread.join();
  }
  {
    std::lock_guard<std::mutex> lk(conns_mtx_);
    conns_.clear();  // Conn destructors close the fds
  }
  if (listen_fd_ >= 0) ::close(listen_fd_);
  for (auto& eng : engines_) {
    if (eng->epoll_fd >= 0) ::close(eng->epoll_fd);
    if (eng->wake_fd >= 0) ::close(eng->wake_fd);
    Task* t = nullptr;
    while (eng->ring.pop(&t)) delete t;
  }
}

int64_t Endpoint::connect(const std::string& ip, uint16_t port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (::inet_pton(AF_INET, ip.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto c = std::make_shared<Conn>();
  c->fd = fd;
  c->id = next_conn_.fetch_add(1);
  uint64_t id = c->id;
  register_conn(c);
  return static_cast<int64_t>(id);
}

void Endpoint::register_conn(const std::shared_ptr<Conn>& c) {
  c->engine = static_cast<int>(c->id % engines_.size());
  {
    std::lock_guard<std::mutex> lk(conns_mtx_);
    conns_[c->id] = c;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = (c->id << 2) | 2;  // tag 2 => conn
  ::epoll_ctl(engines_[c->engine]->epoll_fd, EPOLL_CTL_ADD, c->fd, &ev);
}

int64_t Endpoint::accept(int timeout_ms) {
  std::lock_guard<std::mutex> alk(accept_mtx_);  // queue pop is single-consumer
  auto deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  uint64_t id = 0;
  while (!accept_queue_.pop(&id)) {
    if (stop_.load() || std::chrono::steady_clock::now() > deadline) return -1;
    std::this_thread::sleep_for(std::chrono::microseconds(100));
  }
  return static_cast<int64_t>(id);
}

bool Endpoint::remove_conn(uint64_t conn_id) {
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(conns_mtx_);
    auto it = conns_.find(conn_id);
    if (it == conns_.end()) return false;
    c = it->second;
    conns_.erase(it);
  }
  ::epoll_ctl(engines_[c->engine]->epoll_fd, EPOLL_CTL_DEL, c->fd, nullptr);
  // Unblock any thread mid-send/recv on this fd; the fd itself closes when
  // the last shared_ptr holder drops (Conn::~Conn), never under a user.
  ::shutdown(c->fd, SHUT_RDWR);
  return true;
}

uint64_t Endpoint::reg(void* ptr, size_t len) {
  Reg r{ptr, len};
  uint64_t id = next_reg_.fetch_add(1);
  std::lock_guard<std::mutex> lk(regs_mtx_);
  regs_[id] = r;
  return id;
}

bool Endpoint::dereg(uint64_t mr_id) {
  std::shared_ptr<std::atomic<int>> pins;
  {
    std::lock_guard<std::mutex> lk(regs_mtx_);
    for (auto it = windows_.begin(); it != windows_.end();) {
      if (it->second.mr_id == mr_id) {
        it = windows_.erase(it);
      } else {
        ++it;
      }
    }
    auto rit = regs_.find(mr_id);
    if (rit == regs_.end()) return false;
    pins = rit->second.pins;
    regs_.erase(rit);
  }
  // Drain in-flight zero-copy receives before the caller may free the buffer.
  while (pins->load(std::memory_order_acquire) > 0) {
    std::this_thread::sleep_for(std::chrono::microseconds(50));
  }
  return true;
}

bool Endpoint::advertise(uint64_t mr_id, size_t offset, size_t len,
                         FifoItem* out) {
  std::lock_guard<std::mutex> lk(regs_mtx_);
  auto it = regs_.find(mr_id);
  if (it == regs_.end() || offset > it->second.len ||
      len > it->second.len - offset) {
    return false;
  }
  uint64_t wid = next_window_.fetch_add(1);
  windows_[wid] = Window{mr_id, offset, len, random_token()};
  std::memset(out, 0, sizeof(*out));
  out->rid = wid;
  out->size = len;
  out->token = windows_[wid].token;
  out->offset = 0;
  return true;
}

// Resolve a (window id, token, offset, len) quadruple from the wire into a
// host pointer, enforcing the advertised byte range with overflow-safe math.
// Returns nullptr if anything is off. Caller must hold regs_mtx_.
void* Endpoint::resolve_window_locked(
    uint64_t wid, uint64_t token, uint64_t offset, uint64_t len,
    std::shared_ptr<std::atomic<int>>* pin_out) {
  auto wit = windows_.find(wid);
  if (wit == windows_.end() || wit->second.token != token) return nullptr;
  const Window& w = wit->second;
  if (offset > w.len || len > w.len - offset) return nullptr;
  auto rit = regs_.find(w.mr_id);
  if (rit == regs_.end()) return nullptr;
  if (pin_out != nullptr) {
    // Caller will touch the memory after dropping regs_mtx_: pin so dereg()
    // blocks until the access completes.
    rit->second.pins->fetch_add(1, std::memory_order_acq_rel);
    *pin_out = rit->second.pins;
  }
  return static_cast<uint8_t*>(rit->second.ptr) + w.offset + offset;
}

std::shared_ptr<Endpoint::Conn> Endpoint::get_conn(uint64_t id) {
  std::lock_guard<std::mutex> lk(conns_mtx_);
  auto it = conns_.find(id);
  return it == conns_.end() ? nullptr : it->second;
}

uint64_t Endpoint::new_xfer() {
  uint64_t id = next_xfer_.fetch_add(1);
  std::lock_guard<std::mutex> lk(xfers_mtx_);
  xfers_[id] = XferState::kPending;
  return id;
}

void Endpoint::complete(uint64_t xfer_id, XferState st) {
  {
    std::lock_guard<std::mutex> lk(xfers_mtx_);
    xfers_[xfer_id] = st;
    if (st == XferState::kError) pending_reads_.erase(xfer_id);
  }
  xfers_cv_.notify_all();
}

void Endpoint::enqueue_task(Task* t) {
  // Route to the engine serving this conn so its tx thread owns the send.
  auto c = get_conn(t->conn_id);
  EngineCtx& eng = *engines_[c ? c->engine : 0];
  {
    std::lock_guard<std::mutex> lk(eng.push_mtx);
    while (!eng.ring.push(t)) std::this_thread::yield();
  }
  eng.cv.notify_one();
}

uint64_t Endpoint::write_async(uint64_t conn_id, const void* src, size_t len,
                               const FifoItem& item) {
  uint64_t xid = new_xfer();
  if (len > item.size) {  // reject over-window writes before they hit the wire
    complete(xid, XferState::kError);
    return xid;
  }
  auto* t = new Task;
  t->conn_id = conn_id;
  t->op = Op::kWrite;
  t->xfer_id = xid;
  t->src = src;
  t->len = len;
  t->item = item;
  enqueue_task(t);
  return xid;
}

uint64_t Endpoint::read_async(uint64_t conn_id, void* dst, size_t len,
                              const FifoItem& item) {
  uint64_t xid = new_xfer();
  if (len > item.size) {
    complete(xid, XferState::kError);
    return xid;
  }
  {
    std::lock_guard<std::mutex> lk(xfers_mtx_);
    pending_reads_[xid] = PendingRead{dst, len};
  }
  auto* t = new Task;
  t->conn_id = conn_id;
  t->op = Op::kRead;
  t->xfer_id = xid;
  t->len = len;
  t->item = item;
  enqueue_task(t);
  return xid;
}

bool Endpoint::write(uint64_t conn_id, const void* src, size_t len,
                     const FifoItem& item) {
  return wait(write_async(conn_id, src, len, item), 30000);
}

bool Endpoint::read(uint64_t conn_id, void* dst, size_t len,
                    const FifoItem& item) {
  return wait(read_async(conn_id, dst, len, item), 30000);
}

bool Endpoint::send(uint64_t conn_id, const void* buf, size_t len) {
  auto c = get_conn(conn_id);
  if (!c) return false;
  FrameHeader h{};
  h.magic = kMagic;
  h.op = static_cast<uint16_t>(Op::kSend);
  h.len = len;
  return send_frame(c.get(), h, buf);
}

int64_t Endpoint::recv(uint64_t conn_id, void* buf, size_t cap,
                       int timeout_ms) {
  std::unique_lock<std::mutex> lk(recvq_mtx_);
  bool ok = recvq_cv_.wait_for(
      lk, std::chrono::milliseconds(timeout_ms),
      [&] { return !recvq_[conn_id].empty() || stop_.load(); });
  if (!ok || recvq_[conn_id].empty()) return -1;
  auto& front = recvq_[conn_id].front();
  if (front.size() > cap) {
    // Leave the message queued; tell the caller the size it needs.
    return -static_cast<int64_t>(front.size()) - 2;
  }
  auto msg = std::move(front);
  recvq_[conn_id].pop_front();
  lk.unlock();
  std::memcpy(buf, msg.data(), msg.size());
  return static_cast<int64_t>(msg.size());
}

XferState Endpoint::poll(uint64_t xfer_id) {
  std::lock_guard<std::mutex> lk(xfers_mtx_);
  auto it = xfers_.find(xfer_id);
  if (it == xfers_.end()) return XferState::kError;
  XferState st = it->second;
  if (st != XferState::kPending) xfers_.erase(it);  // one-shot reclaim
  return st;
}

bool Endpoint::wait(uint64_t xfer_id, int timeout_ms) {
  std::unique_lock<std::mutex> lk(xfers_mtx_);
  bool ok = xfers_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
    auto it = xfers_.find(xfer_id);
    return it == xfers_.end() || it->second != XferState::kPending;
  });
  if (!ok) return false;
  auto it = xfers_.find(xfer_id);
  if (it == xfers_.end()) return false;  // already consumed elsewhere
  XferState st = it->second;
  xfers_.erase(it);  // one-shot reclaim
  return st == XferState::kDone;
}

bool Endpoint::send_frame(Conn* c, const FrameHeader& h, const void* payload) {
  // Fault injection: silently drop the frame (reference kTestLoss,
  // transport_config.h:222) — the transfer then times out at the caller.
  double p = drop_rate_.load();
  if (p > 0.0) {
    static thread_local std::mt19937_64 gen{std::random_device{}()};
    std::uniform_real_distribution<double> d(0.0, 1.0);
    if (d(gen) < p) return true;
  }
  std::lock_guard<std::mutex> lk(c->tx_mtx);
  if (!send_all(c->fd, &h, sizeof(h))) return false;
  if (h.len > 0 && payload != nullptr) {
    if (!send_all(c->fd, payload, h.len)) return false;
  }
  bytes_tx_.fetch_add(sizeof(h) + h.len);
  return true;
}

// Token-bucket pacing: before a payload send, wait until enough tokens have
// accrued. ONE bucket shared by all engines — the cap is the endpoint's
// aggregate egress regardless of how traffic spreads across paths (reference
// analog: the Carousel timing wheel pacing chunk injection,
// collective/rdma/timing_wheel.h).
void Endpoint::pace(EngineCtx& /*eng*/, uint64_t bytes) {
  uint64_t bps = rate_bps_.load(std::memory_order_relaxed);
  if (bps == 0 || bytes == 0) return;
  const double rate = static_cast<double>(bps);
  constexpr double kBurstS = 0.01;  // at most 10ms of credit after idle
  double wait_s = 0.0;
  {
    // Virtual-time leaky bucket: pace_next_ is when the next byte may go.
    // Exact long-run rate (each send advances it by bytes/rate), bounded
    // burst (it can lag `now` by at most kBurstS).
    std::lock_guard<std::mutex> lk(pace_mtx_);
    auto now = std::chrono::steady_clock::now();
    auto floor = now - std::chrono::duration_cast<
                           std::chrono::steady_clock::duration>(
                           std::chrono::duration<double>(kBurstS));
    if (pace_next_ < floor) pace_next_ = floor;
    pace_next_ += std::chrono::duration_cast<std::chrono::steady_clock::duration>(
        std::chrono::duration<double>(bytes / rate));
    // Wait until this frame's own virtual finish time: a single frame larger
    // than the burst window is paced too, not just its successors.
    wait_s = std::chrono::duration<double>(pace_next_ - now).count();
  }
  // Interruptible sleep: never outlive shutdown by more than one slice.
  while (wait_s > 0.0 && !stop_.load(std::memory_order_relaxed)) {
    double slice = std::min(wait_s, 0.01);
    std::this_thread::sleep_for(std::chrono::duration<double>(slice));
    wait_s -= slice;
  }
}

void Endpoint::tx_loop(int engine) {
  EngineCtx& eng = *engines_[engine];
  while (!stop_.load()) {
    Task* t = nullptr;
    if (!eng.ring.pop(&t)) {
      std::unique_lock<std::mutex> lk(eng.cv_mtx);
      eng.cv.wait_for(lk, std::chrono::milliseconds(1));
      continue;
    }
    auto c = get_conn(t->conn_id);
    if (!c) {
      complete(t->xfer_id, XferState::kError);
      delete t;
      continue;
    }
    FrameHeader h{};
    h.magic = kMagic;
    h.op = static_cast<uint16_t>(t->op);
    h.xfer_id = t->xfer_id;
    h.rid = t->item.rid;
    h.token = t->item.token;
    h.offset = t->item.offset;
    h.flags = t->flags;
    if (t->op == Op::kWrite) {
      h.len = t->len;
      pace(eng, t->len);
      if (!send_frame(c.get(), h, t->src))
        complete(t->xfer_id, XferState::kError);
      // completion arrives as kWriteAck
    } else if (t->op == Op::kRead) {
      // kRead frames carry the *requested* length in len, no payload bytes.
      h.len = t->len;
      if (!send_frame(c.get(), h, nullptr))
        complete(t->xfer_id, XferState::kError);
    } else if (t->op == Op::kReadResp) {
      // Read responses are sent from here (not the io thread) so a blocked
      // peer can never wedge the frame-dispatch loop: the io thread stays
      // free to drain inbound bytes while this send backpressures.
      h.rid = 0;
      h.token = 0;
      h.offset = 0;
      h.len = t->owned.size();
      pace(eng, h.len);
      send_frame(c.get(), h, t->owned.data());
    } else if (t->op == Op::kWriteAck) {
      h.rid = 0;
      h.token = 0;
      h.offset = 0;
      h.len = 0;
      send_frame(c.get(), h, nullptr);
    }
    delete t;
  }
}

void Endpoint::handle_frame(Conn* c, const FrameHeader& h,
                            std::vector<uint8_t>& payload) {
  switch (static_cast<Op>(h.op)) {
    // Op::kWrite is fully handled by io_loop's zero-copy fast path.
    case Op::kWriteAck:
      complete(h.xfer_id, h.flags == 0 ? XferState::kDone : XferState::kError);
      break;
    case Op::kRead: {
      // Copy the window contents into a task-owned buffer and hand the
      // (possibly large, blocking) send to the tx proxy thread.
      auto* t = new Task;
      t->conn_id = c->id;
      t->op = Op::kReadResp;
      t->xfer_id = h.xfer_id;
      {
        std::lock_guard<std::mutex> lk(regs_mtx_);
        void* src = resolve_window_locked(h.rid, h.token, h.offset, h.len);
        if (src != nullptr) {
          t->owned.assign(static_cast<uint8_t*>(src),
                          static_cast<uint8_t*>(src) + h.len);
        } else {
          t->flags = 1;
        }
      }
      enqueue_task(t);
      break;
    }
    case Op::kReadResp: {
      PendingRead pr{};
      {
        std::lock_guard<std::mutex> lk(xfers_mtx_);
        auto it = pending_reads_.find(h.xfer_id);
        if (it != pending_reads_.end()) {
          pr = it->second;
          pending_reads_.erase(it);
        }
      }
      if (h.flags == 0 && pr.dst != nullptr && h.len <= pr.len) {
        std::memcpy(pr.dst, payload.data(), h.len);
        complete(h.xfer_id, XferState::kDone);
      } else {
        complete(h.xfer_id, XferState::kError);
      }
      break;
    }
    case Op::kSend: {
      {
        std::lock_guard<std::mutex> lk(recvq_mtx_);
        recvq_[c->id].push_back(std::move(payload));
      }
      recvq_cv_.notify_all();
      break;
    }
    default:
      break;
  }
}

void Endpoint::io_loop(int engine) {
  EngineCtx& eng = *engines_[engine];
  constexpr int kMaxEvents = 64;
  epoll_event events[kMaxEvents];
  while (!stop_.load()) {
    int n = ::epoll_wait(eng.epoll_fd, events, kMaxEvents, 100);
    for (int i = 0; i < n; ++i) {
      uint64_t tag = events[i].data.u64;
      if (tag == 0) {  // wake fd
        uint64_t v;
        ::read(eng.wake_fd, &v, sizeof(v));
        continue;
      }
      if (tag == 1) {  // listener (engine 0 only)
        int fd = ::accept(listen_fd_, nullptr, nullptr);
        if (fd < 0) continue;
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        auto c = std::make_shared<Conn>();
        c->fd = fd;
        c->id = next_conn_.fetch_add(1);
        uint64_t id = c->id;
        register_conn(c);
        if (!accept_queue_.push(id)) {
          // accept backlog overflow: reject the connection rather than leak
          // an id the application can never accept()
          remove_conn(id);
        }
        continue;
      }
      // connection frame
      uint64_t conn_id = tag >> 2;
      auto conn = get_conn(conn_id);
      if (!conn) continue;
      Conn* c = conn.get();
      FrameHeader h{};
      if (!recv_all(c->fd, &h, sizeof(h)) || h.magic != kMagic ||
          h.len > kMaxFrameLen) {
        remove_conn(conn_id);
        continue;
      }
      // Fast path: land write payloads straight into the resolved window —
      // no intermediate buffer, one copy total (the DCN analog of the
      // reference's zero-copy RDMA receive into registered memory).
      if (static_cast<Op>(h.op) == Op::kWrite) {
        void* dst = nullptr;
        std::shared_ptr<std::atomic<int>> pin;
        {
          std::lock_guard<std::mutex> lk(regs_mtx_);
          dst = resolve_window_locked(h.rid, h.token, h.offset, h.len, &pin);
        }
        bool ok = false;
        if (dst != nullptr) {
          ok = recv_all(c->fd, dst, h.len);
          pin->fetch_sub(1, std::memory_order_acq_rel);
          if (!ok) {
            remove_conn(conn_id);
            continue;
          }
        } else if (h.len > 0) {
          // invalid target: drain the payload to keep the stream framed
          std::vector<uint8_t> sink;
          try {
            sink.resize(h.len);
          } catch (const std::exception&) {
            remove_conn(conn_id);
            continue;
          }
          if (!recv_all(c->fd, sink.data(), h.len)) {
            remove_conn(conn_id);
            continue;
          }
        }
        bytes_rx_.fetch_add(sizeof(h) + h.len);
        auto* ack = new Task;
        ack->conn_id = c->id;
        ack->op = Op::kWriteAck;
        ack->xfer_id = h.xfer_id;
        ack->flags = ok ? 0 : 1;
        enqueue_task(ack);
        continue;
      }
      std::vector<uint8_t> payload;
      // kRead carries requested length in h.len but no payload bytes.
      size_t body = (static_cast<Op>(h.op) == Op::kRead) ? 0 : h.len;
      if (body > 0) {
        try {
          payload.resize(body);
        } catch (const std::exception&) {
          remove_conn(conn_id);
          continue;
        }
        if (!recv_all(c->fd, payload.data(), body)) {
          remove_conn(conn_id);
          continue;
        }
      }
      bytes_rx_.fetch_add(sizeof(h) + body);
      handle_frame(c, h, payload);
    }
  }
}

}  // namespace uccl_tpu
