// Order-0 rANS byte codec for the lossless wire path.
//
// The native entropy coder behind uccl_tpu/p2p/lossless.py — the role DietGPU's
// ANS kernels play on the reference's P2P wire (p2p/rdma/compression.h:46,
// thirdparty/dietgpu): the Python layer splits floats into an exponent plane
// (low entropy) and sign+mantissa planes (ship raw), and this codec squeezes
// the compressible planes to within ~1% of order-0 entropy at memory-ish
// speed — where DEFLATE leaves ~20% on the table and runs 50x slower.
//
// Format (self-contained, per call):
//   u8  tag  (1 = rANS, magic check)
//   u64 n    (decoded byte count)
//   u16 freq[256]  (frequencies quantized to sum 1<<PROB_BITS)
//   u8  stream[...]  (rANS bytes, decoder reads forward)
//
// Standard single-state byte-renormalizing rANS (public technique); written
// from scratch for this runtime.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t kProbBits = 12;
constexpr uint32_t kProbScale = 1u << kProbBits;
constexpr uint32_t kRansL = 1u << 23;  // normalization interval lower bound
constexpr uint8_t kTagRans = 1;

struct Header {
  uint64_t n;
  uint16_t freq[256];
};

// Quantize a histogram to sum exactly kProbScale, keeping every present
// symbol at freq >= 1 (largest-remainder style with a greedy fixup).
bool normalize_freqs(const uint64_t* hist, uint64_t total, uint16_t* freq) {
  if (total == 0) return false;
  uint32_t assigned = 0;
  int present = 0;
  double scale = double(kProbScale) / double(total);
  uint32_t f32[256];
  for (int s = 0; s < 256; ++s) {
    if (hist[s] == 0) {
      f32[s] = 0;
      continue;
    }
    ++present;
    uint32_t f = uint32_t(double(hist[s]) * scale);
    if (f == 0) f = 1;
    f32[s] = f;
    assigned += f;
  }
  // fix the sum: push the difference onto the most frequent symbols (cheap
  // and entropy-neutral to first order)
  while (assigned != kProbScale) {
    int best = -1;
    uint64_t best_h = 0;
    for (int s = 0; s < 256; ++s) {
      if (f32[s] == 0) continue;
      if (assigned > kProbScale && f32[s] <= 1) continue;
      if (hist[s] >= best_h) {
        best_h = hist[s];
        best = s;
      }
    }
    if (best < 0) return false;
    if (assigned > kProbScale) {
      --f32[best];
      --assigned;
    } else {
      ++f32[best];
      ++assigned;
    }
  }
  (void)present;
  for (int s = 0; s < 256; ++s) freq[s] = uint16_t(f32[s]);
  return true;
}

}  // namespace

extern "C" {

// Encode n bytes into out (capacity cap). Returns bytes written, or -1 when
// the coded form would not fit in cap (caller ships the plane raw).
int64_t ucclt_codec_encode(const uint8_t* in, int64_t n, uint8_t* out,
                           int64_t cap) {
  if (n <= 0 || cap < int64_t(sizeof(uint8_t) + sizeof(uint64_t) +
                              256 * sizeof(uint16_t) + 8))
    return -1;
  uint64_t hist[256] = {0};
  for (int64_t i = 0; i < n; ++i) ++hist[in[i]];
  uint16_t freq[256];
  if (!normalize_freqs(hist, uint64_t(n), freq)) return -1;
  uint32_t cum[257];
  cum[0] = 0;
  for (int s = 0; s < 256; ++s) cum[s + 1] = cum[s] + freq[s];

  // encode in reverse, emitting renormalization bytes into a scratch buffer
  std::vector<uint8_t> rev;
  rev.reserve(size_t(n));
  uint32_t x = kRansL;
  for (int64_t i = n - 1; i >= 0; --i) {
    uint8_t s = in[i];
    uint32_t f = freq[s];
    // renormalize so the state stays in [kRansL, kRansL << 8) after encode
    uint32_t x_max = ((kRansL >> kProbBits) << 8) * f;
    while (x >= x_max) {
      rev.push_back(uint8_t(x & 0xFF));
      x >>= 8;
    }
    x = ((x / f) << kProbBits) + (x % f) + cum[s];
  }

  int64_t header = 1 + int64_t(sizeof(uint64_t)) + 256 * 2;
  int64_t coded = header + 4 + int64_t(rev.size());
  if (coded > cap) return -1;
  uint8_t* p = out;
  *p++ = kTagRans;
  uint64_t n64 = uint64_t(n);
  std::memcpy(p, &n64, sizeof(n64));
  p += sizeof(n64);
  std::memcpy(p, freq, 256 * 2);
  p += 256 * 2;
  // final state, little-endian, then the stream in forward (decode) order
  for (int b = 0; b < 4; ++b) *p++ = uint8_t((x >> (8 * b)) & 0xFF);
  for (size_t i = rev.size(); i > 0; --i) *p++ = rev[i - 1];
  return coded;
}

// Decode a blob produced by ucclt_codec_encode. out must hold out_n bytes
// (the caller knows the plane size). Returns bytes produced or -1.
int64_t ucclt_codec_decode(const uint8_t* in, int64_t in_n, uint8_t* out,
                           int64_t out_n) {
  int64_t header = 1 + int64_t(sizeof(uint64_t)) + 256 * 2;
  if (in_n < header + 4 || in[0] != kTagRans) return -1;
  uint64_t n64;
  std::memcpy(&n64, in + 1, sizeof(n64));
  if (int64_t(n64) != out_n) return -1;
  uint16_t freq[256];
  std::memcpy(freq, in + 1 + sizeof(n64), 256 * 2);
  uint32_t cum[257];
  cum[0] = 0;
  for (int s = 0; s < 256; ++s) cum[s + 1] = cum[s] + freq[s];
  if (cum[256] != kProbScale) return -1;
  // slot -> symbol table
  std::vector<uint8_t> slot2sym(kProbScale);
  for (int s = 0; s < 256; ++s)
    for (uint32_t j = cum[s]; j < cum[s + 1]; ++j) slot2sym[j] = uint8_t(s);

  const uint8_t* p = in + header;
  const uint8_t* end = in + in_n;
  uint32_t x = 0;
  for (int b = 0; b < 4; ++b) x |= uint32_t(*p++) << (8 * b);
  for (int64_t i = 0; i < out_n; ++i) {
    uint32_t slot = x & (kProbScale - 1);
    uint8_t s = slot2sym[slot];
    out[i] = s;
    x = uint32_t(freq[s]) * (x >> kProbBits) + slot - cum[s];
    while (x < kRansL) {
      if (p >= end) return -1;
      x = (x << 8) | *p++;
    }
  }
  return out_n;
}

}  // extern "C"
