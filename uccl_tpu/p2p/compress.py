"""Float compression for the DCN wire: fp8 + per-group scales, host-side.

The analog of the reference's DietGPU wire compression
(p2p/rdma/compression.h:46 FloatCompressCtx — strategy + threshold knobs for
fp16/bf16/fp32 payloads on the P2P path). Our codec quantizes to
``float8_e4m3fn`` with per-group f32 scales — the same wire format the EP
fast path uses on-mesh (ops/quant.py), here as a pure-numpy host codec so the
transfer engine can move KV caches / weights at ~3.5-3.8x fewer bytes.

Blobs are self-describing (header carries dtype/shape/group), so the window
owner can decode with no side channel:

    blob = encode_fp8(arr)           # np.uint8, ratio ~3.84x for f32
    arr2 = decode_fp8(blob)          # dtype+shape restored, |err| <~ 2%

``maybe_compress`` applies the reference-style threshold policy: payloads
below ``UCCL_TPU_COMPRESS_MIN_BYTES`` or of non-float dtype pass through.
"""

from __future__ import annotations

import struct
from typing import Tuple

import ml_dtypes
import numpy as np

from uccl_tpu.utils.config import param

_min_bytes = param(
    "compress_min_bytes", 64 * 1024,
    help="payloads below this (or non-float) skip wire compression",
)

FP8 = np.dtype(ml_dtypes.float8_e4m3fn)
FP8_MAX = 448.0  # max normal of e4m3fn

_MAGIC = 0x55435138  # "UCQ8"
# dtype codes in the header
_DTYPES = {0: np.dtype(np.float32), 1: np.dtype(ml_dtypes.bfloat16),
           2: np.dtype(np.float16)}
_CODES = {v: k for k, v in _DTYPES.items()}
_HDR = struct.Struct("<IBBBBIQ")  # magic, ver, dtype, ndim, pad, group, elems


def compressible(arr: np.ndarray) -> bool:
    return arr.dtype in _CODES


def compressed_bound(shape, dtype, group: int = 128) -> int:
    """Max blob bytes for an array of this shape/dtype — what the window
    owner should advertise for a compressed transfer."""
    elems = int(np.prod(shape))
    padded = ((elems + group - 1) // group) * group
    n_groups = padded // group
    ndim = len(tuple(shape))
    return _HDR.size + 8 * ndim + 4 * n_groups + padded


def encode_fp8(arr: np.ndarray, group: int = 128) -> np.ndarray:
    """Encode a float array into a self-describing uint8 blob."""
    if arr.dtype not in _CODES:
        raise TypeError(f"cannot fp8-compress dtype {arr.dtype}")
    if arr.ndim > 255:
        raise ValueError("too many dimensions")
    flat = np.ascontiguousarray(arr).reshape(-1).astype(np.float32)
    elems = flat.size
    padded = ((elems + group - 1) // group) * group
    if padded != elems:
        flat = np.concatenate([flat, np.zeros(padded - elems, np.float32)])
    g = flat.reshape(-1, group)
    amax = np.max(np.abs(g), axis=1)
    scale = np.maximum(amax, 1e-12) / FP8_MAX
    q = (g / scale[:, None]).astype(FP8)
    hdr = _HDR.pack(_MAGIC, 1, _CODES[arr.dtype], arr.ndim, 0, group, elems)
    shape = np.asarray(arr.shape, np.uint64).tobytes()
    return np.frombuffer(
        hdr + shape + scale.astype(np.float32).tobytes() + q.tobytes(),
        np.uint8,
    ).copy()


def decode_fp8(blob) -> np.ndarray:
    """Decode a blob (or a window prefix containing one) back to the
    original dtype/shape. |error| is bounded by the fp8 relative step
    (~2^-3 of each group's max)."""
    # zero-copy view: the window may be huge (a whole KV cache)
    buf = memoryview(np.ascontiguousarray(np.asarray(blob, np.uint8)))
    if len(buf) < _HDR.size:
        raise ValueError("blob shorter than header")
    magic, ver, dcode, ndim, _, group, elems = _HDR.unpack_from(buf, 0)
    if magic != _MAGIC or ver != 1 or dcode not in _DTYPES:
        raise ValueError("not an fp8 wire blob")
    off = _HDR.size
    shape = tuple(np.frombuffer(buf, np.uint64, ndim, off).astype(int))
    off += 8 * ndim
    padded = ((elems + group - 1) // group) * group
    n_groups = padded // group
    scale = np.frombuffer(buf, np.float32, n_groups, off)
    off += 4 * n_groups
    q = np.frombuffer(buf, FP8, padded, off).astype(np.float32)
    out = (q.reshape(-1, group) * scale[:, None]).reshape(-1)[:elems]
    return out.astype(_DTYPES[dcode]).reshape(shape)


def maybe_compress(arr: np.ndarray, group: int = 128) -> Tuple[np.ndarray, bool]:
    """Threshold policy (reference kMinCompressBytes, compression.h:8):
    returns (payload, True) when compression applies, else (arr, False)."""
    if not compressible(arr) or arr.nbytes < int(_min_bytes.get()):
        return arr, False
    return encode_fp8(arr, group), True


# -- codec dispatch ----------------------------------------------------------
# The reference picks a compression strategy per transfer
# (CompressStrategy, p2p/rdma/compression.h:14); here the two wire codecs are
# fp8 (lossy, ~3.8x) and lossless (byte-plane + native rANS, ~1.5x on bf16
# weights, exact — the DietGPU analog, uccl_tpu/p2p/lossless.py).


def encode(arr: np.ndarray, codec: str = "fp8", group: int = 128) -> np.ndarray:
    """Encode with the named codec ("fp8" | "lossless")."""
    if codec == "fp8":
        return encode_fp8(arr, group)
    if codec == "lossless":
        from uccl_tpu.p2p.lossless import encode_lossless

        return encode_lossless(arr)
    raise ValueError(f"unknown wire codec {codec!r}")


def decode_any(blob) -> np.ndarray:
    """Decode a wire blob of either codec (routed by magic)."""
    buf = np.ascontiguousarray(np.asarray(blob, np.uint8))
    if buf.nbytes < 4:
        raise ValueError("blob shorter than any codec header")
    magic = int(np.frombuffer(buf, np.uint32, 1, 0)[0])
    if magic == _MAGIC:
        return decode_fp8(buf)
    from uccl_tpu.p2p import lossless

    if magic == lossless.MAGIC:
        return lossless.decode_lossless(buf)
    raise ValueError(f"unknown wire codec magic 0x{magic:08x}")
