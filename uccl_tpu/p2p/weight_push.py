"""Versioned fleet weight distribution over the p2p plane (ISSUE 14).

Serving fleets replicate the same model to N peers constantly — replica
spin-up, elastic-resize warm spares, RL-style weight refresh — and the
naive shape is N point-to-point copies out of one root (the root's egress
is the bottleneck: time-to-consistent-fleet grows linearly in N). This
module makes a fleet update ONE planned pipeline instead:

* a :class:`WeightPublisher` registers a named, **versioned** param-tree
  snapshot — the tree is flattened into dtype-tagged contiguous slabs
  (optionally wire-compressed through the shared host codec,
  :mod:`uccl_tpu.p2p.compress`) behind a JSON manifest with per-group and
  whole-snapshot CRCs;
* subscribers **fetch-or-forward** in a relay chain: every node advertises
  one receive window, the upstream ships slab *groups* over the PR 13
  windowed SACK transport (``Channel.writev`` — chunk-granular, selective
  repeat, path steering, pull-credit-eligible), and a relay node forwards
  group g downstream the moment its CRC lands while group g+1 is still in
  flight from upstream — the root ships each chunk ONCE and the chain's
  completion time is ~one snapshot time plus (N-1) group times, sublinear
  in N (benchmarks/weight_push_bench.py measures it);
* every peer's received tree is verified (CRC per group + whole snapshot)
  and — because a lossy wire codec is applied ONCE at publish, making the
  published version its own canonical bytes — **bit-exact against the
  published version** on every peer, however many relay hops it crossed.

Wire accounting: served bytes land on
``weight_push_bytes_total{role="tx",name,src}`` (``src="publisher"`` vs
``"relay"`` splits root egress from peer forwarding) and fetched bytes on
``{role="rx",name}`` plus the fleet byte plane
``p2p_bytes_total{verb="weight_push"}`` — the service-level INGRESS verb
(tx bytes already ride the transport-level ``write`` series, so a
multi-process fleet's per-process audits see each byte once);
``weight_push_versions_total{name}`` counts publishes and
``weight_push_peers_total{name}`` counts peers reaching consistency. Each
fetch/serve runs under a ``weight_push.*`` trace span carrying the
version (docs/OBSERVABILITY.md).

Consumers: ``serving.replicate_backend(..., weights=snapshot)`` spins
replicas up on a fetched version, and ``ep.elastic.admit_warm_spare``
imports one into an :class:`~uccl_tpu.ep.elastic.ElasticBuffer` as the
warm-spare admission path.
"""

from __future__ import annotations

import json
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from uccl_tpu import obs
from uccl_tpu.p2p.channel import Channel, FifoItem
from uccl_tpu.utils.config import param
from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")

_group_bytes = param(
    "push_group_bytes",
    1 << 20,
    help="weight-push pipeline granularity: slab groups of about this many "
    "bytes are shipped (and relay-forwarded) as independent windowed "
    "transfers — smaller groups deepen the relay pipeline, larger ones "
    "amortize per-transfer overhead",
)

_PUSH_BYTES = obs.counter(
    "weight_push_bytes_total",
    "weight-push payload bytes by role (tx = served to a downstream peer, "
    "rx = fetched from upstream) and snapshot name",
)
_PUSH_VERSIONS = obs.counter(
    "weight_push_versions_total",
    "published weight-snapshot versions by name",
)
_PUSH_PEERS = obs.counter(
    "weight_push_peers_total",
    "peers that completed a verified fetch (reached consistency) by name",
)
_PUSH_RESUMED = obs.counter(
    "weight_push_resumed_groups_total",
    "slab groups SKIPPED on a resumed fetch because the partial buffer "
    "from the failed attempt already held them CRC-verified — the "
    "counter-audited face of not re-shipping a whole snapshot on a "
    "mid-transfer error",
)
# the one shared p2p byte family (p2p/endpoint.py declares it): the
# service-level verb beside the transport-level write/read/send series
_P2P_BYTES = obs.counter(
    "p2p_bytes_total",
    "bytes moved through p2p endpoints by verb",
)

_MAGIC = b"UWP1"


class FetchError(IOError):
    """A fetch died mid-transfer. ``partial`` is the WeightSnapshot as
    far as it got (manifest + partially-filled buffer) and ``groups_ok``
    the groups whose CRCs verified before the failure — pass it back as
    ``fetch(..., resume=err.partial)`` and only the missing groups cross
    the wire again (counted on ``weight_push_resumed_groups_total``)."""

    def __init__(self, msg: str, partial: "WeightSnapshot" = None,
                 groups_ok: Optional[List[int]] = None):
        super().__init__(msg)
        self.partial = partial
        self.groups_ok = list(groups_ok or [])


# -- param-tree <-> flat slabs ------------------------------------------------


def flatten_tree(tree) -> List[Tuple[str, np.ndarray]]:
    """Flatten a nested dict/list/tuple of arrays into sorted
    (dotted-path, contiguous array) pairs — the jax-free pytree walk the
    wire format is defined over. Leaves are anything np.asarray accepts
    (jax arrays stage to host here)."""
    out: List[Tuple[str, np.ndarray]] = []

    def walk(node, path):
        if isinstance(node, dict):
            if not node:
                raise ValueError(f"empty dict at {path or '<root>'}")
            for k in sorted(node):
                walk(node[k], f"{path}.{k}" if path else str(k))
            return
        if isinstance(node, (list, tuple)):
            if not node:
                raise ValueError(f"empty sequence at {path or '<root>'}")
            for i, v in enumerate(node):
                walk(v, f"{path}.{i}" if path else str(i))
            return
        arr = np.ascontiguousarray(np.asarray(node))
        out.append((path, arr))

    walk(tree, "")
    if not out:
        raise ValueError("empty param tree")
    out.sort(key=lambda kv: kv[0])
    return out


def unflatten_tree(pairs: Dict[str, np.ndarray]):
    """Rebuild the nested structure from dotted paths (a node whose keys
    are all decimal strings becomes a list — the flatten convention)."""
    root: Dict = {}
    for path, arr in pairs.items():
        parts = path.split(".")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr

    def build(node):
        if not isinstance(node, dict):
            return node
        if node and all(k.isdigit() for k in node):
            return [build(node[k]) for k in
                    sorted(node, key=int)]
        return {k: build(v) for k, v in node.items()}

    return build(root)


def _encode_entry(arr: np.ndarray, wire: Optional[str]
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """(canonical value, wire slab) of one entry. ``wire=None`` ships raw
    bytes (canonical == input). A wire codec is applied ONCE here — the
    published version's canonical value IS the decoded wire bytes, so
    every peer (any relay depth) is bit-exact against the published
    version; ``fp8`` costs one documented quantize round trip vs the
    INPUT, ``lossless`` none."""
    if wire is None:
        return arr, arr.reshape(-1).view(np.uint8)
    from uccl_tpu.p2p import compress

    if wire == "fp8" and not np.issubdtype(arr.dtype, np.floating):
        # non-float leaves (step counters, token ids) ship raw — the same
        # non-float downgrade rule as the device wire codec
        return arr, arr.reshape(-1).view(np.uint8)
    blob = compress.encode(arr, wire)
    return compress.decode_any(blob), blob


def _decode_entry(raw: np.ndarray, ent: dict) -> np.ndarray:
    if ent["enc"] == "raw":
        return (raw.view(np.dtype(ent["dtype"]))
                .reshape([int(s) for s in ent["shape"]]).copy())
    from uccl_tpu.p2p import compress

    return compress.decode_any(raw.copy())


class WeightSnapshot:
    """One named, versioned param-tree snapshot in wire form: a JSON
    manifest + a flat byte buffer holding every entry's slab. The
    publisher's stored record and the subscriber's fetch result are the
    same type — which is exactly what lets a relay node forward verbatim
    and re-serve."""

    def __init__(self, manifest: dict, buf: np.ndarray):
        self.manifest = manifest
        self.buf = buf  # flat uint8, manifest["total"] bytes

    @property
    def name(self) -> str:
        return self.manifest["name"]

    @property
    def version(self) -> int:
        return int(self.manifest["version"])

    @property
    def total_bytes(self) -> int:
        return int(self.manifest["total"])

    def flat(self) -> Dict[str, np.ndarray]:
        """{dotted path: decoded array} — the canonical published values."""
        out = {}
        for ent in self.manifest["entries"]:
            off, nb = int(ent["offset"]), int(ent["nbytes"])
            out[ent["key"]] = _decode_entry(self.buf[off:off + nb], ent)
        return out

    def tree(self):
        """The param tree rebuilt from the slabs (bit-exact vs what the
        publisher canonicalized)."""
        return unflatten_tree(self.flat())

    # replicate_backend/elastic call this to know the wire already counted
    params = tree

    def group_range(self, g: int) -> Tuple[int, int]:
        ents = self.manifest["entries"]
        lo, hi = self.manifest["groups"][g]
        return int(ents[lo]["offset"]), int(
            ents[hi - 1]["offset"]) + int(ents[hi - 1]["nbytes"])

    def group_crc(self, g: int) -> int:
        a, b = self.group_range(g)
        return zlib.crc32(self.buf[a:b])

    def crc(self) -> int:
        return zlib.crc32(self.buf)


def _build_snapshot(name: str, version: int, tree,
                    wire: Optional[str], group_bytes: int
                    ) -> WeightSnapshot:
    pairs = flatten_tree(tree)
    entries, slabs = [], []
    off = 0
    for key, arr in pairs:
        _canon, slab = _encode_entry(arr, wire)
        raw = wire is None or (wire == "fp8"
                               and not np.issubdtype(arr.dtype,
                                                     np.floating))
        entries.append({
            "key": key, "dtype": np.dtype(arr.dtype).name,
            "shape": list(arr.shape), "nbytes": int(slab.nbytes),
            "offset": off, "enc": "raw" if raw else wire,
        })
        slabs.append(slab)
        off += slab.nbytes
    # entry groups of ~group_bytes: the pipeline (and relay-forward) unit
    groups: List[List[int]] = []
    lo = 0
    acc = 0
    for i, ent in enumerate(entries):
        acc += ent["nbytes"]
        if acc >= group_bytes or i == len(entries) - 1:
            groups.append([lo, i + 1])
            lo, acc = i + 1, 0
    buf = np.concatenate([s.reshape(-1).view(np.uint8) for s in slabs]) \
        if slabs else np.zeros(0, np.uint8)
    manifest = {
        "name": name, "version": int(version), "wire": wire,
        "entries": entries, "groups": groups, "total": int(buf.nbytes),
        "crc": zlib.crc32(buf),
    }
    snap = WeightSnapshot(manifest, buf)
    # per-group crcs recorded so relays can verify before forwarding
    manifest["group_crcs"] = [snap.group_crc(g) for g in range(len(groups))]
    return snap


# -- the wire protocol --------------------------------------------------------
#
# Control messages ride the channel's ordered path-0 send/recv as
# MAGIC + JSON; the data plane is one-sided windowed writev into the
# subscriber's advertised whole-snapshot window, one transfer per group.


def _send_msg(chan: Channel, msg: dict) -> None:
    chan.send(_MAGIC + json.dumps(msg).encode())


def _recv_msg(chan: Channel, timeout_ms: int) -> dict:
    raw = chan.recv(timeout_ms=timeout_ms)
    if not raw.startswith(_MAGIC):
        raise IOError(f"weight_push: bad control frame {raw[:8]!r}")
    return json.loads(raw[len(_MAGIC):].decode())


def _serve_groups(chan: Channel, snap: WeightSnapshot, fifo: bytes,
                  timeout_ms: int, have_group=None,
                  src: str = "publisher", skip=frozenset()) -> None:
    """Ship every group of ``snap`` into the peer's window ``fifo`` — one
    windowed writev per group, a group control msg after each (the relay
    pipeline tick). ``have_group(g)`` blocks until group g's bytes are
    locally valid (a relay mid-fetch); None means all bytes are resident
    (the publisher). ``src`` labels the tx byte series
    (publisher|relay) — the counter-audited form of "the root ships each
    chunk once": under a relay chain the publisher-labeled tx bytes stay
    ONE snapshot however many peers reach consistency. ``skip`` holds
    groups the peer already verified locally (a resumed fetch): no bytes
    move, just a ``skipped`` control tick keeping the in-order group
    protocol intact."""
    item = FifoItem.unpack(fifo)
    if item.size < snap.total_bytes:
        raise IOError(
            f"weight_push: peer window {item.size}B < snapshot "
            f"{snap.total_bytes}B"
        )
    name = snap.name
    for g in range(len(snap.manifest["groups"])):
        if g in skip:
            _send_msg(chan, {"op": "group", "idx": g, "skipped": True,
                             "crc": int(snap.manifest["group_crcs"][g])})
            continue
        if have_group is not None:
            have_group(g)
        a, b = snap.group_range(g)
        lo, hi = snap.manifest["groups"][g]
        srcs, fifos = [], []
        for ent in snap.manifest["entries"][lo:hi]:
            off, nb = int(ent["offset"]), int(ent["nbytes"])
            srcs.append(snap.buf[off:off + nb])
            fifos.append(item.slice(off, nb).pack())
        with obs.span("weight_push.group", track="wire", snapshot=name,
                      version=snap.version, group=g, bytes=b - a):
            chan.writev(srcs, fifos, timeout_ms=timeout_ms)
        # the p2p_bytes_total{verb="weight_push"} series counts weight
        # INGRESS (fetch/import side) — tx bytes ride the transport-level
        # verb="write" series the writev already lands on, so a
        # multi-process fleet's per-process audits see each byte once
        _PUSH_BYTES.inc(b - a, role="tx", name=name, src=src)
        _send_msg(chan, {"op": "group", "idx": g,
                         "crc": int(snap.manifest["group_crcs"][g])})
    _send_msg(chan, {"op": "done", "crc": int(snap.manifest["crc"])})


class WeightPublisher:
    """The root of the push plane: holds named, versioned snapshots and
    serves fetches over channels."""

    def __init__(self, group_bytes: Optional[int] = None,
                 keep_versions: int = 2):
        self.group_bytes = group_bytes or _group_bytes.get()
        self.keep_versions = max(1, int(keep_versions))
        self._lock = threading.Lock()
        # name -> {version: WeightSnapshot}, insertion-ordered
        self._store: Dict[str, Dict[int, WeightSnapshot]] = {}

    def publish(self, name: str, tree, *, wire: Optional[str] = None,
                version: Optional[int] = None) -> int:
        """Register a snapshot of ``tree`` under ``name``; returns its
        version (auto-incremented unless pinned). ``wire`` ∈ {None,
        "fp8", "lossless"} — applied ONCE here, so the stored version is
        its own canonical bytes (module docstring)."""
        if wire not in (None, "fp8", "lossless"):
            raise ValueError(f"unknown weight-push wire {wire!r}")
        with self._lock:
            versions = self._store.setdefault(name, {})
            if version is None:
                version = max(versions) + 1 if versions else 1
            elif version in versions:
                raise ValueError(f"{name} v{version} already published")
        with obs.span("weight_push.publish", track="wire", snapshot=name,
                      version=version, wire=wire or "none"):
            snap = _build_snapshot(name, version, tree, wire,
                                   self.group_bytes)
        with self._lock:
            versions[int(version)] = snap
            while len(versions) > self.keep_versions:
                del versions[min(versions)]
        _PUSH_VERSIONS.inc(name=name)
        _log.info("weight_push: published %s v%d (%d entries, %d B%s)",
                  name, version, len(snap.manifest["entries"]),
                  snap.total_bytes, f", wire={wire}" if wire else "")
        return int(version)

    def get(self, name: str, version: Optional[int] = None
            ) -> WeightSnapshot:
        with self._lock:
            versions = self._store.get(name)
            if not versions:
                raise KeyError(f"no published snapshot named {name!r}")
            v = max(versions) if version is None else int(version)
            if v not in versions:
                raise KeyError(f"{name} v{v} not available "
                               f"(have {sorted(versions)})")
            return versions[v]

    def serve(self, chan: Channel, timeout_ms: int = 60000
              ) -> Tuple[str, int]:
        """Handle ONE fetch request on ``chan`` (blocking): manifest →
        window → groups → done. Returns (name, version) served."""
        req = _recv_msg(chan, timeout_ms)
        if req.get("op") != "fetch":
            raise IOError(f"weight_push: expected fetch, got {req}")
        snap = self.get(req["name"], req.get("version"))
        with obs.span("weight_push.serve", track="wire", snapshot=snap.name,
                      version=snap.version):
            _send_msg(chan, {"op": "manifest", **snap.manifest})
            win = _recv_msg(chan, timeout_ms)
            if win.get("op") != "window":
                raise IOError(f"weight_push: expected window, got {win}")
            _serve_groups(chan, snap, bytes.fromhex(win["fifo"]),
                          timeout_ms,
                          skip=frozenset(win.get("have", [])))
        return snap.name, snap.version

    def serve_forever(self, chan: Channel, timeout_ms: int = 60000):
        """Daemon helper: serve fetches on ``chan`` until it dies.
        Returns the started thread. A dying loop is never silent (the
        Channel CC-probe rule): the terminating exception is counted on
        ``weight_push_serve_errors_total{reason}`` and logged — a
        timed-out idle recv (no fetch arrived) is the one quiet exit."""

        def loop():
            while True:
                try:
                    self.serve(chan, timeout_ms)
                except TimeoutError:
                    return  # idle channel: nobody fetched within the window
                except Exception as e:
                    obs.counter(
                        "weight_push_serve_errors_total",
                        "weight-push serve loops terminated by an "
                        "exception, by exception class",
                    ).inc(reason=type(e).__name__)
                    _log.warning(
                        "weight_push: serve loop terminating (%s: %s)",
                        type(e).__name__, e,
                    )
                    return

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t


def _resume_groups(resume: Optional[WeightSnapshot], man: Dict,
                   buf: np.ndarray) -> List[int]:
    """CRC-verify which groups of a prior partial fetch already match
    ``man``'s published bytes, copy them into ``buf``, and return their
    indices — the guarded skip list of a resumed fetch. A resume against
    a DIFFERENT snapshot/version (the publisher moved on mid-retry)
    matches nothing and the fetch falls back to a full transfer."""
    if resume is None:
        return []
    rman = resume.manifest
    if (rman.get("name") != man["name"]
            or rman.get("version") != man["version"]
            or int(rman.get("total", -1)) != int(man["total"])
            or rman.get("group_crcs") != man["group_crcs"]
            or resume.buf.nbytes != buf.nbytes):
        return []
    tmp = WeightSnapshot(man, resume.buf)  # range math off the manifest
    have = []
    for g in range(len(man["groups"])):
        a, b = tmp.group_range(g)
        if zlib.crc32(resume.buf[a:b]) == int(man["group_crcs"][g]):
            buf[a:b] = resume.buf[a:b]
            have.append(g)
    return have


def fetch(chan: Channel, name: str, *, version: Optional[int] = None,
          forward_to: Sequence[Channel] = (), timeout_ms: int = 60000,
          resume: Optional[WeightSnapshot] = None,
          on_group=None) -> WeightSnapshot:
    """Fetch ``name`` (latest or pinned ``version``) from the upstream on
    ``chan``; with ``forward_to``, act as a relay — downstream peers'
    fetch requests are accepted against the SAME manifest and every
    verified group is forwarded the moment it lands, while later groups
    are still in flight from upstream (the pipeline that makes
    time-to-consistent-fleet sublinear in N). Returns the verified
    snapshot; raises :class:`FetchError` on CRC mismatch, version skew
    or a mid-transfer failure — the error carries the partial snapshot,
    and passing it back as ``resume=`` skips every group whose CRC
    already verified (counted ``weight_push_resumed_groups_total``)
    instead of restarting the whole snapshot. ``on_group(g)`` fires as
    each group verifies (progress hook)."""
    ep = chan.ep
    _send_msg(chan, {"op": "fetch", "name": name, "version": version})
    man = _recv_msg(chan, timeout_ms)
    if man.get("op") != "manifest":
        raise IOError(f"weight_push: expected manifest, got {man}")
    man = {k: v for k, v in man.items() if k != "op"}
    buf = np.zeros(int(man["total"]), np.uint8)
    snap = WeightSnapshot(man, buf)
    mr = ep.reg(buf)
    n_groups = len(man["groups"])
    got = threading.Event()
    lock = threading.Lock()
    landed: set = set()  # groups verified locally
    dead = [False]  # upstream fetch aborted: wake + fail the forwarders
    fail: List[BaseException] = []
    have = _resume_groups(resume, man, buf)
    if have:
        _PUSH_RESUMED.inc(len(have))
        landed.update(have)
        obs.instant("weight_push.resume", track="wire",
                    snapshot=man["name"], version=man["version"],
                    groups=len(have))

    def have_group(g: int):
        while True:
            with lock:
                if g in landed:
                    return
            if fail or dead[0]:
                raise IOError("weight_push: upstream fetch failed")
            got.wait(0.05)
            got.clear()

    def mark(g: int):
        with lock:
            landed.add(g)
        got.set()
        if on_group is not None:
            on_group(g)

    # downstream relays: accept each peer's fetch, hand it OUR manifest
    # (same name/version/groups), then forward groups as they land
    down_threads = []
    try:
        fifo = ep.advertise(mr)
        with obs.span("weight_push.fetch", track="wire",
                      snapshot=man["name"], version=man["version"],
                      relay=len(forward_to)):
            for dchan in forward_to:
                req = _recv_msg(dchan, timeout_ms)
                if req.get("op") != "fetch" or req["name"] != man["name"]:
                    raise IOError(f"weight_push: bad relay fetch {req}")
                if req.get("version") not in (None, man["version"]):
                    raise IOError(
                        f"weight_push: relay peer wants v{req['version']}"
                        f", upstream serves v{man['version']}"
                    )
                _send_msg(dchan, {"op": "manifest", **man})
                win = _recv_msg(dchan, timeout_ms)
                if win.get("op") != "window":
                    raise IOError(f"weight_push: expected window, got {win}")

                def fwd(dc=dchan, wf=bytes.fromhex(win["fifo"]),
                        sk=frozenset(win.get("have", []))):
                    try:
                        _serve_groups(dc, snap, wf, timeout_ms,
                                      have_group=have_group, src="relay",
                                      skip=sk)
                    except BaseException as e:  # surfaced on join below
                        fail.append(e)

                t = threading.Thread(target=fwd, daemon=True)
                t.start()
                down_threads.append(t)
            _send_msg(chan, {"op": "window", "fifo": fifo.hex(),
                             "have": have})
            for g in range(n_groups):
                msg = _recv_msg(chan, timeout_ms)
                if msg.get("op") != "group" or msg["idx"] != g:
                    raise IOError(f"weight_push: expected group {g}, "
                                  f"got {msg}")
                if msg.get("skipped"):
                    # our own resume skip, ticked back in order: the
                    # bytes were CRC-verified before the window opened
                    continue
                if snap.group_crc(g) != int(msg["crc"]):
                    raise IOError(
                        f"weight_push: group {g} CRC mismatch (wire "
                        f"corruption past the SACK layer)"
                    )
                a, b = snap.group_range(g)
                _PUSH_BYTES.inc(b - a, role="rx", name=man["name"])
                _P2P_BYTES.inc(b - a, verb="weight_push")
                mark(g)
            done = _recv_msg(chan, timeout_ms)
            if done.get("op") != "done" or snap.crc() != int(done["crc"]):
                raise IOError("weight_push: snapshot CRC mismatch")
            for t in down_threads:
                t.join(timeout=timeout_ms / 1e3)
            if fail:
                raise IOError(
                    f"weight_push: downstream forward failed: {fail[0]!r}"
                )
        _PUSH_PEERS.inc(name=man["name"])
        obs.instant("weight_push.consistent", track="wire",
                    snapshot=man["name"], version=man["version"])
        return snap
    except Exception as e:
        # Exception, not BaseException: KeyboardInterrupt/SystemExit must
        # terminate, not be rewrapped into the retry-with-resume contract
        ok = sorted(landed)
        raise FetchError(
            f"weight_push: fetch of {man['name']} v{man['version']} "
            f"failed with {len(ok)}/{n_groups} groups verified "
            f"({type(e).__name__}: {e}) — retry with resume= to skip "
            f"them", partial=snap, groups_ok=ok,
        ) from e
    finally:
        dead[0] = True  # no-op after success (every group landed)
        got.set()
        ep.dereg(mr)
