"""Congestion control for the DCN engine: Timely and Swift rate controllers.

TPU-native re-design of the reference's pluggable CC layer
(include/cc/timely.h:49 TimelyCC — RTT-gradient rate control, SIGCOMM'15;
include/cc/swift.h:42 SwiftCC — delay-target cwnd, SIGCOMM'20). On the DCN
engine the actuator is the endpoint's token-bucket pacer
(``Endpoint.set_rate_limit``) rather than per-QP pacing; the sensor is the
measured completion RTT of chunk transfers. The algorithms themselves are
pure-python, unit-testable state machines — same role as the reference's
header-only CC classes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")


@dataclasses.dataclass
class TimelyCC:
    """RTT-gradient rate control.

    Rate increases additively while RTT gradients are flat/negative, and
    decreases multiplicatively proportional to the normalized gradient when
    RTTs grow (the HAI/gradient scheme of the paper, as in the reference's
    include/cc/timely.h parameter block :20-26).
    """

    min_rtt_us: float = 50.0
    t_low_us: float = 100.0
    t_high_us: float = 5000.0
    add_step: float = 10e6  # additive increase, bytes/s
    beta: float = 0.8  # multiplicative decrease factor
    ewma_alpha: float = 0.46
    rate: float = 100e6  # current rate, bytes/s
    max_rate: float = 12.5e9
    min_rate: float = 1e6

    _prev_rtt: Optional[float] = None
    _gradient: float = 0.0
    _hai_count: int = 0

    def on_rtt(self, rtt_us: float) -> float:
        """Feed one RTT sample; returns the new rate (bytes/s)."""
        if self._prev_rtt is None:
            self._prev_rtt = rtt_us
            return self.rate
        delta = rtt_us - self._prev_rtt
        self._prev_rtt = rtt_us
        norm_grad = (
            self.ewma_alpha * (delta / self.min_rtt_us)
            + (1 - self.ewma_alpha) * self._gradient
        )
        self._gradient = norm_grad

        if rtt_us < self.t_low_us:
            self._hai_count += 1
            boost = 5 if self._hai_count >= 5 else 1
            self.rate += boost * self.add_step
        elif rtt_us > self.t_high_us:
            self._hai_count = 0
            self.rate *= 1 - self.beta * (1 - self.t_high_us / rtt_us)
        elif norm_grad <= 0:
            self._hai_count += 1
            boost = 5 if self._hai_count >= 5 else 1
            self.rate += boost * self.add_step
        else:
            self._hai_count = 0
            self.rate *= 1 - self.beta * min(norm_grad, 1.0)
        self.rate = min(max(self.rate, self.min_rate), self.max_rate)
        return self.rate


@dataclasses.dataclass
class SwiftCC:
    """Delay-target congestion window control (cwnd in bytes).

    AIMD around a target delay: grow additively when the measured delay is
    under target, back off multiplicatively (bounded per-RTT) when over —
    the reference's include/cc/swift.h scheme with flow-scaling omitted
    (single flow per channel here).
    """

    target_delay_us: float = 300.0
    additive_inc: float = 64 * 1024  # bytes per update under target
    beta: float = 0.7  # max multiplicative decrease
    cwnd: float = 1e6
    min_cwnd: float = 64 * 1024
    max_cwnd: float = 1e9

    _last_decrease: float = 0.0

    def on_delay(self, delay_us: float, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        if delay_us < self.target_delay_us:
            self.cwnd += self.additive_inc
        else:
            # at most one multiplicative decrease per RTT-ish interval
            if now - self._last_decrease > self.target_delay_us / 1e6:
                factor = max(
                    self.beta, 1 - (delay_us - self.target_delay_us) / delay_us
                )
                self.cwnd *= factor
                self._last_decrease = now
        self.cwnd = min(max(self.cwnd, self.min_cwnd), self.max_cwnd)
        return self.cwnd

    def rate_for_rtt(self, rtt_us: float) -> float:
        """bytes/s equivalent of the current window at the given RTT."""
        return self.cwnd / (max(rtt_us, 1.0) / 1e6)


class CongestionControl:
    """Window-bytes congestion-control protocol for the data path.

    The windowed channel sender (``Channel`` + :class:`~uccl_tpu.p2p.sack.
    SackTxWindow`) gates NEW chunk issue on ``cwnd_bytes()`` and feeds the
    controller every chunk's **completion RTT** (``on_ack``) and every
    loss event (``on_loss`` — RTO fire or path death). Window-sized rather
    than rate-sized because the sender's actuator is "how many bytes may
    be un-acked", the same quantity Swift controls natively and the
    reference actuates per flow (include/cc/swift.h cwnd). Implementations
    are plain objects with these three methods — duck-typed, no inheritance
    required; this class just documents the contract.
    """

    def cwnd_bytes(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def on_ack(self, rtt_us: float, nbytes: int) -> None:  # pragma: no cover
        raise NotImplementedError

    def on_loss(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class WindowedSwift(CongestionControl):
    """Swift on the data path: the cwnd IS the sender window.

    Completion RTTs feed the delay-target AIMD directly (they include
    queueing on the path — the signal Swift wants); a loss event applies
    the multiplicative decrease, bounded to once per target-delay interval
    exactly like the over-target path (include/cc/swift.h's
    retransmit-triggered decrease)."""

    def __init__(self, swift: Optional[SwiftCC] = None,
                 loss_beta: float = 0.7):
        self.swift = swift if swift is not None else SwiftCC()
        self.loss_beta = loss_beta

    def cwnd_bytes(self) -> int:
        return int(self.swift.cwnd)

    def on_ack(self, rtt_us: float, nbytes: int) -> None:
        self.swift.on_delay(rtt_us)

    def on_loss(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        s = self.swift
        if now - s._last_decrease > s.target_delay_us / 1e6:
            s.cwnd = max(s.min_cwnd, s.cwnd * self.loss_beta)
            s._last_decrease = now

    def __repr__(self) -> str:
        return f"WindowedSwift(cwnd={int(self.swift.cwnd)})"


class WindowedTimely(CongestionControl):
    """Timely on the data path: rate control converted to a window.

    Timely emits a RATE; the sender needs a WINDOW. The bridge is the
    bandwidth-delay product of the controlled rate: ``cwnd = rate × srtt``
    (srtt EWMA'd from the same completion samples). Loss feeds the
    gradient an RTT pinned above ``t_high`` — the loss-IS-congestion
    stance ``CcController.tick`` already takes for the UDP wire — so
    multiplicative decrease engages even when surviving chunks look
    healthy."""

    def __init__(self, timely: Optional[TimelyCC] = None,
                 min_window: int = 64 * 1024, max_window: int = 1 << 30):
        self.timely = timely if timely is not None else TimelyCC()
        self.srtt_us = 0.0
        self.min_window = min_window
        self.max_window = max_window

    def cwnd_bytes(self) -> int:
        srtt = max(self.srtt_us, self.timely.min_rtt_us)
        w = self.timely.rate * srtt / 1e6
        return int(min(max(w, self.min_window), self.max_window))

    def on_ack(self, rtt_us: float, nbytes: int) -> None:
        self.srtt_us = (rtt_us if self.srtt_us == 0.0
                        else 0.875 * self.srtt_us + 0.125 * rtt_us)
        self.timely.on_rtt(rtt_us)

    def on_loss(self) -> None:
        self.timely.on_rtt(self.timely.t_high_us * 2.0)

    def __repr__(self) -> str:
        return (f"WindowedTimely(rate={self.timely.rate:.3g}, "
                f"cwnd={self.cwnd_bytes()})")


def make_window_cc(algo: Optional[str]) -> Optional[CongestionControl]:
    """Factory for the channel's data-path CC: "timely", "swift" or None
    (fixed window)."""
    if algo is None or algo in ("", "off", "none"):
        return None
    if algo == "timely":
        return WindowedTimely()
    if algo == "swift":
        return WindowedSwift()
    raise ValueError(f"unknown window cc algo {algo!r}")


class SwiftRateAdapter:
    """Feed delays to Swift; expose ``on_rtt`` for :class:`RateController`
    (the probe-thread path wants a rate). Lived inline in
    ``Channel.enable_cc`` before the windowed data path existed — it is
    controller-adapter logic and belongs beside RateController."""

    def __init__(self, swift: SwiftCC):
        self._s = swift
        self.rate = swift.rate_for_rtt(swift.target_delay_us)

    def on_rtt(self, rtt_us: float) -> float:
        self._s.on_delay(rtt_us)
        self.rate = self._s.rate_for_rtt(rtt_us)
        return self.rate


class RateController:
    """Wires a CC algorithm onto an Endpoint's pacer.

    Call :meth:`sample` with each chunk's completion RTT; the controller
    updates the endpoint's token-bucket rate every ``update_every`` samples.
    """

    def __init__(self, ep, algo: Optional[TimelyCC] = None, update_every: int = 4):
        self.ep = ep
        self.algo = algo if algo is not None else TimelyCC()
        self.update_every = update_every
        self._n = 0

    def sample(self, rtt_us: float) -> None:
        rate = self.algo.on_rtt(rtt_us)
        self._n += 1
        if self._n % self.update_every == 0:
            self.ep.set_rate_limit(int(rate))

    _PROBE = None

    def probe(
        self, conn_id: int, probe_fifo: bytes, timeout_ms: int = 1000
    ) -> float:
        """Measure network delay with a 1-byte one-sided write (ack round
        trip) and feed it to the controller. This is the right Timely signal:
        decoupled from transfer size and (nearly) from the pacer itself —
        feeding whole-transfer completion times instead creates a positive
        feedback loop where the pacer's own delay drives the rate to the
        floor.

        A probe that exceeds ``timeout_ms`` (loss, or a congested peer) is
        fed to the controller as an RTT of the full timeout — loss IS a
        congestion signal, and bounding the wait keeps a background CC
        thread live through drops.

        ``probe_fifo`` MUST reference a dedicated scratch window on the peer
        (e.g. ``peer.advertise(peer.reg(np.zeros(1, np.uint8)))``) — the
        probe genuinely writes one byte at its offset 0, so pointing it at a
        data window would clobber the first byte of real data."""
        import numpy as np

        if RateController._PROBE is None:
            RateController._PROBE = np.zeros(1, np.uint8)
        # reap probes that timed out earlier but completed/failed since (a
        # raise here must never propagate — it would kill a background CC
        # thread over a bookkeeping error)
        def _still_pending(x):
            try:
                if self.ep.poll_async(x) is None:
                    return True
            except Exception:
                pass  # terminal either way; fall through to reap
            reap = getattr(self.ep, "reap", None)
            if reap is not None:
                reap(x)  # drop the cached result nobody will wait() on
            return False
        self._stale = [x for x in getattr(self, "_stale", []) if _still_pending(x)]
        t0 = time.perf_counter()
        xid = self.ep.write_async(conn_id, RateController._PROBE, probe_fifo)
        if self.ep.wait(xid, timeout_ms):
            rtt_us = (time.perf_counter() - t0) * 1e6
        else:
            self._stale.append(xid)
            rtt_us = timeout_ms * 1000.0
        self.sample(rtt_us)
        return rtt_us

    def timed_write(self, conn_id: int, src, fifo) -> float:
        """Write and return the completion time in µs (diagnostic only — do
        NOT feed transfer completion times to Timely; see :meth:`probe`)."""
        t0 = time.perf_counter()
        self.ep.write(conn_id, src, fifo)
        return (time.perf_counter() - t0) * 1e6


class CcController:
    """Per-conn CC loop for the UDP wire — the configuration where the CC
    algorithms are genuinely load-bearing: the engine's datagram path has no
    kernel congestion control underneath, so the pacing rate this controller
    sets is the ONLY thing standing between the sender and real packet loss
    (reference: per-flow CC actuation through the EventOn* hooks,
    collective/rdma/transport.h:449-533).

    Sensor: the engine's in-protocol RTT EWMA (ack timestamp echoes,
    ``Endpoint.conn_stats``) — no probe traffic needed. Actuator:
    ``Endpoint.set_conn_rate``. Call :meth:`tick` periodically (e.g. every
    few ms from a transfer loop or a background thread).
    """

    def __init__(self, ep, conn_id: int, algo=None, min_rate: float = 1e6):
        self.ep = ep
        self.conn_id = conn_id
        self.algo = algo if algo is not None else TimelyCC()
        self.min_rate = min_rate
        self._last_rtx = 0

    def tick(self) -> Optional[float]:
        """Read transport stats, update the algorithm, actuate the per-conn
        rate. Returns the new rate (bytes/s) or None when there is no RTT
        signal yet. Retransmissions since the last tick count as a loss
        signal: the RTT fed to the algorithm is inflated toward t_high so
        multiplicative decrease engages even when the surviving packets'
        RTTs look healthy (loss-IS-congestion, the EQDS/Swift stance)."""
        st = self.ep.conn_stats(self.conn_id)
        rtt = st["rtt_us"]
        if rtt <= 0.0:
            return None
        new_rtx = st["pkts_rtx"] - self._last_rtx
        self._last_rtx = st["pkts_rtx"]
        if new_rtx > 0:
            t_high = getattr(
                self.algo, "t_high_us",
                getattr(self.algo, "target_delay_us", 5000.0) * 4,
            )
            rtt = max(rtt, t_high)
        if hasattr(self.algo, "on_rtt"):  # Timely: gradient rate control
            rate = self.algo.on_rtt(rtt)
        else:  # Swift: update the delay-target window, convert to a rate
            self.algo.on_delay(rtt)
            rate = self.algo.rate_for_rtt(rtt)
        rate = max(rate, self.min_rate)
        self.ep.set_conn_rate(self.conn_id, int(rate))
        return rate
