"""Congestion control for the DCN engine: Timely and Swift rate controllers.

TPU-native re-design of the reference's pluggable CC layer
(include/cc/timely.h:49 TimelyCC — RTT-gradient rate control, SIGCOMM'15;
include/cc/swift.h:42 SwiftCC — delay-target cwnd, SIGCOMM'20). On the DCN
engine the actuator is the endpoint's token-bucket pacer
(``Endpoint.set_rate_limit``) rather than per-QP pacing; the sensor is the
measured completion RTT of chunk transfers. The algorithms themselves are
pure-python, unit-testable state machines — same role as the reference's
header-only CC classes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")


@dataclasses.dataclass
class TimelyCC:
    """RTT-gradient rate control.

    Rate increases additively while RTT gradients are flat/negative, and
    decreases multiplicatively proportional to the normalized gradient when
    RTTs grow (the HAI/gradient scheme of the paper, as in the reference's
    include/cc/timely.h parameter block :20-26).
    """

    min_rtt_us: float = 50.0
    t_low_us: float = 100.0
    t_high_us: float = 5000.0
    add_step: float = 10e6  # additive increase, bytes/s
    beta: float = 0.8  # multiplicative decrease factor
    ewma_alpha: float = 0.46
    rate: float = 100e6  # current rate, bytes/s
    max_rate: float = 12.5e9
    min_rate: float = 1e6

    _prev_rtt: Optional[float] = None
    _gradient: float = 0.0
    _hai_count: int = 0

    def on_rtt(self, rtt_us: float) -> float:
        """Feed one RTT sample; returns the new rate (bytes/s)."""
        if self._prev_rtt is None:
            self._prev_rtt = rtt_us
            return self.rate
        delta = rtt_us - self._prev_rtt
        self._prev_rtt = rtt_us
        norm_grad = (
            self.ewma_alpha * (delta / self.min_rtt_us)
            + (1 - self.ewma_alpha) * self._gradient
        )
        self._gradient = norm_grad

        if rtt_us < self.t_low_us:
            self._hai_count += 1
            boost = 5 if self._hai_count >= 5 else 1
            self.rate += boost * self.add_step
        elif rtt_us > self.t_high_us:
            self._hai_count = 0
            self.rate *= 1 - self.beta * (1 - self.t_high_us / rtt_us)
        elif norm_grad <= 0:
            self._hai_count += 1
            boost = 5 if self._hai_count >= 5 else 1
            self.rate += boost * self.add_step
        else:
            self._hai_count = 0
            self.rate *= 1 - self.beta * min(norm_grad, 1.0)
        self.rate = min(max(self.rate, self.min_rate), self.max_rate)
        return self.rate


@dataclasses.dataclass
class SwiftCC:
    """Delay-target congestion window control (cwnd in bytes).

    AIMD around a target delay: grow additively when the measured delay is
    under target, back off multiplicatively (bounded per-RTT) when over —
    the reference's include/cc/swift.h scheme with flow-scaling omitted
    (single flow per channel here).
    """

    target_delay_us: float = 300.0
    additive_inc: float = 64 * 1024  # bytes per update under target
    beta: float = 0.7  # max multiplicative decrease
    cwnd: float = 1e6
    min_cwnd: float = 64 * 1024
    max_cwnd: float = 1e9

    _last_decrease: float = 0.0

    def on_delay(self, delay_us: float, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        if delay_us < self.target_delay_us:
            self.cwnd += self.additive_inc
        else:
            # at most one multiplicative decrease per RTT-ish interval
            if now - self._last_decrease > self.target_delay_us / 1e6:
                factor = max(
                    self.beta, 1 - (delay_us - self.target_delay_us) / delay_us
                )
                self.cwnd *= factor
                self._last_decrease = now
        self.cwnd = min(max(self.cwnd, self.min_cwnd), self.max_cwnd)
        return self.cwnd

    def rate_for_rtt(self, rtt_us: float) -> float:
        """bytes/s equivalent of the current window at the given RTT."""
        return self.cwnd / (max(rtt_us, 1.0) / 1e6)


class RateController:
    """Wires a CC algorithm onto an Endpoint's pacer.

    Call :meth:`sample` with each chunk's completion RTT; the controller
    updates the endpoint's token-bucket rate every ``update_every`` samples.
    """

    def __init__(self, ep, algo: Optional[TimelyCC] = None, update_every: int = 4):
        self.ep = ep
        self.algo = algo if algo is not None else TimelyCC()
        self.update_every = update_every
        self._n = 0

    def sample(self, rtt_us: float) -> None:
        rate = self.algo.on_rtt(rtt_us)
        self._n += 1
        if self._n % self.update_every == 0:
            self.ep.set_rate_limit(int(rate))

    _PROBE = None

    def probe(
        self, conn_id: int, probe_fifo: bytes, timeout_ms: int = 1000
    ) -> float:
        """Measure network delay with a 1-byte one-sided write (ack round
        trip) and feed it to the controller. This is the right Timely signal:
        decoupled from transfer size and (nearly) from the pacer itself —
        feeding whole-transfer completion times instead creates a positive
        feedback loop where the pacer's own delay drives the rate to the
        floor.

        A probe that exceeds ``timeout_ms`` (loss, or a congested peer) is
        fed to the controller as an RTT of the full timeout — loss IS a
        congestion signal, and bounding the wait keeps a background CC
        thread live through drops.

        ``probe_fifo`` MUST reference a dedicated scratch window on the peer
        (e.g. ``peer.advertise(peer.reg(np.zeros(1, np.uint8)))``) — the
        probe genuinely writes one byte at its offset 0, so pointing it at a
        data window would clobber the first byte of real data."""
        import numpy as np

        if RateController._PROBE is None:
            RateController._PROBE = np.zeros(1, np.uint8)
        # reap probes that timed out earlier but completed/failed since (a
        # raise here must never propagate — it would kill a background CC
        # thread over a bookkeeping error)
        def _still_pending(x):
            try:
                if self.ep.poll_async(x) is None:
                    return True
            except Exception:
                pass  # terminal either way; fall through to reap
            reap = getattr(self.ep, "reap", None)
            if reap is not None:
                reap(x)  # drop the cached result nobody will wait() on
            return False
        self._stale = [x for x in getattr(self, "_stale", []) if _still_pending(x)]
        t0 = time.perf_counter()
        xid = self.ep.write_async(conn_id, RateController._PROBE, probe_fifo)
        if self.ep.wait(xid, timeout_ms):
            rtt_us = (time.perf_counter() - t0) * 1e6
        else:
            self._stale.append(xid)
            rtt_us = timeout_ms * 1000.0
        self.sample(rtt_us)
        return rtt_us

    def timed_write(self, conn_id: int, src, fifo) -> float:
        """Write and return the completion time in µs (diagnostic only — do
        NOT feed transfer completion times to Timely; see :meth:`probe`)."""
        t0 = time.perf_counter()
        self.ep.write(conn_id, src, fifo)
        return (time.perf_counter() - t0) * 1e6


class CcController:
    """Per-conn CC loop for the UDP wire — the configuration where the CC
    algorithms are genuinely load-bearing: the engine's datagram path has no
    kernel congestion control underneath, so the pacing rate this controller
    sets is the ONLY thing standing between the sender and real packet loss
    (reference: per-flow CC actuation through the EventOn* hooks,
    collective/rdma/transport.h:449-533).

    Sensor: the engine's in-protocol RTT EWMA (ack timestamp echoes,
    ``Endpoint.conn_stats``) — no probe traffic needed. Actuator:
    ``Endpoint.set_conn_rate``. Call :meth:`tick` periodically (e.g. every
    few ms from a transfer loop or a background thread).
    """

    def __init__(self, ep, conn_id: int, algo=None, min_rate: float = 1e6):
        self.ep = ep
        self.conn_id = conn_id
        self.algo = algo if algo is not None else TimelyCC()
        self.min_rate = min_rate
        self._last_rtx = 0

    def tick(self) -> Optional[float]:
        """Read transport stats, update the algorithm, actuate the per-conn
        rate. Returns the new rate (bytes/s) or None when there is no RTT
        signal yet. Retransmissions since the last tick count as a loss
        signal: the RTT fed to the algorithm is inflated toward t_high so
        multiplicative decrease engages even when the surviving packets'
        RTTs look healthy (loss-IS-congestion, the EQDS/Swift stance)."""
        st = self.ep.conn_stats(self.conn_id)
        rtt = st["rtt_us"]
        if rtt <= 0.0:
            return None
        new_rtx = st["pkts_rtx"] - self._last_rtx
        self._last_rtx = st["pkts_rtx"]
        if new_rtx > 0:
            t_high = getattr(
                self.algo, "t_high_us",
                getattr(self.algo, "target_delay_us", 5000.0) * 4,
            )
            rtt = max(rtt, t_high)
        if hasattr(self.algo, "on_rtt"):  # Timely: gradient rate control
            rate = self.algo.on_rtt(rtt)
        else:  # Swift: update the delay-target window, convert to a rate
            self.algo.on_delay(rtt)
            rate = self.algo.rate_for_rtt(rtt)
        rate = max(rate, self.min_rate)
        self.ep.set_conn_rate(self.conn_id, int(rate))
        return rate
