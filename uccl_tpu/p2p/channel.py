"""Multipath transfer channel: chunk spraying over parallel connections.

The DCN re-expression of UCCL-Tran's core idea — spray chunks of one message
over many paths and complete out-of-order (reference: 32-way packet spraying,
collective/rdma/transport_config.h:40 PORT_ENTROPY; chunk size knob
UCCL_CHUNK_SIZE_KB:42). A :class:`Channel` bundles ``n_paths`` engine
connections to one peer; large writes split into chunks issued round-robin
across paths as independent one-sided writes into the same advertised window
(each chunk at its own offset), completing when every chunk acks. Each
connection is served by its own engine thread pair on both ends, so paths
genuinely move bytes in parallel.
"""

from __future__ import annotations

import struct
import time
import uuid
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from uccl_tpu import obs
from uccl_tpu.p2p.endpoint import FIFO_ITEM_BYTES, Endpoint
from uccl_tpu.utils.config import param

# Channel-level spray accounting (payload bytes are already counted per
# verb on p2p_bytes_total by the Endpoint the chunks issue through): how
# many chunk transfers the multipath fan-out created, and how many were
# re-issued after a completion timeout — the wire-health face of the
# credit-paced spray (docs/OBSERVABILITY.md).
_CHAN_CHUNKS = obs.counter(
    "p2p_channel_chunks_total",
    "chunk transfers issued by the multipath channel spray",
)
_CHAN_RETX = obs.counter(
    "p2p_channel_retx_total",
    "channel chunks re-issued after a completion timeout (loss/failover)",
)

_chunk_kb = param("chunk_size_kb", 1024, help="multipath chunk size in KiB")
_abandoned_cap = param(
    "chan_abandoned_cap",
    1024,
    help="max abandoned (timed-out, non-terminal) transfer ids kept alive; "
    "past this the oldest is force-reaped — only injected frame loss can "
    "reach the cap, so the traded keepalive guarantee is test-only",
)
_chunk_retries = param(
    "chunk_retries",
    2,
    help="extra attempts for chunks whose completion times out: the chunk "
    "is re-issued on the next path (rotation = failover). The engine wire "
    "is reliable TCP, so a timeout means injected loss (set_drop_rate), a "
    "dead path, or a stalled peer — the channel-level analog of the "
    "reference's SACK retransmit path (collective/rdma/pcb.h:20, "
    "__retransmit_for_flow transport.cc:3376)",
)
_nic_list = param(
    "nic_list",
    "",
    str,
    "comma-separated local source IPs to stripe channel paths across "
    "(multi-NIC data path; path 0 — which also carries channel control "
    "messages — is striped like any path, while the OOB store/bootstrap "
    "stay on the default route). Reference: per-GPU NIC selection + data "
    "channels across NICs, p2p/rdma/rdma_endpoint.h:117",
)


@dataclass(frozen=True)
class FifoItem:
    """Python view of the engine's 64-byte descriptor (native engine.h)."""

    rid: int
    size: int
    token: int
    offset: int

    _FMT = "<QQQQ32x"

    def pack(self) -> bytes:
        return struct.pack(self._FMT, self.rid, self.size, self.token, self.offset)

    @staticmethod
    def unpack(raw: bytes) -> "FifoItem":
        rid, size, token, offset = struct.unpack(FifoItem._FMT, raw)
        return FifoItem(rid, size, token, offset)

    def slice(self, offset: int, length: int) -> "FifoItem":
        """Descriptor for a chunk inside this window (server-side bounds are
        still enforced against the full advertised window)."""
        if offset + length > self.size:
            raise ValueError(f"chunk [{offset}, {offset + length}) outside window {self.size}")
        return FifoItem(self.rid, length, self.token, self.offset + offset)


class Channel:
    """n_paths connections to one peer + chunked multipath transfers.

    Client side: ``Channel.connect(ep, ip, port, n_paths)``.
    Server side: ``Channel.accept(ep)`` (reads the path handshake).
    """

    _HELLO = b"UCCLT_CHAN"

    def __init__(
        self,
        ep: Endpoint,
        conns: List[int],
        chunk_bytes: Optional[int] = None,
        meta: bytes = b"",
    ):
        self.ep = ep
        self.conns = conns
        self.chunk_bytes = chunk_bytes or _chunk_kb.get() * 1024
        self.retries = _chunk_retries.get()
        self.retransmitted_chunks = 0  # lifetime count of re-issued chunks
        self._abandoned: List[int] = []  # timed-out xids awaiting terminal
        # application tag carried in the connect handshake (e.g. which peer
        # rank dialed, for multi-channel topologies like a DCN full mesh)
        self.meta = meta
        # CC probe scratch: a 1-byte window each side advertises at channel
        # setup (reference analog: per-flow CC state installed at connection
        # setup, transport.cc handle_install_flow). Populated by
        # _exchange_probe_window on every public creation path.
        self._probe_buf = None
        self._probe_mr = None
        self._peer_probe_fifo: Optional[bytes] = None
        self._cc_stop = None
        self._cc_thread = None
        self.cc: Optional[object] = None  # active RateController, if any
        # EQDS-style pull mode (receiver-driven credit; reference
        # include/cc/eqds.h, pacer collective/rdma/eqds.h:93): the peer
        # one-sided-writes a CUMULATIVE byte allowance into _credit_buf;
        # when _pull_mode is set, write() gates chunk issue on it.
        self._credit_buf = None
        self._credit_mr = None
        self._peer_credit_fifo: Optional[bytes] = None
        self._pull_mode = False
        self._pull_sent = 0  # cumulative bytes issued under pull mode

    def _exchange_probe_window(self, timeout_ms: int = 10000) -> None:
        """Mint a 1-byte scratch window and swap descriptors with the peer on
        path 0 — the landing pad for the CC delay probes. Symmetric send-then
        -recv; runs before any application traffic on the channel.

        Eager by design even though CC may stay off: a lazy exchange would
        race application control messages on path 0 (the peer's first recv
        could consume the PF frame), and the cost is one 1-byte registration
        plus one round trip at setup — the dialer's PF is already in flight
        when the acceptor finishes assembling, so the recv is ~instant."""
        self._probe_buf = np.zeros(1, np.uint8)
        self._probe_mr = self.ep.reg(self._probe_buf)
        fifo = self.ep.advertise(self._probe_mr)
        self.ep.send(self.conns[0], b"PF" + fifo)
        msg = self.ep.recv(self.conns[0], timeout_ms=timeout_ms)
        if not msg.startswith(b"PF") or len(msg) != 2 + FIFO_ITEM_BYTES:
            raise IOError(f"probe-window exchange broken: {msg[:8]!r}")
        self._peer_probe_fifo = msg[2:]
        # credit window for EQDS-style pull mode: the peer writes a
        # cumulative uint64 byte allowance here (same eager rationale as PF)
        self._credit_buf = np.zeros(1, np.uint64)
        self._credit_mr = self.ep.reg(self._credit_buf)
        cw = self.ep.advertise(self._credit_mr)
        self.ep.send(self.conns[0], b"CW" + cw)
        msg = self.ep.recv(self.conns[0], timeout_ms=timeout_ms)
        if not msg.startswith(b"CW") or len(msg) != 2 + FIFO_ITEM_BYTES:
            raise IOError(f"credit-window exchange broken: {msg[:8]!r}")
        self._peer_credit_fifo = msg[2:]

    # -- congestion control (reference: CC in the transport hot path,
    # transport.cc:2845 EventOnRxACK; here a per-channel probe thread
    # actuating the endpoint's token-bucket pacer) ------------------------
    def enable_cc(
        self,
        algo: str = "timely",
        interval_s: float = 0.02,
        probe_timeout_ms: int = 250,
    ) -> None:
        """Start the background delay-probe thread driving the pacer.

        ``algo``: "timely" (RTT gradient) or "swift" (delay-target window).
        Probes ride the channel's LAST path into the peer's scratch window
        (see :meth:`probe_conn`); timed-out probes feed the controller the
        full timeout (loss is a congestion signal)."""
        import threading

        from uccl_tpu.p2p.cc import RateController, SwiftCC, TimelyCC

        if self._peer_probe_fifo is None:
            raise RuntimeError(
                "channel has no probe window (built without a handshake?)"
            )
        if self._cc_thread is not None:
            return
        if algo == "timely":
            rc = RateController(self.ep, TimelyCC())
        elif algo == "swift":
            swift = SwiftCC()

            class _SwiftAdapter:
                """Feed delays to Swift; expose on_rtt for RateController."""

                def __init__(self, s):
                    self._s = s
                    self.rate = s.rate_for_rtt(s.target_delay_us)

                def on_rtt(self, rtt_us):
                    self._s.on_delay(rtt_us)
                    self.rate = self._s.rate_for_rtt(rtt_us)
                    return self.rate

            rc = RateController(self.ep, _SwiftAdapter(swift))
        else:
            raise ValueError(f"unknown cc algo {algo!r}")
        self.cc = rc
        self._cc_stop = threading.Event()

        def loop():
            try:
                while not self._cc_stop.wait(interval_s):
                    rc.probe(
                        self.probe_conn, self._peer_probe_fifo,
                        probe_timeout_ms,
                    )
            except Exception:
                pass  # endpoint/conn closed under us
            finally:
                # Never exit leaving the pacer stuck at a collapsed rate.
                try:
                    self.ep.set_rate_limit(0)
                except Exception:
                    pass

        self._cc_thread = threading.Thread(target=loop, daemon=True)
        self._cc_thread.start()

    def disable_cc(self) -> None:
        if self._cc_thread is None:
            return
        self._cc_stop.set()
        self._cc_thread.join(timeout=5)
        self._cc_thread = None
        self.ep.set_rate_limit(0)

    # -- EQDS-style receiver-driven pull mode ------------------------------
    def enable_pull_sender(self) -> None:
        """Gate this channel's writes on receiver credit (EQDS pull mode,
        reference include/cc/eqds.h). Until the peer grants (via
        :class:`uccl_tpu.p2p.eqds.PullPacer` or :meth:`grant_credit`),
        writes block at chunk granularity.

        The grant counter is cumulative over the CONNECTION (never reset —
        zeroing it would race in-flight grant writes, and a re-enable would
        otherwise inherit all historically granted bytes as free unpaced
        credit). Gating instead resumes from the current cumulative grant:
        bytes issued while pull mode was off are treated as already
        licensed, and new issues wait for NEW credit."""
        if self._peer_credit_fifo is None:
            raise RuntimeError(
                "channel has no credit window (built without a handshake?)"
            )
        self._pull_sent = int(self._credit_buf[0])
        self._pull_mode = True

    def disable_pull_sender(self) -> None:
        self._pull_mode = False

    @property
    def pull_credit(self) -> int:
        """Cumulative bytes the peer has licensed us to send."""
        return int(self._credit_buf[0])

    @property
    def pull_granted(self) -> int:
        """Cumulative bytes WE have granted the peer (receiver side)."""
        return getattr(self, "_granted", 0)

    def grant_credit(self, nbytes: int) -> int:
        """Receiver side: extend the peer's cumulative allowance by
        ``nbytes`` — one 8-byte one-sided write into the peer's credit
        window on the isolated probe path (ordered per conn, so the
        cumulative counter is monotonic on the peer). Returns the new
        cumulative grant. The EQDS 'pull quantum'."""
        if self._peer_credit_fifo is None:
            raise RuntimeError("channel has no peer credit window")
        self._granted = getattr(self, "_granted", 0) + int(nbytes)
        arr = np.asarray([self._granted], np.uint64)
        self.ep.write(self.probe_conn, arr, self._peer_credit_fifo)
        return self._granted

    @classmethod
    def connect(
        cls,
        ep: Endpoint,
        ip: str,
        port: int,
        n_paths: int = 4,
        chunk_bytes: Optional[int] = None,
        meta: bytes = b"",
        nics: Optional[list] = None,
    ) -> "Channel":
        """``nics`` (or UCCL_TPU_NIC_LIST) stripes the data paths across
        local source interfaces round-robin: path i binds nics[i % len] —
        the multi-NIC data/ctrl split (control messages ride path 0 like
        any path, but the OOB store and bootstrap use the default route)."""
        if nics is None:
            raw = _nic_list.get()
            nics = [s.strip() for s in raw.split(",") if s.strip()] if raw else []
        token = uuid.uuid4().bytes
        conns = []
        try:
            for i in range(n_paths):
                local_ip = nics[i % len(nics)] if nics else None
                cid = ep.connect(ip, port, local_ip=local_ip)
                ep.send(cid, cls._HELLO + token + bytes([i, n_paths]) + meta)
                conns.append(cid)
        except Exception:
            # A later path failing (e.g. a misconfigured NIC bind) must not
            # leak the established ones; tearing them down also unblocks the
            # server's accept loop immediately instead of at its timeout.
            for cid in conns:
                ep.remove_conn(cid)
            raise
        chan = cls(ep, conns, chunk_bytes, meta)
        chan._exchange_probe_window()
        return chan

    @classmethod
    def _parse_hello(cls, hello: bytes):
        if not hello.startswith(cls._HELLO) or len(hello) < len(cls._HELLO) + 18:
            raise IOError("not a channel handshake")
        base = len(cls._HELLO)
        token = hello[base : base + 16]
        idx, n_paths = hello[base + 16], hello[base + 17]
        return token, idx, n_paths, hello[base + 18 :]

    @classmethod
    def accept(
        cls, ep: Endpoint, timeout_ms: int = 10000, chunk_bytes: Optional[int] = None
    ) -> "Channel":
        first_conn = ep.accept(timeout_ms)
        hello = ep.recv(first_conn, timeout_ms=timeout_ms)
        token, idx, n_paths, meta = cls._parse_hello(hello)
        paths = {idx: first_conn}
        while len(paths) < n_paths:
            cid = ep.accept(timeout_ms)
            h = ep.recv(cid, timeout_ms=timeout_ms)
            t2, i2, _, _ = cls._parse_hello(h)
            if t2 != token:
                raise IOError("path handshake mismatch (interleaved channels?)")
            paths[i2] = cid
        chan = cls(ep, [paths[i] for i in range(n_paths)], chunk_bytes, meta)
        chan._exchange_probe_window(timeout_ms)
        return chan


    @property
    def n_paths(self) -> int:
        return len(self.conns)

    @property
    def probe_conn(self) -> int:
        """The conn CC delay probes ride: the LAST path when there is more
        than one. Path 0 also carries application control messages, whose
        frames queue ahead of a probe on the same conn — a multi-MB control
        message would then inflate probe RTT and collapse the rate with no
        network congestion at all (per-conn queues are FIFO). The last data
        path shares the data plane's fate — queueing behind striped data
        chunks IS the congestion signal delay-CC wants — without the
        control-plane noise. Single-path channels have no choice."""
        return self.conns[-1] if len(self.conns) > 1 else self.conns[0]

    # -- control-plane helpers (ride path 0, ordered) ----------------------
    def send(self, data) -> None:
        self.ep.send(self.conns[0], data)

    def recv(self, max_bytes: int = 1 << 20, timeout_ms: int = 10000) -> bytes:
        return self.ep.recv(self.conns[0], max_bytes, timeout_ms)

    # -- data-plane: chunked multipath one-sided ops -----------------------
    def _chunks(self, total: int):
        """(offset, length) chunk list of `total` bytes."""
        cb = self.chunk_bytes
        return [(off, min(cb, total - off)) for off in range(0, total, cb)]

    @staticmethod
    def _flat_view(arr: np.ndarray) -> np.ndarray:
        if not arr.flags["C_CONTIGUOUS"]:
            raise ValueError("channel transfers need C-contiguous arrays")
        # reshape BEFORE the uint8 view: a 0-d row (e.g. a 1-D all_to_all's
        # scalar slice) rejects view() but reshapes to (1,) for free
        return arr.reshape(-1).view(np.uint8)

    def _await_credit(self, needed: int, timeout_ms: int) -> None:
        """Block until the peer's cumulative grant covers ``needed`` bytes.

        The receiver one-sided-writes a growing uint64 into our credit
        window (ordered per conn, so the counter never regresses); polling
        local memory costs nothing on the wire — the EQDS pull-quanta
        mechanism with the grant carried by an RDMA-style write instead of a
        pull packet."""
        import time as _time

        deadline = _time.monotonic() + timeout_ms / 1e3
        while int(self._credit_buf[0]) < needed:
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"pull credit stalled: need {needed}, have "
                    f"{int(self._credit_buf[0])}"
                )
            _time.sleep(0.0005)

    def _spray(self, arr, fifo, async_op, timeout_ms: int) -> None:
        """Shared chunk fan-out for one-sided ops: small transfers ride one
        path; large ones split round-robin across paths. Under pull mode
        every chunk issue is licensed by receiver credit. Everything issues
        through the async op so the caller's timeout_ms governs waits."""
        item = FifoItem.unpack(fifo)
        if not isinstance(arr, np.ndarray):
            # lists/bytes would be silently copied — fatal on the read path
            # (the transfer would land in a discarded temporary)
            raise TypeError(
                f"channel transfers need numpy arrays, got {type(arr)}"
            )
        if arr.ndim == 0:
            arr = arr.reshape(1)  # 0-d → (1,) view: same memory, both paths
        flat = self._flat_view(arr)
        total = flat.nbytes
        self._prune_abandoned()
        # Pull-mode credit is charged ONCE per payload byte, at first issue:
        # the receiver granted an allowance for the message, and a
        # retransmission replaces a lost frame rather than sending new
        # payload — re-debiting would wedge exact-credit receivers.
        if total <= self.chunk_bytes or self.n_paths == 1:
            if self._pull_mode:
                self._await_credit(self._pull_sent + total, timeout_ms)
                self._pull_sent += total
            # async + wait so the caller's timeout_ms governs each attempt
            # (the native sync op carries its own fixed internal timeout)
            for attempt in range(self.retries + 1):
                _CHAN_CHUNKS.inc()
                xid = async_op(
                    self.conns[attempt % self.n_paths], arr, fifo
                )
                if self.ep.wait(xid, timeout_ms):
                    return
                self._abandon(xid)
                if attempt < self.retries:
                    self.retransmitted_chunks += 1
                    _CHAN_RETX.inc()
            raise IOError(
                f"transfer failed: undelivered after {self.retries + 1} "
                "attempts"
            )
        # Chunked path with retransmission: a chunk whose completion times
        # out is re-issued on the NEXT path (rotation doubles as failover).
        # Re-writes are idempotent — same bytes into the same window slice.
        pending = list(enumerate(self._chunks(total)))  # (chunk_idx, (off, ln))
        for attempt in range(self.retries + 1):
            xids = []
            for ci, (off, ln) in pending:
                if self._pull_mode and attempt == 0:
                    self._await_credit(self._pull_sent + ln, timeout_ms)
                    self._pull_sent += ln
                _CHAN_CHUNKS.inc()
                xids.append(
                    async_op(
                        self.conns[(ci + attempt) % self.n_paths],
                        flat[off : off + ln],
                        item.slice(off, ln).pack(),
                    )
                )
            # Progress-based deadline: chunks complete concurrently, so an
            # attempt times out only after timeout_ms with ZERO completions
            # — a slow-but-moving transfer keeps extending its budget (no
            # mass-retransmit of in-flight chunks), while total loss is
            # detected within ~one timeout. Detection is a non-blocking
            # poll sweep + one short sleep per pass, so scan cost per pass
            # is O(1) in wall time regardless of chunk count.
            pend = list(zip(xids, pending))
            dead = []  # terminal-error chunks (conn died): retry immediately
            last_progress = time.monotonic()
            while pend:
                # Block on the oldest pending chunk: completion-driven wake,
                # O(n) waits total in the no-loss case. Only when the oldest
                # TIMES OUT (loss suspected) does a non-blocking sweep
                # classify the rest — so sweeps are paced at ≥50 ms apart,
                # not run per completion.
                if self.ep.wait(pend[0][0], 50):
                    last_progress = time.monotonic()
                    pend.pop(0)
                    continue
                nxt = []
                progressed = False
                for x, p in pend:
                    try:
                        r = self.ep.poll_async(x)
                    except IOError:
                        dead.append(p)  # consumed error; no keepalive held
                        continue
                    if r is None:
                        nxt.append((x, p))
                    else:
                        self.ep.wait(x, 0)  # consume the parked success
                        progressed = True
                pend = nxt
                if progressed:
                    last_progress = time.monotonic()
                elif time.monotonic() - last_progress > timeout_ms / 1e3:
                    break
            if not pend and not dead:
                return
            for x, _ in pend:
                self._abandon(x)
            failed = dead + [p for _, p in pend]
            if attempt < self.retries:
                self.retransmitted_chunks += len(failed)
                _CHAN_RETX.inc(len(failed))
            pending = failed
        raise IOError(
            f"chunked transfer failed: {len(pending)} chunks undelivered "
            f"after {self.retries + 1} attempts"
        )

    def _abandon(self, xid: int) -> None:
        """Stop waiting on a timed-out transfer WITHOUT freeing its
        keepalive: the native tx path may still hold a zero-copy pointer
        into the source buffer (queued or mid-send frame), so the memory
        must stay alive until a terminal state is observed. Every abandoned
        id terminates eventually in production — a late ack completes it, a
        dead conn fails it — and the next _spray call prunes it. Only
        injected frame loss (set_drop_rate) produces never-terminating ids;
        so that long loss-soak tests don't grow memory unboundedly, the
        list is capped: past the cap the OLDEST id is force-reaped, trading
        the keepalive guarantee only in that already-test-only case."""
        self._abandoned.append(xid)
        cap = _abandoned_cap.get()
        if len(self._abandoned) > cap:
            # Prune terminal ids first — the cap should only ever evict a
            # genuinely still-in-flight id (the documented test-only trade),
            # not force-reap a live one while reapable dead ids sit in the
            # list.
            self._prune_abandoned()
            if len(self._abandoned) > cap:
                self.ep.reap(self._abandoned.pop(0))

    def _prune_abandoned(self) -> None:
        still = []
        for x in self._abandoned:
            try:
                r = self.ep.poll_async(x)
            except IOError:
                self.ep.reap(x)  # consumed error: clear parked state
                continue
            if r is None:
                still.append(x)  # still in flight: keepalive must live on
            else:
                self.ep.reap(x)  # parked success: release result+keepalive
        self._abandoned = still

    def fence(self, timeout_ms: int = 60000) -> None:
        """Block until every abandoned transfer reaches a terminal state.

        After a write/read that retransmitted, a stale attempt's frame can
        still be in flight on a recovering path; if the caller then REUSES
        the same advertised window (or read destination) for a *different*
        message, that late frame would land over the new bytes. fence()
        makes window reuse safe again: once every abandoned id is terminal
        (late ack — the peer consumed the frame — or conn death — the
        frame died with it), no stale data can arrive. Raises IOError if
        any id is still in flight at the deadline. Fresh-advertise-per-
        message callers never need this (a stale frame NACKs on the old
        token)."""
        deadline = time.monotonic() + timeout_ms / 1e3
        still = []
        for x in self._abandoned:
            while True:
                try:
                    r = self.ep.poll_async(x)
                except IOError:
                    r = False  # terminal error: consumed
                if r is not None:
                    if r:
                        self.ep.wait(x, 0)  # consume the parked success
                    self.ep.reap(x)
                    break
                if time.monotonic() > deadline:
                    still.append(x)
                    break
                time.sleep(0.005)
        self._abandoned = still
        if still:
            raise IOError(
                f"fence: {len(still)} abandoned transfers still in flight"
            )

    def write(self, src: np.ndarray, fifo: bytes, timeout_ms: int = 60000) -> None:
        """Spray `src` into the peer's advertised window across all paths."""
        if isinstance(src, np.generic):
            # numpy scalar (e.g. a 1-D array's row slice): value-copy is
            # fine for a TX source — never for a read destination
            src = np.asarray(src).reshape(1)
        self._spray(src, fifo, self.ep.write_async, timeout_ms)

    def write_compressed(
        self, src: np.ndarray, fifo: bytes, timeout_ms: int = 60000,
        group: int = 128, codec: str = "fp8",
    ) -> int:
        """Compress `src` and spray the blob (reference: DietGPU wire
        compression on the P2P path, p2p/rdma/compression.h:46). codec:
        "fp8" (lossy, ~3.8x) or "lossless" (exact, byte-plane + native rANS —
        the DietGPU-faithful mode). The window owner decodes with
        :func:`Channel.decode` (blobs self-describe); size the window with
        ``compress.compressed_bound`` (fp8) or raw nbytes + 16 KiB slack
        (lossless). Returns the blob byte count (for the wire ratio)."""
        from uccl_tpu.p2p.compress import encode

        blob = encode(src, codec, group)
        self.write(blob, fifo, timeout_ms)
        return int(blob.nbytes)

    @staticmethod
    def decode(window: np.ndarray) -> np.ndarray:
        """Decode a compressed blob previously landed in a window (either
        codec; routed by magic)."""
        from uccl_tpu.p2p.compress import decode_any

        return decode_any(window)

    def read(self, dst: np.ndarray, fifo: bytes, timeout_ms: int = 60000) -> None:
        """Chunked multipath one-sided read into `dst`."""
        self._spray(dst, fifo, self.ep.read_async, timeout_ms)

    def close(self) -> None:
        self.disable_cc()
        for attr in ("_probe_mr", "_credit_mr"):
            mr = getattr(self, attr)
            if mr is not None:
                try:
                    self.ep.dereg(mr)
                except Exception:
                    pass  # endpoint already closed
                setattr(self, attr, None)
        for c in self.conns:
            self.ep.remove_conn(c)


class ChannelAcceptor:
    """Background channel dispatcher for multi-peer topologies.

    Several peers dialing one endpoint concurrently interleave their path
    connections in the accept queue; plain :meth:`Channel.accept` would see a
    token mismatch. This acceptor takes every inbound conn, groups handshakes
    by token, and delivers each completed channel to ``on_channel(chan)``
    (called on the acceptor thread; ``chan.meta`` identifies the dialer)."""

    # Worst-case blocking inside the loop: one accept (200ms) + one hello
    # recv + the setup-exchange recvs (PF probe window AND CW credit
    # window), each _HELLO_TIMEOUT_MS. close() must join for longer than
    # their sum so the native endpoint is never destroyed under a thread
    # inside a C call.
    _HELLO_TIMEOUT_MS = 2000
    _PARTIAL_TTL_S = 30.0

    @classmethod
    def _join_timeout_s(cls) -> float:
        return 0.2 + 3 * (cls._HELLO_TIMEOUT_MS / 1000.0) + 1.0

    def __init__(self, ep: Endpoint, on_channel, chunk_bytes: Optional[int] = None):
        import threading

        self.ep = ep
        self._on_channel = on_channel
        self._chunk_bytes = chunk_bytes
        self._stop = False
        self._partial = {}  # token -> (meta, n_paths, {idx: conn}, first_seen)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _expire_partials(self):
        """Drop handshakes whose dialer died mid-way so their conns don't
        accumulate on a long-lived endpoint."""
        import time

        now = time.monotonic()
        for token in list(self._partial):
            meta, np_, paths, first_seen = self._partial[token]
            if now - first_seen > self._PARTIAL_TTL_S:
                del self._partial[token]
                for cid in paths.values():
                    self.ep.remove_conn(cid)

    def _run(self):
        import time

        while not self._stop:
            self._expire_partials()
            try:
                cid = self.ep.accept(timeout_ms=200)
            except TimeoutError:
                continue
            except Exception:
                return  # endpoint closed
            try:
                hello = self.ep.recv(cid, timeout_ms=self._HELLO_TIMEOUT_MS)
                token, idx, n_paths, meta = Channel._parse_hello(hello)
            except Exception:
                self.ep.remove_conn(cid)  # junk or dawdling dialer
                continue
            meta0, np_, paths, _ = self._partial.setdefault(
                token, (meta, n_paths, {}, time.monotonic())
            )
            paths[idx] = cid
            if len(paths) == np_:
                del self._partial[token]
                chan = Channel(
                    self.ep,
                    [paths[i] for i in range(np_)],
                    self._chunk_bytes,
                    meta0,
                )
                try:
                    chan._exchange_probe_window(self._HELLO_TIMEOUT_MS)
                except Exception:
                    chan.close()  # dialer died mid-setup
                    continue
                self._on_channel(chan)

    def close(self):
        self._stop = True
        self._thread.join(timeout=self._join_timeout_s())
