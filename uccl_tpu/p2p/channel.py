"""Multipath transfer channel: windowed SACK transport over parallel conns.

The DCN re-expression of UCCL-Tran's core idea — spray chunks of one message
over many paths and complete out-of-order (reference: 32-way packet spraying,
collective/rdma/transport_config.h:40 PORT_ENTROPY; chunk size knob
UCCL_CHUNK_SIZE_KB:42). A :class:`Channel` bundles ``n_paths`` engine
connections to one peer; large writes split into chunks issued as independent
one-sided writes into the same advertised window (each chunk at its own
offset). Each connection is served by its own engine thread pair on both
ends, so paths genuinely move bytes in parallel.

Reliability is a real sender window (:mod:`uccl_tpu.p2p.sack`): per-chunk
sequence numbers, bounded in-flight bytes, cumulative-ack + SACK state fed
by per-chunk completion acks, *selective repeat* — fast-retransmit of
exactly the SACK-gap chunks after K duplicate acks, RTO with exponential
backoff for the rest — and a per-path quality EWMA steering both
retransmits and new chunks away from lossy/slow paths (reference:
__retransmit_for_flow + pcb.h SACK bitmaps, collective/rdma/transport.cc).
Congestion control plugs into the same loop as a window-bytes protocol
(:class:`uccl_tpu.p2p.cc.CongestionControl` — Timely/Swift fed by per-chunk
completion RTTs via :meth:`Channel.enable_window_cc`), and EQDS-style
receiver-driven credit (:mod:`uccl_tpu.p2p.eqds`) gates chunk issue under
incast.
"""

from __future__ import annotations

import struct
import time
import uuid
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from uccl_tpu import obs
from uccl_tpu.p2p.endpoint import FIFO_ITEM_BYTES, Endpoint
from uccl_tpu.utils.config import param

# Channel-level spray accounting (payload bytes are already counted per
# verb on p2p_bytes_total by the Endpoint the chunks issue through): how
# many chunk transfers the multipath fan-out created, and how many were
# re-issued after a completion timeout — the wire-health face of the
# credit-paced spray (docs/OBSERVABILITY.md).
_CHAN_CHUNKS = obs.counter(
    "p2p_channel_chunks_total",
    "chunk transfers issued by the multipath channel spray (incl. retx)",
)
_CHAN_RETX = obs.counter(
    "p2p_channel_retx_total",
    "channel chunks retransmitted, split by recovery kind "
    "(kind=fast: SACK-gap dup-ack fast retransmit; kind=rto: timeout "
    "with exponential backoff / path death)",
)
_CC_PROBE_ERRS = obs.counter(
    "p2p_cc_probe_errors_total",
    "background CC delay-probe iterations that raised (reason=exception "
    "class) — a dead CC loop is visible here instead of silent",
)
_CREDIT_STALL = obs.counter(
    "p2p_credit_stall_seconds_total",
    "seconds senders spent stalled waiting for receiver pull credit "
    "(EQDS pull mode) — the incast backpressure face of the credit plane",
)
_CREDIT_GRANTED = obs.gauge(
    "p2p_credit_granted_bytes",
    "cumulative pull-credit bytes GRANTED to the peer, per channel "
    "(conn=path-0 conn id of the granting side)",
)
_CREDIT_CONSUMED = obs.gauge(
    "p2p_credit_consumed_bytes",
    "cumulative pull-credit bytes CONSUMED by issued chunks, per channel "
    "(conn=path-0 conn id of the sending side)",
)
_CHAN_CWND = obs.gauge(
    "p2p_chan_cwnd_bytes",
    "sender window in effect at the last windowed transfer "
    "(CC cwnd when window CC is on, else the static cap; "
    "last-writer-wins across channels)",
)
_CHAN_SRTT = obs.gauge(
    "p2p_chan_srtt_us",
    "smoothed per-chunk completion RTT of the last windowed transfer "
    "(last-writer-wins across channels)",
)
_CHAN_RTO = obs.gauge(
    "p2p_chan_rto_ms",
    "retransmission timeout of the last windowed transfer "
    "(last-writer-wins across channels)",
)
# declared in p2p/endpoint.py — the windowed transport's terminal
# failures land on the same family so a chaos run's failure mix is
# auditable from metrics alone (docs/OBSERVABILITY.md)
_XFER_FAILS = obs.counter("p2p_transfer_failures_total")

_chunk_kb = param("chunk_size_kb", 1024, help="multipath chunk size in KiB")
_abandoned_cap = param(
    "chan_abandoned_cap",
    1024,
    help="max abandoned (timed-out, non-terminal) transfer ids kept alive; "
    "past this the oldest is force-reaped — only injected frame loss can "
    "reach the cap, so the traded keepalive guarantee is test-only",
)
_chunk_retries = param(
    "chunk_retries",
    2,
    help="extra transmissions per chunk (max_tx = retries + 1) for the "
    "windowed SACK sender: a chunk is re-issued by dup-ack fast "
    "retransmit or RTO, steered to the best-quality path. The engine "
    "wire is reliable TCP, so losing a chunk means injected loss "
    "(set_drop_rate), a dead path, or a stalled peer — the channel-level "
    "analog of the reference's SACK retransmit path "
    "(collective/rdma/pcb.h:20, __retransmit_for_flow transport.cc:3376)",
)
_window_bytes = param(
    "chan_window_bytes",
    8 << 20,
    help="static cap on a windowed transfer's in-flight bytes; window CC "
    "(Channel.enable_window_cc) tightens it dynamically, never widens it",
)
_dupack_k = param(
    "chan_dupack_k",
    3,
    help="duplicate-ack threshold for SACK-gap fast retransmit: K "
    "later-sequence completions while a chunk is outstanding mark it "
    "lost (TCP's classic 3, tolerant of mild multipath reordering)",
)
_nic_list = param(
    "nic_list",
    "",
    str,
    "comma-separated local source IPs to stripe channel paths across "
    "(multi-NIC data path; path 0 — which also carries channel control "
    "messages — is striped like any path, while the OOB store/bootstrap "
    "stay on the default route). Reference: per-GPU NIC selection + data "
    "channels across NICs, p2p/rdma/rdma_endpoint.h:117",
)


@dataclass(frozen=True)
class FifoItem:
    """Python view of the engine's 64-byte descriptor (native engine.h)."""

    rid: int
    size: int
    token: int
    offset: int

    _FMT = "<QQQQ32x"

    def pack(self) -> bytes:
        return struct.pack(self._FMT, self.rid, self.size, self.token, self.offset)

    @staticmethod
    def unpack(raw: bytes) -> "FifoItem":
        rid, size, token, offset = struct.unpack(FifoItem._FMT, raw)
        return FifoItem(rid, size, token, offset)

    def slice(self, offset: int, length: int) -> "FifoItem":
        """Descriptor for a chunk inside this window (server-side bounds are
        still enforced against the full advertised window)."""
        if offset + length > self.size:
            raise ValueError(f"chunk [{offset}, {offset + length}) outside window {self.size}")
        return FifoItem(self.rid, length, self.token, self.offset + offset)


class Channel:
    """n_paths connections to one peer + chunked multipath transfers.

    Client side: ``Channel.connect(ep, ip, port, n_paths)``.
    Server side: ``Channel.accept(ep)`` (reads the path handshake).
    """

    _HELLO = b"UCCLT_CHAN"

    def __init__(
        self,
        ep: Endpoint,
        conns: List[int],
        chunk_bytes: Optional[int] = None,
        meta: bytes = b"",
    ):
        self.ep = ep
        self.conns = conns
        self.chunk_bytes = chunk_bytes or _chunk_kb.get() * 1024
        self.retries = _chunk_retries.get()
        self.retransmitted_chunks = 0  # lifetime count of re-issued chunks
        self.retx_fast = 0  # lifetime SACK-gap fast retransmits
        self.retx_rto = 0   # lifetime RTO/path-death retransmits
        self.window_bytes = _window_bytes.get()
        self.dupack_k = _dupack_k.get()
        # window-bytes CC on the data path (cc.CongestionControl); None =
        # fixed window_bytes cap. Enable via enable_window_cc().
        self.window_cc = None
        self._last_win = None  # last transfer's SackTxWindow (stats)
        # persistent link-quality EWMA (ISSUE 19): the per-transfer
        # PathQuality resets with each SackTxWindow, so cross-transfer
        # consumers (the DCN scheduled-a2a demotion) fold each finished
        # window's WORST per-path delivery score here. None until the
        # first windowed transfer completes or fails.
        self._link_ewma: Optional[float] = None
        self._abandoned: List[int] = []  # timed-out xids awaiting terminal
        self._grant_xids: List[int] = []  # fire-and-forget grant writes
        self._cc_probe_logged = False  # log-once guard for probe errors
        # application tag carried in the connect handshake (e.g. which peer
        # rank dialed, for multi-channel topologies like a DCN full mesh)
        self.meta = meta
        # CC probe scratch: a 1-byte window each side advertises at channel
        # setup (reference analog: per-flow CC state installed at connection
        # setup, transport.cc handle_install_flow). Populated by
        # _exchange_probe_window on every public creation path.
        self._probe_buf = None
        self._probe_mr = None
        self._peer_probe_fifo: Optional[bytes] = None
        self._cc_stop = None
        self._cc_thread = None
        self.cc: Optional[object] = None  # active RateController, if any
        # EQDS-style pull mode (receiver-driven credit; reference
        # include/cc/eqds.h, pacer collective/rdma/eqds.h:93): the peer
        # one-sided-writes a CUMULATIVE byte allowance into _credit_buf;
        # when _pull_mode is set, write() gates chunk issue on it.
        self._credit_buf = None
        self._credit_mr = None
        self._peer_credit_fifo: Optional[bytes] = None
        self._pull_mode = False
        self._pull_sent = 0  # cumulative bytes issued under pull mode

    def _exchange_probe_window(self, timeout_ms: int = 10000) -> None:
        """Mint a 1-byte scratch window and swap descriptors with the peer on
        path 0 — the landing pad for the CC delay probes. Symmetric send-then
        -recv; runs before any application traffic on the channel.

        Eager by design even though CC may stay off: a lazy exchange would
        race application control messages on path 0 (the peer's first recv
        could consume the PF frame), and the cost is one 1-byte registration
        plus one round trip at setup — the dialer's PF is already in flight
        when the acceptor finishes assembling, so the recv is ~instant."""
        self._probe_buf = np.zeros(1, np.uint8)
        self._probe_mr = self.ep.reg(self._probe_buf)
        fifo = self.ep.advertise(self._probe_mr)
        self.ep.send(self.conns[0], b"PF" + fifo)
        msg = self.ep.recv(self.conns[0], timeout_ms=timeout_ms)
        if not msg.startswith(b"PF") or len(msg) != 2 + FIFO_ITEM_BYTES:
            raise IOError(f"probe-window exchange broken: {msg[:8]!r}")
        self._peer_probe_fifo = msg[2:]
        # credit window for EQDS-style pull mode: the peer writes a
        # cumulative uint64 byte allowance here (same eager rationale as PF)
        self._credit_buf = np.zeros(1, np.uint64)
        self._credit_mr = self.ep.reg(self._credit_buf)
        cw = self.ep.advertise(self._credit_mr)
        self.ep.send(self.conns[0], b"CW" + cw)
        msg = self.ep.recv(self.conns[0], timeout_ms=timeout_ms)
        if not msg.startswith(b"CW") or len(msg) != 2 + FIFO_ITEM_BYTES:
            raise IOError(f"credit-window exchange broken: {msg[:8]!r}")
        self._peer_credit_fifo = msg[2:]

    # -- congestion control (reference: CC in the transport hot path,
    # transport.cc:2845 EventOnRxACK; here a per-channel probe thread
    # actuating the endpoint's token-bucket pacer) ------------------------
    def enable_cc(
        self,
        algo: str = "timely",
        interval_s: float = 0.02,
        probe_timeout_ms: int = 250,
    ) -> None:
        """Start the background delay-probe thread driving the pacer.

        ``algo``: "timely" (RTT gradient) or "swift" (delay-target window).
        Probes ride the channel's LAST path into the peer's scratch window
        (see :meth:`probe_conn`); timed-out probes feed the controller the
        full timeout (loss is a congestion signal)."""
        import threading

        from uccl_tpu.p2p.cc import (RateController, SwiftCC,
                                     SwiftRateAdapter, TimelyCC)
        from uccl_tpu.utils.logging import get_logger

        if self._peer_probe_fifo is None:
            raise RuntimeError(
                "channel has no probe window (built without a handshake?)"
            )
        if self._cc_thread is not None:
            return
        if algo == "timely":
            rc = RateController(self.ep, TimelyCC())
        elif algo == "swift":
            rc = RateController(self.ep, SwiftRateAdapter(SwiftCC()))
        else:
            raise ValueError(f"unknown cc algo {algo!r}")
        self.cc = rc
        self._cc_stop = threading.Event()
        log = get_logger("P2P")

        def loop():
            try:
                while not self._cc_stop.wait(interval_s):
                    try:
                        rc.probe(
                            self.probe_conn, self._peer_probe_fifo,
                            probe_timeout_ms,
                        )
                    except ValueError:
                        return  # endpoint closed under us: loop is done
                    except Exception as e:
                        # A broken probe path must be VISIBLE, not a
                        # silently dead CC loop: count every failed
                        # iteration, log the first one per channel.
                        _CC_PROBE_ERRS.inc(reason=type(e).__name__)
                        if not self._cc_probe_logged:
                            self._cc_probe_logged = True
                            log.warning(
                                "channel CC probe failing (%s: %s); "
                                "counting on p2p_cc_probe_errors_total",
                                type(e).__name__, e,
                            )
            finally:
                # Never exit leaving the pacer stuck at a collapsed rate.
                try:
                    self.ep.set_rate_limit(0)
                except Exception:
                    pass

        self._cc_thread = threading.Thread(target=loop, daemon=True)
        self._cc_thread.start()

    def disable_cc(self) -> None:
        if self._cc_thread is None:
            return
        self._cc_stop.set()
        self._cc_thread.join(timeout=5)
        self._cc_thread = None
        self.ep.set_rate_limit(0)

    # -- window CC on the data path (no probe thread) ----------------------
    def enable_window_cc(self, algo="swift") -> None:
        """Congestion-control the windowed sender itself: a window-bytes
        controller (:class:`uccl_tpu.p2p.cc.CongestionControl`) fed by
        every chunk's COMPLETION RTT and loss event inside the transfer
        loop — no side probe thread, no decoupled pacer. ``algo`` is
        "swift" | "timely" | a CongestionControl instance."""
        from uccl_tpu.p2p.cc import make_window_cc

        self.window_cc = make_window_cc(algo) if isinstance(algo, str) else algo

    def disable_window_cc(self) -> None:
        self.window_cc = None

    # -- EQDS-style receiver-driven pull mode ------------------------------
    def enable_pull_sender(self) -> None:
        """Gate this channel's writes on receiver credit (EQDS pull mode,
        reference include/cc/eqds.h). Until the peer grants (via
        :class:`uccl_tpu.p2p.eqds.PullPacer` or :meth:`grant_credit`),
        writes block at chunk granularity.

        The grant counter is cumulative over the CONNECTION (never reset —
        zeroing it would race in-flight grant writes, and a re-enable would
        otherwise inherit all historically granted bytes as free unpaced
        credit). Gating instead resumes from the current cumulative grant:
        bytes issued while pull mode was off are treated as already
        licensed, and new issues wait for NEW credit."""
        if self._peer_credit_fifo is None:
            raise RuntimeError(
                "channel has no credit window (built without a handshake?)"
            )
        self._pull_sent = int(self._credit_buf[0])
        self._pull_mode = True

    def disable_pull_sender(self) -> None:
        self._pull_mode = False

    @property
    def pull_credit(self) -> int:
        """Cumulative bytes the peer has licensed us to send."""
        return int(self._credit_buf[0])

    @property
    def pull_granted(self) -> int:
        """Cumulative bytes WE have granted the peer (receiver side)."""
        return getattr(self, "_granted", 0)

    def grant_credit(self, nbytes: int) -> int:
        """Receiver side: extend the peer's cumulative allowance by
        ``nbytes`` — one 8-byte one-sided write into the peer's credit
        window on the isolated probe path (ordered per conn, so the
        cumulative counter is monotonic on the peer). Returns the new
        cumulative grant. The EQDS 'pull quantum'.

        Fire-and-forget: the counter is CUMULATIVE, so a lost grant write
        (or a fault-injected lost ack) is superseded by the next one —
        blocking for the completion here would couple the receiver's
        grant loop to data-plane fault injection. Completion ids are
        reaped opportunistically, bounded."""
        if self._peer_credit_fifo is None:
            raise RuntimeError("channel has no peer credit window")
        self._granted = getattr(self, "_granted", 0) + int(nbytes)
        arr = np.asarray([self._granted], np.uint64)
        self._grant_xids.append(
            self.ep.write_async(self.probe_conn, arr, self._peer_credit_fifo)
        )
        if len(self._grant_xids) > 64:
            self._reap_grants()
        _CREDIT_GRANTED.set(self._granted, conn=str(self.conns[0]))
        return self._granted

    def _reap_grants(self) -> None:
        still = []
        for xid in self._grant_xids:
            try:
                r = self.ep.poll_async(xid)
            except IOError:
                self.ep.reap(xid)  # consumed error: clear parked state
                continue
            if r is None:
                still.append(xid)
            else:
                self.ep.reap(xid)
        # cap: a grant whose ack was fault-injected away never terminates;
        # past the cap the OLDEST is force-reaped — same documented
        # test-only trade as _abandon (only injected loss reaches here,
        # and by then the 8-byte frame left the tx queue long ago)
        while len(still) > 256:
            self.ep.reap(still.pop(0))
        self._grant_xids = still

    @classmethod
    def connect(
        cls,
        ep: Endpoint,
        ip: str,
        port: int,
        n_paths: int = 4,
        chunk_bytes: Optional[int] = None,
        meta: bytes = b"",
        nics: Optional[list] = None,
    ) -> "Channel":
        """``nics`` (or UCCL_TPU_NIC_LIST) stripes the data paths across
        local source interfaces round-robin: path i binds nics[i % len] —
        the multi-NIC data/ctrl split (control messages ride path 0 like
        any path, but the OOB store and bootstrap use the default route)."""
        if nics is None:
            raw = _nic_list.get()
            nics = [s.strip() for s in raw.split(",") if s.strip()] if raw else []
        token = uuid.uuid4().bytes
        conns = []
        try:
            for i in range(n_paths):
                local_ip = nics[i % len(nics)] if nics else None
                cid = ep.connect(ip, port, local_ip=local_ip)
                ep.send(cid, cls._HELLO + token + bytes([i, n_paths]) + meta)
                conns.append(cid)
        except Exception:
            # A later path failing (e.g. a misconfigured NIC bind) must not
            # leak the established ones; tearing them down also unblocks the
            # server's accept loop immediately instead of at its timeout.
            for cid in conns:
                ep.remove_conn(cid)
            raise
        chan = cls(ep, conns, chunk_bytes, meta)
        chan._exchange_probe_window()
        return chan

    @classmethod
    def _parse_hello(cls, hello: bytes):
        if not hello.startswith(cls._HELLO) or len(hello) < len(cls._HELLO) + 18:
            raise IOError("not a channel handshake")
        base = len(cls._HELLO)
        token = hello[base : base + 16]
        idx, n_paths = hello[base + 16], hello[base + 17]
        return token, idx, n_paths, hello[base + 18 :]

    @classmethod
    def accept(
        cls, ep: Endpoint, timeout_ms: int = 10000, chunk_bytes: Optional[int] = None
    ) -> "Channel":
        first_conn = ep.accept(timeout_ms)
        hello = ep.recv(first_conn, timeout_ms=timeout_ms)
        token, idx, n_paths, meta = cls._parse_hello(hello)
        paths = {idx: first_conn}
        while len(paths) < n_paths:
            cid = ep.accept(timeout_ms)
            h = ep.recv(cid, timeout_ms=timeout_ms)
            t2, i2, _, _ = cls._parse_hello(h)
            if t2 != token:
                raise IOError("path handshake mismatch (interleaved channels?)")
            paths[i2] = cid
        chan = cls(ep, [paths[i] for i in range(n_paths)], chunk_bytes, meta)
        chan._exchange_probe_window(timeout_ms)
        return chan


    @property
    def n_paths(self) -> int:
        return len(self.conns)

    @property
    def probe_conn(self) -> int:
        """The conn CC delay probes ride: the LAST path when there is more
        than one. Path 0 also carries application control messages, whose
        frames queue ahead of a probe on the same conn — a multi-MB control
        message would then inflate probe RTT and collapse the rate with no
        network congestion at all (per-conn queues are FIFO). The last data
        path shares the data plane's fate — queueing behind striped data
        chunks IS the congestion signal delay-CC wants — without the
        control-plane noise. Single-path channels have no choice."""
        return self.conns[-1] if len(self.conns) > 1 else self.conns[0]

    # -- control-plane helpers (ride path 0, ordered) ----------------------
    def send(self, data) -> None:
        self.ep.send(self.conns[0], data)

    def recv(self, max_bytes: int = 1 << 20, timeout_ms: int = 10000) -> bytes:
        return self.ep.recv(self.conns[0], max_bytes, timeout_ms)

    # -- data-plane: chunked multipath one-sided ops -----------------------
    def _chunks(self, total: int):
        """(offset, length) chunk list of `total` bytes."""
        cb = self.chunk_bytes
        return [(off, min(cb, total - off)) for off in range(0, total, cb)]

    @staticmethod
    def _flat_view(arr: np.ndarray) -> np.ndarray:
        if not arr.flags["C_CONTIGUOUS"]:
            raise ValueError("channel transfers need C-contiguous arrays")
        # reshape BEFORE the uint8 view: a 0-d row (e.g. a 1-D all_to_all's
        # scalar slice) rejects view() but reshapes to (1,) for free
        return arr.reshape(-1).view(np.uint8)

    def _elem_chunks(self, arr, fifo: bytes, scalar_ok: bool = False):
        """One transfer element → its windowed chunk descriptors
        ``(view, packed_fifo, nbytes)`` — the single place element
        validation and chunk splitting live (shared by write/read and
        writev so the entry points cannot drift)."""
        if scalar_ok and isinstance(arr, np.generic):
            # numpy scalar (e.g. a 1-D array's row slice): value-copy is
            # fine for a TX source — never for a read destination (the
            # transfer would land in a discarded temporary; reads keep
            # the TypeError below)
            arr = np.asarray(arr).reshape(1)
        if not isinstance(arr, np.ndarray):
            # lists/bytes would be silently copied — fatal on the read path
            # (the transfer would land in a discarded temporary)
            raise TypeError(
                f"channel transfers need numpy arrays, got {type(arr)}"
            )
        if arr.ndim == 0:
            arr = arr.reshape(1)  # 0-d → (1,) view: same memory, both paths
        flat = self._flat_view(arr)
        item = FifoItem.unpack(fifo)
        return [
            (flat[off:off + ln], item.slice(off, ln).pack(), ln)
            for off, ln in self._chunks(flat.nbytes)
        ]

    def _spray(self, arr, fifo, async_op, timeout_ms: int,
               scalar_ok: bool = False) -> None:
        """Windowed chunk fan-out for one-sided ops: the transfer's chunks
        run through the selective-repeat SACK window (`p2p/sack.py`) over
        all paths. Under pull mode every NEW chunk issue is licensed by
        receiver credit; window CC bounds in-flight bytes."""
        chunks = self._elem_chunks(arr, fifo, scalar_ok=scalar_ok)
        self._prune_abandoned()
        self._run_window(chunks, async_op, timeout_ms)

    def _run_window(self, chunks, async_op, timeout_ms: int) -> None:
        """Drive one windowed transfer: issue chunks within the sender
        window, feed completions (acks/errors) and their RTTs back into
        the SACK state machine and the window CC, retransmit exactly what
        the SACK state marks lost. ``chunks`` is a list of
        ``(src_or_dst_view, packed_fifo, nbytes)``.

        Pull-mode credit is charged ONCE per payload byte, at first issue:
        the receiver granted an allowance for the message, and a
        retransmission replaces a lost frame rather than sending new
        payload — re-debiting would wedge exact-credit receivers. A
        credit shortfall pauses NEW chunks only; retransmits (already
        licensed) keep flowing, so loss recovery is never credit-gated.
        """
        from uccl_tpu.p2p.sack import NEW, SackTxWindow

        if not chunks:
            return
        win = SackTxWindow(
            [ln for _, _, ln in chunks],
            self.n_paths,
            max_tx=self.retries + 1,
            dupack_k=self.dupack_k,
            rto_init_s=min(max(0.05, timeout_ms / 1e3 / 4.0), 1.0),
            rto_max_s=max(0.2, timeout_ms / 1e3),
        )
        self._last_win = win
        # flight bundles capture this channel's live transport face
        # (cwnd, SACK splits, path EWMAs) for the duration of the
        # transfer — last writer wins across concurrent channels, and
        # the trigger's own context carries its window's stats anyway
        obs.flight_provider("transport", self.transport_stats)
        cc = self.window_cc
        inflight = {}  # xid -> (seq, t_issue, path); attempt-granular
        last_progress = time.monotonic()
        credit_stall_t0 = None  # monotonic start of a continuous stall

        def on_complete(xid: int, ok: bool, now: float) -> None:
            nonlocal last_progress
            seq, t0, path = inflight.pop(xid)
            if ok:
                rtt_us = (now - t0) * 1e6
                if win.on_ack(seq, path=path, rtt_us=rtt_us, now=now):
                    if cc is not None:
                        cc.on_ack(rtt_us, chunks[seq][2])
                last_progress = now
            else:
                # CC hears about this loss when the retransmit issues
                # (every lost chunk causes exactly one) — not here too.
                # t_sent lets the window ignore a SUPERSEDED attempt's
                # late error (a newer attempt owns recovery).
                win.on_error(seq, path, now, t_sent=t0)

        try:
            while not win.done():
                now = time.monotonic()
                # 1) non-blocking completion sweep (acks arrive out of
                # order across paths — this IS the SACK feed)
                for xid in list(inflight):
                    try:
                        r = self.ep.poll_async(xid)
                    except IOError:
                        on_complete(xid, False, now)
                        continue
                    if r is None:
                        continue
                    self.ep.reap(xid)  # consume the parked success
                    on_complete(xid, True, now)
                if win.done():
                    break
                # 2) issue within the window (retransmits first — sendable
                # orders them ahead of new chunks)
                limit = self.window_bytes
                if cc is not None:
                    limit = min(limit, cc.cwnd_bytes())
                for seq, kind in win.sendable(now, limit):
                    view, fifo_b, ln = chunks[seq]
                    if self._pull_mode and kind == NEW:
                        need = self._pull_sent + ln
                        if int(self._credit_buf[0]) < need:
                            # new chunks pause for credit; sendable lists
                            # retransmits first, so nothing lost waits
                            if credit_stall_t0 is None:
                                credit_stall_t0 = now
                            break
                        if credit_stall_t0 is not None:
                            _CREDIT_STALL.inc(now - credit_stall_t0)
                            credit_stall_t0 = None
                        self._pull_sent += ln
                        _CREDIT_CONSUMED.set(
                            self._pull_sent, conn=str(self.conns[0])
                        )
                    path = win.pick_path(seq, kind)
                    _CHAN_CHUNKS.inc()
                    if kind != NEW:
                        self.retransmitted_chunks += 1
                        _CHAN_RETX.inc(kind=kind)
                        if kind == "fast":
                            self.retx_fast += 1
                        else:
                            self.retx_rto += 1
                        if cc is not None:
                            cc.on_loss()
                    t_issue = time.monotonic()
                    xid = async_op(self.conns[path], view, fifo_b)
                    win.mark_sent(seq, path, kind, t_issue)
                    inflight[xid] = (seq, t_issue, path)
                # 3) failure checks
                now = time.monotonic()
                dead = win.exhausted(now)
                if dead:
                    _XFER_FAILS.inc(len(dead), reason="undelivered")
                    obs.instant("p2p_transfer_failed", track="wire",
                                reason="undelivered", chunks=len(dead),
                                attempts=win.max_tx)
                    raise IOError(
                        f"transfer failed: {len(dead)} chunks undelivered "
                        f"after {win.max_tx} attempts"
                    )
                if (credit_stall_t0 is not None
                        and now - credit_stall_t0 > timeout_ms / 1e3):
                    _CREDIT_STALL.inc(now - credit_stall_t0)
                    credit_stall_t0 = None
                    _XFER_FAILS.inc(reason="credit_stall")
                    obs.instant("p2p_transfer_failed", track="wire",
                                reason="credit_stall")
                    raise TimeoutError(
                        f"pull credit stalled: need "
                        f"{self._pull_sent + chunks[win._next_new][2]}, "
                        f"have {int(self._credit_buf[0])}"
                    )
                if now - last_progress > timeout_ms / 1e3:
                    _XFER_FAILS.inc(reason="stalled")
                    obs.instant("p2p_transfer_failed", track="wire",
                                reason="stalled", inflight=len(inflight))
                    raise IOError(
                        f"transfer stalled: no chunk completion in "
                        f"{timeout_ms} ms ({len(inflight)} in flight)"
                    )
                # 4) completion-driven wake: block briefly on the OLDEST
                # in-flight attempt instead of spinning the sweep
                if inflight:
                    oldest = next(iter(inflight))
                    if self.ep.wait(oldest, 2):
                        on_complete(oldest, True, time.monotonic())
                else:
                    time.sleep(0.0002)
        finally:
            if credit_stall_t0 is not None:
                _CREDIT_STALL.inc(time.monotonic() - credit_stall_t0)
            # stale attempts (superseded by a delivered retransmit, or a
            # failed transfer's in-flight chunks) keep their keepalive
            # until a terminal state is observed
            for xid in inflight:
                self._abandon(xid)
            _CHAN_CWND.set(
                cc.cwnd_bytes() if cc is not None else self.window_bytes
            )
            _CHAN_SRTT.set(win.srtt_us)
            _CHAN_RTO.set(win.rto_s * 1e3)
            worst = min(win.paths.score)
            self._link_ewma = (worst if self._link_ewma is None
                               else 0.5 * self._link_ewma + 0.5 * worst)

    def link_score(self) -> Optional[float]:
        """Cross-transfer link quality in [0, 1]: an EWMA (over completed
        windowed transfers) of the worst per-path delivery score — the
        pessimistic signal a scheduler reads to demote this link's edges
        (``DcnGroup.all_to_all(path_floor=...)``) while the per-transfer
        PathQuality keeps steering chunks WITHIN the link. None until a
        windowed transfer has run."""
        return self._link_ewma

    def transport_stats(self) -> dict:
        """Snapshot of the windowed transport's state: last transfer's
        SACK/RTT/path-quality stats plus lifetime retransmit splits — the
        numbers the incast bench reports per arm."""
        st = dict(self._last_win.stats()) if self._last_win is not None else {}
        st.update(
            retx_fast_total=self.retx_fast,
            retx_rto_total=self.retx_rto,
            retransmitted_chunks=self.retransmitted_chunks,
            cwnd_bytes=(self.window_cc.cwnd_bytes()
                        if self.window_cc is not None else self.window_bytes),
            pull_mode=self._pull_mode,
            pull_sent=self._pull_sent,
            pull_credit=(int(self._credit_buf[0])
                         if self._credit_buf is not None else 0),
            link_score=self._link_ewma,
        )
        return st

    def _abandon(self, xid: int) -> None:
        """Stop waiting on a timed-out transfer WITHOUT freeing its
        keepalive: the native tx path may still hold a zero-copy pointer
        into the source buffer (queued or mid-send frame), so the memory
        must stay alive until a terminal state is observed. Every abandoned
        id terminates eventually in production — a late ack completes it, a
        dead conn fails it — and the next _spray call prunes it. Only
        injected frame loss (set_drop_rate) produces never-terminating ids;
        so that long loss-soak tests don't grow memory unboundedly, the
        list is capped: past the cap the OLDEST id is force-reaped, trading
        the keepalive guarantee only in that already-test-only case."""
        self._abandoned.append(xid)
        cap = _abandoned_cap.get()
        if len(self._abandoned) > cap:
            # Prune terminal ids first — the cap should only ever evict a
            # genuinely still-in-flight id (the documented test-only trade),
            # not force-reap a live one while reapable dead ids sit in the
            # list.
            self._prune_abandoned()
            if len(self._abandoned) > cap:
                self.ep.reap(self._abandoned.pop(0))

    def _prune_abandoned(self) -> None:
        still = []
        for x in self._abandoned:
            try:
                r = self.ep.poll_async(x)
            except IOError:
                self.ep.reap(x)  # consumed error: clear parked state
                continue
            if r is None:
                still.append(x)  # still in flight: keepalive must live on
            else:
                self.ep.reap(x)  # parked success: release result+keepalive
        self._abandoned = still

    def fence(self, timeout_ms: int = 60000) -> None:
        """Block until every abandoned transfer reaches a terminal state.

        After a write/read that retransmitted, a stale attempt's frame can
        still be in flight on a recovering path; if the caller then REUSES
        the same advertised window (or read destination) for a *different*
        message, that late frame would land over the new bytes. fence()
        makes window reuse safe again: once every abandoned id is terminal
        (late ack — the peer consumed the frame — or conn death — the
        frame died with it), no stale data can arrive. Raises IOError if
        any id is still in flight at the deadline. Fresh-advertise-per-
        message callers never need this (a stale frame NACKs on the old
        token)."""
        deadline = time.monotonic() + timeout_ms / 1e3
        still = []
        for x in self._abandoned:
            while True:
                try:
                    r = self.ep.poll_async(x)
                except IOError:
                    r = False  # terminal error: consumed
                if r is not None:
                    if r:
                        self.ep.wait(x, 0)  # consume the parked success
                    self.ep.reap(x)
                    break
                if time.monotonic() > deadline:
                    still.append(x)
                    break
                time.sleep(0.005)
        self._abandoned = still
        if still:
            raise IOError(
                f"fence: {len(still)} abandoned transfers still in flight"
            )

    def write(self, src: np.ndarray, fifo: bytes, timeout_ms: int = 60000) -> None:
        """Spray `src` into the peer's advertised window across all paths."""
        self._spray(src, fifo, self.ep.write_async, timeout_ms,
                    scalar_ok=True)

    def writev(self, srcs, fifos, timeout_ms: int = 60000) -> None:
        """Vectorized windowed write: every (src, fifo) element becomes
        one or more chunks of ONE windowed transfer, so selective repeat,
        path steering, CC and pull credit act across the whole batch (the
        disagg KV slab path — reference: writev over descriptor lists,
        engine.h:311). Returns once every element is delivered."""
        chunks = []
        for src, fifo in zip(srcs, fifos):
            chunks.extend(self._elem_chunks(src, fifo, scalar_ok=True))
        self._prune_abandoned()
        self._run_window(chunks, self.ep.write_async, timeout_ms)

    def write_compressed(
        self, src: np.ndarray, fifo: bytes, timeout_ms: int = 60000,
        group: int = 128, codec: str = "fp8",
    ) -> int:
        """Compress `src` and spray the blob (reference: DietGPU wire
        compression on the P2P path, p2p/rdma/compression.h:46). codec:
        "fp8" (lossy, ~3.8x) or "lossless" (exact, byte-plane + native rANS —
        the DietGPU-faithful mode). The window owner decodes with
        :func:`Channel.decode` (blobs self-describe); size the window with
        ``compress.compressed_bound`` (fp8) or raw nbytes + 16 KiB slack
        (lossless). Returns the blob byte count (for the wire ratio)."""
        from uccl_tpu.p2p.compress import encode

        blob = encode(src, codec, group)
        self.write(blob, fifo, timeout_ms)
        return int(blob.nbytes)

    @staticmethod
    def decode(window: np.ndarray) -> np.ndarray:
        """Decode a compressed blob previously landed in a window (either
        codec; routed by magic)."""
        from uccl_tpu.p2p.compress import decode_any

        return decode_any(window)

    def read(self, dst: np.ndarray, fifo: bytes, timeout_ms: int = 60000) -> None:
        """Chunked multipath one-sided read into `dst`."""
        self._spray(dst, fifo, self.ep.read_async, timeout_ms)

    def close(self) -> None:
        self.disable_cc()
        for attr in ("_probe_mr", "_credit_mr"):
            mr = getattr(self, attr)
            if mr is not None:
                try:
                    self.ep.dereg(mr)
                except Exception:
                    pass  # endpoint already closed
                setattr(self, attr, None)
        for c in self.conns:
            self.ep.remove_conn(c)


class ChannelAcceptor:
    """Background channel dispatcher for multi-peer topologies.

    Several peers dialing one endpoint concurrently interleave their path
    connections in the accept queue; plain :meth:`Channel.accept` would see a
    token mismatch. This acceptor takes every inbound conn, groups handshakes
    by token, and delivers each completed channel to ``on_channel(chan)``
    (called on the acceptor thread; ``chan.meta`` identifies the dialer)."""

    # Worst-case blocking inside the loop: one accept (200ms) + one hello
    # recv + the setup-exchange recvs (PF probe window AND CW credit
    # window), each _HELLO_TIMEOUT_MS. close() must join for longer than
    # their sum so the native endpoint is never destroyed under a thread
    # inside a C call.
    _HELLO_TIMEOUT_MS = 2000
    _PARTIAL_TTL_S = 30.0

    @classmethod
    def _join_timeout_s(cls) -> float:
        return 0.2 + 3 * (cls._HELLO_TIMEOUT_MS / 1000.0) + 1.0

    def __init__(self, ep: Endpoint, on_channel, chunk_bytes: Optional[int] = None):
        import threading

        self.ep = ep
        self._on_channel = on_channel
        self._chunk_bytes = chunk_bytes
        self._stop = False
        self._partial = {}  # token -> (meta, n_paths, {idx: conn}, first_seen)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _expire_partials(self):
        """Drop handshakes whose dialer died mid-way so their conns don't
        accumulate on a long-lived endpoint."""
        import time

        now = time.monotonic()
        for token in list(self._partial):
            meta, np_, paths, first_seen = self._partial[token]
            if now - first_seen > self._PARTIAL_TTL_S:
                del self._partial[token]
                for cid in paths.values():
                    self.ep.remove_conn(cid)

    def _run(self):
        import time

        while not self._stop:
            self._expire_partials()
            try:
                cid = self.ep.accept(timeout_ms=200)
            except TimeoutError:
                continue
            except Exception:
                return  # endpoint closed
            try:
                hello = self.ep.recv(cid, timeout_ms=self._HELLO_TIMEOUT_MS)
                token, idx, n_paths, meta = Channel._parse_hello(hello)
            except Exception:
                self.ep.remove_conn(cid)  # junk or dawdling dialer
                continue
            meta0, np_, paths, _ = self._partial.setdefault(
                token, (meta, n_paths, {}, time.monotonic())
            )
            paths[idx] = cid
            if len(paths) == np_:
                del self._partial[token]
                chan = Channel(
                    self.ep,
                    [paths[i] for i in range(np_)],
                    self._chunk_bytes,
                    meta0,
                )
                try:
                    chan._exchange_probe_window(self._HELLO_TIMEOUT_MS)
                except Exception:
                    chan.close()  # dialer died mid-setup
                    continue
                self._on_channel(chan)

    def close(self):
        self._stop = True
        self._thread.join(timeout=self._join_timeout_s())
