"""Python API of the P2P transfer engine (ctypes over the C++ runtime).

Mirrors the reference's ``uccl.p2p`` surface (p2p/engine_api.cc nanobind module:
Endpoint with connect/accept/reg/advertise/read/write/[_async]/poll_async) with
jax/numpy-aware helpers. TPU HBM arrays move via host staging (``np.asarray`` /
``jax.device_put``) — the TPU analog of the reference's GPU-bounce paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple, Union

import numpy as np

from uccl_tpu import obs
from uccl_tpu.utils.config import param
from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")

# Transfer-engine byte accounting on the obs registry (docs/OBSERVABILITY.md):
# one labeled series for every verb class, incremented at the Python call
# site with the payload size — the auditable "every transferred byte" face
# of the KV-handoff path (native bytes_tx/rx remain the wire-level truth,
# including retransmits; this series is application intent).
_P2P_BYTES = obs.counter(
    "p2p_bytes_total",
    "payload bytes entering the p2p engine per verb "
    "(write/read/send/recv/notif; vectorized calls count per element)",
)
# Terminal transfer failures, by reason — raised exceptions also land
# here so a chaos run's failure mix is auditable from metrics alone
# (reason=wait_timeout: a vectorized write/read element never completed;
# reason=undelivered/stalled/credit_stall: the windowed SACK transport
# gave up — p2p/channel.py; reason=kv_slab: a disagg KV slab write —
# serving/disagg.py).
_P2P_FAILS = obs.counter(
    "p2p_transfer_failures_total",
    "one-sided transfers that failed terminally, by reason",
)

_stage_chunk_bytes = param(
    "stage_chunk_bytes", 8 << 20,
    help="HBM<->host staging pipeline chunk size for send_jax/recv_jax",
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libuccl_tpu.so")
# Installed-wheel location: setup.py packages the prebuilt runtime inside the
# package (uccl_tpu/_native/); present there, no source tree is needed.
_WHEEL_SO = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "_native", "libuccl_tpu.so",
)

FIFO_ITEM_BYTES = 64

_lib = None
_lib_lock = threading.Lock()


def _build_if_needed() -> str:
    # Installed wheel: the runtime ships prebuilt inside the package and
    # there is no source tree to hash or rebuild against.
    if not os.path.isdir(_NATIVE_DIR) and os.path.exists(_WHEEL_SO):
        return _WHEEL_SO
    srcs = [
        os.path.join(_NATIVE_DIR, "src", "engine.cc"),
        os.path.join(_NATIVE_DIR, "src", "c_api.cc"),
        os.path.join(_NATIVE_DIR, "src", "net_plugin.cc"),
        os.path.join(_NATIVE_DIR, "src", "float_codec.cc"),
        os.path.join(_NATIVE_DIR, "include", "uccl_tpu", "engine.h"),
        os.path.join(_NATIVE_DIR, "include", "uccl_tpu", "net_plugin.h"),
        os.path.join(_NATIVE_DIR, "include", "uccl_tpu", "ring.h"),
        os.path.join(_NATIVE_DIR, "include", "uccl_tpu", "lrpc.h"),
        os.path.join(_NATIVE_DIR, "include", "uccl_tpu", "pool.h"),
    ]
    # `make all` produces every artifact; freshness requires them all so a
    # consumer of any one (e.g. the net plugin tests) can trust the build.
    _artifacts = [
        _SO_PATH,
        os.path.join(_NATIVE_DIR, "build", "libuccl_tpu_net.so"),
    ]

    # Content-hash freshness (not mtimes): a prebuilt .so is only trusted if
    # it was produced from exactly the sources present now, so checkout-order
    # mtime skew can neither skip a needed rebuild nor load a stale binary.
    import hashlib

    def src_digest() -> str:
        hasher = hashlib.sha256()
        for s in srcs:
            if os.path.exists(s):
                with open(s, "rb") as f:
                    hasher.update(f.read())
        return hasher.hexdigest()

    digest_path = os.path.join(_NATIVE_DIR, "build", ".src_hash")

    def fresh() -> bool:
        if not all(os.path.exists(a) for a in _artifacts):
            return False
        if not os.path.exists(digest_path):
            return False
        with open(digest_path) as f:
            return f.read().strip() == src_digest()

    if fresh():
        return _SO_PATH
    # Cross-process build lock: concurrent first-use (e.g. multiprocessing
    # tests) must not race `make` writing the same objects.
    import fcntl

    os.makedirs(os.path.join(_NATIVE_DIR, "build"), exist_ok=True)
    lock_path = os.path.join(_NATIVE_DIR, "build", ".build.lock")
    with open(lock_path, "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        if not fresh():  # re-check under the lock
            _log.info("building native runtime: make -C %s", _NATIVE_DIR)
            subprocess.run(
                ["make", "-C", _NATIVE_DIR], check=True, capture_output=True
            )
            with open(digest_path, "w") as f:
                f.write(src_digest())
    return _SO_PATH


def net_plugin_path() -> str:
    """Path to the loadable NCCL-net-shaped plugin .so (built if needed).

    Consumers dlopen it and read the exported ``ucclt_net_v1`` vtable
    (native/include/uccl_tpu/net_plugin.h) — the analog of pointing
    NCCL_NET_PLUGIN at the reference's libnccl-net-uccl.so."""
    main = _build_if_needed()
    if main == _WHEEL_SO:
        return os.path.join(os.path.dirname(_WHEEL_SO), "libuccl_tpu_net.so")
    return os.path.join(_NATIVE_DIR, "build", "libuccl_tpu_net.so")


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_build_if_needed())
        c = ctypes.c_void_p
        lib.ucclt_create.restype = c
        lib.ucclt_create.argtypes = [ctypes.c_uint16, ctypes.c_int]
        lib.ucclt_create_bound.restype = ctypes.c_void_p
        lib.ucclt_create_bound.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int,
        ]
        lib.ucclt_destroy.argtypes = [c]
        lib.ucclt_listen_port.restype = ctypes.c_uint16
        lib.ucclt_listen_port.argtypes = [c]
        lib.ucclt_connect.restype = ctypes.c_int64
        lib.ucclt_connect.argtypes = [c, ctypes.c_char_p, ctypes.c_uint16]
        lib.ucclt_connect_from.restype = ctypes.c_int64
        lib.ucclt_connect_from.argtypes = [
            c, ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p,
        ]
        lib.ucclt_peer_addr.restype = ctypes.c_int
        lib.ucclt_peer_addr.argtypes = [
            c, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.ucclt_conn_alive.restype = ctypes.c_int
        lib.ucclt_conn_alive.argtypes = [c, ctypes.c_uint64]
        lib.ucclt_accept.restype = ctypes.c_int64
        lib.ucclt_accept.argtypes = [c, ctypes.c_int]
        lib.ucclt_remove_conn.restype = ctypes.c_int
        lib.ucclt_remove_conn.argtypes = [c, ctypes.c_uint64]
        lib.ucclt_reg.restype = ctypes.c_uint64
        lib.ucclt_reg.argtypes = [c, ctypes.c_void_p, ctypes.c_size_t]
        lib.ucclt_dereg.restype = ctypes.c_int
        lib.ucclt_dereg.argtypes = [c, ctypes.c_uint64]
        lib.ucclt_advertise.restype = ctypes.c_int
        lib.ucclt_advertise.argtypes = [
            c, ctypes.c_uint64, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_char_p,
        ]
        for name in ("ucclt_write", "ucclt_read"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [c, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
                           ctypes.c_char_p]
        for name in ("ucclt_write_async", "ucclt_read_async"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_uint64
            fn.argtypes = [c, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t,
                           ctypes.c_char_p]
        for name in ("ucclt_writev_async", "ucclt_readv_async"):
            fn = getattr(lib, name)
            fn.restype = None
            fn.argtypes = [
                c, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_void_p),   # srcs/dsts
                ctypes.POINTER(ctypes.c_size_t),   # lens
                ctypes.c_char_p,                   # packed fifos (n*64)
                ctypes.c_size_t,                   # n
                ctypes.POINTER(ctypes.c_uint64),   # xids_out
            ]
        lib.ucclt_poll.restype = ctypes.c_int
        lib.ucclt_poll.argtypes = [c, ctypes.c_uint64]
        lib.ucclt_wait.restype = ctypes.c_int
        lib.ucclt_wait.argtypes = [c, ctypes.c_uint64, ctypes.c_int]
        lib.ucclt_send.restype = ctypes.c_int
        lib.ucclt_send.argtypes = [c, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t]
        lib.ucclt_recv.restype = ctypes.c_int64
        lib.ucclt_recv.argtypes = [c, ctypes.c_uint64, ctypes.c_void_p,
                                   ctypes.c_size_t, ctypes.c_int]
        if hasattr(lib, "ucclt_reap"):  # added after the v1 ABI
            lib.ucclt_reap.restype = None
            lib.ucclt_reap.argtypes = [c, ctypes.c_uint64]
        if hasattr(lib, "ucclt_send_notif"):
            lib.ucclt_send_notif.restype = ctypes.c_int
            lib.ucclt_send_notif.argtypes = [
                c, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t
            ]
            lib.ucclt_get_notif.restype = ctypes.c_int64
            lib.ucclt_get_notif.argtypes = [
                c, ctypes.POINTER(ctypes.c_uint64), ctypes.c_void_p,
                ctypes.c_size_t,
            ]
        lib.ucclt_set_drop_rate.argtypes = [c, ctypes.c_double]
        if hasattr(lib, "ucclt_set_reorder_rate"):
            lib.ucclt_set_reorder_rate.argtypes = [c, ctypes.c_double]
            lib.ucclt_set_delay_jitter_us.argtypes = [c, ctypes.c_int64]
            lib.ucclt_set_conn_fault.restype = ctypes.c_int
            lib.ucclt_set_conn_fault.argtypes = [
                c, ctypes.c_uint64, ctypes.c_double, ctypes.c_double,
                ctypes.c_int64,
            ]
        lib.ucclt_set_rate_limit.argtypes = [c, ctypes.c_uint64]
        if hasattr(lib, "ucclt_conn_stats"):
            lib.ucclt_conn_stats.restype = ctypes.c_int
            lib.ucclt_conn_stats.argtypes = [
                c, ctypes.c_uint64, ctypes.POINTER(_ConnStatsC)
            ]
            lib.ucclt_set_conn_rate.restype = ctypes.c_int
            lib.ucclt_set_conn_rate.argtypes = [
                c, ctypes.c_uint64, ctypes.c_uint64
            ]
        if hasattr(lib, "ucclt_flush_conn"):
            lib.ucclt_flush_conn.restype = ctypes.c_int
            lib.ucclt_flush_conn.argtypes = [c, ctypes.c_uint64, ctypes.c_int]
        lib.ucclt_bytes_tx.restype = ctypes.c_uint64
        lib.ucclt_bytes_tx.argtypes = [c]
        lib.ucclt_bytes_rx.restype = ctypes.c_uint64
        lib.ucclt_bytes_rx.argtypes = [c]
        lib.ucclt_stats_json.restype = ctypes.c_int64
        lib.ucclt_stats_json.argtypes = [c, ctypes.c_char_p, ctypes.c_size_t]
        _lib = lib
        return _lib


class _ConnStatsC(ctypes.Structure):
    """Mirror of ucclt_conn_stats_t (append-only layout)."""

    _fields_ = [
        ("rtt_us", ctypes.c_double),
        ("pkts_tx", ctypes.c_uint64),
        ("pkts_rtx", ctypes.c_uint64),
        ("pkts_rx", ctypes.c_uint64),
        ("acks_rx", ctypes.c_uint64),
        ("bytes_unacked", ctypes.c_uint64),
        ("rate_bps", ctypes.c_uint64),
        ("udp_active", ctypes.c_int32),
        ("pad", ctypes.c_int32),
    ]


def _as_buffer(arr: np.ndarray) -> Tuple[ctypes.c_void_p, int]:
    if not arr.flags["C_CONTIGUOUS"]:
        raise ValueError("array must be C-contiguous")
    return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes


class Endpoint:
    """P2P transfer endpoint (reference: p2p Endpoint, engine.h:243).

    Threat model: built for a trusted cluster fabric (the reference's RDMA
    assumption) — window tokens guard against buggy peers and stale
    descriptors, not adversaries with TCP reach. On multi-tenant hosts pass
    ``listen_ip`` (or set ``UCCL_TPU_LISTEN_IP``) to pin the listener to the
    fabric interface instead of INADDR_ANY.
    """

    def __init__(self, port: int = 0, n_engines: int = 2,
                 listen_ip: Optional[str] = None):
        self._lib = _load()
        if listen_ip is None:
            listen_ip = os.environ.get("UCCL_TPU_LISTEN_IP")
        self.listen_ip = listen_ip  # the bound interface (None = INADDR_ANY)
        self._h = self._lib.ucclt_create_bound(
            listen_ip.encode() if listen_ip else None, port, n_engines
        )
        if not self._h:
            raise RuntimeError(
                f"failed to create endpoint (port {port} in use, or bad "
                f"listen ip {listen_ip!r}?)"
            )
        self._mrs = {}  # mr_id -> ndarray (keepalive)
        self._inflight = {}  # xfer_id -> ndarray (keepalive until completion)
        # C++ completions are one-shot (the engine reclaims the entry on first
        # observation); this caches the terminal result so wait() followed by
        # poll_async() stays friendly. Entries are tiny and consumed on read.
        self._results = {}

    def _handle(self):
        if not self._h:
            raise ValueError("endpoint is closed")
        return self._h

    # -- lifecycle -------------------------------------------------------
    @property
    def port(self) -> int:
        return self._lib.ucclt_listen_port(self._handle())

    def close(self):
        if self._h:
            self._lib.ucclt_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- connections -----------------------------------------------------
    def connect(self, ip: str, port: int, local_ip: str = None) -> int:
        """``local_ip`` binds the conn's source address to one interface —
        per-path NIC selection for multipath channels (the reference's
        multi-NIC data channels, p2p/rdma/rdma_endpoint.h:117)."""
        if local_ip:
            cid = self._lib.ucclt_connect_from(
                self._handle(), ip.encode(), port, local_ip.encode()
            )
        else:
            cid = self._lib.ucclt_connect(self._handle(), ip.encode(), port)
        if cid < 0:
            raise ConnectionError(
                f"connect to {ip}:{port} failed"
                + (f" (local_ip={local_ip})" if local_ip else "")
            )
        return cid

    def peer_addr(self, conn_id: int) -> str:
        """'ip:port' of the conn's peer (verifies per-path NIC placement)."""
        buf = ctypes.create_string_buffer(64)
        if self._lib.ucclt_peer_addr(self._handle(), conn_id, buf, 64) != 0:
            # Unknown id OR getpeername failed (peer reset a registered conn)
            raise KeyError(
                f"conn {conn_id}: unknown, or peer address unavailable "
                "(disconnected?)"
            )
        return buf.value.decode()

    def conn_alive(self, conn_id: int) -> bool:
        """True while the conn is registered and not marked dead."""
        return bool(self._lib.ucclt_conn_alive(self._handle(), conn_id))

    def accept(self, timeout_ms: int = 10000) -> int:
        cid = self._lib.ucclt_accept(self._handle(), timeout_ms)
        if cid < 0:
            raise TimeoutError("accept timed out")
        return cid

    def remove_conn(self, conn_id: int) -> bool:
        return self._lib.ucclt_remove_conn(self._handle(), conn_id) == 0

    # -- memory ----------------------------------------------------------
    def reg(self, arr: np.ndarray) -> int:
        """Register a writable numpy buffer; the endpoint keeps it alive."""
        ptr, nbytes = _as_buffer(arr)
        mr = self._lib.ucclt_reg(self._handle(), ptr, nbytes)
        self._mrs[mr] = arr
        return mr

    def dereg(self, mr: int) -> bool:
        self._mrs.pop(mr, None)
        return self._lib.ucclt_dereg(self._handle(), mr) == 0

    def advertise(self, mr: int, offset: int = 0, length: Optional[int] = None) -> bytes:
        """Serialize a 64-byte FifoItem for out-of-band exchange (reference:
        advertise + serialize_fifo_item, engine.h:347)."""
        if length is None:
            length = self._mrs[mr].nbytes - offset
        buf = ctypes.create_string_buffer(FIFO_ITEM_BYTES)
        if self._lib.ucclt_advertise(self._handle(), mr, offset, length, buf) != 0:
            raise ValueError("advertise failed (bad mr/range)")
        return buf.raw

    # -- one-sided -------------------------------------------------------
    def write(self, conn_id: int, src: np.ndarray, fifo: bytes) -> None:
        ptr, nbytes = _as_buffer(src)
        _P2P_BYTES.inc(nbytes, verb="write")
        if self._lib.ucclt_write(self._handle(), conn_id, ptr, nbytes, fifo) != 0:
            raise IOError("write failed")

    def read(self, conn_id: int, dst: np.ndarray, fifo: bytes) -> None:
        ptr, nbytes = _as_buffer(dst)
        _P2P_BYTES.inc(nbytes, verb="read")
        if self._lib.ucclt_read(self._handle(), conn_id, ptr, nbytes, fifo) != 0:
            raise IOError("read failed")

    def write_async(self, conn_id: int, src: np.ndarray, fifo: bytes) -> int:
        ptr, nbytes = _as_buffer(src)
        _P2P_BYTES.inc(nbytes, verb="write")
        xid = self._lib.ucclt_write_async(self._handle(), conn_id, ptr, nbytes, fifo)
        # Keep the buffer alive until completion: the tx proxy thread reads
        # from the raw pointer after this call returns.
        self._inflight[xid] = src
        return xid

    def read_async(self, conn_id: int, dst: np.ndarray, fifo: bytes) -> int:
        ptr, nbytes = _as_buffer(dst)
        _P2P_BYTES.inc(nbytes, verb="read")
        xid = self._lib.ucclt_read_async(self._handle(), conn_id, ptr, nbytes, fifo)
        self._inflight[xid] = dst
        return xid

    def _vec_async(self, c_fn, conn_id: int, arrays, fifos, verb: str):
        """Shared descriptor-array fan-out: one C call, one engine wake."""
        n = len(arrays)
        bufs = [_as_buffer(a) for a in arrays]
        ptrs = (ctypes.c_void_p * n)(*[p for p, _ in bufs])
        lens = (ctypes.c_size_t * n)(*[ln for _, ln in bufs])
        packed = b"".join(bytes(f) for f in fifos)
        if len(packed) != n * FIFO_ITEM_BYTES:
            raise ValueError("fifos must be n packed 64-byte descriptors")
        _P2P_BYTES.inc(sum(ln for _, ln in bufs), verb=verb)
        xids = (ctypes.c_uint64 * n)()
        c_fn(self._handle(), conn_id, ptrs, lens, packed, n, xids)
        out = list(xids)
        for x, a in zip(out, arrays):
            self._inflight[x] = a
        return out

    def writev_async(self, conn_id: int, srcs, fifos):
        """Vectorized async write over descriptor arrays (reference:
        writev_async + XferDescList, engine.h:317, engine_api.cc:448):
        one C call enqueues the whole batch with a single proxy wake.
        Returns per-element xfer ids."""
        return self._vec_async(self._lib.ucclt_writev_async, conn_id, srcs,
                               fifos, "write")

    def readv_async(self, conn_id: int, dsts, fifos):
        """Vectorized async read (reference: readv, engine.h:324)."""
        return self._vec_async(self._lib.ucclt_readv_async, conn_id, dsts,
                               fifos, "read")

    def _wait_all(self, xids, what: str) -> None:
        # Drain EVERY element before raising: abandoning the rest of the
        # batch would leak their _inflight keepalives + native completions.
        failed = [x for x in xids if not self.wait(x)]
        if failed:
            _P2P_FAILS.inc(len(failed), reason="wait_timeout")
            obs.instant("p2p_transfer_failed", track="wire",
                        reason="wait_timeout", what=what,
                        failed=len(failed), total=len(xids))
            raise IOError(f"{what}: {len(failed)}/{len(xids)} elements failed")

    def writev(self, conn_id: int, srcs, fifos) -> None:
        """Vectorized write (reference: writev, engine.h:311)."""
        self._wait_all(self.writev_async(conn_id, srcs, fifos), "writev")

    def readv(self, conn_id: int, dsts, fifos) -> None:
        """Vectorized read (reference: readv, engine.h:321)."""
        self._wait_all(self.readv_async(conn_id, dsts, fifos), "readv")

    def poll_async(self, xfer_id: int) -> Optional[bool]:
        """None = pending, True = done; raises on error (reference
        poll_async). Completions are one-shot: the first terminal
        observation (here or in wait()) consumes the id; polling a consumed
        id raises. A successful terminal poll leaves one cached entry for a
        follow-up wait() — wait() consumes it."""
        if xfer_id in self._results:
            return True  # parked success; wait() consumes it
        r = self._lib.ucclt_poll(self._handle(), xfer_id)
        if r == 0:
            return None
        self._inflight.pop(xfer_id, None)  # completed either way
        if r == 1:
            self._results[xfer_id] = True  # allow one follow-up observation
            return True
        raise IOError(f"transfer {xfer_id} failed")

    def wait(self, xfer_id: int, timeout_ms: int = 30000) -> bool:
        # _results holds only successful ids parked by poll_async for a
        # follow-up wait (errors raise there and then); popping one is True.
        if self._results.pop(xfer_id, None) is not None:
            return True
        ok = self._lib.ucclt_wait(self._handle(), xfer_id, timeout_ms) == 0
        if ok:
            # Terminal observation consumes the id — caching a True here
            # "for a follow-up" would leak one entry per completed transfer
            # (nothing performs the follow-up on success paths).
            self._inflight.pop(xfer_id, None)
            return True
        # Distinguish timeout (entry still pending) from a consumed
        # terminal. The completion can land in the race window between the
        # native wait's deadline and this poll — a kDone here IS success
        # (returning False would make retry loops count a delivered
        # transfer as lost, raising on the final attempt).
        r = self._lib.ucclt_poll(self._handle(), xfer_id)
        if r != 0:
            self._inflight.pop(xfer_id, None)
        return r == 1

    def reap(self, xfer_id: int) -> None:
        """Forget an abandoned transfer on BOTH sides of the boundary. For
        callers that observed completion via poll_async and will never
        wait() on the id, and for timed-out chunks being retransmitted —
        without this, late completions accumulate in the results cache and
        lost-frame xfers (which never complete) accumulate in the native
        tracking map forever."""
        self._results.pop(xfer_id, None)
        self._inflight.pop(xfer_id, None)
        reap = getattr(self._lib, "ucclt_reap", None)
        if reap is not None:
            reap(self._handle(), ctypes.c_uint64(xfer_id))

    # -- two-sided -------------------------------------------------------
    def send(self, conn_id: int, data: Union[bytes, np.ndarray]) -> None:
        if isinstance(data, np.ndarray):
            ptr, nbytes = _as_buffer(data)
        else:
            ptr, nbytes = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p), len(data)
        _P2P_BYTES.inc(nbytes, verb="send")
        if self._lib.ucclt_send(self._handle(), conn_id, ptr, nbytes) != 0:
            raise IOError("send failed")

    def send_notif(self, conn_id: int, data: bytes) -> None:
        """Send an out-of-band notification (NIXL notify: reference
        p2p/uccl_engine.h uccl_engine_send_notif). The peer drains these
        with :meth:`get_notifs` — across ALL connections, non-blocking —
        instead of a per-connection recv()."""
        fn = getattr(self._lib, "ucclt_send_notif", None)
        if fn is None:
            raise RuntimeError("loaded libuccl_tpu.so predates notif ABI")
        ptr = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p)
        _P2P_BYTES.inc(len(data), verb="notif")
        if fn(self._handle(), conn_id, ptr, len(data)) != 0:
            raise IOError("send_notif failed")

    def get_notifs(self, max_n: int = 0) -> list:
        """Drain pending notifications non-blocking (NIXL get_notifs).
        Returns [(conn_id, bytes), ...] oldest-first; at most max_n if >0."""
        fn = getattr(self._lib, "ucclt_get_notif", None)
        if fn is None:
            return []  # old ABI: nothing can have been sent either
        out = []
        cap = 4096
        buf = ctypes.create_string_buffer(cap)
        conn = ctypes.c_uint64()
        while not max_n or len(out) < max_n:
            n = fn(self._handle(), ctypes.byref(conn), buf, cap)
            if n <= -2:  # message larger than buf: resize and retry
                cap = -(int(n) + 2)
                buf = ctypes.create_string_buffer(cap)
                continue
            if n < 0:
                break
            out.append((conn.value, buf.raw[: int(n)]))
        return out

    def recv(self, conn_id: int, max_bytes: int = 1 << 20, timeout_ms: int = 10000) -> bytes:
        buf = ctypes.create_string_buffer(max_bytes)
        n = self._lib.ucclt_recv(self._handle(), conn_id, buf, max_bytes, timeout_ms)
        if n <= -2:
            # message larger than the buffer: engine left it queued and told
            # us the required size — retry with an exact-size buffer
            needed = -(n + 2)
            buf = ctypes.create_string_buffer(needed)
            n = self._lib.ucclt_recv(self._handle(), conn_id, buf, needed, timeout_ms)
        if n < 0:
            raise TimeoutError("recv timed out")
        _P2P_BYTES.inc(int(n), verb="recv")
        return buf.raw[:n]

    def recv_into(self, conn_id: int, out: np.ndarray, timeout_ms: int = 10000) -> int:
        """Receive one message directly into a caller buffer (no allocation,
        no zero-fill — ``create_string_buffer`` memsets its whole capacity,
        which the chunked staging loop cannot afford). ``out`` must be a
        C-contiguous uint8 array; returns the message length."""
        assert out.dtype == np.uint8 and out.flags["C_CONTIGUOUS"]
        ptr = out.ctypes.data_as(ctypes.c_void_p)
        n = self._lib.ucclt_recv(
            self._handle(), conn_id, ptr, out.nbytes, timeout_ms
        )
        if n <= -2:
            raise IOError(
                f"recv_into: {-(n + 2)} B message exceeds {out.nbytes} B buffer"
            )
        if n < 0:
            raise TimeoutError("recv timed out")
        _P2P_BYTES.inc(int(n), verb="recv")
        return n

    # -- observability / fault injection ---------------------------------
    def set_drop_rate(self, p: float) -> None:
        """Drop each one-sided DATA-plane frame (kWrite/kRead/kReadResp/
        kWriteAck) with probability ``p``. Two-sided send/notif and the
        handshake ride untouched — injection models a lossy data fabric
        under a reliable control plane (UDP wire mode injects at the
        packet level instead, recovered by its SACK layer)."""
        self._lib.ucclt_set_drop_rate(self._handle(), p)

    def set_reorder_rate(self, p: float) -> None:
        """Hold each data frame back with probability ``p`` so the next
        frame on its conn overtakes it (released after ≤2 ms regardless):
        chunks land — and their completions arrive — out of order."""
        fn = getattr(self._lib, "ucclt_set_reorder_rate", None)
        if fn is None:
            raise RuntimeError("loaded libuccl_tpu.so predates fault ABI")
        fn(self._handle(), p)

    def set_delay_jitter_us(self, max_us: int) -> None:
        """Stamp each data frame with a uniform [0, max_us] not-before
        delay (head-of-line per conn — an artificially slow path)."""
        fn = getattr(self._lib, "ucclt_set_delay_jitter_us", None)
        if fn is None:
            raise RuntimeError("loaded libuccl_tpu.so predates fault ABI")
        fn(self._handle(), max_us)

    def set_conn_fault(self, conn_id: int, *, drop: float = -1.0,
                       reorder: float = -1.0, jitter_us: int = -1) -> None:
        """Per-conn fault overrides (−1 inherits the endpoint-global
        knobs) — make SOME multipath channel paths lossy/slow while the
        control path stays clean (the path-quality steering testbed)."""
        fn = getattr(self._lib, "ucclt_set_conn_fault", None)
        if fn is None:
            raise RuntimeError("loaded libuccl_tpu.so predates fault ABI")
        if fn(self._handle(), conn_id, drop, reorder, jitter_us) != 0:
            raise KeyError(f"unknown conn {conn_id}")

    def set_rate_limit(self, bytes_per_sec: int) -> None:
        """Token-bucket pacing on the tx proxies; 0 disables (reference:
        Carousel timing-wheel pacing; actuator for the CC layer in cc.py)."""
        self._lib.ucclt_set_rate_limit(self._handle(), bytes_per_sec)

    def flush(self, conn_id: int, timeout_ms: int = 5000) -> bool:
        """Wait until every queued frame on the conn was handed to the
        kernel — and, on the UDP wire, until every serialized byte was
        ACKED by the peer (delivered, not merely transmitted)."""
        return self._lib.ucclt_flush_conn(
            self._handle(), conn_id, timeout_ms
        ) == 0

    def conn_stats(self, conn_id: int) -> dict:
        """Per-conn transport stats (UDP wire mode: RTT EWMA, packet/retx
        counts, unacked bytes) — the observation side of the CC control
        plane; see :class:`uccl_tpu.p2p.cc.CcController`."""
        s = _ConnStatsC()
        if self._lib.ucclt_conn_stats(
            self._handle(), conn_id, ctypes.byref(s)
        ) != 0:
            raise KeyError(f"unknown conn {conn_id}")
        return {
            "rtt_us": s.rtt_us,
            "pkts_tx": s.pkts_tx,
            "pkts_rtx": s.pkts_rtx,
            "pkts_rx": s.pkts_rx,
            "acks_rx": s.acks_rx,
            "bytes_unacked": s.bytes_unacked,
            "rate_bps": s.rate_bps,
            "udp_active": bool(s.udp_active),
        }

    def set_conn_rate(self, conn_id: int, bytes_per_sec: int) -> None:
        """Per-conn pacing rate (0 = fall back to the endpoint-global
        bucket) — the actuation side of the CC control plane."""
        if self._lib.ucclt_set_conn_rate(
            self._handle(), conn_id, bytes_per_sec
        ) != 0:
            raise KeyError(f"unknown conn {conn_id}")

    @property
    def stats(self) -> dict:
        """Hot-loop engine stats (reference: periodic transport stats,
        collective/rdma/transport.cc:1797 + util/latency.h histograms):
        ``bytes_tx/rx``, ``stats_ticks`` (heartbeats of the 2s stats
        thread; UCCL_TPU_ENGINE_STATS=1 also logs each tick),
        ``notifs_pending`` (undrained out-of-band notifications), and
        per-engine ``engines[i]`` dicts with tx/rx frame counts, frame
        service latency p50/p99 (µs), queued tx bytes, and task-ring
        depth."""
        import json as _json

        buf = ctypes.create_string_buffer(1 << 16)
        n = self._lib.ucclt_stats_json(self._handle(), buf, len(buf))
        return _json.loads(buf.raw[:n].decode())

    # -- jax staging helpers ---------------------------------------------
    def send_jax(self, conn_id: int, x, *, chunk_bytes: Optional[int] = None) -> None:
        """Device→host stage then two-sided send (KV-cache push path).

        Pipelined (SURVEY §7 hard-part 3; the reference hides staging with
        GPUDirect/bounce-pool pipelining, p2p/engine.cc staged paths): the
        tensor is sliced on-device into ``chunk_bytes`` pieces whose
        device→host DMAs all start up-front (``copy_to_host_async``); each
        chunk is enqueued on the wire the moment it lands, so TX of chunk i
        overlaps D2H of chunks i+1..  ``Endpoint.send`` itself only copies
        into the conn's tx queue (engine.cc:490-507) — the tx proxy thread
        drains it concurrently. One message per chunk; ``recv_jax``
        reassembles by total byte count, so chunked and monolithic senders
        interoperate."""
        import jax

        if chunk_bytes is None:
            chunk_bytes = int(_stage_chunk_bytes.get())
        if not isinstance(x, jax.Array) or x.nbytes <= chunk_bytes:
            self.send(conn_id, np.ascontiguousarray(np.asarray(x)))
            return
        flat = x.reshape(-1)  # row-major flatten: layout-preserving
        elems = max(1, chunk_bytes // x.dtype.itemsize)
        parts = [flat[i:i + elems] for i in range(0, flat.shape[0], elems)]
        for p in parts:
            try:
                p.copy_to_host_async()  # start every D2H DMA now
            except AttributeError:  # non-ArrayImpl (e.g. tracer-free numpy)
                break
        for p in parts:
            self.send(conn_id, np.ascontiguousarray(np.asarray(p)))

    def recv_jax(self, conn_id: int, shape, dtype, device=None, timeout_ms: int = 30000):
        """Receive a tensor staged by :meth:`send_jax` (either monolithic or
        chunked): messages are reassembled by total byte count, and each
        chunk's host→device transfer starts as soon as it arrives
        (``jax.device_put`` dispatches asynchronously), overlapping H2D with
        the remaining wire receives."""
        import jax
        import jax.numpy as jnp

        itemsize = np.dtype(dtype).itemsize
        nbytes = int(np.prod(shape)) * itemsize
        if nbytes == 0:
            return jax.device_put(np.empty(shape, dtype), device)
        host = np.empty(nbytes, np.uint8)  # one buffer, messages land in place
        # Per-chunk H2D pipelining applies to single-Device targets on real
        # accelerators. A Sharding target (multi-axis specs shard the FULL
        # shape — flat chunks can't be placed) and the CPU backend (put is a
        # zero-copy view) both take the assemble-then-put path.
        plat = getattr(device, "platform", None)
        if device is None:
            plat = jax.default_backend()
        pipelined = plat is not None and plat != "cpu"
        parts, got = [], 0
        while got < nbytes:
            n = self.recv_into(conn_id, host[got:], timeout_ms=timeout_ms)
            if n % itemsize:
                raise IOError(
                    f"recv_jax: {n} B message misaligned with dtype "
                    f"{np.dtype(dtype)}"
                )
            if pipelined:
                # start this chunk's H2D DMA now (device_put dispatches
                # asynchronously) — overlaps with the remaining wire recvs
                parts.append(
                    jax.device_put(
                        host[got:got + n].view(dtype), device
                    )
                )
            got += n
        if not pipelined:
            return jax.device_put(
                host.view(dtype).reshape(shape), device
            )
        dev = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        return dev.reshape(shape)
