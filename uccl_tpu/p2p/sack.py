"""Selective-repeat SACK sender window for the multipath channel.

The paper's transport pillar (PAPER.md §0.1) re-expressed at chunk
granularity: a transfer's chunks get per-chunk sequence numbers and a
bounded in-flight window; per-chunk completion acks (the engine's
kWriteAck, arriving out of order across paths) drive **cumulative ack +
SACK** state exactly like the native UDP wire's packet layer
(native/src/engine.cc udp_send_ack / pcb.h in the reference:
snd_una/rcv_nxt + kSackBitmapSize bitmaps) — and that state drives
*selective repeat*: after ``dupack_k`` later-sequence acks land while an
earlier chunk is still outstanding, exactly that chunk fast-retransmits;
chunks nothing vouches for retransmit on an RTO with exponential backoff
(Jacobson srtt/rttvar, Karn's rule for samples). A per-path delivery EWMA
steers both retransmits and new chunks away from lossy/slow paths instead
of the old blind ``(ci + attempt) % n_paths`` rotation.

Pure host state machine — no transport calls, no threads — so the whole
window logic is property-testable in microseconds (tests/test_sack.py).
:class:`uccl_tpu.p2p.channel.Channel` owns the transport loop that feeds
it.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

# (re)transmission kinds, as exported on p2p_channel_retx_total{kind=}
NEW = "new"
FAST = "fast"  # SACK-gap fast retransmit after dupack_k duplicate acks
RTO = "rto"    # retransmission timeout (exponential backoff) / path death

# Flight-recorder arming (docs/OBSERVABILITY.md). Both thresholds are
# process-wide and default OFF: a handful of retransmits is the normal
# cost of a lossy path, not a pathology. A chaos arm (or a deployment
# that knows its loss budget) arms them; every subsequently created
# window then fires AT MOST one ``retx_storm`` / ``rto_backoff`` flight
# trigger, carrying its own stats() as the post-mortem context.
_FLIGHT = {"storm_after": None, "rto_backoff_s": None}


def arm_flight(storm_after: Optional[int] = None,
               rto_backoff_s: Optional[float] = None) -> None:
    """Arm (or with Nones, disarm) the transport flight triggers:
    ``storm_after`` = total retransmits within ONE window that count as
    a storm; ``rto_backoff_s`` = a backed-off per-chunk RTO this large
    means sustained loss/blackout rather than isolated drops."""
    _FLIGHT["storm_after"] = storm_after
    _FLIGHT["rto_backoff_s"] = rto_backoff_s


def flight_armed() -> Dict[str, Optional[float]]:
    return dict(_FLIGHT)


class PathQuality:
    """Per-path delivery EWMA + smoothed RTT + in-flight load.

    ``score`` is an EWMA of delivery outcomes in [0, 1] (ack → toward 1,
    loss → toward 0). New chunks go to the path maximizing
    ``score / (1 + inflight)`` — quality-weighted load balancing that
    degenerates to round-robin on healthy symmetric paths and starves a
    lossy path in proportion to its loss. Retransmits go to the
    best-scoring path *other than* the one that just lost the chunk.
    """

    def __init__(self, n_paths: int, alpha: float = 0.25):
        if n_paths < 1:
            raise ValueError("need at least one path")
        self.alpha = alpha
        self.score = [1.0] * n_paths
        self.srtt_us = [0.0] * n_paths
        self.inflight = [0] * n_paths

    @property
    def n_paths(self) -> int:
        return len(self.score)

    def on_sent(self, path: int) -> None:
        self.inflight[path] += 1

    def on_ack(self, path: int, rtt_us: Optional[float] = None) -> None:
        self.inflight[path] = max(0, self.inflight[path] - 1)
        self.score[path] += self.alpha * (1.0 - self.score[path])
        if rtt_us is not None:
            s = self.srtt_us[path]
            self.srtt_us[path] = (
                rtt_us if s == 0.0 else 0.875 * s + 0.125 * rtt_us
            )

    def on_loss(self, path: int) -> None:
        self.inflight[path] = max(0, self.inflight[path] - 1)
        self.score[path] *= 1.0 - self.alpha

    def pick_new(self) -> int:
        best, best_w = 0, -1.0
        for i in range(self.n_paths):
            w = self.score[i] / (1.0 + self.inflight[i])
            if w > best_w:
                best, best_w = i, w
        return best

    def pick_retx(self, avoid: int) -> int:
        if self.n_paths == 1:
            return 0
        best, best_w = -1, -1.0
        for i in range(self.n_paths):
            if i == avoid:
                continue
            # prefer quality; break ties toward the less-loaded path
            w = self.score[i] / (1.0 + self.inflight[i])
            if w > best_w:
                best, best_w = i, w
        return best


@dataclasses.dataclass
class _Chunk:
    seq: int
    nbytes: int
    acked: bool = False
    n_tx: int = 0
    t_last_tx: float = -1.0     # monotonic seconds of last (re)transmission
    last_path: int = -1
    dupacks: int = 0            # later-seq acks seen while outstanding
    fast_pending: bool = False  # marked for SACK-gap fast retransmit
    err_pending: bool = False   # transport error (path died): reissue now


class SackTxWindow:
    """Sender-side selective-repeat window over a fixed chunk list.

    Drive it with::

        win = SackTxWindow([len0, len1, ...], n_paths=4)
        while not win.done():
            for seq, kind in win.sendable(now, cwnd_bytes):
                path = win.pick_path(seq, kind)
                ...issue chunk seq on path...
                win.mark_sent(seq, path, kind, now)
            ...observe completions...
            win.on_ack(seq, path=path, rtt_us=rtt, now=now)

    ``max_tx`` bounds per-chunk attempts; once a chunk is *due* again with
    no attempts left it lands in :meth:`exhausted` and the caller fails
    the transfer. RTT samples follow Karn's rule (first transmissions
    only) into Jacobson srtt/rttvar; the RTO backs off 2× per attempt.
    """

    def __init__(
        self,
        sizes: Sequence[int],
        n_paths: int,
        *,
        max_tx: int = 3,
        dupack_k: int = 3,
        rto_init_s: float = 0.2,
        rto_min_s: float = 0.025,
        rto_max_s: float = 2.0,
    ):
        if max_tx < 1:
            raise ValueError("max_tx must be >= 1")
        self.chunks = [_Chunk(i, int(n)) for i, n in enumerate(sizes)]
        self.paths = PathQuality(n_paths)
        self.max_tx = max_tx
        self.dupack_k = dupack_k
        self.rto_min_s = rto_min_s
        self.rto_max_s = rto_max_s
        self.rto_s = min(max(rto_init_s, rto_min_s), rto_max_s)
        self.srtt_us = 0.0
        self.rttvar_us = 0.0
        self.cum_ack = 0        # every seq < cum_ack is acked
        self.acks = 0
        self.retx_fast = 0
        self.retx_rto = 0
        self._next_new = 0
        self._inflight_bytes = 0  # sent & un-acked, kept incrementally
        self._flight_fired = set()  # trigger kinds already fired here

    # -- progress --------------------------------------------------------
    def done(self) -> bool:
        return self.cum_ack >= len(self.chunks)

    def inflight_bytes(self) -> int:
        # maintained incrementally (mark_sent/on_ack) — sendable() runs
        # every transfer-loop tick, so a full O(chunks) sum here would
        # dominate large transfers' sender CPU
        return self._inflight_bytes

    def _backoff_rto(self, c: _Chunk) -> float:
        return min(self.rto_s * (2 ** (c.n_tx - 1)), self.rto_max_s)

    # -- receiver-view introspection (mirrors the native ack packet) -----
    def sack_bitmap(self, width: int = 64) -> int:
        """Bit ``rel-1`` set for acked seq ``cum_ack + rel`` (rel ≥ 1) —
        the same layout the native UDP wire puts on its ack packets."""
        bm = 0
        for rel in range(1, width + 1):
            s = self.cum_ack + rel
            if s < len(self.chunks) and self.chunks[s].acked:
                bm |= 1 << (rel - 1)
        return bm

    # -- events ----------------------------------------------------------
    def on_ack(
        self,
        seq: int,
        *,
        now: float,
        path: Optional[int] = None,
        rtt_us: Optional[float] = None,
    ) -> bool:
        """One chunk's delivery confirmed. Returns False for duplicate /
        stale acks (late completion of a superseded attempt)."""
        c = self.chunks[seq]
        if c.acked:
            # stale completion of a superseded attempt: no score/RTT
            # credit, but the attempt leaves the wire — balance the
            # per-path in-flight load term or steering would be biased
            # against the path for the rest of the transfer
            if path is not None:
                self.paths.inflight[path] = max(
                    0, self.paths.inflight[path] - 1)
            return False
        c.acked = True
        c.fast_pending = False
        c.err_pending = False
        self._inflight_bytes -= c.nbytes
        self.acks += 1
        first_tx = c.n_tx <= 1
        if path is not None:
            # Karn's rule: a retransmitted chunk's completion time is
            # ambiguous (which attempt got through?) — no RTT sample.
            self.paths.on_ack(path, rtt_us if first_tx else None)
        if rtt_us is not None and first_tx:
            self._rtt_sample(rtt_us)
        while (self.cum_ack < len(self.chunks)
               and self.chunks[self.cum_ack].acked):
            self.cum_ack += 1
        # Duplicate-ack bookkeeping: this completion is out-of-order
        # evidence against every earlier-sent, still-outstanding chunk
        # below it — after dupack_k such acks the gap chunk fast-retxes
        # (at most once per transmission: mark_sent resets the count).
        for h in self.chunks[self.cum_ack:seq]:
            if h.acked or h.n_tx == 0 or h.fast_pending or h.err_pending:
                continue
            if h.t_last_tx <= c.t_last_tx:
                h.dupacks += 1
                if h.dupacks >= self.dupack_k and h.n_tx < self.max_tx:
                    h.fast_pending = True
        return True

    def on_error(self, seq: int, path: int, now: float,
                 t_sent: Optional[float] = None) -> None:
        """The attempt's transport failed terminally (conn died): count
        the loss against the path and reissue without waiting for RTO.
        ``t_sent`` (the failed attempt's issue time) lets a SUPERSEDED
        attempt's late error charge the path without forcing another
        retransmission — a newer attempt is already in flight, and
        burning an extra n_tx here can exhaust max_tx on a chunk that
        was about to be delivered."""
        c = self.chunks[seq]
        if c.acked:
            self.paths.inflight[path] = max(0, self.paths.inflight[path] - 1)
            return
        self.paths.on_loss(path)
        if t_sent is not None and t_sent < c.t_last_tx:
            return  # stale attempt: the live one owns recovery
        if not c.fast_pending:
            c.err_pending = True

    def _rtt_sample(self, rtt_us: float) -> None:
        if self.srtt_us == 0.0:
            self.srtt_us = rtt_us
            self.rttvar_us = rtt_us / 2.0
        else:
            self.rttvar_us = (0.75 * self.rttvar_us
                              + 0.25 * abs(self.srtt_us - rtt_us))
            self.srtt_us = 0.875 * self.srtt_us + 0.125 * rtt_us
        self.rto_s = min(
            max((self.srtt_us + 4.0 * self.rttvar_us) / 1e6, self.rto_min_s),
            self.rto_max_s,
        )

    # -- scheduling ------------------------------------------------------
    def sendable(self, now: float, cwnd_bytes: int) -> List[Tuple[int, str]]:
        """(seq, kind) list to issue now: fast retransmits first (the SACK
        gaps), then RTO-due chunks, then new chunks while in-flight bytes
        fit ``cwnd_bytes``. Retransmits are exempt from the window gate
        (loss means the window has room); at least one chunk is always
        eligible when nothing is in flight, so a collapsed window can
        never livelock a transfer."""
        out: List[Tuple[int, str]] = []
        for c in self.chunks:
            if c.acked or c.n_tx == 0 or c.n_tx >= self.max_tx:
                continue
            if c.fast_pending:
                out.append((c.seq, FAST))
            elif c.err_pending:
                out.append((c.seq, RTO))
            elif now - c.t_last_tx > self._backoff_rto(c):
                out.append((c.seq, RTO))
        infl = self.inflight_bytes()
        i = self._next_new
        while i < len(self.chunks):
            c = self.chunks[i]
            if infl > 0 and infl + c.nbytes > cwnd_bytes:
                break
            out.append((c.seq, NEW))
            infl += c.nbytes
            i += 1
        return out

    def pick_path(self, seq: int, kind: str) -> int:
        if kind == NEW:
            return self.paths.pick_new()
        return self.paths.pick_retx(avoid=self.chunks[seq].last_path)

    def mark_sent(self, seq: int, path: int, kind: str, now: float) -> None:
        c = self.chunks[seq]
        if kind == NEW:
            self._next_new = max(self._next_new, seq + 1)
            self._inflight_bytes += c.nbytes
        else:
            # the previous attempt is now presumed lost on its path
            if not c.err_pending:  # on_error already charged the loss
                self.paths.on_loss(c.last_path)
            if kind == FAST:
                self.retx_fast += 1
            else:
                self.retx_rto += 1
                # the RTO that just expired for this attempt — past the
                # armed ceiling it is a blackout, not a drop
                limit = _FLIGHT["rto_backoff_s"]
                if (limit is not None
                        and self._backoff_rto(c) >= limit):
                    self._fire_flight("rto_backoff", seq=seq, path=path,
                                      backoff_s=self._backoff_rto(c),
                                      backoff_limit_s=limit)
            storm = _FLIGHT["storm_after"]
            if (storm is not None
                    and self.retx_fast + self.retx_rto >= storm):
                self._fire_flight("retx_storm", seq=seq, path=path,
                                  storm_after=storm)
        c.n_tx += 1
        c.t_last_tx = now
        c.last_path = path
        c.dupacks = 0
        c.fast_pending = False
        c.err_pending = False
        self.paths.on_sent(path)

    def _fire_flight(self, kind: str, **ctx) -> None:
        """At most one flight dump per (window, kind) locally, and the
        PROCESS-WIDE key ``sack:<kind>`` dedupes across windows too —
        one sustained loss episode spans many transfer windows, and one
        post-mortem per fault class is the recorder's contract (later
        windows' storms are counted suppressed, not dumped). Context
        carries the full window stats so the bundle is self-sufficient
        even when no transport provider is registered."""
        if kind in self._flight_fired:
            return
        self._flight_fired.add(kind)
        from uccl_tpu.obs import flight as _flight_mod

        _flight_mod.trigger(kind, key=f"sack:{kind}",
                            **ctx, **self.stats())

    def exhausted(self, now: float) -> List[int]:
        """Chunks due for another transmission with no attempts left —
        non-empty means the transfer has failed."""
        out = []
        for c in self.chunks:
            if c.acked or c.n_tx < self.max_tx:
                continue
            if (c.fast_pending or c.err_pending
                    or now - c.t_last_tx > self._backoff_rto(c)):
                out.append(c.seq)
        return out

    # -- introspection ---------------------------------------------------
    def stats(self) -> Dict[str, object]:
        return {
            "chunks": len(self.chunks),
            "cum_ack": self.cum_ack,
            "acks": self.acks,
            "retx_fast": self.retx_fast,
            "retx_rto": self.retx_rto,
            "srtt_us": round(self.srtt_us, 3),
            "rto_ms": round(self.rto_s * 1e3, 3),
            "inflight_bytes": self.inflight_bytes(),
            "path_scores": [round(s, 4) for s in self.paths.score],
        }
