"""Registration cache: interval-containment reuse of memory registrations.

The reference caches MRs so repeated registration of the same buffer — or a
subregion of an already-registered buffer — reuses the underlying NIC MR
(same lkeys/rkeys) behind a fresh API handle, with refcounts deciding
eviction (p2p/tests/test_register_memory_cache.py) on top of a closed-
interval tree (p2p/tests/test_util_interval_tree.py). On this engine the
costly object is the registration + its advertised windows; the cache
gives the same contract: containment hits reuse the base registration
(windows advertised at an offset into it), partial overlaps and disjoint
ranges register fresh, and a base stays alive while any handle still
references it.

re-registration cost this avoids (the round-4 verdict's 'unmeasured'
point): reg + advertise of a large KV buffer per transfer round trip —
with the cache, steady-state repeat registrations are a dict/bisect hit.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Tuple


class ClosedIntervalTree:
    """Closed-interval index with containment queries — the reference's
    ClosedIntervalTree surface (add/remove/query_containing/query_exact/
    iterate). Backed by a start-sorted list with bisect: registration
    working sets are tens of buffers, where the sorted list beats a
    pointer-chasing tree and keeps removal trivial; the API is what the
    consumers depend on, not the asymptotics."""

    def __init__(self):
        self._starts: List[int] = []  # sorted keys
        self._rows: List[Tuple[int, int, object]] = []  # (start, end, data)

    def add(self, start: int, end: int, data) -> None:
        if end < start:
            raise ValueError(f"bad interval [{start}, {end}]")
        i = bisect.bisect_left(self._starts, start)
        self._starts.insert(i, start)
        self._rows.insert(i, (start, end, data))

    def remove(self, start: int, end: int, data) -> bool:
        i = bisect.bisect_left(self._starts, start)
        while i < len(self._rows) and self._rows[i][0] == start:
            if self._rows[i][1] == end and self._rows[i][2] == data:
                del self._starts[i]
                del self._rows[i]
                return True
            i += 1
        return False

    def query_containing(self, start: int, end: int) -> List[Tuple]:
        """All intervals [s, e] with s <= start and end <= e (closed)."""
        out = []
        hi = bisect.bisect_right(self._starts, start)
        for s, e, d in self._rows[:hi]:
            if e >= end:
                out.append((s, e, d))
        return out

    def query_exact(self, start: int, end: int) -> List[Tuple]:
        i = bisect.bisect_left(self._starts, start)
        out = []
        while i < len(self._rows) and self._rows[i][0] == start:
            if self._rows[i][1] == end:
                out.append(self._rows[i])
            i += 1
        return out

    def query_overlapping(self, start: int, end: int) -> List[Tuple]:
        """All intervals intersecting [start, end]."""
        return [
            (s, e, d) for s, e, d in self._rows if s <= end and e >= start
        ]

    def __iter__(self) -> Iterator[Tuple[int, int, object]]:
        return iter(self._rows)

    def __len__(self) -> int:
        return len(self._rows)


@dataclass
class _Base:
    mr_id: int
    start: int
    end: int  # inclusive of last byte
    refs: int = 0


@dataclass
class _Handle:
    base: _Base
    offset: int  # byte offset of this registration inside the base


class MrCache:
    """Refcounted registration cache over an Endpoint.

    register(arr) returns (handle_id, mr_id, offset): mr_id/offset address
    the (possibly shared) base registration; handle_id is the fresh
    per-call API handle deregister() takes. Contract (mirrors the
    reference's cache tests):

    * same range, or a range fully CONTAINED in a live base → reuse (same
      mr_id; offset points into the base),
    * partial overlap or disjoint → fresh base registration,
    * a base is evicted (ep.dereg) only when its last handle is released.
    """

    def __init__(self, ep):
        self.ep = ep
        self._tree = ClosedIntervalTree()
        self._handles: dict = {}
        self._next_handle = 1
        self.hits = 0
        self.misses = 0

    def register(self, arr) -> Tuple[int, int, int]:
        start = arr.ctypes.data
        end = start + arr.nbytes - 1
        containing = self._tree.query_containing(start, end)
        if containing:
            s, _e, base = containing[0]
            self.hits += 1
        else:
            mr = self.ep.reg(arr)
            base = _Base(mr_id=mr, start=start, end=end)
            self._tree.add(start, end, base)
            s = start
            self.misses += 1
        base.refs += 1
        hid = self._next_handle
        self._next_handle += 1
        self._handles[hid] = _Handle(base=base, offset=start - s)
        return hid, base.mr_id, start - s

    def deregister(self, handle_id: int) -> None:
        h = self._handles.pop(handle_id, None)
        if h is None:
            raise KeyError(f"unknown registration handle {handle_id}")
        h.base.refs -= 1
        if h.base.refs == 0:
            self._tree.remove(h.base.start, h.base.end, h.base)
            self.ep.dereg(h.base.mr_id)

    def stats(self) -> dict:
        return {
            "bases": len(self._tree),
            "handles": len(self._handles),
            "hits": self.hits,
            "misses": self.misses,
        }
