"""P2P transfer engine (pillar 2): NIXL-style register/connect/one-sided
read-write over DCN, with a C++ host runtime underneath.

The analog of the reference's ``p2p/engine.{h,cc}`` (SURVEY.md §2.2). The C++
engine lives in ``native/``; :class:`Endpoint` binds it via ctypes.
"""

from uccl_tpu.p2p.endpoint import Endpoint, FIFO_ITEM_BYTES
from uccl_tpu.p2p.ray_api import XferEndpoint
from uccl_tpu.p2p.channel import Channel, FifoItem
from uccl_tpu.p2p.eqds import PullPacer
from uccl_tpu.p2p.sack import PathQuality, SackTxWindow
from uccl_tpu.p2p.weight_push import (WeightPublisher, WeightSnapshot,
                                      fetch as fetch_weights)

__all__ = ["Endpoint", "FIFO_ITEM_BYTES", "Channel", "FifoItem", "PullPacer",
           "PathQuality", "SackTxWindow", "XferEndpoint", "WeightPublisher",
           "WeightSnapshot", "fetch_weights"]
