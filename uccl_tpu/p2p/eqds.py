"""EQDS-style receiver-driven congestion control: the pull pacer.

The reference ships EQDS (include/cc/eqds.h; pacer thread
collective/rdma/eqds.h:93) — NSDI'22 receiver-driven credit, built for
incast: many senders converging on one receiver link, where sender-side
delay CC reacts a full RTT late. This is the credit half of the windowed
SACK transport (channel.py + sack.py): docs/EQDS.md records the measured
incast sweep where sender-side window CC collapses under loss while this
pacer holds goodput at the receiver's drain rate, and the disagg decode
worker runs it as the fan-in actuator (serving/disagg.py
``DecodeWorker(pull_rate_bps=...)``).

Mechanism (Channel-layer, wire-agnostic):

* every Channel minted a 1×uint64 **credit window** at setup (symmetric,
  like the CC probe window);
* a sender in pull mode (``chan.enable_pull_sender()``) issues a NEW chunk
  only once the receiver's CUMULATIVE grant covers it (the non-blocking
  credit gate inside ``Channel._run_window`` — retransmits are
  pre-licensed, and stalled wall time lands on
  ``p2p_credit_stall_seconds_total``) — the pull quantum, carried by an
  8-byte one-sided write instead of a pull packet;
* the receiver runs ONE :class:`PullPacer` for all inbound channels: a
  token bucket at the receiver's known link rate, split round-robin across
  active channels — the same fair pull schedule the reference's pacer
  computes, pointed at the receiver's own capacity (the EQDS premise: the
  receiver knows its downlink).

Grant writes ride each channel's isolated probe path, so credits never
queue behind striped data chunks or control messages.
"""

from __future__ import annotations

import threading
import time
from typing import List

from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")


class PullPacer:
    """Receiver-side credit scheduler over any number of inbound channels.

    ``rate_bytes_per_sec`` is the aggregate grant rate (the receiver's
    downlink budget); each tick mints ``rate * dt`` bytes of credit and
    splits them equally across attached channels (fair quanta). ``quantum``
    bounds per-tick growth so a long scheduler stall cannot mint one huge
    burst (EQDS's bounded credit backlog).
    """

    def __init__(
        self,
        rate_bytes_per_sec: float,
        tick_s: float = 0.002,
        quantum: int = 1 << 20,
    ):
        self.rate = float(rate_bytes_per_sec)
        self.tick_s = tick_s
        self.quantum = int(quantum)
        self._chans: List[object] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._residual = 0.0  # fractional bytes carried between ticks

    def attach(self, chan) -> None:
        """Start granting to this channel (its peer should be in pull mode)."""
        with self._lock:
            if chan not in self._chans:
                self._chans.append(chan)

    def detach(self, chan) -> None:
        with self._lock:
            if chan in self._chans:
                self._chans.remove(chan)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self, flush_bytes: int = 0) -> None:
        """Stop granting. ``flush_bytes`` > 0 hands every attached channel a
        final allowance so an in-flight sender can finish rather than stall
        at the exact moment the pacer goes away."""
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None
        if flush_bytes:
            with self._lock:
                chans = list(self._chans)
            for c in chans:
                try:
                    c.grant_credit(flush_bytes)
                except Exception:
                    pass

    def _loop(self) -> None:
        last = time.monotonic()
        while not self._stop.wait(self.tick_s):
            now = time.monotonic()
            dt = now - last
            last = now
            with self._lock:
                chans = list(self._chans)
            if not chans:
                continue
            minted = min(self.rate * dt + self._residual,
                         float(self.quantum * len(chans)))
            share = int(minted // len(chans))
            self._residual = minted - share * len(chans)
            if share <= 0:
                continue
            for c in chans:
                try:
                    c.grant_credit(share)
                except Exception:
                    # a torn-down channel just stops receiving grants; the
                    # pacer must outlive individual flows
                    with self._lock:
                        if c in self._chans:
                            self._chans.remove(c)
