"""NIXL-style tensor-transfer API — the surface Ray-based RL frameworks use.

The reference exposes this through nanobind (p2p/engine_api.cc:143
``NB_MODULE(p2p)``: ``register_memory`` over tensor lists, descriptor
serialize/deserialize, ``get_metadata``/``add_remote_endpoint``,
``transfer(conn_id, op, local_descs, remote_descs)`` — exercised by
p2p/tests/test_ray_api.py from Ray actors doing weight transfer). This
module is that veneer over :class:`uccl_tpu.p2p.Endpoint`:

* arrays are host numpy (TPU arrays reach it via staging, the framework's
  standing analog of the reference's GPU registration),
* a descriptor carries the window token (``fifo``) the engine's one-sided
  ops need — the role of the reference's rkeys: possession of a serialized
  descriptor is the permission to read/write that byte range,
* metadata is the dialable (ip, port) blob exchanged out-of-band (a Ray
  object store, the repo's StoreClient, a pipe — anything).

Works the same inside Ray actors or plain processes; see
examples/ray_weight_transfer.py.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence, Tuple

import numpy as np

from uccl_tpu.p2p.endpoint import Endpoint


class XferEndpoint:
    """Endpoint wrapper speaking the reference's tensor/descriptor API
    (p2p/engine_api.cc: register_memory:?, transfer:448, serialize:420)."""

    def __init__(self, ep: Optional[Endpoint] = None, *, n_engines: int = 2):
        from uccl_tpu.p2p.mr_cache import MrCache

        self.ep = ep if ep is not None else Endpoint(n_engines=n_engines)
        # interval-containment registration cache (reference:
        # test_register_memory_cache.py): repeat/subregion registrations
        # reuse the base MR behind fresh handles, refcounted
        self.mr_cache = MrCache(self.ep)

    # -- registration + descriptors ------------------------------------
    def register_memory(self, arrays: Sequence[np.ndarray]) -> List[dict]:
        """Register each array and mint transfer descriptors.

        Descriptor fields mirror the reference's (addr/size + key material):
        ``fifo`` is the engine's advertised-window token — the rkey analog —
        so a peer holding the descriptor can one-sided read/write exactly
        this byte range and nothing else. Arrays must be C-contiguous: a
        silent ascontiguousarray copy here would register the COPY, and
        peer writes would never reach the caller's array (live model
        weights, in the Ray pattern). The endpoint's registry keeps each
        registered array alive."""
        # Validate the WHOLE batch first: a failure after some registrations
        # already happened would discard the descs list and leak handles the
        # caller can never release.
        for arr in arrays:
            if not isinstance(arr, np.ndarray):
                raise TypeError("register_memory takes host numpy arrays "
                                "(stage device arrays first)")
            if not arr.flags["C_CONTIGUOUS"]:
                raise ValueError(
                    "register_memory needs C-contiguous arrays (a view/"
                    "transpose would silently register a copy the peer "
                    "writes into instead of your array)"
                )
            if arr.nbytes == 0:
                raise ValueError("register_memory: zero-size array")
        descs = []
        try:
            for arr in arrays:
                hid, mr, off = self.mr_cache.register(arr)
                fifo = self.ep.advertise(mr, off, arr.nbytes)
                descs.append({
                    "addr": arr.ctypes.data,
                    "size": int(arr.nbytes),
                    # the shared key material (reference lkeys/rkeys
                    # analog): cache hits repeat the base mr_id at an offset
                    "mr_id": int(mr),
                    # the per-call API handle deregister_memory() takes
                    "handle": int(hid),
                    "fifo": fifo.hex(),
                })
        except Exception:
            for d in descs:  # unwind the partial batch
                self.mr_cache.deregister(d["handle"])
            raise
        return descs

    def deregister_memory(self, descs: List[dict]) -> None:
        """Release registrations by descriptor (reference
        deregister_memory): the underlying base MR is freed only when its
        last handle is gone. Drains the WHOLE batch even when one handle is
        bad, then reports the failures — stopping early would leave the
        tail pinned forever."""
        bad = []
        for d in descs:
            try:
                self.mr_cache.deregister(d["handle"])
            except KeyError:
                bad.append(d.get("handle"))
        if bad:
            raise KeyError(f"unknown registration handle(s): {bad}")

    @staticmethod
    def get_serialized_descs(descs: List[dict]) -> bytes:
        return json.dumps(descs).encode()

    @staticmethod
    def deserialize_descs(blob: bytes) -> List[dict]:
        descs = json.loads(blob.decode())
        if not isinstance(descs, list):
            raise ValueError("descriptor blob must decode to a list")
        return descs

    # -- out-of-band endpoint exchange ---------------------------------
    def get_metadata(self) -> bytes:
        """Dialable endpoint blob (reference get_metadata, p2p/engine.h:289):
        ship it to the peer over any OOB channel. Address preference: the
        interface the endpoint is actually BOUND to (listen_ip /
        UCCL_TPU_LISTEN_IP — on a multi-homed host the hostname may resolve
        to a NIC nothing is listening on), then UCCL_TPU_HOST_IP, then the
        hostname's address, then loopback."""
        import os
        import socket

        host = getattr(self.ep, "listen_ip", None)
        if host in ("0.0.0.0", "::"):  # wildcard binds are not dialable
            host = None
        if not host:
            host = os.environ.get("UCCL_TPU_HOST_IP")
        if not host:
            try:
                resolved = socket.gethostbyname(socket.gethostname())
                if resolved and not resolved.startswith("127."):
                    host = resolved
            except OSError:
                pass
        if not host:
            host = "127.0.0.1"
        return json.dumps({"ip": host, "port": self.ep.port}).encode()

    def add_remote_endpoint(self, metadata: bytes) -> Tuple[bool, int]:
        """Connect to a peer's metadata blob (reference add_remote_endpoint,
        p2p/engine.h:269). Returns (ok, conn_id)."""
        try:
            md = json.loads(metadata.decode())
            cid = self.ep.connect(md["ip"], int(md["port"]))
            return True, cid
        except Exception:
            return False, -1

    def accept(self, timeout_ms: int = 30000) -> int:
        return self.ep.accept(timeout_ms=timeout_ms)

    # -- transfers -----------------------------------------------------
    def transfer(self, conn_id: int, op: str, local: Sequence[np.ndarray],
                 remote_descs: List[dict]) -> List[int]:
        """Issue one-sided transfers pairing local arrays with remote
        descriptors (reference transfer over XferDescList,
        engine_api.cc:448). op: "WRITE" pushes local -> remote window;
        "READ" pulls remote window -> local. Returns per-pair transfer ids
        for :meth:`poll`/:meth:`wait`."""
        if op not in ("WRITE", "READ"):
            raise ValueError(f"op must be WRITE or READ, got {op!r}")
        if len(local) != len(remote_descs):
            raise ValueError(
                f"{len(local)} local arrays vs {len(remote_descs)} remote "
                "descriptors"
            )
        arrs, fifos = [], []
        for arr, desc in zip(local, remote_descs):
            arr = np.ascontiguousarray(arr) if op == "WRITE" else arr
            if arr.nbytes > desc["size"]:
                raise ValueError(
                    f"local {arr.nbytes}B exceeds remote window "
                    f"{desc['size']}B"
                )
            if op == "READ" and (
                not arr.flags["C_CONTIGUOUS"] or not arr.flags["WRITEABLE"]
            ):
                raise ValueError("READ needs a writable contiguous dst")
            arrs.append(arr)
            fifos.append(bytes.fromhex(desc["fifo"]))
        # vectorized batch (one C call, one engine wake — the XferDescList
        # semantics of engine_api.cc:448)
        if op == "WRITE":
            return self.ep.writev_async(conn_id, arrs, fifos)
        return self.ep.readv_async(conn_id, arrs, fifos)

    def poll(self, xid: int) -> Optional[bool]:
        return self.ep.poll_async(xid)

    def wait(self, xids, timeout_ms: int = 30000) -> bool:
        """Wait on every id, DRAINING all of them even after a failure —
        abandoning the tail would leak keepalives and let callers reuse
        buffers a proxy thread is still reading (Endpoint._wait_all's
        pattern)."""
        if isinstance(xids, int):
            xids = [xids]
        ok = True
        for x in xids:
            ok = self.ep.wait(x, timeout_ms=timeout_ms) and ok
        return ok

    def send_notif(self, conn_id: int, payload: bytes) -> None:
        self.ep.send_notif(conn_id, payload)

    def get_notifs(self):
        return self.ep.get_notifs()

    def close(self) -> None:
        self.ep.close()
