"""Lossless float compression for the DCN wire: exponent planes + native rANS.

The honest analog of the reference's DietGPU integration
(p2p/rdma/compression.h:46; thirdparty/dietgpu): DietGPU is a *lossless* ANS
float codec that splits each float into an exponent part (low entropy in real
tensors — neighboring weights share scale) and a sign+mantissa part
(near-random), entropy-coding only what compresses. For RL weight transfer —
a headline reference use case (README.md:18) — lossy fp8 is not a substitute,
so this codec rides next to :mod:`uccl_tpu.p2p.compress`'s fp8 path under
``compress="lossless"``.

Scheme (host-side; the DCN wire is host-owned on TPU pods):

1. **Rotate the sign bit to the LSB** (``v' = rotl(v, 1)``). For every IEEE
   width this lands the full exponent in the TOP byte with no sign pollution
   — the sign is ~1 random bit and would otherwise double the top plane's
   alphabet (measured: bf16 plane ratio 2.6x with sign vs 3.14x without).
2. **Byte-plane split** of the rotated values (transpose of the
   [elems, itemsize] uint8 view).
3. **Per-plane entropy coding** with the native order-0 rANS coder
   (native/src/float_codec.cc, within ~0.2% of order-0 entropy; encoders
   without the native runtime fall back to zlib, and a pure-Python rANS
   decoder keeps rANS blobs readable there too), keeping the coded form
   only when it actually shrank — mantissa planes of trained weights are
   incompressible and ship raw, exactly DietGPU's split-and-skip strategy.

Measured on weight-like bf16 (σ=0.02): ~1.52x, the order-0 information
bound for that distribution (sign+7 mantissa bits are irreducible; the
8-bit exponent plane carries ~2.5 bits). Low-entropy tensors (norm gains,
biases, embeddings, sparse grads) compress far harder.

Blobs are self-describing and tagged with a distinct magic, so
:func:`uccl_tpu.p2p.compress.decode_any` can route fp8 and lossless blobs
off the same wire.
"""

from __future__ import annotations

import ctypes
import struct
import zlib
from typing import Optional

import ml_dtypes
import numpy as np

from uccl_tpu.utils.config import param
from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")

_use_native = param(
    "lossless_native", 1,
    help="use the native rANS coder for lossless planes (0 = zlib only)",
)

MAGIC = 0x55434C5A  # "UCLZ"
_HDR = struct.Struct("<IBBBBQ")  # magic, ver, dtype, ndim, itemsize, elems

_FLOATS = {
    np.dtype(np.float32),
    np.dtype(ml_dtypes.bfloat16),
    np.dtype(np.float16),
    np.dtype(np.float64),
}
_DTYPES = {
    0: np.dtype(np.float32),
    1: np.dtype(ml_dtypes.bfloat16),
    2: np.dtype(np.float16),
    3: np.dtype(np.float64),
    4: np.dtype(np.int32),
    5: np.dtype(np.int8),
    6: np.dtype(np.uint8),
    7: np.dtype(np.int64),
}
_CODES = {v: k for k, v in _DTYPES.items()}
_UINT = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}

# plane coding tags
_RAW, _RANS, _ZLIB = 0, 1, 2

_codec_lib = None


def _native():
    """The rANS coder from the native runtime, or None (zlib fallback)."""
    global _codec_lib
    if not int(_use_native.get()):
        return None
    if _codec_lib is None:
        try:
            from uccl_tpu.p2p.endpoint import _build_if_needed

            lib = ctypes.CDLL(_build_if_needed())
            lib.ucclt_codec_encode.restype = ctypes.c_int64
            lib.ucclt_codec_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int64,
            ]
            lib.ucclt_codec_decode.restype = ctypes.c_int64
            lib.ucclt_codec_decode.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int64,
            ]
            _codec_lib = lib
        except Exception as e:  # no toolchain: stay pure-python
            _log.info("native codec unavailable (%s); zlib fallback", e)
            _codec_lib = False
    return _codec_lib or None


def compressible(arr: np.ndarray) -> bool:
    return arr.dtype in _CODES


def _rotl1(flat: np.ndarray) -> np.ndarray:
    """Rotate each element left by one bit (sign -> LSB)."""
    u = _UINT[flat.dtype.itemsize]
    bits = flat.dtype.itemsize * 8
    v = flat.view(u)
    return ((v << u(1)) | (v >> u(bits - 1))).astype(u)


def _rotr1(v: np.ndarray, itemsize: int) -> np.ndarray:
    u = _UINT[itemsize]
    bits = itemsize * 8
    return ((v >> u(1)) | (v << u(bits - 1))).astype(u)


def _encode_plane(plane: bytes) -> tuple[int, bytes]:
    n = len(plane)
    buf = np.frombuffer(plane, np.uint8)
    if n >= 64:
        # order-0 entropy estimate first: mantissa planes are ~8 bits/byte
        # and coding them would waste a full pass to learn they ship raw
        # (DietGPU's split strategy decides this statically per float part)
        counts = np.bincount(buf, minlength=256)
        p = counts[counts > 0] / n
        est = n * float(-(p * np.log2(p)).sum()) / 8.0 + 522
        if est >= n * 0.98:
            return _RAW, plane
    lib = _native()
    if lib is not None and n >= 64:
        out = np.empty(n, np.uint8)  # beyond raw size = not worth it
        m = lib.ucclt_codec_encode(
            buf.ctypes.data_as(ctypes.c_void_p), n,
            out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
        )
        if 0 < m < n:
            return _RANS, out[:m].tobytes()
    coded = zlib.compress(plane, 1)
    if len(coded) < n:
        return _ZLIB, coded
    return _RAW, plane


def _rans_decode_py(data: bytes, n: int) -> bytes:
    """Pure-Python decode of the native rANS format (float_codec.cc:10-17).

    The sender's toolchain decides the wire encoding, so a receiver without
    the native runtime must still be able to decode rANS planes. Sequential
    by construction (single rANS state) — ~1 MB/s — but a fallback only;
    hosts with the native codec never take this path."""
    header = 1 + 8 + 256 * 2
    if len(data) < header + 4 or data[0] != 1:
        raise ValueError("corrupt rANS plane (header)")
    (n64,) = struct.unpack_from("<Q", data, 1)
    if n64 != n:
        raise ValueError("corrupt rANS plane (length)")
    freq = np.frombuffer(data, np.uint16, 256, 9).astype(np.uint32)
    if int(freq.sum()) != 1 << 12:
        raise ValueError("corrupt rANS plane (freq table)")
    cum = np.concatenate([[0], np.cumsum(freq)[:-1]]).astype(np.uint32)
    slot2sym = np.repeat(
        np.arange(256, dtype=np.int64), freq.astype(np.int64)
    ).tolist()
    freq_l, cum_l = freq.tolist(), cum.tolist()
    p = header
    x = int.from_bytes(data[p:p + 4], "little")
    p += 4
    out = bytearray(n)
    lo, end = 1 << 23, len(data)
    for i in range(n):
        slot = x & 0xFFF
        s = slot2sym[slot]
        out[i] = s
        x = freq_l[s] * (x >> 12) + slot - cum_l[s]
        while x < lo:
            if p >= end:
                raise ValueError("corrupt rANS plane (stream underrun)")
            x = (x << 8) | data[p]
            p += 1
    return bytes(out)


def _decode_plane(tag: int, data: bytes, n: int) -> bytes:
    if tag == _RAW:
        return data
    if tag == _ZLIB:
        return zlib.decompress(data)
    if tag == _RANS:
        lib = _native()
        if lib is None:
            return _rans_decode_py(data, n)
        src = np.frombuffer(data, np.uint8)
        out = np.empty(n, np.uint8)
        r = lib.ucclt_codec_decode(
            src.ctypes.data_as(ctypes.c_void_p), len(data),
            out.ctypes.data_as(ctypes.c_void_p), n,
        )
        if r != n:
            raise ValueError("corrupt rANS plane")
        return out.tobytes()
    raise ValueError(f"unknown plane tag {tag}")


def encode_lossless(arr: np.ndarray) -> np.ndarray:
    """Encode an array into a self-describing uint8 blob, bit-exactly."""
    if arr.dtype not in _CODES:
        raise TypeError(f"cannot lossless-compress dtype {arr.dtype}")
    if arr.ndim > 255:
        raise ValueError("too many dimensions")
    itemsize = arr.dtype.itemsize
    flat = np.ascontiguousarray(arr).reshape(-1)
    elems = flat.size
    if itemsize == 1:
        planes = [flat.view(np.uint8)]
    else:
        v = _rotl1(flat) if arr.dtype in _FLOATS else flat.view(
            _UINT[itemsize]
        )
        raw = v.view(np.uint8).reshape(elems, itemsize)
        planes = [np.ascontiguousarray(raw[:, b]) for b in range(itemsize)]
    parts, meta = [], []
    for p in planes:
        tag, data = _encode_plane(p.tobytes())
        meta.append((tag, len(data)))
        parts.append(data)
    hdr = _HDR.pack(MAGIC, 1, _CODES[arr.dtype], arr.ndim, itemsize, elems)
    shape = np.asarray(arr.shape, np.uint64).tobytes()
    metab = b"".join(struct.pack("<BQ", t, n) for t, n in meta)
    return np.frombuffer(hdr + shape + metab + b"".join(parts), np.uint8).copy()


def decode_lossless(blob) -> np.ndarray:
    """Exact inverse of :func:`encode_lossless` (bit-identical round trip)."""
    buf = bytes(memoryview(np.ascontiguousarray(np.asarray(blob, np.uint8))))
    if len(buf) < _HDR.size:
        raise ValueError("blob shorter than header")
    magic, ver, dcode, ndim, itemsize, elems = _HDR.unpack_from(buf, 0)
    if magic != MAGIC or ver != 1 or dcode not in _DTYPES:
        raise ValueError("not a lossless wire blob")
    off = _HDR.size
    shape = tuple(np.frombuffer(buf, np.uint64, ndim, off).astype(int))
    off += 8 * ndim
    meta = []
    for _ in range(itemsize):
        t, n = struct.unpack_from("<BQ", buf, off)
        meta.append((t, n))
        off += 9
    raw = np.empty((elems, itemsize), np.uint8)
    for b, (tag, n) in enumerate(meta):
        plane = _decode_plane(tag, buf[off:off + n], elems)
        off += n
        raw[:, b] = np.frombuffer(plane, np.uint8, elems)
    dtype = _DTYPES[dcode]
    if itemsize == 1:
        return raw.reshape(-1).view(dtype)[:elems].reshape(shape)
    v = raw.reshape(-1).view(_UINT[itemsize])[:elems]
    if dtype in _FLOATS:
        v = _rotr1(v, itemsize)
    return v.view(dtype).reshape(shape)


def ratio(arr: np.ndarray) -> float:
    """Measured compression ratio on one array (for benchmarks/tests)."""
    return arr.nbytes / float(encode_lossless(arr).nbytes)
