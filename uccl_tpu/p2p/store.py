"""Out-of-band rendezvous store over the P2P engine.

The reference bootstraps every pillar over plain TCP metadata exchange
(include/util/net.h OOB handshakes; ukernel's oob exchangers,
experimental/ukernel/src/transport/oob/; torch Store in the EP benches). This
is the TPU framework's equivalent: a tiny key-value store served by rank 0's
Endpoint, used to exchange FifoItems, mesh coordinates, and addresses before
any data-plane traffic. Protocol: length-prefixed msgpack-free frames —
``SET key value`` / ``GET key`` / ``WAIT key timeout`` over the engine's
two-sided send/recv.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from uccl_tpu.p2p.endpoint import Endpoint
from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")


def _pack(*parts: bytes) -> bytes:
    out = []
    for p in parts:
        out.append(len(p).to_bytes(4, "big"))
        out.append(p)
    return b"".join(out)


def _unpack(buf: bytes):
    parts = []
    i = 0
    while i < len(buf):
        n = int.from_bytes(buf[i : i + 4], "big")
        i += 4
        parts.append(buf[i : i + n])
        i += n
    return parts


class StoreServer:
    """Rank-0 side: serves SET/GET over accepted connections."""

    def __init__(self, port: int = 0):
        self._ep = Endpoint(port)
        self._kv: Dict[bytes, bytes] = {}
        self._cv = threading.Condition()
        self._stop = False
        self._threads = []
        self._acceptor = threading.Thread(target=self._accept_loop, daemon=True)
        self._acceptor.start()

    @property
    def port(self) -> int:
        return self._ep.port

    def close(self):
        # Signal and join worker threads BEFORE destroying the native
        # endpoint: they block inside its accept/recv calls.
        self._stop = True
        self._acceptor.join(timeout=5)
        for t in self._threads:
            t.join(timeout=5)
        self._ep.close()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn = self._ep.accept(timeout_ms=500)
            except TimeoutError:
                continue
            except Exception:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: int):
        while not self._stop:
            try:
                msg = self._ep.recv(conn, timeout_ms=1000)
            except TimeoutError:
                continue
            except Exception:
                return
            try:
                parts = _unpack(msg)
                op = parts[0]
                if op == b"SET":
                    with self._cv:
                        self._kv[parts[1]] = parts[2]
                        self._cv.notify_all()
                    self._ep.send(conn, _pack(b"OK"))
                elif op == b"GET":
                    with self._cv:
                        val = self._kv.get(parts[1])
                    if val is None:
                        self._ep.send(conn, _pack(b"MISS"))
                    else:
                        self._ep.send(conn, _pack(b"OK", val))
                elif op == b"WAIT":
                    timeout_s = float(parts[2].decode())
                    deadline = time.monotonic() + timeout_s
                    with self._cv:
                        while parts[1] not in self._kv:
                            left = deadline - time.monotonic()
                            if left <= 0 or self._stop:
                                break
                            self._cv.wait(timeout=min(left, 0.5))
                        val = self._kv.get(parts[1])
                    if val is None:
                        self._ep.send(conn, _pack(b"MISS"))
                    else:
                        self._ep.send(conn, _pack(b"OK", val))
                else:
                    self._ep.send(conn, _pack(b"ERR", b"bad op"))
            except Exception as e:  # keep serving other clients
                _log.warning("store serve error: %r", e)
                return


class StoreClient:
    """Any rank: set/get/wait against the rank-0 store.

    Connect retries for ``connect_timeout_s`` — at bootstrap the server rank
    may come up a beat later than the workers (the reference's bootstrap
    handshakes retry the same way).
    """

    def __init__(self, ip: str, port: int, connect_timeout_s: float = 10.0):
        self._ep = Endpoint()
        deadline = time.monotonic() + connect_timeout_s
        while True:
            try:
                self._conn = self._ep.connect(ip, port)
                break
            except ConnectionError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.1)
        self._lock = threading.Lock()

    def close(self):
        self._ep.close()

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._ep.send(self._conn, _pack(b"SET", key.encode(), value))
            resp = _unpack(self._ep.recv(self._conn))
        if resp[0] != b"OK":
            raise IOError(f"store set({key}) failed: {resp}")

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            self._ep.send(self._conn, _pack(b"GET", key.encode()))
            resp = _unpack(self._ep.recv(self._conn))
        return resp[1] if resp[0] == b"OK" else None

    def wait(self, key: str, timeout_s: float = 30.0) -> bytes:
        with self._lock:
            self._ep.send(
                self._conn,
                _pack(b"WAIT", key.encode(), str(timeout_s).encode()),
            )
            resp = _unpack(
                self._ep.recv(self._conn, timeout_ms=int(timeout_s * 1000) + 2000)
            )
        if resp[0] != b"OK":
            raise TimeoutError(f"store wait({key}) timed out")
        return resp[1]
