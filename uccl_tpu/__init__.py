"""uccl_tpu — a TPU-native communication + parallelism framework.

A ground-up rebuild of the capabilities of uccl-project/uccl (see SURVEY.md) designed
for TPU hardware: JAX/XLA/Pallas for the device compute path, a C++ host runtime for
the DCN transfer engine, and `jax.sharding` meshes for multi-chip scale.

Three pillars (mirroring the reference's product surface, reference README.md:18-66):

1. ``uccl_tpu.collective`` — NCCL-shaped collectives API lowered to XLA collectives
   over the ICI mesh (the analog of the reference's ``collective/`` NCCL plugin).
2. ``uccl_tpu.p2p``        — NIXL-style transfer engine for KV-cache / weight movement
   over DCN (the analog of ``p2p/engine.{h,cc}``), C++ host runtime underneath.
3. ``uccl_tpu.ep``         — DeepEP-compatible MoE expert-parallel dispatch/combine
   (the analog of ``ep/``), as sharded ragged all-to-all on the mesh.

Plus ``uccl_tpu.parallel`` (mesh management, ring attention, Ulysses, pipeline — the
sequence/context-parallel layer SURVEY.md §5 requires), ``uccl_tpu.ops`` (Pallas
kernels), and ``uccl_tpu.models`` (flagship model families exercising every axis).
"""

# Version-bridge the jax APIs the codebase targets (jax.shard_map,
# lax.axis_size, ...) at package import, so EVERY subpackage — including
# ones that never import the shim themselves (ops.attention traces
# lax.axis_size inside shard_map) — sees them on legacy jax 0.4.x
# containers. No-op on modern jax.
from uccl_tpu.utils import jaxcompat as _jaxcompat  # noqa: F401
from uccl_tpu.version import __version__

__all__ = ["__version__"]
