"""Compatibility façades for users arriving from the reference's ecosystems."""

from uccl_tpu.compat import dist

__all__ = ["dist"]
