"""torch.distributed-shaped process-group API over the DCN engine.

The reference plugs under ``torch.distributed`` (NCCL plugin) so its users
write ``dist.init_process_group / all_reduce / all_gather / barrier``. This
module keeps those verbs for host arrays across processes — backed by the
rendezvous store + DcnGroup ring — so reference-style launch scripts port
with a changed import. Device-side (on-mesh) collectives live in
``uccl_tpu.collective.Communicator``; this is the host/process-group face.

Ops mutate in place like torch.distributed: ``all_reduce(x)`` leaves the
global sum in ``x``.

Device arrays are first-class: passing a ``jax.Array`` stages it to host,
runs the DCN collective, and RETURNS a new device array placed with the
input's sharding (jax arrays are immutable, so the torch in-place contract
becomes a functional one — ``x = dist.all_reduce(x)``). numpy inputs keep
the exact torch.distributed in-place semantics.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from uccl_tpu.collective.hierarchical import DcnGroup
from uccl_tpu.parallel.distributed import Session
from uccl_tpu.utils.logging import get_logger

_log = get_logger("COLL")

_group: Optional[DcnGroup] = None
_session: Optional[Session] = None


def init_process_group(
    rank: int,
    world_size: int,
    *,
    master_addr: str = "127.0.0.1",
    master_port: int = 29500,
    n_paths: int = 2,
) -> None:
    """Bring up the default process group (rank 0 hosts the store)."""
    global _group, _session
    if _group is not None:
        raise RuntimeError("process group already initialized")
    from uccl_tpu.parallel.distributed import initialize

    try:
        # Reuse the session bootstrap (rank 0 serves the store at master_port
        # and connects to itself via loopback; failures close the server).
        _session = initialize(
            f"{master_addr}:{master_port}",
            rank,
            world_size,
            store_port=master_port,
            init_jax=False,
        )
        _group = DcnGroup(_session, n_paths=n_paths, tag="default_pg")
    except Exception:
        destroy_process_group()  # release partial state so retry can succeed
        raise
    _log.info("process group up: rank %d/%d", rank, world_size)


def is_initialized() -> bool:
    return _group is not None


def _require() -> DcnGroup:
    if _group is None:
        raise RuntimeError("call init_process_group first")
    return _group


def get_rank() -> int:
    """This rank's POSITION in the active group (torch.distributed invariant
    rank < world_size holds across elastic heals; == the global rank until a
    lower-numbered rank dies). Collective row indices use the same positions."""
    return _require().pos


def get_world_size() -> int:
    """Size of the ACTIVE group (shrinks after an elastic heal)."""
    return _require().active_world


def _as_jax(x):
    """(is_jax, host_view): stage a jax.Array to host, pass numpy through."""
    try:
        import jax

        if isinstance(x, jax.Array):
            return True, np.asarray(x)
    except ImportError:  # pragma: no cover - jax is a hard dep in practice
        pass
    return False, x


def _placed_like(host: np.ndarray, ref):
    """Put a host result back on ref's device/sharding."""
    import jax

    return jax.device_put(host, ref.sharding)


def all_reduce(x):
    """Sum across the group. numpy: in place (torch.distributed semantics),
    returns None. jax.Array: returns the reduced array placed with x's
    sharding (jax arrays are immutable)."""
    g = _require()
    is_jax, host = _as_jax(x)
    if is_jax:
        return _placed_like(g.all_reduce(host), x)
    x[...] = g.all_reduce(x)
    return None


def all_gather(out_list: Optional[List[np.ndarray]], x):
    """Fill out_list[i] with the i-th ACTIVE rank's x (== rank i before any
    heal; after a heal, positions close the gap and the list shrinks).
    jax.Array input: pass ``out_list=None`` and receive the gathered list of
    device arrays as the return value."""
    g = _require()
    is_jax, host = _as_jax(x)
    if is_jax and out_list is not None:
        # jax arrays are immutable — filling out_list is impossible, and
        # silently ignoring it would hand torch-ported callers stale buffers
        raise ValueError(
            "all_gather with a jax.Array input takes out_list=None and "
            "returns the gathered list"
        )
    if not is_jax and (out_list is None or len(out_list) != g.active_world):
        # validate BEFORE participating: a caller error must fail fast, not
        # after this rank already joined the collective (which would leave
        # the group skewed for the other ranks)
        raise ValueError(
            f"out_list has {0 if out_list is None else len(out_list)} "
            f"entries; active world size is {g.active_world}"
        )
    gathered = g.all_gather(host)
    if is_jax:
        return [_placed_like(gathered[i], x) for i in range(g.active_world)]
    for i in range(g.active_world):
        out_list[i][...] = gathered[i]
    return None


def all_to_all(out: Optional[np.ndarray], x):
    """out[i] receives the i-th active rank's row for us; x[j] goes to the
    j-th active rank. jax.Array input: pass ``out=None`` and take the result
    as the return value."""
    g = _require()
    is_jax, host = _as_jax(x)
    if is_jax:
        if out is not None:
            raise ValueError(
                "all_to_all with a jax.Array input takes out=None and "
                "returns the result"
            )
        return _placed_like(g.all_to_all(host), x)
    out[...] = g.all_to_all(x)
    return None


def broadcast(x, src: int = 0):
    """Every rank ends with src's x (binomial tree over the DCN full mesh —
    log(world) rounds, no gather blow-up). numpy: in place; jax.Array:
    returned."""
    g = _require()
    is_jax, host = _as_jax(x)
    if is_jax:
        return _placed_like(g.broadcast(host, root=src), x)
    x[...] = g.broadcast(x, root=src)
    return None


def barrier() -> None:
    _require().barrier()


def destroy_process_group() -> None:
    global _group, _session
    if _group is not None:
        _group.close()
        _group = None
    if _session is not None:
        _session.close()  # closes store client and (on rank 0) the server
        _session = None
