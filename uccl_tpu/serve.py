"""Serving entry: ``python -m uccl_tpu.serve`` — the inference face of the
trainer's checkpoints.

Train → checkpoint → serve, end to end: `python -m uccl_tpu.train
--ckpt-dir d --ckpt-every k` writes orbax state whose parameter tree is
layout-identical to the serving model's, so this entry restores the params
subtree and generates through :class:`uccl_tpu.models.moe_inference.
MoEServer` — EP-sharded KV-cache prefill (sorted throughput path) +
decode (packed low-latency path, the DeepEP LL regime). The reference's
consumers reach this shape through vLLM + its transfer/EP plugins
(ep/bench/vllm/disagg_proxy.py); here it is one command:

    python -m uccl_tpu.serve --devices 8 --ckpt-dir /tmp/run1 \
        --batch 8 --prompt-len 8 --new-tokens 16

Without --ckpt-dir, params initialize from --seed (smoke/benchmark mode).
Prompts are deterministic synthetic token ids (no tokenizer in scope).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

if __package__ in (None, ""):
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _load_params(ckpt_dir, step):
    """Restore the params subtree of a trainer checkpoint as HOST arrays.

    Restoring to numpy (restore_args built from the checkpoint's own
    metadata tree) decouples serving from the training topology: a
    checkpoint saved on 8 devices loads on any serving host — a plain
    restore would try to re-apply the save-time shardings and die when
    the device counts differ."""
    import numpy as np
    import orbax.checkpoint as ocp

    from uccl_tpu.train import _latest_step

    if step is None:
        step = _latest_step(ckpt_dir)
        if step is None:
            raise SystemExit(f"no step_N checkpoints in {ckpt_dir}")
    path = os.path.join(ckpt_dir, f"step_{step}")
    ckpt = ocp.PyTreeCheckpointer()
    meta = ckpt.metadata(path).item_metadata  # dict-shaped pytree metadata

    # walk the metadata tree by mapping structure (its leaves are metadata
    # objects that jax.tree would descend into)
    def to_args(node, as_args):
        if hasattr(node, "keys"):
            return {k: to_args(node[k], as_args) for k in node.keys()}
        if isinstance(node, (list, tuple)):
            return type(node)(to_args(v, as_args) for v in node)
        if as_args:
            return ocp.RestoreArgs(restore_type=np.ndarray)
        return 0  # placeholder leaf for the item template

    if "params" not in (meta.keys() if hasattr(meta, "keys") else ()):
        raise SystemExit(f"{path} is not a trainer checkpoint (no params)")
    # Restore ONLY the params subtree (transforms-based partial restore):
    # the optimizer moments are ~2x the param bytes and serving never
    # touches them.
    tree = ckpt.restore(
        path,
        item={"params": to_args(meta["params"], as_args=False)},
        restore_args={"params": to_args(meta["params"], as_args=True)},
        transforms={},
    )
    return tree["params"], step


def _check_sizes(params, cfg):
    """Friendly mismatch errors for EVERY size flag, before any placement:
    embed pins (vocab, dim), we_gate pins (layers, experts, ffn), wq pins
    heads*head_dim."""
    import numpy as np

    if "we_gate" not in params.get("blocks", {}):
        raise SystemExit(
            "checkpoint parameter tree has no expert weights — this looks "
            "like a dense-family checkpoint; serve routes families via the "
            "checkpoint dir's config.json (re-save with the current trainer "
            "or restore it manually)"
        )
    checks = [
        ("embed", (cfg.vocab, cfg.dim), "--vocab/--dim"),
        ("blocks.we_gate",
         (cfg.n_layers, cfg.moe_experts, cfg.dim, cfg.moe_ffn),
         "--layers/--experts/--dim/--ffn"),
        ("blocks.wq",
         (cfg.n_layers, cfg.dim, cfg.n_heads * cfg.head_dim),
         "--layers/--dim/--heads"),
    ]
    for name, want, flags in checks:
        leaf = params
        for part in name.split("."):
            leaf = leaf[part]
        got = tuple(np.shape(leaf))
        if got != want:
            raise SystemExit(
                f"checkpoint {name} {got} != model {want} ({flags}): "
                "pass the training run's size flags"
            )


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m uccl_tpu.serve")
    ap.add_argument("--devices", type=int, default=0,
                    help="force an N-device virtual CPU mesh (tests/dev)")
    ap.add_argument("--dp", type=int, default=0,
                    help="serving world (default: all devices)")
    ap.add_argument("--batch", type=int, default=8, help="global batch")
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--max-seq", type=int, default=0,
                    help="KV capacity (default: prompt+new)")
    ap.add_argument("--impl", default="auto", choices=["auto", "ll", "sort"],
                    help="decode-step EP path (prefill always uses sort). "
                         "'auto' follows the measurements: sort at world 1 "
                         "(wins 1.2-3.2x at every batch, PERF.md), ll on "
                         "multi-member worlds where its packed rows cut "
                         "actual wire bytes (the DeepEP LL regime)")
    ap.add_argument("--seed", type=int, default=0)
    # model size — must match the checkpoint when --ckpt-dir is given
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--ffn", type=int, default=128)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--step", type=int, default=None,
                    help="checkpoint step (default: latest)")
    args = ap.parse_args(argv)

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.models.moe_inference import (
        MoEServeConfig, MoEServer, init_params,
    )
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    # A trainer checkpoint records its model family + sizes in config.json;
    # prefer that over flags (flags that DIFFER are an error — shapes like
    # heads vs kv-heads cannot all be recovered from param shapes alone,
    # so silent flag drift would serve silently-wrong tokens).
    saved_cfg = None
    if args.ckpt_dir:
        cfg_path = os.path.join(args.ckpt_dir, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                saved_cfg = json.load(f)
            if saved_cfg.get("model") not in ("flagship", "dense"):
                raise SystemExit(
                    f"{args.ckpt_dir} holds a {saved_cfg.get('model')!r} "
                    "checkpoint; serve handles flagship (MoE) and dense"
                )
            defaults = ap.parse_args([])
            pairs = [
                ("vocab", "vocab"), ("dim", "dim"), ("layers", "layers"),
                ("heads", "heads"), ("kv_heads", "kv_heads"),
                ("ffn", "ffn"),
            ]
            if saved_cfg.get("model") == "flagship":
                pairs.append(("experts", "experts"))  # MoE-only flag
            for flag, key in pairs:
                given = getattr(args, flag)
                if given != getattr(defaults, flag) and given != saved_cfg[key]:
                    raise SystemExit(
                        f"--{flag.replace('_', '-')} {given} != checkpoint "
                        f"config {saved_cfg[key]} ({cfg_path})"
                    )
                setattr(args, flag, saved_cfg[key])
    if saved_cfg is not None and saved_cfg.get("model") == "dense":
        # Dense (Llama-family) checkpoints generate through the cached
        # single-shard KV path (models/inference.py) — no EP mesh.
        from uccl_tpu.models.dense import DenseConfig
        from uccl_tpu.models.inference import generate

        dcfg = DenseConfig(
            vocab=args.vocab, dim=args.dim, n_layers=args.layers,
            n_heads=args.heads, n_kv_heads=args.kv_heads,
            head_dim=args.dim // args.heads, ffn=args.ffn,
        )
        max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
        if args.prompt_len + args.new_tokens > max_seq:
            raise SystemExit("--prompt-len + --new-tokens exceed --max-seq")
        params, step = _load_params(args.ckpt_dir, args.step)
        params = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params)
        print(f"serving {args.ckpt_dir}/step_{step} (dense)", flush=True)
        rng = np.random.default_rng(args.seed)
        prompt = jnp.asarray(
            rng.integers(0, dcfg.vocab, (args.batch, args.prompt_len)),
            jnp.int32,
        )
        # One jitted program (prefill + decode scan), cached per shape in
        # inference.generate — the warmup call at the SAME new_tokens
        # compiles it; the timed call is a pure cache hit. (The old
        # per-token decode_j loop paid ~10 ms of dispatch per token over
        # the tunnel — the same fix as MoEServer.generate, PERF.md.)
        # host-read the warmup: the call itself is async and compile can
        # complete with the execution still queued — an unread warmup
        # leaks its execution (and, observed on the axon tunnel, a
        # compile-sized stall) into the timed window
        np.asarray(generate(params, prompt, dcfg,
                            max_new_tokens=args.new_tokens,
                            max_seq=max_seq))
        # Honest decode throughput: this timed window INCLUDES prefill, so
        # dividing by batch*new_tokens alone would flatter short windows.
        # Time a second program at 1 new token (warmed the same way) and
        # difference the windows — prefill + the fixed dispatch cost cancel
        # in the delta, leaving decode-only time for new_tokens-1 tokens.
        t_one = None
        if args.new_tokens > 1:
            np.asarray(generate(params, prompt, dcfg, max_new_tokens=1,
                                max_seq=max_seq))
            t0 = time.perf_counter()
            np.asarray(generate(params, prompt, dcfg, max_new_tokens=1,
                                max_seq=max_seq))
            t_one = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = np.asarray(generate(
            params, prompt, dcfg, max_new_tokens=args.new_tokens,
            max_seq=max_seq,
        ))
        dt = time.perf_counter() - t0
        summary = {
            "mode": "serve", "ckpt_step": step, "impl": "dense",
            "world": 1, "batch": args.batch,
            "new_tokens": args.new_tokens,
            # the raw window metric, kept under an honest name: it spans
            # prefill AND decode
            "window": "prefill+decode",
            "tokens_per_sec": round(args.batch * args.new_tokens / dt, 1),
        }
        # only report the delta metric when the differenced window is
        # positive — on prefill-dominated runs jitter can make t_one >= dt,
        # and clamping would print an absurd throughput as the honest number
        if t_one is not None and dt > t_one:
            summary["decode_tokens_per_sec"] = round(
                args.batch * (args.new_tokens - 1) / (dt - t_one), 1
            )
        print(f"first sequence: {out[0].tolist()}", flush=True)
        print(json.dumps(summary), flush=True)
        return

    cfg = MoEServeConfig(
        vocab=args.vocab, dim=args.dim, n_layers=args.layers,
        n_heads=args.heads, n_kv_heads=args.kv_heads,
        head_dim=args.dim // args.heads, moe_experts=args.experts,
        moe_ffn=args.ffn,
    )
    n = len(jax.devices())
    world = args.dp or n
    # fail the cheap flag checks in milliseconds, BEFORE any restore work
    if world > n:
        raise SystemExit(f"--dp {world} exceeds the {n} available device(s)")
    if args.batch % world:
        raise SystemExit(f"--batch {args.batch} must divide by world {world}")
    max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
    if args.prompt_len + args.new_tokens > max_seq:
        raise SystemExit(
            f"--prompt-len {args.prompt_len} + --new-tokens "
            f"{args.new_tokens} exceed --max-seq {max_seq}"
        )
    # '--impl auto' follows the measurements (PERF.md round-5 decode table):
    # at world 1 the sorted path wins 1.2-3.2x at every batch — LL's packed
    # rows save WIRE bytes, which a single-member world never moves. Multi-
    # member worlds keep the DeepEP LL decode regime. Explicit --impl wins.
    impl = args.impl if args.impl != "auto" else (
        "sort" if world == 1 else "ll"
    )
    mesh = make_mesh(MeshConfig(dp=world), jax.devices()[:world])
    server = MoEServer(cfg, mesh)

    step = None
    if args.ckpt_dir:
        params, step = _load_params(args.ckpt_dir, args.step)
        _check_sizes(params, cfg)
        params = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params)
        print(f"serving {args.ckpt_dir}/step_{step}", flush=True)
    else:
        params = init_params(jax.random.PRNGKey(args.seed), cfg)
    placed = server.shard_params(params)

    b_local = args.batch // world
    rng = np.random.default_rng(args.seed)
    prompt = jnp.asarray(
        rng.integers(0, cfg.vocab, (world, b_local, args.prompt_len)),
        jnp.int32,
    )

    # Warmup compiles the prefill + decode-scan programs. It must use the
    # SAME new_tokens as the timed run: generate's decode loop is one
    # jitted lax.scan whose length is baked into the program, so a
    # 1-token warmup would compile a different scan and the timed call
    # would pay the real compile. Host-READ the result: the call is
    # async, and an unread warmup leaks its execution into the timed
    # window (see the dense branch note).
    np.asarray(server.generate(
        placed, prompt, args.new_tokens, max_seq, impl=impl
    ))
    # decode-only throughput via the 1-token delta (see the dense branch:
    # the timed window spans prefill+decode, so the delta of two windows
    # is the honest decode number)
    t_one = None
    if args.new_tokens > 1:
        np.asarray(server.generate(placed, prompt, 1, max_seq, impl=impl))
        t0 = time.perf_counter()
        np.asarray(server.generate(placed, prompt, 1, max_seq, impl=impl))
        t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = server.generate(
        placed, prompt, args.new_tokens, max_seq, impl=impl
    )
    out = np.asarray(out)  # [W, B_loc, N]
    dt = time.perf_counter() - t0
    total = args.batch * args.new_tokens
    summary = {
        "mode": "serve",
        "ckpt_step": step,
        "impl": impl,
        "world": world,
        "batch": args.batch,
        "new_tokens": args.new_tokens,
        "window": "prefill+decode",
        "tokens_per_sec": round(total / dt, 1),
    }
    # see the dense branch: report the delta metric only when the
    # differenced window is positive, never a clamped absurdity
    if t_one is not None and dt > t_one:
        summary["decode_tokens_per_sec"] = round(
            args.batch * (args.new_tokens - 1) / (dt - t_one), 1
        )
    print(f"first sequence: {out[0, 0].tolist()}", flush=True)
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
