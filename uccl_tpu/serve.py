"""Serving entry: ``python -m uccl_tpu.serve`` — the inference face of the
trainer's checkpoints.

Train → checkpoint → serve, end to end: `python -m uccl_tpu.train
--ckpt-dir d --ckpt-every k` writes orbax state whose parameter tree is
layout-identical to the serving model's, so this entry restores the params
subtree and generates through :class:`uccl_tpu.models.moe_inference.
MoEServer` — EP-sharded KV-cache prefill (sorted throughput path) +
decode (packed low-latency path, the DeepEP LL regime). The reference's
consumers reach this shape through vLLM + its transfer/EP plugins
(ep/bench/vllm/disagg_proxy.py); here it is one command:

    python -m uccl_tpu.serve --devices 8 --ckpt-dir /tmp/run1 \
        --batch 8 --prompt-len 8 --new-tokens 16

Without --ckpt-dir, params initialize from --seed (smoke/benchmark mode).
Prompts are deterministic synthetic token ids (no tokenizer in scope).

``--server`` switches from the one-shot fixed batch to the
continuous-batching engine (uccl_tpu/serving, docs/SERVING.md): a synthetic
Poisson arrival stream of mixed-length prompts flows through a FIFO
scheduler into a fixed KV slot pool, requests join and leave mid-decode,
and the summary reports TTFT/TPOT percentiles, goodput and slot occupancy.
``--check-oracle`` additionally verifies every completed request against
the one-shot ``generate`` oracle (bit-exact) and that no slot leaked — the
CI serving smoke tier:

    python -m uccl_tpu.serve --server --devices 2 --slots 2 --requests 6 \
        --prompt-len 8 --new-tokens 4 --arrival-rate 50 --check-oracle
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

if __package__ in (None, ""):
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _load_params(ckpt_dir, step):
    """Restore the params subtree of a trainer checkpoint as HOST arrays.

    Restoring to numpy (restore_args built from the checkpoint's own
    metadata tree) decouples serving from the training topology: a
    checkpoint saved on 8 devices loads on any serving host — a plain
    restore would try to re-apply the save-time shardings and die when
    the device counts differ."""
    import numpy as np
    import orbax.checkpoint as ocp

    from uccl_tpu.train import _latest_step

    if step is None:
        step = _latest_step(ckpt_dir)
        if step is None:
            raise SystemExit(f"no step_N checkpoints in {ckpt_dir}")
    path = os.path.join(ckpt_dir, f"step_{step}")
    ckpt = ocp.PyTreeCheckpointer()
    meta = ckpt.metadata(path).item_metadata  # dict-shaped pytree metadata

    # walk the metadata tree by mapping structure (its leaves are metadata
    # objects that jax.tree would descend into)
    def to_args(node, as_args):
        if hasattr(node, "keys"):
            return {k: to_args(node[k], as_args) for k in node.keys()}
        if isinstance(node, (list, tuple)):
            return type(node)(to_args(v, as_args) for v in node)
        if as_args:
            return ocp.RestoreArgs(restore_type=np.ndarray)
        return 0  # placeholder leaf for the item template

    if "params" not in (meta.keys() if hasattr(meta, "keys") else ()):
        raise SystemExit(f"{path} is not a trainer checkpoint (no params)")
    # Restore ONLY the params subtree (transforms-based partial restore):
    # the optimizer moments are ~2x the param bytes and serving never
    # touches them.
    tree = ckpt.restore(
        path,
        item={"params": to_args(meta["params"], as_args=False)},
        restore_args={"params": to_args(meta["params"], as_args=True)},
        transforms={},
    )
    return tree["params"], step


def _check_sizes(params, cfg):
    """Friendly mismatch errors for EVERY size flag, before any placement:
    embed pins (vocab, dim), we_gate pins (layers, experts, ffn), wq pins
    heads*head_dim."""
    import numpy as np

    if "we_gate" not in params.get("blocks", {}):
        raise SystemExit(
            "checkpoint parameter tree has no expert weights — this looks "
            "like a dense-family checkpoint; serve routes families via the "
            "checkpoint dir's config.json (re-save with the current trainer "
            "or restore it manually)"
        )
    checks = [
        ("embed", (cfg.vocab, cfg.dim), "--vocab/--dim"),
        ("blocks.we_gate",
         (cfg.n_layers, cfg.moe_experts, cfg.dim, cfg.moe_ffn),
         "--layers/--experts/--dim/--ffn"),
        ("blocks.wq",
         (cfg.n_layers, cfg.dim, cfg.n_heads * cfg.head_dim),
         "--layers/--dim/--heads"),
    ]
    for name, want, flags in checks:
        leaf = params
        for part in name.split("."):
            leaf = leaf[part]
        got = tuple(np.shape(leaf))
        if got != want:
            raise SystemExit(
                f"checkpoint {name} {got} != model {want} ({flags}): "
                "pass the training run's size flags"
            )


def _timed_windows(run_full, run_one, batch, new_tokens, reps):
    """Measure the one-shot serving windows ``reps`` times; returns
    (last full-window output, last full-window seconds, extra summary).

    The 1-token window IS the TTFT window (prompt → first token), and the
    per-rep delta (full − one)/(N−1) is the decode-step window — prefill
    and the fixed dispatch cost cancel in the delta (the honest-decode
    rationale below). Percentile definitions are shared with the
    continuous-batching engine (uccl_tpu/serving/metrics.py). Callers must
    have warmed BOTH programs; ``run_one`` is None when N == 1 (the full
    window then doubles as the TTFT window)."""
    from uccl_tpu import obs
    from uccl_tpu.serving.metrics import percentile, percentiles_ms

    ttft, steps, fulls = [], [], []
    out = None
    for _ in range(max(1, reps)):
        if run_one is not None:
            t0 = time.perf_counter()
            with obs.span("serve.ttft_window", track="serve"):
                run_one()
            ttft.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        with obs.span("serve.full_window", track="serve",
                      new_tokens=new_tokens):
            out = run_full()
        fulls.append(time.perf_counter() - t0)
        if run_one is not None and fulls[-1] > ttft[-1]:
            steps.append((fulls[-1] - ttft[-1]) / (new_tokens - 1))
    if run_one is None:
        ttft = list(fulls)
    extra = {"ttft_ms": percentiles_ms(ttft)}
    if steps:
        extra["decode_step_ms"] = percentiles_ms(steps)
        # the delta metric over the MEDIAN windows — only when positive,
        # never a clamped absurdity (see the window notes below)
        med_one, med_full = percentile(ttft, 50), percentile(fulls, 50)
        if med_full > med_one:
            extra["decode_tokens_per_sec"] = round(
                batch * (new_tokens - 1) / (med_full - med_one), 1
            )
    return out, fulls[-1], extra


def _serve_continuous(args, saved_cfg):
    """--server: the continuous-batching engine under Poisson arrivals.

    Mixed-length synthetic prompts arrive at --arrival-rate req/s, flow
    through the FIFO scheduler into a --slots KV slot pool, and decode in
    one masked batch; the summary line is the engine's metrics snapshot
    (TTFT/TPOT percentiles, goodput, occupancy — docs/SERVING.md).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu import obs
    from uccl_tpu.serving import (
        AdapterStore, DenseBackend, MoEBackend, Router, SamplingParams,
        ServingEngine, ServingMetrics, make_lora, materialize,
        replicate_backend,
    )
    from uccl_tpu.serving.loadgen import (
        assign_classes, drive, synth_workload, warm_engine, warm_replicas,
    )

    stack = args.stack
    if stack == "auto":
        stack = ("dense" if saved_cfg is not None
                 and saved_cfg.get("model") == "dense" else "moe")
    if args.slots < 1:
        raise SystemExit(f"--slots must be >= 1, got {args.slots}")
    if args.spec_k < 0:
        raise SystemExit(f"--spec-k must be >= 0, got {args.spec_k}")
    if args.replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")
    if not (0.0 <= args.interactive_frac <= 1.0):
        raise SystemExit("--interactive-frac must be in [0, 1]")
    if args.temperature < 0:
        raise SystemExit(f"--temperature must be >= 0, got "
                         f"{args.temperature}")
    if not (0.0 < args.top_p <= 1.0):
        raise SystemExit(f"--top-p must be in (0, 1], got {args.top_p}")
    if args.top_k < 0:
        raise SystemExit(f"--top-k must be >= 0, got {args.top_k}")
    if args.tenants < 0:
        raise SystemExit(f"--tenants must be >= 0, got {args.tenants}")
    if args.tenants and args.priority_classes:
        raise SystemExit("--tenants and --priority-classes are mutually "
                         "exclusive admission policies (per-tenant DRR "
                         "has no class ladder)")
    if args.adapter_rank < 0:
        raise SystemExit(f"--adapter-rank must be >= 0, got "
                         f"{args.adapter_rank}")
    if args.adapter_rank and not args.tenants:
        raise SystemExit("--adapter-rank needs --tenants (adapters are "
                         "per-tenant)")
    if args.step_tokens and not args.prefill_chunk:
        raise SystemExit("--step-tokens needs --prefill-chunk (the "
                         "whole-prompt path has no sub-step unit to budget)")
    if args.prefill_chunk and args.step_tokens \
            and args.step_tokens < args.prefill_chunk:
        raise SystemExit(
            f"--step-tokens {args.step_tokens} must be >= --prefill-chunk "
            f"{args.prefill_chunk}, or no request could ever be admitted"
        )
    max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
    if args.prompt_len + args.new_tokens > max_seq:
        raise SystemExit("--prompt-len + --new-tokens exceed --max-seq")

    # per-tenant LoRA adapters: one published adapter per synthetic
    # tenant; the engine fuses them as batched per-slot deltas and the
    # oracle re-derives each request from dense-materialized W+BA params
    head_dim = args.dim // args.heads
    store = None
    lora_trees = {}
    if args.adapter_rank:
        store = AdapterStore(
            args.layers, args.dim, args.heads * head_dim,
            args.kv_heads * head_dim, max_rank=args.adapter_rank,
            capacity=max(4, args.slots),
        )
        for j in range(args.tenants):
            tree = make_lora(
                jax.random.PRNGKey(args.seed * 7919 + j + 1), args.layers,
                args.dim, args.heads * head_dim,
                args.kv_heads * head_dim, args.adapter_rank,
            )
            lora_trees[f"t{j}"] = tree
            store.publish(f"t{j}", tree)

    step = None
    world = 1
    if stack == "dense":
        from uccl_tpu.models.dense import DenseConfig, init_params
        from uccl_tpu.models.inference import generate

        dcfg = DenseConfig(
            vocab=args.vocab, dim=args.dim, n_layers=args.layers,
            n_heads=args.heads, n_kv_heads=args.kv_heads,
            head_dim=args.dim // args.heads, ffn=args.ffn,
        )
        if args.ckpt_dir:
            params, step = _load_params(args.ckpt_dir, args.step)
            params = jax.tree.map(
                lambda x: jnp.asarray(x, jnp.float32), params
            )
            print(f"serving {args.ckpt_dir}/step_{step} (dense)", flush=True)
        else:
            params = init_params(jax.random.PRNGKey(args.seed), dcfg)
        backends = replicate_backend(
            DenseBackend(params, dcfg, n_slots=args.slots,
                         max_seq=max_seq),
            args.replicas,
        )
        vocab = dcfg.vocab

        mat_params = {}

        def oracle(req):
            # adapted requests verify against dense-materialized W+BA
            # params (cached per adapter) — the fused-delta exactness bar
            p = params
            if req.adapter is not None:
                if req.adapter not in mat_params:
                    mat_params[req.adapter] = materialize(
                        params, lora_trees[req.adapter]
                    )
                p = mat_params[req.adapter]
            toks = generate(
                p, jnp.asarray(req.prompt)[None], dcfg,
                max_new_tokens=req.max_new_tokens, max_seq=max_seq,
                sampling=req.sampling,
            )
            return np.asarray(toks)[0, : req.n_generated]
    else:
        from uccl_tpu.models.moe_inference import (
            MoEServeConfig, MoEServer, init_params,
        )
        from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

        cfg = MoEServeConfig(
            vocab=args.vocab, dim=args.dim, n_layers=args.layers,
            n_heads=args.heads, n_kv_heads=args.kv_heads,
            head_dim=args.dim // args.heads, moe_experts=args.experts,
            moe_ffn=args.ffn,
        )
        n = len(jax.devices())
        world = args.dp or n
        if world > n:
            raise SystemExit(
                f"--dp {world} exceeds the {n} available device(s)"
            )
        if args.slots % world:
            raise SystemExit(
                f"--slots {args.slots} must divide by the serving world "
                f"{world} (one slot pool row per shard batch row)"
            )
        impl = args.impl if args.impl != "auto" else (
            "sort" if world == 1 else "ll"
        )
        mesh = make_mesh(MeshConfig(dp=world), jax.devices()[:world])
        server = MoEServer(cfg, mesh)
        if args.ckpt_dir:
            params, step = _load_params(args.ckpt_dir, args.step)
            _check_sizes(params, cfg)
            params = jax.tree.map(
                lambda x: jnp.asarray(x, jnp.float32), params
            )
            print(f"serving {args.ckpt_dir}/step_{step}", flush=True)
        else:
            params = init_params(jax.random.PRNGKey(args.seed), cfg)
        backends = replicate_backend(
            MoEBackend(server, server.shard_params(params),
                       batch_local=args.slots // world, max_seq=max_seq,
                       decode_impl=impl),
            args.replicas,
        )
        vocab = cfg.vocab

        oracle_srv = {}

        def oracle(req):
            # one-shot generate on a world-1 mesh: sharding is
            # semantics-free (the tested parity property), so the 1-shard
            # program is the cheapest exact oracle. Built once — its _fns
            # cache then makes per-request calls pure cache hits. Adapted
            # requests verify against dense-materialized W+BA params,
            # sharded once per adapter.
            if "srv" not in oracle_srv:
                srv1 = MoEServer(cfg, make_mesh(MeshConfig(dp=1),
                                                jax.devices()[:1]))
                oracle_srv["srv"] = srv1
                oracle_srv[None] = srv1.shard_params(params)
            srv1 = oracle_srv["srv"]
            if req.adapter not in oracle_srv:
                oracle_srv[req.adapter] = srv1.shard_params(
                    materialize(params, lora_trees[req.adapter])
                )
            toks = srv1.generate(
                oracle_srv[req.adapter],
                jnp.asarray(req.prompt)[None, None],
                req.max_new_tokens, max_seq, impl=impl,
                sampling=req.sampling,
            )
            return np.asarray(toks)[0, 0, : req.n_generated]

    # preemption rides the priority flag whenever the engine is chunked
    # (chunk boundaries are what make pause/resume nearly free); a
    # whole-prompt priority engine still class-orders its queue
    preempt = bool(args.priority_classes and args.prefill_chunk)
    engines = [ServingEngine(
        b, max_queue=args.max_queue or None, register_stats=True,
        prefill_chunk=args.prefill_chunk or None,
        step_tokens=args.step_tokens or None,
        spec_k=args.spec_k or None,
        priority_classes=args.priority_classes, preempt=preempt,
        adapters=store, tenant_fair=bool(args.tenants) or None,
    ) for b in backends]
    target = engines[0] if args.replicas == 1 else Router(engines)

    # synthetic workload (mixed prompt lengths, Poisson arrivals), compile
    # warmup, and the wall-clock drive loop — shared with
    # benchmarks/serving_bench.py (uccl_tpu/serving/loadgen.py)
    rng = np.random.default_rng(args.seed)
    prompts, lens, arrivals = synth_workload(
        rng, args.requests, args.prompt_len, vocab, args.arrival_rate
    )
    # classes AFTER arrivals: the mix knob never perturbs arrival timing
    priorities = (assign_classes(rng, args.requests, args.interactive_frac,
                                 pattern=args.class_pattern)
                  if args.priority_classes else None)
    # tenants round-robin the arrival order; per-request seeds are
    # --seed + i (lockstep counter keys keep --check-oracle bit-exact)
    tenant_labels = ([f"t{i % args.tenants}" for i in range(args.requests)]
                     if args.tenants else None)
    adapter_labels = (list(tenant_labels) if args.adapter_rank else None)
    samplings = None
    if args.temperature > 0:
        samplings = [
            SamplingParams(temperature=args.temperature, top_p=args.top_p,
                           top_k=args.top_k, seed=args.seed + i)
            for i in range(args.requests)
        ]
    if args.replicas == 1:
        warm_engine(target, lens, max_seq, args.new_tokens)
    else:
        warm_replicas(target, lens, max_seq, args.new_tokens)
    metrics_srv = None
    if args.metrics_port:
        # live /metrics (Prometheus text) + /snapshot (JSON) for the run's
        # duration — each scrape appends the engine's current percentile
        # lines to the registry dump
        metrics_srv = obs.MetricsServer(
            args.metrics_port,
            extra_lines_fn=lambda: ServingMetrics.prometheus_lines(
                target.snapshot()
            ),
        )
        print(f"metrics: http://127.0.0.1:{metrics_srv.port}/metrics "
              f"(+ /snapshot)", flush=True)
    try:
        reqs, wall = drive(target, prompts, arrivals, args.new_tokens,
                           priorities=priorities, tenants=tenant_labels,
                           samplings=samplings, adapters=adapter_labels)
    finally:
        if metrics_srv is not None:
            metrics_srv.close()

    snap = target.snapshot()
    target.close()
    # histogram-derived TTFT percentiles beside the sample-derived ones
    # (snap["ttft_ms"]): warmup reset both, so the two derivations cover
    # the same observations and must agree within one bucket width — the
    # recorded cross-check for the merge-safe fleet path
    # (docs/OBSERVABILITY.md)
    from uccl_tpu.serving.metrics import TTFT_HIST

    ttft_hist_ms = {
        f"p{q}": round(v * 1e3, 3) for q in (50, 95)
        for v in [TTFT_HIST.quantile(q)] if v is not None
    }
    written = obs.dump_from_args(
        args, extra_lines=ServingMetrics.prometheus_lines(snap)
    )
    for path in written:
        print(f"wrote {path}", flush=True)
    summary = {
        "mode": "serve-continuous", "schema_version": obs.SCHEMA_VERSION,
        "stack": stack, "ckpt_step": step,
        "world": world, "slots": args.slots, "requests": args.requests,
        "arrival_rate": args.arrival_rate, "new_tokens": args.new_tokens,
        "prefill_chunk": args.prefill_chunk or None,
        "step_tokens": args.step_tokens or None,
        "spec_k": args.spec_k or None,
        "replicas": args.replicas,
        "priority_classes": bool(args.priority_classes),
        "preempt": preempt,
        "interactive_frac": (args.interactive_frac
                             if args.priority_classes else None),
        "temperature": args.temperature or None,
        "top_p": args.top_p if args.temperature else None,
        "top_k": args.top_k if args.temperature else None,
        "tenants": args.tenants or None,
        "adapter_rank": args.adapter_rank or None,
        "wall_s": round(wall, 3), "ttft_hist_ms": ttft_hist_ms, **snap,
    }
    if reqs:
        print(f"first request: {reqs[0].out_tokens}", flush=True)

    if args.check_oracle:
        leaked = (target.leaked() if args.replicas > 1
                  else target.pool.leaked())
        qsize = (target.qsize if args.replicas > 1
                 else target.sched.qsize)
        mismatched = []
        for r in reqs:
            want = oracle(r)
            if r.out_tokens != want.tolist():
                mismatched.append((r.rid, r.out_tokens, want.tolist()))
        ok = (not leaked and not mismatched and qsize == 0
              and snap["completed"] == len(reqs))
        summary["oracle_match"] = bool(ok)
        summary["leaked_slots"] = leaked
        print(json.dumps(summary), flush=True)
        if not ok:
            for rid, got, want in mismatched:
                print(f"request {rid}: got {got} want {want}",
                      file=sys.stderr)
            raise SystemExit(
                f"oracle check FAILED: leaked={leaked} "
                f"mismatched={len(mismatched)}"
            )
        print(f"oracle check: {len(reqs)} requests bit-exact, "
              f"0 leaked slots", flush=True)
    else:
        print(json.dumps(summary), flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m uccl_tpu.serve")
    ap.add_argument("--devices", type=int, default=0,
                    help="force an N-device virtual CPU mesh (tests/dev)")
    ap.add_argument("--dp", type=int, default=0,
                    help="serving world (default: all devices)")
    ap.add_argument("--batch", type=int, default=8, help="global batch")
    ap.add_argument("--prompt-len", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--max-seq", type=int, default=0,
                    help="KV capacity (default: prompt+new)")
    ap.add_argument("--impl", default="auto", choices=["auto", "ll", "sort"],
                    help="decode-step EP path (prefill always uses sort). "
                         "'auto' follows the measurements: sort at world 1 "
                         "(wins 1.2-3.2x at every batch, PERF.md), ll on "
                         "multi-member worlds where its packed rows cut "
                         "actual wire bytes (the DeepEP LL regime)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timing-reps", type=int, default=3,
                    help="one-shot mode: repetitions of the timing windows "
                         "feeding the TTFT/decode-step p50/p95 percentiles")
    # continuous-batching server mode (uccl_tpu/serving, docs/SERVING.md)
    ap.add_argument("--server", action="store_true",
                    help="continuous-batching engine under a synthetic "
                         "Poisson arrival stream (vs the one-shot batch)")
    ap.add_argument("--slots", type=int, default=4,
                    help="server: KV slot pool size (MoE: must divide by "
                         "the serving world; B_loc = slots/world)")
    ap.add_argument("--requests", type=int, default=16,
                    help="server: number of synthetic requests")
    ap.add_argument("--arrival-rate", type=float, default=8.0,
                    help="server: Poisson arrival rate in req/s "
                         "(0 = all arrive at t=0)")
    ap.add_argument("--max-queue", type=int, default=0,
                    help="server: bounded queue depth; submissions beyond "
                         "it are rejected (backpressure). 0 = unbounded")
    ap.add_argument("--stack", default="auto",
                    choices=["auto", "dense", "moe"],
                    help="server: model stack ('auto': dense for dense "
                         "checkpoints, else MoE)")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="server: chunked prefill — admitted prompts "
                         "prefill C tokens per engine step so in-flight "
                         "decodes never stall behind more than one chunk "
                         "(one compiled prefill program instead of pow2 "
                         "buckets). 0 = whole-prompt prefill")
    ap.add_argument("--step-tokens", type=int, default=0,
                    help="server: per-step token budget (decoding slot = 1 "
                         "token, or 1+K under --spec-k — the verify window "
                         "really runs K+1 rows; prefill chunk = C); "
                         "admission defers while the step's committed "
                         "spend would exceed it. Needs --prefill-chunk. "
                         "0 = unbudgeted")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="server: speculative decoding — the prompt-lookup "
                         "NGram drafter proposes K tokens per decoding "
                         "slot each step, one batched [slots, K+1] verify "
                         "commits each slot's accepted prefix + 1 "
                         "target token (bit-identical to vanilla greedy "
                         "decode, docs/SERVING.md). 0 = off")
    ap.add_argument("--replicas", type=int, default=1,
                    help="server: engine replica count behind the "
                         "least-loaded router (each replica owns a "
                         "--slots KV pool; admission steers by live "
                         "free-slot/token-debt/queue-wait signals, "
                         "docs/SERVING.md)")
    ap.add_argument("--priority-classes", action="store_true",
                    help="server: SLO classes — each request is "
                         "'interactive' (admits first; with "
                         "--prefill-chunk it preempts running batch work "
                         "at chunk boundaries, bit-exact resume) or "
                         "'batch', drawn per request at "
                         "--interactive-frac")
    ap.add_argument("--interactive-frac", type=float, default=0.5,
                    help="server: fraction of requests in the "
                         "interactive class under --priority-classes")
    ap.add_argument("--class-pattern", default="bernoulli",
                    choices=["bernoulli", "batch-first"],
                    help="server: how classes map onto the arrival "
                         "order — 'bernoulli' interleaves (realistic "
                         "mixed traffic), 'batch-first' front-loads all "
                         "batch work so every interactive arrival finds "
                         "the slots occupied (the deterministic "
                         "preemption smoke fixture)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="server: stochastic sampling temperature "
                         "(0 = greedy). Request i samples under "
                         "per-request seed --seed+i with lockstep "
                         "counter-based keys, so --check-oracle stays "
                         "bit-exact against the SAMPLED one-shot "
                         "generate oracle at the same seed")
    ap.add_argument("--top-p", type=float, default=1.0,
                    help="server: nucleus sampling mass in (0, 1] "
                         "(active with --temperature > 0)")
    ap.add_argument("--top-k", type=int, default=0,
                    help="server: top-k truncation, 0 = off (active "
                         "with --temperature > 0)")
    ap.add_argument("--tenants", type=int, default=0,
                    help="server: N synthetic tenants round-robin over "
                         "the arrival stream, admitted via per-tenant "
                         "deficit round-robin (TenantFairScheduler); "
                         "metrics gain tenant= labeled series. 0 = one "
                         "implicit tenant, plain FIFO")
    ap.add_argument("--adapter-rank", type=int, default=0,
                    help="server: per-tenant LoRA adapters of this rank "
                         "(needs --tenants), applied as batched fused "
                         "per-slot deltas; --check-oracle verifies "
                         "against dense-materialized W+BA params. "
                         "0 = no adapters")
    ap.add_argument("--check-oracle", action="store_true",
                    help="server: verify every completed request is "
                         "bit-identical to the one-shot generate oracle "
                         "and that no KV slot leaked (CI smoke tier)")
    # model size — must match the checkpoint when --ckpt-dir is given
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--ffn", type=int, default=128)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--step", type=int, default=None,
                    help="checkpoint step (default: latest)")
    # observability surfaces (docs/OBSERVABILITY.md): --trace-out enables
    # the event tracer and writes a Chrome-trace/Perfetto JSON at exit;
    # --metrics-out dumps the Prometheus-text registry; --metrics-port
    # serves live /metrics + /snapshot during --server runs
    from uccl_tpu import obs

    obs.add_cli_args(ap)
    args = ap.parse_args(argv)
    obs.setup_from_args(args)
    # crash-safety net: a run that dies mid-flight still dumps its partial
    # trace/metrics (the explicit dumps below win when they run)
    obs.dump_at_exit(args)

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    import jax.numpy as jnp
    import numpy as np

    from uccl_tpu.models.moe_inference import (
        MoEServeConfig, MoEServer, init_params,
    )
    from uccl_tpu.parallel.mesh import MeshConfig, make_mesh

    # A trainer checkpoint records its model family + sizes in config.json;
    # prefer that over flags (flags that DIFFER are an error — shapes like
    # heads vs kv-heads cannot all be recovered from param shapes alone,
    # so silent flag drift would serve silently-wrong tokens).
    saved_cfg = None
    if args.ckpt_dir:
        cfg_path = os.path.join(args.ckpt_dir, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                saved_cfg = json.load(f)
            if saved_cfg.get("model") not in ("flagship", "dense"):
                raise SystemExit(
                    f"{args.ckpt_dir} holds a {saved_cfg.get('model')!r} "
                    "checkpoint; serve handles flagship (MoE) and dense"
                )
            defaults = ap.parse_args([])
            pairs = [
                ("vocab", "vocab"), ("dim", "dim"), ("layers", "layers"),
                ("heads", "heads"), ("kv_heads", "kv_heads"),
                ("ffn", "ffn"),
            ]
            if saved_cfg.get("model") == "flagship":
                pairs.append(("experts", "experts"))  # MoE-only flag
            for flag, key in pairs:
                given = getattr(args, flag)
                if given != getattr(defaults, flag) and given != saved_cfg[key]:
                    raise SystemExit(
                        f"--{flag.replace('_', '-')} {given} != checkpoint "
                        f"config {saved_cfg[key]} ({cfg_path})"
                    )
                setattr(args, flag, saved_cfg[key])
    if args.server:
        return _serve_continuous(args, saved_cfg)
    if saved_cfg is not None and saved_cfg.get("model") == "dense":
        # Dense (Llama-family) checkpoints generate through the cached
        # single-shard KV path (models/inference.py) — no EP mesh.
        from uccl_tpu.models.dense import DenseConfig
        from uccl_tpu.models.inference import generate

        dcfg = DenseConfig(
            vocab=args.vocab, dim=args.dim, n_layers=args.layers,
            n_heads=args.heads, n_kv_heads=args.kv_heads,
            head_dim=args.dim // args.heads, ffn=args.ffn,
        )
        max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
        if args.prompt_len + args.new_tokens > max_seq:
            raise SystemExit("--prompt-len + --new-tokens exceed --max-seq")
        params, step = _load_params(args.ckpt_dir, args.step)
        params = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params)
        print(f"serving {args.ckpt_dir}/step_{step} (dense)", flush=True)
        rng = np.random.default_rng(args.seed)
        prompt = jnp.asarray(
            rng.integers(0, dcfg.vocab, (args.batch, args.prompt_len)),
            jnp.int32,
        )
        # One jitted program (prefill + decode scan), cached per shape in
        # inference.generate — the warmup call at the SAME new_tokens
        # compiles it; the timed call is a pure cache hit. (The old
        # per-token decode_j loop paid ~10 ms of dispatch per token over
        # the tunnel — the same fix as MoEServer.generate, PERF.md.)
        # host-read the warmup: the call itself is async and compile can
        # complete with the execution still queued — an unread warmup
        # leaks its execution (and, observed on the axon tunnel, a
        # compile-sized stall) into the timed window
        np.asarray(generate(params, prompt, dcfg,
                            max_new_tokens=args.new_tokens,
                            max_seq=max_seq))
        # Honest decode throughput: the full timed window INCLUDES prefill,
        # so dividing by batch*new_tokens alone would flatter short windows.
        # A second program at 1 new token (warmed the same way) gives the
        # TTFT window, and the window delta is decode-only time for
        # new_tokens-1 tokens. Repeated reps feed the p50/p95 percentiles
        # (serving/metrics.py definitions).
        run_one = None
        if args.new_tokens > 1:
            np.asarray(generate(params, prompt, dcfg, max_new_tokens=1,
                                max_seq=max_seq))
            run_one = lambda: np.asarray(generate(  # noqa: E731
                params, prompt, dcfg, max_new_tokens=1, max_seq=max_seq))
        run_full = lambda: np.asarray(generate(  # noqa: E731
            params, prompt, dcfg, max_new_tokens=args.new_tokens,
            max_seq=max_seq))
        out, dt, extra = _timed_windows(
            run_full, run_one, args.batch, args.new_tokens, args.timing_reps
        )
        summary = {
            "mode": "serve", "schema_version": obs.SCHEMA_VERSION,
            "ckpt_step": step, "impl": "dense",
            "world": 1, "batch": args.batch,
            "new_tokens": args.new_tokens,
            # the raw window metric, kept under an honest name: it spans
            # prefill AND decode
            "window": "prefill+decode",
            "tokens_per_sec": round(args.batch * args.new_tokens / dt, 1),
            **extra,
        }
        print(f"first sequence: {out[0].tolist()}", flush=True)
        print(json.dumps(summary), flush=True)
        obs.dump_from_args(args)
        return

    cfg = MoEServeConfig(
        vocab=args.vocab, dim=args.dim, n_layers=args.layers,
        n_heads=args.heads, n_kv_heads=args.kv_heads,
        head_dim=args.dim // args.heads, moe_experts=args.experts,
        moe_ffn=args.ffn,
    )
    n = len(jax.devices())
    world = args.dp or n
    # fail the cheap flag checks in milliseconds, BEFORE any restore work
    if world > n:
        raise SystemExit(f"--dp {world} exceeds the {n} available device(s)")
    if args.batch % world:
        raise SystemExit(f"--batch {args.batch} must divide by world {world}")
    max_seq = args.max_seq or (args.prompt_len + args.new_tokens)
    if args.prompt_len + args.new_tokens > max_seq:
        raise SystemExit(
            f"--prompt-len {args.prompt_len} + --new-tokens "
            f"{args.new_tokens} exceed --max-seq {max_seq}"
        )
    # '--impl auto' follows the measurements (PERF.md round-5 decode table):
    # at world 1 the sorted path wins 1.2-3.2x at every batch — LL's packed
    # rows save WIRE bytes, which a single-member world never moves. Multi-
    # member worlds keep the DeepEP LL decode regime. Explicit --impl wins.
    impl = args.impl if args.impl != "auto" else (
        "sort" if world == 1 else "ll"
    )
    mesh = make_mesh(MeshConfig(dp=world), jax.devices()[:world])
    server = MoEServer(cfg, mesh)

    step = None
    if args.ckpt_dir:
        params, step = _load_params(args.ckpt_dir, args.step)
        _check_sizes(params, cfg)
        params = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params)
        print(f"serving {args.ckpt_dir}/step_{step}", flush=True)
    else:
        params = init_params(jax.random.PRNGKey(args.seed), cfg)
    placed = server.shard_params(params)

    b_local = args.batch // world
    rng = np.random.default_rng(args.seed)
    prompt = jnp.asarray(
        rng.integers(0, cfg.vocab, (world, b_local, args.prompt_len)),
        jnp.int32,
    )

    # Warmup compiles the prefill + decode-scan programs. It must use the
    # SAME new_tokens as the timed run: generate's decode loop is one
    # jitted lax.scan whose length is baked into the program, so a
    # 1-token warmup would compile a different scan and the timed call
    # would pay the real compile. Host-READ the result: the call is
    # async, and an unread warmup leaks its execution into the timed
    # window (see the dense branch note).
    np.asarray(server.generate(
        placed, prompt, args.new_tokens, max_seq, impl=impl
    ))
    # decode-only throughput via the 1-token delta (see the dense branch:
    # the timed window spans prefill+decode, so the delta of two windows
    # is the honest decode number); repeated reps feed the TTFT /
    # decode-step percentiles
    run_one = None
    if args.new_tokens > 1:
        np.asarray(server.generate(placed, prompt, 1, max_seq, impl=impl))
        run_one = lambda: np.asarray(server.generate(  # noqa: E731
            placed, prompt, 1, max_seq, impl=impl))
    run_full = lambda: np.asarray(server.generate(  # noqa: E731
        placed, prompt, args.new_tokens, max_seq, impl=impl))
    out, dt, extra = _timed_windows(
        run_full, run_one, args.batch, args.new_tokens, args.timing_reps
    )
    total = args.batch * args.new_tokens
    summary = {
        "mode": "serve",
        "schema_version": obs.SCHEMA_VERSION,
        "ckpt_step": step,
        "impl": impl,
        "world": world,
        "batch": args.batch,
        "new_tokens": args.new_tokens,
        "window": "prefill+decode",
        "tokens_per_sec": round(total / dt, 1),
        **extra,
    }
    print(f"first sequence: {out[0, 0].tolist()}", flush=True)
    print(json.dumps(summary), flush=True)
    obs.dump_from_args(args)


if __name__ == "__main__":
    main()
