"""``python -m uccl_tpu.doctor`` — forensic reader for flight bundles.

The flight recorder (obs/flight.py) freezes evidence; this CLI turns it
back into a story. For each bundle it cross-links the trigger with the
preceding ring events, the frozen transport/engine/fleet state, and the
registry counters, then prints a root-cause narrative::

    == flight_001_retx_storm.json · t=4.21s · trigger=retx_storm ==
    root cause: path_loss
    SACK retransmit storm on path 2: 14 fast + 3 RTO retx over 38
    chunks (44.7%); rto backed off to 812.0 ms; path scores
    [1.00, 1.00, 0.31, 0.98] ...

Each trigger kind maps to a stable machine-readable ``root_cause`` tag
(``--json`` emits the verdicts as JSON) — the chaos bench asserts
doctor's verdict matches the fault it injected, and ``check_obs
--flight`` re-runs the same mapping in CI:

    peer_dead          -> replica_failure
    retx_storm         -> path_loss
    rto_backoff        -> path_blackout
    ctrl_storm         -> control_plane_loss
    conservation       -> accounting_leak
    slo_burn           -> slo_violation
    step_stall         -> engine_stall
    uncaught_exception -> driver_crash

Inputs are bundle paths or directories (scanned for
``flight_*.json``); ``--trace merged.json`` optionally cross-links a
clock-aligned merged trace (scripts/trace_merge.py) so the narrative
can cite fleet-wide events around the trigger instant.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

ROOT_CAUSE = {
    "peer_dead": "replica_failure",
    "retx_storm": "path_loss",
    "rto_backoff": "path_blackout",
    "ctrl_storm": "control_plane_loss",
    "conservation": "accounting_leak",
    "slo_burn": "slo_violation",
    "step_stall": "engine_stall",
    "uncaught_exception": "driver_crash",
}

# ring-event names worth citing as precursors, by trigger kind
_PRECURSORS = {
    "peer_dead": ("peer_suspect", "peer_dead", "heartbeat"),
    "retx_storm": ("p2p_transfer_failed", "flight_dump"),
    "rto_backoff": ("p2p_transfer_failed",),
    "ctrl_storm": ("grant", "begin", "final"),
    "conservation": ("submit", "reject", "expired", "recovered"),
    "slo_burn": ("first_token", "submit", "preempt"),
    "step_stall": ("engine.step", "preempt", "resume"),
    "uncaught_exception": (),
}


def load_bundle(path: str) -> Dict:
    with open(path) as f:
        b = json.load(f)
    if b.get("schema") != "uccl_tpu.flight/1":
        raise ValueError(f"{path}: not a flight bundle "
                         f"(schema={b.get('schema')!r})")
    b["_path"] = path
    return b


def _expand(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "flight_*.json"))))
        else:
            out.append(p)
    return out


def _counters(bundle: Dict) -> Dict[str, float]:
    """Flatten the bundle's Prometheus text to {series-line-key: value}
    using the same parser the federator uses."""
    from uccl_tpu.obs.aggregate import parse_prometheus

    _types, samples = parse_prometheus(bundle.get("metrics_prom", ""))
    out: Dict[str, float] = {}
    for name, series in samples.items():
        for labels, v in series.items():
            lbl = ",".join(f'{k}="{val}"' for k, val in labels)
            out[f"{name}{'{' + lbl + '}' if lbl else ''}"] = v
    return out


def _sum_counter(counters: Dict[str, float], prefix: str) -> float:
    return sum(v for k, v in counters.items()
               if k == prefix or k.startswith(prefix + "{"))


def _preceding(bundle: Dict, kind: str, n: int = 8) -> List[Dict]:
    names = _PRECURSORS.get(kind, ())
    evs = [e for e in bundle.get("events", [])
           if not names or e.get("name") in names
           or any(e.get("name", "").startswith(p) for p in names)]
    return evs[-n:]


def _transport_state(bundle: Dict) -> Optional[Dict]:
    ctx = bundle["trigger"].get("context") or {}
    state = bundle.get("state")
    sources = [ctx] + (list(state.values())
                       if isinstance(state, dict) else [])
    for src in sources:
        if isinstance(src, dict) and ("retx_fast" in src
                                      or "path_scores" in src):
            return src
    return None


def _conservation_terms(counters: Dict[str, float]) -> Dict[str, float]:
    terms = {}
    for t in ("submitted", "completed", "active", "queued", "rejected",
              "expired", "lost"):
        terms[t] = _sum_counter(counters, f"uccl_serving_{t}")
    return terms


def diagnose(bundle: Dict) -> Dict:
    """One bundle -> verdict dict with a stable root_cause tag and a
    human narrative. Never raises on a well-formed bundle — a sparse
    bundle degrades to a sparser narrative."""
    trig = bundle["trigger"]
    kind = trig["kind"]
    ctx = trig.get("context") or {}
    counters = _counters(bundle)
    lines: List[str] = []
    details: Dict = {}

    if kind == "peer_dead":
        peer = ctx.get("peer") or ctx.get("owner") or trig.get("key")
        src = ctx.get("source", "health")
        suspects = [e for e in bundle.get("events", [])
                    if e.get("name") == "peer_suspect"]
        lines.append(
            f"replica {peer!r} declared DEAD (detected via {src})"
            + (f" after {len(suspects)} SUSPECT transition(s) in the ring"
               if suspects else " with no SUSPECT precursor in the ring"))
        recovered = _sum_counter(counters, "serving_recovered_total")
        if recovered:
            lines.append(f"{int(recovered)} request(s) already re-placed "
                         f"on survivors at dump time")
        details.update(peer=peer, source=src)
    elif kind in ("retx_storm", "rto_backoff"):
        st = _transport_state(bundle) or {}
        fast = int(st.get("retx_fast", ctx.get("retx_fast", 0)) or 0)
        rto = int(st.get("retx_rto", ctx.get("retx_rto", 0)) or 0)
        chunks = int(st.get("chunks", ctx.get("chunks", 0)) or 0)
        rto_ms = st.get("rto_ms", ctx.get("rto_ms"))
        scores = st.get("path_scores", ctx.get("path_scores"))
        frac = (f" ({100.0 * (fast + rto) / chunks:.1f}% of {chunks} "
                f"chunks)") if chunks else ""
        if kind == "retx_storm":
            lines.append(f"SACK retransmit storm: {fast} fast + {rto} RTO "
                         f"retransmits{frac}")
        else:
            lines.append(f"RTO backed off past the armed ceiling"
                         + (f" to {float(rto_ms):.1f} ms"
                            if rto_ms is not None else "")
                         + f" — sustained loss or path blackout"
                         + (f"; {fast} fast + {rto} RTO retx{frac}"
                            if fast + rto else ""))
        if scores:
            worst = min(range(len(scores)), key=lambda i: scores[i])
            lines.append(
                f"path quality {['%.2f' % s for s in scores]} — "
                f"path {worst} is the casualty ({scores[worst]:.2f})")
            details["worst_path"] = worst
        if rto_ms is not None and kind == "retx_storm":
            lines.append(f"smoothed RTO at dump: {float(rto_ms):.1f} ms")
        details.update(retx_fast=fast, retx_rto=rto, chunks=chunks)
    elif kind == "ctrl_storm":
        retries = ctx.get("retries",
                          _sum_counter(counters, "disagg_ctrl_retries_total"))
        dropped = _sum_counter(counters, "disagg_ctrl_dropped_total")
        lines.append(f"disagg control-plane storm: {int(retries)} notif "
                     f"retransmission(s)"
                     + (f", {int(dropped)} injected drop(s) counted"
                        if dropped else "")
                     + " — notif plane lossy or the peer is unresponsive")
        details.update(retries=int(retries), dropped=int(dropped))
    elif kind == "conservation":
        terms = ctx.get("terms") or _conservation_terms(counters)
        rhs = sum(v for k, v in terms.items() if k != "submitted")
        lines.append(
            f"serving conservation broke: submitted "
            f"{terms.get('submitted')} != "
            f"completed+active+queued+rejected+expired+lost = {rhs} "
            f"({ {k: int(v) for k, v in terms.items()} })")
        details["terms"] = terms
    elif kind == "slo_burn":
        obj = ctx.get("objective", "?")
        win = ctx.get("window_s", "?")
        lines.append(
            f"SLO burn alert: objective {obj!r} burned at "
            f"{float(ctx.get('burn', 0)):.1f}x budget over the {win}s "
            f"window — {int(ctx.get('violations', 0))} of "
            f"{int(ctx.get('total', 0))} request(s) past the "
            f"{float(ctx.get('threshold_s', 0)) * 1e3:.0f} ms objective")
        if ctx.get("labels"):
            lines.append(f"scope: {ctx['labels']}")
        details.update(objective=obj, burn=ctx.get("burn"),
                       labels=ctx.get("labels"))
    elif kind == "step_stall":
        dur = float(ctx.get("dur_s", 0.0))
        budget = ctx.get("budget_s")
        occ = ctx.get("occupancy")
        lines.append(f"engine step stalled: one step() took {dur * 1e3:.1f}"
                     f" ms"
                     + (f" against a {float(budget) * 1e3:.0f} ms budget"
                        if budget is not None else "")
                     + (f" at occupancy {occ}" if occ is not None else ""))
        details.update(dur_s=dur, budget_s=budget)
    elif kind == "uncaught_exception":
        lines.append(f"driver crashed in {ctx.get('where', '?')}: "
                     f"{ctx.get('exc_type', '?')}: {ctx.get('exc', '')}")
        tail = (ctx.get("traceback_tail") or "").strip().splitlines()
        if tail:
            lines.append("traceback tail: " + tail[-1].strip())
        details.update(exc_type=ctx.get("exc_type"))
    else:
        lines.append(f"unknown trigger kind {kind!r}")

    pre = _preceding(bundle, kind)
    if pre:
        tail = ", ".join(f"{e['name']}@{e['ts_us'] / 1e6:.3f}s"
                         for e in pre[-4:])
        lines.append(f"preceding ring events: {tail}")
    burns = _sum_counter(counters, "obs_slo_burn_alerts_total")
    if burns and kind != "slo_burn":
        lines.append(f"{int(burns)} SLO burn alert(s) already counted at "
                     f"dump time — user-visible impact likely")
    dumps = _sum_counter(counters, "obs_flight_dumps_total")
    details["dumps_counted"] = dumps

    return {
        "bundle": bundle["_path"],
        "seq": bundle.get("seq"),
        "trigger": kind,
        "t_wall_s": trig.get("t_wall_s"),
        "root_cause": ROOT_CAUSE.get(kind, "unknown"),
        "narrative": lines,
        "details": details,
    }


def _trace_context(trace_path: str, bundle: Dict,
                   window_us: float = 2e5) -> List[str]:
    """Cite merged-trace instants near the trigger instant (both sides
    are wall-anchored: trace_merge rebases onto wall epochs, the bundle
    carries t_wall_s)."""
    with open(trace_path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    base = ((doc.get("otherData") or {}).get("merged_wall_epoch_us")
            if isinstance(doc, dict) else None)
    if base is None or bundle["trigger"].get("t_wall_s") is None:
        return []
    t_us = bundle["trigger"]["t_wall_s"] * 1e6 - float(base)
    near = [e for e in evs
            if isinstance(e, dict) and e.get("ph") == "i"
            and abs(float(e.get("ts", 0)) - t_us) <= window_us]
    return [f"merged-trace instants within {window_us / 1e3:.0f} ms of the "
            f"trigger: "
            + ", ".join(sorted({e.get('name', '?') for e in near}))] \
        if near else []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m uccl_tpu.doctor",
        description="root-cause narratives from flight-recorder bundles")
    ap.add_argument("bundles", nargs="+",
                    help="bundle files or directories of flight_*.json")
    ap.add_argument("--trace", default="",
                    help="merged Chrome trace (scripts/trace_merge.py) to "
                         "cross-link around each trigger")
    ap.add_argument("--json", action="store_true",
                    help="emit verdicts as a JSON array instead of prose")
    args = ap.parse_args(argv)

    paths = _expand(args.bundles)
    if not paths:
        print("doctor: no flight bundles found", file=sys.stderr)
        return 1
    verdicts = []
    for p in paths:
        try:
            b = load_bundle(p)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"doctor: {e}", file=sys.stderr)
            return 1
        v = diagnose(b)
        if args.trace:
            try:
                v["narrative"].extend(_trace_context(args.trace, b))
            except (OSError, json.JSONDecodeError) as e:
                v["narrative"].append(f"(merged trace unreadable: {e})")
        verdicts.append(v)
    verdicts.sort(key=lambda v: (v["t_wall_s"] or 0.0, v["bundle"]))

    if args.json:
        json.dump(verdicts, sys.stdout, indent=1)
        print()
        return 0
    t0 = next((v["t_wall_s"] for v in verdicts
               if v["t_wall_s"] is not None), None)
    for v in verdicts:
        t = v["t_wall_s"]
        head = (f"== {os.path.basename(v['bundle'])}"
                + (f" · t=+{t - t0:.2f}s" if t is not None else "")
                + f" · trigger={v['trigger']} ==")
        print(head)
        print(f"root cause: {v['root_cause']}")
        for ln in v["narrative"]:
            print(f"  {ln}")
        print()
    print(f"doctor: {len(verdicts)} bundle(s) examined")
    return 0


if __name__ == "__main__":
    sys.exit(main())
