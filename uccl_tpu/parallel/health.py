"""Failure detection: heartbeats over the OOB store.

The reference's failure handling is transport-level (RTO abort after
kRTOAbortThreshold consecutive RTOs, transport_config.h:202; peer teardown via
remove_remote_endpoint, p2p/engine.h:273 — SURVEY.md §5). This adds the
job-level piece on top: every rank posts heartbeats to the rendezvous store; a
monitor thread flags peers whose heartbeats stall, so the application can
remove their endpoints / rebuild groups (elastic peer remove).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

from uccl_tpu.parallel.distributed import Session
from uccl_tpu.utils.logging import get_logger

_log = get_logger("PARALLEL")


class HeartbeatMonitor:
    """Post own heartbeats; watch everyone else's.

    on_failure(rank) fires once per newly-suspected peer (heartbeat older
    than ``timeout_s``). Ranks that resume beating are un-suspected.
    """

    def __init__(
        self,
        sess: Session,
        interval_s: float = 1.0,
        timeout_s: float = 5.0,
        on_failure: Optional[Callable[[int], None]] = None,
        key: str = "health/hb",
    ):
        self.sess = sess
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.on_failure = on_failure
        self.key = key
        self._stop = threading.Event()
        self._suspected: set = set()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._started_at: Optional[float] = None
        # Staleness is judged LOCALLY: we record the local monotonic time at
        # which each peer's posted value last *changed*. Comparing a peer's
        # wall clock against ours would turn cross-host clock skew into
        # false suspicions (or masked failures).
        self._last_seen: Dict[int, tuple] = {}  # rank -> (value, local_mono)

    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def suspected(self) -> List[int]:
        with self._lock:
            return sorted(self._suspected)

    def beat_once(self) -> None:
        """Post one heartbeat (called by the monitor loop; callable directly
        from training loops that want heartbeats tied to step progress).

        The value is an opaque monotonically-increasing counter — peers only
        check that it CHANGES, never compare it against their own clocks."""
        self.sess.store.set(
            f"{self.key}/{self.sess.rank}",
            json.dumps(time.monotonic()).encode(),
        )

    # ------------------------------------------------------------------
    def _check_peers(self) -> None:
        now = time.monotonic()
        newly_dead = []
        for r in range(self.sess.world):
            if r == self.sess.rank:
                continue
            raw = self.sess.store.get(f"{self.key}/{r}")
            value = json.loads(raw.decode()) if raw else None
            if value is None:
                # never-seen peer gets the full timeout as a startup grace
                dead = (now - self._started_at) > self.timeout_s
            else:
                seen = self._last_seen.get(r)
                if seen is None or seen[0] != value:
                    self._last_seen[r] = (value, now)  # changed -> alive now
                dead = (now - self._last_seen[r][1]) > self.timeout_s
            last = value
            with self._lock:
                if dead and r not in self._suspected:
                    self._suspected.add(r)
                    _log.warning("peer rank %d suspected dead (last hb %s)", r, last)
                    newly_dead.append(r)
                elif not dead and r in self._suspected:
                    self._suspected.discard(r)
                    _log.info("peer rank %d recovered", r)
        # callbacks fire outside the lock: they may call suspected()/stop()
        if self.on_failure is not None:
            for r in newly_dead:
                self.on_failure(r)

    def _run(self) -> None:
        self._started_at = time.monotonic()
        self.beat_once()
        self._stop.wait(self.interval_s)
        while not self._stop.is_set():
            self.beat_once()
            self._check_peers()
            self._stop.wait(self.interval_s)
