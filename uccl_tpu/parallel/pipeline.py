"""SPMD pipeline parallelism over the ``pp`` mesh axis.

The reference offers "0 SM PP (with RDMA)" — one-sided activation sends between
pipeline stages with zero compute occupancy (experimental/lite/lite-ep/README.md:24,
tests/elastic/test_pp.py). The TPU-native equivalent: a GPipe schedule written as
a single ``lax.scan`` whose stage-to-stage hand-off is ``lax.ppermute`` over the
``pp`` axis — XLA turns those into async ICI sends that overlap the next
microbatch's compute, which is exactly the zero-SM property (no device compute
spent on communication).

Per-shard function (use inside shard_map). All stages run the same program; a
stage's identity comes from ``lax.axis_index``.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from uccl_tpu.collective.plan import tree_broadcast
from uccl_tpu.utils.topology import ppermute_pairs


def gpipe_spmd(
    stage_fn: Callable[[jax.Array], Tuple[jax.Array, jax.Array]],
    xmb: jax.Array,
    axis: str = "pp",
) -> Tuple[jax.Array, jax.Array]:
    """Run microbatches through the pipeline stages.

    Args:
      stage_fn: per-stage computation ``x -> (y, aux)`` where x/y are one
        microbatch of activations ``[B_mb, ...]`` (same shape in and out) and
        aux is a scalar side-channel (e.g. MoE aux losses), summed over valid
        microbatches.
      xmb: ``[M, B_mb, ...]`` microbatched input activations (the stage-0
        input stream; other stages ignore it).
      axis: the pipeline mesh axis.

    Returns:
      (out ``[M, B_mb, ...]`` final-stage outputs replicated across pp members,
       aux scalar summed over all stages and microbatches, replicated).

    Schedule: step t has stage s working on microbatch ``t - s`` (valid when
    0 <= t-s < M); total ``M + P - 1`` steps; bubble fraction (P-1)/(M+P-1).
    """
    p = lax.axis_size(axis)
    s = lax.axis_index(axis)
    m = xmb.shape[0]
    perm = ppermute_pairs(p, 1)

    def step(carry, t):
        xbuf, outbuf, aux = carry
        fresh = lax.dynamic_index_in_dim(
            xmb, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(s == 0, fresh, xbuf)
        y, aux_step = stage_fn(x_in)
        m_local = t - s
        valid = (m_local >= 0) & (m_local < m)
        aux = aux + jnp.where(valid, aux_step, jnp.zeros_like(aux_step))
        # Collect this stage's output for microbatch t-(p-1); only the last
        # stage's buffer survives the psum below.
        m_out = t - (p - 1)
        idx = jnp.clip(m_out, 0, m - 1)
        cur = lax.dynamic_index_in_dim(outbuf, idx, axis=0, keepdims=False)
        newv = jnp.where((m_out >= 0) & (m_out < m), y, cur)
        outbuf = lax.dynamic_update_index_in_dim(outbuf, newv, idx, axis=0)
        x_next = lax.ppermute(y, axis, perm)
        return (x_next, outbuf, aux), None

    xbuf0 = jnp.zeros_like(xmb[0])
    outbuf0 = jnp.zeros_like(xmb)
    aux0 = jnp.zeros((), jnp.float32)
    (xbuf, outbuf, aux), _ = lax.scan(
        step, (xbuf0, outbuf0, aux0), jnp.arange(m + p - 1)
    )
    # Broadcast the last stage's collected outputs to all pp members so
    # downstream loss code is uniform SPMD — binomial tree (log P rounds of
    # the buffer) instead of a full-buffer psum of mostly zeros.
    out = tree_broadcast(outbuf, axis, root=p - 1)
    aux_total = lax.psum(aux, axis)
    return out, aux_total


# ---------------------------------------------------------------------------
# 1F1B (manual schedule): bounded-activation pipeline training
#
# GPipe above leans on autodiff: jax.grad through the scan stashes every
# microbatch's residuals on every stage (fine with remat, but liveness is
# O(M)). This primitive writes the backward by hand on the classic
# one-forward-one-backward schedule, so a stage never holds more than
# min(M, P - s) stashed microbatch INPUTS (activations are recomputed at
# backward time from the stashed input — the recompute discipline the rest
# of this framework already uses). The schedule table is built statically by
# a slot-synchronous simulator; each scan slot does at most one forward and
# one backward under lax.cond, with activations ppermuting forward and
# cotangents ppermuting backward every slot.


def _simulate_1f1b(m: int, p: int):
    """Slot-synchronous 1F1B schedule. Returns four [T, P] int tables:
    (do_fwd, fwd_mb, do_bwd, bwd_mb) — what stage s runs at slot t.

    Policy per stage: run a backward as soon as a cotangent is available;
    otherwise run the next forward if its input is available. Capping
    in-flight forwards at (P - s) yields the classic 1F1B memory profile.
    """
    fwd_done = [0] * p
    bwd_done = [0] * p
    # activation availability: arrival_slot of mb f at stage s
    ready_f = [[0 if s == 0 else None for _ in range(m)] for s in range(p)]
    ready_b = [[0 if s == p - 1 else None for _ in range(m)] for s in range(p)]
    rows = []
    t = 0
    while any(bwd_done[s] < m for s in range(p)) and t < 4 * (m + p):
        row = []
        for s in range(p):
            do_f, f_mb, do_b, b_mb = 0, 0, 0, 0
            inflight = fwd_done[s] - bwd_done[s]
            b = bwd_done[s]
            f = fwd_done[s]
            can_b = (
                b < m
                and b < fwd_done[s]  # its own fwd must have run
                and ready_b[s][b] is not None
                and ready_b[s][b] <= t
            )
            can_f = (
                f < m
                and ready_f[s][f] is not None
                and ready_f[s][f] <= t
                and inflight < min(m, p - s)  # 1F1B in-flight cap
            )
            if can_b:
                do_b, b_mb = 1, b
                bwd_done[s] += 1
            elif can_f:
                do_f, f_mb = 1, f
                fwd_done[s] += 1
            row.append((do_f, f_mb, do_b, b_mb))
        # propagate availability for slot t+1
        for s in range(p):
            do_f, f_mb, do_b, b_mb = row[s]
            if do_f and s + 1 < p:
                ready_f[s + 1][f_mb] = t + 1
            if do_b and s - 1 >= 0:
                ready_b[s - 1][b_mb] = t + 1
        rows.append(row)
        t += 1
    if any(bwd_done[s] < m for s in range(p)):
        raise RuntimeError(f"1F1B schedule did not converge (m={m}, p={p})")
    tab = np.asarray(rows, np.int32)  # [T, P, 4]
    return tab[..., 0], tab[..., 1], tab[..., 2], tab[..., 3]


def one_f_one_b(
    stage_fn: Callable[..., jax.Array],
    loss_fn: Callable[[jax.Array], jax.Array],
    params,
    xmb: jax.Array,
    axis: str = "pp",
):
    """Manual 1F1B pipeline training step (per-shard fn, inside shard_map).

    Args:
      stage_fn: ``(stage_params, x) -> y`` for this member's stage; x/y are
        one microbatch ``[B_mb, ...]`` with matching shapes across stages.
      loss_fn: ``y -> scalar`` applied to the LAST stage's outputs, summed
        over microbatches.
      params: THIS stage's parameter pytree (already sharded by stage).
      xmb: ``[M, B_mb, ...]`` microbatches (consumed by stage 0).

    Returns ``(loss, d_params)``: total loss (replicated over pp) and this
    stage's parameter cotangents. Live stashed state per stage is bounded by
    min(M, P - s) microbatch INPUTS (buffers are allocated at the uniform
    SPMD bound: a min(M,P)-slot stash + a min(M,P+1)-slot inbound queue of
    single microbatches) — the 1F1B liveness profile, vs autodiff-GPipe
    whose residual liveness grows with M.

    Thin adapter over :func:`pipeline_train` (no aux channel, no loss
    parameters, input cotangents discarded).
    """
    def stage2(p_, x):
        return stage_fn(p_, x), jnp.zeros((), jnp.float32)

    def loss2(_lp, y, _tgt):
        return loss_fn(y)

    m = xmb.shape[0]
    total, _loss, dparams, _dlp, _dxmb = pipeline_train(
        stage2, loss2, params, {}, xmb, jnp.zeros((m, 1), jnp.float32),
        axis, aux_weight=0.0,
    )
    return total, dparams


# ---------------------------------------------------------------------------
# Interleaved 1F1B (virtual pipeline chunks): each device holds V model
# chunks, so the pipeline has L = P*V logical stages and the warm-up/drain
# bubble shrinks by ~V (each ramp slot is 1/V of a device's layer budget).
# The reference's PP story is one-sided activation sends with zero compute
# occupancy (lite-ep/README.md:24); here the analog stays "two ppermutes per
# slot" because of the stage numbering below — interleaving adds no new
# communication structure, only a denser static schedule.


def _simulate_interleaved(m: int, p: int, v: int, policy: str = "best"):
    """Slot-synchronous interleaved 1F1B schedule builder.

    Chunk ``c`` on device ``s`` is global stage ``g = c*p + s`` of ``L = p*v``
    stages. Every forward hop g -> g+1 is then a ring ``+1`` hop over the pp
    axis (the chunk wrap (c, p-1) -> (c+1, 0) included) and every backward
    hop a ring ``-1`` hop, so the runtime needs exactly one forward and one
    backward wire regardless of V.

    Policies (one op per device per slot, like :func:`_simulate_1f1b`):

    * ``greedy`` — backward-first in Megatron order preference; forwards
      choose the ready candidate earliest in Megatron's interleaved order
      ``(mb//p, c, mb%p)``, capped per chunk at ``min(m, (v-1-c)*p + p - s)``
      in-flight (= downstream stages + 1, the interleaved generalization of
      the classic ``p - s`` cap).
    * ``strict`` — the Megatron static schedule: ``2(p-s-1) + (v-1)p + 1``
      warm-up forwards in strict order, then backward-preferred alternation,
      idling when the next op in order isn't ready.
    * ``best`` (default) — build both and keep the shorter table.

    Queue/stash slots are allocated by a free-list here at build time, so the
    runtime's ring buffers are plain static-size arrays with precomputed
    bank/read indices. Returns a dict of [T, P] int32 tables + capacities.
    """
    if policy == "best":
        cands = [_simulate_interleaved(m, p, v, pol)
                 for pol in ("greedy", "strict")]
        cands = [c for c in cands if c is not None]
        if not cands:
            raise RuntimeError(
                f"interleaved 1F1B schedule did not converge "
                f"(m={m}, p={p}, v={v})"
            )
        return min(cands, key=lambda c: c["do_f"].shape[0])
    L = p * v
    fwd_done = [[0] * v for _ in range(p)]
    bwd_done = [[0] * v for _ in range(p)]
    ready_f = [[[None] * m for _ in range(v)] for _ in range(p)]
    ready_b = [[[None] * m for _ in range(v)] for _ in range(p)]
    for mb in range(m):
        ready_f[0][0][mb] = 0

    class _Alloc:
        def __init__(self):
            self.used = set()
            self.high = 0

        def get(self):
            i = 0
            while i in self.used:
                i += 1
            self.used.add(i)
            self.high = max(self.high, i + 1)
            return i

        def put(self, i):
            self.used.discard(i)

    qf_a = [_Alloc() for _ in range(p)]
    qb_a = [_Alloc() for _ in range(p)]
    st_a = [_Alloc() for _ in range(p)]
    qf_slot = [[[None] * m for _ in range(v)] for _ in range(p)]
    qb_slot = [[[None] * m for _ in range(v)] for _ in range(p)]
    st_slot = [[[None] * m for _ in range(v)] for _ in range(p)]

    # Megatron interleaved op order per device: microbatches in groups of p,
    # chunks inner-sequenced within the group; backwards mirror with chunks
    # reversed (deepest drains first).
    fseq = sorted(
        ((mb // p, c, mb % p), c, mb) for c in range(v) for mb in range(m)
    )
    bseq = sorted(
        ((mb // p, v - 1 - c, mb % p), c, mb)
        for c in range(v)
        for mb in range(m)
    )
    fi, bi = [0] * p, [0] * p
    warm = [min(2 * (p - s - 1) + (v - 1) * p + 1, m * v) for s in range(p)]

    def _f_ready(s, c, f, t):
        if s == 0 and c == 0:
            return True
        return ready_f[s][c][f] is not None and ready_f[s][c][f] <= t

    def _b_ready(s, c, b, t):
        if fwd_done[s][c] <= b:
            return False
        if s == p - 1 and c == v - 1:
            return True
        return ready_b[s][c][b] is not None and ready_b[s][c][b] <= t

    def _pick(s, t):
        """Returns ('f'|'b', chunk) or None for this device this slot."""
        if policy == "strict":
            nf = fseq[fi[s]] if fi[s] < m * v else None
            nb = bseq[bi[s]] if bi[s] < m * v else None
            if fi[s] >= warm[s] and nb and _b_ready(s, nb[1], nb[2], t):
                return "b", nb[1]
            if nf and _f_ready(s, nf[1], nf[2], t):
                return "f", nf[1]
            return None
        cand_b = []
        for c in range(v):
            b = bwd_done[s][c]
            if b < m and _b_ready(s, c, b, t):
                cand_b.append(((b // p, -c, b % p), c))
        if cand_b:
            return "b", min(cand_b)[1]
        cand_f = []
        for c in range(v):
            f = fwd_done[s][c]
            if f >= m or not _f_ready(s, c, f, t):
                continue
            cap = min(m, (v - 1 - c) * p + (p - s))
            if fwd_done[s][c] - bwd_done[s][c] >= cap:
                continue
            cand_f.append(((f // p, c, f % p), c))
        if cand_f:
            return "f", min(cand_f)[1]
        return None

    rows, qf_banks, qb_banks = [], [], []
    next_qf_bank = [-1] * p
    next_qb_bank = [-1] * p
    t = 0
    limit = 8 * (v * m + p) + 16
    while (
        any(bwd_done[s][c] < m for s in range(p) for c in range(v))
        and t < limit
    ):
        qf_banks.append(next_qf_bank)
        qb_banks.append(next_qb_bank)
        next_qf_bank = [-1] * p
        next_qb_bank = [-1] * p
        row = []
        for s in range(p):
            do_f = f_c = f_mb = st_put = 0
            do_b = b_c = b_mb = st_get = 0
            f_src = b_src = -1
            pick = _pick(s, t)
            if pick and pick[0] == "b":
                c = pick[1]
                b = bwd_done[s][c]
                do_b, b_c, b_mb = 1, c, b
                bwd_done[s][c] += 1
                bi[s] += 1
                st_get = st_slot[s][c][b]
                st_a[s].put(st_get)
                g = c * p + s
                if g < L - 1:
                    b_src = qb_slot[s][c][b]
                    qb_a[s].put(b_src)
                if g > 0:
                    d = (s - 1) % p
                    c2 = c if s > 0 else c - 1
                    a = qb_a[d].get()
                    qb_slot[d][c2][b] = a
                    ready_b[d][c2][b] = t + 1
                    next_qb_bank[d] = a
            elif pick and pick[0] == "f":
                c = pick[1]
                f = fwd_done[s][c]
                do_f, f_c, f_mb = 1, c, f
                fwd_done[s][c] += 1
                fi[s] += 1
                if not (s == 0 and c == 0):
                    f_src = qf_slot[s][c][f]
                    qf_a[s].put(f_src)
                st_put = st_a[s].get()
                st_slot[s][c][f] = st_put
                g = c * p + s
                if g < L - 1:
                    d = (s + 1) % p
                    c2 = c if s < p - 1 else c + 1
                    a = qf_a[d].get()
                    qf_slot[d][c2][f] = a
                    ready_f[d][c2][f] = t + 1
                    next_qf_bank[d] = a
            row.append(
                (do_f, f_c, f_mb, f_src, st_put, do_b, b_c, b_mb, b_src, st_get)
            )
        rows.append(row)
        t += 1
    if any(bwd_done[s][c] < m for s in range(p) for c in range(v)):
        return None
    tab = np.asarray(rows, np.int32)  # [T, P, 10]
    return {
        "do_f": tab[..., 0],
        "f_c": tab[..., 1],
        "f_mb": tab[..., 2],
        "f_src": tab[..., 3],
        "st_put": tab[..., 4],
        "do_b": tab[..., 5],
        "b_c": tab[..., 6],
        "b_mb": tab[..., 7],
        "b_src": tab[..., 8],
        "st_get": tab[..., 9],
        "qf_bank": np.asarray(qf_banks, np.int32),
        "qb_bank": np.asarray(qb_banks, np.int32),
        "n_qf": max(1, max(a.high for a in qf_a)),
        "n_qb": max(1, max(a.high for a in qb_a)),
        "n_stash": max(1, max(a.high for a in st_a)),
    }


def interleaved_1f1b(
    stage_fn: Callable[..., jax.Array],
    loss_fn: Callable[[jax.Array], jax.Array],
    params,
    xmb: jax.Array,
    n_chunks: int,
    axis: str = "pp",
):
    """Interleaved-schedule pipeline training step (per-shard, in shard_map).

    Args:
      stage_fn: ``(chunk_params, x) -> y`` for ONE model chunk; x/y are one
        microbatch ``[B_mb, ...]`` with matching shapes across all chunks.
      loss_fn: ``y -> scalar`` applied to the final stage's outputs.
      params: this device's chunk parameters STACKED on a leading axis of
        size ``n_chunks``: leaf ``[V, ...]`` where chunk ``c`` holds global
        stage ``c*P + s`` (Megatron-interleaved assignment).
      xmb: ``[M, B_mb, ...]`` microbatches (consumed by stage 0 = chunk 0 of
        device 0).
      n_chunks: V, the virtual-chunk count per device.

    Returns ``(loss, d_params)`` with d_params stacked like ``params``.
    The warm-up/drain bubble is ~1/V of :func:`one_f_one_b`'s in wall-clock
    terms (each slot runs one chunk = 1/V of a device's layers).
    """
    p = lax.axis_size(axis)
    s = lax.axis_index(axis)
    m = xmb.shape[0]
    v = int(n_chunks)
    if m < 1 or v < 1:
        raise ValueError(f"need >=1 microbatch and >=1 chunk (m={m}, v={v})")
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(params)}
    if leading != {v}:
        raise ValueError(
            f"params leaves must stack {v} chunks on axis 0; got leading "
            f"dims {sorted(leading)}"
        )
    sched = _simulate_interleaved(m, int(p), v)
    T = sched["do_f"].shape[0]
    tabs = {k: jnp.asarray(sched[k]) for k in sched if k.startswith(("do_", "f_", "b_", "st_", "qf_", "qb_"))}
    n_qf, n_qb, n_st = sched["n_qf"], sched["n_qb"], sched["n_stash"]
    fwd_perm = ppermute_pairs(p, 1)
    bwd_perm = ppermute_pairs(p, -1)

    mb_shape = xmb.shape[1:]
    zeros_mb = jnp.zeros(mb_shape, xmb.dtype)
    chunk_zero = jax.tree.map(lambda a: jnp.zeros_like(a[0]), params)

    def _chunk(tree_v, c):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, c, axis=0, keepdims=False),
            tree_v,
        )

    def step(carry, t):
        stash, qf, qb, fwd_in, bwd_in, dparams, loss_acc = carry

        # ---- bank this slot's wire arrivals into their precomputed slots
        qa = tabs["qf_bank"][t, s]
        qai = jnp.clip(qa, 0, n_qf - 1)
        cur = lax.dynamic_index_in_dim(qf, qai, axis=0, keepdims=False)
        qf = lax.dynamic_update_index_in_dim(
            qf, jnp.where(qa >= 0, fwd_in, cur), qai, axis=0
        )
        ba = tabs["qb_bank"][t, s]
        bai = jnp.clip(ba, 0, n_qb - 1)
        curb = lax.dynamic_index_in_dim(qb, bai, axis=0, keepdims=False)
        qb = lax.dynamic_update_index_in_dim(
            qb, jnp.where(ba >= 0, bwd_in, curb), bai, axis=0
        )

        do_f = tabs["do_f"][t, s]
        f_c = tabs["f_c"][t, s]
        f_mb = tabs["f_mb"][t, s]
        f_src = tabs["f_src"][t, s]
        st_put = tabs["st_put"][t, s]
        do_b = tabs["do_b"][t, s]
        b_c = tabs["b_c"][t, s]
        b_mb = tabs["b_mb"][t, s]
        b_src = tabs["b_src"][t, s]
        st_get = tabs["st_get"][t, s]

        def fwd(_):
            x_q = lax.dynamic_index_in_dim(
                qf, jnp.clip(f_src, 0, n_qf - 1), axis=0, keepdims=False
            )
            x_0 = lax.dynamic_index_in_dim(xmb, f_mb, axis=0, keepdims=False)
            x = jnp.where(f_src < 0, x_0, x_q)
            y = stage_fn(_chunk(params, f_c), x)
            st = lax.dynamic_update_index_in_dim(stash, x, st_put, axis=0)
            return y, st

        y_out, stash = lax.cond(
            do_f == 1, fwd, lambda _: (zeros_mb, stash), None
        )

        def bwd(_):
            x = lax.dynamic_index_in_dim(stash, st_get, axis=0, keepdims=False)
            pc = _chunk(params, b_c)
            y, vjp = jax.vjp(stage_fn, pc, x)
            g_q = lax.dynamic_index_in_dim(
                qb, jnp.clip(b_src, 0, n_qb - 1), axis=0, keepdims=False
            )
            # b_src < 0 marks the final logical stage: cotangent comes from
            # the loss instead of the wire.
            lv, gl = jax.value_and_grad(loss_fn)(y)
            gy = jnp.where(b_src < 0, gl, g_q.astype(y.dtype))
            dp, dx = vjp(gy)
            lval = jnp.where(b_src < 0, lv, 0.0).astype(jnp.float32)
            return dp, dx, lval

        dp, dx_out, lval = lax.cond(
            do_b == 1,
            bwd,
            lambda _: (chunk_zero, zeros_mb, jnp.float32(0.0)),
            None,
        )

        def _acc(acc, d):
            cur = lax.dynamic_index_in_dim(acc, b_c, axis=0, keepdims=False)
            return lax.dynamic_update_index_in_dim(acc, cur + d, b_c, axis=0)

        dparams = jax.tree.map(_acc, dparams, dp)
        loss_acc = loss_acc + lval

        fwd_next = lax.ppermute(y_out, axis, fwd_perm)
        bwd_next = lax.ppermute(dx_out, axis, bwd_perm)
        return (stash, qf, qb, fwd_next, bwd_next, dparams, loss_acc), None

    stash0 = jnp.zeros((n_st,) + mb_shape, xmb.dtype)
    qf0 = jnp.zeros((n_qf,) + mb_shape, xmb.dtype)
    qb0 = jnp.zeros((n_qb,) + mb_shape, xmb.dtype)
    d0 = jax.tree.map(jnp.zeros_like, params)
    (stash, _, _, _, _, dparams, loss_acc), _ = lax.scan(
        step,
        (stash0, qf0, qb0, zeros_mb, zeros_mb, d0, jnp.float32(0.0)),
        jnp.arange(T),
    )
    return lax.psum(loss_acc, axis), dparams


# ---------------------------------------------------------------------------
# Full-model manual-schedule training: the 1F1B above trains the pipeline
# BODY; a real model also has parameters outside it — an embedding feeding
# stage 0 and a loss head consuming the last stage — plus per-stage scalar
# side losses (MoE aux/z). pipeline_train closes those three gaps so a
# whole transformer can run on the manual schedule: it returns the input
# cotangents d(xmb) (backprop them through the embedding outside), the
# loss-side parameter grads, and threads an aux channel whose gradient
# flows into the stage parameters via the vjp cotangent.


def pipeline_train(
    stage_fn: Callable[..., Tuple[jax.Array, jax.Array]],
    loss_fn: Callable[..., jax.Array],
    params,
    loss_params,
    xmb: jax.Array,
    ymb,
    axis: str = "pp",
    aux_weight: float = 1.0,
    uniform: bool = False,
):
    """Manual 1F1B training step with boundary gradients (in shard_map).

    Args:
      stage_fn: ``(stage_params, x) -> (y, aux)`` — one microbatch through
        this member's stage; ``aux`` is a scalar side loss (0 if unused).
      loss_fn: ``(loss_params, y, target) -> scalar`` applied to the LAST
        stage's outputs, summed over microbatches; ``target`` is that
        microbatch's slice of ``ymb``.
      params: THIS stage's parameter pytree.
      loss_params: the loss-side parameters (final norm, unembedding, ...);
        passed on every member (uniform SPMD), differentiated only where
        the last stage computes the loss.
      xmb: ``[M, B_mb, ...]`` microbatches (consumed by stage 0).
      ymb: per-microbatch loss targets, a pytree with leading dim M
        (labels, target logits, masks, ...), replicated across members.
      aux_weight: weight of the summed aux losses in the total.
      uniform: run every slot's forward/backward on every member and mask the
        results, instead of gating them behind ``lax.cond``. REQUIRED when
        stage_fn contains collectives without replica groups — ``ppermute``
        (ring-attention CP): XLA lowers collective-permute with *global*
        source-target pairs, so members on stages whose cond predicate is
        false never post their sends and the matched members deadlock (or
        read garbage on fabrics with static schedules). psum/all_to_all are
        safe under cond because their replica groups never cross the pp axis.
        Uniform mode is the same select-not-branch discipline
        :func:`gpipe_spmd` uses; it costs ~(P-1)/M extra compute (idle ramp
        slots run masked work instead of skipping).

    Returns ``(total, loss, dparams, d_loss_params, d_xmb)``:
      total — loss + aux_weight * sum(aux), replicated over pp;
      loss — the loss_fn sum alone (no aux), replicated over pp;
      dparams — this stage's parameter cotangents (aux grads included);
      d_loss_params — cotangents of loss_params, replicated over pp;
      d_xmb — ``[M, B_mb, ...]`` cotangents of the stage-0 inputs,
      replicated over pp (backprop them through the embedding).
    """
    p = lax.axis_size(axis)
    s = lax.axis_index(axis)
    m = xmb.shape[0]
    slots = min(m, p)
    qslots = min(m, p + 1)
    np_do_f, np_f_mb, np_do_b, np_b_mb = _simulate_1f1b(m, int(p))
    n_slots = np_do_f.shape[0]
    np_arr = np.zeros_like(np_do_f)
    np_arr[1:, 1:] = np_do_f[:-1, :-1]
    np_arr_idx = np.zeros_like(np_do_f)
    np_arr_idx[1:] = np.cumsum(np_arr, axis=0)[:-1]
    do_f_t, f_mb_t = jnp.asarray(np_do_f), jnp.asarray(np_f_mb)
    do_b_t, b_mb_t = jnp.asarray(np_do_b), jnp.asarray(np_b_mb)
    arr_t, arr_idx_t = jnp.asarray(np_arr), jnp.asarray(np_arr_idx)
    fwd_perm = ppermute_pairs(p, 1)
    bwd_perm = ppermute_pairs(p, -1)

    mb_shape = xmb.shape[1:]
    zeros_mb = jnp.zeros(mb_shape, xmb.dtype)
    zero_lp = jax.tree.map(jnp.zeros_like, loss_params)
    is_last = s == p - 1
    is_first = s == 0

    def step(carry, t):
        (stash, queue, fwd_in, bwd_in, dparams, dlp, dx_buf, loss_acc,
         aux_acc) = carry
        do_f = do_f_t[t, s]
        f_mb = f_mb_t[t, s]
        do_b = do_b_t[t, s]
        b_mb = b_mb_t[t, s]

        arrived = arr_t[t, s]
        bank_at = arr_idx_t[t, s] % qslots
        cur = lax.dynamic_index_in_dim(queue, bank_at, axis=0, keepdims=False)
        banked = jnp.where(arrived == 1, fwd_in, cur)
        queue = lax.dynamic_update_index_in_dim(queue, banked, bank_at, axis=0)

        def fwd(_):
            x = jnp.where(
                is_first,
                lax.dynamic_index_in_dim(xmb, f_mb, axis=0, keepdims=False),
                lax.dynamic_index_in_dim(
                    queue, f_mb % qslots, axis=0, keepdims=False
                ),
            )
            y, aux = stage_fn(params, x)
            st_idx = f_mb % slots
            cur_st = lax.dynamic_index_in_dim(
                stash, st_idx, axis=0, keepdims=False
            )
            st = lax.dynamic_update_index_in_dim(
                stash, jnp.where(do_f == 1, x, cur_st), st_idx, axis=0
            )
            return y, st, aux.astype(jnp.float32)

        if uniform:
            # select-not-branch: the stage (and any ppermute inside it) runs
            # on every member every slot; tables only gate what is kept
            y_raw, stash, aux_raw = fwd(None)
            y_out = jnp.where(do_f == 1, y_raw, zeros_mb)
            aux_step = jnp.where(do_f == 1, aux_raw, 0.0)
        else:
            y_out, stash, aux_step = lax.cond(
                do_f == 1, fwd,
                lambda _: (zeros_mb, stash, jnp.zeros((), jnp.float32)),
                None,
            )
        aux_acc = aux_acc + aux_step

        def bwd(_):
            x = lax.dynamic_index_in_dim(stash, b_mb % slots, axis=0,
                                         keepdims=False)
            (y, _aux), vjp = jax.vjp(stage_fn, params, x)
            tgt = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, b_mb, axis=0,
                                                   keepdims=False),
                ymb,
            )

            # Loss head only where it's real: (P-1)/P of the schedule's
            # backward slots are non-final stages, and the head (unembedding
            # matmul + CE in a transformer) is expensive. The predicate is
            # uniform across every non-pp axis, so collectives inside
            # loss_fn stay matched within their groups.
            def loss_part(_):
                lv_, (g_lp_, gy_) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1)
                )(loss_params, y, tgt)
                return lv_.astype(jnp.float32), g_lp_, gy_.astype(y.dtype)

            def no_loss(_):
                return jnp.zeros((), jnp.float32), zero_lp, jnp.zeros_like(y)

            lval, g_lp, gy_loss = lax.cond(is_last, loss_part, no_loss, None)
            gy = jnp.where(is_last, gy_loss, bwd_in.astype(y.dtype))
            # aux cotangent: d(total)/d(aux) = aux_weight on every stage
            dp, dx = vjp((gy, jnp.asarray(aux_weight, _aux.dtype)))
            return dp, dx, g_lp, lval

        zero_dp = jax.tree.map(jnp.zeros_like, params)
        if uniform:
            dp_raw, dx_raw, g_lp_raw, lval_raw = bwd(None)
            on = do_b == 1
            dp = jax.tree.map(
                lambda a: jnp.where(on, a, jnp.zeros_like(a)), dp_raw
            )
            dx_out = jnp.where(on, dx_raw, zeros_mb)
            g_lp = jax.tree.map(
                lambda a: jnp.where(on, a, jnp.zeros_like(a)), g_lp_raw
            )
            lval = jnp.where(on, lval_raw, 0.0)
        else:
            dp, dx_out, g_lp, lval = lax.cond(
                do_b == 1,
                bwd,
                lambda _: (zero_dp, zeros_mb, zero_lp, jnp.float32(0.0)),
                None,
            )
        dparams = jax.tree.map(jnp.add, dparams, dp)
        dlp = jax.tree.map(jnp.add, dlp, g_lp)
        loss_acc = loss_acc + lval
        # stage 0's dx is the cotangent of xmb[b_mb] (zeros when no bwd ran)
        mb_at = jnp.where(do_b == 1, b_mb, 0)
        curx = lax.dynamic_index_in_dim(dx_buf, mb_at, axis=0, keepdims=False)
        newx = jnp.where((do_b == 1) & is_first, dx_out, curx)
        dx_buf = lax.dynamic_update_index_in_dim(dx_buf, newx, mb_at, axis=0)

        fwd_next = lax.ppermute(y_out, axis, fwd_perm)
        bwd_next = lax.ppermute(dx_out, axis, bwd_perm)
        return (stash, queue, fwd_next, bwd_next, dparams, dlp, dx_buf,
                loss_acc, aux_acc), None

    stash0 = jnp.zeros((slots,) + mb_shape, xmb.dtype)
    queue0 = jnp.zeros((qslots,) + mb_shape, xmb.dtype)
    d0 = jax.tree.map(jnp.zeros_like, params)
    dx0 = jnp.zeros_like(xmb)
    (_, _, _, _, dparams, dlp, dx_buf, loss_acc, aux_acc), _ = lax.scan(
        step,
        (stash0, queue0, zeros_mb, zeros_mb, d0, zero_lp, dx0,
         jnp.float32(0.0), jnp.float32(0.0)),
        jnp.arange(n_slots),
    )
    loss = lax.psum(loss_acc, axis)
    total = loss + aux_weight * lax.psum(aux_acc, axis)
    d_loss_params = jax.tree.map(lambda g: lax.psum(g, axis), dlp)
    d_xmb = lax.psum(
        jnp.where(is_first, dx_buf, jnp.zeros_like(dx_buf)), axis
    )
    return total, loss, dparams, d_loss_params, d_xmb
