"""SPMD pipeline parallelism over the ``pp`` mesh axis.

The reference offers "0 SM PP (with RDMA)" — one-sided activation sends between
pipeline stages with zero compute occupancy (experimental/lite/lite-ep/README.md:24,
tests/elastic/test_pp.py). The TPU-native equivalent: a GPipe schedule written as
a single ``lax.scan`` whose stage-to-stage hand-off is ``lax.ppermute`` over the
``pp`` axis — XLA turns those into async ICI sends that overlap the next
microbatch's compute, which is exactly the zero-SM property (no device compute
spent on communication).

Per-shard function (use inside shard_map). All stages run the same program; a
stage's identity comes from ``lax.axis_index``.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from uccl_tpu.collective.plan import tree_broadcast
from uccl_tpu.utils.topology import ppermute_pairs


def gpipe_spmd(
    stage_fn: Callable[[jax.Array], Tuple[jax.Array, jax.Array]],
    xmb: jax.Array,
    axis: str = "pp",
) -> Tuple[jax.Array, jax.Array]:
    """Run microbatches through the pipeline stages.

    Args:
      stage_fn: per-stage computation ``x -> (y, aux)`` where x/y are one
        microbatch of activations ``[B_mb, ...]`` (same shape in and out) and
        aux is a scalar side-channel (e.g. MoE aux losses), summed over valid
        microbatches.
      xmb: ``[M, B_mb, ...]`` microbatched input activations (the stage-0
        input stream; other stages ignore it).
      axis: the pipeline mesh axis.

    Returns:
      (out ``[M, B_mb, ...]`` final-stage outputs replicated across pp members,
       aux scalar summed over all stages and microbatches, replicated).

    Schedule: step t has stage s working on microbatch ``t - s`` (valid when
    0 <= t-s < M); total ``M + P - 1`` steps; bubble fraction (P-1)/(M+P-1).
    """
    p = lax.axis_size(axis)
    s = lax.axis_index(axis)
    m = xmb.shape[0]
    perm = ppermute_pairs(p, 1)

    def step(carry, t):
        xbuf, outbuf, aux = carry
        fresh = lax.dynamic_index_in_dim(
            xmb, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(s == 0, fresh, xbuf)
        y, aux_step = stage_fn(x_in)
        m_local = t - s
        valid = (m_local >= 0) & (m_local < m)
        aux = aux + jnp.where(valid, aux_step, jnp.zeros_like(aux_step))
        # Collect this stage's output for microbatch t-(p-1); only the last
        # stage's buffer survives the psum below.
        m_out = t - (p - 1)
        idx = jnp.clip(m_out, 0, m - 1)
        cur = lax.dynamic_index_in_dim(outbuf, idx, axis=0, keepdims=False)
        newv = jnp.where((m_out >= 0) & (m_out < m), y, cur)
        outbuf = lax.dynamic_update_index_in_dim(outbuf, newv, idx, axis=0)
        x_next = lax.ppermute(y, axis, perm)
        return (x_next, outbuf, aux), None

    xbuf0 = jnp.zeros_like(xmb[0])
    outbuf0 = jnp.zeros_like(xmb)
    aux0 = jnp.zeros((), jnp.float32)
    (xbuf, outbuf, aux), _ = lax.scan(
        step, (xbuf0, outbuf0, aux0), jnp.arange(m + p - 1)
    )
    # Broadcast the last stage's collected outputs to all pp members so
    # downstream loss code is uniform SPMD — binomial tree (log P rounds of
    # the buffer) instead of a full-buffer psum of mostly zeros.
    out = tree_broadcast(outbuf, axis, root=p - 1)
    aux_total = lax.psum(aux, axis)
    return out, aux_total


# ---------------------------------------------------------------------------
# 1F1B (manual schedule): bounded-activation pipeline training
#
# GPipe above leans on autodiff: jax.grad through the scan stashes every
# microbatch's residuals on every stage (fine with remat, but liveness is
# O(M)). This primitive writes the backward by hand on the classic
# one-forward-one-backward schedule, so a stage never holds more than
# min(M, P - s) stashed microbatch INPUTS (activations are recomputed at
# backward time from the stashed input — the recompute discipline the rest
# of this framework already uses). The schedule table is built statically by
# a slot-synchronous simulator; each scan slot does at most one forward and
# one backward under lax.cond, with activations ppermuting forward and
# cotangents ppermuting backward every slot.


def _simulate_1f1b(m: int, p: int):
    """Slot-synchronous 1F1B schedule. Returns four [T, P] int tables:
    (do_fwd, fwd_mb, do_bwd, bwd_mb) — what stage s runs at slot t.

    Policy per stage: run a backward as soon as a cotangent is available;
    otherwise run the next forward if its input is available. Capping
    in-flight forwards at (P - s) yields the classic 1F1B memory profile.
    """
    fwd_done = [0] * p
    bwd_done = [0] * p
    # activation availability: arrival_slot of mb f at stage s
    ready_f = [[0 if s == 0 else None for _ in range(m)] for s in range(p)]
    ready_b = [[0 if s == p - 1 else None for _ in range(m)] for s in range(p)]
    rows = []
    t = 0
    while any(bwd_done[s] < m for s in range(p)) and t < 4 * (m + p):
        row = []
        for s in range(p):
            do_f, f_mb, do_b, b_mb = 0, 0, 0, 0
            inflight = fwd_done[s] - bwd_done[s]
            b = bwd_done[s]
            f = fwd_done[s]
            can_b = (
                b < m
                and b < fwd_done[s]  # its own fwd must have run
                and ready_b[s][b] is not None
                and ready_b[s][b] <= t
            )
            can_f = (
                f < m
                and ready_f[s][f] is not None
                and ready_f[s][f] <= t
                and inflight < min(m, p - s)  # 1F1B in-flight cap
            )
            if can_b:
                do_b, b_mb = 1, b
                bwd_done[s] += 1
            elif can_f:
                do_f, f_mb = 1, f
                fwd_done[s] += 1
            row.append((do_f, f_mb, do_b, b_mb))
        # propagate availability for slot t+1
        for s in range(p):
            do_f, f_mb, do_b, b_mb = row[s]
            if do_f and s + 1 < p:
                ready_f[s + 1][f_mb] = t + 1
            if do_b and s - 1 >= 0:
                ready_b[s - 1][b_mb] = t + 1
        rows.append(row)
        t += 1
    if any(bwd_done[s] < m for s in range(p)):
        raise RuntimeError(f"1F1B schedule did not converge (m={m}, p={p})")
    tab = np.asarray(rows, np.int32)  # [T, P, 4]
    return tab[..., 0], tab[..., 1], tab[..., 2], tab[..., 3]


def one_f_one_b(
    stage_fn: Callable[..., jax.Array],
    loss_fn: Callable[[jax.Array], jax.Array],
    params,
    xmb: jax.Array,
    axis: str = "pp",
):
    """Manual 1F1B pipeline training step (per-shard fn, inside shard_map).

    Args:
      stage_fn: ``(stage_params, x) -> y`` for this member's stage; x/y are
        one microbatch ``[B_mb, ...]`` with matching shapes across stages.
      loss_fn: ``y -> scalar`` applied to the LAST stage's outputs, summed
        over microbatches.
      params: THIS stage's parameter pytree (already sharded by stage).
      xmb: ``[M, B_mb, ...]`` microbatches (consumed by stage 0).

    Returns ``(loss, d_params)``: total loss (replicated over pp) and this
    stage's parameter cotangents. Live stashed state per stage is bounded by
    min(M, P - s) microbatch INPUTS (buffers are allocated at the uniform
    SPMD bound: a min(M,P)-slot stash + a min(M,P+1)-slot inbound queue of
    single microbatches) — the 1F1B liveness profile, vs autodiff-GPipe
    whose residual liveness grows with M.
    """
    p = lax.axis_size(axis)
    s = lax.axis_index(axis)
    m = xmb.shape[0]
    slots = min(m, p)  # stash ring size (>= the per-stage in-flight cap)
    qslots = min(m, p + 1)  # inbound activation queue (lag bound is p)
    np_do_f, np_f_mb, np_do_b, np_b_mb = _simulate_1f1b(m, int(p))
    # Arrival bookkeeping (static): an activation emitted by stage s-1 at
    # slot t-1 lands in stage s's wire register at slot t and is banked into
    # the inbound queue — a stage may legally sit on several unconsumed
    # inputs while it prioritizes backwards, so a single register would drop
    # them.
    n_slots = np_do_f.shape[0]
    np_arr = np.zeros_like(np_do_f)
    np_arr[1:, 1:] = np_do_f[:-1, :-1]
    np_arr_idx = np.zeros_like(np_do_f)
    np_arr_idx[1:] = np.cumsum(np_arr, axis=0)[:-1]
    do_f_t, f_mb_t = jnp.asarray(np_do_f), jnp.asarray(np_f_mb)
    do_b_t, b_mb_t = jnp.asarray(np_do_b), jnp.asarray(np_b_mb)
    arr_t, arr_idx_t = jnp.asarray(np_arr), jnp.asarray(np_arr_idx)
    fwd_perm = ppermute_pairs(p, 1)
    bwd_perm = ppermute_pairs(p, -1)

    mb_shape = xmb.shape[1:]
    zeros_mb = jnp.zeros(mb_shape, xmb.dtype)

    def step(carry, t):
        stash, queue, fwd_in, bwd_in, dparams, loss_acc = carry
        do_f = do_f_t[t, s]
        f_mb = f_mb_t[t, s]
        do_b = do_b_t[t, s]
        b_mb = b_mb_t[t, s]

        # ---- bank the wire register into the inbound queue on arrival
        arrived = arr_t[t, s]
        bank_at = arr_idx_t[t, s] % qslots
        cur = lax.dynamic_index_in_dim(queue, bank_at, axis=0, keepdims=False)
        banked = jnp.where(arrived == 1, fwd_in, cur)
        queue = lax.dynamic_update_index_in_dim(queue, banked, bank_at, axis=0)

        # ---- forward slot: consume input, stash it, emit activation
        def fwd(_):
            x = jnp.where(
                s == 0,
                lax.dynamic_index_in_dim(xmb, f_mb, axis=0, keepdims=False),
                lax.dynamic_index_in_dim(
                    queue, f_mb % qslots, axis=0, keepdims=False
                ),
            )
            y = stage_fn(params, x)
            st = lax.dynamic_update_index_in_dim(stash, x, f_mb % slots, axis=0)
            return y, st

        y_out, stash = lax.cond(
            do_f == 1, fwd, lambda _: (zeros_mb, stash), None
        )

        # ---- backward slot: recompute from the stashed input, push grads
        def bwd(_):
            x = lax.dynamic_index_in_dim(stash, b_mb % slots, axis=0,
                                         keepdims=False)
            y, vjp = jax.vjp(stage_fn, params, x)
            # last stage sources its cotangent from the loss; others from
            # the cotangent that arrived over the wire
            gy = jnp.where(
                s == p - 1, jax.grad(loss_fn)(y), bwd_in.astype(y.dtype)
            )
            dp, dx = vjp(gy)
            lval = jnp.where(s == p - 1, loss_fn(y), 0.0)
            return dp, dx, lval

        zero_dp = jax.tree.map(jnp.zeros_like, params)
        dp, dx_out, lval = lax.cond(
            do_b == 1, bwd, lambda _: (zero_dp, zeros_mb, jnp.float32(0.0)),
            None,
        )
        dparams = jax.tree.map(jnp.add, dparams, dp)
        loss_acc = loss_acc + lval

        fwd_next = lax.ppermute(y_out, axis, fwd_perm)
        bwd_next = lax.ppermute(dx_out, axis, bwd_perm)
        return (stash, queue, fwd_next, bwd_next, dparams, loss_acc), None

    stash0 = jnp.zeros((slots,) + mb_shape, xmb.dtype)
    queue0 = jnp.zeros((qslots,) + mb_shape, xmb.dtype)
    d0 = jax.tree.map(jnp.zeros_like, params)
    (stash, _, _, _, dparams, loss_acc), _ = lax.scan(
        step,
        (stash0, queue0, zeros_mb, zeros_mb, d0, jnp.float32(0.0)),
        jnp.arange(n_slots),
    )
    return lax.psum(loss_acc, axis), dparams
