"""SPMD pipeline parallelism over the ``pp`` mesh axis.

The reference offers "0 SM PP (with RDMA)" — one-sided activation sends between
pipeline stages with zero compute occupancy (experimental/lite/lite-ep/README.md:24,
tests/elastic/test_pp.py). The TPU-native equivalent: a GPipe schedule written as
a single ``lax.scan`` whose stage-to-stage hand-off is ``lax.ppermute`` over the
``pp`` axis — XLA turns those into async ICI sends that overlap the next
microbatch's compute, which is exactly the zero-SM property (no device compute
spent on communication).

Per-shard function (use inside shard_map). All stages run the same program; a
stage's identity comes from ``lax.axis_index``.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.utils.topology import ppermute_pairs


def gpipe_spmd(
    stage_fn: Callable[[jax.Array], Tuple[jax.Array, jax.Array]],
    xmb: jax.Array,
    axis: str = "pp",
) -> Tuple[jax.Array, jax.Array]:
    """Run microbatches through the pipeline stages.

    Args:
      stage_fn: per-stage computation ``x -> (y, aux)`` where x/y are one
        microbatch of activations ``[B_mb, ...]`` (same shape in and out) and
        aux is a scalar side-channel (e.g. MoE aux losses), summed over valid
        microbatches.
      xmb: ``[M, B_mb, ...]`` microbatched input activations (the stage-0
        input stream; other stages ignore it).
      axis: the pipeline mesh axis.

    Returns:
      (out ``[M, B_mb, ...]`` final-stage outputs replicated across pp members,
       aux scalar summed over all stages and microbatches, replicated).

    Schedule: step t has stage s working on microbatch ``t - s`` (valid when
    0 <= t-s < M); total ``M + P - 1`` steps; bubble fraction (P-1)/(M+P-1).
    """
    p = lax.axis_size(axis)
    s = lax.axis_index(axis)
    m = xmb.shape[0]
    perm = ppermute_pairs(p, 1)

    def step(carry, t):
        xbuf, outbuf, aux = carry
        fresh = lax.dynamic_index_in_dim(
            xmb, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(s == 0, fresh, xbuf)
        y, aux_step = stage_fn(x_in)
        m_local = t - s
        valid = (m_local >= 0) & (m_local < m)
        aux = aux + jnp.where(valid, aux_step, jnp.zeros_like(aux_step))
        # Collect this stage's output for microbatch t-(p-1); only the last
        # stage's buffer survives the psum below.
        m_out = t - (p - 1)
        idx = jnp.clip(m_out, 0, m - 1)
        cur = lax.dynamic_index_in_dim(outbuf, idx, axis=0, keepdims=False)
        newv = jnp.where((m_out >= 0) & (m_out < m), y, cur)
        outbuf = lax.dynamic_update_index_in_dim(outbuf, newv, idx, axis=0)
        x_next = lax.ppermute(y, axis, perm)
        return (x_next, outbuf, aux), None

    xbuf0 = jnp.zeros_like(xmb[0])
    outbuf0 = jnp.zeros_like(xmb)
    aux0 = jnp.zeros((), jnp.float32)
    (xbuf, outbuf, aux), _ = lax.scan(
        step, (xbuf0, outbuf0, aux0), jnp.arange(m + p - 1)
    )
    # Broadcast the last stage's collected outputs (and every stage's aux) to
    # all pp members so downstream loss code is uniform SPMD.
    out = lax.psum(jnp.where(s == p - 1, outbuf, jnp.zeros_like(outbuf)), axis)
    aux_total = lax.psum(aux, axis)
    return out, aux_total
