"""Parallelism layer: device meshes, sharding helpers, ring attention, Ulysses,
pipeline parallelism.

The reference is a comm substrate under torch parallelism (SURVEY.md §2.6); on TPU
the mesh + sharding annotations ARE the parallelism API, so this package owns them.
"""

from uccl_tpu.parallel.mesh import MeshConfig, make_mesh, get_mesh, AXIS
from uccl_tpu.parallel import sharding

__all__ = ["MeshConfig", "make_mesh", "get_mesh", "AXIS", "sharding"]
