"""Device-mesh construction and the framework's canonical parallel axes.

The reference scales through NCCL communicators created per torch process group;
the TPU-native analog is a single `jax.sharding.Mesh` whose named axes carry every
parallelism strategy (SURVEY.md §2.6 inventory):

* ``dp`` — data parallel (reference: examples/ddp_train.py over the NCCL plugin)
* ``pp`` — pipeline parallel (reference: lite-ep 0-SM PP primitives)
* ``cp`` — context/sequence parallel, ring attention + Ulysses
  (reference: lite-ep 0-SM CP primitive; here first-class)
* ``tp`` — tensor parallel (reference: Megatron over the plugin)
* expert parallel (``ep``) runs over the combined (``dp``, ``cp``) axes — the
  DeepSeek-style layout where EP reuses the data-parallel world, matching the
  reference's EP ranks == torch.distributed world (ep/bench/buffer.py).

Axis order is ('pp','dp','cp','tp') with ``tp`` innermost so the most
latency-sensitive collectives ride the shortest ICI hops.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


class AXIS:
    """Canonical mesh-axis names."""

    PP = "pp"
    DP = "dp"
    CP = "cp"
    TP = "tp"
    ALL: Tuple[str, ...] = ("pp", "dp", "cp", "tp")
    # Expert parallelism runs over the flattened data+context world.
    EP: Tuple[str, ...] = ("dp", "cp")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes of each parallel axis. Product must equal the device count."""

    pp: int = 1
    dp: int = 1
    cp: int = 1
    tp: int = 1

    @property
    def size(self) -> int:
        return self.pp * self.dp * self.cp * self.tp

    @property
    def ep(self) -> int:
        """Expert-parallel world size (dp × cp)."""
        return self.dp * self.cp

    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.pp, self.dp, self.cp, self.tp)

    @staticmethod
    def auto(n_devices: int, want_pp: bool = True, want_cp: bool = True) -> "MeshConfig":
        """Pick a balanced config for n devices, spending factors in priority
        order tp → dp → pp → cp, two-way at a time (mirrors how users of the
        reference lay Megatron TP innermost on NVLink)."""
        sizes = {"pp": 1, "dp": 1, "cp": 1, "tp": 1}
        order = ["tp", "dp"] + (["pp"] if want_pp else []) + (["cp"] if want_cp else [])
        remaining = n_devices
        i = 0
        # Round-robin factors of 2 over the axes; any odd residue folds into dp.
        while remaining > 1:
            if remaining % 2 == 0:
                sizes[order[i]] *= 2
                remaining //= 2
            else:
                sizes["dp"] *= remaining
                remaining = 1
            i = (i + 1) % len(order)
        cfg = MeshConfig(**sizes)
        assert cfg.size == n_devices, (cfg, n_devices)
        return cfg


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the canonical 4-axis mesh.

    With no config, all visible devices land on ``dp``. Devices are laid out in
    their default (topology-sorted) order so contiguous ``tp`` groups occupy
    adjacent ICI neighbors.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if config is None:
        config = MeshConfig(dp=n)
    if config.size != n:
        raise ValueError(f"mesh config {config} needs {config.size} devices, have {n}")
    dev_array = np.asarray(devices).reshape(config.axis_sizes())
    return Mesh(dev_array, AXIS.ALL)


_default_mesh: Optional[Mesh] = None


def set_mesh(mesh: Mesh) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_mesh() -> Mesh:
    """The process-wide default mesh, creating a dp-only mesh lazily."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def mesh_axis_size(mesh: Mesh, axes) -> int:
    if isinstance(axes, str):
        axes = (axes,)
    return int(math.prod(mesh.shape[a] for a in axes))
