"""PartitionSpec/NamedSharding helpers used across the framework.

These are the TPU-native contract that replaces the reference's per-rank tensor
handles: instead of each rank holding a local torch tensor and calling NCCL, arrays
carry shardings and XLA inserts the collectives.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uccl_tpu.parallel.mesh import AXIS


def spec(*axes) -> P:
    """PartitionSpec from axis names (None entries = replicated dims)."""
    return P(*axes)


def named(mesh: Mesh, pspec: P) -> NamedSharding:
    return NamedSharding(mesh, pspec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# Canonical activation layout: [batch, seq, hidden] with batch over dp, seq over cp
# (context parallel), hidden replicated (tp shards weights, not activations).
def activation_spec(seq_sharded: bool = True) -> P:
    return P(AXIS.DP, AXIS.CP if seq_sharded else None, None)


def batch_spec() -> P:
    return P(AXIS.DP, None, None)


def constrain(x: Any, pspec: P) -> Any:
    """with_sharding_constraint that is a no-op outside jit/mesh contexts."""
    try:
        return jax.lax.with_sharding_constraint(x, pspec)
    except (ValueError, RuntimeError):
        return x


def put(mesh: Mesh, x: Any, pspec: Optional[P] = None) -> Any:
    """Device-put a host array with the given layout on the mesh."""
    return jax.device_put(x, NamedSharding(mesh, pspec if pspec is not None else P()))
