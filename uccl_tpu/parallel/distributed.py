"""Multi-host session bootstrap.

The reference's connection-setup layer (SURVEY.md §3.2: TCP bootstrap
handshake, rank↔address registry, `jax.distributed`-compatible init per §7
step 2). One call per process:

* :func:`initialize` — wraps ``jax.distributed.initialize`` (multi-host JAX:
  all hosts' chips form one global mesh; collectives ride ICI/DCN as laid out
  by the mesh) and stands up the OOB rendezvous (rank 0 serves a
  :class:`~uccl_tpu.p2p.store.StoreServer`, everyone gets a client).
* :func:`exchange` — all-gather style metadata exchange through the store
  (the analog of the reference's PeerMeta allgather, ep/src/proxy.cpp:210).
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional

import jax

from uccl_tpu.p2p.store import StoreClient, StoreServer
from uccl_tpu.utils.logging import get_logger

_log = get_logger("PARALLEL")


@dataclasses.dataclass
class Session:
    rank: int
    world: int
    store: StoreClient
    _server: Optional[StoreServer] = None

    def close(self):
        self.store.close()
        if self._server is not None:
            self._server.close()


def initialize(
    coordinator: str,
    rank: int,
    world: int,
    *,
    store_port: int = 0,
    init_jax: bool = True,
) -> Session:
    """Bring up the distributed session.

    coordinator: ``ip:port`` of rank 0 (the jax coordinator); the OOB store
    binds on rank 0 at ``store_port`` (or coordinator port + 1 when 0).
    """
    ip, port_s = coordinator.rsplit(":", 1)
    if init_jax and world > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator, num_processes=world, process_id=rank
        )
    server = None
    sport = store_port or int(port_s) + 1
    try:
        if rank == 0:
            server = StoreServer(sport)
            sport = server.port
        client = StoreClient(ip if rank != 0 else "127.0.0.1", sport)
    except Exception:
        if server is not None:
            server.close()  # don't leak the bound port on a failed bootstrap
        raise
    sess = Session(rank=rank, world=world, store=client, _server=server)
    _log.info("session up: rank %d/%d store %s:%d", rank, world, ip, sport)
    return sess


def initialize_from_env() -> Session:
    """Bring up the session from launcher-provided environment variables
    (``scripts/launch.py`` sets them — the torchrun-shaped entry):

      UCCL_TPU_COORD     rank 0's ip:port
      UCCL_TPU_RANK      this process's global rank
      UCCL_TPU_WORLD     total processes
      UCCL_TPU_INIT_JAX  "0" to skip jax.distributed (default on)
    """
    import os

    coord = os.environ["UCCL_TPU_COORD"]
    rank = int(os.environ["UCCL_TPU_RANK"])
    world = int(os.environ["UCCL_TPU_WORLD"])
    init_jax = os.environ.get("UCCL_TPU_INIT_JAX", "1") != "0"
    return initialize(coord, rank, world, init_jax=init_jax)


def exchange(sess: Session, key: str, payload: bytes, timeout_s: float = 60.0) -> List[bytes]:
    """Every rank contributes ``payload`` under ``key``; returns all ranks'
    payloads in rank order (the PeerMeta allgather)."""
    sess.store.set(f"{key}/{sess.rank}", payload)
    return [
        sess.store.wait(f"{key}/{r}", timeout_s=timeout_s)
        for r in range(sess.world)
    ]


def exchange_json(sess: Session, key: str, obj, timeout_s: float = 60.0) -> list:
    blobs = exchange(sess, key, json.dumps(obj).encode(), timeout_s)
    return [json.loads(b.decode()) for b in blobs]
