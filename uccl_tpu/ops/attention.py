"""Attention: reference, ring (context-parallel over a mesh axis), Ulysses.

Sequence/context parallelism is a first-class capability this framework adds over
the reference (SURVEY.md §5 "long-context": the reference has only lite-ep's
experimental 0-SM CP primitive, lite-ep/README.md:25). Two schemes:

* :func:`ring_attention` — KV blocks rotate around the ``cp`` ring via
  ``lax.ppermute`` while each member accumulates blockwise online-softmax
  attention for its local queries. Communication rides ICI neighbor links and
  overlaps with compute under XLA's async collective scheduling.
* :func:`ulysses_attention` — all-to-all reshard (sequence ↔ heads) so each
  member runs full-sequence attention on a head slice; reuses the same
  ``all_to_all`` machinery as expert parallelism.

All functions are *per-shard* (designed for use inside ``shard_map``), take
``[B, S, H, D]`` tensors, support GQA (fewer KV heads than Q heads), causal
masking, and accumulate in float32 regardless of input dtype.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from uccl_tpu.utils.topology import ppermute_pairs

_NEG_INF = -1e30  # finite "masked" score: keeps online-softmax math NaN-free


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """GQA: repeat KV heads to match Q heads. [B,S,Hkv,D] -> [B,S,Hkv*n_rep,D]."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def _scores(q: jax.Array, k: jax.Array, scale: float) -> jax.Array:
    """[B,Sq,H,D] x [B,Sk,H,D] -> [B,H,Sq,Sk] in f32."""
    return jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jax.Array:
    """Full (single-shard) attention. Offsets give the absolute positions of the
    local q/kv blocks so causal masking stays correct under sequence sharding."""
    n_rep = q.shape[2] // k.shape[2]
    k, v = _repeat_kv(k, n_rep), _repeat_kv(v, n_rep)
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = _scores(q, k, scale)
    if causal:
        qpos = jnp.arange(q.shape[1]) + q_offset
        kpos = jnp.arange(k.shape[1]) + kv_offset
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(q.dtype)


def _block_attend(q, k, v, m, l, o, scale, mask):
    """One online-softmax accumulation step.

    q: [B,Sq,H,D]; k,v: [B,Sk,H,D] (heads already repeated); mask: [Sq,Sk] bool
    or None; m,l: [B,H,Sq] f32 running max / normalizer; o: [B,Sq,H,D] f32.
    """
    s = _scores(q, k, scale)  # [B,H,Sq,Sk]
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_blk = jnp.max(s, axis=-1)  # [B,H,Sq]
    m_new = jnp.maximum(m, m_blk)
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])  # [B,H,Sq,Sk]
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _auto_block(s: int, cap: int = 1024) -> int:
    """Largest power-of-two block <= cap dividing s (1 if s is odd).

    cap=1024 is the measured v5e optimum at head_dim 64: the on-chip block
    sweep (PERF.md round-5, flagship shapes B=16/32 S=1024 and B=4 S=4096)
    is monotone in block size — bq=bk=1024 beats 128 by 2.9x fwd+bwd at
    S=1024 and 4.7x at S=4096, and beats XLA's fused attention 1.7-4x.
    VMEM stays comfortable: the f32 score tile is 4 MB; q/k/v/o tiles are
    O(block*head_dim)."""
    b = cap
    while b > 1 and s % b:
        b //= 2
    return b


def _merge_blocks(o_acc, lse_acc, o_blk, lse_blk):
    """Merge two normalized blockwise attention results via their LSEs.

    o: [B,Sq,H,D] f32 (each already softmax-normalized over its own keys);
    lse: [B,H,Sq]. Fully-masked blocks carry lse=-1e30 and merge as no-ops.
    """
    m = jnp.maximum(lse_acc, lse_blk)
    w_acc = jnp.exp(lse_acc - m)
    w_blk = jnp.exp(lse_blk - m)
    denom = w_acc + w_blk

    def bcast(w):  # [B,H,Sq] -> [B,Sq,H,1]
        return w.transpose(0, 2, 1)[..., None]

    o = (o_acc * bcast(w_acc) + o_blk * bcast(w_blk)) / bcast(denom)
    return o, m + jnp.log(denom)


def _flash_ring(q, k, v, axis, causal, block_q, block_k, interpret):
    """Ring attention with the Pallas flash kernel as the per-block compute.

    Step 0 is every member's own (causal-diagonal) block — a static causal
    flash call. Later steps are either fully visible (source chunk strictly
    earlier) or fully masked; a lax.cond picks between a non-causal flash
    call and a skip, so no per-element ring mask is ever built and the whole
    schedule stays SPMD. Blocks merge through the differentiable LSE merge,
    so training works end to end with no [S, S] materialization anywhere.
    """
    from uccl_tpu.ops.pallas_attention import flash_attention_lse

    n = lax.axis_size(axis)
    r = lax.axis_index(axis)
    b, sq, h, d = q.shape
    perm = ppermute_pairs(n, 1)

    o0, lse0 = flash_attention_lse(q, k, v, causal, block_q, block_k, interpret)
    o0 = o0.astype(jnp.float32)
    if n == 1:
        return o0.astype(q.dtype)

    def step(carry, t):
        k_blk, v_blk, o_acc, lse_acc = carry
        src = (r - t) % n

        def full(_):
            ob, lb = flash_attention_lse(
                q, k_blk, v_blk, False, block_q, block_k, interpret
            )
            return ob.astype(jnp.float32), lb

        def skip(_):
            return (
                jnp.zeros((b, sq, h, d), jnp.float32),
                jnp.full((b, h, sq), _NEG_INF, jnp.float32),
            )

        if causal:
            o_blk, lse_blk = lax.cond(src < r, full, skip, None)
        else:
            o_blk, lse_blk = full(None)
        o_acc, lse_acc = _merge_blocks(o_acc, lse_acc, o_blk, lse_blk)
        k_nxt = lax.ppermute(k_blk, axis, perm)
        v_nxt = lax.ppermute(v_blk, axis, perm)
        return (k_nxt, v_nxt, o_acc, lse_acc), None

    k1 = lax.ppermute(k, axis, perm)
    v1 = lax.ppermute(v, axis, perm)
    (_, _, o, _), _ = lax.scan(
        step, (k1, v1, o0, lse0), jnp.arange(1, n)
    )
    return o.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    *,
    causal: bool = True,
    impl: str = "xla",
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Context-parallel attention over mesh axis ``axis`` (per-shard fn).

    Each member holds the sequence chunk at position ``axis_index``; KV blocks
    rotate backwards around the ring so member r sees blocks originating from
    r, r-1, r-2, ... — with causal masking, later-origin blocks contribute
    nothing and are masked entirely (the compute is uniform across members to
    stay SPMD; XLA overlaps the ppermute with the block compute).

    impl="flash" runs each block through the Pallas flash kernel and merges
    via LSEs (:func:`_flash_ring`); impl="xla" uses einsum block attends.
    """
    if impl == "flash":
        bq = block_q or _auto_block(q.shape[1])
        bk = block_k or _auto_block(k.shape[1])
        if min(bq, bk) >= 8:
            return _flash_ring(q, k, v, axis, causal, bq, bk, interpret)
        # fall through to the XLA path when blocks would be degenerate
    n = lax.axis_size(axis)
    r = lax.axis_index(axis)
    n_rep = q.shape[2] // k.shape[2]
    scale = 1.0 / math.sqrt(q.shape[-1])
    b, sq, h, d = q.shape
    sk = k.shape[1]
    perm = ppermute_pairs(n, 1)  # send local block to the next member

    qpos = jnp.arange(sq)[:, None]  # positions within a chunk
    kpos = jnp.arange(sk)[None, :]

    def step(carry, _):
        k_blk, v_blk, src, m, l, o = carry
        if causal:
            # absolute positions: q at r*sq + qpos, kv at src*sk + kpos
            mask = (r * sq + qpos) >= (src * sk + kpos)
        else:
            mask = None
        # GQA-repeat only at compute time: the ring carries the narrow KV
        # blocks, so ppermute traffic stays 1/n_rep of the repeated size.
        m, l, o = _block_attend(
            q, _repeat_kv(k_blk, n_rep), _repeat_kv(v_blk, n_rep), m, l, o, scale, mask
        )
        k_nxt = lax.ppermute(k_blk, axis, perm)
        v_nxt = lax.ppermute(v_blk, axis, perm)
        return (k_nxt, v_nxt, (src - 1) % n, m, l, o), None

    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    (k, v, _, m, l, o), _ = lax.scan(step, (k, v, r, m0, l0, o0), None, length=n)
    l = jnp.maximum(l, 1e-20)  # fully-masked rows (can't happen with causal self-block)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    *,
    causal: bool = True,
    impl: str = "xla",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Ulysses sequence parallelism (per-shard fn): all-to-all turns the
    sequence sharding into a head sharding, full-sequence attention runs on
    the local head slice, and the inverse all-to-all restores sequence
    sharding. Reuses the EP all-to-all path (SURVEY.md §2.6: "Ulysses =
    head-sharded all-to-all reusing the EP path"). Q heads must divide the
    axis size; KV heads are GQA-repeated up to the Q head count first when they
    don't divide it (costs wire bandwidth, keeps the schedule uniform)."""
    n = lax.axis_size(axis)
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs q heads divisible by axis size {n}: q{q.shape}"
        )
    if k.shape[2] % n:
        rep = q.shape[2] // k.shape[2]
        k, v = _repeat_kv(k, rep), _repeat_kv(v, rep)

    def seq_to_heads(x):
        # [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if impl == "flash":
        from uccl_tpu.ops.pallas_attention import flash_attention

        bq = _auto_block(qg.shape[1])
        bk = _auto_block(kg.shape[1])
        if min(bq, bk) >= 8:
            out = flash_attention(qg, kg, vg, causal, bq, bk, interpret)
            return heads_to_seq(out)
    out = attention_reference(qg, kg, vg, causal=causal)
    return heads_to_seq(out)
