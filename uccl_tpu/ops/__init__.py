"""Device compute ops: attention (full / ring / Ulysses), quantization, MoE math.

The reference keeps device work in CUDA kernels (ep/src/*.cu,
collective/efa/scattered_memcpy.cu); here the device path is JAX/XLA + Pallas.
Every op has a pure-XLA implementation that runs anywhere (CPU tests, TPU), with
Pallas TPU kernels layered on where they beat XLA fusion.
"""

from uccl_tpu.ops.attention import (
    attention_reference,
    ring_attention,
    ulysses_attention,
)
from uccl_tpu.ops.quant import quantize_fp8, dequantize_fp8

__all__ = [
    "attention_reference",
    "ring_attention",
    "ulysses_attention",
    "quantize_fp8",
    "dequantize_fp8",
]
