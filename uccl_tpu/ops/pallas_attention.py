"""Pallas TPU flash attention: forward + backward kernels, LSE-exposing API.

The hot attention op on the MXU: blockwise online-softmax attention computed in
VMEM, one (batch×head, q-block) program at a time, streaming KV blocks. The
causal variant skips fully-masked KV blocks, so wasted FLOPs shrink from 2× to
~0 at long sequence.

This is the framework's analog of the reference's hand-written device kernels
(the reference's compute-heavy paths are CUDA kernels, e.g.
ep/src/internode_ll.cu; attention itself lives in the frameworks UCCL serves).

Three public entry points:

* :func:`flash_attention` — drop-in attention, custom VJP backed by Pallas
  dq and dk/dv kernels (FlashAttention-2-style recomputation from the saved
  LSE — no [S, S] matrix is ever materialized, forward or backward).
* :func:`flash_attention_lse` — same, returning ``(out, lse)``. The LSE
  output is differentiable: its cotangent folds into the backward row term
  (``dS = P∘(dP − (Δ − g_lse))``), which is exactly what blockwise/ring
  merging needs to train through merged blocks.
* The kernels fall back to interpret mode automatically off-TPU so every
  test runs anywhere.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# Renamed from TPUCompilerParams in older jax releases.
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)


def _is_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Forward kernel


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    *, scale, block_q, block_k, causal,
):
    """Grid (bh, iq, jk): one KV block per program, streamed through VMEM.

    Ref shapes: q [1, BQ, D]; k/v [1, BK, D]; o [1, BQ, D]; lse [1, BQ, 1].
    The LSE rides as a [BQ, 1] column (trailing singleton) so its block spec
    is TPU-tileable — a 2-D [1, BQ] block over [B*H, S] violates Mosaic's
    (8, 128) tiling rule, which only surfaces on real hardware. All kernel
    arithmetic stays rank-2 for the same reason.
    Scratch (m/l [BQ, 1], acc [BQ, D]) carries the online softmax across the
    jk dimension — jk is innermost, so for a fixed (bh, iq) the programs run
    back-to-back and the scratch is private to that q block.
    """
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        m_ref[:, :] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:, :] = jnp.zeros_like(l_ref)
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    # Causal: KV blocks strictly after this q block contribute nothing.
    last_q_pos = (iq + 1) * block_q - 1
    relevant = (not causal) or (jk * block_k <= last_q_pos)

    @pl.when(relevant)
    def _attend():
        q = q_ref[0].astype(jnp.float32)  # [BQ, D]
        k = k_ref[0].astype(jnp.float32)  # [BK, D]
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        if causal:
            qpos = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = jk * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m = m_ref[:, :]  # [BQ, 1]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_ref[:, :] = l_ref[:, :] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:, :] = acc_ref[:, :] * corr + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:, :] = m_new

    @pl.when(jk == n_kv - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :], 1e-20)  # [BQ, 1]
        o_ref[0] = (acc_ref[:, :] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :] + jnp.log(l)


def _flash_fwd(
    q, k, v, causal, block_q, block_k, interpret
) -> Tuple[jax.Array, jax.Array]:
    """q: [B, S, H, D]; k/v: [B, Sk, Hkv, D] -> (out [B,S,H,D], lse [B,H,S])."""
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} must be a multiple of kv heads {hkv}")
    n_rep = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq},{sk}) must divide blocks ({block_q},{block_k})"
        )
    if interpret is None:
        interpret = not _is_tpu()
    scale = 1.0 / math.sqrt(d)

    # [B, S, H, D] -> [B*H, S, D] program-major layout
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k, causal=causal
    )
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, iq, jk: (bh, iq, 0)),
            # GQA: head bh maps to kv head bh//n_rep; one KV block per program
            pl.BlockSpec((1, block_k, d), lambda bh, iq, jk: (bh // n_rep, jk, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, jk: (bh // n_rep, jk, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, iq, jk: (bh, iq, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),  # normalizer l
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        compiler_params=_CompilerParams(
            # scratch carries state only across jk (innermost); bh/iq programs
            # are independent, so let megacore split them.
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt)
    return (
        out.reshape(b, h, sq, d).transpose(0, 2, 1, 3),
        lse.reshape(b, h, sq),
    )


# ---------------------------------------------------------------------------
# Backward kernels (FlashAttention-2 style: recompute P from saved LSE)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref,
    *, scale, block_q, block_k, causal,
):
    """Grid (bh, iq, jk), jk innermost: accumulate dQ for one q block while
    streaming KV blocks. delta = rowsum(dO∘O) − g_lse (the combined row term)."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    last_q_pos = (iq + 1) * block_q - 1
    relevant = (not causal) or (jk * block_k <= last_q_pos)

    @pl.when(relevant)
    def _accum():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # [BQ, 1]
        delta = delta_ref[0]  # [BQ, 1]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            qpos = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = jk * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)  # masked scores underflow to 0
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        ds = p * (dp - delta) * scale
        acc_ref[:, :] += lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(jk == n_kv - 1)
    def _finish():
        dq_ref[0] = acc_ref[:, :].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, scale, block_q, block_k, causal,
):
    """Grid (bh, jk, iq), iq innermost: accumulate dK/dV for one KV block while
    streaming q blocks (at full q-head resolution; GQA-reduced outside)."""
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:, :] = jnp.zeros_like(dk_acc)
        dv_acc[:, :] = jnp.zeros_like(dv_acc)

    last_q_pos = (iq + 1) * block_q - 1
    relevant = (not causal) or (jk * block_k <= last_q_pos)

    @pl.when(relevant)
    def _accum():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # [BQ, 1]
        delta = delta_ref[0]  # [BQ, 1]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            qpos = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = jk * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)  # [BQ, BK]
        dv_acc[:, :] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        dk_acc[:, :] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(iq == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[:, :].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:, :].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g_out, g_lse, causal, block_q, block_k,
               interpret):
    """Pallas backward: returns (dq, dk, dv) without materializing [S, S]."""
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    n_rep = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if interpret is None:
        interpret = not _is_tpu()
    scale = 1.0 / math.sqrt(d)

    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    dot = g_out.transpose(0, 2, 1, 3).reshape(b * h, sq, d).astype(q.dtype)
    # LSE/delta travel as [B*H, S, 1] columns (TPU-tileable blocks, see
    # _fwd_kernel docstring).
    lse_t = lse.reshape(b * h, sq, 1)
    # Combined row term: Δ − g_lse. The g_lse fold-in makes the LSE output
    # differentiable (dS = P∘(dP − (Δ − g_lse))), which ring merging needs.
    delta = jnp.sum(
        g_out.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1).reshape(b * h, sq, 1)
    if g_lse is not None:
        delta = delta - g_lse.reshape(b * h, sq, 1)

    common = dict(scale=scale, block_q=block_q, block_k=block_k, causal=causal)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, jk: (bh // n_rep, jk, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, jk: (bh // n_rep, jk, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, iq, jk: (bh, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, iq, jk: (bh, iq, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt, dot, lse_t, delta)

    dk_full, dv_full = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sk, d), jnp.float32),
        ),
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, jk, iq: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, jk, iq: (bh // n_rep, jk, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, jk, iq: (bh // n_rep, jk, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, jk, iq: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, jk, iq: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, jk, iq: (bh, iq, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d), lambda bh, jk, iq: (bh, jk, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, jk, iq: (bh, jk, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt, dot, lse_t, delta)

    # GQA: fold the n_rep q-head contributions back onto each KV head.
    dk = dk_full.reshape(b, hkv, n_rep, sk, d).sum(2).transpose(0, 2, 1, 3)
    dv = dv_full.reshape(b, hkv, n_rep, sk, d).sum(2).transpose(0, 2, 1, 3)
    return (
        dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )


# ---------------------------------------------------------------------------
# Public API


def _default_blocks():
    """Tile sizes from config (UCCL_TPU_FLASH_BLOCK_Q/K): the on-chip tuning
    knob — the flash-vs-XLA crossover moves with (BQ, BKV) at long sequence,
    and an env sweep (benchmarks/attention_bench.py --block-sweep) must be
    able to retune without code changes. 0 (the default) means auto-size
    from the sequence: largest power-of-two divisor capped at 1024, the
    measured v5e optimum (see ops.attention._auto_block)."""
    from uccl_tpu.utils.config import param

    bq = param("flash_block_q", 0,
               help="flash attention q-tile rows (0 = auto-size)")
    bk = param("flash_block_k", 0,
               help="flash attention kv-tile rows (0 = auto-size)")
    return int(bq.get()), int(bk.get())


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse_core(q, k, v, causal, block_q, block_k, interpret):
    # block_q/block_k are CONCRETE here: custom_vjp routes differentiation
    # through _lse_vjp_fwd (not this body), so any None-resolution must
    # happen in the public wrapper below, before the custom_vjp boundary.
    return _flash_fwd(q, k, v, causal, block_q, block_k, interpret)


def _lse_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _lse_vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    return _flash_bwd(
        q, k, v, out, lse, g_out, g_lse, causal, block_q, block_k, interpret
    )


_flash_lse_core.defvjp(_lse_vjp_fwd, _lse_vjp_bwd)


def flash_attention_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Flash attention returning (out [B,S,H,D], lse [B,H,S]).

    The lse output is differentiable, so callers may merge blocks (ring/
    blockwise attention) and train straight through the merge. block_q/k
    default from UCCL_TPU_FLASH_BLOCK_Q/K; unset (0) auto-sizes to the
    largest power-of-two divisor of the sequence capped at 1024 — the
    measured v5e optimum at head_dim 64 (PERF.md round-5 block sweep).
    """
    from uccl_tpu.ops.attention import _auto_block

    dq, dk = _default_blocks()
    auto_q = auto_k = False
    if block_q is None:
        block_q = dq or _auto_block(q.shape[1])
        auto_q = not dq
    if block_k is None:
        block_k = dk or _auto_block(k.shape[1])
        auto_k = not dk
    # Fail fast when AUTO-sizing produced a sub-8 tile (ragged sequence,
    # e.g. S=1001 -> 1) that is about to be compiled by Mosaic, which
    # would reject it obscurely. Explicitly passed blocks (args or env)
    # are the caller's own; interpret mode accepts any tile and keeps
    # working (short decode-style sequences included).
    will_compile = interpret is False or (interpret is None and _is_tpu())
    if will_compile and (
        (auto_q and block_q < 8) or (auto_k and block_k < 8)
    ):
        raise ValueError(
            f"flash attention: no usable block for seq lengths "
            f"q={q.shape[1]}, kv={k.shape[1]} (auto-sized blocks "
            f"({block_q},{block_k}) < 8). Pad the sequence to a multiple "
            f"of 8 or pass explicit block_q/block_k."
        )
    return _flash_lse_core(q, k, v, causal, block_q, block_k, interpret)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention. q: [B, S, H, D]; k/v: [B, Sk, Hkv, D] (GQA-aware).
    Forward and backward both run as Pallas kernels; no [S, S] tensor is
    materialized in either direction."""
    out, _ = flash_attention_lse(q, k, v, causal, block_q, block_k, interpret)
    return out
