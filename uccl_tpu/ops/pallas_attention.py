"""Pallas TPU flash attention (forward kernel + recompute backward).

The hot attention op on the MXU: blockwise online-softmax attention computed in
VMEM, one (batch×head, q-block) program at a time, streaming KV blocks. The
causal variant skips fully-masked KV blocks (the fori_loop upper bound depends
on the q-block index), so wasted FLOPs shrink from 2× to ~0 at long sequence.

This is the framework's analog of the reference's hand-written device kernels
(the reference's compute-heavy paths are CUDA kernels, e.g.
ep/src/internode_ll.cu; attention itself lives in the frameworks UCCL serves).
Backward pass recomputes through the XLA reference implementation via
``jax.custom_vjp`` — correct everywhere, with the forward on the fast path.

Falls back to interpret mode automatically off-TPU so tests run anywhere.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _is_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale, block_q, block_k, causal,
):
    """Grid (bh, iq, jk): one KV block per program, streamed through VMEM.

    Ref shapes: q [1, BQ, D]; k/v [1, BK, D]; o [1, BQ, D]. Scratch
    (m/l [BQ, 1], acc [BQ, D]) carries the online softmax across the jk
    dimension — jk is innermost, so for a fixed (bh, iq) the programs run
    back-to-back and the scratch is private to that q block.
    """
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        m_ref[:, :] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:, :] = jnp.zeros_like(l_ref)
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    # Causal: KV blocks strictly after this q block contribute nothing.
    last_q_pos = (iq + 1) * block_q - 1
    relevant = (not causal) or (jk * block_k <= last_q_pos)

    @pl.when(relevant)
    def _attend():
        q = q_ref[0].astype(jnp.float32)  # [BQ, D]
        k = k_ref[0].astype(jnp.float32)  # [BK, D]
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        if causal:
            qpos = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = jk * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m = m_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=1)
        acc_ref[:, :] = acc_ref[:, :] * corr[:, None] + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:, 0] = m_new

    @pl.when(jk == n_kv - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, 0], 1e-20)
        o_ref[0] = (acc_ref[:, :] / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: Optional[bool],
) -> jax.Array:
    """q: [B, S, H, D]; k/v: [B, S, Hkv, D] -> [B, S, H, D]."""
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} must be a multiple of kv heads {hkv}")
    n_rep = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq},{sk}) must divide blocks ({block_q},{block_k})"
        )
    if interpret is None:
        interpret = not _is_tpu()
    scale = 1.0 / math.sqrt(d)

    # [B, S, H, D] -> [B*H, S, D] program-major layout
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k, causal=causal
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, iq, jk: (bh, iq, 0)),
            # GQA: head bh maps to kv head bh//n_rep; one KV block per program
            pl.BlockSpec((1, block_k, d), lambda bh, iq, jk: (bh // n_rep, jk, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, iq, jk: (bh // n_rep, jk, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, iq, jk: (bh, iq, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 1), jnp.float32),  # normalizer l
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention. q: [B, S, H, D]; k/v: [B, S, Hkv, D] (GQA-aware)."""
    return _flash_fwd(q, k, v, causal, block_q, block_k, interpret)


def _ref_attention(q, k, v, causal):
    # local import to avoid a cycle (attention.py may route here)
    from uccl_tpu.ops.attention import attention_reference

    return attention_reference(q, k, v, causal=causal)


def _vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    # Recompute-through-reference backward: one extra forward at XLA speed,
    # exact gradients, zero extra residual memory from the kernel.
    _, vjp = jax.vjp(lambda a, b, c: _ref_attention(a, b, c, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
