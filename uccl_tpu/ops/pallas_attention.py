"""Pallas TPU flash attention (forward kernel + recompute backward).

The hot attention op on the MXU: blockwise online-softmax attention computed in
VMEM, one (batch×head, q-block) program at a time, streaming KV blocks. The
causal variant skips fully-masked KV blocks (the fori_loop upper bound depends
on the q-block index), so wasted FLOPs shrink from 2× to ~0 at long sequence.

This is the framework's analog of the reference's hand-written device kernels
(the reference's compute-heavy paths are CUDA kernels, e.g.
ep/src/internode_ll.cu; attention itself lives in the frameworks UCCL serves).
Backward pass recomputes through the XLA reference implementation via
``jax.custom_vjp`` — correct everywhere, with the forward on the fast path.

Falls back to interpret mode automatically off-TPU so tests run anywhere.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _is_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, block_q, block_k, causal):
    """One program: q block (iq) of one (batch*head) against all its KV blocks.

    Ref shapes: q [1, BQ, D]; k/v [1, Sk, D]; o [1, BQ, D].
    """
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [BQ, D]
    sk = k_ref.shape[1]
    d = q_ref.shape[2]
    n_kv = sk // block_k

    if causal:
        # KV blocks strictly after this q block's last row are fully masked.
        last_q_pos = (iq + 1) * block_q - 1
        n_blocks = lax.min(n_kv, last_q_pos // block_k + 1)
    else:
        n_blocks = n_kv

    qpos = iq * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        if causal:
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: Optional[bool],
) -> jax.Array:
    """q: [B, S, H, D]; k/v: [B, S, Hkv, D] -> [B, S, H, D]."""
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    n_rep = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq},{sk}) must divide blocks ({block_q},{block_k})"
        )
    if interpret is None:
        interpret = not _is_tpu()
    scale = 1.0 / math.sqrt(d)

    # [B, S, H, D] -> [B*H, S, D] program-major layout
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k, causal=causal
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, iq: (bh, iq, 0)),
            # GQA: head bh maps to kv head bh//n_rep; whole KV slab per program
            pl.BlockSpec((1, sk, d), lambda bh, iq: (bh // n_rep, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, iq: (bh // n_rep, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, iq: (bh, iq, 0)),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention. q: [B, S, H, D]; k/v: [B, S, Hkv, D] (GQA-aware)."""
    return _flash_fwd(q, k, v, causal, block_q, block_k, interpret)


def _ref_attention(q, k, v, causal):
    # local import to avoid a cycle (attention.py may route here)
    from uccl_tpu.ops.attention import attention_reference

    return attention_reference(q, k, v, causal=causal)


def _vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    # Recompute-through-reference backward: one extra forward at XLA speed,
    # exact gradients, zero extra residual memory from the kernel.
    _, vjp = jax.vjp(lambda a, b, c: _ref_attention(a, b, c, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
