"""Block-scaled wire codec: fp8 / int8 payloads + per-block f32 scales.

The analog of the reference's fp8-packed EP payloads (ep/src/internode_ll.cu:62
casts tokens to fp8 + per-group scales before RDMA) and the DietGPU float
compression on the P2P wire (p2p/rdma/compression.{h,cc}): shrink what moves
across the fabric, restore on arrival. On TPU the payload dtypes are native
``float8_e4m3fn`` and ``int8`` with per-block f32 scales — MXU-friendly and
XLA-fusable into the surrounding ops.

This module is the ONE scale rule every wire shares (EQuARX-style: quantize
on the wire only, never store partial sums in wire precision):

* the EP all-to-all paths (:mod:`uccl_tpu.ep.ops` sorted/dense,
  :mod:`uccl_tpu.ep.ll` packed LL) quantize along the hidden dim in
  ``quant_group``-sized blocks;
* the Pallas ring collectives (:mod:`uccl_tpu.collective.pallas_ccl`
  ``wire_dtype=``) quantize per 128-lane row of their padded chunk layout;
* the host-side P2P codec (:mod:`uccl_tpu.p2p.compress`) still carries the
  legacy numpy variant of the rule (amax floored at 1e-12, no zero-exact /
  non-finite guards) — it pre-dates this codec and its self-describing blob
  header pins that format; converging it here is tracked with the
  quantized-p2p roadmap item.

Codec contract (``quantize_block`` / ``dequantize_block``):

* symmetric block scaling along the LAST dim: ``scale = amax / QMAX`` per
  block (``QMAX`` = 448 for fp8 e4m3fn, 127 for int8), values divided by the
  scale and cast (int8 additionally rounds-to-nearest);
* **padding-safe**: a trailing block that does not divide the last dim is
  zero-padded internally and sliced back — padding never changes the scale
  of real data (zeros cannot raise an amax);
* **zero/denormal-safe**: an exact-zero block takes ``scale = 1.0`` (so it
  round-trips to EXACT zeros), a denormal-amax block's scale is floored at
  the smallest normal f32 (no inf from the divide), quantized values are
  clipped to ±QMAX before the cast (e4m3fn has no inf — an unclipped
  overflow would become nan), and ``dequantize_block`` maps zero/denormal/
  nan scales to 0 instead of propagating garbage;
* **non-finite-loud**: a block containing any inf/nan input element gets
  its scale poisoned to +inf so the WHOLE block dequantizes non-finite —
  a full-precision wire would deliver the divergence, so the quantized
  wire must never mask it as zeros (int8's nan→0 cast otherwise would).

Per-block error bound of one quantize→dequantize round trip (the unit the
wire designs budget in — docs/QUANT_WIRE.md): ``|err| <= amax / 27.7`` for
fp8 (half-ulp at 448 is 16 ⇒ 16/448 = amax/28 for a correctly-rounded
cast, plus up to half an f16 ulp where the substrate double-rounds the
f32→e4m3 cast through f16 — XLA:CPU does ⇒ 16.125/448) and
``|err| <= amax / 254`` for int8 (half a step of amax/127;
``jnp.round`` is correctly rounded, no double-rounding slack).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

FP8_DTYPE = jnp.float8_e4m3fn
FP8_MAX = 448.0  # max normal of e4m3fn
INT8_MAX = 127.0  # symmetric int8 (−127..127; −128 unused)

# wire_dtype name -> (payload jnp dtype, QMAX, needs integer rounding)
WIRE_DTYPES = {
    "fp8": (FP8_DTYPE, FP8_MAX, False),
    "int8": (jnp.int8, INT8_MAX, True),
}

# Documented per-block round-trip error divisors (module docstring): one
# quantize→dequantize trip is bounded by |err| <= amax / ROUND_TRIP_DIVISOR.
# Every consumer that budgets or TESTS against the bound reads it from here
# (tests/test_quant.py, the tiered KV cache's quantized-at-rest contract in
# serving/kv_tiers.py) so the codec and its promises cannot drift apart.
ROUND_TRIP_DIVISOR = {"fp8": 27.7, "int8": 254.0}


def round_trip_bound(amax: float, wire_dtype: str) -> float:
    """Max |error| of one quantize→dequantize round trip for a block whose
    abs-max is ``amax`` (the documented contract, not a re-derivation)."""
    return float(amax) / ROUND_TRIP_DIVISOR[resolve_wire_dtype(wire_dtype)]

# scale floor: the smallest NORMAL f32. A denormal scale risks flushing to
# zero (then x / scale = inf) and denormal arithmetic on some substrates;
# flooring here keeps |x / scale| finite (clipped to QMAX right after).
_SCALE_TINY = float(jnp.finfo(jnp.float32).tiny)


def resolve_wire_dtype(wire_dtype: Optional[str]) -> Optional[str]:
    """Validate a ``wire_dtype`` knob value (None | "fp8" | "int8")."""
    if wire_dtype is None or wire_dtype in ("", "none"):
        return None
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(
            f"unknown wire_dtype {wire_dtype!r} (want None, 'fp8', or "
            "'int8')"
        )
    return wire_dtype


def wire_payload_dtype(wire_dtype: str):
    """The jnp payload dtype of a wire_dtype."""
    return WIRE_DTYPES[wire_dtype][0]


def wire_qmax(wire_dtype: str) -> float:
    return WIRE_DTYPES[wire_dtype][1]


def adapt_block(d: int, block: int) -> int:
    """Adapt a block size to a dim: the largest divisor of ``d`` no bigger
    than the requested block (trace-time loop; keeps the scale overhead
    minimal instead of gcd's tiny-block collapse). The ONE divisor rule the
    EP paths share (formerly ep.ops._adapt_quant_group)."""
    if d % block:
        block = max(b for b in range(min(block, d), 0, -1) if d % b == 0)
    return block


def paying_block(d: int, block: int) -> Optional[int]:
    """The adapted block when block-scaled quantization PAYS on the wire,
    else None: 1 payload byte + 4/g scale bytes beats bf16's 2 only for
    g > 4; the codebase's established margin is g >= 8 (formerly
    ep.ll._adapt_group — the one payoff rule every wire shares; identical
    for fp8 and int8, both 1-byte payloads)."""
    g = adapt_block(d, block)
    return g if g >= 8 else None


def wire_bytes_of(shape, dtype, wire_dtype: Optional[str] = None,
                  quant_group: int = 128) -> int:
    """Actual wire bytes one exchange of a payload array moves under the
    block codec: quantized payload (1 byte/elem) PLUS the f32 scale sidecar
    when the wire dtype applies, raw element bytes otherwise — the ONE
    arithmetic the ``ep_bytes_total`` counter, the bench bandwidth math and
    the :class:`uccl_tpu.collective.plan.CollectivePlanner` cost model
    share (docs/QUANT_WIRE.md). Formerly ``ep.ops.wire_bytes_of``, which
    still re-exports it."""
    elems = 1
    for s in shape:
        elems *= int(s)
    itemsize = jnp.dtype(dtype).itemsize
    if wire_dtype is None or not jnp.issubdtype(
        jnp.dtype(dtype), jnp.floating
    ):
        return elems * itemsize  # full precision / non-float raw wire
    g = paying_block(int(shape[-1]), quant_group) if shape else None
    if g is None:
        return elems * itemsize  # quantization would not pay — raw wire
    return elems + (elems // g) * 4


def quantize_block(
    x: jax.Array, wire_dtype: str = "fp8", block: int = 128
) -> Tuple[jax.Array, jax.Array]:
    """Block-scaled symmetric quantization along the last dim.

    x: [..., D] → (values [..., D] in the wire payload dtype,
    scales [..., ceil(D/block)] f32) such that ``values * scale ≈ x``.
    Padding-safe on a non-dividing trailing block; exact-zero blocks take
    scale 1.0 and round-trip to exact zeros (see module docstring).
    """
    wire_dtype = resolve_wire_dtype(wire_dtype)
    if wire_dtype is None:
        raise ValueError("quantize_block needs a wire_dtype ('fp8'/'int8')")
    dtype, qmax, integer = WIRE_DTYPES[wire_dtype]
    *lead, d = x.shape
    nb = -(-d // block)
    pad = nb * block - d
    g = x.astype(jnp.float32)
    if pad:
        g = jnp.pad(g, [(0, 0)] * len(lead) + [(0, pad)])
    g = g.reshape(*lead, nb, block)
    amax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    scale = jnp.where(
        amax > 0.0, jnp.maximum(amax / qmax, _SCALE_TINY), 1.0
    )
    # A block holding any non-finite element cannot be block-scaled (one
    # shared scale cannot carry inf AND its finite neighbors). Poison its
    # scale to +inf so the whole block dequantizes non-finite — divergence
    # stays loud; int8's nan→0 cast would otherwise mask it as exact zeros.
    scale = jnp.where(jnp.isfinite(amax), scale, jnp.inf)
    q = jnp.clip(g / scale, -qmax, qmax)
    if integer:
        q = jnp.round(q)
    q = q.astype(dtype).reshape(*lead, nb * block)
    if pad:
        q = q[..., :d]
    return q, scale[..., 0]


def dequantize_block(
    q: jax.Array,
    scale: jax.Array,
    block: int = 128,
    dtype=jnp.bfloat16,
) -> jax.Array:
    """Inverse of :func:`quantize_block`.

    Scale guard: a zero/denormal/nan scale dequantizes its block to exact
    zeros — those only arise from garbage sidecar lanes or a legitimately
    zero block (which carries q == 0 either way). A **+inf** scale is the
    quantizer's poison marker for a non-finite input block and is let
    through, so the whole block arrives non-finite (nan) instead of
    silently zeroed — divergence on a quantized wire must stay loud."""
    *lead, d = q.shape
    nb = scale.shape[-1]
    pad = nb * block - d
    g = q.astype(jnp.float32)
    if pad:
        g = jnp.pad(g, [(0, 0)] * len(lead) + [(0, pad)])
    g = g.reshape(*lead, nb, block)
    scale = scale.astype(jnp.float32)
    safe = jnp.where(
        jnp.isnan(scale) | (scale < _SCALE_TINY), 0.0, scale
    )
    out = (g * safe[..., None]).reshape(*lead, nb * block)
    if pad:
        out = out[..., :d]
    return out.astype(dtype)


# -- legacy fp8 surface (PR 1's EP wire) -------------------------------------
# Thin wrappers over the generic codec; bit-equal to the pre-codec
# quantize_fp8/dequantize_fp8 on their original contract — last dim divisible
# by the group and per-block amax >= 1e-12, the old rule's scale floor
# (below it the old rule collapsed blocks to q ≈ 0 while the codec's
# TINY-floored scale keeps them representable: different wire bits, strictly
# tighter round-trip error) — regression-tested in tests/test_quant.py so
# the LL wire format cannot drift.


def quantize_fp8(
    x: jax.Array, group_size: int = 128
) -> Tuple[jax.Array, jax.Array]:
    """Quantize along the last dim in groups: returns (fp8 values, f32 scales).

    x: [..., D] with D % group_size == 0 → values [..., D] fp8,
    scales [..., D // group_size] f32 such that values * scale ≈ x.
    """
    if x.shape[-1] % group_size:
        raise ValueError(
            f"last dim {x.shape[-1]} not divisible by group size {group_size}"
        )
    return quantize_block(x, "fp8", group_size)


def dequantize_fp8(
    q: jax.Array, scale: jax.Array, group_size: int = 128, dtype=jnp.bfloat16
) -> jax.Array:
    """Inverse of :func:`quantize_fp8`."""
    return dequantize_block(q, scale, group_size, dtype=dtype)
