"""FP8 payload quantization for wire transfer.

The analog of the reference's fp8-packed EP payloads (ep/src/internode_ll.cu:62
casts tokens to fp8 + per-group scales before RDMA) and the DietGPU float
compression on the P2P wire (p2p/rdma/compression.{h,cc}): shrink what moves
across the fabric, restore on arrival. On TPU we use native ``float8_e4m3fn``
with per-group scales — MXU-friendly and XLA-fusable into the surrounding ops.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

FP8_DTYPE = jnp.float8_e4m3fn
FP8_MAX = 448.0  # max normal of e4m3fn


def quantize_fp8(
    x: jax.Array, group_size: int = 128
) -> Tuple[jax.Array, jax.Array]:
    """Quantize along the last dim in groups: returns (fp8 values, f32 scales).

    x: [..., D] with D % group_size == 0 → values [..., D] fp8,
    scales [..., D // group_size] f32 such that values * scale ≈ x.
    """
    *lead, d = x.shape
    if d % group_size:
        raise ValueError(f"last dim {d} not divisible by group size {group_size}")
    g = x.reshape(*lead, d // group_size, group_size).astype(jnp.float32)
    amax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / FP8_MAX
    q = (g / scale).astype(FP8_DTYPE)
    return q.reshape(*lead, d), scale[..., 0]


def dequantize_fp8(
    q: jax.Array, scale: jax.Array, group_size: int = 128, dtype=jnp.bfloat16
) -> jax.Array:
    """Inverse of :func:`quantize_fp8`."""
    *lead, d = q.shape
    g = q.reshape(*lead, d // group_size, group_size).astype(jnp.float32)
    out = g * scale[..., None]
    return out.reshape(*lead, d).astype(dtype)
