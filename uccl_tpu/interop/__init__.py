"""Interop adapters: torch tensors over the transfer engine, jax↔torch."""

from uccl_tpu.interop.torch_bridge import (
    tensor_buffer,
    register_tensor,
    advertise_tensor,
    send_tensor,
    allreduce_gradients,
)

__all__ = [
    "tensor_buffer",
    "register_tensor",
    "advertise_tensor",
    "send_tensor",
    "allreduce_gradients",
]
