"""Torch interop: move torch tensors through the transfer engine, DDP-style
gradient averaging over the DCN group.

The reference's front doors are torch-shaped (NCCL plugin under
torch.distributed, nanobind Endpoint taking torch tensors —
p2p/engine_api.cc:448 `transfer` over tensor descriptor lists, examples/
ddp_train.py). This bridge gives torch users the same entry points against the
TPU framework's engine: zero-copy registration of CPU tensors, one-sided
transfer, and a DDP hook that averages `model.parameters()` gradients across
processes via :class:`~uccl_tpu.collective.hierarchical.DcnGroup`.
"""

from __future__ import annotations

from typing import Iterable

import numpy as np

from uccl_tpu.utils.logging import get_logger

_log = get_logger("P2P")


def tensor_buffer(t) -> np.ndarray:
    """Zero-copy numpy view of a contiguous CPU torch tensor.

    Dtypes numpy can't express (bfloat16, fp8, …) are reinterpreted as
    same-width integers — transfers move bytes, so the view is faithful."""
    import torch

    if t.device.type != "cpu":
        raise ValueError("engine transfers operate on CPU tensors (stage first)")
    if not t.is_contiguous():
        raise ValueError("tensor must be contiguous")
    t = t.detach()
    try:
        return t.numpy()
    except TypeError:
        widths = {1: torch.uint8, 2: torch.int16, 4: torch.int32, 8: torch.int64}
        return t.view(widths[t.element_size()]).numpy()


def register_tensor(ep, t) -> int:
    """Register a torch tensor's memory with an Endpoint; returns mr id."""
    return ep.reg(tensor_buffer(t))


def send_tensor(ep_or_chan, conn_or_none, t, fifo: bytes) -> None:
    """One-sided write of a torch tensor into a peer's advertised window.

    Accepts either (Endpoint, conn_id) or (Channel, None).
    """
    buf = tensor_buffer(t)
    if conn_or_none is None:
        ep_or_chan.write(buf, fifo)
    else:
        ep_or_chan.write(conn_or_none, buf, fifo)


def advertise_tensor(ep, t) -> bytes:
    """Register + advertise a torch tensor in one step; returns the 64-byte
    FifoItem to hand to the writer. One-sided writes then land in the tensor
    in place — there is no separate receive call."""
    return ep.advertise(register_tensor(ep, t))


def allreduce_gradients(parameters: Iterable, dcn_group) -> None:
    """Average gradients of torch parameters across the DCN group in place.

    The DDP contract over this framework's wire: flatten all grads into one
    bucket (like DDP's gradient bucketing), ring-allreduce it across
    processes through the transfer engine, unflatten, divide by world.
    """
    import torch

    params = [p for p in parameters if p.grad is not None]
    if not params:
        return
    flats = [p.grad.detach().reshape(-1) for p in params]
    # Reduce in float32: bf16 has no numpy dtype, and summing lower-precision
    # grads in f32 is what DDP does anyway. Cast back per-param on copy_.
    bucket = torch.cat(flats).float().contiguous()
    reduced = dcn_group.all_reduce(bucket.numpy())
    reduced = torch.from_numpy(reduced) / dcn_group.world
    off = 0
    for p in params:
        n = p.grad.numel()
        p.grad.copy_(reduced[off : off + n].reshape(p.grad.shape).to(p.grad.dtype))
        off += n
