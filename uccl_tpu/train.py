"""Unified trainer entry: ``python -m uccl_tpu.train``.

The consumer-facing front door the reference's users reach through
torchrun + Megatron/DDP scripts (examples/ddp_train.py there; OSDI AE
workloads, collective/utran_osdi26ae.md:151-163): pick a model family,
describe the mesh, train — with periodic orbax checkpoints and
bit-identical resume (tests/test_checkpoint.py proves the state trees are
checkpoint-transparent; this wires the loop around them).

    python -m uccl_tpu.train --model flagship --mesh dp=2,cp=2,tp=2 \
        --devices 8 --steps 20 --batch 8 --seq 64 \
        --ckpt-dir /tmp/run1 --ckpt-every 10
    # later, continue from the newest checkpoint:
    python -m uccl_tpu.train ... --ckpt-dir /tmp/run1 --resume

Data is a seeded synthetic stream where step i's batch depends only on i,
so an interrupted+resumed run replays the exact uninterrupted trajectory
(the resume test's contract). Swap ``_batch_for_step`` for a real loader
in production.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

# Runnable both as `python -m uccl_tpu.train` and as a plain script path
# (the launcher's contract: scripts/launch.py train.py ...). Only the
# script-path case needs the repo root on sys.path — a library import must
# not mutate it (it could shadow an installed uccl_tpu).
if __package__ in (None, ""):
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def parse_mesh(spec: str):
    """"dp=2,cp=2,tp=2" -> MeshConfig (unnamed axes default to 1)."""
    from uccl_tpu.parallel.mesh import MeshConfig

    sizes = {}
    if spec:
        for part in spec.split(","):
            m = re.fullmatch(r"(pp|dp|cp|tp)=(\d+)", part.strip())
            if not m:
                raise SystemExit(
                    f"bad --mesh entry {part!r} (want e.g. dp=2,tp=2)"
                )
            sizes[m.group(1)] = int(m.group(2))
    return MeshConfig(**sizes)


def build(args, mesh):
    """Returns (cfg, params, train_step, init_opt) for the model family."""
    import jax

    if args.model == "flagship":
        from uccl_tpu.models import flagship as fam
    else:
        from uccl_tpu.models import dense as fam

    size_kw = dict(
        vocab=args.vocab, dim=args.dim, n_layers=args.layers,
        n_heads=args.heads, n_kv_heads=args.kv_heads,
        head_dim=args.dim // args.heads,
        n_microbatches=args.microbatches,
    )
    if args.model == "flagship":
        size_kw.update(
            moe_experts=args.experts, moe_ffn=args.ffn,
            moe_topk=2, remat=args.remat,
        )
    else:
        size_kw.update(ffn=args.ffn, remat=args.remat)
    cfg = (fam.FlagshipConfig if args.model == "flagship"
           else fam.DenseConfig)(**size_kw)
    params = fam.shard_params(
        fam.init_params(jax.random.PRNGKey(args.seed), cfg), mesh, cfg
    )
    train_step, init_opt = fam.make_train_step(cfg, mesh, learning_rate=args.lr)
    return cfg, params, train_step, init_opt


def _batch_for_step(step_i, batch, seq, vocab, corpus=None):
    """Deterministic batch (host arrays): a function of the step index
    ONLY, so resumed runs see the same stream. Device placement is the
    caller's job — single-controller jit takes numpy directly; multihost
    shards it via make_array_from_callback.

    With a ``corpus`` (a 1-D int token memmap from --data), batch rows are
    contiguous windows at deterministic step-indexed offsets and targets
    are the next-token shift — the standard LM objective. Without one, the
    stream is seeded synthetic noise."""
    import numpy as np

    if corpus is not None:
        n = corpus.shape[0] - seq - 1
        rng = np.random.default_rng(10_000 + step_i)
        starts = rng.integers(0, n, batch)
        tokens = np.stack([corpus[s : s + seq] for s in starts])
        targets = np.stack([corpus[s + 1 : s + seq + 1] for s in starts])
        return tokens.astype(np.int32), targets.astype(np.int32)
    rng = np.random.default_rng(10_000 + step_i)
    tokens = rng.integers(0, vocab, (batch, seq)).astype(np.int32)
    targets = rng.integers(0, vocab, (batch, seq)).astype(np.int32)
    return tokens, targets


def _open_corpus(path, vocab, seq):
    """Memmap a 1-D int token file (.npy). Validated once over the WHOLE
    corpus: every id in [0, vocab), long enough for one window — an
    out-of-range id would otherwise clamp in the embedding gather and
    silently corrupt training."""
    import numpy as np

    corpus = np.load(path, mmap_mode="r")
    if corpus.ndim != 1 or not np.issubdtype(corpus.dtype, np.integer):
        raise SystemExit(f"--data {path}: want a 1-D integer token array")
    if corpus.shape[0] < seq + 2:
        raise SystemExit(
            f"--data {path}: {corpus.shape[0]} tokens < one {seq}-token window"
        )
    hi, lo = int(corpus.max()), int(corpus.min())
    if lo < 0 or hi >= vocab:
        raise SystemExit(
            f"--data {path}: token ids span [{lo}, {hi}], outside "
            f"[0, {vocab})"
        )
    return corpus


def _latest_step(ckpt_dir):
    steps = []
    for name in os.listdir(ckpt_dir):
        m = re.fullmatch(r"step_(\d+)", name)
        if m:
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def _save(ckpt_dir, step_i, params, opt_state, model_cfg=None):
    """ONE orbax save of the combined state tree: the write is a single
    atomic directory rename, so an interrupted run can never leave a
    half-checkpoint that _latest_step would pick but _restore cannot load.
    ``model_cfg`` (model family + size flags) is recorded ONCE as
    config.json beside the checkpoints — serving reads it back instead of
    guessing sizes from flags."""
    import orbax.checkpoint as ocp

    if model_cfg is not None:
        cfg_path = os.path.join(ckpt_dir, "config.json")
        if not os.path.exists(cfg_path):
            os.makedirs(ckpt_dir, exist_ok=True)
            tmp = f"{cfg_path}.{os.getpid()}.tmp"  # rank-unique
            with open(tmp, "w") as f:
                json.dump(model_cfg, f)
            os.replace(tmp, cfg_path)
    path = os.path.join(ckpt_dir, f"step_{step_i}")
    ocp.PyTreeCheckpointer().save(path, {"params": params, "opt": opt_state})


def _restore(ckpt_dir, step_i, params, opt_state, mesh):
    """Restore WITH explicit target shardings: the live trees' shardings
    become orbax restore_args, so a checkpoint saved under one process
    topology resumes under another (elastic restart; without this, orbax
    can only re-apply the save-time shardings and cross-topology resume
    dies with a 'sharding ... should be specified' error). Leaves without
    a mesh sharding (optimizer scalars like adam's count are born on one
    device) restore REPLICATED over the mesh — a committed single-device
    scalar would conflict with the 8-device params inside jit."""
    import jax
    import orbax.checkpoint as ocp
    from jax.sharding import NamedSharding, PartitionSpec as P

    path = os.path.join(ckpt_dir, f"step_{step_i}")
    item = {"params": params, "opt": opt_state}

    def args_for(x):
        sh = getattr(x, "sharding", None)
        if not isinstance(sh, NamedSharding):
            sh = NamedSharding(mesh, P())
        return ocp.ArrayRestoreArgs(
            sharding=sh, global_shape=x.shape, dtype=x.dtype
        )

    tree = ocp.PyTreeCheckpointer().restore(
        path, item=item, restore_args=jax.tree.map(args_for, item)
    )
    return tree["params"], tree["opt"]


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m uccl_tpu.train")
    ap.add_argument("--model", default="flagship",
                    choices=["flagship", "dense"])
    ap.add_argument("--mesh", default="", help="e.g. pp=2,dp=2,tp=2")
    ap.add_argument("--devices", type=int, default=0,
                    help="force an N-device virtual CPU mesh (tests/dev)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--log-every", type=int, default=5)
    # model size
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--ffn", type=int, default=128)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--microbatches", type=int, default=1)
    ap.add_argument(
        "--remat", default="full", choices=["full", "dots", "mlp", "none"],
        help="backward recompute schedule (mlp: save the expert GEMMs, "
        "rematerialize attention — the measured v5e sweet spot for "
        "--model flagship; for --model dense it is equivalent to dots)",
    )
    ap.add_argument("--data", default="",
                    help="1-D int token .npy (memmapped); batches are "
                         "next-token windows at step-indexed offsets")
    # checkpointing
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--ckpt-every", type=int, default=0)
    ap.add_argument("--resume", action="store_true")
    args = ap.parse_args(argv)

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    session = None
    if "UCCL_TPU_COORD" in os.environ:
        # Launched by scripts/launch.py (torchrun-shaped): join the
        # session BEFORE any device query so jax.distributed can assemble
        # the global device view.
        from uccl_tpu.parallel.distributed import initialize_from_env

        session = initialize_from_env()
        print(
            f"joined session rank {session.rank}/{session.world}", flush=True
        )

    from uccl_tpu.parallel.mesh import make_mesh

    # Multi-controller mode (scripts/launch.py with jax.distributed on):
    # every process sees the GLOBAL device list; batches must be assembled
    # as global arrays and only rank 0 narrates.
    multihost = session is not None and session.world > 1
    chatty = not multihost or session.rank == 0
    mcfg = parse_mesh(args.mesh)
    devices = jax.devices()
    if args.mesh and mcfg.size != len(devices):
        raise SystemExit(
            f"mesh size {mcfg.size} != device count {len(devices)}"
        )
    mesh = make_mesh(mcfg if args.mesh else None, devices)
    dp = mcfg.dp if args.mesh else len(devices)
    cp = mcfg.cp if args.mesh else 1
    if args.batch % dp or args.seq % cp:
        raise SystemExit(
            f"--batch {args.batch} must divide by dp={dp} and --seq "
            f"{args.seq} by cp={cp} (data is sharded [batch/dp, seq/cp])"
        )
    corpus = _open_corpus(args.data, args.vocab, args.seq) if args.data \
        else None
    cfg, params, train_step, init_opt = build(args, mesh)
    opt_state = init_opt(params)

    start = 0
    if args.resume:
        if not (args.ckpt_dir and os.path.isdir(args.ckpt_dir)):
            raise SystemExit("--resume needs an existing --ckpt-dir")
        latest = _latest_step(args.ckpt_dir)
        if latest is None:
            raise SystemExit(f"no step_N checkpoints in {args.ckpt_dir}")
        params, opt_state = _restore(
            args.ckpt_dir, latest, params, opt_state, mesh
        )
        start = latest
        if chatty:
            print(
                f"resumed from {args.ckpt_dir}/step_{latest}", flush=True
            )
    elif args.ckpt_dir and os.path.isdir(args.ckpt_dir) \
            and _latest_step(args.ckpt_dir) is not None:
        # fail BEFORE training, not at the first save (orbax refuses to
        # overwrite an existing step_N and would waste the whole run)
        raise SystemExit(
            f"{args.ckpt_dir} already holds checkpoints; pass --resume to "
            "continue from them or choose a fresh --ckpt-dir"
        )

    step = jax.jit(train_step)
    if multihost:
        # Every process builds the SAME deterministic global batch (cheap,
        # synthetic); make_array_from_callback hands each process only its
        # addressable shards of the [batch, seq] arrays, laid out exactly
        # as the model's data spec expects — no resharding inside jit.
        from jax.sharding import NamedSharding, PartitionSpec as P

        data_sharding = NamedSharding(mesh, P("dp", "cp"))

        def place(arr):
            return jax.make_array_from_callback(
                arr.shape, data_sharding, lambda idx: arr[idx]
            )
    else:
        place = None
    t0 = time.perf_counter()
    metrics = None
    for i in range(start, args.steps):
        tokens, targets = _batch_for_step(
            i, args.batch, args.seq, args.vocab, corpus
        )
        if place is not None:
            tokens, targets = place(tokens), place(targets)
        params, opt_state, metrics = step(params, opt_state, tokens, targets)
        if chatty and args.log_every and (i + 1) % args.log_every == 0:
            extra = (
                f" ce {float(metrics['ce']):.6f}" if "ce" in metrics else ""
            )
            print(
                f"step {i + 1:5d} loss {float(metrics['loss']):.6f}{extra}",
                flush=True,
            )
        if args.ckpt_dir and args.ckpt_every and (i + 1) % args.ckpt_every == 0:
            _save(args.ckpt_dir, i + 1, params, opt_state, model_cfg={
                "model": args.model, "vocab": args.vocab, "dim": args.dim,
                "layers": args.layers, "heads": args.heads,
                "kv_heads": args.kv_heads, "ffn": args.ffn,
                "experts": args.experts,
            })
            if chatty:
                print(f"checkpointed step {i + 1}", flush=True)
    dt = time.perf_counter() - t0
    done = args.steps - start
    summary = {
        "model": args.model,
        "mesh": {"pp": mcfg.pp, "dp": mcfg.dp, "cp": mcfg.cp, "tp": mcfg.tp}
        if args.mesh else {"dp": len(devices)},
        "steps": done,
        "final_loss": round(float(metrics["loss"]), 6) if metrics else None,
        "steps_per_sec": round(done / dt, 3) if done else 0.0,
    }
    if multihost:
        summary["processes"] = session.world
    if chatty:
        print(json.dumps(summary), flush=True)
    if session is not None:
        session.close()  # release the OOB store port/threads promptly


if __name__ == "__main__":
    main()
