"""Per-shard MoE expert-parallel primitives (use inside shard_map).

Capacity-based top-k routing + all-to-all dispatch/combine, the TPU-native
re-design of the reference's EP kernels (ep/src/internode_ll.cu dispatch:62 /
combine:747 pack per-expert token messages and RDMA them via a CPU proxy;
ep/src/layout.cu computes the dispatch layout). Here the same contracts are
static-shape einsums + ``lax.all_to_all`` so XLA can schedule the exchange on
ICI and keep the expert GEMMs on the MXU:

* :func:`route_topk`   — top-k gating with per-expert capacity, position
  assignment, load-balance + z losses (= get_dispatch_layout's counting,
  ep/bench/buffer.py:797, done as cumsums).
* :func:`dispatch`     — [T,H] tokens → [E_local, W*C, H] per-expert buffers on
  the owning EP member (= Buffer.dispatch).
* :func:`combine`      — weighted return path (= Buffer.combine).

Two implementations of the same contract:

* **dense** (``dispatch``/``combine``): one-hot ``[T,E,C]`` mask einsums —
  simple, always correct, kept as the oracle. Cost O(T·E·C·H) FLOPs.
* **sorted** (``dispatch_sorted``/``combine_sorted``): the fast path — a
  k-major stable argsort by expert id assigns capacity slots, dispatch is one
  [E·C, H]-row gather and combine a [T,K]-row gather, so cost is O(T·K·H)
  data movement with no mask tensor at all. This is the TPU re-design of the
  reference's ragged message packing (ep/src/internode_ll.cu:62 packs per-
  expert token messages; ep/src/layout.cu computes the layout): the argsort
  plays the role of the layout kernel, the gathers the role of the pack/unpack
  copies. Drop priority is identical to the dense path (earlier k-slots fill
  expert queues first, then token order), so the two paths agree exactly —
  including which tokens drop — at any capacity.

Token layout convention: ``E`` global experts, EP world ``W``, ``E_local=E/W``
experts per member, per-member capacity ``C`` tokens per expert per source
member. Dropped tokens (over capacity) contribute zero, matching
drop-and-renormalize MoE training semantics.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from uccl_tpu.collective import dma as _dma
from uccl_tpu.ops import quant as _quant
from uccl_tpu.ops.quant import dequantize_block, quantize_block

# checkpoint_name tags on the expert-GEMM operands/results, shared by the
# sort/dense path here, the ll path (ep.ll.grouped_ffn), and the
# remat="mlp" save policy (models.flagship._remat_wrap). A drifted name
# fails SILENTLY (the policy just stops matching and the memory win
# evaporates), so every site must import this tuple.
MOE_CHECKPOINT_NAMES = ("moe_xe", "moe_hg", "moe_hu", "moe_ye")
_XE, _HG, _HU, _YE = MOE_CHECKPOINT_NAMES

Axis = Union[str, Tuple[str, ...]]


class Routing(NamedTuple):
    """Routing decision for one shard's tokens."""

    dispatch_mask: jax.Array  # [T, E, C] one-hot slot assignment (bool)
    combine_weights: jax.Array  # [T, E, C] f32 gate weights at assigned slots
    aux_loss: jax.Array  # load-balance loss (scalar)
    z_loss: jax.Array  # router z-loss (scalar)
    counts: jax.Array  # [E] tokens kept per expert (before capacity the raw
    # demand is counts_raw; kept counts reflect drops)


def _gate_topk(router_logits, num_selected: int, renormalize: bool):
    """Shared gating math for both routing impls: softmax gates, z-loss,
    (renormalized) top-k selection, GShard load-balance loss.
    Returns (topk_vals [T,K], topk_idx [T,K], aux_loss, z_loss)."""
    e = router_logits.shape[-1]
    logits32 = router_logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits32, axis=-1)  # [T, E]
    # z-loss stabilizes router logits; load-balance loss follows GShard.
    z = jax.nn.logsumexp(logits32, axis=-1)
    z_loss = jnp.mean(z * z)

    topk_vals, topk_idx = lax.top_k(gates, num_selected)  # [T, K]
    if renormalize:
        topk_vals = topk_vals / jnp.maximum(
            jnp.sum(topk_vals, axis=-1, keepdims=True), 1e-9
        )

    # GShard load-balance loss: E * mean(fraction routed) . mean(gate prob)
    me = jnp.mean(gates, axis=0)  # [E]
    raw_onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.float32)  # [T, K, E]
    ce = jnp.mean(jnp.sum(raw_onehot, axis=1), axis=0)  # [E] fraction demand
    aux_loss = jnp.sum(me * ce) * (e / num_selected)
    return topk_vals, topk_idx, aux_loss, z_loss


def route_topk(
    router_logits: jax.Array,
    num_selected: int,
    capacity: int,
    *,
    renormalize: bool = True,
) -> Routing:
    """Top-k gating with per-expert capacity and in-expert position assignment.

    router_logits: [T, E]. Returns masks/weights of shape [T, E, C].
    """
    e = router_logits.shape[-1]
    topk_vals, topk_idx, aux_loss, z_loss = _gate_topk(
        router_logits, num_selected, renormalize
    )
    dispatch, combine, counts_running = masks_from_topk(
        topk_idx, topk_vals, e, capacity
    )
    return Routing(dispatch, combine, aux_loss, z_loss, counts_running)


def masks_from_topk(
    idx: jax.Array, wts: jax.Array, num_experts: int, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Build [T,E,C] dispatch/combine masks from explicit top-k assignments.

    Position assignment is sequential over the k slots so earlier choices fill
    expert queues first; over-capacity assignments drop (zero contribution).
    Returns (dispatch_mask bool, combine_weights f32, kept counts [E]).
    """
    t, k = idx.shape
    counts = jnp.zeros((num_experts,), jnp.int32)
    dispatch = jnp.zeros((t, num_experts, capacity), jnp.bool_)
    combine = jnp.zeros((t, num_experts, capacity), jnp.float32)
    for j in range(k):
        onehot = jax.nn.one_hot(idx[:, j], num_experts, dtype=jnp.int32)  # [T,E]
        # position of each token inside its expert's queue for this k-slot,
        # continuing from tokens already placed by earlier k-slots
        pos = jnp.cumsum(onehot, axis=0) - 1 + counts[None, :]
        keep = (pos < capacity) & (onehot > 0)
        slot = jax.nn.one_hot(pos, capacity, dtype=jnp.bool_)  # [T,E,C]
        d_j = slot & keep[..., None]
        dispatch = dispatch | d_j
        combine = combine + d_j.astype(jnp.float32) * wts[:, j][:, None, None]
        counts = counts + jnp.sum(keep.astype(jnp.int32), axis=0)
    return dispatch, combine, counts


class SortedRouting(NamedTuple):
    """Routing decision in sorted/ragged form (no [T,E,C] mask tensor)."""

    token_for_slot: jax.Array  # [E*C] int32 source token per slot (T = empty)
    slot: jax.Array  # [T, K] int32 slot per assignment (E*C = dropped)
    weights: jax.Array  # [T, K] f32 gate weights (renormalized)
    aux_loss: jax.Array  # load-balance loss (scalar)
    z_loss: jax.Array  # router z-loss (scalar)
    counts: jax.Array  # [E] tokens kept per expert


def counts_exchange(mat, axis):
    """[W, ...] per-destination rows → [W, ...] per-source rows (row s of
    the result is what source s computed for me). The counts/offsets
    exchange both dispatch paths use for receive bookkeeping (sorted-path
    recv_counts, LL recv_mat/offsets)."""
    return lax.all_to_all(mat, axis, split_axis=0, concat_axis=0, tiled=True)


def sorted_from_topk(
    idx: jax.Array, num_experts: int, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Slot assignment from explicit top-k expert ids via one stable argsort.

    idx: [T, K]. Flattening is k-major so earlier k-slots fill expert queues
    first (then token order) — byte-identical drop semantics to
    :func:`masks_from_topk`. Returns (token_for_slot [E*C] with T as the
    empty sentinel, slot [T, K] with E*C as the dropped sentinel,
    kept counts [E]).
    """
    t, k = idx.shape
    tk = t * k
    flat_e = idx.T.reshape(tk)  # k-major
    flat_t = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)
    order = jnp.argsort(flat_e, stable=True)
    sorted_e = flat_e[order]
    sorted_t = flat_t[order]
    counts = jnp.bincount(flat_e, length=num_experts)  # [E] raw demand
    seg_start = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    pos = jnp.arange(tk, dtype=jnp.int32) - seg_start[sorted_e].astype(jnp.int32)
    keep = pos < capacity
    slot_sorted = jnp.where(
        keep, sorted_e * capacity + pos, num_experts * capacity
    ).astype(jnp.int32)
    slot = (
        jnp.zeros((tk,), jnp.int32).at[order].set(slot_sorted).reshape(k, t).T
    )
    # Inverse view: which sorted position feeds slot (e, p)?
    slot_ids = jnp.arange(num_experts * capacity, dtype=jnp.int32)
    e_of_slot = slot_ids // capacity
    p_of_slot = slot_ids % capacity
    j = seg_start[e_of_slot].astype(jnp.int32) + p_of_slot
    kept = jnp.minimum(counts, capacity).astype(jnp.int32)
    valid = p_of_slot < kept[e_of_slot]
    token_for_slot = jnp.where(
        valid, sorted_t[jnp.clip(j, 0, tk - 1)], t
    ).astype(jnp.int32)
    return token_for_slot, slot, kept


class SlotPlan(NamedTuple):
    """The slot permutation of ONE routing decision, computed once and
    consumed by BOTH sides of the layer: dispatch gathers payload rows with
    ``token_for_slot`` (the forward permutation), combine gathers returned
    rows with ``slot`` (its inverse). Both views come out of the single
    stable argsort in :func:`sorted_from_topk`; building the plan once per
    routing decision (instead of re-deriving index math on each side) is
    what keeps the two sides structurally unable to disagree on drops —
    and gives the chunk-pipelined layer one shared index set to slice."""

    token_for_slot: jax.Array  # [E*C] int32 source token per slot (T = empty)
    slot: jax.Array  # [T, K] int32 slot per assignment (E*C = dropped)
    kept: jax.Array  # [E] int32 tokens kept per expert

    def chunk_token_for_slot(self, num_experts: int, n_chunks: int,
                             empty_sentinel: int) -> jax.Array:
        """Per-chunk gather indices for the pipelined layer: the [E*C] slot
        axis padded (``dma.pad_capacity`` — the shared rounding rule) with
        empty slots and resliced to [n_chunks, E * C_pad/n_chunks]. Padding
        lives only on the wire; it never changes which tokens drop."""
        cap = self.token_for_slot.shape[0] // num_experts
        cap_p = _dma.pad_capacity(cap, n_chunks)
        tfs = self.token_for_slot.reshape(num_experts, cap)
        if cap_p != cap:
            tfs = jnp.pad(tfs, ((0, 0), (0, cap_p - cap)),
                          constant_values=empty_sentinel)
        cs = cap_p // n_chunks
        return tfs.reshape(num_experts, n_chunks, cs).transpose(1, 0, 2)


def plan_slots(
    topk_idx: jax.Array, num_experts: int, capacity: int
) -> SlotPlan:
    """One argsort → the reusable :class:`SlotPlan` for a routing decision
    (dispatch- and combine-side gather indices plus kept counts)."""
    return SlotPlan(*sorted_from_topk(topk_idx, num_experts, capacity))


def route_topk_sorted(
    router_logits: jax.Array,
    num_selected: int,
    capacity: int,
    *,
    renormalize: bool = True,
) -> SortedRouting:
    """Top-k gating in sorted/ragged form — same math and losses as
    :func:`route_topk`, without materializing [T,E,C] masks."""
    e = router_logits.shape[-1]
    topk_vals, topk_idx, aux_loss, z_loss = _gate_topk(
        router_logits, num_selected, renormalize
    )
    token_for_slot, slot, kept = sorted_from_topk(topk_idx, e, capacity)
    return SortedRouting(token_for_slot, slot, topk_vals, aux_loss, z_loss, kept)


def dispatch_sorted(
    x: jax.Array,
    token_for_slot,
    num_experts: int,
    capacity: int,
    axis: Axis,
    *,
    wire_fp8: bool = False,
    quant_group: int = 128,
    wire: str = "lax",
    n_chunks: int = 1,
    wire_dtype=None,
    schedule=None,
) -> jax.Array:
    """Ragged dispatch: one gather packs [E*C, H] slot payloads, then the same
    member-major all-to-all as the dense path. Empty slots (sentinel index T,
    out of bounds) gather as zeros. ``token_for_slot`` may be the raw [E*C]
    index array or a :class:`SlotPlan` (the once-per-routing-decision form).
    ``n_chunks > 1`` splits the capacity axis of the pallas wire into that
    many double-buffered chunk kernels (identical numerics; lax wire
    ignores it — XLA owns that schedule). ``wire_dtype="fp8"|"int8"``
    block-quantizes the wire payload (wire_fp8=True = legacy "fp8").
    ``schedule`` runs the pallas wire one contention-free permutation
    round at a time (a2a_sched.wire_schedule; bit-identical output).
    Returns [E_local, W*C, H]."""
    if isinstance(token_for_slot, SlotPlan):
        token_for_slot = token_for_slot.token_for_slot
    w = lax.axis_size(axis)
    if num_experts % w:
        raise ValueError(f"experts {num_experts} not divisible by EP world {w}")
    e_local = num_experts // w
    h = x.shape[-1]
    buf = jnp.take(x, token_for_slot, axis=0, mode="fill", fill_value=0)
    buf = buf.reshape(w, e_local, capacity, h)
    cid = _dma.CID_SCHED if schedule is not None else _dma.CID_EP_DISPATCH
    buf = _wire_all_to_all(buf, axis, wire_fp8, quant_group, x.dtype, wire,
                           n_chunks=n_chunks, chunk_axis=2,
                           collective_id=cid,
                           wire_dtype=wire_dtype, schedule=schedule)
    return buf.transpose(1, 0, 2, 3).reshape(e_local, w * capacity, h)


def combine_sorted(
    expert_out: jax.Array,
    slot,
    weights: jax.Array,
    axis: Axis,
    *,
    wire_fp8: bool = False,
    quant_group: int = 128,
    wire: str = "lax",
    n_chunks: int = 1,
    wire_dtype=None,
    schedule=None,
) -> jax.Array:
    """Ragged combine: all-to-all the expert outputs home, then one [T, K]-row
    gather + weighted sum. Dropped assignments (sentinel slot E*C, out of
    bounds) gather as zeros. ``slot`` may be the raw [T, K] array or the
    :class:`SlotPlan` dispatch already used — the same permutation, never
    re-derived. ``schedule`` is the combine-direction round schedule (the
    dispatch matrix TRANSPOSED — traffic flows home). expert_out:
    [E_local, W*C, H] → [T, H]."""
    if isinstance(slot, SlotPlan):
        slot = slot.slot
    w = lax.axis_size(axis)
    e_local, wc, h = expert_out.shape
    c = wc // w
    buf = expert_out.reshape(e_local, w, c, h).transpose(1, 0, 2, 3)
    cid = (_dma.CID_SCHED_COMBINE if schedule is not None
           else _dma.CID_EP_COMBINE)
    buf = _wire_all_to_all(buf, axis, wire_fp8, quant_group,
                           expert_out.dtype, wire,
                           n_chunks=n_chunks, chunk_axis=2,
                           collective_id=cid,
                           wire_dtype=wire_dtype, schedule=schedule)
    y = buf.reshape(w * e_local * c, h)  # [E*C, H], expert-major
    yk = jnp.take(y, slot, axis=0, mode="fill", fill_value=0)  # [T, K, H]
    return jnp.einsum("tk,tkh->th", weights.astype(yk.dtype), yk)


def dispatch(
    x: jax.Array,
    dispatch_mask: jax.Array,
    axis: Axis,
    *,
    wire_fp8: bool = False,
    quant_group: int = 128,
    wire: str = "lax",
    wire_dtype=None,
) -> jax.Array:
    """Scatter local tokens to their experts' owners over the EP axis.

    x: [T, H]; dispatch_mask: [T, E, C] with E = W * E_local.
    Returns [E_local, W * C, H]: for each local expert, the capacity slots
    contributed by every source member (source-major order).
    """
    w = lax.axis_size(axis)
    t, e, c = dispatch_mask.shape
    if e % w:
        raise ValueError(f"experts {e} not divisible by EP world {w}")
    e_local = e // w
    buf = jnp.einsum(
        "tec,th->ech", dispatch_mask.astype(x.dtype), x
    )  # [E, C, H]
    buf = buf.reshape(w, e_local, c, x.shape[-1])
    buf = _wire_all_to_all(buf, axis, wire_fp8, quant_group, x.dtype, wire,
                           collective_id=_dma.CID_EP_DISPATCH,
                           wire_dtype=wire_dtype)
    # buf: [W, E_local, C, H] with dim0 = source member
    return buf.transpose(1, 0, 2, 3).reshape(e_local, w * c, x.shape[-1])


def _member_all_to_all(buf, axis, wire, *, n_chunks=1, chunk_axis=1,
                       collective_id=None, schedule=None):
    """One member-major [W, ...] exchange on the selected wire: the XLA
    collective ("lax") or the device-initiated Pallas remote-DMA kernel
    ("pallas", uccl_tpu.ep.pallas_a2a — falls back to lax past its VMEM
    budget). Both implement the identical tiled contract. ``n_chunks``/
    ``chunk_axis``/``collective_id``/``schedule`` reach only the pallas
    kernel (slot-axis chunking on 2-parity rotated ids; ``schedule`` —
    a ``(rounds, K)`` pair from a2a_sched.wire_schedule — swaps in the
    contention-aware per-round wire, bit-identical output); the lax wire
    is XLA-scheduled and ignores them."""
    if wire == "pallas":
        from uccl_tpu.ep import pallas_a2a

        if schedule is not None:
            return pallas_a2a.scheduled_all_to_all(
                buf, axis, schedule, n_chunks=n_chunks,
                chunk_axis=chunk_axis, collective_id=collective_id)
        return pallas_a2a.all_to_all(buf, axis, n_chunks=n_chunks,
                                     chunk_axis=chunk_axis,
                                     collective_id=collective_id)
    if wire != "lax":
        raise ValueError(f"unknown EP wire {wire!r} (want 'lax' or 'pallas')")
    return lax.all_to_all(buf, axis, split_axis=0, concat_axis=0, tiled=True)


# the ONE divisor rule every wire shares — re-exported under the
# long-standing name (uccl_tpu.ops.quant owns the codec now)
_adapt_quant_group = _quant.adapt_block


def resolve_wire_dtype(wire_fp8: bool, wire_dtype=None):
    """The EP knob-resolution rule: an explicit ``wire_dtype`` wins; the
    legacy ``wire_fp8`` bool maps to "fp8"; otherwise full precision."""
    wire_dtype = _quant.resolve_wire_dtype(wire_dtype)
    if wire_dtype is None and wire_fp8:
        wire_dtype = "fp8"
    return wire_dtype


def wire_itemsize(wire_fp8: bool, hidden: int, dtype,
                  quant_group: int = 128, wire_dtype=None) -> int:
    """Bytes per element the wire actually moves — the itemsize budget
    gates must charge: 1 when the block-scaled packing applies (fp8 or
    int8, identical 1-byte payloads), else the raw activation width
    (shared with ep_bench's transport labels so the gate's arithmetic is
    never mirrored)."""
    wire_dtype = resolve_wire_dtype(wire_fp8, wire_dtype)
    if (wire_dtype is not None
            and jnp.issubdtype(jnp.dtype(dtype), jnp.floating)
            and _quant.paying_block(hidden, quant_group)):
        return 1
    return jnp.dtype(dtype).itemsize


# the ONE wire-byte arithmetic (codec-owned now: the planner cost model,
# the ep_bytes_total counter and the benches all import the same rule) —
# re-exported under the long-standing EP name
wire_bytes_of = _quant.wire_bytes_of


def _wire_all_to_all(buf, axis, wire_fp8, quant_group, dtype, wire="lax", *,
                     n_chunks=1, chunk_axis=1, collective_id=None,
                     wire_dtype=None, schedule=None):
    """Member-major all-to-all of a [W, ...] buffer, optionally block-scale
    quantized on the wire (``wire_dtype="fp8"|"int8"``; ``wire_fp8=True``
    is the legacy spelling of "fp8" — the analog of internode_ll.cu's
    fp8+scales message packing). ``schedule`` selects the contention-aware
    per-round pallas wire; when quantizing, the scale exchange rides the
    same schedule on its own id lane (same rounds, same exactness)."""

    def xchg(rows, cid_off=0):
        cid = None if collective_id is None else collective_id + cid_off
        return _member_all_to_all(rows, axis, wire, n_chunks=n_chunks,
                                  chunk_axis=chunk_axis, collective_id=cid,
                                  schedule=schedule)

    wire_dtype = resolve_wire_dtype(wire_fp8, wire_dtype)
    if wire_dtype is not None and not jnp.issubdtype(
        jnp.dtype(buf.dtype), jnp.floating
    ):
        # same rule as the rings' _ring_wire_dtype: a non-float payload
        # rides the full-precision wire — counted, never silently cast
        # through the float codec
        _dma.record_fallback(
            "ep_wire_quant", "quant_dtype",
            detail=jnp.dtype(buf.dtype).name,
            msg=f"ep wire_dtype={wire_dtype!r} needs a float payload, got "
                f"{jnp.dtype(buf.dtype).name}; shipping full precision",
        )
        wire_dtype = None
    if wire_dtype is not None:
        group = _quant.paying_block(buf.shape[-1], quant_group)
        if group is None:
            # quantization would inflate traffic — ship raw, but never
            # silently: the quantized→full-precision downgrade is counted
            # like every other transparent wire decision
            _dma.record_fallback(
                "ep_wire_quant", "block_too_small",
                detail=(buf.shape[-1], quant_group),
                msg=f"ep wire_dtype={wire_dtype!r}: hidden {buf.shape[-1]} "
                    f"only admits blocks < 8 (requested {quant_group}); "
                    "scale overhead would exceed the payload saving — "
                    "shipping full precision",
            )
            return xchg(buf)
        q, scale = quantize_block(buf, wire_dtype, group)
        # scales ride their own id lane: the value and scale exchanges have
        # no data dependency and may be airborne together
        q = xchg(q)
        scale = xchg(scale, _dma.CID_SCALE_OFFSET)
        return dequantize_block(q, scale, group, dtype=dtype)
    return xchg(buf)


def combine(
    expert_out: jax.Array,
    combine_weights: jax.Array,
    axis: Axis,
    *,
    wire_fp8: bool = False,
    quant_group: int = 128,
    wire: str = "lax",
    wire_dtype=None,
) -> jax.Array:
    """Return expert outputs to their source members and weight-sum per token.

    expert_out: [E_local, W*C, H]; combine_weights: [T, E, C].
    Returns [T, H].
    """
    w = lax.axis_size(axis)
    t, e, c = combine_weights.shape
    e_local = e // w
    h = expert_out.shape[-1]
    buf = expert_out.reshape(e_local, w, c, h).transpose(1, 0, 2, 3)  # [W,E_l,C,H]
    buf = _wire_all_to_all(buf, axis, wire_fp8, quant_group,
                           expert_out.dtype, wire,
                           collective_id=_dma.CID_EP_COMBINE,
                           wire_dtype=wire_dtype)
    # buf: [W, E_local, C, H] with dim0 = owner member -> [E, C, H]
    buf = buf.reshape(e, c, h)
    out = jnp.einsum("tec,ech->th", combine_weights.astype(buf.dtype), buf)
    return out


def resolve_chunks(n_chunks: int, wire: str, world: int, capacity: int,
                   e_local: int, hidden: int, itemsize: int,
                   axis=None, wire_dtype=None) -> int:
    """Effective chunk count for the pipelined EP layer. ``0`` = auto: the
    :class:`~uccl_tpu.collective.plan.CollectivePlanner` picks the depth
    off its cost model (2 — the minimum that buys dispatch/compute/combine
    overlap — growing to 4/8 once the modeled wire time of one exchange
    dwarfs the per-launch gamma) on the pallas wire when the world and
    capacity can chunk, else 1. Any request
    collapses to 1 off the pallas wire (XLA owns the lax schedule), at world
    1 (no wire), on meshes the kernel cannot address (a tuple EP axis under
    the legacy discharge interpreter — every chunk would silently ride lax
    and the split would be pure overhead), or when the pipeline's resident
    footprint — 4 send+recv chunk pairs: two airborne kernels in EACH of
    the dispatch and combine families — is over budget. All of these are
    the automatic fallback to the unchunked wire. Every downgrade of an
    EXPLICITLY requested chunk pipeline (n_chunks > 1 on the pallas wire)
    is recorded on the ``ep_wire_fallback_total`` counter with its reason
    (docs/OBSERVABILITY.md) — ``0`` (auto) resolving to 1 on an
    unchunkable config is the correct auto answer, not a downgrade, and
    stays silent (the budget gate still counts either way: there a
    RESOLVED pipeline was pushed back). The resolved depth — including a
    downgraded 1 — lands on the ``ep_chunk_depth`` gauge AND on the plan
    counter (``collective_plan_total{algo="ep_a2a", chunks, wire_dtype}``)
    so benches label their chunk arms off the real resolution, not the
    requested knob."""
    n = _resolve_chunks_value(n_chunks, wire, world, capacity, e_local,
                              hidden, itemsize, axis)
    from uccl_tpu.collective import plan as _plan
    from uccl_tpu.obs import counters as _obsc

    _obsc.gauge(
        "ep_chunk_depth",
        "resolved chunk-pipeline depth of the last traced EP layer",
    ).set(n, what="moe_layer")
    _plan.get_planner().record_ep_chunks(n, wire=wire,
                                         wire_dtype=wire_dtype,
                                         auto=(n_chunks == 0))
    return n


def _resolve_chunks_value(n_chunks, wire, world, capacity, e_local, hidden,
                          itemsize, axis) -> int:
    requested = n_chunks > 1 and wire == "pallas"
    if wire != "pallas" or world <= 1 or capacity < 2:
        if requested:
            _dma.record_fallback(
                "ep_moe_chunked",
                "world_size" if world <= 1 else "capacity",
                detail=(world, capacity),
            )
        return 1
    if (
        axis is not None
        and isinstance(axis, (tuple, list))
        and len(axis) > 1
        and not _dma.faithful_sync(_dma.resolve_interpret(None))
    ):
        if requested:
            _dma.record_fallback("ep_moe_chunked", "tuple_axis_mesh",
                                 detail=tuple(axis))
        return 1
    if n_chunks == 0:
        # auto: the planner's cost model picks the depth from the modeled
        # wire time of ONE exchange vs the per-launch gamma
        from uccl_tpu.collective import plan as _plan

        n_chunks = _plan.get_planner().ep_auto_depth(
            world * e_local * capacity * hidden * itemsize, capacity
        )
    n_chunks = max(1, min(int(n_chunks), capacity))
    if n_chunks > 1:
        cs = _dma.pad_capacity(capacity, n_chunks) // n_chunks
        if not _dma.chunk_budget(world, e_local * cs * hidden, itemsize,
                                 "ep_moe_chunked", resident_kernels=4):
            n_chunks = 1  # chunk_budget already counted + logged the reason
    return n_chunks


def _expert_gemms(xe, w_gate, w_up, w_down):
    """The SwiGLU expert GEMMs with their checkpoint_name tags — ONE copy
    shared by the phased and chunk-pipelined layers so the remat="mlp"
    policy (which matches these exact tags) can never diverge between them.
    checkpoint_name tags let a remat policy pin exactly the expert-GEMM
    operands/results (see flagship._remat_wrap mode "mlp"): with these
    saved, the backward pass re-runs NO forward expert GEMM — the policy
    lever dots_with_no_batch_dims misses, because these einsums carry the
    `e` batch dim and are therefore excluded from it. (Keeping the
    BATCHED einsum form is deliberate: unrolling to per-expert 2-D dots
    measured 1.65x faster in isolation on v5e — scripts/
    expert_gemm_probe.py — but in the fused model context the end-to-end
    gain was <1%, and the unrolled dots lose their `e` batch dim, which
    silently drags every expert GEMM into the remat="dots" saved set and
    OOMs the documented-working B=32 dots config.)"""
    xe = checkpoint_name(xe, _XE)
    h_gate = checkpoint_name(jnp.einsum("ebh,ehf->ebf", xe, w_gate), _HG)
    h_up = checkpoint_name(jnp.einsum("ebh,ehf->ebf", xe, w_up), _HU)
    act = jax.nn.silu(h_gate) * h_up
    return checkpoint_name(jnp.einsum("ebf,efh->ebh", act, w_down), _YE)


def _moe_ffn_sort_chunked(
    x, plan: SlotPlan, weights, w_gate, w_up, w_down, axis,
    num_experts: int, capacity: int, n_chunks: int,
    wire_fp8: bool, quant_group: int, wire_dtype=None,
):
    """The chunk-pipelined sorted MoE step on the device-initiated wire.

    The capacity/slot axis is split into ``n_chunks`` (padded with empty
    slots by the shared ``dma.pad_capacity`` rule — drop semantics are those
    of the UNCHUNKED layer, always), and each chunk runs dispatch-a2a →
    expert GEMM → combine-a2a as its own dependency chain: chunk c's GEMM
    depends only on chunk c's dispatch, and the per-chunk Pallas kernels
    rotate 2-parity collective ids (dispatch {2,3}, combine {4,5}), so the
    remote DMA of dispatch chunk c+1 and the combine return of chunk c-1
    are free to fly while chunk c sits on the MXU — XLA's latency-hiding
    scheduler has both the dataflow freedom and the non-aliased semaphores
    it needs to hide the wire under compute. Slot rows are independent
    through the SwiGLU GEMMs and the a2a is position-preserving, so the
    result is numerically identical to the unchunked layer; the final
    token gather/weighted-sum runs once on the reassembled buffer (it is
    O(T·K·H) arithmetic XLA fuses into the consumer, not wire time)."""
    w = lax.axis_size(axis)
    e_local = num_experts // w
    t, h = x.shape
    tfs_chunks = plan.chunk_token_for_slot(num_experts, n_chunks, t)
    cs = tfs_chunks.shape[-1]
    recv_chunks, y_chunks = [], []
    for c in range(n_chunks):
        buf = jnp.take(x, tfs_chunks[c].reshape(-1), axis=0, mode="fill",
                       fill_value=0)
        buf = buf.reshape(w, e_local, cs, h)
        # launch-granularity credit (dma.tie_chunk): chunk c's wire waits
        # on chunk c-2's — its collective-id parity twin — so at most two
        # kernels per family are airborne, matching the 2-id rotation and
        # the 2-resident-pair budget charge
        buf = _dma.tie_chunk(
            buf, recv_chunks[c - 2] if c >= 2 else None
        )
        buf = _wire_all_to_all(
            buf, axis, wire_fp8, quant_group, x.dtype, "pallas",
            collective_id=_dma.chunk_collective_id(_dma.CID_EP_DISPATCH, c),
            wire_dtype=wire_dtype,
        )
        xe = buf.transpose(1, 0, 2, 3).reshape(e_local, w * cs, h)
        recv_chunks.append(xe)
        ye = _expert_gemms(xe, w_gate, w_up, w_down)
        back = ye.reshape(e_local, w, cs, h).transpose(1, 0, 2, 3)
        back = _dma.tie_chunk(
            back, y_chunks[c - 2] if c >= 2 else None
        )
        back = _wire_all_to_all(
            back, axis, wire_fp8, quant_group, ye.dtype, "pallas",
            collective_id=_dma.chunk_collective_id(_dma.CID_EP_COMBINE, c),
            wire_dtype=wire_dtype,
        )
        y_chunks.append(back.reshape(num_experts, cs, h))
    # reassemble the expert-major [E, C, H] buffer (chunks are contiguous
    # slices of each expert's padded capacity), drop the wire-only padding,
    # then ONE token gather + weighted sum — same math as combine_sorted
    y = jnp.concatenate(y_chunks, axis=1)[:, :capacity]
    y = y.reshape(num_experts * capacity, h)
    yk = jnp.take(y, plan.slot, axis=0, mode="fill", fill_value=0)
    return jnp.einsum("tk,tkh->th", weights.astype(yk.dtype), yk)


def moe_ffn(
    x: jax.Array,
    router_logits: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    axis: Axis,
    *,
    num_selected: int = 2,
    capacity_factor: float = 1.25,
    wire_fp8: bool = False,
    impl: str = "sort",
    wire: str = "lax",
    n_chunks: int = 1,
    wire_dtype=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full per-shard MoE layer: route → dispatch → SwiGLU experts → combine.

    x: [T, H]; router_logits: [T, E]; expert weights are the *local* shard:
    w_gate/w_up: [E_local, H, F], w_down: [E_local, F, H].
    impl: "sort" (ragged fast path, default), "dense" (mask-einsum oracle),
    or "ll" (packed low-latency path: grouped GEMMs over receive counts, no
    padded FLOPs — :mod:`uccl_tpu.ep.ll`; capacity_factor maps to its
    pair_capacity_factor bound).
    wire: "lax" (XLA collectives) or "pallas" (device-initiated remote-DMA
    all-to-all, :mod:`uccl_tpu.ep.pallas_a2a`); for impl="ll" the value maps
    onto that path's wire form ("pallas" selects its dense-chunk layout on
    the Pallas wire, anything else keeps its own auto resolution).
    n_chunks: chunk-pipeline depth on the pallas wire (0 = auto, 1 = strictly
    phased). With impl="sort" and n_chunks > 1 the layer runs the
    chunk-pipelined step (:func:`_moe_ffn_sort_chunked`: dispatch chunk c+1
    and combine chunk c-1 overlap the expert GEMM of chunk c); impl="ll"
    chunks its wire exchanges; the dense oracle ignores it.
    wire_dtype: block-scale quantize the dispatch/combine wire payloads
    ("fp8" | "int8"; the shared :mod:`uccl_tpu.ops.quant` codec —
    ``wire_fp8=True`` is the legacy spelling of "fp8"). Chunking composes
    bit-identically (blocks run along the hidden dim, untouched by the
    capacity split).
    Returns (out [T, H], aux_loss, z_loss).
    """
    t, h = x.shape
    e = router_logits.shape[-1]
    w = lax.axis_size(axis)
    capacity = max(1, int(capacity_factor * t * num_selected / e))
    wire_dtype = resolve_wire_dtype(wire_fp8, wire_dtype)
    if impl == "ll":
        from uccl_tpu.ep.ll import ll_moe_ffn

        return ll_moe_ffn(
            x, router_logits, w_gate, w_up, w_down, axis,
            num_selected=num_selected,
            pair_capacity_factor=capacity_factor,
            wire="pallas" if wire == "pallas" else "auto",
            wire_dtype=wire_dtype,
            n_chunks=n_chunks,
        )
    if impl == "sort":
        rs = route_topk_sorted(router_logits, num_selected, capacity)
        n_chunks = resolve_chunks(
            n_chunks, wire, w, capacity, e // w, h,
            wire_itemsize(wire_fp8, h, x.dtype, wire_dtype=wire_dtype),
            axis=axis, wire_dtype=wire_dtype,
        )
        if n_chunks > 1:
            plan = SlotPlan(rs.token_for_slot, rs.slot, rs.counts)
            out = _moe_ffn_sort_chunked(
                x, plan, rs.weights, w_gate, w_up, w_down, axis, e,
                capacity, n_chunks, False, 128, wire_dtype=wire_dtype,
            )
            return out.astype(x.dtype), rs.aux_loss, rs.z_loss
        xe = dispatch_sorted(
            x, rs.token_for_slot, e, capacity, axis, wire=wire,
            wire_dtype=wire_dtype,
        )
        aux_loss, z_loss = rs.aux_loss, rs.z_loss
    elif impl == "dense":
        r = route_topk(router_logits, num_selected, capacity)
        xe = dispatch(x, r.dispatch_mask, axis, wire=wire,
                      wire_dtype=wire_dtype)
        aux_loss, z_loss = r.aux_loss, r.z_loss
    else:
        raise ValueError(
            f"unknown moe impl {impl!r} (want 'sort', 'dense', or 'll')"
        )
    # tagged SwiGLU GEMMs shared with the chunked layer (the tags and the
    # batched einsum form are load-bearing for remat — see _expert_gemms)
    ye = _expert_gemms(xe, w_gate, w_up, w_down)
    if impl == "sort":
        out = combine_sorted(ye, rs.slot, rs.weights, axis, wire=wire,
                             wire_dtype=wire_dtype)
    else:
        out = combine(ye, r.combine_weights, axis, wire=wire,
                      wire_dtype=wire_dtype)
    return out.astype(x.dtype), aux_loss, z_loss
