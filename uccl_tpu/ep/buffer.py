"""DeepEP-shaped host API for expert-parallel dispatch/combine.

The reference exposes EP through a ``Buffer`` class with a DeepEP-identical
surface (ep/src/uccl_ep.cc:348; python mirror ep/bench/buffer.py —
``get_dispatch_layout``:797, ``dispatch``, ``combine``,
``low_latency_dispatch``:285, ``low_latency_combine``:454). This Buffer keeps
those verbs and tensor contracts in jax-global form: arrays carry a leading EP
rank dimension (one row per EP member, sharded over the EP mesh axes), and each
verb is a cached jit of the per-shard primitives in :mod:`uccl_tpu.ep.ops`.

``low_latency_*`` maps to the fp8-wire path (the reference's LL kernels pack
fp8+scales, internode_ll.cu:62); normal dispatch/combine move payloads at full
precision (the reference's "normal" internode mode).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from uccl_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uccl_tpu.ep import ll as ep_ll
from uccl_tpu.ep import ops as ep_ops
from uccl_tpu.obs import counters as _obsc
from uccl_tpu.obs import tracer as _obst
from uccl_tpu.parallel.mesh import AXIS, get_mesh, mesh_axis_size
from uccl_tpu.utils.logging import get_logger

_log = get_logger("EP")

# host-level wire telemetry: WIRE bytes of the payload handed to each EP
# verb (the global [W, ...] array — what the exchange moves end to end):
# quantized payload + f32 scale sidecar when a wire_dtype applies
# (ep_ops.wire_bytes_of — the one arithmetic benches share), raw element
# bytes otherwise; labeled by verb, the wire that carried it, and the
# wire_dtype ("none" = full precision). The companion span on the "wire"
# track measures the verb's HOST call window (dispatch + any compile on
# first call) — device time proper belongs to jax.profiler.
EP_BYTES = _obsc.counter(
    "ep_bytes_total",
    "actual wire bytes moved by EP verbs and ring collectives (quantized "
    "payload + f32 scale sidecar when a wire_dtype applies, raw element "
    "bytes otherwise), by verb, wire, and wire_dtype",
)


def _observed_call(verb: str, fn, args, *, wire: str, n_chunks: int,
                   payload, wire_dtype=None) -> tuple:
    """Run one verb's jitted fn under the bytes counter + wire span."""
    nbytes = ep_ops.wire_bytes_of(payload.shape, payload.dtype, wire_dtype)
    EP_BYTES.inc(nbytes, verb=verb, wire=wire,
                 wire_dtype=wire_dtype or "none")
    with _obst.span(f"ep.{verb}", track="wire", wire=wire,
                    n_chunks=n_chunks, bytes=nbytes,
                    wire_dtype=wire_dtype or "none"):
        return fn(*args)


class EventOverlap:
    """The overlap half of the DeepEP contract, re-expressed in dataflow.

    On GPU, DeepEP records a CUDA event after the comm kernels and consumers
    either wait on it from the current stream or pass it as
    ``previous_event`` to order a later kernel behind it
    (``EventOverlap`` in the reference's ep/bench/utils.py, used throughout
    ep/bench/buffer.py:285-464). On TPU there are no user-visible streams —
    XLA's async dispatch makes every returned array a future, and ordering
    is dataflow. This class therefore wraps the arrays a verb produced:

    * ``current_stream_wait()`` — host-side barrier on those arrays (the
      analog of ``event.current_stream_wait()``; jax arrays self-order for
      device consumers, so this is only needed for host readbacks/timing).
    * as ``previous_event`` — the next verb ties its computation to this
      event's token array with ``lax.optimization_barrier``, so the later
      jit cannot begin before the earlier verb's outputs exist (a REAL
      cross-jit dependency, not a host sync; an unused jit arg would be
      pruned, hence the explicit tie).
    """

    def __init__(self, arrays):
        self._arrays = arrays

    @property
    def token(self) -> jax.Array:
        """A representative array consumers tie ordering to (global form,
        leading EP-rank dim)."""
        return jax.tree.leaves(self._arrays)[0]

    def current_stream_wait(self) -> None:
        jax.block_until_ready(self._arrays)

    wait = current_stream_wait


def _tie(x, tok):
    """Order ``x`` after ``tok`` inside a jit without consuming values."""
    x, _ = lax.optimization_barrier((x, tok))
    return x


@dataclasses.dataclass(frozen=True)
class Config:
    """Tuning hints — the TPU mapping of the reference ``Config`` row
    ``(num_sms, send_tokens, recv_tokens, rdma_send_tokens, chunk)`` from
    ep/bench/buffer.py:741-796. SM counts and NVL/RDMA chunk depths have no
    TPU meaning; the knobs that do are the wire form, fp8 packing, and
    recv-buffer sizing. A Config only fills knobs the caller left unset —
    an explicit keyword always wins.

    ``wire`` picks the transport: ``ragged``/``dense`` are the LL layouts on
    XLA collectives, ``pallas`` is the device-initiated remote-DMA
    all-to-all (:mod:`uccl_tpu.ep.pallas_a2a`; applies to BOTH the normal
    and LL verbs), ``auto`` defers to the Buffer/backend resolution.
    ``n_chunks`` is the pallas-wire chunk-pipeline depth (0 = auto, 1 =
    strictly phased; ignored off the pallas wire). ``wire_dtype`` picks the
    block-quantized wire payload ("fp8" | "int8", the shared ops.quant
    codec; None defers to ``wire_fp8``/the Buffer default)."""

    max_tokens_per_rank: Optional[int] = None  # LL recv-buffer sizing
    pair_capacity_factor: Optional[float] = None  # dense-wire pair capacity
    wire: str = "auto"  # ragged | dense | pallas | auto
    wire_fp8: bool = True
    n_chunks: Optional[int] = None  # pallas chunk-pipeline depth (0 = auto)
    wire_dtype: Optional[str] = None  # fp8 | int8 | None (full precision)
    a2a_sched: Optional[str] = None  # off | on | auto (None = Buffer's)


class DispatchHandle(NamedTuple):
    """Opaque handle threaded from dispatch to combine (the analog of the
    reference's handle tuple, ep/bench/buffer.py dispatch returns). Compact
    sorted-form routing — O(T·K) per rank, not a dense [T,E,C] mask.

    ``recv_counts`` mirrors the reference handle's received-row bookkeeping:
    entry [w, s, le] is how many of source s's rows landed for shard w's
    local expert le — i.e. the occupancy of the [s*C, s*C+C) chunk of
    ``recv_x[w, le]``. A consumer can skip empty slots or size grouped GEMMs
    from it instead of assuming full capacity.

    ``wire`` records which transport carried dispatch ("lax" XLA collective
    or "pallas" device-initiated remote DMA) and ``n_chunks`` its
    chunk-pipeline depth, so combine retraces the same path without
    re-resolving — the same role LowLatencyHandle.wire plays.
    ``wire_dtype`` records dispatch's quantized wire payload (audit +
    stats; combine resolves its OWN quantization — get_combine_config
    deliberately keeps the return path full-precision by default, since
    gate weights amplify combine error)."""

    slot: jax.Array  # [W, T, K] int32 slot per assignment (E*C = dropped)
    weights: jax.Array  # [W, T, K] f32 gate weights
    recv_counts: jax.Array  # [W, W_src, E_local] int32 (always populated)
    wire: str = "lax"  # lax | pallas (defaulted: pre-wire handles pickle)
    n_chunks: int = 1  # pallas chunk depth (defaulted: pre-chunk handles)
    wire_dtype: Optional[str] = None  # fp8 | int8 | None (pre-quant: None)
    a2a_sched: bool = False  # dispatch rode the scheduled rounds; combine
    #   rebuilds the TRANSPOSED schedule (defaulted: pre-sched handles)


class LowLatencyHandle(NamedTuple):
    """Handle for the packed low-latency path (ep/ll.py): the global [W, ...]
    form of :class:`uccl_tpu.ep.ll.LLState` plus the static wire choice —
    DeepEP keeps the same bookkeeping inside its returned handle tuple
    (ep/bench/buffer.py:285-454)."""

    send_slot: jax.Array  # [W, T, K]
    weights: jax.Array  # [W, T, K]
    send_mat: jax.Array  # [W, W, E_local]
    recv_mat: jax.Array  # [W, W, E_local]
    regroup: jax.Array  # [W, R_max]
    src_in_offsets: jax.Array  # [W, W]
    wire: str
    wire_fp8: bool
    n_chunks: int = 1  # pallas chunk depth (defaulted: pre-chunk handles)
    wire_dtype: Optional[str] = None  # resolved quantized payload (None =
    #   wire_fp8 decides — pre-quant handles unpickle to that legacy rule)


class Buffer:
    """Expert-parallel buffer bound to a mesh's EP axes.

    Args mirror the reference Buffer's construction knobs (group/world implied
    by the mesh; hidden size checked at call time; capacity via factor).

    ``wire`` selects the transport every verb rides unless a call overrides
    it: ``"auto"`` keeps today's resolution (XLA collectives; ragged LL wire
    where the backend lowers it), ``"pallas"`` routes the member-major
    exchanges of BOTH the normal (sorted) and low-latency row formats
    through the device-initiated remote-DMA all-to-all kernel
    (:mod:`uccl_tpu.ep.pallas_a2a`), keeping ``lax`` as the transparent
    fallback past its VMEM budget or where the kernel cannot address the
    mesh (legacy interpreters on multi-axis meshes).

    ``n_chunks`` sets the pallas wire's chunk-pipeline depth: the
    capacity/slot axis splits into that many double-buffered per-chunk
    kernels on rotated collective ids, so a consumer's expert compute can
    hide under the neighboring chunks' DMAs (0 = auto, 1 = strictly
    phased). Identical numerics either way; over the 2x double-buffer
    budget the verbs fall back to the unchunked wire automatically, and
    the knob is ignored off the pallas wire.

    ``wire_dtype`` quantizes every verb's wire payload with the shared
    block-scale codec ("fp8" | "int8", :mod:`uccl_tpu.ops.quant`; values +
    per-block f32 scales move, one quantize round trip of error per
    exchange — docs/QUANT_WIRE.md). Per-call ``wire_dtype=``/``wire_fp8=``
    keywords and a Config override it; None keeps full precision.

    ``a2a_sched`` orders the pallas wire's exchange as contention-free
    permutation rounds built from ``a2a_traffic`` (the host [W, W] routing
    matrix; :mod:`uccl_tpu.ep.a2a_sched`): "on" pins the schedule, "auto"
    lets the planner flip between it and the fixed streams off the traffic
    skew, "off" (default) keeps the streams. Bit-identical output either
    way — the schedule reorders the same write-once DMAs."""

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        axis=AXIS.EP,
        *,
        num_experts: int,
        num_selected: int = 2,
        capacity_factor: float = 1.25,
        wire: str = "auto",
        n_chunks: int = 1,
        wire_dtype: Optional[str] = None,
        a2a_sched: str = "off",
        a2a_traffic=None,
    ):
        self.mesh = mesh if mesh is not None else get_mesh()
        self.axes = (axis,) if isinstance(axis, str) else tuple(axis)
        self.world = mesh_axis_size(self.mesh, self.axes)
        if num_experts % self.world:
            raise ValueError(
                f"num_experts {num_experts} must divide EP world {self.world}"
            )
        if wire not in ("auto", "ragged", "dense", "pallas"):
            raise ValueError(
                f"unknown wire {wire!r} (want 'auto', 'ragged', 'dense', or "
                "'pallas')"
            )
        if n_chunks < 0:
            raise ValueError(f"n_chunks must be >= 0 (0 = auto), got "
                             f"{n_chunks}")
        if a2a_sched not in ("off", "on", "auto"):
            raise ValueError(
                f"unknown a2a_sched {a2a_sched!r} (want 'off', 'on', or "
                "'auto')"
            )
        from uccl_tpu.ops import quant as _quant

        self.num_experts = num_experts
        self.num_local_experts = num_experts // self.world
        self.num_selected = num_selected
        self.capacity_factor = capacity_factor
        self.wire = wire
        self.n_chunks = n_chunks
        self.wire_dtype = _quant.resolve_wire_dtype(wire_dtype)
        # contention-aware a2a rounds (uccl_tpu.ep.a2a_sched): "on" always
        # rides the Birkhoff schedule on the pallas wire, "auto" lets the
        # planner arbitrate off the traffic skew. ``a2a_traffic`` is the
        # host [W, W] per-step routing matrix the schedule is built from
        # (a2a_sched.traffic_from_topk / zipf_topk; None = uniform, which
        # auto correctly answers with the fixed streams). Static per
        # Buffer — a new routing regime warrants a new matrix, i.e. a new
        # Buffer or an explicit re-assignment before the next dispatch.
        self.a2a_sched = a2a_sched
        self.a2a_traffic = (None if a2a_traffic is None
                            else np.asarray(a2a_traffic, float))
        self._cache = {}
        # host-path wire/chunk resolutions memoize per distinct config:
        # the fallback counter's contract is one event per compiled
        # program (collective/dma.py WIRE_FALLBACK), and these decisions
        # are static per (buffer, shape/knob tuple) — re-resolving them on
        # every verb call of a hot serving loop would re-count a single
        # decision thousands of times
        self._resolve_memo = {}
        # per-op stats (reference: EP Stats bound at uccl_ep.cc:2411 and the
        # dispatch_wait_recv_cost_stats tensor plumbed through
        # internode_ll.cu:66): op counters update eagerly; row/byte
        # aggregates are computed lazily from saved device refs in stats()
        self._op_counts = {
            "dispatch": 0, "combine": 0,
            "low_latency_dispatch": 0, "low_latency_combine": 0,
            "get_dispatch_layout": 0,
        }
        self._last_dispatch = None  # (topk_idx ref, capacity)
        self._last_ll = None  # (group_sizes ref, r_max, hidden, wire_fp8)
        # flight-bundle face (obs/flight.py): host-resident EP state only
        # — stats() syncs saved device refs, which a post-mortem dump
        # mid-failure must never do
        from uccl_tpu.obs import flight as _obsf

        _obsf.register_provider("ep_buffer", self._flight_state)

    def _flight_state(self) -> dict:
        return {
            "world": self.world,
            "num_experts": self.num_experts,
            "wire": self.wire,
            "wire_dtype": str(self.wire_dtype),
            "a2a_sched": self.a2a_sched,
            "ops": dict(self._op_counts),
        }

    # ------------------------------------------------------------------
    def _axis_name(self):
        return self.axes if len(self.axes) > 1 else self.axes[0]

    def _pallas_wire_ok(self) -> bool:
        """Whether the Pallas all-to-all can address this mesh in the mode
        it would trace under: always, for real Mosaic lowering or the
        faithful TPU interpreter; on the legacy discharge interpreter (jax
        0.4.x CPU runs) only single-named-axis meshes are addressable."""
        from uccl_tpu.collective import dma

        return (
            dma.faithful_sync(dma.interpret_default())
            or len(self.mesh.axis_names) == 1
        )

    def _resolve_wire(self, requested, config) -> str:
        """Effective wire for a verb: explicit call value, else the Config,
        else the Buffer's. "pallas" downgrades to "auto" (with a log) where
        the kernel cannot address the mesh, so the surface stays
        transparent."""
        wire = requested if requested is not None else "auto"
        if wire == "auto" and config is not None:
            wire = config.wire
        if wire == "auto":
            wire = self.wire
        if wire == "pallas" and not self._pallas_wire_ok():
            # static per Buffer (mesh + interpreter): count/log the
            # downgrade once, not per verb call
            if "wire_downgrade" not in self._resolve_memo:
                self._resolve_memo["wire_downgrade"] = True
                from uccl_tpu.collective import dma

                dma.record_fallback(
                    "buffer_verb", "legacy_interpret_mesh",
                    detail=tuple(self.mesh.axis_names),
                    msg="wire='pallas' cannot address a multi-axis mesh "
                        "under the legacy interpret mode; falling back to "
                        "the XLA wire",
                )
            wire = "auto"
        return wire

    def _resolve_chunks(self, requested, config, wire: str) -> int:
        """Effective chunk-pipeline depth for a verb: explicit call value,
        else the Config, else the Buffer's. Collapses to 1 off the pallas
        wire or at world 1; 0 stays 0 (= auto) for the per-shard resolver,
        which also owns the double-buffer budget fallback."""
        n = requested
        if n is None and config is not None:
            n = config.n_chunks
        if n is None:
            n = self.n_chunks
        n = int(n)
        if n < 0:  # same contract as the Buffer constructor
            raise ValueError(f"n_chunks must be >= 0 (0 = auto), got {n}")
        if wire != "pallas" or self.world <= 1:
            # an EXPLICIT depth > 1 on the pallas wire collapsing at world
            # 1 is the same downgrade the per-shard resolvers record —
            # count it here too (once: the world is static per Buffer), or
            # counter coverage would depend on which call path resolved it
            if n > 1 and wire == "pallas" and self.world <= 1 \
                    and "chunks_world" not in self._resolve_memo:
                self._resolve_memo["chunks_world"] = True
                from uccl_tpu.collective import dma

                dma.record_fallback("buffer_verb", "world_size",
                                    detail=self.world)
            return 1
        return n

    def _resolve_wire_dtype(self, wire_dtype, wire_fp8, config,
                            default_fp8: bool = False):
        """Effective quantized wire payload for a verb: explicit
        ``wire_dtype`` keyword, else the explicit ``wire_fp8`` bool (True =
        "fp8", False = full precision), else the Config (its wire_dtype,
        then its wire_fp8), else the Buffer default, else ``default_fp8``
        (the LL verbs' legacy fp8-on default)."""
        from uccl_tpu.ops import quant as _quant

        if wire_dtype is not None:
            return _quant.resolve_wire_dtype(wire_dtype)
        if wire_fp8 is not None:
            return "fp8" if wire_fp8 else None
        if config is not None:
            if config.wire_dtype is not None:
                return _quant.resolve_wire_dtype(config.wire_dtype)
            if config.wire_fp8 is not None:
                return "fp8" if config.wire_fp8 else None
        if self.wire_dtype is not None:
            return self.wire_dtype
        return "fp8" if default_fp8 else None

    def _sched_chunk_charge(self, n_chunks: int, cap: int, slot_elems: int):
        """Per-chunk per-peer element count of the chunk-pipelined
        scheduled wire — pallas_a2a._scheduled_chunked's own arithmetic
        (slot axis padded to a chunk multiple), so plan_ep_a2a's budget
        probe charges exactly what the device gate will. None when the
        effective depth degenerates to 1 (monolithic gate applies)."""
        from uccl_tpu.collective import dma as _dma

        nc = min(int(n_chunks), int(cap))
        if nc <= 1:
            return None
        return int(slot_elems) * (_dma.pad_capacity(int(cap), nc) // nc)

    def _resolve_a2a_sched(self, config, wire: str, verb: str,
                           payload_shape, dtype, wire_dtype,
                           n_chunks: int = 1):
        """Effective round schedule for a verb's exchange, or None for the
        fixed streams: resolution Config > Buffer mode, then — on the
        pallas wire at world > 1 — the Birkhoff schedule is built from the
        Buffer's traffic matrix (combine sees it TRANSPOSED: traffic flows
        home) and either pinned ("on", recorded as an explicit plan) or
        arbitrated by the planner off the skew ("auto",
        CollectivePlanner.plan_ep_a2a). Memoized per static config — the
        decision, the plan counter event and the rounds/skew series fire
        once per compiled program, like every other host resolution."""
        mode = None
        if config is not None and config.a2a_sched is not None:
            mode = config.a2a_sched
        if mode is None:
            mode = self.a2a_sched
        if mode not in ("off", "on", "auto"):
            raise ValueError(
                f"unknown a2a_sched {mode!r} (want 'off', 'on', or 'auto')"
            )
        if mode == "off" or self.world <= 1:
            return None
        if wire != "pallas":
            # an explicit "on" off the pallas wire is a real downgrade (the
            # lax wire has no round order to steer) — counted once
            if mode == "on" and "a2a_sched_wire" not in self._resolve_memo:
                self._resolve_memo["a2a_sched_wire"] = True
                from uccl_tpu.collective import dma

                dma.record_fallback(
                    "ep_a2a_sched", "wire", detail=wire,
                    msg="a2a_sched='on' needs the pallas wire (XLA owns "
                        "the lax schedule); riding the fixed streams",
                )
            return None
        mat = self.a2a_traffic
        if mat is None:
            mat = np.ones((self.world, self.world), float)
            np.fill_diagonal(mat, 0.0)
        mat = np.asarray(mat, float)
        if verb == "combine":
            mat = mat.T
        key = ("a2a_sched", mode, verb, tuple(payload_shape),
               jnp.dtype(dtype).name, wire_dtype, n_chunks, mat.tobytes())
        if key in self._resolve_memo:
            return self._resolve_memo[key]
        from uccl_tpu.collective.plan import get_planner
        from uccl_tpu.ep import a2a_sched as _sched

        schedule = _sched.wire_schedule(mat, self.world)
        n_rounds = len(schedule[0])
        planner = get_planner()
        if mode == "on":
            planner.plan_explicit("ep_sched", payload_shape, dtype,
                                  self.world, wire_dtype=wire_dtype,
                                  verb="ep_a2a")
            algo = "ep_sched"
        else:
            # the wire buffer is [W, E_local, C, H] for both verbs; its
            # chunked slot axis is C, so the per-chunk per-peer charge
            # (what _scheduled_chunked's gate checks) is E_local*cs*H.
            # dispatch's payload_shape is that buffer; combine's is the
            # [E_local, W*C, H] expert view of the same bytes.
            elems = int(np.prod(payload_shape))
            cap = int(payload_shape[-2])
            if verb != "dispatch":
                cap //= self.world
            slot_elems = elems // self.world // max(cap, 1)
            cep = (self._sched_chunk_charge(n_chunks, cap, slot_elems)
                   if n_chunks > 1 and cap else None)
            algo = planner.plan_ep_a2a(
                payload_shape, dtype, self.world,
                skew=_sched.skew(mat), n_rounds=n_rounds,
                wire_dtype=wire_dtype,
                n_chunks=n_chunks if cep is not None else 1,
                chunk_elems_per_peer=cep,
            ).algo
        _sched.record_decision(
            algo, self.world,
            n_rounds=n_rounds if algo == "ep_sched" else None,
            matrix=mat,
        )
        result = schedule if algo == "ep_sched" else None
        self._resolve_memo[key] = result
        return result

    def _spec(self, extra_dims: int) -> P:
        return P(self.axes, *([None] * extra_dims))

    def _jit(self, key, fn, n_in_extra, n_out_extra):
        cached = self._cache.get(key)
        if cached is None:
            in_specs = tuple(self._spec(d) for d in n_in_extra)
            out_specs = jax.tree.map(lambda d: self._spec(d), n_out_extra)
            cached = jax.jit(
                shard_map(
                    fn,
                    mesh=self.mesh,
                    in_specs=in_specs,
                    out_specs=out_specs,
                    check_vma=False,
                )
            )
            self._cache[key] = cached
        return cached

    def stats(self) -> dict:
        """Per-op EP stats (reference: the `Stats` class bound at
        uccl_ep.cc:2411 + the dispatch cost tensors internode_ll.cu:66):
        op counters plus aggregates of the LAST dispatch of each mode —
        routed/kept/dropped rows for the capacity path (computed from the
        routing demand vs capacity, the exact drop rule of the sorted
        layout), and recv rows + approximate wire payload bytes for the
        low-latency path. Reading materializes saved device values (a sync
        point) — call it off the hot loop, like the reference's stats
        thread."""
        out = {"ops": dict(self._op_counts)}
        if self._last_dispatch is not None:
            idx, cap = self._last_dispatch
            idx_np = np.asarray(idx)  # [W, T, K]
            # capacity bounds each SOURCE shard's rows per expert (the
            # sorted layout assigns cap slots per expert per shard), so the
            # drop rule applies shard-wise before summing
            routed = kept = 0
            for r in range(idx_np.shape[0]):
                flat = idx_np[r].reshape(-1)
                # -1 = "no expert" (DeepEP-supported): claims no slot, so it
                # must not be counted as expert-0 demand
                flat = flat[flat >= 0]
                d = np.bincount(flat, minlength=self.num_experts)
                routed += int(d.sum())
                kept += int(np.minimum(d, cap).sum())
            out["dispatch"] = {
                "capacity": int(cap),
                "routed_rows": routed,
                "kept_rows": kept,
                "dropped_rows": routed - kept,
                "drop_fraction": float((routed - kept) / max(1, routed)),
            }
        if self._last_ll is not None:
            counts, r_max, hidden, wire_fp8 = self._last_ll
            rows = int(np.asarray(counts).sum())
            payload = hidden * (1 if wire_fp8 else 2)
            out["low_latency"] = {
                "recv_rows": rows,
                "r_max_per_rank": int(r_max),
                "wire_payload_bytes": rows * payload,
            }
        return out

    @staticmethod
    def get_dispatch_config(num_ranks: int) -> Config:
        """Recommended dispatch config per EP world size (the role of
        ep/bench/buffer.py:741 ``get_dispatch_config``). Small worlds ride
        the ragged wire; larger worlds shrink the dense-wire pair capacity
        so padded slots don't dominate the exchanged volume."""
        if num_ranks <= 8:
            return Config(wire="auto", wire_fp8=True)
        if num_ranks <= 32:
            return Config(wire="auto", wire_fp8=True,
                          pair_capacity_factor=1.0)
        return Config(wire="auto", wire_fp8=True, pair_capacity_factor=0.75)

    @staticmethod
    def get_combine_config(num_ranks: int) -> Config:
        """Recommended combine config per EP world size (reference
        get_combine_config, ep/bench/buffer.py:771), consumable by the
        normal-mode :meth:`combine` ``config=`` parameter. Combine payloads
        stay bf16/f32 (gate weights are applied at the destination, so fp8
        error would be amplified by the reduction), hence wire_fp8=False."""
        cfg = Buffer.get_dispatch_config(num_ranks)
        return dataclasses.replace(cfg, wire_fp8=False)

    def capacity(self, num_tokens: int) -> int:
        return max(
            1,
            int(
                self.capacity_factor
                * num_tokens
                * self.num_selected
                / self.num_experts
            ),
        )

    def device_put(self, x) -> jax.Array:
        x = jnp.asarray(x)
        return jax.device_put(
            x, NamedSharding(self.mesh, self._spec(x.ndim - 1))
        )

    # ------------------------------------------------------------------
    def get_dispatch_layout(self, topk_idx: jax.Array):
        """topk_idx: [W, T, K] global expert ids.

        Returns (num_tokens_per_rank [W, W], num_tokens_per_expert [W, E],
        is_token_in_rank [W, T, W]) — the counting contract of the reference's
        get_dispatch_layout (ep/bench/buffer.py:797) minus the CUDA event.
        """
        e, w = self.num_experts, self.world
        e_local = self.num_local_experts
        key = ("layout", topk_idx.shape)

        def f(idx):
            idx = idx[0]  # [T, K]
            onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)  # [T, K, E]
            per_expert = jnp.sum(onehot, axis=(0, 1))  # [E]
            per_rank_tok = (
                jnp.sum(onehot, axis=1).reshape(-1, w, e_local).sum(-1) > 0
            )  # [T, W] token touches rank
            per_rank = jnp.sum(per_rank_tok.astype(jnp.int32), axis=0)  # [W]
            return (
                per_rank[None],
                per_expert[None],
                per_rank_tok[None],
            )

        fn = self._jit(key, f, (2,), (1, 1, 2))
        self._op_counts["get_dispatch_layout"] += 1
        return fn(topk_idx)

    def dispatch(
        self,
        x: jax.Array,
        topk_idx: jax.Array,
        topk_weights: Optional[jax.Array] = None,
        *,
        wire_fp8: Optional[bool] = None,
        wire_dtype: Optional[str] = None,
        config: Optional[Config] = None,
        previous_event: Optional[EventOverlap] = None,
        async_finish: bool = False,
        allocate_on_comm_stream: bool = False,
    ):
        """x: [W, T, H]; topk_idx: [W, T, K]; topk_weights: [W, T, K] (defaults
        to uniform 1/K). Returns (recv_x [W, E_local, W*C, H], handle), plus
        an :class:`EventOverlap` when ``async_finish`` is set.

        ``wire_dtype`` ("fp8" | "int8") block-quantizes the wire payload
        (``wire_fp8=True`` is the legacy spelling of "fp8"; resolution:
        explicit keyword > Config > Buffer default).

        Overlap knobs (reference dispatch, ep/bench/buffer.py:801-824):
        ``config`` fills wire knobs the caller left unset (explicit keywords
        win); ``previous_event`` orders this dispatch after another verb's
        event by dataflow; ``async_finish`` returns an event to wait on /
        chain from; ``allocate_on_comm_stream`` is stream-allocator
        bookkeeping with no TPU meaning — accepted (with the reference's own
        precondition) and otherwise a no-op, since XLA owns allocation."""
        wire_dtype = self._resolve_wire_dtype(wire_dtype, wire_fp8, config)
        if allocate_on_comm_stream and not (
            previous_event is not None and async_finish
        ):
            raise ValueError(
                "allocate_on_comm_stream requires previous_event and "
                "async_finish (reference precondition, buffer.py:826)"
            )
        # "pallas" = device-initiated remote-DMA all-to-all; else the XLA
        # collective ("ragged"/"dense" are LL-layout knobs, not this path's)
        wire = (
            "pallas" if self._resolve_wire(None, config) == "pallas"
            else "lax"
        )
        w, t, h = x.shape
        k = topk_idx.shape[-1]
        cap = self.capacity(t)
        e = self.num_experts
        n_chunks = self._resolve_chunks(None, config, wire)
        if n_chunks != 1:
            # memoized: resolve_chunks records budget/capacity fallbacks,
            # and this host call repeats per dispatch() of one static
            # config — count once, like the traced (per-compile) gates
            rkey = ("chunks", n_chunks, wire, cap, h, wire_dtype,
                    jnp.dtype(x.dtype).name)
            if rkey not in self._resolve_memo:
                self._resolve_memo[rkey] = ep_ops.resolve_chunks(
                    n_chunks, wire, self.world, cap,
                    self.num_local_experts, h,
                    ep_ops.wire_itemsize(False, h, x.dtype,
                                         wire_dtype=wire_dtype),
                    wire_dtype=wire_dtype,
                )
            n_chunks = self._resolve_memo[rkey]
        schedule = self._resolve_a2a_sched(
            config, wire, "dispatch",
            (self.world, self.num_local_experts, cap, h), x.dtype,
            wire_dtype, n_chunks=n_chunks,
        )
        has_ev = previous_event is not None
        tok = previous_event.token if has_ev else None
        key = ("dispatch", x.shape, topk_idx.shape, wire_dtype, x.dtype,
               wire, n_chunks, has_ev and (tok.shape, tok.dtype),
               schedule is not None
               and (tuple(schedule[0]), schedule[1].tobytes()))

        def f(xv, idx, *tok_arg):
            xv, idx = xv[0], idx[0]
            if tok_arg:
                xv = _tie(xv, tok_arg[0])
            # sorted/ragged layout (the fast path): ONE argsort per routing
            # decision builds the SlotPlan both sides of the layer consume;
            # dispatch is a gather; drops match the dense oracle exactly
            # (ep/ops.py)
            plan = ep_ops.plan_slots(idx, e, cap)
            slot, kept = plan.slot, plan.kept
            recv = ep_ops.dispatch_sorted(
                xv, plan, e, cap, self._axis_name(),
                wire_dtype=wire_dtype, wire=wire, n_chunks=n_chunks,
                schedule=schedule,
            )
            # per-(source, local-expert) received-row counts: kept[E] is MY
            # contribution per global expert; the all_to_all hands each
            # member row s = source s's counts for ITS experts (the same
            # counts exchange as the LL path's recv_mat). Always on — the
            # DeepEP handle always carries receive bookkeeping, and the
            # [W, E_local] int32 exchange is launch-latency-only next to
            # the payload all_to_all it accompanies.
            rc = ep_ops.counts_exchange(
                kept.reshape(-1, self.num_local_experts).astype(jnp.int32),
                self._axis_name(),
            )
            return recv[None], slot[None], rc[None]

        if topk_weights is None:
            topk_weights = jnp.full(topk_idx.shape, 1.0 / k, jnp.float32)
        extra_in = (2, 2) + ((tok.ndim - 1,) if has_ev else ())
        fn = self._jit(key, f, extra_in, (3, 2, 2))
        args = (x, topk_idx) + ((tok,) if has_ev else ())
        recv, slot, recv_counts = _observed_call(
            "dispatch", fn, args, wire=wire, n_chunks=n_chunks, payload=x,
            wire_dtype=wire_dtype,
        )
        self._op_counts["dispatch"] += 1
        self._last_dispatch = (topk_idx, cap)
        # weights go straight into the handle (combine reshards them itself)
        handle = DispatchHandle(slot, topk_weights, recv_counts, wire,
                                n_chunks, wire_dtype,
                                schedule is not None)
        if async_finish:
            return recv, handle, EventOverlap((recv, slot, recv_counts))
        return recv, handle

    def combine(
        self,
        expert_out: jax.Array,
        handle: DispatchHandle,
        *,
        wire_fp8: Optional[bool] = None,
        wire_dtype: Optional[str] = None,
        config: Optional[Config] = None,
        previous_event: Optional[EventOverlap] = None,
        async_finish: bool = False,
        allocate_on_comm_stream: bool = False,
    ):
        """expert_out: [W, E_local, W*C, H] → [W, T, H] (plus an
        :class:`EventOverlap` when ``async_finish``); overlap knobs as in
        :meth:`dispatch` (``config``: see :meth:`get_combine_config`). The
        reverse exchange rides the wire (and chunk depth) the handle's
        dispatch used; ``wire_dtype`` resolves independently of dispatch's
        (explicit keyword > Config > Buffer default — combine error is
        amplified by the gate weights, so get_combine_config keeps the
        return path full-precision even under an fp8 dispatch Config)."""
        wire_dtype = self._resolve_wire_dtype(wire_dtype, wire_fp8, config)
        if allocate_on_comm_stream and not (
            previous_event is not None and async_finish
        ):
            raise ValueError(
                "allocate_on_comm_stream requires previous_event and "
                "async_finish (reference precondition, buffer.py:826)"
            )
        wire = handle.wire
        n_chunks = handle.n_chunks  # retrace dispatch's chunking exactly
        schedule = None
        if handle.a2a_sched:
            # dispatch rode the scheduled rounds: the return exchange is
            # the transposed traffic (every row flows home), so combine
            # rebuilds its own schedule, arbitrated for ITS direction (row
            # and column skew differ on asymmetric matrices)
            schedule = self._resolve_a2a_sched(
                config, wire, "combine", expert_out.shape[1:],
                expert_out.dtype, wire_dtype, n_chunks=n_chunks,
            )
        has_ev = previous_event is not None
        tok = previous_event.token if has_ev else None
        key = ("combine", expert_out.shape, handle.slot.shape, wire_dtype,
               wire, n_chunks, has_ev and (tok.shape, tok.dtype),
               schedule is not None
               and (tuple(schedule[0]), schedule[1].tobytes()))

        def f(y, slot, wts, *tok_arg):
            if tok_arg:
                y = _tie(y, tok_arg[0])
            out = ep_ops.combine_sorted(
                y[0], slot[0], wts[0], self._axis_name(),
                wire_dtype=wire_dtype, wire=wire, n_chunks=n_chunks,
                schedule=schedule,
            )
            return out[None]

        extra_in = (3, 2, 2) + ((tok.ndim - 1,) if has_ev else ())
        fn = self._jit(key, f, extra_in, 2)
        self._op_counts["combine"] += 1
        args = (expert_out, handle.slot, handle.weights) + (
            (tok,) if has_ev else ()
        )
        out = _observed_call(
            "combine", fn, args, wire=wire, n_chunks=n_chunks,
            payload=expert_out, wire_dtype=wire_dtype,
        )
        if async_finish:
            return out, EventOverlap(out)
        return out

    # -- low-latency mode: packed fp8 payloads + recv counts -------------
    def low_latency_dispatch(
        self,
        x: jax.Array,
        topk_idx: jax.Array,
        num_max_dispatch_tokens_per_rank: Optional[int] = None,
        topk_weights: Optional[jax.Array] = None,
        *,
        pair_capacity_factor: Optional[float] = None,
        wire: str = "auto",
        wire_fp8: Optional[bool] = None,
        wire_dtype: Optional[str] = None,
        n_chunks: Optional[int] = None,
        config: Optional[Config] = None,
        previous_event: Optional[EventOverlap] = None,
        async_finish: bool = False,
        return_recv_hook: bool = False,
    ):
        """The DeepEP low-latency contract (ep/bench/buffer.py:285-454):
        packed per-expert buffers sized by ``num_max_dispatch_tokens_per_rank``
        plus per-expert receive counts, fp8 on the wire.

        x: [W, T, H]; topk_idx: [W, T, K] — entries of ``-1`` mean "no
        expert" (DeepEP-supported): such assignments claim no wire slot and
        contribute zero in combine. Returns
        (recv_x [W, R_max, H] group-major packed,
         recv_count [W, E_local],
         handle) — the consumer feeds (recv_x, recv_count) straight into
        grouped GEMMs (:func:`uccl_tpu.ep.ll.grouped_ffn`) so neither wire
        nor MXU touches padding.

        Overlap knobs (reference LL dispatch, ep/bench/buffer.py:285-346):
        ``config`` supplies defaults for the wire/sizing knobs
        (:class:`Config`, see get_dispatch_config); ``previous_event``
        orders this verb after another's event; ``async_finish`` /
        ``return_recv_hook`` switch the return to the reference's 5-tuple
        ``(recv_x, recv_count, handle, event, hook)`` — the hook is the
        two-phase receive: the dispatch is issued asynchronously and
        ``hook()`` blocks until the receive buffers have landed (on GPU the
        unhooked kernel skips the receive entirely; on TPU arrival is the
        XLA program itself, so the hook is the explicit arrival barrier)."""
        # the quantized-payload knob resolves through the one Buffer rule
        # (explicit wire_dtype/wire_fp8 > Config > Buffer default), with
        # the LL legacy default of fp8-on (internode_ll.cu's fp8 wire)
        wire_dtype = self._resolve_wire_dtype(wire_dtype, wire_fp8, config,
                                              default_fp8=True)
        if config is not None:
            if num_max_dispatch_tokens_per_rank is None:
                num_max_dispatch_tokens_per_rank = config.max_tokens_per_rank
            if pair_capacity_factor is None:
                pair_capacity_factor = config.pair_capacity_factor
            if wire == "auto":
                wire = config.wire
        w, t, h = x.shape
        k = topk_idx.shape[-1]
        # Buffer-level default + the pallas addressability gate (config was
        # already applied by the fill block above)
        wire = self._resolve_wire(wire, None)
        if wire == "auto":
            wire = "ragged" if ep_ll.wire_supports_ragged() else "dense"
        # resolve the chunk depth HERE (the shared ll rule) so the handle
        # records exactly the depth dispatch traced with
        per_pair, _ = ep_ll.ll_bounds(
            t, k, self.num_local_experts, self.world,
            num_max_dispatch_tokens_per_rank, pair_capacity_factor,
        )
        n_chunks = ep_ll.resolve_ll_chunks(
            self._resolve_chunks(n_chunks, config, wire), wire, self.world,
            per_pair,
        )
        if topk_weights is None:
            topk_weights = jnp.full(topk_idx.shape, 1.0 / k, jnp.float32)
        has_ev = previous_event is not None
        tok = previous_event.token if has_ev else None
        key = (
            "ll_dispatch", x.shape, topk_idx.shape, x.dtype,
            num_max_dispatch_tokens_per_rank, pair_capacity_factor, wire,
            wire_dtype, n_chunks, has_ev and (tok.shape, tok.dtype),
        )

        def f(xv, idx, wts, *tok_arg):
            if tok_arg:
                xv = _tie(xv, tok_arg[0])
            r = ep_ll.ll_dispatch(
                xv[0], idx[0], wts[0], self.num_experts, self._axis_name(),
                num_max_dispatch_tokens_per_rank=(
                    num_max_dispatch_tokens_per_rank
                ),
                pair_capacity_factor=pair_capacity_factor,
                wire=wire, wire_fp8=False, wire_dtype=wire_dtype,
                n_chunks=n_chunks,
            )
            s = r.state
            return (
                r.recv_x[None], r.group_sizes[None], s.send_slot[None],
                s.weights[None], s.send_mat[None], s.recv_mat[None],
                s.regroup[None], s.src_in_offsets[None],
            )

        extra_in = (2, 2, 2) + ((tok.ndim - 1,) if has_ev else ())
        fn = self._jit(key, f, extra_in, (2, 1, 2, 2, 2, 2, 1, 1))
        args = (x, topk_idx, topk_weights) + ((tok,) if has_ev else ())
        (recv_x, counts, send_slot, weights, send_mat, recv_mat, regroup,
         src_in_offsets) = _observed_call(
            "low_latency_dispatch", fn, args, wire=wire, n_chunks=n_chunks,
            payload=x, wire_dtype=wire_dtype,
        )
        handle = LowLatencyHandle(
            send_slot, weights, send_mat, recv_mat, regroup,
            src_in_offsets, wire, wire_dtype == "fp8", n_chunks, wire_dtype,
        )
        self._op_counts["low_latency_dispatch"] += 1
        self._last_ll = (counts, recv_x.shape[1], x.shape[-1],
                         wire_dtype is not None)
        if async_finish or return_recv_hook:
            event = EventOverlap((recv_x, counts)) if async_finish else None
            hook: Optional[Callable[[], None]] = (
                (lambda: jax.block_until_ready((recv_x, counts)))
                if return_recv_hook else None
            )
            return recv_x, counts, handle, event, hook
        return recv_x, counts, handle

    def low_latency_combine(
        self,
        expert_out: jax.Array,
        handle: LowLatencyHandle,
        *,
        previous_event: Optional[EventOverlap] = None,
        async_finish: bool = False,
        return_recv_hook: bool = False,
    ):
        """expert_out: [W, R_max, H] group-major → [W, T, H]; with
        ``async_finish``/``return_recv_hook`` set, returns the reference's
        ``(combined_x, event, hook)`` triple (ep/bench/buffer.py:454-530)."""
        # pre-quant pickled handles carry wire_dtype=None + the legacy
        # wire_fp8 bool — the resolution every reader must apply
        wire_dtype = handle.wire_dtype or (
            "fp8" if handle.wire_fp8 else None
        )
        has_ev = previous_event is not None
        tok = previous_event.token if has_ev else None
        key = (
            "ll_combine", expert_out.shape, handle.send_slot.shape,
            expert_out.dtype, handle.wire, wire_dtype,
            handle.n_chunks, has_ev and (tok.shape, tok.dtype),
        )

        def f(y, send_slot, wts, send_mat, recv_mat, regroup, src_off,
              *tok_arg):
            if tok_arg:
                y = _tie(y, tok_arg[0])
            state = ep_ll.LLState(
                send_slot[0], wts[0], send_mat[0], recv_mat[0],
                regroup[0], src_off[0], handle.wire, handle.n_chunks,
            )
            out = ep_ll.ll_combine(
                y[0], state, self._axis_name(), wire_fp8=False,
                wire_dtype=wire_dtype,
            )
            return out[None]

        extra_in = (2, 2, 2, 2, 2, 1, 1) + ((tok.ndim - 1,) if has_ev else ())
        fn = self._jit(key, f, extra_in, 2)
        self._op_counts["low_latency_combine"] += 1
        args = (
            expert_out, handle.send_slot, handle.weights, handle.send_mat,
            handle.recv_mat, handle.regroup, handle.src_in_offsets,
        ) + ((tok,) if has_ev else ())
        out = _observed_call(
            "low_latency_combine", fn, args, wire=handle.wire,
            n_chunks=handle.n_chunks, payload=expert_out,
            wire_dtype=wire_dtype,
        )
        if async_finish or return_recv_hook:
            event = EventOverlap(out) if async_finish else None
            hook: Optional[Callable[[], None]] = (
                (lambda: jax.block_until_ready(out))
                if return_recv_hook else None
            )
            return out, event, hook
        return out
