"""Cross-pod expert parallelism: experts sharded over DCN-connected pods.

The reference's EP pillar spans hosts through its CPU proxies posting RDMA
(ep/src/proxy.cpp:701, rdma.cpp:1554 — the dispatch/combine all-to-all runs
over the NIC fabric between nodes, *inside torch autograd*: training fwd+bwd
both cross the wire). On TPU the intra-pod leg is compiler-driven ICI
(`ep.ops` / `ep.Buffer`); this module adds the inter-pod leg over the DCN
transfer engine — training-grade:

* **forward**: tokens bucket by destination pod (vectorized numpy — one
  broadcasting pass, no Python loops over k), payloads + routing metadata
  ride ``DcnGroup.all_to_all`` (direct pairwise writes), each pod computes
  its own experts' contributions on its mesh, the weighted partials return
  over the same exchange.
* **backward**: the same two DCN exchanges in cotangent space —
  ``backward(dout)`` ships per-slot output cotangents to the pods that
  computed them, runs ``jax.vjp`` of the local expert compute on the saved
  received buffers, and returns (d_x, d_topk_weights) to the source pods
  while d_expert_weights stays where the experts live. Gradients match a
  single-process oracle exactly (tests/test_ep.py).
* **overlap**: ``n_chunks > 1`` pipelines the slot space — the exchange of
  chunk c+1 overlaps the (asynchronously dispatched) expert compute of
  chunk c, and the return exchange of chunk c overlaps compute of c+1; the
  moral analog of the reference's proxy threads running ahead of the GPU
  (proxy.cpp:701 drains rings while kernels run).

Semantics: drop-and-renormalize like the on-mesh path, with capacity applied
per (token, pod) bucket — a token reaching experts in ``p`` pods occupies
``p`` slots. Every pod calls forward/backward collectively (SPMD across
pods).
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uccl_tpu.collective import dma as _dma
from uccl_tpu.collective.hierarchical import DcnGroup
from uccl_tpu.ep import ops as ep_ops


class _StageClock:
    """Env-gated per-stage wall profiler (UCCL_TPU_XPOD_PROFILE=1): the
    knob that localizes cross-pod overhead (comm vs host glue vs compute)
    without guessing — the stats-surface idiom of the reference's proxy
    timing counters (dispatch_wait_recv_cost_stats, internode_ll.cu:66)."""

    def __init__(self):
        # read per instance (one per forward): enabling the profiler after
        # module import must work
        self.enabled = os.environ.get("UCCL_TPU_XPOD_PROFILE", "") == "1"
        self.t = {}
        self._t0 = time.perf_counter()

    def lap(self, name: str):
        if not self.enabled:
            return
        now = time.perf_counter()
        self.t[name] = self.t.get(name, 0.0) + (now - self._t0) * 1e3
        self._t0 = now

    def dump(self, tag: str):
        if self.enabled and self.t:
            total = sum(self.t.values())
            parts = " ".join(f"{k}={v:.1f}ms" for k, v in self.t.items())
            print(f"[xpod-profile] {tag}: total={total:.1f}ms {parts}",
                  flush=True)


def _np_token_for_slot(idx: np.ndarray, num_experts: int,
                       capacity: int) -> np.ndarray:
    """numpy twin of ep_ops.sorted_from_topk's token_for_slot output —
    same k-major flattening and STABLE sort, so drop semantics stay
    byte-identical to the jax path (tests compare against the dense
    oracle either way). idx: [T, K] bucket ids; returns [E*C] with T as
    the empty sentinel."""
    t, k = idx.shape
    tk = t * k
    flat_e = idx.T.reshape(tk)
    flat_t = np.tile(np.arange(t, dtype=np.int32), k)
    order = np.argsort(flat_e, kind="stable")
    sorted_t = flat_t[order]
    counts = np.bincount(flat_e, minlength=num_experts)[:num_experts]
    seg_start = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slot_ids = np.arange(num_experts * capacity)
    e_of = slot_ids // capacity
    p_of = slot_ids % capacity
    j = seg_start[e_of] + p_of
    kept = np.minimum(counts, capacity)
    valid = p_of < kept[e_of]
    return np.where(
        valid, sorted_t[np.clip(j, 0, tk - 1)], t
    ).astype(np.int32)


class CrossPodMoE:
    """MoE layer whose experts live across DCN-connected pods.

    Args:
      dcn: the cross-pod group (one member per pod).
      mesh: this pod's device mesh (expert weights replicated across it for
        simplicity of the reference layer; shard further with `ep.ops` TP in
        the expert_fn if desired).
      num_global_experts: total experts; pod i owns the contiguous block
        ``[i*E/P, (i+1)*E/P)``.
      capacity_factor: per-(token, pod) bucketing slack.
      n_chunks: slot-space pipelining depth (1 = no overlap; 2+ overlaps DCN
        exchanges with expert compute).
      a2a_sched: "off" | "on" | "auto" — order the pairwise DCN exchanges
        by the contention-aware round schedule built from ``a2a_traffic``
        (uccl_tpu.ep.a2a_sched; heavy inter-pod flows first, each round a
        partial matching so no pod's NIC carries two transfers at once;
        every write still rides the multipath Channel's SACK + PathQuality
        steering). "auto" schedules only when the matrix is actually
        skewed — a uniform matrix keeps the fixed hop order. Identical
        bytes and result either way. The matrix is SPMD state: every pod
        must construct with the same one.
      a2a_traffic: host [P, P] inter-pod routing matrix (None = uniform).
    """

    def __init__(
        self,
        dcn: DcnGroup,
        mesh: Mesh,
        *,
        num_global_experts: int,
        num_selected: int = 2,
        capacity_factor: float = 1.25,
        n_chunks: int = 1,
        a2a_sched: str = "off",
        a2a_traffic=None,
    ):
        self.dcn = dcn
        self.mesh = mesh
        self.n_pods = dcn.active_world
        if num_global_experts % self.n_pods:
            raise ValueError(
                f"experts {num_global_experts} must divide pods {self.n_pods}"
            )
        if a2a_sched not in ("off", "on", "auto"):
            raise ValueError(
                f"unknown a2a_sched {a2a_sched!r} (want 'off', 'on', or "
                "'auto')"
            )
        self.num_global_experts = num_global_experts
        self.experts_per_pod = num_global_experts // self.n_pods
        self.num_selected = num_selected
        self.capacity_factor = capacity_factor
        self.n_chunks = max(1, int(n_chunks))
        self._dcn_schedule = self._resolve_dcn_schedule(a2a_sched,
                                                        a2a_traffic)
        self._compute_cache = {}
        self._vjp_cache = {}
        self._ctx = None

    def _resolve_dcn_schedule(self, mode: str, traffic):
        """Build the (rounds, K) order the DCN exchanges will follow, or
        None for the fixed hop order. Decided once at construction (the
        matrix is static per layer, like ep.Buffer's): "on" pins the
        schedule; "auto" takes it only when the matrix is skewed — the
        host-side rounds cost nothing extra, so the only reason to keep
        the fixed order on a uniform matrix is that it IS that schedule
        already. The decision lands on the rounds/skew series either way
        (a2a_sched.record_decision)."""
        if mode == "off" or self.n_pods <= 1:
            return None
        from uccl_tpu.ep import a2a_sched as _sched

        mat = traffic
        if mat is None:
            mat = np.ones((self.n_pods, self.n_pods), float)
            np.fill_diagonal(mat, 0.0)
        mat = np.asarray(mat, float)
        sk = _sched.skew(mat)
        if mode == "auto" and sk <= 1.0 + 1e-9:
            _sched.record_decision("ep_streams", self.n_pods, matrix=mat)
            return None
        schedule = _sched.wire_schedule(mat, self.n_pods)
        _sched.record_decision("ep_sched", self.n_pods,
                               n_rounds=len(schedule[0]), matrix=mat)
        # the return exchange sees the transposed traffic (partials flow
        # home) — ordered by its own decomposition
        back = _sched.wire_schedule(mat.T, self.n_pods)
        return (schedule, back)

    # ------------------------------------------------------------------
    def _pod_capacity(self, t: int) -> int:
        # worst case every one of a token's K experts lives in one pod; the
        # expected per-pod demand is T*K/P, bucketed with slack
        cap = max(
            1,
            int(self.capacity_factor * t * self.num_selected / self.n_pods),
        )
        # chunked pipelining slices the slot axis evenly — the SAME rounding
        # rule as the device-level chunked wire (dma.pad_capacity), so the
        # host and device pipelines cannot drift on drop semantics
        return _dma.pad_capacity(cap, self.n_chunks)

    def _local_fn(self, expert_fn):
        """The pure per-pod compute: (xs [S,H], idx [S,K] local ids with -1
        invalid, wts [S,K], warrs) -> weighted partial sums [S,H]."""
        epp = self.experts_per_pod

        def f(xs, idx, wts, warrs):
            valid = (idx >= 0) & (idx < epp)
            safe_idx = jnp.where(valid, idx, 0)
            w = jnp.where(valid, wts, 0.0)
            k = idx.shape[-1]
            # one expert can legally receive up to S*K assignments (duplicate
            # expert ids within a token's top-k are allowed)
            cap = xs.shape[0] * k
            tfs, slot, _ = ep_ops.sorted_from_topk(
                jnp.where(valid, safe_idx, epp), epp + 1, cap
            )
            # gather per-expert buffers [epp+1, cap, H]; bucket epp = invalid
            buf = jnp.take(xs, tfs, axis=0, mode="fill", fill_value=0)
            buf = buf.reshape(epp + 1, cap, -1)
            out_e = expert_fn(buf[:epp], warrs)
            out_e = jnp.concatenate(
                [out_e, jnp.zeros_like(out_e[:1])], axis=0
            ).reshape((epp + 1) * cap, -1)
            yk = jnp.take(out_e, slot, axis=0, mode="fill", fill_value=0)
            return jnp.einsum("sk,skh->sh", w, yk)

        return f

    # Bounded: callers that build a fresh expert_fn closure per step would
    # otherwise grow the caches (and their pinned executables) without limit.
    # 8 generously covers the steady state of (a few shapes) x (a few fns);
    # a per-step-fresh closure simply pays a recompile per step, which is
    # also the signal to hoist the fn out of the loop.
    _CACHE_MAX = 8

    def _cached_jit(self, cache, shape_key, expert_fn, build):
        """LRU over ((shape_key, id(fn)) -> (fn pin, jitted)). The entry pins
        expert_fn so its id() cannot be recycled while the entry lives; same
        shapes with a different expert_fn never reuse a stale closure."""
        key = (shape_key, id(expert_fn))
        cached = cache.pop(key, None)
        if cached is None:
            cached = (expert_fn, jax.jit(build(expert_fn)))
        cache[key] = cached  # (re)insert at the end: dict order = recency
        while len(cache) > self._CACHE_MAX:
            cache.pop(next(iter(cache)))  # evict least-recently-used
        return cached[1]

    def _local_compute(self, shape_key, expert_fn):
        return self._cached_jit(
            self._compute_cache, shape_key, expert_fn, self._local_fn
        )

    def _local_vjp(self, shape_key, expert_fn):
        """Jitted vjp of the local compute w.r.t. (xs, wts, warrs)."""

        def build(fn):
            f = self._local_fn(fn)

            def g(xs, idx, wts, warrs, ct):
                _, vjp = jax.vjp(
                    lambda a, w_, ww: f(a, idx, w_, ww), xs, wts, warrs
                )
                return vjp(ct)

            return g

        return self._cached_jit(self._vjp_cache, shape_key, expert_fn, build)

    # ------------------------------------------------------------------
    def _bucket(self, x, topk_idx, topk_weights):
        """Vectorized host bucketing: slots, payload, per-slot metadata.

        Returns (tfs [P*cap], valid_slot, safe_tfs, hits [P*cap, K],
        meta_idx, meta_w, payload [P*cap, H])."""
        t, h = x.shape
        k = topk_idx.shape[-1]
        n_pods = self.n_pods
        cap = self._pod_capacity(t)
        epp = self.experts_per_pod

        pod_of = topk_idx // epp  # [T, K]
        # dedup (token, pod): keep the FIRST k hitting each pod — one
        # broadcasting compare against earlier k-slots, no Python loop
        eq = pod_of[:, :, None] == pod_of[:, None, :]  # [T, K, K]
        dup = np.tril(eq, -1).any(axis=-1)  # [T, K] matches an earlier k
        coarse = np.where(~dup, pod_of, n_pods)  # sentinel: no slot
        # pure-numpy twin of ep_ops.sorted_from_topk's token_for_slot: the
        # bucketing is host-side, and dispatching ~15 eager jax CPU ops per
        # forward cost 22 ms of the measured 40 ms — more than the entire
        # wire exchange (UCCL_TPU_XPOD_PROFILE breakdown, round 5)
        tfs = _np_token_for_slot(coarse, n_pods + 1, cap)[: n_pods * cap]

        valid_slot = tfs < t
        safe_tfs = np.where(valid_slot, tfs, 0)
        payload = np.where(valid_slot[:, None], x[safe_tfs], 0).astype(
            np.float32
        )
        slot_pod = np.repeat(np.arange(n_pods), cap)  # [P*cap]
        # hits[s, j]: assignment (token(s), j) targets slot s's pod
        hits = valid_slot[:, None] & (pod_of[safe_tfs] == slot_pod[:, None])
        meta_idx = np.where(hits, topk_idx[safe_tfs] % epp, -1).astype(
            np.int32
        )
        meta_w = np.where(hits, topk_weights[safe_tfs], 0.0).astype(
            np.float32
        )
        return tfs, valid_slot, safe_tfs, hits, meta_idx, meta_w, payload

    def _chunked_exchange_compute(self, wire, fn_args_builder, fn,
                                  clk=None):
        """Pipelined: all_to_all chunk c, dispatch compute c asynchronously
        (jax dispatch returns before the device finishes), exchange c+1
        while c computes, then return-exchange each chunk's result as it
        resolves. wire: [P, cap, D]. Returns [P*cap, H] numpy."""
        n_pods, cap = wire.shape[0], wire.shape[1]
        cs = cap // self.n_chunks
        sched_out, sched_back = self._dcn_schedule or (None, None)
        partials = []
        for c in range(self.n_chunks):
            sl = slice(c * cs, (c + 1) * cs)
            recv = self.dcn.all_to_all(np.ascontiguousarray(wire[:, sl]),
                                       schedule=sched_out)
            if clk:
                clk.lap("a2a_out")
            partials.append(fn(*fn_args_builder(recv)))  # async dispatch
            if clk:
                clk.lap("dispatch")
        backs = []
        for c in range(self.n_chunks):
            part = np.asarray(partials[c])  # blocks on chunk c only
            if clk:
                clk.lap("compute_wait")
            h = part.shape[-1]
            backs.append(
                self.dcn.all_to_all(
                    np.ascontiguousarray(part.reshape(n_pods, cs, h)),
                    schedule=sched_back,
                )
            )
            if clk:
                clk.lap("a2a_back")
        return np.concatenate(backs, axis=1).reshape(n_pods * cap, -1)

    # ------------------------------------------------------------------
    def forward(
        self,
        x: np.ndarray,
        topk_idx: np.ndarray,
        topk_weights: np.ndarray,
        expert_weights,
        *,
        save_for_backward: bool = True,
    ) -> np.ndarray:
        """x: [T, H] host tokens; topk_idx: [T, K] GLOBAL expert ids;
        topk_weights: [T, K]. ``expert_weights`` is a dict with ``"fn"``:
        ``(buf [epp, cap, H], warrs) -> [epp, cap, H]`` computing every
        local expert on its bucketed tokens (plus whatever arrays fn needs).
        Returns [T, H]. With save_for_backward, :meth:`backward` afterwards
        produces exact gradients."""
        t, h = x.shape
        k = topk_idx.shape[-1]
        if k != self.num_selected:
            raise ValueError(
                f"topk_idx has K={k} but the layer was built with "
                f"num_selected={self.num_selected} (capacity is sized by it)"
            )
        n_pods = self.n_pods
        cap = self._pod_capacity(t)
        clk = _StageClock()

        tfs, valid_slot, safe_tfs, hits, meta_idx, meta_w, payload = (
            self._bucket(x, topk_idx, topk_weights)
        )
        clk.lap("bucket")

        # wire rows: payload + (local idx, weight) metadata per k
        wire = np.concatenate(
            [payload, meta_idx.astype(np.float32), meta_w], axis=1
        ).reshape(n_pods, cap, h + 2 * k)
        clk.lap("pack")

        warrs = {kk: v for kk, v in expert_weights.items() if kk != "fn"}
        cs = cap // self.n_chunks
        shape_key = ((n_pods * cs, h), k)
        fn = self._local_compute(shape_key, expert_weights["fn"])
        # single-device meshes skip the device_put round trip (measured ~1ms
        # of glue per chunk on the loopback substrate); the jit commits
        # host arrays itself
        multi = len(self.mesh.devices.flat) > 1
        sharding = self._slot_sharding(n_pods * cs) if multi else None
        recvs = []

        def build_args(recv):
            flat = recv.reshape(-1, h + 2 * k)
            xs = jnp.asarray(flat[:, :h])
            idx_r = jnp.asarray(flat[:, h:h + k].astype(np.int32))
            w_r = jnp.asarray(flat[:, h + k:])
            if multi:
                xs = jax.device_put(xs, sharding)
                idx_r = jax.device_put(idx_r, sharding)
                w_r = jax.device_put(w_r, sharding)
            recvs.append((xs, idx_r, w_r))
            return xs, idx_r, w_r, warrs

        back = self._chunked_exchange_compute(wire, build_args, fn, clk=clk)

        out = np.zeros((t, h), np.float32)
        np.add.at(out, safe_tfs[valid_slot], back[valid_slot])
        clk.lap("combine")
        clk.dump(f"forward pod={self.dcn.pos} chunks={self.n_chunks}")

        if save_for_backward:
            self._ctx = dict(
                t=t, h=h, k=k, cap=cap, recvs=recvs, hits=hits,
                valid_slot=valid_slot, safe_tfs=safe_tfs,
                expert_fn=expert_weights["fn"], warrs=warrs,
                shape_key=shape_key,
            )
        return out

    def backward(self, dout: np.ndarray):
        """Cotangent pass: dout [T, H] → (d_x [T, H], d_topk_weights [T, K],
        d_expert_weights dict). Runs the same two DCN exchanges as forward,
        in cotangent space; every pod calls it collectively."""
        ctx = self._ctx
        if ctx is None:
            raise RuntimeError("backward() without a saved forward")
        t, h, k, cap = ctx["t"], ctx["h"], ctx["k"], ctx["cap"]
        n_pods = self.n_pods
        valid_slot, safe_tfs = ctx["valid_slot"], ctx["safe_tfs"]
        cs = cap // self.n_chunks

        # leg 1 (cotangent of the partial-return exchange): each slot's
        # output cotangent is dout at its source token; ship to the pod
        # that computed that slot's partial
        dpart = np.where(
            valid_slot[:, None], dout[safe_tfs], 0.0
        ).astype(np.float32).reshape(n_pods, cap, h)

        vjp_fn = self._local_vjp(ctx["shape_key"], ctx["expert_fn"])
        warrs = ctx["warrs"]
        d_warrs_acc = None
        outs = []
        chunk_i = [0]

        def build_args(recv_ct):
            xs, idx_r, w_r = ctx["recvs"][chunk_i[0]]
            chunk_i[0] += 1
            ct = jnp.asarray(recv_ct.reshape(-1, h))
            return xs, idx_r, w_r, warrs, ct

        # local vjp returns (dxs, dwts, dwarrs); the wire carries dxs+dwts,
        # dwarrs stays on this pod (experts live here)
        def fn(xs, idx_r, w_r, warrs_, ct):
            dxs, dwts, dwarrs = vjp_fn(xs, idx_r, w_r, warrs_, ct)
            outs.append(dwarrs)
            return jnp.concatenate([dxs, dwts.astype(dxs.dtype)], axis=1)

        back = self._chunked_exchange_compute(dpart, build_args, fn)
        for dwarrs in outs:
            dwarrs = jax.tree.map(np.asarray, dwarrs)
            if d_warrs_acc is None:
                d_warrs_acc = dwarrs
            else:
                d_warrs_acc = jax.tree.map(np.add, d_warrs_acc, dwarrs)

        dxs_back = back[:, :h]
        dwts_back = back[:, h:]

        d_x = np.zeros((t, h), np.float32)
        np.add.at(d_x, safe_tfs[valid_slot], dxs_back[valid_slot])
        d_w = np.zeros((t, k), np.float32)
        hits = ctx["hits"]  # [P*cap, K]
        rows = np.repeat(safe_tfs, k).reshape(-1, k)
        np.add.at(
            d_w,
            (rows[hits], np.broadcast_to(np.arange(k), hits.shape)[hits]),
            dwts_back[hits],
        )
        return d_x, d_w, d_warrs_acc

    # ------------------------------------------------------------------
    def _slot_sharding(self, n_slots: int) -> NamedSharding:
        """Slots shard over the first mesh axis when divisible (data-parallel
        expert compute with replicated weights), else run replicated."""
        ax0 = next(iter(self.mesh.shape))
        spec = P(ax0) if n_slots % self.mesh.shape[ax0] == 0 else P()
        return NamedSharding(self.mesh, spec)
