"""Cross-pod expert parallelism: experts sharded over DCN-connected pods.

The reference's EP pillar spans hosts through its CPU proxies posting RDMA
(ep/src/proxy.cpp:701, rdma.cpp:1554 — the dispatch/combine all-to-all runs
over the NIC fabric between nodes). On TPU the intra-pod leg is
compiler-driven ICI (`ep.ops` / `ep.Buffer`); this module adds the inter-pod
leg over the DCN transfer engine: global experts are sharded across pods,
tokens bucket by destination pod with the same sorted/capacity machinery the
on-mesh path uses, payloads + routing metadata ride
``DcnGroup.all_to_all`` (direct pairwise writes), each pod computes its own
experts' contributions on its mesh, and the weighted partials return over
the same exchange.

Semantics: drop-and-renormalize like the on-mesh path, with capacity applied
per (token, pod) bucket — a token reaching experts in ``p`` pods occupies
``p`` slots. Every pod calls :meth:`CrossPodMoE.forward` collectively
(SPMD across pods).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from uccl_tpu.collective.hierarchical import DcnGroup
from uccl_tpu.ep import ops as ep_ops


class CrossPodMoE:
    """MoE layer whose experts live across DCN-connected pods.

    Args:
      dcn: the cross-pod group (one member per pod).
      mesh: this pod's device mesh (expert weights replicated across it for
        simplicity of the reference layer; shard further with `ep.ops` TP in
        the expert_fn if desired).
      num_global_experts: total experts; pod i owns the contiguous block
        ``[i*E/P, (i+1)*E/P)``.
      capacity_factor: per-(token, pod) bucketing slack.
    """

    def __init__(
        self,
        dcn: DcnGroup,
        mesh: Mesh,
        *,
        num_global_experts: int,
        num_selected: int = 2,
        capacity_factor: float = 1.25,
    ):
        self.dcn = dcn
        self.mesh = mesh
        self.n_pods = dcn.active_world
        if num_global_experts % self.n_pods:
            raise ValueError(
                f"experts {num_global_experts} must divide pods {self.n_pods}"
            )
        self.num_global_experts = num_global_experts
        self.experts_per_pod = num_global_experts // self.n_pods
        self.num_selected = num_selected
        self.capacity_factor = capacity_factor
        self._compute_cache = {}

    # ------------------------------------------------------------------
    def _pod_capacity(self, t: int) -> int:
        # worst case every one of a token's K experts lives in one pod; the
        # expected per-pod demand is T*K/P, bucketed with slack
        return max(
            1,
            int(
                self.capacity_factor
                * t
                * self.num_selected
                / self.n_pods
            ),
        )

    def _local_compute(self, shape_key, expert_fn):
        """Jitted per-pod expert compute over received foreign tokens.

        xs: [S, H] slot payloads; idx: [S, K] LOCAL expert ids (-1 = not
        ours/invalid); wts: [S, K]; warrs: the expert weight arrays (a jit
        ARGUMENT, so updated weights are never baked in as stale constants).
        Returns weighted partial sums [S, H].
        """
        cached = self._compute_cache.get(shape_key)
        if cached is not None:
            return cached

        epp = self.experts_per_pod

        def f(xs, idx, wts, warrs):
            # mask assignments that don't belong to this pod
            valid = (idx >= 0) & (idx < epp)
            safe_idx = jnp.where(valid, idx, 0)
            w = jnp.where(valid, wts, 0.0)
            k = idx.shape[-1]
            # one expert can legally receive up to S*K assignments (duplicate
            # expert ids within a token's top-k are allowed)
            cap = xs.shape[0] * k
            tfs, slot, _ = ep_ops.sorted_from_topk(
                jnp.where(valid, safe_idx, epp), epp + 1, cap
            )
            # gather per-expert buffers [epp+1, cap, H]; bucket epp = invalid
            buf = jnp.take(xs, tfs, axis=0, mode="fill", fill_value=0)
            buf = buf.reshape(epp + 1, cap, -1)
            out_e = expert_fn(buf[:epp], warrs)
            out_e = jnp.concatenate(
                [out_e, jnp.zeros_like(out_e[:1])], axis=0
            ).reshape((epp + 1) * cap, -1)
            yk = jnp.take(out_e, slot, axis=0, mode="fill", fill_value=0)
            return jnp.einsum("sk,skh->sh", w, yk)

        fn = jax.jit(f)
        self._compute_cache[shape_key] = fn
        return fn

    # ------------------------------------------------------------------
    def forward(
        self,
        x: np.ndarray,
        topk_idx: np.ndarray,
        topk_weights: np.ndarray,
        expert_weights,
    ) -> np.ndarray:
        """x: [T, H] host tokens; topk_idx: [T, K] GLOBAL expert ids;
        topk_weights: [T, K]. ``expert_weights`` is a dict with ``"fn"``:
        ``(buf [epp, cap, H], weights) -> [epp, cap, H]`` computing every
        local expert on its bucketed tokens (plus whatever arrays fn needs).
        Returns [T, H].
        """
        t, h = x.shape
        k = topk_idx.shape[-1]
        if k != self.num_selected:
            raise ValueError(
                f"topk_idx has K={k} but the layer was built with "
                f"num_selected={self.num_selected} (capacity is sized by it)"
            )
        n_pods = self.n_pods
        cap = self._pod_capacity(t)
        epp = self.experts_per_pod

        # 1) bucket (token, k) assignments by destination pod — same sorted
        #    machinery as on-mesh dispatch, with pod id as the coarse expert.
        #    A token with multiple experts in ONE pod occupies one slot per
        #    distinct (token, pod... k) assignment; dedup to (token, pod)
        #    pairs so its payload travels once per pod.
        pod_of = topk_idx // epp  # [T, K]
        # dedup: keep the FIRST k hitting each (token, pod); later ks merge
        # their expert ids into the same slot's metadata below.
        first_hit = np.ones_like(pod_of, dtype=bool)
        for j in range(1, k):
            for jj in range(j):
                first_hit[:, j] &= pod_of[:, j] != pod_of[:, jj]
        coarse = np.where(first_hit, pod_of, n_pods)  # sentinel: no slot
        tfs, slot, _ = (
            np.asarray(a)
            for a in ep_ops.sorted_from_topk(
                jnp.asarray(coarse), n_pods + 1, cap
            )
        )
        # drop the sentinel bucket
        tfs = tfs[: n_pods * cap]

        # 2) build the wire arrays: payload + per-slot (local idx, weight)
        #    metadata for EVERY k of the slot's token that targets that pod.
        valid_slot = tfs < t
        safe_tfs = np.where(valid_slot, tfs, 0)
        payload = np.where(valid_slot[:, None], x[safe_tfs], 0).astype(
            np.float32
        )  # [P*cap, H]
        slot_pod = np.repeat(np.arange(n_pods), cap)  # [P*cap]
        tok_idx = np.where(valid_slot, safe_tfs, -1)
        meta_idx = np.full((n_pods * cap, k), -1, np.int32)
        meta_w = np.zeros((n_pods * cap, k), np.float32)
        for j in range(k):
            hits = valid_slot & (pod_of[safe_tfs, j] == slot_pod) & (
                tok_idx >= 0
            )
            meta_idx[hits, j] = (topk_idx[safe_tfs, j] % epp)[hits]
            meta_w[hits, j] = topk_weights[safe_tfs, j][hits]

        # 3) DCN exchange (direct pairwise writes): rows bucket by dest pod
        wire = np.concatenate(
            [payload, meta_idx.astype(np.float32), meta_w], axis=1
        ).reshape(n_pods, cap, h + 2 * k)
        recv = self.dcn.all_to_all(wire)  # [P, cap, H+2K], row i from pod i

        # 4) local expert compute on this pod's mesh: slots shard over the
        #    first mesh axis when divisible (data-parallel expert compute
        #    with replicated weights), else run replicated
        flat = recv.reshape(n_pods * cap, h + 2 * k)
        ax0 = next(iter(self.mesh.shape))
        n_slots = n_pods * cap
        spec = P(ax0) if n_slots % self.mesh.shape[ax0] == 0 else P()
        sharding = NamedSharding(self.mesh, spec)
        xs = jax.device_put(jnp.asarray(flat[:, :h]), sharding)
        idx_r = jax.device_put(
            jnp.asarray(flat[:, h : h + k].astype(np.int32)), sharding
        )
        w_r = jax.device_put(jnp.asarray(flat[:, h + k :]), sharding)
        warrs = {kk: v for kk, v in expert_weights.items() if kk != "fn"}
        fn = self._local_compute((xs.shape, k), expert_weights["fn"])
        partial = np.asarray(fn(xs, idx_r, w_r, warrs))  # [P*cap, H]

        # 5) return partials to their source pods + combine by slot map
        back = self.dcn.all_to_all(
            partial.reshape(n_pods, cap, h)
        ).reshape(n_pods * cap, h)
        out = np.zeros((t, h), np.float32)
        np.add.at(out, safe_tfs[valid_slot], back[valid_slot])
        return out
