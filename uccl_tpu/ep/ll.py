"""Low-latency expert-parallel path: packed no-padding dispatch/combine.

This is the TPU-native re-design of the reference's low-latency EP mode
(ep/src/internode_ll.cu:62 dispatch / :747 combine; python contract
ep/bench/buffer.py:285-454): per-expert *packed* fp8 payloads sized by
``num_max_dispatch_tokens_per_rank``, per-expert **receive counts** returned to
the caller, and expert compute that never touches a padded slot. Where the
reference packs token messages in CUDA warp-groups and RDMA-writes them via a
CPU proxy, here:

* the *layout kernel* (ep/src/layout.cu) is one stable argsort by global
  expert id — because each EP member owns a contiguous expert range, expert
  order IS destination-rank-major order, so one sort yields both the wire
  packing and the per-expert receive grouping;
* the *wire* is ``lax.ragged_all_to_all`` (TPU/GPU): only actual rows move,
  fp8 values + per-group scales, like internode_ll's fp8+scales messages. On
  backends without ragged collectives (XLA:CPU) a dense-chunked
  ``lax.all_to_all`` carries the same packed layout inside fixed-size per-pair
  chunks (padding on the wire, still none on the MXU) — and that path is
  fully differentiable, making it the training-grade ragged MoE. A third
  form, ``wire="pallas"``, keeps the dense-chunk layout but issues the
  exchange as device-initiated remote DMAs from ONE Pallas kernel
  (:mod:`uccl_tpu.ep.pallas_a2a` — the TPU analog of internode_ll's
  proxy-posted RDMA writes, selected via ``Buffer(..., wire="pallas")``);
* the *grouped GEMM* is ``lax.ragged_dot`` over the receive counts
  (megablocks-style): FLOPs proportional to real tokens, not capacity.

Contracts (per-shard, inside ``shard_map`` over the EP axis):

``ll_dispatch(x[T,H], topk_idx[T,K], ...)`` (``topk_idx`` entries of ``-1``
    mean "no expert" — DeepEP-supported; they claim no wire slot and combine
    to zero) →
    ``(recv_x [R_max, H], group_sizes [E_local], state)`` with ``recv_x``
    packed group-major (rows of local expert 0 first, then 1, ...; zeros past
    ``sum(group_sizes)``) — DeepEP's packed_recv_x + packed_recv_count.
``ll_combine(expert_out [R_max, H], state, axis)`` → ``[T, H]`` weighted
    per-token sums (dropped assignments contribute zero).

``num_max_dispatch_tokens_per_rank`` (``M``) bounds tokens sent by one rank
(DeepEP's meaning, ep/bench/buffer.py:285); the static receive bound is then
``R_max = W * M * min(K, E_local)`` rows. Rows past a violated bound drop
tail-first per destination (tested; the lossless default never drops).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from uccl_tpu.collective import dma as _dma
from uccl_tpu.ep.ops import MOE_CHECKPOINT_NAMES
from uccl_tpu.ep.ops import counts_exchange as _counts_exchange
from uccl_tpu.ops.quant import (
    dequantize_block,
    paying_block,
    quantize_block,
)

Axis = Union[str, Tuple[str, ...]]


def _exclusive_cumsum(x):
    return jnp.concatenate([jnp.zeros((1,), x.dtype), jnp.cumsum(x)[:-1]])


def wire_supports_ragged() -> bool:
    """ragged-all-to-all lowers on TPU/GPU; XLA:CPU has no thunk for it."""
    return jax.default_backend() in ("tpu", "gpu")


# ONE scale/payoff rule everywhere: the LL wire's block adaption is the
# shared codec's (uccl_tpu.ops.quant.paying_block — formerly a private
# duplicate of ops._adapt_quant_group + the >= 8 payoff margin here).
_adapt_group = paying_block


def _resolve_quant(h: int, wire_fp8: bool, wire_dtype,
                   quant_group: int):
    """The LL wire's quantization decision: (wire_dtype, adapted group) or
    None. A requested wire dtype that would not pay (only blocks < 8
    divide h) ships raw — counted on the shared fallback counter like
    every quantized→full-precision downgrade, never silent."""
    from uccl_tpu.ep.ops import resolve_wire_dtype

    wire_dtype = resolve_wire_dtype(wire_fp8, wire_dtype)
    if wire_dtype is None:
        return None
    g = _adapt_group(h, quant_group)
    if g is None:
        _dma.record_fallback(
            "ep_wire_quant", "block_too_small", detail=(h, quant_group),
            msg=f"ll wire_dtype={wire_dtype!r}: hidden {h} only admits "
                f"blocks < 8 (requested {quant_group}); shipping full "
                "precision",
        )
        return None
    return (wire_dtype, g)


def resolve_ll_chunks(n_chunks: int, wire: str, world: int,
                      per_pair: int) -> int:
    """Effective chunk-pipeline depth for the LL dense-chunk wire (shared
    with the Buffer verbs so the handle records exactly what dispatch ran):
    1 off the pallas wire or at world 1; 0 = auto (2 when the per-pair slot
    axis can split); clamped to per_pair. An explicitly-requested depth
    (> 1) that gets downgraded is recorded on the shared fallback counter
    (docs/OBSERVABILITY.md); auto (0) resolving to 1 stays silent."""
    if wire != "pallas" or world <= 1:
        if n_chunks > 1 and wire == "pallas":
            from uccl_tpu.collective import dma as _dma

            _dma.record_fallback("ep_ll_chunked", "world_size", detail=world)
        return 1
    if n_chunks == 0:
        n_chunks = 2 if per_pair >= 2 else 1
    return max(1, min(int(n_chunks), per_pair))


class LLState(NamedTuple):
    """Per-shard layout saved by ll_dispatch for ll_combine (the handle)."""

    send_slot: jax.Array  # [T, K] int32 wire-buffer row per assignment
    #   (sentinel = send-buffer size ⇒ dropped)
    weights: jax.Array  # [T, K] f32 gate weights
    send_mat: jax.Array  # [W, E_local] int32 rows I send per (dst, expert)
    recv_mat: jax.Array  # [W, E_local] int32 rows received per (src, expert)
    regroup: jax.Array  # [R_max] int32 perm: grouped row i ← wire row
    src_in_offsets: jax.Array  # [W] int32 where my chunk sat in each source's
    #   send buffer (ragged-wire reverse path; zeros on dense wire)
    wire: str  # "ragged" | "dense" | "pallas"
    n_chunks: int = 1  # pallas-wire chunk-pipeline depth (static; combine
    #   retraces dispatch's chunking without re-resolving)


class LLDispatchResult(NamedTuple):
    recv_x: jax.Array  # [R_max, H] group-major packed tokens
    group_sizes: jax.Array  # [E_local] int32 recv_count per local expert
    state: LLState


def ll_bounds(
    t: int,
    k: int,
    e_local: int,
    w: int,
    m: Optional[int],
    pair_capacity_factor: Optional[float] = None,
) -> Tuple[int, int]:
    """Static buffer bounds: (per_pair, r_max). m bounds tokens one rank
    dispatches (default t); one source aims ≤ m·min(k, e_local) rows at one
    destination (a token repeats an expert at most once and a destination owns
    e_local experts) — the lossless bound. ``pair_capacity_factor`` trades
    losslessness for economy: per_pair shrinks to ceil(cf·t·k/w) (the expected
    per-destination row count under balanced routing, scaled), and rows past
    it drop tail-first — the moral twin of capacity_factor on the padded
    path, and of DeepEP's caller-guaranteed num_max_dispatch_tokens_per_rank
    sizing (ep/bench/buffer.py:285)."""
    m = t if m is None else m
    per_pair = min(m * min(k, e_local), t * k)
    if pair_capacity_factor is not None:
        per_pair = min(
            per_pair, max(1, -(-int(pair_capacity_factor * t * k) // w))
        )
    return per_pair, w * per_pair


def _layout(topk_idx, num_experts: int, e_local: int, per_pair: int, wire: str):
    """One stable argsort = the layout kernel (ep/src/layout.cu analog).

    Returns (sorted_t, slot_sorted, send_slot [T,K], send_mat [W,E_local],
    sent_rows): slot positions are in the WIRE layout — packed ("ragged",
    sentinel T*K) or per-dest chunks of ``per_pair`` ("dense", sentinel
    W*per_pair)."""
    t, k = topk_idx.shape
    tk = t * k
    w = num_experts // e_local
    flat_e = topk_idx.T.reshape(tk)  # k-major: earlier k-slots win on drops
    flat_t = jnp.tile(jnp.arange(t, dtype=jnp.int32), k)
    # DeepEP's contract admits -1 "no expert" assignments
    # (ep/bench/buffer.py:285). Map them to a sort-last sentinel id so they
    # never claim a wire slot or shift the packed positions of real rows.
    valid = flat_e >= 0
    key_e = jnp.where(valid, flat_e, num_experts).astype(jnp.int32)
    order = jnp.argsort(key_e, stable=True)
    sorted_e = key_e[order]
    sorted_t = flat_t[order]
    is_real = sorted_e < num_experts
    dest = jnp.where(is_real, sorted_e // e_local, 0).astype(jnp.int32)

    counts_e = jnp.bincount(key_e, length=num_experts + 1)[:num_experts]
    dest_sizes = counts_e.reshape(w, e_local).sum(-1)
    dest_start = _exclusive_cumsum(dest_sizes)
    pos_in_dest = (
        jnp.arange(tk, dtype=jnp.int32) - dest_start[dest].astype(jnp.int32)
    )
    keep = is_real & (pos_in_dest < per_pair)  # drop dest-tail + no-expert

    kept_e = jax.ops.segment_sum(
        keep.astype(jnp.int32), sorted_e, num_segments=num_experts
    )
    send_mat = kept_e.reshape(w, e_local)

    if wire == "ragged":
        # kept rows are per-dest prefixes of the sorted order, so the packed
        # position is simply the row's rank among kept rows
        slot_sorted = jnp.where(
            keep, jnp.cumsum(keep.astype(jnp.int32)) - 1, tk
        ).astype(jnp.int32)
        sentinel = tk
    else:
        slot_sorted = jnp.where(
            keep, dest * per_pair + pos_in_dest, w * per_pair
        ).astype(jnp.int32)
        sentinel = w * per_pair
    send_slot = (
        jnp.full((tk,), sentinel, jnp.int32)
        .at[order]
        .set(slot_sorted)
        .reshape(k, t)
        .T
    )
    return sorted_t, slot_sorted, send_slot, send_mat


def _regroup_perm(recv_mat, per_pair: int, wire: str):
    """Permutation taking the wire receive layout → local-expert-major packing.

    Wire layout: rows from source s occupy, in expert order, either the packed
    range starting at cumsum(recv_sizes)[s] ("ragged") or the chunk starting
    at s*per_pair ("dense"). Grouped row i gathers wire row regroup[i];
    invalid rows point past the buffer (gather with fill=0)."""
    w, e_local = recv_mat.shape
    r_max = w * per_pair
    recv_sizes = recv_mat.sum(-1)
    if wire == "ragged":
        chunk_start = _exclusive_cumsum(recv_sizes)
    else:
        chunk_start = jnp.arange(w, dtype=jnp.int32) * per_pair
    src_of = jnp.repeat(
        jnp.arange(w, dtype=jnp.int32), per_pair, total_repeat_length=r_max
    )
    off_in_chunk = jnp.arange(r_max, dtype=jnp.int32) - src_of * per_pair
    seg_end = jnp.cumsum(recv_mat, axis=-1)  # [W, E_local]
    le_of = jnp.sum(off_in_chunk[:, None] >= seg_end[src_of], axis=-1)
    valid = off_in_chunk < recv_sizes[src_of]
    wire_row = jnp.where(
        valid, chunk_start[src_of].astype(jnp.int32) + off_in_chunk, r_max
    )
    key = jnp.where(valid, le_of, e_local)
    grouped_order = jnp.argsort(key, stable=True)
    return wire_row[grouped_order].astype(jnp.int32)


class _RaggedSpec(NamedTuple):
    in_offsets: jax.Array  # [W] chunk starts in my send buffer
    send_sizes: jax.Array  # [W]
    out_offsets: jax.Array  # [W] where my chunk lands in each DEST's output
    recv_sizes: jax.Array  # [W]


def _ragged_exchange(rows, out_rows: int, spec: _RaggedSpec, axis):
    out = jnp.zeros((out_rows,) + rows.shape[1:], rows.dtype)
    return lax.ragged_all_to_all(
        rows,
        out,
        spec.in_offsets.astype(jnp.int32),
        spec.send_sizes.astype(jnp.int32),
        spec.out_offsets.astype(jnp.int32),
        spec.recv_sizes.astype(jnp.int32),
        axis_name=axis,
    )


def _dense_exchange(rows, w: int, axis):
    """Fixed-chunk all_to_all of a [W*per_pair, ...] buffer."""
    shape = rows.shape
    return lax.all_to_all(
        rows.reshape(w, shape[0] // w, *shape[1:]), axis, 0, 0, tiled=True
    ).reshape(shape)


def _pallas_exchange(rows, w: int, axis, *, n_chunks=1, collective_id=None):
    """The dense-chunk layout on the device-initiated wire: same [W*per_pair,
    ...] contract as :func:`_dense_exchange`, but the member-major exchange is
    the Pallas remote-DMA all-to-all kernel (uccl_tpu.ep.pallas_a2a) instead
    of an XLA collective. ``n_chunks > 1`` splits the per-pair slot axis into
    that many double-buffered chunk kernels on rotated collective ids."""
    from uccl_tpu.ep import pallas_a2a

    shape = rows.shape
    return pallas_a2a.all_to_all(
        rows.reshape(w, shape[0] // w, *shape[1:]), axis,
        n_chunks=n_chunks, chunk_axis=1, collective_id=collective_id,
    ).reshape(shape)


def _send_payload(send_rows, out_rows, w, spec, wire, axis, quant_spec,
                  dtype, *, n_chunks=1, collective_id=None):
    """Move a row payload across the wire, optionally block-quantized
    (``quant_spec`` = (wire_dtype, group) or None — values + scale sidecar,
    the shared ops.quant codec)."""

    def exchange(rows, cid_off=0):
        if wire == "ragged":
            return _ragged_exchange(rows, out_rows, spec, axis)
        if wire == "dense":
            return _dense_exchange(rows, w, axis)
        cid = None if collective_id is None else collective_id + cid_off
        return _pallas_exchange(rows, w, axis, n_chunks=n_chunks,
                                collective_id=cid)

    if quant_spec is not None:
        wire_dtype, group = quant_spec
        q, scale = quantize_block(send_rows, wire_dtype, group)
        return dequantize_block(
            exchange(q), exchange(scale, _dma.CID_SCALE_OFFSET),
            group, dtype=dtype,
        )
    return exchange(send_rows)


def ll_dispatch(
    x: jax.Array,
    topk_idx: jax.Array,
    topk_weights: Optional[jax.Array],
    num_experts: int,
    axis: Axis,
    *,
    num_max_dispatch_tokens_per_rank: Optional[int] = None,
    pair_capacity_factor: Optional[float] = None,
    wire: str = "auto",
    wire_fp8: bool = True,
    quant_group: int = 128,
    n_chunks: int = 1,
    wire_dtype: Optional[str] = None,
) -> LLDispatchResult:
    """Packed low-latency dispatch (per-shard). See module docstring.

    ``n_chunks`` (pallas wire only; 0 = auto) splits the per-pair slot axis
    of the dense-chunk exchange into double-buffered chunk kernels — the LL
    grouped GEMM regroups across sources, so here chunking pipelines the
    WIRE itself (and whatever compute XLA schedules beside it), not a
    per-chunk GEMM like the sorted layer's pipelined step.

    ``wire_dtype`` picks the quantized wire payload ("fp8" | "int8");
    ``wire_fp8=True`` is the legacy spelling of "fp8"."""
    w = lax.axis_size(axis)
    t, h = x.shape
    k = topk_idx.shape[-1]
    if num_experts % w:
        raise ValueError(f"experts {num_experts} not divisible by world {w}")
    e_local = num_experts // w
    per_pair, r_max = ll_bounds(
        t, k, e_local, w, num_max_dispatch_tokens_per_rank,
        pair_capacity_factor,
    )
    if wire == "auto":
        wire = "ragged" if wire_supports_ragged() else "dense"
    if wire not in ("ragged", "dense", "pallas"):
        raise ValueError(
            f"unknown LL wire {wire!r} (want 'auto', 'ragged', 'dense', or "
            "'pallas')"
        )
    n_chunks = resolve_ll_chunks(n_chunks, wire, w, per_pair)
    if topk_weights is None:
        topk_weights = jnp.full((t, k), 1.0 / k, jnp.float32)
    quant_spec = _resolve_quant(h, wire_fp8, wire_dtype, quant_group)

    sorted_t, slot_sorted, send_slot, send_mat = _layout(
        topk_idx, num_experts, e_local, per_pair, wire
    )
    recv_mat = _counts_exchange(send_mat, axis)

    send_buf_rows = t * k if wire == "ragged" else w * per_pair
    send_rows = (
        jnp.zeros((send_buf_rows, h), x.dtype)
        .at[slot_sorted]
        .set(x[sorted_t], mode="drop")
    )

    if wire == "ragged":
        send_sizes = send_mat.sum(-1).astype(jnp.int32)
        recv_sizes = recv_mat.sum(-1).astype(jnp.int32)
        in_offsets = _exclusive_cumsum(send_sizes)
        recv_start = _exclusive_cumsum(recv_sizes)
        # each source needs where its chunk lands in MY output, and the
        # reverse path later needs where my chunk sat in each source's input
        out_offsets = _counts_exchange(recv_start[:, None], axis)[:, 0]
        src_in_offsets = _counts_exchange(in_offsets[:, None], axis)[:, 0]
        spec = _RaggedSpec(in_offsets, send_sizes, out_offsets, recv_sizes)
    else:
        spec = None
        src_in_offsets = jnp.zeros((w,), jnp.int32)

    recv_rows = _send_payload(
        send_rows, r_max, w, spec, wire, axis, quant_spec, x.dtype,
        n_chunks=n_chunks, collective_id=_dma.CID_EP_DISPATCH,
    )

    regroup = _regroup_perm(recv_mat, per_pair, wire)
    recv_x = jnp.take(recv_rows, regroup, axis=0, mode="fill", fill_value=0)
    group_sizes = recv_mat.sum(0).astype(jnp.int32)
    state = LLState(
        send_slot, topk_weights, send_mat, recv_mat, regroup,
        src_in_offsets, wire, n_chunks,
    )
    return LLDispatchResult(recv_x, group_sizes, state)


def ll_combine(
    expert_out: jax.Array,
    state: LLState,
    axis: Axis,
    *,
    wire_fp8: bool = True,
    quant_group: int = 128,
    wire_dtype: Optional[str] = None,
) -> jax.Array:
    """Packed low-latency combine (per-shard): ungroup → reverse wire →
    weighted per-token sum. expert_out: [R_max, H] group-major."""
    w = lax.axis_size(axis)
    r_max, h = expert_out.shape
    per_pair = r_max // w
    t, k = state.send_slot.shape
    quant_spec = _resolve_quant(h, wire_fp8, wire_dtype, quant_group)

    # grouped → wire layout (inverse of the regroup gather)
    wire_rows = (
        jnp.zeros((r_max, h), expert_out.dtype)
        .at[state.regroup]
        .set(expert_out, mode="drop")
    )

    if state.wire == "ragged":
        # send back what was received: my chunk from source s sits at
        # cumsum(recv_sizes)[s]; it lands where s originally packed it
        send_sizes = state.recv_mat.sum(-1).astype(jnp.int32)
        recv_sizes = state.send_mat.sum(-1).astype(jnp.int32)
        spec = _RaggedSpec(
            _exclusive_cumsum(send_sizes),
            send_sizes,
            state.src_in_offsets.astype(jnp.int32),
            recv_sizes,
        )
        out_rows = t * k
    else:
        spec, out_rows = None, r_max

    back = _send_payload(
        wire_rows, out_rows, w, spec, state.wire, axis, quant_spec,
        expert_out.dtype,
        n_chunks=state.n_chunks, collective_id=_dma.CID_EP_COMBINE,
    )

    yk = jnp.take(
        back, state.send_slot, axis=0, mode="fill", fill_value=0
    )  # [T, K, H]
    return jnp.einsum("tk,tkh->th", state.weights.astype(yk.dtype), yk)


def grouped_ffn(
    recv_x: jax.Array,
    group_sizes: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
) -> jax.Array:
    """SwiGLU expert FFN over packed rows: three grouped GEMMs via
    ``lax.ragged_dot`` — FLOPs ∝ sum(group_sizes), not capacity (the
    megablocks-style economy the reference gets from per-expert packed
    messages, internode_ll.cu:62). recv_x: [R, H]; w_gate/w_up: [E_local, H,
    F]; w_down: [E_local, F, H]."""
    # Same checkpoint_name tags as the sort/dense path (ep.ops.moe_ffn):
    # remat="mlp" (flagship._remat_wrap) saves these, so backward re-runs
    # no grouped GEMM regardless of which moe impl is selected.
    xe_tag, hg_tag, hu_tag, ye_tag = MOE_CHECKPOINT_NAMES
    recv_x = checkpoint_name(recv_x, xe_tag)
    gate = checkpoint_name(lax.ragged_dot(recv_x, w_gate, group_sizes),
                           hg_tag)
    up = checkpoint_name(lax.ragged_dot(recv_x, w_up, group_sizes), hu_tag)
    act = jax.nn.silu(gate) * up
    return checkpoint_name(
        lax.ragged_dot(act, w_down, group_sizes), ye_tag
    )


def ll_moe_ffn(
    x: jax.Array,
    router_logits: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    axis: Axis,
    *,
    num_selected: int = 2,
    num_max_dispatch_tokens_per_rank: Optional[int] = None,
    pair_capacity_factor: Optional[float] = None,
    wire: str = "auto",
    wire_fp8: bool = False,
    renormalize: bool = True,
    n_chunks: int = 1,
    wire_dtype: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full MoE layer on the low-latency path: route → packed dispatch →
    grouped GEMMs over counts → packed combine. Drop-free by default (the
    packed path has no per-expert capacity), so it is also the *lossless*
    alternative to the capacity-dropping sorted/dense paths. Differentiable
    end to end on the dense wire; the ragged wire targets decode (DeepEP LL's
    use case). Returns (out [T, H], aux_loss, z_loss)."""
    from uccl_tpu.ep.ops import _gate_topk

    e = router_logits.shape[-1]
    topk_vals, topk_idx, aux_loss, z_loss = _gate_topk(
        router_logits, num_selected, renormalize
    )
    r = ll_dispatch(
        x, topk_idx, topk_vals, e, axis,
        num_max_dispatch_tokens_per_rank=num_max_dispatch_tokens_per_rank,
        pair_capacity_factor=pair_capacity_factor,
        wire=wire, wire_fp8=wire_fp8, n_chunks=n_chunks,
        wire_dtype=wire_dtype,
    )
    y = grouped_ffn(
        r.recv_x, r.group_sizes,
        w_gate.astype(r.recv_x.dtype),
        w_up.astype(r.recv_x.dtype),
        w_down.astype(r.recv_x.dtype),
    )
    out = ll_combine(y, r.state, axis, wire_fp8=wire_fp8,
                     wire_dtype=wire_dtype)
    return out.astype(x.dtype), aux_loss, z_loss
